# TPU runtime image: engine + server + operator in one image (the
# reference ships two images — the Go manager and the delegated
# ollama/ollama runtime; here one image plays both roles, selected by the
# entrypoint arg vocabulary: serve / pull / operator).
#
# Build args let CI pin the JAX stack; the TPU libtpu wheel comes from the
# jax[tpu] extra and is only resolvable on TPU VMs / with the libtpu
# release index, hence the BACKEND switch (cpu image for kind e2e).
ARG PYTHON_VERSION=3.12
FROM python:${PYTHON_VERSION}-slim AS base

ARG BACKEND=tpu
RUN apt-get update && apt-get install -y --no-install-recommends \
      g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY ollama_operator_tpu/ ollama_operator_tpu/
COPY native/ native/
# tests/ + hack/ ship in the image for the kind e2e's in-cluster fixtures
# (hack/fake_registry_entry.py) — a few KB, and the e2e then needs zero
# network egress from the cluster
COPY tests/ tests/
COPY hack/fake_registry_entry.py hack/fake_registry_entry.py
COPY hack/entrypoint.sh /usr/local/bin/entrypoint.sh
RUN chmod +x /usr/local/bin/entrypoint.sh

RUN pip install --no-cache-dir numpy ml_dtypes einops && \
    if [ "$BACKEND" = "tpu" ]; then \
      pip install --no-cache-dir "jax[tpu]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html; \
    else \
      pip install --no-cache-dir jax; \
    fi

# native dequant kernels (ctypes-loaded from native/build/; gguf/native.py
# also builds lazily at runtime if this layer is skipped)
RUN mkdir -p native/build && \
    (g++ -O3 -march=native -shared -fPIC -o \
      native/build/libtpuop_dequant.so native/dequant.cpp || true) && \
    (g++ -O3 -std=c++17 -shared -fPIC -o \
      native/build/libtpuop_grammar.so native/grammar.cpp || true)

ENV PYTHONUNBUFFERED=1
EXPOSE 11434
ENTRYPOINT ["/usr/local/bin/entrypoint.sh"]
CMD ["serve"]
