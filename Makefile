# Build/test/deploy targets mirroring the reference's kubebuilder Makefile
# surface (/root/reference/Makefile) where each has a meaning here.
IMG ?= ghcr.io/ollama-operator-tpu/tpu-runtime:v0.1.0
BACKEND ?= tpu
PY ?= python

.PHONY: all test test-fast lint lint-verbose kernel-interpret native bench \
        bench-smoke docker-build docker-build-cpu build-installer install \
        uninstall deploy undeploy kind-e2e clean

all: test build-installer

##@ Development

test:  ## full suite on the 8-device CPU mesh (conftest.py sets XLA flags)
	$(PY) -m pytest tests/ -q

test-fast:  ## operator + serving tiers only (no engine compiles)
	$(PY) -m pytest tests/test_operator_*.py tests/test_registry.py \
	  tests/test_modelfile.py tests/test_template.py -q

lint:  ## pyflakes (or py_compile) + the invariant linter (tools/invariant_lint)
	$(PY) -m pyflakes ollama_operator_tpu tests 2>/dev/null || \
	  $(PY) -m py_compile $$(git ls-files '*.py')
	$(PY) -m tools.invariant_lint --root .

lint-verbose:  ## invariant linter incl. suppressed findings + per-pass table
	$(PY) -m tools.invariant_lint --root . --verbose

kernel-interpret:  ## pallas kernels in interpret mode on CPU: fused paged A/B, int4 pool, device grammar
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_pallas.py tests/test_paged.py \
	  tests/test_paged_fused.py tests/test_grammar_device.py -q

# (grammar otherwise builds lazily at the first format:"json" request —
# a latency spike)
native:  ## build the C++ dequant + grammar libraries
	mkdir -p native/build
	g++ -O3 -march=native -shared -fPIC \
	  -o native/build/libtpuop_dequant.so native/dequant.cpp
	g++ -O3 -std=c++17 -shared -fPIC \
	  -o native/build/libtpuop_grammar.so native/grammar.cpp

bench:  ## headline decode-throughput benchmark (one JSON line)
	$(PY) bench.py

# BENCH_XLA_CACHE=0: the CPU-backend persistent-cache deserialization
# path is unstable on some hosts (wrong tokens, then a native crash) —
# tiny smoke programs recompile in seconds anyway
bench-smoke:  ## seconds-scale CPU bench: engine + HTTP + mixed + prefix + spec + overload + restart + coldstart + fused-paged + disagg arms
	JAX_PLATFORMS=cpu BENCH_CHILD=1 BENCH_HTTP=1 BENCH_MIXED_ARM=1 \
	  BENCH_PREFIX_ARM=1 BENCH_TIER_ARMS=1 \
	  BENCH_PAGED_ASYNC_ARM=1 BENCH_PAGED_FUSED_ARM=1 \
	  BENCH_SPEC_ARM=1 \
	  BENCH_OVERLOAD_ARM=1 BENCH_RESTART_ARM=1 BENCH_COLDSTART_ARM=1 \
	  BENCH_DISAGG_ARM=1 BENCH_ASSERT_DISAGG=1 \
	  BENCH_ASSERT_COLDSTART=1 BENCH_XLA_CACHE=0 \
	  BENCH_SLOTS=4 BENCH_STEPS=16 BENCH_SEQ=512 BENCH_PROMPT=16 \
	  BENCH_CAPTURE_LOG=0 $(PY) bench.py

##@ Build

docker-build:
	docker build --build-arg BACKEND=$(BACKEND) -t $(IMG) .

docker-build-cpu:
	docker build --build-arg BACKEND=cpu -t $(IMG) .

build-installer:  ## dist/install.yaml (single-file apply, ref Makefile:117)
	$(PY) hack/build_installer.py --image $(IMG)

##@ Deployment

install:  ## CRDs only
	kubectl apply -f config/crd/ollama.ayaka.io_models.yaml

uninstall:
	kubectl delete -f config/crd/ollama.ayaka.io_models.yaml

deploy: build-installer
	kubectl apply -f dist/install.yaml

undeploy:
	kubectl delete -f dist/install.yaml

kind-e2e:  ## CPU-backend image into a kind cluster (ref test-e2e analog)
	kind create cluster --config hack/kind-config.yaml || true
	$(MAKE) docker-build-cpu
	kind load docker-image $(IMG)
	$(MAKE) deploy
	kubectl apply -f config/samples/ollama_v1_model.yaml

clean:
	rm -rf native/build dist/install.yaml
