"""Headline benchmark: aggregate decode throughput through the real Engine.

Measures the serving path of BASELINE.md's ladder (config 1 model: phi 2.7B,
the reference's sample CR `config/samples/ollama_v1_model.yaml` image) —
continuous-batching decode tok/s plus p50 TTFT — on whatever accelerator is
attached (one real TPU chip under the driver; CPU elsewhere). Prints ONE
JSON line:

  {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N,
   "captures": [...], ...}

The headline metric is the first capture (phi int8 dense B=8, comparable
across rounds); on a TPU the run also captures the paged cache at B=32
mixed-length and a GQA model (tinyllama) so the pallas decode kernels are in
a measured path, each with an HBM-bandwidth-utilization estimate
(bytes_touched/step ÷ 819 GB/s on v5e).

vs_baseline is the ratio against the earliest recorded BENCH_r*.json in the
repo root (the reference publishes no numbers — BASELINE.md — so round 1
self-baselines at 1.0 and later rounds are measured against it).

Env knobs: BENCH_MODEL (preset name — pins a SINGLE capture with the
BENCH_SLOTS/BENCH_STEPS/BENCH_SEQ/BENCH_PROMPT/BENCH_PAGED knobs as before;
without it the CPU plan honors the same knobs on the tiny model).
BENCH_BUDGET_S caps the child's capture loop: a capture is only STARTED if
the worst observed capture time still fits before the deadline. The
supervisor passes an absolute BENCH_DEADLINE_TS so the budget covers
import/backend-init time too, and recovers completed captures from a
partial file if it has to kill a child mid-capture.
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np

V5E_HBM_GBS = 819e9   # v5e HBM bandwidth, bytes/s (public spec)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Supervisor: the TPU tunnel (axon backend) is flaky — jax.devices() can hang
# indefinitely or raise UNAVAILABLE. Running the measurement in a child
# process lets us bound backend init (kill + retry with backoff) and, as a
# last resort, capture on CPU so a parseable JSON line always lands.
# ---------------------------------------------------------------------------

INIT_MARKER = "bench: devices="   # child logs this right after jax.devices()


def _run_attempt(env: dict, init_timeout: float, total_timeout: float):
    """One child run. Returns (rc, stdout, streamed) — rc None on
    timeout-kill; streamed False when stdout was assembled from the
    partial file (never relayed live).

    On a timeout-kill, completed captures the child logged to its partial
    file are recovered and assembled into the final JSON line — a stalled
    4th capture must not void an already-measured TPU headline."""
    partial = os.path.abspath(f".bench_partial.{os.getpid()}.jsonl")
    env = dict(env, BENCH_PARTIAL=partial,
               BENCH_DEADLINE_TS=str(time.time() + total_timeout - 30))
    try:
        os.unlink(partial)
    except OSError:
        pass
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    init_seen = threading.Event()
    out_chunks: list[str] = []

    def pump_stderr():
        for line in p.stderr:
            if INIT_MARKER in line:
                init_seen.set()
            sys.stderr.write(line)
            sys.stderr.flush()

    # stdout must be drained concurrently too: one capture's JSON is small,
    # but a pile-up past the pipe buffer (~64KB) would deadlock p.wait().
    # Relay each line LIVE — the child rewrites the summary after every
    # capture, and an external kill of this supervisor must still leave the
    # latest summary on the real stdout, not in a private buffer.
    def pump_stdout():
        for line in p.stdout:
            out_chunks.append(line)
            sys.stdout.write(line)
            sys.stdout.flush()

    t = threading.Thread(target=pump_stderr, daemon=True)
    to = threading.Thread(target=pump_stdout, daemon=True)
    t.start()
    to.start()
    start = time.monotonic()
    try:
        # wait for the init marker OR child exit — an instant crash (import
        # error, bad model name) must not burn the whole init window
        while not init_seen.is_set():
            if p.poll() is not None:
                t.join(timeout=5)
                to.join(timeout=5)
                return p.returncode, "".join(out_chunks), True
            if time.monotonic() - start > init_timeout:
                log(f"bench: backend init exceeded {init_timeout:.0f}s, "
                    f"killing child")
                p.kill()
                p.wait()
                return None, "", True
            time.sleep(1.0)
        remaining = total_timeout - (time.monotonic() - start)
        try:
            p.wait(timeout=max(remaining, 1.0))
        except subprocess.TimeoutExpired:
            log(f"bench: run exceeded {total_timeout:.0f}s total, "
                f"killing child")
            p.kill()
            p.wait()
            rec = _recover_partial(partial)
            if rec:
                log("bench: recovered completed captures from killed child")
                return 0, rec, False
            return None, "", True
        t.join(timeout=5)
        to.join(timeout=5)
        return p.returncode, "".join(out_chunks), True
    finally:
        try:
            os.unlink(partial)
        except OSError:
            pass


def _recover_partial(partial: str) -> str:
    """Assemble the final JSON line from a killed child's capture log."""
    try:
        with open(partial) as f:
            lines = f.readlines()
    except OSError:
        return ""
    caps = []
    for line in lines:
        if not line.strip():
            continue
        try:
            caps.append(json.loads(line))
        except json.JSONDecodeError:
            # SIGKILL can land mid-write: a truncated trailing line must
            # not void the complete captures before it
            continue
    if not caps:
        return ""
    meta, captures = None, []
    for c in caps:
        if c.get("_meta"):
            meta = c
        else:
            captures.append(c)
    if not captures or meta is None:
        return ""
    return assemble(captures, meta["platform"], meta["n_devices"]) + "\n"


def run_supervised() -> int:
    # generous init windows: this box has been observed at >85% iowait,
    # where a cold `import jax` alone can take minutes — a tight timeout
    # would kill children that are merely slow-importing, not hung
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "2"))
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "300"))
    # the r4 plan is 10 captures (~45 min warm+measure on the tunnel);
    # the deadline-ordered plan still cuts gracefully if the window is
    # shorter, but the default budget must fit the full suite
    total_timeout = float(os.environ.get("BENCH_TIMEOUT", "3600"))
    backoff = 10.0
    # BENCH_NO_FALLBACK=1: fail instead of capturing on CPU — the probe
    # loop (hack/bench_probe.sh) wants "TPU or nothing" per attempt while
    # the driver's single run wants "a parseable line no matter what"
    no_fallback = os.environ.get("BENCH_NO_FALLBACK", "") == "1"
    for attempt in range(retries + 1):
        env = dict(os.environ, BENCH_CHILD="1")
        fallback = attempt == retries and not no_fallback
        # NB: this image's profile exports JAX_PLATFORMS=axon (preventing
        # silent CPU fallback in normal runs), so the fallback must
        # OVERRIDE it — only an explicit cpu pin skips the accelerator
        # attempts entirely
        if fallback and os.environ.get("JAX_PLATFORMS", "") != "cpu":
            # Last attempt: the accelerator never came up. Capture on CPU —
            # a real (if slow) number beats a hang for the record. The CPU
            # box may be a single core, so the fallback also drops to the
            # tiny model unless the caller pinned one: phi-2.7B f32 decode
            # on one core would blow the child budget.
            log("bench: TPU backend unavailable after retries; CPU fallback")
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("BENCH_STEPS", "32")
            env.setdefault("BENCH_SEQ", "512")
            env.setdefault("BENCH_MODEL", "tiny")
        # CPU fallback has no hang risk but single-core init is slow;
        # give it extra headroom.
        rc, out, streamed = _run_attempt(
            env, init_timeout * (2 if fallback else 1), total_timeout)
        if rc == 0 and out.strip():
            if not streamed:   # recovered-partial line never hit stdout
                sys.stdout.write(out)
                sys.stdout.flush()
            return 0
        more = attempt < retries
        log(f"bench: attempt {attempt + 1}/{retries + 1} failed "
            f"(rc={rc}); retrying in {backoff:.0f}s" if more else
            f"bench: final attempt failed (rc={rc})")
        if more:   # no dead sleep after the LAST attempt (no-fallback probes)
            time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)
    return 1


def load_baseline(metric: str) -> tuple[float, int] | None:
    """Earliest recorded value for ``metric`` → (value, round_number).

    The round number is surfaced as ``baseline_round`` in the output line
    so vs_baseline's provenance is explicit (VERDICT r4 hygiene item)."""
    runs = []
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                       "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        # rounds ≥3 nest the parsed line under "parsed" (driver format) or
        # are the line itself; accept either
        for cand in (rec, rec.get("parsed") or {}):
            if cand.get("metric") == metric and isinstance(
                    cand.get("value"), (int, float)):
                runs.append((int(m.group(1)), float(cand["value"])))
                break
    if not runs:
        return None
    rnd, val = min(runs)
    return val, rnd


# ---------------------------------------------------------------------------
# Child: one measure() per capture config.
# ---------------------------------------------------------------------------


def _init_quantized_leafwise(jax, cfg, decoder, bits: int):
    """Random params for big models, materialised one leaf at a time:
    each bf16 leaf is generated on device, quantized (donated) if it is
    a quantizable matmul leaf, and only then does the next leaf
    materialise — peak HBM = quantized tree + one bf16 leaf."""
    import jax.numpy as jnp

    from ollama_operator_tpu.ops.quant import (QUANT_LAYER_KEYS,
                                               QUANT_TOP_KEYS,
                                               quantize_groupwise,
                                               quantize_groupwise_int4)
    quant = quantize_groupwise if bits == 8 else quantize_groupwise_int4
    avals = jax.eval_shape(
        lambda k: decoder.init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.key(0))

    def gen(key, aval):
        mk = jax.jit(lambda k: (jax.random.normal(k, aval.shape,
                                                  jnp.float32)
                                * 0.02).astype(aval.dtype))
        return mk(key)

    out = {}
    ki = 0
    for name, sub in avals.items():
        if name == "layers":
            lo = {}
            for lk, aval in sub.items():
                leaf = gen(jax.random.key(ki), aval)
                ki += 1
                if lk in QUANT_LAYER_KEYS:
                    leaf = quant(leaf)
                jax.block_until_ready(leaf)
                lo[lk] = leaf
            out[name] = lo
        else:
            leaf = gen(jax.random.key(ki), sub)
            ki += 1
            if name in QUANT_TOP_KEYS:
                leaf = quant(leaf)
            jax.block_until_ready(leaf)
            out[name] = leaf
    return out


def _bench_params(jax, cfg, model: str, dtype: str, on_cpu: bool,
                  params_cache: dict | None):
    """Initialized (and possibly quantized) bench params, via the shared
    cache so adjacent same-model captures skip the minutes-long init.
    Returns (params, param_bytes, resolved_dtype)."""
    import gc

    import jax.numpy as jnp

    from ollama_operator_tpu.models import decoder

    cache_key = (model, dtype)
    if params_cache is not None and cache_key in params_cache:
        log("bench: reusing cached params")
        return params_cache[cache_key]
    if params_cache:
        params_cache.clear()   # free the previous model's HBM first
        gc.collect()
    t0 = time.perf_counter()
    if dtype in ("int8", "int4") and cfg.n_experts:
        dtype = "bfloat16"       # MoE expert stacks serve dense
    if dtype in ("int8", "int4") and not on_cpu and cfg.n_params > 3e9:
        # 7B-class models: the whole-tree bf16 init (13.4+ GB) OOMs
        # a shared 16 GB chip before quantization can halve it —
        # init + quantize LEAF BY LEAF instead, so peak HBM is the
        # quantized tree plus ONE bf16 leaf (a real pull quantizes
        # host-side during transcode; this is bench-only synthesis)
        params = _init_quantized_leafwise(
            jax, cfg, decoder, bits=4 if dtype == "int4" else 8)
    else:
        params = decoder.init_params(
            cfg, jax.random.key(0),
            dtype=jnp.float32 if on_cpu else jnp.bfloat16)
        jax.block_until_ready(params)
        if dtype in ("int8", "int4"):
            # weight-only quantized serving (ops/quant.py): decode is
            # HBM-bound, so weight bytes set the step floor — int8
            # halves bf16's, int4 packs two codes per byte
            from ollama_operator_tpu.ops.quant import quantize_params
            params = quantize_params(
                params, bits=4 if dtype == "int4" else 8)
            jax.block_until_ready(params)
    param_bytes = sum(x.nbytes for x in jax.tree.leaves(params))
    log(f"params init ({cfg.n_params/1e9:.2f}B, serve dtype={dtype}, "
        f"{param_bytes/1e9:.2f} GB) in {time.perf_counter()-t0:.1f}s")
    if params_cache is not None:
        params_cache[cache_key] = (params, param_bytes, dtype)
    return params, param_bytes, dtype


def _sched_utilization(sched, recompiles0: int = 0) -> dict:
    """Compact utilization block for a scheduler-driven arm — every bench
    arm's summary reports mfu/occupancy/waste_pct plus the recompiles that
    landed in the MEASURED window (recompiles0 is the post-warmup
    snapshot; the CI smoke asserts the delta stays 0)."""
    try:
        snap = sched.utilization_stats(window_s=600)
    except Exception:  # noqa: BLE001 — summaries must never kill a capture
        return {}
    rc = snap.get("recompiles") or {}
    out = {"enabled": bool(snap.get("enabled")),
           "recompiles": int(sum(rc.values())) - int(recompiles0)}
    if out["enabled"]:
        # aggregate over LIFETIME totals, not the per-second window: a
        # seconds-scale arm can finish inside the in-progress second,
        # which snapshot() deliberately excludes from windowed rates —
        # the arm's honest aggregate is totals over its own wall clock
        tot = snap.get("totals") or {}
        useful = float(sum((tot.get("useful_tokens") or {}).values()))
        padded = float(sum((tot.get("padded_tokens") or {}).values()))
        issued = useful + padded
        wall = float((snap.get("breakdown") or {}).get("wall_s") or 0.0)
        peak = snap.get("peak_flops")
        flops = float(tot.get("model_flops") or 0.0)
        out.update(
            mfu=(round(flops / wall / peak, 6)
                 if peak and wall > 0 else None),
            occupancy=round(useful / issued, 4) if issued else None,
            waste_pct=(round(100.0 * padded / issued, 2)
                       if issued else 0.0),
            goodput_tok_s=round(useful / wall, 2) if wall > 0 else 0.0)
    return out


def _analytic_utilization(cfg, *, dt_s: float, flops: float, useful: float,
                          issued: float) -> dict:
    """Utilization block for engine-level captures (no scheduler in the
    loop): same closed-form FLOPs model as runtime/accounting.py, grid
    geometry supplied by the capture itself."""
    from ollama_operator_tpu.runtime.accounting import detect_peak_flops
    peak, kind = detect_peak_flops()
    waste = max(0.0, issued - useful)
    return {
        "mfu": (round(flops / dt_s / peak, 6)
                if peak and dt_s > 0 else None),
        "occupancy": round(useful / issued, 4) if issued else None,
        "waste_pct": round(100.0 * waste / issued, 2) if issued else 0.0,
        "device_kind": kind,
    }


def measure(jax, *, model: str, dtype: str, slots: int, steps: int,
            seq: int, prompt_len: int, paged: bool, mixed: bool,
            chunk: int, page_size: int, n_pages: int | None,
            platform: str, params_cache: dict | None = None,
            env: dict | None = None) -> dict:
    """Run one engine capture and return its record (also frees the engine
    before returning so sequential captures don't stack HBM).

    params_cache (shared across a capture plan) keeps the last model's
    initialized+quantized params alive so adjacent same-model captures —
    the TPU plan runs each model dense then paged — skip the minutes-long
    init; it holds ONE model at a time, freed when the model changes."""
    import gc

    import jax.numpy as jnp

    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    resolve_cache_dtype)

    on_cpu = platform == "cpu"
    if on_cpu:
        # XLA's CPU thunk runtime lacks bf16 dots; CPU captures run f32.
        dtype = "float32"
        kv_dtype = resolve_cache_dtype(
            os.environ.get("BENCH_KV_DTYPE", "float32"))
    else:
        kv_dtype = resolve_cache_dtype(
            os.environ.get("BENCH_KV_DTYPE", "int8"))

    cfg = get_config(model)
    log(f"bench: capture model={model} dtype={dtype} slots={slots} "
        f"steps={steps} seq={seq} paged={paged} mixed={mixed} "
        f"env={env or {}}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)

    devs = jax.devices()
    mesh = None
    if len(devs) > 1:
        from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
        tp = 1
        while (tp * 2 <= len(devs) and cfg.n_heads % (tp * 2) == 0
               and len(devs) % (tp * 2) == 0):
            tp *= 2
        mesh = make_mesh(MeshPlan.for_devices(len(devs), tp=tp))
        log(f"mesh: {dict(mesh.shape)}")

    if dtype == "int4":
        # shared routing with the server loader (ops/quant.int4_mm_kernels)
        # so the bench can never measure a different matmul path than the
        # server ships
        from ollama_operator_tpu.ops.quant import int4_mm_kernels
        cfg = int4_mm_kernels(cfg, mesh)
    eng = Engine(cfg, params, mesh=mesh,
                 ecfg=EngineConfig(
                     max_slots=slots, max_seq_len=seq, decode_chunk=chunk,
                     cache_dtype=kv_dtype, paged=paged,
                     page_size=page_size, n_pages=n_pages))

    # the whole run must fit the context whatever the plan says (the
    # engine clamps max_seq to cfg.max_seq_len): prompt + warmup chunk +
    # measured steps, else cache writes would clamp into the tail and
    # corrupt the measurement
    prompt_len = min(prompt_len, eng.max_seq // 2)
    calls_budget = max(1, steps // chunk)
    need = prompt_len + chunk + calls_budget * chunk + 2
    if need > eng.max_seq:
        steps = max(chunk, (eng.max_seq - prompt_len - chunk - 2)
                    // chunk * chunk)
        log(f"bench: clamping steps to {steps} to fit context "
            f"{eng.max_seq}")
        # the steps clamp floors at one chunk; if that still overflows
        # (short-context model), shrink the prompt instead — decode must
        # never write past max_seq or the tail clamp corrupts the capture
        if prompt_len + chunk + max(1, steps // chunk) * chunk + 2 \
                > eng.max_seq:
            prompt_len = eng.max_seq - 2 * chunk - 2
            if prompt_len < 8:
                raise ValueError(
                    f"capture cannot fit context {eng.max_seq} with "
                    f"decode_chunk {chunk}: reduce BENCH_DECODE_CHUNK")
            log(f"bench: shrinking prompt to {prompt_len} to fit context")
    rng = np.random.default_rng(0)
    if mixed:
        # mixed-length batch: the paged pool's reason to exist — HBM scales
        # with live tokens, not slots × max_seq
        plens = rng.integers(max(8, prompt_len // 4), prompt_len + 1,
                             size=slots)
    else:
        plens = np.full(slots, prompt_len)
    prompts = [rng.integers(1, cfg.vocab_size, size=int(n),
                            endpoint=False).astype(np.int32) for n in plens]

    # TTFT: prompt admission → first sampled token back on host, per slot.
    # First admit pays compile; measure it separately, then re-admit.
    t0 = time.perf_counter()
    eng.admit(0, prompts[0])
    compile_s = time.perf_counter() - t0
    log(f"prefill compile+run: {compile_s:.1f}s")
    eng.release(0)

    ttfts = []
    for s in range(slots):
        t0 = time.perf_counter()
        eng.admit(s, prompts[s])
        ttfts.append(time.perf_counter() - t0)
    ttft_p50_ms = float(np.median(ttfts) * 1e3)

    t0 = time.perf_counter()
    # warm ONLY the attention buckets this capture's context range reaches
    # (admissions above already compiled their prefill buckets lazily) —
    # with the persistent compile cache this drops warm from ~250 s cold /
    # full to seconds on a cached plan
    ctx_hi = int(np.max(plens)) + chunk + max(1, steps // chunk) * chunk + 2
    eng.warm_buckets(ctx_lo=int(np.max(plens)), ctx_hi=ctx_hi, full=False)
    decode_compile_s = time.perf_counter() - t0
    log(f"decode warm (reachable buckets ≤{ctx_hi}): "
        f"{decode_compile_s:.1f}s (chunk={chunk})")
    eng.decode_n()
    rc0 = sum(getattr(eng, "recompiles", {}).values())

    calls = max(1, steps // chunk)
    t0 = time.perf_counter()
    for _ in range(calls):
        eng.decode_n()   # [chunk, B], one dispatch+sync per call
    dt = time.perf_counter() - t0
    rc_measured = sum(getattr(eng, "recompiles", {}).values()) - rc0
    n_steps = calls * chunk
    tok_s = n_steps * slots / dt
    per_step_ms = dt / n_steps * 1e3

    # HBM traffic estimate per decode step: every weight byte streams once
    # (batch ≤ 32 decode is weight-bound), plus the live KV window read per
    # slot at the mid-run context length. Utilization vs the v5e spec shows
    # the headroom VERDICT round-2 weak #4 flagged.
    if kv_dtype == "int4":
        kv_item = 0.5            # nibble-packed: two positions per byte
    elif kv_dtype == jnp.int8:
        kv_item = 1
    else:
        kv_item = jnp.dtype(kv_dtype).itemsize
    mid_ctx = plens.astype(np.int64) + chunk + n_steps // 2
    kv_bytes = int(np.sum(np.minimum(mid_ctx, eng.max_seq))
                   * cfg.n_layers * 2 * cfg.kv_dim * kv_item)
    # the unfused reference path (TPU_PAGED_FUSED=0) materialises the
    # gathered KV window, then re-reads it for scores and mix: ~3x the
    # KV traffic of the fused kernel's single streaming pass
    paged_fused = paged and os.environ.get(
        "TPU_PAGED_FUSED", "1").lower() not in ("0", "false")
    if paged and not paged_fused:
        kv_bytes *= 3
    bytes_per_step = param_bytes + kv_bytes
    # per-chip: params and KV are sharded over the mesh, so each chip
    # streams ~1/n_devices of the aggregate bytes
    n_dev = len(devs)
    hbm_gbs = bytes_per_step / n_dev / (per_step_ms / 1e3) / 1e9
    rec = {
        "model": model,
        "tok_s": round(tok_s, 2),
        "ttft_p50_ms": round(ttft_p50_ms, 1),
        "decode_step_ms": round(per_step_ms, 2),
        "slots": slots,
        "steps": n_steps,
        "dtype": dtype,
        "kv_dtype": ("int4" if kv_dtype == "int4"
                     else "int8" if kv_item == 1
                     else str(jnp.dtype(kv_dtype))),
        "paged": paged,
        "mixed_len": mixed,
        "prompt_len": int(np.max(plens)),
        # full config provenance: without these the committed capture log
        # can't distinguish A/B arms (a ps-64 and a ps-128 record would be
        # byte-identical in every config field)
        "decode_chunk": chunk,
        "seq": seq,
        # 6 decimals: a tiny-model smoke capture is ~1e-4 GB/step and the
        # summary's traffic ratios must not collapse to 0/0
        "bytes_per_step_gb": round(bytes_per_step / 1e9, 6),
        "hbm_gb_s": round(hbm_gbs, 1),
    }
    # analytic utilization: this capture decodes the full resident batch
    # (every slot active, no padding) so occupancy is 1.0 by construction;
    # MFU is the closed-form FLOPs model over the measured wall time
    from ollama_operator_tpu.runtime.accounting import decode_flops
    ctx0 = plens.astype(np.int64) + 1 + chunk   # prompt + first tok + warm
    model_flops = float(sum(decode_flops(cfg, int(c), n_steps)
                            for c in ctx0))
    rec["utilization"] = _analytic_utilization(
        cfg, dt_s=dt, flops=model_flops,
        useful=float(n_steps * slots), issued=float(n_steps * slots))
    if paged:
        rec["page_size"] = page_size
        rec["n_pages"] = n_pages or eng._pt.n_pages
        rec["paged_fused"] = paged_fused
        # recompiles landed in the MEASURED window (warmup compiles are
        # not recompiles) — the fused-kernel arm must hold this at 0
        rec["recompiles"] = int(rc_measured)
        depth = os.environ.get("TPU_PAGED_DEPTH")
        if depth:
            rec["paged_depth"] = int(depth)
        # ambient kernel routing (capture-scoped env is recorded via
        # rec["env"]; pinned runs set these in the process environment)
        for var in ("TPU_PAGED_V4", "TPU_PAGED_V3"):
            if os.environ.get(var):
                rec[var.lower()] = os.environ[var]
    # per-chip bytes vs the v5e spec (other TPU generations will read
    # slightly off; the driver chip is a v5e — BASELINE.md). On CPU this
    # is a PROJECTION — what the same traffic would demand of a v5e —
    # flagged so the smoke plan can exercise the bandwidth accounting
    # without a chip attached.
    rec["hbm_bw_util_pct"] = round(
        bytes_per_step / n_dev / (per_step_ms / 1e3)
        / V5E_HBM_GBS * 100, 1)
    if platform == "cpu":
        rec["hbm_bw_projected"] = True
    if env:
        rec["env"] = dict(env)
    log(f"bench: capture done: {json.dumps(rec)}")
    del eng, params   # params stay alive in params_cache if one was given
    gc.collect()
    return rec


def measure_spec(jax, *, model: str, dtype: str, slots: int, steps: int,
                 seq: int, prompt_len: int, paged: bool, mixed: bool,
                 chunk: int, page_size: int, n_pages: int | None,
                 platform: str, params_cache: dict | None = None,
                 env: dict | None = None, spec_k: int = 4) -> dict:
    """Fused speculative-decoding arm (ISSUE 6): greedy slots driven
    through the ONE production dispatch surface —
    ``decode_n_launch(drafts=)`` + ``wait`` + ``spec_ack`` — on a
    repetition-heavy workload, in three sub-arms:

      lookup     — real prompt-lookup drafts (runtime/drafter.py), the
                   number the serving default is decided from
      accept_all — oracle drafts replayed from the recorded baseline
                   continuation: the scheme's ceiling
      reject_all — garbage drafts: its floor, pure dispatch overhead

    A chunk dispatch advances `chunk` steps sequentially; a spec dispatch
    scores k+1 positions in ONE forward, so ms_per_dispatch vs the
    baseline dispatch separates "the spec program is slow" from "the
    model forward dominates" — the CI gate asserts the lookup arm stays
    within 1.2x of the baseline dispatch AND beats its tok/s."""
    import gc

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime import drafter
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: SPEC capture model={model} dtype={dtype} slots={slots} "
        f"k={spec_k}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    if dtype == "int4":
        from ollama_operator_tpu.ops.quant import int4_mm_kernels
        cfg = int4_mm_kernels(cfg, None)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=slots, max_seq_len=seq,
                                   decode_chunk=chunk,
                                   cache_dtype=kv_dtype))
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    k = spec_k
    prompt_len = min(prompt_len, eng.max_seq // 2)
    calls = max(1, steps // chunk)
    # the whole run must fit the context: prompt + first token + warm
    # chunk + measured steps + the transient k+1 launch over-advance
    if prompt_len + 1 + chunk + calls * chunk + k + 2 > eng.max_seq:
        steps = max(chunk, (eng.max_seq - prompt_len - chunk - k - 3)
                    // chunk * chunk)
        calls = max(1, steps // chunk)
        log(f"bench: clamping spec steps to {steps} to fit context "
            f"{eng.max_seq}")
    n_steps = calls * chunk
    rng = np.random.default_rng(0)
    # repetition-heavy workload — the regime prompt-lookup targets
    # (code, JSON, summarisation): each slot's prompt cycles a short
    # random pattern, so the drafter finds its first match immediately
    # and greedy continuations stay periodic
    pats = [rng.integers(1, cfg.vocab_size, size=8,
                         endpoint=False).astype(np.int32)
            for _ in range(slots)]
    prompts = [np.tile(p, prompt_len // len(p) + 1)[:prompt_len]
               for p in pats]

    def admit_all():
        return [int(eng.admit(s, prompts[s], greedy))
                for s in range(slots)]

    firsts = admit_all()
    # warm every program the timed loops can touch: chunk programs for
    # the reachable buckets, and the spec verify program per bucket —
    # a bucket crossing mid-run must swap executables, never compile
    # (the BENCH_r05 623ms/spec-dispatch anomaly)
    ctx_lo, ctx_hi = prompt_len, prompt_len + 1 + chunk + n_steps + k + 2
    eng.warm_buckets(ctx_lo=ctx_lo, ctx_hi=ctx_hi, full=False)
    if eng._bucketed_attn:
        lo = eng.bucket_for(min(ctx_lo + chunk, eng.max_seq))
        hi = eng.bucket_for(min(ctx_hi, eng.max_seq))
        spec_buckets = [b for b in eng._buckets if lo <= b <= hi] or [hi]
    else:
        spec_buckets = [eng.max_seq]
    for b in spec_buckets:
        eng._spec_exec(k, b)
    # record the true greedy continuation — the accept_all draft oracle —
    # and time the plain decode_n baseline on the same work
    eng.decode_n()                      # first-dispatch runtime setup
    t0 = time.perf_counter()
    recs = [eng.decode_n() for _ in range(calls)]
    base_dt = time.perf_counter() - t0
    base_tok_s = n_steps * slots / base_dt
    # continuation per slot, starting right after the warm chunk
    cont = np.concatenate(recs, axis=0).T          # [B, n_steps]

    def run_spec(make_arm, label):
        for s in range(slots):
            eng.release(s)
        first = admit_all()
        warm = eng.decode_n()           # same warm chunk → positions align
        draft_fn, feed = make_arm(first, warm)
        pos = np.zeros(slots, np.int64)
        drafted_tot = accepted_tot = dispatches = 0
        t0 = time.perf_counter()
        while pos.min() < n_steps and dispatches < 4 * n_steps:
            drafts, drafted = draft_fn(pos)
            h = eng.decode_n_launch(drafts=drafts)
            toks = h.wait()                        # [k+1, B]
            rollback = np.maximum(h.budgets - h.accepted, 0)
            if rollback.any():
                eng.spec_ack(rollback)
            emit = h.accepted.astype(np.int64)     # tokens emitted/slot
            pos += emit
            drafted_tot += int(drafted.sum())
            accepted_tot += int(np.minimum(np.maximum(emit - 1, 0),
                                           drafted).sum())
            if feed is not None:
                feed(toks)
            dispatches += 1
        dt = time.perf_counter() - t0
        emitted = int(pos.sum())
        # utilization: every spec dispatch runs all slots over k+1
        # positions; useful = tokens that advanced streams, the rest of
        # the issued grid (rejected drafts) is waste. FLOPs estimated at
        # the mid-run context (exact would need per-dispatch ctx capture)
        from ollama_operator_tpu.runtime.accounting import spec_verify_flops
        issued = float(dispatches * slots * (k + 1))
        ctx_mid = int(prompt_len + 1 + chunk + emitted / (2 * slots))
        flops = dispatches * slots * spec_verify_flops(cfg, ctx_mid, k)
        rec = {"label": label, "tok_s": round(emitted / dt, 2),
               "dispatches": dispatches,
               "ms_per_dispatch": round(dt / max(dispatches, 1) * 1e3, 2),
               "tokens_per_dispatch": round(emitted / max(dispatches, 1),
                                            2),
               "acceptance_rate": round(accepted_tot / drafted_tot, 4)
               if drafted_tot else 0.0,
               "utilization": _analytic_utilization(
                   cfg, dt_s=dt, flops=flops, useful=float(emitted),
                   issued=issued)}
        log(f"bench: spec {label}: {json.dumps(rec)}")
        return rec

    def lookup_arm(first, warm):
        # per-slot incremental bigram index over prompt + emitted stream,
        # exactly what Scheduler._lookup_draft maintains per request
        hists = [list(map(int, prompts[s])) + [first[s]]
                 + [int(t) for t in warm[:, s]] for s in range(slots)]
        idxs = [{} for _ in range(slots)]
        upto = [0] * slots

        def draft_fn(pos):
            d = np.zeros((slots, k), np.int32)
            dr = np.zeros(slots, np.int32)
            for b in range(slots):
                prop, upto[b] = drafter.propose(hists[b], idxs[b],
                                                upto[b], k)
                if prop:
                    d[b, :len(prop)] = prop
                    dr[b] = len(prop)
            return d, dr

        def feed(toks):
            for b in range(slots):
                hists[b] += [int(t) for t in toks[:, b]
                             if int(t) < cfg.vocab_size]
        return draft_fn, feed

    def oracle_arm(first, warm):
        def draft_fn(pos):
            d = np.zeros((slots, k), np.int32)
            for b in range(slots):
                seg = cont[b, int(pos[b]):int(pos[b]) + k]
                d[b, :len(seg)] = seg
            return d, np.full(slots, k, np.int32)
        return draft_fn, None

    def junk_arm(first, warm):
        def draft_fn(pos):
            return (np.full((slots, k), cfg.vocab_size - 1, np.int32),
                    np.full(slots, k, np.int32))
        return draft_fn, None

    lookup = run_spec(lookup_arm, "lookup")
    best = run_spec(oracle_arm, "accept_all")
    worst = run_spec(junk_arm, "reject_all")
    base_ms_per_dispatch = round(base_dt / calls * 1e3, 2)
    dispatch_ratio = round(
        lookup["ms_per_dispatch"] / max(base_ms_per_dispatch, 1e-9), 3)
    rec = {
        "model": model,
        "mode": f"spec_fused_k{k}",
        "tok_s": lookup["tok_s"],          # headline: the REAL drafter
        "baseline_tok_s": round(base_tok_s, 2),
        "baseline_ms_per_dispatch": base_ms_per_dispatch,
        "lookup": lookup,
        "accept_all": best,
        "reject_all": worst,
        "spec_acceptance": lookup["acceptance_rate"],
        "speedup": round(lookup["tok_s"] / base_tok_s, 3),
        "speedup_ceiling": round(best["tok_s"] / base_tok_s, 3),
        "overhead_floor": round(worst["tok_s"] / base_tok_s, 3),
        # per-dispatch: a spec verify (ONE forward over k+1 positions)
        # vs a chunk dispatch (`chunk` sequential forwards) — must stay
        # near or below 1.0; >= 2.0 means launch overhead, not compute
        "dispatch_ratio": dispatch_ratio,
        # headline utilization follows the headline arm (the real drafter)
        "utilization": lookup.get("utilization"),
        "slots": slots, "steps": n_steps, "dtype": dtype,
        "decode_chunk": chunk, "spec_k": k,
        "prompt_len": prompt_len,
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: spec capture done: {json.dumps(rec)}")
    del eng, params
    gc.collect()
    return rec


def _bench_tokenizer(vocab_size: int):
    """A byte-fallback llama tokenizer over a synthetic vocab: any prompt
    text encodes (one byte token per char), so the HTTP capture's prompt
    length is controllable without a real model's vocab."""
    from ollama_operator_tpu.tokenizer.tokenizer import (TT_BYTE, TT_CONTROL,
                                                         TT_NORMAL, Tokenizer)
    toks = ["<unk>", "<s>", "</s>"]
    tt = [TT_CONTROL, TT_CONTROL, TT_CONTROL]
    for i in range(256):
        toks.append(f"<0x{i:02X}>")
        tt.append(TT_BYTE)
    while len(toks) < vocab_size:
        toks.append(f"<fill{len(toks)}>")
        tt.append(TT_NORMAL)
    return Tokenizer("llama", toks[:vocab_size],
                     token_types=tt[:vocab_size], bos_id=1, eos_id=-1)


def measure_http(jax, *, model: str, dtype: str, slots: int, steps: int,
                 seq: int, prompt_len: int, paged: bool, mixed: bool,
                 chunk: int, page_size: int, n_pages: int | None,
                 platform: str, params_cache: dict | None = None,
                 env: dict | None = None) -> dict:
    """One capture through the REAL server: ModelManager + the Ollama
    /api/generate surface over sockets, concurrent streaming clients —
    the surface BASELINE.json's metric names (and the reference probes,
    /root/reference/pkg/model/pod.go:41-64). The delta vs the engine-level
    capture quantifies HTTP + scheduler + tokenize overhead."""
    import gc
    import json as _json
    import tempfile
    import threading
    import urllib.request

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.engine import (EngineConfig,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.service import LoadedModel
    from ollama_operator_tpu.server.app import ModelManager, serve
    from ollama_operator_tpu.server.names import ModelName

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: HTTP capture model={model} dtype={dtype} slots={slots} "
        f"steps={steps} paged={paged}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    if dtype == "int4":
        from ollama_operator_tpu.ops.quant import int4_mm_kernels
        cfg = int4_mm_kernels(cfg, None)

    tok = _bench_tokenizer(cfg.vocab_size)
    name = ModelName.parse("bench").short
    lm = LoadedModel(
        name, cfg, params, tok,
        ecfg=EngineConfig(max_slots=slots, max_seq_len=seq,
                          decode_chunk=chunk, cache_dtype=kv_dtype,
                          paged=paged, page_size=page_size,
                          n_pages=n_pages))
    tmp = tempfile.mkdtemp(prefix="bench-http-")
    manager = ModelManager(tmp, serve_models=True, default_keep_alive=-1)
    manager.loaded = lm
    httpd = serve(manager, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    prompt = "x" * prompt_len          # byte fallback: ~1 token per char
    rng = np.random.default_rng(0)
    lens = (rng.integers(max(8, prompt_len // 4), prompt_len + 1,
                         size=slots) if mixed
            else np.full(slots, prompt_len))

    def generate(n_predict: int, plen: int, out: dict | None = None):
        req = urllib.request.Request(
            base + "/api/generate",
            data=_json.dumps({
                "model": "bench", "prompt": prompt[:plen], "stream": True,
                "options": {"num_predict": n_predict, "temperature": 0.7,
                            "seed": 7}}).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        n = 0
        frames = []                     # (arrival_s, n_chars) per frame
        with urllib.request.urlopen(req, timeout=600) as resp:
            for line in resp:
                if not line.strip():
                    continue
                t = time.perf_counter()
                rec = _json.loads(line)
                if rec.get("done"):
                    # a stream line may carry several tokens (the server
                    # coalesces frames; each carries a whole decode chunk
                    # or more) — the done record's eval_count is the
                    # authoritative token count
                    n = int(rec.get("eval_count") or n)
                else:
                    n += 1
                    frames.append((t, len(rec.get("response") or "")))
        if out is not None:
            out["tokens"] = n
            out["frames"] = frames
            if frames:
                out["ttft"] = frames[0][0] - t0

    def itl_samples(frames, n_tokens):
        """Per-token inter-arrival latencies from frame arrivals. Tokens
        are apportioned to frames by text share (the wire carries no
        per-frame token count); a frame's gap lands on its first token
        and the rest of its tokens arrive in the same write (0 s) — the
        honest accounting for coalesced frames, so itl_p95 surfaces the
        burstiness that coalescing trades for throughput."""
        if len(frames) < 2 or n_tokens <= 0:
            return []
        chars = [max(c, 1) for _, c in frames]
        tot = sum(chars)
        samples = []
        for (t_prev, _), (t, _), c in zip(frames, frames[1:], chars[1:]):
            k = max(1, round(n_tokens * c / tot))
            samples.append(t - t_prev)
            samples.extend([0.0] * (k - 1))
        return samples

    generate(2, int(lens[0]))          # warm the serving path end to end
    # recompile snapshot after warmup: the measured window must compile 0
    rc0 = sum(getattr(lm.scheduler.engine, "recompiles", {}).values())

    results = [dict() for _ in range(slots)]
    threads = [threading.Thread(target=generate,
                                args=(steps, int(lens[i]), results[i]))
               for i in range(slots)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    total_tokens = sum(r.get("tokens", 0) for r in results)
    ttfts = [r["ttft"] for r in results if "ttft" in r]
    itls = [s for r in results
            for s in itl_samples(r.get("frames", []), r.get("tokens", 0))]
    n_frames = sum(len(r.get("frames", ())) for r in results)
    rec = {
        "model": model,
        "surface": "http",
        "tok_s": round(total_tokens / wall, 2),
        "ttft_p50_ms": round(float(np.median(ttfts)) * 1e3, 1),
        "ttft_p95_ms": round(float(np.percentile(ttfts, 95)) * 1e3, 1),
        "itl_p95_ms": (round(float(np.percentile(itls, 95)) * 1e3, 1)
                       if itls else None),
        "stream_frames": n_frames,
        "tokens_per_frame": (round(total_tokens / n_frames, 1)
                             if n_frames else None),
        "slots": slots,
        "steps": steps,
        "dtype": dtype,
        "paged": paged,
        "mixed_len": mixed,
        "prompt_len": int(np.max(lens)),
        "total_tokens": total_tokens,
        "wall_s": round(wall, 2),
        "utilization": _sched_utilization(lm.scheduler, rc0),
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: HTTP capture done: {json.dumps(rec)}")
    httpd.shutdown()
    manager.loaded = None
    lm.unload()                        # stop the scheduler decode thread
    del lm, params
    gc.collect()
    return rec


def measure_mixed(jax, *, model: str, dtype: str, slots: int, steps: int,
                  seq: int, prompt_len: int, paged: bool, mixed: bool,
                  chunk: int, page_size: int, n_pages: int | None,
                  platform: str, params_cache: dict | None = None,
                  env: dict | None = None) -> dict:
    """Mixed-load arm for the stall-free batching work (ISSUE 3): a steady
    background decode batch with Poisson long-prompt arrivals on top, run
    twice through the REAL scheduler — overlap on (chunked prefill +
    async double-buffered dispatch) vs overlap off (one-shot prefill,
    synchronous dispatch). The background streams' ITL p99 is the stall
    the arrivals inflict; the arrivals' TTFT p95 is what chunking trades
    for it. Counter deltas (admission_stall_ms, prefill_chunks) come from
    the same /metrics series production dashboards read."""
    import gc
    import threading

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime import accounting as acct_mod
    from ollama_operator_tpu.runtime import trace as trace_mod
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.scheduler import Scheduler
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: mixed-load capture model={model} dtype={dtype} "
        f"slots={slots} steps={steps} seq={seq}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    if dtype == "int4":
        from ollama_operator_tpu.ops.quant import int4_mm_kernels
        cfg = int4_mm_kernels(cfg, None)
    # the model config caps the servable context (Engine takes the min),
    # so size the decode chunk and prefill piece to the REAL context —
    # at smoke scale (tiny model, 128 ctx) the defaults would leave no
    # room for a multi-piece prompt and the arm would measure nothing
    serve_seq = min(seq, cfg.max_seq_len)
    chunk_eff = min(chunk, max(4, serve_seq // 16))
    # prefill piece: TPU_PREFILL_CHUNK if set, else small enough that the
    # arrival prompts below are genuinely multi-piece at smoke scale
    piece = (int(os.environ.get("TPU_PREFILL_CHUNK", "0") or 0)
             or chunk_eff * 2)
    # paged=True runs the same A/B on the paged engine (ISSUE 5): the
    # overlap arm then double-buffers through the epoch fence — frees,
    # evictions and preemptions during an in-flight dispatch ride the
    # page quarantine instead of returning to the pool immediately.
    # Pool sized generously so preemption churn stays out of the ITL
    # signal and the arm measures dispatch overlap, not page pressure.
    if paged:
        ps = max(8, min(page_size, serve_seq // 8))
        pool = n_pages or slots * (-(-serve_seq // ps) + 2)
        ecfg = EngineConfig(max_slots=slots, max_seq_len=seq,
                            decode_chunk=chunk_eff,
                            cache_dtype=kv_dtype, paged=True,
                            page_size=ps, n_pages=pool,
                            min_prefill_bucket=max(16, min(64, piece)))
    else:
        ecfg = EngineConfig(max_slots=slots, max_seq_len=seq,
                            decode_chunk=chunk_eff,
                            cache_dtype=kv_dtype, paged=False,
                            min_prefill_bucket=max(16, min(64, piece)))
    eng = Engine(cfg, params, ecfg=ecfg)
    # AOT-warm the programs BOTH arms dispatch (decode, admit buckets,
    # batched admit) so neither arm pays compiles in its measured window
    eng.warm_buckets()
    piece_b = eng.bucket_for(min(piece, eng.max_seq))
    # arrival prompts land in the LARGEST prefill bucket (6 pieces floor
    # puts them past the penultimate one): the off arm then pays a full
    # whole-context one-shot prefill per admission — the stall this work
    # removes — while the on arm pays it one piece at a time
    long_len = min(max(6 * piece_b, prompt_len),
                   eng.max_seq - piece_b - chunk_eff - 2)
    n_bg = max(1, min(slots - 2, slots * 3 // 4))
    n_arr = max(4, min(slots - n_bg, 8))
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    rng = np.random.default_rng(0)
    bg_prompts = [rng.integers(1, cfg.vocab_size, size=16,
                               endpoint=False).astype(np.int32)
                  for _ in range(n_bg)]
    arr_prompts = [rng.integers(1, cfg.vocab_size, size=long_len,
                                endpoint=False).astype(np.int32)
                   for _ in range(n_arr)]
    arr_gap_s = float(os.environ.get("BENCH_MIXED_GAP_S", "0.05"))

    def run_arm(overlap: bool, tracing: bool = True,
                acct: bool = True) -> dict:
        # request-lifecycle tracing (runtime/trace.py) is on by default;
        # the tracing=False arm flips the module switch so its Scheduler
        # hands every request the shared NULL_TRACE — the A/B for the
        # ≤2% tok/s overhead budget tracing must stay under. The
        # acct=False arm does the same for utilization accounting
        # (runtime/accounting.py): its Scheduler gets NULL_ACCOUNTING,
        # the A/B for the accounting overhead budget.
        prev_tracing = trace_mod.TRACE_ENABLED
        prev_acct = acct_mod.ACCOUNTING_ENABLED
        trace_mod.TRACE_ENABLED = tracing
        acct_mod.ACCOUNTING_ENABLED = acct
        sched = Scheduler(eng, prefill_chunk=(piece_b if overlap else 0),
                          async_dispatch=overlap)
        try:
            # warmup: one long admission + a decode chunk so the programs
            # specific to this arm's admission path (one-shot long bucket
            # vs chunked extend pieces) compile before the measured
            # window; everything shared was AOT-warmed above
            w = sched.submit(list(arr_prompts[0]), greedy,
                             max_tokens=chunk_eff)
            for _ in w.chunks():
                pass
            # counter snapshots AFTER warmup: compile time is not stall,
            # and arm-specific warmup compiles are not recompiles — the
            # measured window's recompile delta must stay 0
            stall0 = METRICS.get("tpu_model_admission_stall_ms_total")
            chunks0 = METRICS.get("tpu_model_prefill_chunks_total")
            rc0 = sum(getattr(eng, "recompiles", {}).values())
            stop_bg = threading.Event()
            bg = []
            readers = []

            def bg_runner(p, rec, box):
                # respawn on completion: the background batch must keep
                # decoding for the whole arrival window
                while not stop_bg.is_set():
                    try:
                        r = sched.submit(list(p), greedy,
                                         max_tokens=eng.max_seq)
                    except Exception:   # shedding/shutdown at teardown
                        return
                    box["req"] = r
                    try:
                        for toks in r.chunks():
                            rec.append((time.perf_counter(), len(toks)))
                    except Exception:   # cancelled at teardown
                        return

            for p in bg_prompts:
                rec: list = []
                box: dict = {}
                t = threading.Thread(target=bg_runner, args=(p, rec, box))
                t.start()
                bg.append((box, rec))
                readers.append(t)
            t_wait = time.perf_counter()
            while (any(not rec for _, rec in bg)
                   and time.perf_counter() - t_wait < 120):
                time.sleep(0.005)

            arr = []
            arr_threads = []

            def arr_reader(req, out):
                try:
                    for _ in req.chunks():
                        pass
                    out["ttft"] = req.stats.ttft_s
                except Exception as e:
                    out["error"] = f"{type(e).__name__}: {e}"

            rng_arr = np.random.default_rng(7)  # same draw both arms
            t0 = time.perf_counter()
            for p in arr_prompts:
                time.sleep(float(rng_arr.exponential(arr_gap_s)))
                r = sched.submit(list(p), greedy, max_tokens=chunk)
                out: dict = {}
                th = threading.Thread(target=arr_reader, args=(r, out))
                th.start()
                arr.append(out)
                arr_threads.append(th)
            for th in arr_threads:
                th.join(timeout=600)
            t1 = time.perf_counter()
            stop_bg.set()
            for box, _ in bg:
                r = box.get("req")
                if r is not None:
                    r.cancel()
            for t in readers:
                t.join(timeout=60)

            # per-token ITL from bg frame arrivals inside the arrival
            # window: a k-token chunk's gap lands on its first token, the
            # rest arrive in the same write (0 s) — same accounting as
            # measure_http's itl_samples
            itls = []
            n_bg_tokens = 0
            for _, rec in bg:
                for (tp, _), (t, k) in zip(rec, rec[1:]):
                    if tp < t0 or t > t1:
                        continue
                    itls.append(t - tp)
                    itls.extend([0.0] * (k - 1))
                    n_bg_tokens += k
            ttfts = [o["ttft"] for o in arr if "ttft" in o]
            errors = [o["error"] for o in arr if "error" in o]
            return {
                "overlap": overlap,
                "itl_p99_ms": (round(float(np.percentile(itls, 99)) * 1e3,
                                     2) if itls else None),
                "itl_p95_ms": (round(float(np.percentile(itls, 95)) * 1e3,
                                     2) if itls else None),
                "ttft_p95_ms": (round(float(np.percentile(ttfts, 95))
                                      * 1e3, 1) if ttfts else None),
                "bg_tok_s": (round(n_bg_tokens / (t1 - t0), 2)
                             if t1 > t0 and n_bg_tokens else None),
                "admission_stall_ms": round(
                    METRICS.get("tpu_model_admission_stall_ms_total")
                    - stall0, 1),
                "stall_ms_per_arrival": round(
                    (METRICS.get("tpu_model_admission_stall_ms_total")
                     - stall0) / max(1, len(arr_prompts)), 1),
                "prefill_chunks": int(
                    METRICS.get("tpu_model_prefill_chunks_total")
                    - chunks0),
                "arrival_errors": errors or None,
                "utilization": _sched_utilization(sched, rc0),
            }
        finally:
            trace_mod.TRACE_ENABLED = prev_tracing
            acct_mod.ACCOUNTING_ENABLED = prev_acct
            sched.shutdown()
            for s in range(eng.n_slots):
                try:
                    eng.release(s)
                except Exception:
                    pass

    on = run_arm(True)
    off = run_arm(False)
    # tracing overhead arm: same overlap-on load with per-request span
    # tracing disabled. bg tok/s with tracing on must stay within 2% of
    # this — the budget the ISSUE-7 tracing layer was designed to (an
    # event append is one GIL-atomic list.append per *chunk*, not per
    # token). Set BENCH_ASSERT_TRACE_OVERHEAD=1 to hard-fail the run on
    # a violation (smoke-scale CPU arms are too noisy to gate by
    # default; the TPU bench job opts in).
    notrace = run_arm(True, tracing=False)
    trace_ratio = (round(on["bg_tok_s"] / notrace["bg_tok_s"], 3)
                   if on.get("bg_tok_s") and notrace.get("bg_tok_s")
                   else None)
    if trace_ratio is not None and trace_ratio < 0.98:
        log(f"bench: WARNING tracing-on bg tok/s is {trace_ratio} of "
            f"tracing-off (budget: >= 0.98)")
        if os.environ.get("BENCH_ASSERT_TRACE_OVERHEAD") == "1":
            raise AssertionError(
                f"tracing overhead over budget: tok/s ratio {trace_ratio}"
                f" < 0.98 (on={on['bg_tok_s']} off={notrace['bg_tok_s']})")
    # accounting overhead arm: same overlap-on load with utilization
    # accounting disabled (the Scheduler gets NULL_ACCOUNTING). bg tok/s
    # with accounting on must stay within 2% of this — the budget the
    # closed-form FLOPs model was designed to (one arithmetic-series
    # evaluation per *dispatch*, not per token). Set
    # BENCH_ASSERT_ACCOUNTING=1 to hard-fail on a violation (smoke-scale
    # CPU arms are too noisy to gate by default; the TPU job opts in).
    noacct = run_arm(True, acct=False)
    acct_ratio = (round(on["bg_tok_s"] / noacct["bg_tok_s"], 3)
                  if on.get("bg_tok_s") and noacct.get("bg_tok_s")
                  else None)
    if acct_ratio is not None and acct_ratio < 0.98:
        log(f"bench: WARNING accounting-on bg tok/s is {acct_ratio} of "
            f"accounting-off (budget: >= 0.98)")
        if os.environ.get("BENCH_ASSERT_ACCOUNTING") == "1":
            raise AssertionError(
                f"accounting overhead over budget: tok/s ratio "
                f"{acct_ratio} < 0.98 (on={on['bg_tok_s']} "
                f"off={noacct['bg_tok_s']})")
    rec = {
        "model": model,
        # "mixed_paged" is the ISSUE-5 headline capture: its
        # itl_p99_ratio is the paged async-vs-sync dispatch ratio
        "mode": "mixed_paged" if paged else "mixed",
        "overlap_on": on,
        "overlap_off": off,
        "itl_p99_ratio": (round(off["itl_p99_ms"] / on["itl_p99_ms"], 2)
                          if on.get("itl_p99_ms") and off.get("itl_p99_ms")
                          else None),
        "bg_tok_s_ratio": (round(on["bg_tok_s"] / off["bg_tok_s"], 3)
                           if on.get("bg_tok_s") and off.get("bg_tok_s")
                           else None),
        # tracing-on vs tracing-off throughput on the same overlap-on
        # load; >= 0.98 is the tracing overhead budget
        "trace_tok_s_ratio": trace_ratio,
        "trace_overhead_ok": (trace_ratio >= 0.98
                              if trace_ratio is not None else None),
        "overlap_on_notrace": notrace,
        # accounting-on vs accounting-off throughput on the same
        # overlap-on load; >= 0.98 is the accounting overhead budget
        "acct_tok_s_ratio": acct_ratio,
        "acct_overhead_ok": (acct_ratio >= 0.98
                             if acct_ratio is not None else None),
        "overlap_on_noacct": noacct,
        "utilization": on.get("utilization"),
        "slots": slots,
        "dtype": dtype,
        "paged": paged,
        "prompt_len": int(long_len),
        "prefill_piece": int(piece_b),
        "decode_chunk": chunk_eff,
        "seq": seq,
        "n_background": n_bg,
        "n_arrivals": n_arr,
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: mixed-load capture done: {json.dumps(rec)}")
    del eng, params
    gc.collect()
    return rec


def measure_prefix(jax, *, model: str, dtype: str, slots: int, steps: int,
                   seq: int, prompt_len: int, paged: bool, mixed: bool,
                   chunk: int, page_size: int, n_pages: int | None,
                   platform: str, params_cache: dict | None = None,
                   env: dict | None = None) -> dict:
    """Shared-system-prompt arm for the radix prefix cache (ISSUE 4):
    K concurrent requests sharing a long common prefix (the multi-tenant
    "same system prompt, different question" shape), run twice through
    the REAL scheduler — cache on (radix page stitch) vs cache off
    (TPU_PREFIX_CACHE=0, i.e. the parked-slot-only baseline). Headlines:
    arrival TTFT p95 and the computed-vs-reused prompt-token split from
    the same tpu_model_prefix_{hit,miss}_tokens_total counters production
    dashboards read."""
    import gc
    import threading

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.scheduler import Scheduler
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: prefix-cache capture model={model} dtype={dtype} "
        f"slots={slots} seq={seq}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    if dtype == "int4":
        from ollama_operator_tpu.ops.quant import int4_mm_kernels
        cfg = int4_mm_kernels(cfg, None)
    serve_seq = min(seq, cfg.max_seq_len)
    # page size small enough that the shared prefix spans several pages
    # even at smoke scale (radix nodes are page-granular)
    ps = max(8, min(page_size, serve_seq // 8))
    # the ISSUE-4 shape: 512-token common prefix where the context allows,
    # half the servable context otherwise
    prefix_len = min(512, serve_seq // 2)
    tail_len = max(8, min(32, serve_seq // 16))
    gen_tokens = max(4, min(16, steps // 4))
    k_conc = max(4, min(slots, 8))
    chunk_eff = min(chunk, max(4, serve_seq // 16))
    rng = np.random.default_rng(3)
    prefix = rng.integers(1, cfg.vocab_size, size=prefix_len,
                          endpoint=False).astype(np.int32)
    tails = [rng.integers(1, cfg.vocab_size, size=tail_len,
                          endpoint=False).astype(np.int32)
             for _ in range(k_conc + 2)]
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    pool = (n_pages
            or slots * (-(-serve_seq // ps) + 2) + prefix_len // ps)

    def run_arm(cache_on: bool, overlap: bool = True) -> dict:
        saved = os.environ.get("TPU_PREFIX_CACHE")
        if not cache_on:
            os.environ["TPU_PREFIX_CACHE"] = "0"
        try:
            eng = Engine(cfg, params,
                         ecfg=EngineConfig(max_slots=slots, max_seq_len=seq,
                                           decode_chunk=chunk_eff,
                                           cache_dtype=kv_dtype, paged=True,
                                           page_size=ps, n_pages=pool,
                                           min_prefill_bucket=16))
        finally:
            if saved is None:
                os.environ.pop("TPU_PREFIX_CACHE", None)
            else:
                os.environ["TPU_PREFIX_CACHE"] = saved
        eng.warm_buckets()
        # overlap=False pins the arm to synchronous dispatch (the
        # TPU_ASYNC_DISPATCH=0 baseline of the ISSUE-5 A/B); otherwise
        # the paged scheduler double-buffers through the epoch fence
        sched = Scheduler(eng, async_dispatch=overlap)
        try:
            def run_one(tail, out):
                r = sched.submit(list(prefix) + list(tail), greedy,
                                 max_tokens=gen_tokens)
                try:
                    for _ in r.chunks():
                        pass
                    out["ttft"] = r.stats.ttft_s
                    out["reused"] = getattr(r.stats, "n_reused", 0)
                except Exception as e:
                    out["error"] = f"{type(e).__name__}: {e}"

            # warm request populates the cache (arm A) / parks (arm B);
            # one more unmeasured follower compiles the stitched-extend
            # path so neither arm pays compiles in its measured window
            for t in tails[:2]:
                run_one(t, {})
            hit0 = METRICS.get("tpu_model_prefix_hit_tokens_total")
            miss0 = METRICS.get("tpu_model_prefix_miss_tokens_total")
            rc0 = sum(getattr(eng, "recompiles", {}).values())
            outs = [{} for _ in range(k_conc)]
            threads = [threading.Thread(target=run_one, args=(t, o))
                       for t, o in zip(tails[2:], outs)]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=600)
            t1 = time.perf_counter()
            hits = METRICS.get("tpu_model_prefix_hit_tokens_total") - hit0
            misses = (METRICS.get("tpu_model_prefix_miss_tokens_total")
                      - miss0)
            ttfts = [o["ttft"] for o in outs if "ttft" in o]
            errors = [o["error"] for o in outs if "error" in o]
            return {
                "cache_on": cache_on,
                "async": overlap,
                "ttft_p50_ms": (round(float(np.percentile(ttfts, 50)) * 1e3,
                                      1) if ttfts else None),
                "ttft_p95_ms": (round(float(np.percentile(ttfts, 95)) * 1e3,
                                      1) if ttfts else None),
                "reused_tokens": int(hits),
                "computed_tokens": int(misses),
                "hit_rate": (round(hits / (hits + misses), 3)
                             if hits + misses else None),
                "wall_s": round(t1 - t0, 2),
                "radix_nodes": int(getattr(eng, "radix_nodes", 0)),
                "radix_pages": int(getattr(eng, "radix_pages", 0)),
                "errors": errors or None,
                "utilization": _sched_utilization(sched, rc0),
            }
        finally:
            sched.shutdown()
            for s in range(eng.n_slots):
                try:
                    eng.release(s)
                except Exception:
                    pass
            del eng
            gc.collect()

    on = run_arm(True)
    off = run_arm(False)
    # third arm (ISSUE 5): cache on, synchronous dispatch — isolates the
    # epoch-fenced double-buffering win on the radix-hit serving shape
    sync = run_arm(True, overlap=False)

    # tiered-KV arms: a churn shape whose radix working set overflows the
    # HBM pool (revisits only survive via tier-1 host spill/restitch) and
    # a fleet shape that round-trips a tier-2 prefix snapshot into a
    # fresh engine. Separate record keys — the legacy three-arm shape
    # (cache_on/cache_off/cache_on_sync) stays pinned for dashboards.
    tier_arms = os.environ.get("BENCH_TIER_ARMS", "1") != "0"
    churn_on = churn_off = fleet = None
    if tier_arms:
        c_pages = 4                       # pages per churn prefix
        c_prefix_len = c_pages * ps
        m_prefixes = 4
        c_rounds = 3
        c_tail = max(4, min(8, tail_len))
        c_gen = max(2, min(4, gen_tokens))
        churn_prefixes = [rng.integers(1, cfg.vocab_size, size=c_prefix_len,
                                       endpoint=False).astype(np.int32)
                          for _ in range(m_prefixes)]
        # single slot; pool retains ~1.5 prefixes of radix residency
        # beyond the slot's serving need, so the m-prefix working set
        # (m * c_pages pages) cannot fit — round-robin revisits always
        # land on the LRU (most evicted) prefix, the maximal-churn shape
        c_need = -(-(c_prefix_len + c_tail + c_gen) // ps) + 2
        c_pool = c_need + c_pages + 2

        def _tier_tokens(name):
            return sum(METRICS.get(name, f'{{tier="{t}"}}')
                       for t in ("0", "1", "2"))

        def _with_tiering(host_gb: str):
            saved = {k: os.environ.get(k)
                     for k in ("TPU_HOST_CACHE_GB",
                               "TPU_HOST_CACHE_BREAK_EVEN")}
            os.environ["TPU_HOST_CACHE_GB"] = host_gb
            # flat 1-token floor: restitch whenever there is anything
            # to restitch — keeps the arms deterministic across
            # backends (the FLOPs break-even is platform-dependent)
            os.environ["TPU_HOST_CACHE_BREAK_EVEN"] = "1"
            return saved

        def _restore_env(saved):
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

        def run_churn(host_gb: str) -> dict:
            saved = _with_tiering(host_gb)
            try:
                eng = Engine(cfg, params,
                             ecfg=EngineConfig(max_slots=1, max_seq_len=seq,
                                               decode_chunk=chunk_eff,
                                               cache_dtype=kv_dtype,
                                               paged=True, page_size=ps,
                                               n_pages=c_pool,
                                               min_prefill_bucket=16))
            finally:
                _restore_env(saved)
            eng.warm_buckets()
            sched = Scheduler(eng)
            try:
                hit0 = _tier_tokens("tpu_model_tier_hit_tokens_total")
                miss0 = _tier_tokens("tpu_model_tier_miss_tokens_total")
                sp0 = METRICS.get("tpu_model_spilled_pages_total")
                fb0 = METRICS.get("tpu_model_async_fallback_total")
                ttfts, errors = [], []
                for rnd in range(c_rounds):
                    for pfx in churn_prefixes:
                        tail = rng.integers(1, cfg.vocab_size, size=c_tail,
                                            endpoint=False).astype(np.int32)
                        r = sched.submit(list(pfx) + list(tail), greedy,
                                         max_tokens=c_gen)
                        try:
                            for _ in r.chunks():
                                pass
                            if rnd:       # revisit rounds only
                                ttfts.append(r.stats.ttft_s)
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"{type(e).__name__}: {e}")
                        # retire in-flight epochs so the NEXT admission's
                        # LRU eviction sees a quiescent pool and can
                        # spill instead of plainly freeing
                        try:
                            eng.fence_quiesce()
                        except Exception:  # noqa: BLE001
                            pass
                hits = (_tier_tokens("tpu_model_tier_hit_tokens_total")
                        - hit0)
                misses = (_tier_tokens("tpu_model_tier_miss_tokens_total")
                          - miss0)
                return {
                    "host_gb": host_gb,
                    "hit_tokens": int(hits),
                    "miss_tokens": int(misses),
                    "hit_rate": (round(hits / (hits + misses), 3)
                                 if hits + misses else None),
                    "spilled_pages": int(METRICS.get(
                        "tpu_model_spilled_pages_total") - sp0),
                    "host_pages": int(getattr(eng, "host_cache_pages", 0)),
                    "ttft_p50_ms": (round(
                        float(np.percentile(ttfts, 50)) * 1e3, 1)
                        if ttfts else None),
                    "async_fallbacks": int(METRICS.get(
                        "tpu_model_async_fallback_total") - fb0),
                    "errors": errors or None,
                }
            finally:
                sched.shutdown()
                for s in range(eng.n_slots):
                    try:
                        eng.release(s)
                    except Exception:
                        pass
                del eng
                gc.collect()

        def run_fleet() -> dict:
            import tempfile

            from ollama_operator_tpu.gguf import store as gstore
            saved = _with_tiering("0.5")
            f_ecfg = EngineConfig(max_slots=1, max_seq_len=seq,
                                  decode_chunk=chunk_eff,
                                  cache_dtype=kv_dtype, paged=True,
                                  page_size=ps, n_pages=c_pool + 4,
                                  min_prefill_bucket=16)
            fprefix = rng.integers(1, cfg.vocab_size, size=c_prefix_len,
                                   endpoint=False).astype(np.int32)
            out = {"imported_pages": 0, "first_reused_tokens": 0,
                   "tier2_hit_tokens": 0, "warm_first_hit": False}

            def serve_one(eng, sched):
                tail = rng.integers(1, cfg.vocab_size, size=c_tail,
                                    endpoint=False).astype(np.int32)
                r = sched.submit(list(fprefix) + list(tail), greedy,
                                 max_tokens=c_gen)
                for _ in r.chunks():
                    pass
                # the prefix is donated into the radix by the scheduler
                # thread just after the stream ends — wait it out
                for _ in range(200):
                    if sched.n_active == 0 and eng.radix_nodes > 0:
                        break
                    time.sleep(0.01)
                return int(getattr(r.stats, "n_reused", 0))

            try:
                # replica A serves the shared prefix, then "drains": its
                # hottest prefixes round-trip through the shared volume
                engA = Engine(cfg, params, ecfg=f_ecfg)
                engA.warm_buckets()
                schedA = Scheduler(engA)
                try:
                    serve_one(engA, schedA)
                    blob = engA.export_prefixes()
                finally:
                    schedA.shutdown()
                    del engA
                    gc.collect()
                if blob is None:
                    out["error"] = "export produced no snapshot"
                    return out
                with tempfile.TemporaryDirectory() as td:
                    gstore.save_prefix_snapshot(td, "bench", blob)
                    blob = gstore.load_prefix_snapshot(td, "bench")
                # replica B wakes cold, imports the fleet snapshot, and
                # must answer its FIRST shared-prefix request warm
                engB = Engine(cfg, params, ecfg=f_ecfg)
                out["imported_pages"] = int(engB.import_prefixes(blob))
                engB.warm_buckets()
                schedB = Scheduler(engB)
                try:
                    t2_0 = METRICS.get("tpu_model_tier_hit_tokens_total",
                                       '{tier="2"}')
                    out["first_reused_tokens"] = serve_one(engB, schedB)
                    out["tier2_hit_tokens"] = int(METRICS.get(
                        "tpu_model_tier_hit_tokens_total", '{tier="2"}')
                        - t2_0)
                finally:
                    schedB.shutdown()
                    del engB
                    gc.collect()
                out["warm_first_hit"] = (out["first_reused_tokens"] > 0
                                         and out["tier2_hit_tokens"] > 0)
                return out
            except Exception as e:  # noqa: BLE001
                out["error"] = f"{type(e).__name__}: {e}"
                return out
            finally:
                _restore_env(saved)

        churn_on = run_churn("0.5")
        churn_off = run_churn("0")
        fleet = run_fleet()
    rec = {
        "model": model,
        "mode": "prefix",
        "cache_on": on,
        "cache_off": off,
        "cache_on_sync": sync,
        # >=2.0 on TPU at K>=4 is the ISSUE-4 acceptance bar; the
        # CPU smoke asserts hit_rate only (TTFT is noise at tiny scale)
        "prefix_ttft_ratio": (round(off["ttft_p95_ms"] / on["ttft_p95_ms"],
                                    2)
                              if on.get("ttft_p95_ms")
                              and off.get("ttft_p95_ms") else None),
        "prefix_hit_rate": on.get("hit_rate"),
        # sync/async TTFT on the same cache-on shape: >1 means the
        # overlapped dispatch is ahead even with radix hits in play
        "paged_async_ttft_ratio": (round(
            sync["ttft_p95_ms"] / on["ttft_p95_ms"], 2)
            if on.get("ttft_p95_ms") and sync.get("ttft_p95_ms")
            else None),
        "utilization": on.get("utilization"),
        "slots": slots,
        "dtype": dtype,
        "paged": True,
        "page_size": int(ps),
        "prefix_len": int(prefix_len),
        "tail_len": int(tail_len),
        "k_concurrent": int(k_conc),
        "seq": seq,
    }
    if tier_arms:
        rec["churn_on"] = churn_on
        rec["churn_off"] = churn_off
        # hit rate the tiering holds where the tiering-off pool collapses
        rec["churn_hit_rate"] = churn_on.get("hit_rate")
        rec["churn_hit_rate_off"] = churn_off.get("hit_rate")
        # >1 means restitching from host beats recomputing the prefill
        # the churned pool threw away (TTFT p50 over revisit rounds)
        rec["churn_ttft_ratio"] = (round(churn_off["ttft_p50_ms"]
                                         / churn_on["ttft_p50_ms"], 2)
                                   if churn_on.get("ttft_p50_ms")
                                   and churn_off.get("ttft_p50_ms")
                                   else None)
        rec["fleet"] = fleet
    if env:
        rec["env"] = dict(env)
    log(f"bench: prefix-cache capture done: {json.dumps(rec)}")
    del params
    gc.collect()
    return rec


def measure_overload(jax, *, model: str, dtype: str, slots: int, steps: int,
                     seq: int, prompt_len: int, paged: bool, mixed: bool,
                     chunk: int, page_size: int, n_pages: int | None,
                     platform: str, params_cache: dict | None = None,
                     env: dict | None = None) -> dict:
    """Overload-discipline arm (ISSUE 8): drive the REAL scheduler at
    ~5x slot capacity with a 20/30/50 high/normal/best_effort mix across
    3 tenants, against an unloaded baseline of solo high-priority
    requests. Acceptance: high-class p99 TTFT stays within 2x of the
    unloaded baseline (priority preemption + strict-priority dequeue do
    the work) while best_effort absorbs the overload as shed/throttled
    — not errors — and every SLO early-reject carries a finite computed
    Retry-After. ``tpu_model_shed_total{class="high"}`` must stay 0.
    BENCH_ASSERT_OVERLOAD=1 hard-fails on a violation (CPU smoke asserts
    included — the invariants are scheduling policy, not device perf)."""
    import gc
    import threading

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.admission import shed_labels
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.errors import DeadlineExceeded
    from ollama_operator_tpu.runtime.scheduler import (Scheduler,
                                                       SchedulerBusy,
                                                       SchedulerOverloaded)
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: overload capture model={model} dtype={dtype} "
        f"slots={slots} seq={seq}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    serve_seq = min(seq, cfg.max_seq_len)
    # short decode chunks: the preemption quantum is one dispatch, and a
    # high arrival's TTFT rides on how fast the current dispatch retires
    chunk_eff = max(4, min(chunk, 8))
    ecfg = EngineConfig(max_slots=slots, max_seq_len=seq,
                        decode_chunk=chunk_eff, cache_dtype=kv_dtype,
                        paged=False,
                        min_prefill_bucket=16)
    eng = Engine(cfg, params, ecfg=ecfg)
    eng.warm_buckets()
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    rng = np.random.default_rng(11)
    p_len = max(16, min(prompt_len, serve_seq // 4))
    max_new = max(12, min(24, serve_seq // 8))
    prompt_of = lambda: rng.integers(  # noqa: E731
        1, cfg.vocab_size, size=p_len, endpoint=False).astype(np.int32)

    # -- unloaded baseline: solo high-priority requests, one at a time --
    def run_baseline(sched) -> list:
        ttfts = []
        for _ in range(6):
            r = sched.submit(list(prompt_of()), greedy,
                             max_tokens=max_new, priority="high")
            for _ in r.chunks():
                pass
            ttfts.append(r.stats.ttft_s)
        return ttfts

    # -- overload arm: closed-loop workers at ~5x slot capacity --------
    CLASSES = (["high"] * 2 + ["normal"] * 3 + ["best_effort"] * 5)
    TENANTS = ("alpha", "beta", "gamma")

    def run_overload(sched, n_workers: int, reqs_per_worker: int) -> dict:
        res = {c: {"ttfts": [], "done": 0, "shed": 0, "early": 0,
                   "errors": 0, "retry_afters": []}
               for c in ("high", "normal", "best_effort")}
        lock = threading.Lock()

        def worker(wid: int):
            cls = CLASSES[wid % len(CLASSES)]
            tenant = TENANTS[wid % len(TENANTS)]
            # half the best_effort load declares a tight TTFT SLO so the
            # queue model's early-reject path is exercised under real
            # backlog (the other half rides the queue to completion)
            slo = 0.001 if (cls == "best_effort" and wid % 2 == 0) else None
            wrng = np.random.default_rng(100 + wid)
            for _ in range(reqs_per_worker):
                p = wrng.integers(1, cfg.vocab_size, size=p_len,
                                  endpoint=False).astype(np.int32)
                try:
                    r = sched.submit(list(p), greedy, max_tokens=max_new,
                                     priority=cls, tenant=tenant,
                                     ttft_slo_s=slo)
                except SchedulerOverloaded as e:
                    with lock:
                        res[cls]["early"] += 1
                        res[cls]["retry_afters"].append(
                            getattr(e, "retry_after_s", None))
                    continue
                except SchedulerBusy:
                    with lock:
                        res[cls]["shed"] += 1
                    continue
                try:
                    for _ in r.chunks():
                        pass
                    with lock:
                        res[cls]["done"] += 1
                        res[cls]["ttfts"].append(r.stats.ttft_s)
                except DeadlineExceeded as e:
                    with lock:
                        res[cls]["shed"] += 1
                        res[cls]["retry_afters"].append(
                            getattr(e, "retry_after_s", None))
                except Exception:
                    with lock:
                        res[cls]["errors"] += 1

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        return res

    shed0 = {c: {k: METRICS.get("tpu_model_shed_total", shed_labels(c, k))
                 for k in ("queue_full", "deadline", "slo_predict",
                           "tenant_cap")}
             for c in ("high", "normal", "best_effort")}
    tok0 = {t: METRICS.get("tpu_model_tenant_decode_tokens_total",
                           f'{{tenant="{t}"}}') for t in TENANTS}

    sched = Scheduler(eng, max_queue=3 * slots, prefill_chunk=0,
                      async_dispatch=False)
    try:
        # warmup: populate the dispatch histograms the queue model reads
        w = sched.submit(list(prompt_of()), greedy, max_tokens=chunk_eff)
        for _ in w.chunks():
            pass
        rc0 = sum(getattr(eng, "recompiles", {}).values())
        base_ttfts = run_baseline(sched)
        n_workers = 5 * slots
        over = run_overload(sched, n_workers,
                            reqs_per_worker=int(os.environ.get(
                                "BENCH_OVERLOAD_REQS", "4")))
        base_after = run_baseline(sched)   # recovery: drained queue
        util = _sched_utilization(sched, rc0)
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            try:
                eng.release(s)
            except Exception:
                pass

    shed_delta = {
        c: {k: int(METRICS.get("tpu_model_shed_total", shed_labels(c, k))
                   - shed0[c][k])
            for k in ("queue_full", "deadline", "slo_predict",
                      "tenant_cap")}
        for c in ("high", "normal", "best_effort")}
    tok_delta = {t: METRICS.get("tpu_model_tenant_decode_tokens_total",
                                f'{{tenant="{t}"}}') - tok0[t]
                 for t in TENANTS}
    tok_total = sum(tok_delta.values())
    tenant_share = {t: (round(v / tok_total, 3) if tok_total else None)
                    for t, v in tok_delta.items()}

    def p99(xs):
        return (round(float(np.percentile(xs, 99)) * 1e3, 1)
                if xs else None)

    base_p99 = p99(base_ttfts)
    high_p99 = p99(over["high"]["ttfts"])
    # CPU smoke grace: one decode-dispatch quantum of absolute headroom —
    # at tiny scale a single 20ms dispatch is a large TTFT multiple
    grace_ms = 150.0 if on_cpu else 0.0
    high_ratio = (round(max(high_p99 - grace_ms, 0.0)
                        / max(base_p99, 1e-6), 2)
                  if high_p99 is not None and base_p99 else None)
    be = over["best_effort"]
    be_shed = be["shed"] + be["early"]   # client-observed rejections
    early_rejects = sum(res["early"] for res in over.values())
    retry_afters = [ra for res in over.values()
                    for ra in res["retry_afters"] if ra is not None]
    high_shed = sum(shed_delta["high"].values())
    per_class = {
        c: {"done": over[c]["done"], "shed": over[c]["shed"],
            "early_rejects": over[c]["early"], "errors": over[c]["errors"],
            "ttft_p50_ms": (round(float(np.percentile(
                over[c]["ttfts"], 50)) * 1e3, 1)
                if over[c]["ttfts"] else None),
            "ttft_p99_ms": p99(over[c]["ttfts"]),
            "shed_counters": shed_delta[c]}
        for c in ("high", "normal", "best_effort")}
    rec = {
        "model": model,
        "mode": "overload",
        "offered_x_capacity": 5,
        "baseline_ttft_p99_ms": base_p99,
        "baseline_after_ttft_p99_ms": p99(base_after),
        "overload_high_p99_ttft_ms": high_p99,
        "overload_high_p99_ttft_ratio": high_ratio,
        "overload_high_p99_ttft_ratio_raw": (
            round(high_p99 / max(base_p99, 1e-6), 2)
            if high_p99 is not None and base_p99 else None),
        "overload_high_shed": high_shed,
        "overload_best_effort_shed": be_shed,
        "overload_early_rejects": early_rejects,
        "retry_after_finite": (all(isinstance(ra, (int, float))
                                   and 1 <= ra <= 120
                                   for ra in retry_afters)
                               if retry_afters else None),
        "tenant_token_share": tenant_share,
        "per_class": per_class,
        "utilization": util,
        "slots": slots,
        "n_workers": 5 * slots,
        "dtype": dtype,
        "prompt_len": int(p_len),
        "max_tokens": int(max_new),
        "decode_chunk": chunk_eff,
        "seq": seq,
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: overload capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_OVERLOAD") == "1":
        problems = []
        if high_ratio is None or high_ratio > 2.0:
            problems.append(
                f"high p99 TTFT ratio {high_ratio} > 2.0 "
                f"(base={base_p99}ms overload={high_p99}ms)")
        if high_shed != 0:
            problems.append(f"shed_total{{class=high}} = {high_shed} != 0")
        if be_shed <= 0:
            problems.append("no best_effort shed under 5x overload")
        if sum(res["errors"] for res in over.values()):
            problems.append(
                f"hard errors under overload: "
                f"{ {c: r['errors'] for c, r in over.items()} }")
        if early_rejects and not rec["retry_after_finite"]:
            problems.append(f"non-finite Retry-After among {retry_afters}")
        if problems:
            raise AssertionError("overload arm failed: "
                                 + "; ".join(problems))
    del eng, params
    gc.collect()
    return rec


def measure_restart(jax, *, model: str, dtype: str, slots: int, steps: int,
                    seq: int, prompt_len: int, paged: bool, mixed: bool,
                    chunk: int, page_size: int, n_pages: int | None,
                    platform: str, params_cache: dict | None = None,
                    env: dict | None = None) -> dict:
    """Restart-recovery arm (ISSUE 9): steady greedy serving with an
    engine.step kill injected mid-stream. With restart replay on (the
    default) every in-flight stream must continue on its own queue with
    ZERO client-visible errors and the bit-identical token sequence of
    an uninterrupted reference pass; the cost shows up only as one
    inter-token stall covering restart + re-prefill. Reports
    client_error_rate, bit_identical, recovery_ms (worst inter-token
    gap across the fault), stall p95, and the replayed request/token
    counter deltas. BENCH_ASSERT_RESTART=1 hard-fails on any
    client-visible error or divergence — the invariant is scheduler
    policy, not device perf, so it gates on the CPU smoke too."""
    import gc
    import threading

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.faults import FAULTS
    from ollama_operator_tpu.runtime.scheduler import Scheduler
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: restart capture model={model} dtype={dtype} "
        f"slots={slots} seq={seq} paged={paged}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    serve_seq = min(seq, cfg.max_seq_len)
    # short decode chunks so the kill lands mid-stream, not on a
    # stream's final dispatch, and the gap timeline has resolution
    chunk_eff = max(4, min(chunk, 8))
    ecfg = EngineConfig(max_slots=slots, max_seq_len=seq,
                       decode_chunk=chunk_eff, cache_dtype=kv_dtype,
                       paged=paged, page_size=page_size,
                       n_pages=n_pages,
                       min_prefill_bucket=16)
    eng = Engine(cfg, params, ecfg=ecfg)
    eng.warm_buckets()
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    rng = np.random.default_rng(23)
    p_len = max(16, min(prompt_len, serve_seq // 4))
    max_new = max(12, min(32, serve_seq // 8))
    prompts = [rng.integers(1, cfg.vocab_size, size=p_len,
                            endpoint=False).astype(np.int32)
               for _ in range(slots)]

    def run_pass(sched, fault: bool) -> tuple:
        outs = [[] for _ in prompts]
        stamps = [[] for _ in prompts]
        errs = [0] * len(prompts)

        def worker(i: int):
            try:
                r = sched.submit(list(prompts[i]), greedy,
                                 max_tokens=max_new)
                for tok in r.tokens():
                    outs[i].append(int(tok))
                    stamps[i].append(time.monotonic())
            except Exception:
                errs[i] = 1

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        if fault:
            # kill the engine once every stream is demonstrably
            # mid-generation — the restart then has the full resident
            # batch to classify and replay
            t0 = time.monotonic()
            while (any(len(o) < 2 for o in outs)
                   and time.monotonic() - t0 < 120):
                time.sleep(0.005)
            FAULTS.arm("engine.step", "fail:once")
        for t in threads:
            t.join(timeout=600)
        return outs, stamps, errs

    replay0 = METRICS.get("tpu_model_replayed_requests_total")
    rtok0 = METRICS.get("tpu_model_replayed_tokens_total")
    sched = Scheduler(eng, restart_backoff=0.05, async_dispatch=True)
    try:
        # warmup (also populates the dispatch histograms the watchdog's
        # auto timeout derives from)
        w = sched.submit(list(prompts[0]), greedy, max_tokens=chunk_eff)
        for _ in w.chunks():
            pass
        restarts0 = sched.n_restarts
        ref, _, ref_errs = run_pass(sched, fault=False)
        out, stamps, errs = run_pass(sched, fault=True)
        # serving must resume on the rebuilt engine: one probe request
        probe = list(sched.submit(list(prompts[0]), greedy,
                                  max_tokens=8).tokens())
        n_restarts = sched.n_restarts - restarts0
        n_replays = sched.n_replays
        broken = sched.broken
        # no post-warmup recompile baseline here: restart replay
        # re-prefills interrupted streams, and any bucket that compiles
        # during that recovery is a REAL mid-serving recompile this arm
        # should surface, not warmup noise
        util = _sched_utilization(sched)
    finally:
        FAULTS.disarm("engine.step")
        sched.shutdown()
        for s in range(eng.n_slots):
            try:
                eng.release(s)
            except Exception:
                pass

    gaps = [b - a for ts in stamps for a, b in zip(ts, ts[1:])]
    err_rate = sum(errs) / max(1, len(errs))
    bit_identical = (not any(errs) and not any(ref_errs)
                     and all(o == r for o, r in zip(out, ref)))
    rec = {
        "model": model,
        "mode": "restart",
        "streams": len(prompts),
        "client_error_rate": round(err_rate, 4),
        "bit_identical": bit_identical,
        "probe_served": len(probe) == 8,
        "n_restarts": int(n_restarts),
        "n_replays": int(n_replays),
        "broken": bool(broken),
        "recovery_ms": (round(max(gaps) * 1e3, 1) if gaps else None),
        "stall_p95_ms": (round(float(np.percentile(gaps, 95)) * 1e3, 1)
                         if gaps else None),
        "replayed_requests": int(
            METRICS.get("tpu_model_replayed_requests_total") - replay0),
        "replayed_tokens": int(
            METRICS.get("tpu_model_replayed_tokens_total") - rtok0),
        "utilization": util,
        "slots": slots,
        "dtype": dtype,
        "paged": paged,
        "prompt_len": int(p_len),
        "max_tokens": int(max_new),
        "decode_chunk": chunk_eff,
        "seq": seq,
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: restart capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_RESTART") == "1":
        problems = []
        if sum(errs):
            problems.append(f"client-visible errors: {sum(errs)} of "
                            f"{len(errs)} streams")
        if not bit_identical:
            problems.append("replayed streams diverged from the "
                            "uninterrupted reference")
        if n_restarts < 1:
            problems.append("fault did not force a supervised restart")
        if rec["replayed_requests"] < 1:
            problems.append("no stream was replayed")
        if not rec["probe_served"]:
            problems.append("serving did not resume after the restart")
        if broken:
            problems.append("scheduler marked broken")
        if problems:
            raise AssertionError("restart arm failed: "
                                 + "; ".join(problems))
    del eng, params
    gc.collect()
    return rec


def measure_coldstart(jax, *, model: str, dtype: str, slots: int,
                      steps: int, seq: int, prompt_len: int, paged: bool,
                      mixed: bool, chunk: int, page_size: int,
                      n_pages: int | None, platform: str,
                      params_cache: dict | None = None,
                      env: dict | None = None) -> dict:
    """Scale-to-zero cold-start arm (ISSUE 11): the wake path restores
    the AOT warm-bucket cache from a snapshot instead of re-running
    warm_buckets(). Times the donor's full warm pass vs the woken
    engine's restore, then dispatches on the woken engine and reports
    the recompile count — the acceptance bar is ZERO recompiles after a
    restore (delta vs the no-snapshot control, which must recompile).
    BENCH_ASSERT_COLDSTART=1 hard-fails on a recompiling wake; the
    invariant is engine policy, not device perf, so it gates on CPU."""
    import gc

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions,
                                                    resolve_cache_dtype)

    on_cpu = platform == "cpu"
    saved_execs = os.environ.get("TPU_WARM_SNAPSHOT_EXECS")
    if on_cpu:
        dtype = "float32"
        # the CPU backend's executable deserialization is unstable (see
        # conftest.py's persistent-cache note); the sig-replay path is
        # the portable contract and what this arm gates on
        os.environ["TPU_WARM_SNAPSHOT_EXECS"] = "0"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    log(f"bench: coldstart capture model={model} dtype={dtype} "
        f"slots={slots} seq={seq} paged={paged}")
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    serve_seq = min(seq, cfg.max_seq_len)
    ecfg = EngineConfig(max_slots=slots, max_seq_len=serve_seq,
                       decode_chunk=max(4, min(chunk, 8)),
                       cache_dtype=kv_dtype, paged=paged,
                       page_size=page_size, n_pages=n_pages,
                       min_prefill_bucket=16)
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    rng = np.random.default_rng(31)
    prompt = rng.integers(1, cfg.vocab_size,
                          size=max(16, min(prompt_len, serve_seq // 4)),
                          endpoint=False).astype(np.int32)

    def first_dispatch(eng):
        eng.admit(0, prompt, greedy)
        for _ in range(3):
            eng.decode_n()
        eng.release(0)

    try:
        donor = Engine(cfg, params, ecfg=ecfg)
        t0 = time.monotonic()
        donor.warm_buckets()
        warm_ms = (time.monotonic() - t0) * 1e3
        blob = donor.warm_snapshot()
        n_sigs = len(donor._warmed_sigs)
        del donor
        gc.collect()

        woken = Engine(cfg, params, ecfg=ecfg)
        t0 = time.monotonic()
        out = woken.restore_warm(blob)
        restore_ms = (time.monotonic() - t0) * 1e3
        first_dispatch(woken)
        woken_recompiles = int(sum(woken.recompiles.values()))
        del woken
        gc.collect()

        control = Engine(cfg, params, ecfg=ecfg)   # no snapshot, no warm
        first_dispatch(control)
        control_recompiles = int(sum(control.recompiles.values()))
        del control
        gc.collect()
    finally:
        if saved_execs is None:
            os.environ.pop("TPU_WARM_SNAPSHOT_EXECS", None)
        else:
            os.environ["TPU_WARM_SNAPSHOT_EXECS"] = saved_execs

    rec = {
        "model": model,
        "mode": "coldstart",
        "warm_ms": round(warm_ms, 1),
        "restore_ms": round(restore_ms, 1),
        "restore_speedup": round(warm_ms / max(restore_ms, 1e-6), 2),
        "snapshot_bytes": len(blob),
        "warm_sigs": n_sigs,
        "restored_execs": int(out["restored"]),
        "recompiled_sigs": int(out["compiled"]),
        "recompiles_after_restore": woken_recompiles,
        "control_recompiles": control_recompiles,
        "slots": slots,
        "dtype": dtype,
        "paged": paged,
        "seq": serve_seq,
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: coldstart capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_COLDSTART") == "1":
        problems = []
        if out["restored"] + out["compiled"] != n_sigs:
            problems.append(f"restore covered {out} of {n_sigs} sigs")
        if woken_recompiles:
            problems.append(f"woken engine recompiled "
                            f"{woken_recompiles}x on first dispatch")
        if not control_recompiles:
            problems.append("no-snapshot control did not recompile — "
                            "the A/B measures nothing")
        if problems:
            raise AssertionError("coldstart arm failed: "
                                 + "; ".join(problems))
    del params
    gc.collect()
    return rec


class _SeverableProxy:
    """TCP proxy in front of one in-process replica server. kill()
    severs every live connection mid-byte and refuses new ones — replica
    death exactly as the gateway sees it (RST/EOF on the upstream
    stream), without tearing down the server the other replicas share a
    process with."""

    def __init__(self, backend_port: int):
        import socket
        import threading
        self._socket = socket
        self.backend_port = backend_port
        self.dead = False
        self._conns: list = []
        self._lock = threading.Lock()
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        import threading
        while True:
            try:
                c, _ = self._srv.accept()
            except OSError:
                return
            if self.dead:
                c.close()
                continue
            try:
                b = self._socket.create_connection(
                    ("127.0.0.1", self.backend_port))
            except OSError:
                c.close()
                continue
            with self._lock:
                self._conns.extend((c, b))
            for src, dst in ((c, b), (b, c)):
                threading.Thread(target=self._pump, args=(src, dst),
                                 daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                d = src.recv(65536)
                if not d:
                    break
                dst.sendall(d)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(self._socket.SHUT_RDWR)
            except OSError:
                pass

    def kill(self):
        self.dead = True
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.shutdown(self._socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()

    def close(self):
        self.kill()
        try:
            self._srv.close()
        except OSError:
            pass


def measure_fleet(jax, **kw) -> dict:
    """Fleet-gateway arm wrapper: the K-replica capture is the only one
    that compiles IDENTICAL executables from several engines' scheduler
    threads concurrently in one process, which races the persistent XLA
    compilation cache (observed as heap corruption / wedged dispatch on
    the CPU smoke). The capture is a policy gate, not a perf headline —
    cold compiles are fine, so park the cache for its duration."""
    cache = getattr(jax.config, "jax_compilation_cache_dir", None)
    if cache:
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        return _measure_fleet(jax, **kw)
    finally:
        if cache:
            jax.config.update("jax_compilation_cache_dir", cache)


def _measure_fleet(jax, *, model: str, dtype: str, slots: int, steps: int,
                   seq: int, prompt_len: int, paged: bool, mixed: bool,
                   chunk: int, page_size: int, n_pages: int | None,
                   platform: str, params_cache: dict | None = None,
                   env: dict | None = None) -> dict:
    """Fleet-gateway arm (ISSUE 15): K=4 REAL servers behind the
    cache-aware gateway vs one replica serving the same shared-system-
    prompt workload. Two claims gate: (a) the page-aligned prefix-hash
    routing keeps the fleet's aggregate prefix hit rate >= 0.9x the
    single-replica rate (round-robin routing shreds it to ~0.7x by
    cold-starting every radix tree); (b) a replica killed mid-stream
    fails over with ZERO client-visible error frames and a byte-
    identical greedy continuation, with the journal drained after.
    BENCH_ASSERT_FLEET=1 hard-fails the capture on either."""
    import gc
    import json as _json
    import tempfile
    import threading
    import urllib.request

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.operator.gateway import Gateway
    from ollama_operator_tpu.runtime.engine import (EngineConfig,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.service import LoadedModel
    from ollama_operator_tpu.server.app import ModelManager, serve
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS
    from ollama_operator_tpu.server.names import ModelName

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    tok = _bench_tokenizer(cfg.vocab_size)
    name = ModelName.parse("bench").short

    serve_seq = min(seq, cfg.max_seq_len)
    ps = max(8, min(page_size, serve_seq // 8))
    # the ISSUE-15 shape: 512-token shared system prompt where the
    # context allows, half the servable context at smoke scale
    prefix_len = min(512, serve_seq // 2)
    tail_len = max(8, min(32, serve_seq // 16))
    gen_tokens = max(4, min(12, steps // 4))
    # small decode chunks so the kill lands mid-stream (several frames
    # per response) even on the tiny smoke model
    chunk_eff = max(2, min(chunk, serve_seq // 32))
    kill_tokens = max(24, min(48, serve_seq // 2 - tail_len))
    # chunk the routing hash to the actual prompt scale: the shared
    # prefix must span several full chunks or affinity measures nothing
    hash_chunk = max(16, prefix_len // 4)
    k_replicas = 4
    n_req = 12
    pool = (n_pages
            or slots * (-(-serve_seq // ps) + 2) + prefix_len // ps)
    log(f"bench: fleet capture model={model} k={k_replicas} "
        f"prefix={prefix_len} hash_chunk={hash_chunk} ps={ps}")

    system = ("You are a meticulous TPU serving assistant. "
              * (prefix_len // 8 + 1))[:prefix_len]
    tails = [(f"-q{i:02d}" * (tail_len // 4 + 1))[:tail_len]
             for i in range(n_req + 4)]
    kill_prompts = [f"kill-{a}-" + "z" * 24 for a in range(3)]

    def make_server():
        lm = LoadedModel(
            name, cfg, params, tok,
            ecfg=EngineConfig(max_slots=slots, max_seq_len=serve_seq,
                              decode_chunk=chunk_eff, cache_dtype=kv_dtype,
                              paged=True, page_size=ps, n_pages=pool,
                              min_prefill_bucket=16))
        tmp = tempfile.mkdtemp(prefix="bench-fleet-")
        manager = ModelManager(tmp, serve_models=True, default_keep_alive=-1)
        manager.loaded = lm
        httpd = serve(manager, "127.0.0.1", 0)
        return lm, manager, httpd

    def teardown(lm, manager, httpd):
        httpd.shutdown()
        manager.loaded = None
        lm.unload()

    def generate(base, prompt_text, n_predict, on_frame=None):
        """One greedy stream; returns (text, error_frames). Greedy makes
        the output a pure function of the prompt — the bit-identity
        oracle for cross-replica failover."""
        req = urllib.request.Request(
            base + "/api/generate",
            data=_json.dumps({
                "model": "bench", "prompt": prompt_text, "stream": True,
                "options": {"num_predict": n_predict,
                            "temperature": 0.0}}).encode(),
            headers={"Content-Type": "application/json"})
        text, errors, n = [], [], 0
        with urllib.request.urlopen(req, timeout=600) as resp:
            for line in resp:
                if not line.strip():
                    continue
                frame = _json.loads(line)
                if "error" in frame:
                    errors.append(frame)
                elif not frame.get("done"):
                    text.append(frame.get("response") or "")
                n += 1
                if on_frame is not None:
                    on_frame(n)
        return "".join(text), errors

    def hit_window(fn):
        h0 = METRICS.get("tpu_model_prefix_hit_tokens_total")
        m0 = METRICS.get("tpu_model_prefix_miss_tokens_total")
        fn()
        hits = METRICS.get("tpu_model_prefix_hit_tokens_total") - h0
        miss = METRICS.get("tpu_model_prefix_miss_tokens_total") - m0
        return hits, miss

    # --- arm A: one replica, direct — the hit-rate bar to hold --------
    lm1, mgr1, httpd1 = make_server()
    base1 = f"http://127.0.0.1:{httpd1.server_address[1]}"
    single_errors: list = []

    def run_single():
        for i in range(n_req):
            _, errs = generate(base1, system + tails[i], gen_tokens)
            single_errors.extend(errs)

    s_hits, s_miss = hit_window(run_single)
    single_rate = s_hits / max(1.0, s_hits + s_miss)
    # reference texts for the kill phase: any replica must reproduce
    # these byte-for-byte across a mid-stream failover
    kill_refs = [generate(base1, p, kill_tokens)[0] for p in kill_prompts]
    teardown(lm1, mgr1, httpd1)
    del lm1
    gc.collect()
    log(f"bench: fleet single-replica hit_rate={single_rate:.3f}")

    # --- arm B: K replicas behind the gateway -------------------------
    servers = [make_server() for _ in range(k_replicas)]
    proxies = [_SeverableProxy(s[2].server_address[1]) for s in servers]
    proxy_by_name = {f"r{i}": p for i, p in enumerate(proxies)}
    fleet_env = {
        "TPU_GATEWAY_HASH_CHUNK": str(hash_chunk),
        "TPU_GATEWAY_EJECT_FAILURES": "2",
        "TPU_GATEWAY_EJECT_S": "60",      # a killed replica stays out
        "TPU_GATEWAY_SLOW_SCRAPE_MS": "30000",  # loaded CPU != slow
    }
    saved = {k: os.environ.get(k) for k in fleet_env}
    os.environ.update(fleet_env)
    try:
        gw = Gateway(replicas=[(nm, f"http://127.0.0.1:{p.port}")
                               for nm, p in proxy_by_name.items()],
                     port=0, scrape_period_s=0.2)
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
    gw.start()

    def routes(path):
        return METRICS.get("tpu_model_gateway_routes_total",
                           f'{{path="{path}"}}')

    def failovers(result):
        return METRICS.get("tpu_model_gateway_failovers_total",
                           f'{{result="{result}"}}')

    t0 = time.perf_counter()
    fleet_errors: list = []
    r0 = {p: routes(p) for p in ("affinity", "probe", "least_loaded")}

    def run_fleet():
        for i in range(n_req):
            _, errs = generate(gw.base_url, system + tails[i], gen_tokens)
            fleet_errors.extend(errs)

    f_hits, f_miss = hit_window(run_fleet)
    fleet_rate = f_hits / max(1.0, f_hits + f_miss)
    route_delta = {p: int(routes(p) - r0[p])
                   for p in ("affinity", "probe", "least_loaded")}
    log(f"bench: fleet K={k_replicas} hit_rate={fleet_rate:.3f} "
        f"routes={route_delta}")

    # --- kill phase: sever the serving replica mid-stream -------------
    fo0 = {r: failovers(r) for r in ("replayed", "requeued", "errored")}
    kill_bit_identical = None
    kill_errors: list = []
    killed_name = None
    for attempt, (prompt, ref) in enumerate(zip(kill_prompts, kill_refs)):
        before = {r["name"]: r["served"] for r in gw.status()["replicas"]}
        state: dict = {"killed": None}

        def on_frame(n, _before=before, _state=state):
            if n == 1 and _state["killed"] is None:
                after = {r["name"]: r["served"]
                         for r in gw.status()["replicas"]}
                for nm in after:
                    if (after[nm] > _before.get(nm, 0)
                            and not proxy_by_name[nm].dead):
                        proxy_by_name[nm].kill()
                        _state["killed"] = nm
                        return

        text, errs = generate(gw.base_url, prompt, kill_tokens,
                              on_frame=on_frame)
        kill_errors.extend(errs)
        kill_bit_identical = (text == ref)
        killed_name = state["killed"]
        if not kill_bit_identical:
            log(f"bench: fleet kill attempt {attempt} diverged: "
                f"ref={ref!r} got={text!r}")
        if failovers("replayed") - fo0["replayed"] >= 1:
            break
        # the tiny stream outran the kill (fully pumped before frame 1
        # was processed) — the severed replica is dead either way, try
        # the next one; 3 attempts against K=4 always leaves quorum
        log(f"bench: fleet kill attempt {attempt} raced, retrying")
    # queued-after-death traffic: affinity still points at the corpse,
    # so these exercise the unconditional unstarted-request failover
    post_errors: list = []
    for i in range(n_req, n_req + 3):
        _, errs = generate(gw.base_url, system + tails[i], gen_tokens)
        post_errors.extend(errs)
    fo_delta = {r: int(failovers(r) - fo0[r])
                for r in ("replayed", "requeued", "errored")}
    journal = gw.journal_stats()
    wall = time.perf_counter() - t0

    gw.stop()
    for p in proxies:
        p.close()
    for lm, manager, httpd in servers:
        teardown(lm, manager, httpd)
    del servers

    rec = {
        "model": model,
        "mode": "fleet",
        "k_replicas": k_replicas,
        "n_requests": n_req,
        "single_hit_rate": round(single_rate, 3),
        "fleet_hit_rate": round(fleet_rate, 3),
        "fleet_vs_single_hit_ratio": (round(fleet_rate / single_rate, 3)
                                      if single_rate else None),
        "routes": route_delta,
        "failovers": fo_delta,
        "killed_replica": killed_name,
        "kill_bit_identical": kill_bit_identical,
        "client_error_frames": (len(single_errors) + len(fleet_errors)
                                + len(kill_errors) + len(post_errors)),
        "journal_live": journal["live"],
        "journal_kept": journal["kept"],
        "prefix_len": int(prefix_len),
        "hash_chunk": int(hash_chunk),
        "gen_tokens": int(gen_tokens),
        "kill_tokens": int(kill_tokens),
        "page_size": int(ps),
        "slots": slots,
        "dtype": dtype,
        "paged": True,
        "seq": int(serve_seq),
        "wall_s": round(wall, 2),
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: fleet capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_FLEET") == "1":
        problems = []
        ratio = rec["fleet_vs_single_hit_ratio"]
        if ratio is None or ratio < 0.9:
            problems.append(f"fleet/single hit ratio {ratio} < 0.9 "
                            f"(fleet {fleet_rate:.3f} vs single "
                            f"{single_rate:.3f})")
        if rec["client_error_frames"]:
            problems.append(f"{rec['client_error_frames']} client-visible "
                            f"error frames (want 0)")
        if not kill_bit_identical:
            problems.append("failover continuation was not byte-identical")
        if fo_delta["replayed"] < 1:
            problems.append("mid-stream kill never exercised replay "
                            f"failover: {fo_delta}")
        if fo_delta["errored"]:
            problems.append(f"{fo_delta['errored']} replayable streams "
                            f"errored instead of failing over")
        if journal["live"]:
            problems.append(f"journal not drained: {journal['live']} "
                            f"live entries")
        if problems:
            raise AssertionError("fleet arm failed: "
                                 + "; ".join(problems))
    del params
    gc.collect()
    return rec


def measure_disagg(jax, **kw) -> dict:
    """Disagg arm wrapper: same persistent-cache hazard as the fleet
    arm (several identical engines compiling concurrently in-process)."""
    cache = getattr(jax.config, "jax_compilation_cache_dir", None)
    if cache:
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        return _measure_disagg(jax, **kw)
    finally:
        if cache:
            jax.config.update("jax_compilation_cache_dir", cache)


def _measure_disagg(jax, *, model: str, dtype: str, slots: int, steps: int,
                    seq: int, prompt_len: int, paged: bool, mixed: bool,
                    chunk: int, page_size: int, n_pages: int | None,
                    platform: str, params_cache: dict | None = None,
                    env: dict | None = None) -> dict:
    """Disaggregated prefill/decode arm (ISSUE 20): steady decode load,
    then the same decode load under a long-prompt prefill burst — once
    against a unified 2-replica fleet, once against a 1-prefill +
    1-decode split. The claim that gates: the split keeps decode ITL
    p99 ~flat under the burst (prefill compute lands on the other
    pool), the handoff streams are byte-identical to the unified
    references, real KV pages moved over /api/kv_export -> /api/kv_import,
    and tpu_model_async_fallback_total stays 0 throughout.
    BENCH_ASSERT_DISAGG=1 hard-fails on the policy invariants and on
    the (grace-adjusted) disagg ITL ratio ceiling."""
    import gc
    import json as _json
    import tempfile
    import threading
    import urllib.request

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.operator.gateway import Gateway
    from ollama_operator_tpu.runtime.engine import (EngineConfig,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.service import LoadedModel
    from ollama_operator_tpu.server.app import ModelManager, serve
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS
    from ollama_operator_tpu.server.names import ModelName

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    params, param_bytes, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    tok = _bench_tokenizer(cfg.vocab_size)
    name = ModelName.parse("bench").short

    serve_seq = min(seq, cfg.max_seq_len)
    ps = max(8, min(page_size, serve_seq // 8))
    burst_prompt_len = min(512, serve_seq // 2)
    chunk_eff = max(2, min(chunk, serve_seq // 32))
    decode_tokens = max(16, min(48, steps))
    n_decode = 3          # concurrent interactive decode streams
    n_burst = 4           # long-prompt prefill requests in the burst
    pool = (n_pages
            or slots * (-(-serve_seq // ps) + 2) + burst_prompt_len // ps)
    log(f"bench: disagg capture model={model} burst_prompt="
        f"{burst_prompt_len} decode_tokens={decode_tokens} ps={ps}")

    burst_system = ("Summarize the following operations report. "
                    * (burst_prompt_len // 8 + 1))[:burst_prompt_len]
    decode_prompts = [f"chat-{i}-" + "t" * 24 for i in range(n_decode)]
    burst_tails = [(f"-b{i:02d}" * 8)[:24] for i in range(n_burst)]

    def make_server():
        lm = LoadedModel(
            name, cfg, params, tok,
            ecfg=EngineConfig(max_slots=slots, max_seq_len=serve_seq,
                              decode_chunk=chunk_eff, cache_dtype=kv_dtype,
                              paged=True, page_size=ps, n_pages=pool,
                              min_prefill_bucket=16))
        tmp = tempfile.mkdtemp(prefix="bench-disagg-")
        manager = ModelManager(tmp, serve_models=True, default_keep_alive=-1)
        manager.loaded = lm
        httpd = serve(manager, "127.0.0.1", 0)
        return lm, manager, httpd

    def teardown(servers):
        for lm, manager, httpd in servers:
            httpd.shutdown()
            manager.loaded = None
            lm.unload()

    def stream(base, prompt_text, n_predict, record):
        """One greedy stream; fills ``record`` with text/errors (greedy
        = the cross-arm bit-identity oracle)."""
        req = urllib.request.Request(
            base + "/api/generate",
            data=_json.dumps({
                "model": "bench", "prompt": prompt_text, "stream": True,
                "options": {"num_predict": n_predict,
                            "temperature": 0.0}}).encode(),
            headers={"Content-Type": "application/json"})
        text, errors = [], []
        with urllib.request.urlopen(req, timeout=600) as resp:
            for line in resp:
                if not line.strip():
                    continue
                frame = _json.loads(line)
                if "error" in frame:
                    errors.append(frame)
                elif not frame.get("done"):
                    text.append(frame.get("response") or "")
        record["text"] = "".join(text)
        record["errors"] = errors

    def itl_snap():
        return METRICS.hist_buckets("tpu_model_itl_seconds")

    def itl_p99_ms(before, after):
        """Interpolated p99 (histogram_quantile style) of the decode
        ITL observations made between two hist_buckets snapshots. The
        random-byte bench tokenizer defeats client-side frame timing
        (the incremental detokenizer buffers invalid UTF-8 until the
        stream ends), so the engine's chunk-normalized ITL histogram is
        the cadence a real client would see."""
        bounds, b0 = before
        delta = [a - b for a, b in zip(after[1], b0)]
        n = sum(delta)
        if not n:
            return None
        rank, cum, lo = 0.99 * n, 0, 0.0
        for i, c in enumerate(delta):
            if cum + c >= rank and c:
                hi = bounds[i] if i < len(bounds) else bounds[-1] * 2
                return round((lo + (hi - lo) * (rank - cum) / c) * 1e3, 2)
            cum += c
            if i < len(bounds):
                lo = bounds[i]
        return round(bounds[-1] * 2 * 1e3, 2)

    def run_phase(base, burst: bool):
        """n_decode interactive streams under an ITL-histogram window,
        optionally with the prefill burst riding along. Returns
        (decode_records, burst_records, itl_p99_ms)."""
        recs = [{} for _ in range(n_decode)]
        brecs = [{} for _ in range(n_burst)] if burst else []
        ts = [threading.Thread(target=stream,
                               args=(base, decode_prompts[i],
                                     decode_tokens, recs[i]))
              for i in range(n_decode)]
        bs = [threading.Thread(target=stream,
                               args=(base, burst_system + burst_tails[i],
                                     2, brecs[i]))
              for i in range(len(brecs))]
        snap0 = itl_snap()
        for t in ts:
            t.start()
        for t in bs:                     # burst lands on live decode load
            t.start()
        for t in ts + bs:
            t.join()
        return recs, brecs, itl_p99_ms(snap0, itl_snap())

    def run_arm(pools: list | None):
        """Boot a 2-replica fleet (split when ``pools``), run steady
        then burst, tear down. Returns the arm record."""
        servers = [make_server() for _ in range(2)]
        # the handoff timeout is read per-request, so the overrides stay
        # in place for the whole arm (unlike the fleet arm's
        # construction-time-only knobs)
        arm_env = {
            "TPU_GATEWAY_EJECT_FAILURES": "3",
            "TPU_GATEWAY_EJECT_S": "60",
            "TPU_GATEWAY_SLOW_SCRAPE_MS": "30000",
            "TPU_DISAGG_HANDOFF_TIMEOUT_S": "60",
        }
        saved = {k: os.environ.get(k) for k in arm_env}
        os.environ.update(arm_env)
        try:
            reps = [(f"r{i}",
                     f"http://127.0.0.1:{s[2].server_address[1]}")
                    + ((pools[i],) if pools else ())
                    for i, s in enumerate(servers)]
            gw = Gateway(replicas=reps, port=0, scrape_period_s=0.2)
            gw.start()
            t0 = time.perf_counter()
            warm, _, _ = run_phase(gw.base_url, burst=False)  # compile pass
            steady, _, steady_p99 = run_phase(gw.base_url, burst=False)
            burst, brecs, burst_p99 = run_phase(gw.base_url, burst=True)
            wall = time.perf_counter() - t0
            journal = gw.journal_stats()
            gw.stop()
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        teardown(servers)
        del servers
        gc.collect()
        # CPU smoke grace: both pools share ONE host CPU in-process, so
        # a burst steals decode cycles the split architecture isolates
        # on real hardware — allow one decode-chunk quantum of absolute
        # per-token headroom (the overload arm's TTFT-grace precedent)
        grace_ms = 50.0 if on_cpu else 0.0
        ratio = (round(max(burst_p99 - grace_ms, steady_p99)
                       / max(steady_p99, 1e-6), 2)
                 if burst_p99 is not None and steady_p99 else None)
        return {
            "steady_itl_p99_ms": steady_p99,
            "burst_itl_p99_ms": burst_p99,
            "itl_p99_ratio": ratio,
            "itl_p99_ratio_raw": (round(burst_p99 / max(steady_p99, 1e-6), 2)
                                  if burst_p99 and steady_p99 else None),
            "decode_texts": {decode_prompts[i]: steady[i]["text"]
                             for i in range(n_decode)},
            "burst_texts": {burst_tails[i]: brecs[i]["text"]
                            for i in range(n_burst)} if brecs else {},
            "error_frames": sum(len(r["errors"])
                                for rs in (warm, steady, burst, brecs)
                                for r in rs),
            "journal_live": journal["live"],
            "wall_s": round(wall, 2),
        }

    def handoffs(result):
        return METRICS.get("tpu_model_disagg_handoffs_total",
                           f'{{result="{result}"}}')

    fallback0 = METRICS.get("tpu_model_async_fallback_total")
    unified = run_arm(None)
    log(f"bench: disagg unified arm itl_ratio={unified['itl_p99_ratio']}")

    h0 = {r: handoffs(r)
          for r in ("transferred", "replayed", "unified_fallback")}
    pages0 = METRICS.get("tpu_model_kv_transfer_pages_total")
    bytes0 = METRICS.get("tpu_model_kv_transfer_bytes_total")
    disagg = run_arm(["prefill", "decode"])
    h_delta = {r: int(handoffs(r) - h0[r])
               for r in ("transferred", "replayed", "unified_fallback")}
    pages_moved = int(METRICS.get("tpu_model_kv_transfer_pages_total")
                      - pages0)
    bytes_moved = int(METRICS.get("tpu_model_kv_transfer_bytes_total")
                      - bytes0)
    fallback_delta = int(METRICS.get("tpu_model_async_fallback_total")
                         - fallback0)
    log(f"bench: disagg split arm itl_ratio={disagg['itl_p99_ratio']} "
        f"handoffs={h_delta} pages={pages_moved}")

    # bit-identity: every disagg stream (handoff splice included) must
    # reproduce the unified arm's bytes — greedy text is a pure function
    # of the prompt, so any splice seam shows up as a diff
    mismatched = sorted(
        k for k in unified["decode_texts"]
        if disagg["decode_texts"].get(k) != unified["decode_texts"][k])
    mismatched += sorted(
        k for k in unified["burst_texts"]
        if disagg["burst_texts"].get(k) != unified["burst_texts"][k])

    rec = {
        "model": model,
        "mode": "disagg",
        "n_decode_streams": n_decode,
        "n_burst_requests": n_burst,
        "decode_tokens": int(decode_tokens),
        "burst_prompt_len": int(burst_prompt_len),
        "unified_itl_steady_p99_ms": unified["steady_itl_p99_ms"],
        "unified_itl_burst_p99_ms": unified["burst_itl_p99_ms"],
        "unified_itl_p99_ratio": unified["itl_p99_ratio"],
        "disagg_itl_steady_p99_ms": disagg["steady_itl_p99_ms"],
        "disagg_itl_burst_p99_ms": disagg["burst_itl_p99_ms"],
        "disagg_itl_p99_ratio": disagg["itl_p99_ratio"],
        "disagg_itl_p99_ratio_raw": disagg["itl_p99_ratio_raw"],
        "handoffs": h_delta,
        "kv_transfer_pages": pages_moved,
        "kv_transfer_bytes": bytes_moved,
        "async_fallbacks": fallback_delta,
        "handoff_bit_identical": not mismatched,
        "mismatched_streams": mismatched,
        "client_error_frames": (unified["error_frames"]
                                + disagg["error_frames"]),
        "journal_live": unified["journal_live"] + disagg["journal_live"],
        "pool_replicas": {"prefill": 1, "decode": 1},
        "page_size": int(ps),
        "slots": slots,
        "dtype": dtype,
        "paged": True,
        "seq": int(serve_seq),
        "wall_s": round(unified["wall_s"] + disagg["wall_s"], 2),
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: disagg capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_DISAGG") == "1":
        problems = []
        ratio = rec["disagg_itl_p99_ratio"]
        ceiling = float(os.environ.get("BENCH_DISAGG_RATIO_MAX", "2.0"))
        if ratio is None or ratio > ceiling:
            problems.append(
                f"disagg decode ITL p99 ratio {ratio} > {ceiling} "
                f"(steady={rec['disagg_itl_steady_p99_ms']}ms "
                f"burst={rec['disagg_itl_burst_p99_ms']}ms)")
        if mismatched:
            problems.append(f"handoff streams diverged from unified "
                            f"references: {mismatched}")
        if rec["client_error_frames"]:
            problems.append(f"{rec['client_error_frames']} client-visible "
                            f"error frames (want 0)")
        if h_delta["transferred"] < 1:
            problems.append(f"no handoff ever moved KV pages: {h_delta}")
        if pages_moved < 1:
            problems.append("kv_transfer_pages_total never moved")
        if fallback_delta:
            problems.append(f"tpu_model_async_fallback_total moved by "
                            f"{fallback_delta} (want 0)")
        if rec["journal_live"]:
            problems.append(f"journal not drained: {rec['journal_live']} "
                            f"live entries")
        if problems:
            raise AssertionError("disagg arm failed: "
                                 + "; ".join(problems))
    del params
    gc.collect()
    return rec


class _StallProxy:
    """TCP proxy in front of one in-process replica that can WEDGE (not
    sever) the replica->gateway direction mid-response. arm(n) applies
    to the next /api/generate connection only: its response pump
    forwards n socket reads, then blocks until close() — upstream
    alive-but-silent, the crash shape that leaves the gateway holding
    an open journal entry with progress and no close record. A sever
    would instead trigger the gateway's own in-process failover, which
    is the fleet arm's story, not this one's."""

    def __init__(self, backend_port: int):
        import socket
        import threading
        self._socket = socket
        self._threading = threading
        self.backend_port = backend_port
        self._armed = 0
        self.last_body_bytes = 0
        self._stall = threading.Event()
        self._lock = threading.Lock()
        self._conns: list = []
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def arm(self, body_bytes: int) -> None:
        """Wedge the NEXT generate stream after forwarding this many
        response-BODY bytes (counted past the header terminator, so TCP
        segmentation cannot move the cut)."""
        with self._lock:
            self._armed = body_bytes

    def _accept(self):
        while True:
            try:
                c, _ = self._srv.accept()
            except OSError:
                return
            b = None
            try:
                b = self._socket.create_connection(
                    ("127.0.0.1", self.backend_port))
                # the request line decides whether the armed stall
                # applies: scrapes and probes must always flow free
                first = c.recv(65536)
                if not first:
                    raise OSError("empty request")
                b.sendall(first)
            except OSError:
                c.close()
                if b is not None:
                    b.close()
                continue
            budget = 0
            is_gen = first.startswith(b"POST /api/generate")
            if is_gen:
                with self._lock:
                    budget, self._armed = self._armed, 0
            with self._lock:
                self._conns.extend((c, b))
            self._threading.Thread(target=self._pump, args=(c, b, 0, False),
                                   daemon=True).start()
            self._threading.Thread(target=self._pump,
                                   args=(b, c, budget, is_gen),
                                   daemon=True).start()

    def _pump(self, src, dst, budget, track):
        body = -1            # response-body bytes seen; -1 = in headers
        hdr = b""
        try:
            while True:
                d = src.recv(65536)
                if not d:
                    break
                if track or budget:
                    if body < 0:
                        hdr += d
                        cut = hdr.find(b"\r\n\r\n")
                        if cut >= 0:
                            body = len(hdr) - cut - 4
                    else:
                        body += len(d)
                if budget and body > budget:
                    # forward only up to the cut, then wedge: the
                    # gateway has whole frames up to here and a silent,
                    # still-open upstream after it
                    keep = len(d) - (body - budget)
                    if keep > 0:
                        dst.sendall(d[:keep])
                    self._stall.wait()
                    break
                dst.sendall(d)
        except OSError:
            pass
        if track and body > 0:
            # the uninterrupted reference stream's wire size — the arm
            # calibrates its mid-stream cut from this
            self.last_body_bytes = body
        for s in (src, dst):
            try:
                s.shutdown(self._socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self):
        self._stall.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.shutdown(self._socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()


def measure_gateway_restart(jax, *, model: str, dtype: str, slots: int,
                            steps: int, seq: int, prompt_len: int,
                            paged: bool, mixed: bool, chunk: int,
                            page_size: int, n_pages: int | None,
                            platform: str, params_cache: dict | None = None,
                            env: dict | None = None) -> dict:
    """Gateway crash-recovery arm (ISSUE 17): one REAL replica behind a
    persisting gateway. The upstream wedges mid-stream (stall, not
    sever), the gateway process is abandoned with the journal entry
    open — handler thread still blocked on the silent upstream — and a
    REPLACEMENT gateway boots from the same append-log. The client
    reconnects with its request_id and must receive exactly the
    remaining bytes: zero error frames, prefix + splice byte-identical
    to an uninterrupted greedy run. BENCH_ASSERT_GATEWAY_RESTART=1
    hard-fails the capture on any violation."""
    import gc
    import json as _json
    import tempfile
    import urllib.request

    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.operator.gateway import Gateway
    from ollama_operator_tpu.runtime.engine import (EngineConfig,
                                                    resolve_cache_dtype)
    from ollama_operator_tpu.runtime.service import LoadedModel
    from ollama_operator_tpu.server.app import ModelManager, serve
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS
    from ollama_operator_tpu.server.names import ModelName

    on_cpu = platform == "cpu"
    if on_cpu:
        dtype = "float32"
    kv_dtype = resolve_cache_dtype(
        os.environ.get("BENCH_KV_DTYPE", "float32" if on_cpu else "int8"))
    cfg = get_config(model)
    params, _, dtype = _bench_params(
        jax, cfg, model, dtype, on_cpu, params_cache)
    tok = _bench_tokenizer(cfg.vocab_size)
    name = ModelName.parse("bench").short

    serve_seq = min(seq, cfg.max_seq_len)
    ps = max(8, min(page_size, serve_seq // 8))
    # small decode chunks: many frames per response, so the stall lands
    # mid-stream with real progress journaled on both sides of it
    chunk_eff = max(2, min(chunk, serve_seq // 32))
    gen_tokens = max(24, min(48, serve_seq // 4))
    pool = n_pages or slots * (-(-serve_seq // ps) + 2) + 8
    log(f"bench: gateway-restart capture model={model} "
        f"tokens={gen_tokens} chunk={chunk_eff}")

    lm = LoadedModel(
        name, cfg, params, tok,
        ecfg=EngineConfig(max_slots=slots, max_seq_len=serve_seq,
                          decode_chunk=chunk_eff, cache_dtype=kv_dtype,
                          paged=True, page_size=ps, n_pages=pool,
                          min_prefill_bucket=16))
    tmp = tempfile.mkdtemp(prefix="bench-gwrestart-")
    manager = ModelManager(tmp, serve_models=True, default_keep_alive=-1)
    manager.loaded = lm
    httpd = serve(manager, "127.0.0.1", 0)
    proxy = _StallProxy(httpd.server_address[1])

    persist_path = os.path.join(tmp, "gateway-journal.ndjson")
    genv = {
        "TPU_GATEWAY_PERSIST": persist_path,
        "TPU_GATEWAY_PERSIST_FLUSH_MS": "5",
        "TPU_GATEWAY_EJECT_FAILURES": "3",
        "TPU_GATEWAY_EJECT_S": "60",
        "TPU_GATEWAY_SLOW_SCRAPE_MS": "30000",   # loaded CPU != slow
    }
    saved = {k: os.environ.get(k) for k in genv}
    os.environ.update(genv)

    def boot():
        gw = Gateway(replicas=[("r0", f"http://127.0.0.1:{proxy.port}")],
                     port=0, scrape_period_s=0.2)
        gw.start()
        return gw

    def stream(base, body, timeout_s=600.0):
        """One NDJSON stream -> (text, error_frames, stalled, resp). A
        read timeout marks the wedge: by then every frame the gateway
        emitted has been drained off the socket, so the captured text
        is exactly the client-visible prefix."""
        req = urllib.request.Request(
            base + "/api/generate", data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        text, errors, stalled, resp = [], [], False, None
        try:
            resp = urllib.request.urlopen(req, timeout=timeout_s)
            for line in resp:
                if not line.strip():
                    continue
                frame = _json.loads(line)
                if "error" in frame:
                    errors.append(frame)
                elif not frame.get("done"):
                    text.append(frame.get("response") or "")
        except TimeoutError:
            stalled = True
        except OSError as e:
            if "timed out" in str(e):
                stalled = True
            else:
                raise
        return "".join(text), errors, stalled, resp

    w0 = METRICS.get("tpu_model_gateway_persist_writes_total")
    t_wall = time.perf_counter()
    prompt = "gateway-restart-" + "q" * max(8, prompt_len // 4)
    opts = {"num_predict": gen_tokens, "temperature": 0.0}

    try:
        gw1 = boot()
        # reference: the same greedy request, uninterrupted (no
        # request_id, so it cannot collide with the resume)
        ref_text, ref_errors, _, _ = stream(
            gw1.base_url, {"model": "bench", "prompt": prompt,
                           "stream": True, "options": opts})
        # the reference also calibrates the cut: the proxy saw its full
        # wire size, and 30% of it is safely past the first frame and
        # well short of the last (the pump records it at upstream EOF,
        # a beat after the client finishes reading)
        deadline = time.monotonic() + 5.0
        while not proxy.last_body_bytes and time.monotonic() < deadline:
            time.sleep(0.01)
        if not proxy.last_body_bytes:
            raise AssertionError("reference stream size never recorded")
        proxy.arm(max(120, int(proxy.last_body_bytes * 0.3)))
        body = {"model": "bench", "prompt": prompt, "stream": True,
                "request_id": "bench-gw-restart-1", "options": opts}
        prefix_text, prefix_errors, stalled, dangling = stream(
            gw1.base_url, body, timeout_s=5.0)

        r0 = METRICS.get("tpu_model_gateway_persist_restores_total")
        f0 = METRICS.get("tpu_model_gateway_failovers_total",
                         '{result="replayed"}')
        # the crash: stop() flushes the append-log and kills the scrape
        # loop but leaves the wedged handler thread blocked on its
        # silent upstream — the journal entry stays open, no close
        # record is ever written for it
        gw1.stop()
        t_boot = time.perf_counter()
        gw2 = boot()
        restore_ms = (time.perf_counter() - t_boot) * 1000.0
        restored = int(METRICS.get(
            "tpu_model_gateway_persist_restores_total") - r0)
        t_res = time.perf_counter()
        resume_text, resume_errors, resume_stalled, _ = stream(
            gw2.base_url, body)
        resume_ms = (time.perf_counter() - t_res) * 1000.0
        replayed = int(METRICS.get("tpu_model_gateway_failovers_total",
                                   '{result="replayed"}') - f0)
        journal = gw2.journal_stats()
        writes = int(METRICS.get(
            "tpu_model_gateway_persist_writes_total") - w0)
        gw2.stop()
        del dangling
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        proxy.close()
        httpd.shutdown()
        manager.loaded = None
        lm.unload()

    spliced = prefix_text + resume_text
    bit_identical = bool(ref_text) and spliced == ref_text
    stalled_mid_stream = bool(
        stalled and prefix_text and len(prefix_text) < len(ref_text))
    wall = time.perf_counter() - t_wall

    rec = {
        "model": model,
        "mode": "gateway_restart",
        "ref_chars": len(ref_text),
        "prefix_chars": len(prefix_text),
        "resume_chars": len(resume_text),
        "stalled_mid_stream": stalled_mid_stream,
        "bit_identical": bit_identical,
        "client_error_frames": (len(ref_errors) + len(prefix_errors)
                                + len(resume_errors)),
        "resume_stalled": bool(resume_stalled),
        "persist_writes": writes,
        "restored_streams": restored,
        "failovers_replayed": replayed,
        "journal_live": journal["live"],
        "restore_ms": round(restore_ms, 1),
        "resume_ms": round(resume_ms, 1),
        "gen_tokens": int(gen_tokens),
        "slots": slots,
        "dtype": dtype,
        "paged": True,
        "seq": int(serve_seq),
        "wall_s": round(wall, 2),
    }
    if env:
        rec["env"] = dict(env)
    log(f"bench: gateway-restart capture done: {json.dumps(rec)}")
    if os.environ.get("BENCH_ASSERT_GATEWAY_RESTART") == "1":
        problems = []
        if not stalled_mid_stream:
            problems.append(
                f"stall never landed mid-stream (prefix "
                f"{len(prefix_text)} of {len(ref_text)} chars)")
        if not bit_identical:
            problems.append(
                f"prefix+resume is not byte-identical to the reference "
                f"(ref={len(ref_text)} spliced={len(spliced)} chars)")
        if rec["client_error_frames"]:
            problems.append(f"{rec['client_error_frames']} client-visible "
                            f"error frames (want 0)")
        if resume_stalled:
            problems.append("the resumed stream itself stalled")
        if restored < 1:
            problems.append("replacement gateway restored no streams "
                            "from the persist log")
        if replayed < 1:
            problems.append("reconnect never took the replayed-resume "
                            "path")
        if journal["live"]:
            problems.append(f"journal not drained: {journal['live']} "
                            f"live entries")
        if problems:
            raise AssertionError("gateway-restart arm failed: "
                                 + "; ".join(problems))
    del params
    gc.collect()
    return rec


def main() -> None:
    import jax

    # sitecustomize force-sets jax_platforms="axon,cpu"; honor an explicit
    # JAX_PLATFORMS env override (CPU smoke runs) the same way conftest does.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    # persistent XLA compilation cache, same mechanism the server ships
    # (server/__main__.py --cache): round-4's capture suite died to ~250 s
    # of decode-bucket recompiles PER capture — on a warm cache those are
    # disk reads. Opt out with BENCH_XLA_CACHE=0 (cold-compile A/Bs).
    if os.environ.get("BENCH_XLA_CACHE", "") != "0":
        xla_cache = os.environ.get(
            "BENCH_XLA_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".xla_bench_cache"))
        os.makedirs(xla_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    devs = jax.devices()
    platform = devs[0].platform
    log(f"bench: devices={[d.platform for d in devs]}")

    # deadline: absolute (set by the supervisor to cover import/init time
    # too) or BENCH_BUDGET_S from now for direct BENCH_CHILD=1 runs
    if os.environ.get("BENCH_DEADLINE_TS"):
        deadline = float(os.environ["BENCH_DEADLINE_TS"])
    else:
        deadline = time.time() + float(os.environ.get("BENCH_BUDGET_S",
                                                      "1260"))
    partial_path = os.environ.get("BENCH_PARTIAL")
    partial_f = open(partial_path, "w") if partial_path else None
    if partial_f:
        print(json.dumps({"_meta": True, "platform": platform,
                          "n_devices": len(devs)}),
              file=partial_f, flush=True)

    # committed capture record: every capture also appends to a repo-tracked
    # jsonl (round 4 gitignored its window files and lost the round's
    # headline evidence — VERDICT r4 weak #2). BENCH_CAPTURE_LOG overrides;
    # "0" disables (throwaway probes).
    runlog_f = None
    runlog_path = os.environ.get("BENCH_CAPTURE_LOG", os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_runs",
        "captures.jsonl"))
    if runlog_path and runlog_path != "0":
        if os.path.dirname(runlog_path):
            os.makedirs(os.path.dirname(runlog_path), exist_ok=True)
        runlog_f = open(runlog_path, "a")
        print(json.dumps({"_run": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime()),
                          "platform": platform, "n_devices": len(devs)}),
              file=runlog_f, flush=True)

    def envi(name, dflt):
        return int(os.environ.get(name, str(dflt)))

    common = dict(
        chunk=envi("BENCH_DECODE_CHUNK", 32),
        page_size=envi("BENCH_PAGE_SIZE", 64),
        n_pages=envi("BENCH_N_PAGES", 0) or None,
        platform=platform,
    )
    knobs = dict(slots=envi("BENCH_SLOTS", 8),
                 steps=envi("BENCH_STEPS", 64),
                 seq=envi("BENCH_SEQ", 1024),
                 prompt_len=envi("BENCH_PROMPT", 128),
                 paged=os.environ.get("BENCH_PAGED", "") == "1",
                 mixed=os.environ.get("BENCH_MIXED", "") == "1")
    if os.environ.get("BENCH_MODEL"):
        # pinned single capture — manual runs / CPU fallback keep the old
        # knob semantics exactly; BENCH_HTTP=1 drives it through the real
        # server instead of the bare engine
        plan = [dict(model=os.environ["BENCH_MODEL"],
                     dtype=os.environ.get("BENCH_DTYPE", "int8"),
                     http=os.environ.get("BENCH_HTTP", "") == "1",
                     mixed_arm=os.environ.get("BENCH_MIXED_ARM", "") == "1",
                     prefix_arm=os.environ.get("BENCH_PREFIX_ARM",
                                               "") == "1",
                     overload_arm=os.environ.get("BENCH_OVERLOAD_ARM",
                                                 "") == "1",
                     restart_arm=os.environ.get("BENCH_RESTART_ARM",
                                                "") == "1",
                     coldstart_arm=os.environ.get("BENCH_COLDSTART_ARM",
                                                  "") == "1",
                     fleet_arm=os.environ.get("BENCH_FLEET_ARM",
                                              "") == "1",
                     gateway_restart_arm=os.environ.get(
                         "BENCH_GATEWAY_RESTART_ARM", "") == "1",
                     disagg_arm=os.environ.get("BENCH_DISAGG_ARM",
                                               "") == "1",
                     **knobs)]
    elif platform == "cpu":
        # unpinned CPU smoke: tiny model, but every knob still applies
        smoke = dict(model="tiny", dtype="float32",
                     **{**knobs, "steps": envi("BENCH_STEPS", 32),
                        "seq": envi("BENCH_SEQ", 512),
                        "prompt_len": envi("BENCH_PROMPT", 32)})
        plan = [smoke]
        if os.environ.get("BENCH_HTTP", "") == "1":
            # same config through the real HTTP server so assemble() can
            # report http_vs_engine_pct from a seconds-scale smoke run
            plan.append({**smoke, "http": True})
        if os.environ.get("BENCH_MIXED_ARM", "") == "1":
            # stall-free batching A/B (chunked prefill + async dispatch
            # vs one-shot sync) through the real scheduler
            plan.append({**smoke, "mixed_arm": True})
        if os.environ.get("BENCH_PAGED_ASYNC_ARM", "") == "1":
            # the same A/B on the PAGED engine (ISSUE 5): async dispatch
            # double-buffers through the epoch-fenced page quarantine —
            # reported as paged_async_itl_ratio in the summary
            plan.append({**smoke, "mixed_arm": True, "paged": True})
        if os.environ.get("BENCH_PAGED_FUSED_ARM", "") == "1":
            # fused paged-attention A/B (ISSUE 16): the fused kernel vs
            # the gather+einsum reference (TPU_PAGED_FUSED=0), plus the
            # int4-vs-int8 KV-pool pair on the same paged config — the
            # summary's paged_bw_ratio (bandwidth-normalised speedup)
            # must exceed 1 and the fused arm must hold recompiles at 0
            fused = {**smoke, "paged": True, "mixed": True}
            plan += [fused,
                     {**fused, "env": {"TPU_PAGED_FUSED": "0"}},
                     {**fused, "env": {"BENCH_KV_DTYPE": "int4"}},
                     {**fused, "env": {"BENCH_KV_DTYPE": "int8"}}]
        if os.environ.get("BENCH_PREFIX_ARM", "") == "1":
            # radix prefix cache A/B (shared-system-prompt fan-out,
            # cache on vs TPU_PREFIX_CACHE=0) through the real scheduler
            plan.append({**smoke, "prefix_arm": True})
        if os.environ.get("BENCH_OVERLOAD_ARM", "") == "1":
            # overload-discipline A/B (ISSUE 8): closed-loop 5x-capacity
            # mixed-priority load vs an unloaded high-priority baseline
            # through the real scheduler; the policy invariants (high p99
            # flat, best_effort shed not erroring, shed{high}=0) hold at
            # CPU smoke scale — BENCH_ASSERT_OVERLOAD=1 gates on them
            plan.append({**smoke, "overload_arm": True, "slots": 2})
        if os.environ.get("BENCH_RESTART_ARM", "") == "1":
            # restart recovery (ISSUE 9): mid-stream engine kill with
            # replay on — zero client-visible errors, bit-identical
            # continuation, recovery time in the summary.
            # BENCH_ASSERT_RESTART=1 gates on it (policy, not perf)
            plan.append({**smoke, "restart_arm": True, "slots": 2,
                         "paged": True})
        if os.environ.get("BENCH_COLDSTART_ARM", "") == "1":
            # scale-to-zero cold start (ISSUE 11): warm-snapshot restore
            # vs the full warm_buckets pass — the woken engine's first
            # dispatch must not recompile. BENCH_ASSERT_COLDSTART=1
            # gates on it (engine policy, not perf)
            plan.append({**smoke, "coldstart_arm": True, "slots": 2,
                         "seq": 128})
        if os.environ.get("BENCH_FLEET_ARM", "") == "1":
            # fleet gateway (ISSUE 15): K=4 real servers behind the
            # cache-aware gateway — aggregate prefix hit rate must hold
            # >= 0.9x the single-replica rate, and a replica killed
            # mid-stream must fail over with zero client error frames,
            # byte-identical. BENCH_ASSERT_FLEET=1 gates on it
            plan.append({**smoke, "fleet_arm": True, "slots": 2})
        if os.environ.get("BENCH_GATEWAY_RESTART_ARM", "") == "1":
            # gateway crash recovery (ISSUE 17): a gateway abandoned
            # mid-stream with its journal persisted, the replacement
            # restores from the append-log, and the reconnecting client
            # gets a byte-identical zero-error splice.
            # BENCH_ASSERT_GATEWAY_RESTART=1 gates on it
            plan.append({**smoke, "gateway_restart_arm": True,
                         "slots": 2})
        if os.environ.get("BENCH_DISAGG_ARM", "") == "1":
            # disaggregated prefill/decode (ISSUE 20): steady decode load
            # vs the same load under a long-prompt prefill burst, unified
            # 2-replica fleet vs a 1+1 pool split — decode ITL p99 must
            # stay ~flat under the burst, handoff streams byte-identical
            # to the unified references, real KV pages moved, and
            # async_fallback_total 0. BENCH_ASSERT_DISAGG=1 gates on it
            plan.append({**smoke, "disagg_arm": True, "slots": 2})
        if os.environ.get("BENCH_SPEC_ARM", "") == "1":
            # fused prompt-lookup speculation (ISSUE 6): lookup /
            # accept_all / reject_all sub-arms on a repetition-heavy
            # workload vs the chunked-decode baseline — the summary's
            # spec_* ratios gate per-dispatch cost and tok/s speedup.
            # The arm needs enough steps that the drafter's warm-up miss
            # phase (before the greedy stream settles into its loop)
            # amortises — short runs under-report the steady-state win.
            plan.append({**smoke, "spec": True,
                         "steps": max(96, envi("BENCH_STEPS", 32))})
    else:
        # the full TPU suite, deadline-ordered so a cut run still records
        # the strongest evidence (VERDICT r4 #1/#2): the round-comparable
        # headline first, then the kernel-default A/B pairs — v3 vs v2 on
        # the GQA short-ctx flagship (the one driver-recorded r4 A/B
        # showed v3 −3.3% there, inside noise but the wrong sign for the
        # default flip), the B=64 ladder arm, the long-ctx pair (where v3's
        # +17% claim lives), then MHA paged — each A/B at 128 steps so a
        # ±5% band resolves. Same-model captures are adjacent where the
        # evidence ordering allows (params_cache holds one model).
        ab = dict(steps=128, seq=1024, prompt_len=128, paged=True,
                  mixed=True)
        plan = [
            dict(model="phi", dtype="int8", slots=8, steps=64, seq=1024,
                 prompt_len=128, paged=False, mixed=False),
            # the SHIPPED zero-config GQA default (r5: 64 slots, ps=128,
            # dense-24 pool = 192 pages) — the flagship config every
            # future round must track; a regression here (e.g. pool-dry
            # preemption) is a regression in what `kubectl apply` serves
            dict(model="tinyllama", dtype="int8", slots=64, page_size=128,
                 n_pages=192, **ab),
            # GQA short-ctx flagship A/B: v3 (default) then the v2 revert
            dict(model="tinyllama", dtype="int8", slots=32, **ab),
            dict(model="tinyllama", dtype="int8", slots=32,
                 env={"TPU_PAGED_V3": "0"}, **ab),
            # fused-kernel A/B (ISSUE 16): the gather+einsum reference
            # re-enabled — paired with the fused arm above for the
            # summary's paged_bw_ratio (bandwidth-normalised speedup)
            dict(model="tinyllama", dtype="int8", slots=32,
                 env={"TPU_PAGED_FUSED": "0"}, **ab),
            # int4 KV pool vs the int8 flagship: half the KV stream per
            # step on the same config — capacity AND bandwidth headroom
            dict(model="tinyllama", dtype="int8", slots=32,
                 env={"BENCH_KV_DTYPE": "int4"}, **ab),
            # long-ctx A/B: the regime the v3 live-page pipeline targets
            dict(model="tinyllama", dtype="int8", slots=32, steps=128,
                 seq=2048, prompt_len=1024, paged=True, mixed=True),
            dict(model="tinyllama", dtype="int8", slots=32, steps=128,
                 seq=2048, prompt_len=1024, paged=True, mixed=True,
                 env={"TPU_PAGED_V3": "0"}),
            # dense GQA baseline (paged-vs-dense aggregate ratio)
            dict(model="tinyllama", dtype="int8", slots=8, steps=64,
                 seq=1024, prompt_len=128, paged=False, mixed=False),
            # MHA paged A/B (phi, KvH=32): v3 made MHA page by default;
            # the v2 arm tracks the old per-head-dot gap
            dict(model="phi", dtype="int8", slots=32, steps=128, seq=1024,
                 prompt_len=128, paged=True, mixed=True),
            dict(model="phi", dtype="int8", slots=32, steps=128, seq=1024,
                 prompt_len=128, paged=True, mixed=True,
                 env={"TPU_PAGED_V3": "0"}),
            # the headline config measured THROUGH /api/generate (the
            # surface the metric names) — delta vs capture 1 = HTTP +
            # scheduler + tokenize overhead
            dict(model="phi", dtype="int8", slots=8, steps=64, seq=1024,
                 prompt_len=128, paged=False, mixed=False, http=True),
            # MHA decode-kernel A/B vs capture 1 (same config, kernel
            # on): keeps the einsum bail measurement-backed
            dict(model="phi", dtype="int8", slots=8, steps=64, seq=1024,
                 prompt_len=128, paged=False, mixed=False,
                 env={"TPU_MHA_KERNEL": "1"}),
            # speculative-decoding envelope BEFORE the int4 arm so the
            # (phi, int8) params cache survives into it (the int4 entry
            # evicts the single-model cache)
            dict(model="phi", dtype="int8", slots=8, steps=64, seq=1024,
                 prompt_len=128, paged=False, mixed=False, spec=True),
            # int4 A/B vs capture 1: packed nibbles through the fused
            # pallas qmm (capacity feature; bandwidth parity tracked)
            dict(model="phi", dtype="int4", slots=8, steps=64, seq=1024,
                 prompt_len=128, paged=False, mixed=False),
            # stall-free batching A/B through the real scheduler: steady
            # decode batch + Poisson long-prompt arrivals, chunked prefill
            # + async double-buffered dispatch vs one-shot sync, dense
            dict(model="tinyllama", dtype="int8", slots=16, steps=128,
                 seq=2048, prompt_len=1024, paged=False, mixed=False,
                 mixed_arm=True),
            # the same A/B on the PAGED engine (ISSUE 5): async dispatch
            # now double-buffers in paged mode through the epoch-fenced
            # page quarantine — itl_p99_ratio here is the summary's
            # paged_async_itl_ratio (acceptance: paged async keeps the
            # stall-free win instead of silently falling back to sync)
            dict(model="tinyllama", dtype="int8", slots=16, steps=128,
                 seq=2048, prompt_len=1024, paged=True, mixed=False,
                 mixed_arm=True),
            # radix prefix-cache A/B through the real scheduler: K
            # concurrent requests sharing a 512-token system prompt,
            # cache on (page stitch) vs off (parked-slot baseline) —
            # ISSUE-4 acceptance: >=70% prompt tokens from cache and
            # TTFT p95 >= 2x better with the cache on
            dict(model="tinyllama", dtype="int8", slots=16, steps=64,
                 seq=2048, prompt_len=512, paged=True, mixed=False,
                 prefix_arm=True),
            # overload discipline (ISSUE 8): 5x-capacity mixed-priority
            # closed loop vs unloaded baseline — the summary's
            # overload_high_p99_ttft_ratio must hold <= 2.0 at TPU scale
            dict(model="tinyllama", dtype="int8", slots=16, steps=64,
                 seq=1024, prompt_len=128, paged=False, mixed=False,
                 overload_arm=True),
            # restart recovery (ISSUE 9): mid-stream engine kill on the
            # paged engine with replay on — the summary's
            # restart_client_error_rate must stay 0 and recovery_ms
            # bounds the one stall clients see across a TPU restart
            dict(model="tinyllama", dtype="int8", slots=16, steps=64,
                 seq=1024, prompt_len=128, paged=True, mixed=False,
                 restart_arm=True),
            # scale-to-zero cold start (ISSUE 11): on the TPU the warm
            # snapshot carries serialized executables, so restore_ms is
            # deserialize time, not compile time — the summary's
            # coldstart_speedup is the wake-latency win and
            # coldstart_recompiles must stay 0
            dict(model="tinyllama", dtype="int8", slots=16, steps=64,
                 seq=1024, prompt_len=128, paged=True, mixed=False,
                 coldstart_arm=True),
        ]

    captures = []
    params_cache: dict = {}
    common["params_cache"] = params_cache
    worst_capture_s = 240.0   # prior until a capture is actually timed
    for i, cap in enumerate(plan):
        if i > 0 and time.time() + worst_capture_s > deadline:
            log(f"bench: {deadline - time.time():.0f}s left < worst "
                f"capture {worst_capture_s:.0f}s — skipping remaining "
                f"{len(plan) - i} captures")
            break
        t_cap = time.monotonic()
        # capture-scoped env (e.g. TPU_MHA_KERNEL=1): kernel routing reads
        # the environment at trace time — set before the engine compiles,
        # restore even on failure so captures stay independent
        cap_env = cap.get("env") or {}
        saved_env = {k: os.environ.get(k) for k in cap_env}
        os.environ.update(cap_env)
        http = cap.pop("http", False)
        spec = cap.pop("spec", False)
        mixed_arm = cap.pop("mixed_arm", False)
        prefix_arm = cap.pop("prefix_arm", False)
        overload_arm = cap.pop("overload_arm", False)
        restart_arm = cap.pop("restart_arm", False)
        coldstart_arm = cap.pop("coldstart_arm", False)
        fleet_arm = cap.pop("fleet_arm", False)
        gateway_restart_arm = cap.pop("gateway_restart_arm", False)
        disagg_arm = cap.pop("disagg_arm", False)
        try:
            fn = (measure_disagg if disagg_arm
                  else measure_gateway_restart if gateway_restart_arm
                  else measure_fleet if fleet_arm
                  else measure_coldstart if coldstart_arm
                  else measure_restart if restart_arm
                  else measure_overload if overload_arm
                  else measure_prefix if prefix_arm
                  else measure_mixed if mixed_arm
                  else measure_http if http
                  else measure_spec if spec else measure)
            # plan-level keys override the global knobs (a capture may pin
            # its own page_size/n_pages — e.g. the shipped-default arm)
            captures.append(fn(jax, **{**common, **cap}))
        except Exception as e:   # a later capture must not void the headline
            if i == 0:
                raise
            log(f"bench: capture {cap['model']} paged={cap['paged']} "
                f"failed: {type(e).__name__}: {e}")
            continue
        finally:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        worst_capture_s = max(worst_capture_s, time.monotonic() - t_cap)
        if partial_f:
            print(json.dumps(captures[-1]), file=partial_f, flush=True)
        if runlog_f:
            print(json.dumps(captures[-1]), file=runlog_f, flush=True)
        # rewrite the full summary after EVERY capture: an external kill of
        # the whole process tree (the driver's window timeout — round 4's
        # rc=124) still leaves the latest complete summary as the last
        # parseable stdout line, so `parsed` is never null
        print(assemble(captures, platform, len(devs)), flush=True)

    if partial_f:
        partial_f.close()
    if runlog_f:
        runlog_f.close()


def assemble(captures: list, platform: str, n_devices: int) -> str:
    """The ONE output JSON line, from whatever captures completed."""
    head = captures[0]
    metric = f"{head['model']}_decode_tok_s_b{head['slots']}"
    baseline = load_baseline(metric)
    # a pinned arm-only run (e.g. BENCH_MODEL + BENCH_FLEET_ARM) has a
    # policy capture at the head with no throughput headline
    vs = (head["tok_s"] / baseline[0]
          if baseline and baseline[0] and head.get("tok_s") else 1.0)
    # HTTP-vs-engine serving ratio (ISSUE 1 acceptance: >=85%): pair each
    # http capture with the engine capture of the same config — engine
    # captures are the ones with neither a "surface" nor a "mode" key
    http_vs_engine_pct = http_ttft_ratio = None
    for h in captures:
        if h.get("surface") != "http":
            continue
        eng = next((c for c in captures
                    if "surface" not in c and "mode" not in c
                    and c["model"] == h["model"]
                    and c["slots"] == h["slots"]
                    and c.get("paged") == h.get("paged")), None)
        if eng and eng.get("tok_s"):
            http_vs_engine_pct = round(100.0 * h["tok_s"] / eng["tok_s"], 1)
            if eng.get("ttft_p50_ms"):
                http_ttft_ratio = round(
                    h["ttft_p50_ms"] / eng["ttft_p50_ms"], 2)
            break
    # stall-free batching A/B (ISSUE 3 acceptance: itl_p99_ratio >= 2,
    # bg_tok_s_ratio >= 1): the mixed-load capture's headline ratios
    mixed_itl_p99_ratio = mixed_tok_s_ratio = None
    for c in captures:
        if c.get("mode") == "mixed":
            mixed_itl_p99_ratio = c.get("itl_p99_ratio")
            mixed_tok_s_ratio = c.get("bg_tok_s_ratio")
            break
    # radix prefix-cache A/B (ISSUE 4 acceptance: hit rate >= 0.7,
    # TTFT p95 ratio >= 2 on TPU): the shared-prefix capture's headlines
    prefix_hit_rate = prefix_ttft_ratio = None
    # tiered-KV headlines (ISSUE 18): churn hit rate with the host arena
    # on vs off, off/on TTFT ratio, and the fleet arm's warm-wake verdict
    churn_hit_rate = churn_hit_rate_off = churn_ttft_ratio = None
    tier_fleet_warm_hit = None
    for c in captures:
        if c.get("mode") == "prefix":
            prefix_hit_rate = c.get("prefix_hit_rate")
            prefix_ttft_ratio = c.get("prefix_ttft_ratio")
            churn_hit_rate = c.get("churn_hit_rate")
            churn_hit_rate_off = c.get("churn_hit_rate_off")
            churn_ttft_ratio = c.get("churn_ttft_ratio")
            tier_fleet_warm_hit = (c.get("fleet") or {}).get(
                "warm_first_hit")
            break
    # paged async dispatch A/B (ISSUE 5): the paged mixed-load capture's
    # sync/async ITL ratio, plus the prefix capture's sync/async TTFT
    # ratio on the radix-hit shape — both >= 1 means epoch-fenced
    # double-buffering holds its win in paged mode
    paged_async_itl_ratio = paged_async_ttft_ratio = None
    for c in captures:
        if c.get("mode") == "mixed_paged":
            paged_async_itl_ratio = c.get("itl_p99_ratio")
            break
    for c in captures:
        if c.get("mode") == "prefix":
            paged_async_ttft_ratio = c.get("paged_async_ttft_ratio")
            break
    # fused prompt-lookup speculation (ISSUE 6 acceptance: the REAL
    # lookup arm's per-dispatch cost <= 1.2x a baseline chunk dispatch,
    # tok/s speedup > 1 on the repetition-heavy workload)
    spec_tok_s_ratio = spec_dispatch_ratio = spec_acceptance = None
    for c in captures:
        if str(c.get("mode", "")).startswith("spec_fused"):
            spec_tok_s_ratio = c.get("speedup")
            spec_dispatch_ratio = c.get("dispatch_ratio")
            spec_acceptance = c.get("spec_acceptance")
            break
    # overload discipline (ISSUE 8 acceptance: high p99 TTFT ratio <= 2
    # at 5x load, best_effort shed > 0 while shed{class=high} stays 0,
    # finite Retry-After on every early reject)
    overload_high_ratio = overload_be_shed = overload_high_shed = None
    overload_retry_finite = None
    for c in captures:
        if c.get("mode") == "overload":
            overload_high_ratio = c.get("overload_high_p99_ttft_ratio")
            overload_be_shed = c.get("overload_best_effort_shed")
            overload_high_shed = c.get("overload_high_shed")
            overload_retry_finite = c.get("retry_after_finite")
            break
    # restart recovery (ISSUE 9 acceptance: zero client-visible errors
    # and bit-identical continuation across a mid-stream engine kill
    # with replay on; recovery_ms is the one stall clients see)
    restart_err_rate = restart_bit_identical = restart_recovery_ms = None
    for c in captures:
        if c.get("mode") == "restart":
            restart_err_rate = c.get("client_error_rate")
            restart_bit_identical = c.get("bit_identical")
            restart_recovery_ms = c.get("recovery_ms")
            break
    # scale-to-zero cold start (ISSUE 11 acceptance: a wake served from
    # the warm snapshot dispatches with ZERO recompiles; the speedup is
    # restore time vs the from-scratch warm_buckets pass)
    coldstart_restore_ms = coldstart_speedup = coldstart_recompiles = None
    for c in captures:
        if c.get("mode") == "coldstart":
            coldstart_restore_ms = c.get("restore_ms")
            coldstart_speedup = c.get("restore_speedup")
            coldstart_recompiles = c.get("recompiles_after_restore")
            break
    # fleet gateway (ISSUE 15 acceptance: K=4 aggregate prefix hit rate
    # >= 0.9x single-replica, zero client-visible error frames across a
    # mid-stream replica kill, byte-identical failover continuation)
    fleet_hit_rate = fleet_hit_ratio = fleet_bit_identical = None
    fleet_errors = fleet_replayed = None
    for c in captures:
        if c.get("mode") == "fleet":
            fleet_hit_rate = c.get("fleet_hit_rate")
            fleet_hit_ratio = c.get("fleet_vs_single_hit_ratio")
            fleet_bit_identical = c.get("kill_bit_identical")
            fleet_errors = c.get("client_error_frames")
            fleet_replayed = (c.get("failovers") or {}).get("replayed")
            break
    # gateway crash recovery (ISSUE 17 acceptance: a gateway killed
    # mid-stream leaves a persisted journal; the replacement restores it
    # and the reconnecting client's spliced stream is byte-identical
    # with zero error frames)
    gwr_bit_identical = gwr_errors = gwr_restored = None
    gwr_restore_ms = gwr_resume_ms = None
    for c in captures:
        if c.get("mode") == "gateway_restart":
            gwr_bit_identical = c.get("bit_identical")
            gwr_errors = c.get("client_error_frames")
            gwr_restored = c.get("restored_streams")
            gwr_restore_ms = c.get("restore_ms")
            gwr_resume_ms = c.get("resume_ms")
            break
    # disaggregated prefill/decode (ISSUE 20 acceptance: decode ITL p99
    # stays ~flat under a prefill burst, handoff streams byte-identical
    # to the unified references, real pages moved, async_fallback 0)
    disagg_itl_ratio = disagg_bit_identical = disagg_handoffs = None
    disagg_pages = disagg_errors = None
    for c in captures:
        if c.get("mode") == "disagg":
            disagg_itl_ratio = c.get("disagg_itl_p99_ratio")
            disagg_bit_identical = c.get("handoff_bit_identical")
            disagg_handoffs = (c.get("handoffs") or {}).get("transferred")
            disagg_pages = c.get("kv_transfer_pages")
            disagg_errors = c.get("client_error_frames")
            break
    # fused paged-attention A/B (ISSUE 16): pair the TPU_PAGED_FUSED=0
    # reference with the fused capture of the same config — the ratio is
    # tokens-per-HBM-byte (tok_s x bytes/step, the steps cancel), i.e.
    # how much further the fused kernel stretches the memory bus. The
    # acceptance bar is > 1 with ZERO recompiles in the fused arm.
    paged_bw_ratio = paged_fused_recompiles = None
    kv_int4_tok_s_ratio = kv_int4_bytes_ratio = None
    engine_caps = [c for c in captures
                   if "mode" not in c and "surface" not in c]
    for off in engine_caps:
        if not off.get("paged") or off.get("paged_fused") is not False:
            continue
        on = next((c for c in engine_caps
                   if c.get("paged_fused")
                   and c["model"] == off["model"]
                   and c["slots"] == off["slots"]
                   and c.get("kv_dtype") == off.get("kv_dtype")), None)
        if on and on.get("tok_s") and off.get("tok_s") \
                and on.get("bytes_per_step_gb"):
            paged_bw_ratio = round(
                (off["bytes_per_step_gb"] / on["bytes_per_step_gb"])
                * (on["tok_s"] / off["tok_s"]), 3)
            paged_fused_recompiles = on.get("recompiles")
            break
    # int4 KV pool vs the int8 arm of the same shape: tok/s parity at
    # roughly half the KV stream (capacity is the headline, bandwidth
    # headroom the rider)
    for c in engine_caps:
        if c.get("kv_dtype") != "int4" or not c.get("paged"):
            continue
        i8 = next((d for d in engine_caps
                   if d.get("kv_dtype") == "int8" and d.get("paged_fused")
                   and d["model"] == c["model"]
                   and d["slots"] == c["slots"]), None)
        if i8 and i8.get("tok_s") and i8.get("bytes_per_step_gb"):
            kv_int4_tok_s_ratio = round(c["tok_s"] / i8["tok_s"], 3)
            kv_int4_bytes_ratio = round(
                c["bytes_per_step_gb"] / i8["bytes_per_step_gb"], 3)
            break
    # the retired sync-fallback causes (ISSUE 16): everything the bench
    # drove through the real scheduler must have stayed async — grammar
    # decodes from device tables, dp-sharded pools quarantine per shard
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS
    async_fallbacks = {
        cause: int(METRICS.get("tpu_model_async_fallback_total",
                               f'{{cause="{cause}"}}'))
        for cause in ("grammar", "paged_dp", "spec")}
    return json.dumps({
        "metric": metric,
        "value": head.get("tok_s"),
        "unit": "tok/s",
        "vs_baseline": round(vs, 3),
        # which BENCH_r*.json the ratio resolved against (earliest recorded)
        "baseline_round": baseline[1] if baseline else None,
        # surface-level captures (http/spec) don't carry every
        # engine-capture field — the headline is normally capture 0
        # (engine), but a pinned BENCH_HTTP run must still assemble
        "ttft_p50_ms": head.get("ttft_p50_ms"),
        "decode_step_ms": head.get("decode_step_ms"),
        "http_vs_engine_pct": http_vs_engine_pct,
        "http_ttft_ratio": http_ttft_ratio,
        "mixed_itl_p99_ratio": mixed_itl_p99_ratio,
        "mixed_tok_s_ratio": mixed_tok_s_ratio,
        "prefix_hit_rate": prefix_hit_rate,
        "prefix_ttft_ratio": prefix_ttft_ratio,
        "churn_hit_rate": churn_hit_rate,
        "churn_hit_rate_off": churn_hit_rate_off,
        "churn_ttft_ratio": churn_ttft_ratio,
        "tier_fleet_warm_hit": tier_fleet_warm_hit,
        "paged_async_itl_ratio": paged_async_itl_ratio,
        "paged_async_ttft_ratio": paged_async_ttft_ratio,
        "spec_tok_s_ratio": spec_tok_s_ratio,
        "spec_dispatch_ratio": spec_dispatch_ratio,
        "spec_acceptance": spec_acceptance,
        "overload_high_p99_ttft_ratio": overload_high_ratio,
        "overload_best_effort_shed": overload_be_shed,
        "overload_high_shed": overload_high_shed,
        "overload_retry_after_finite": overload_retry_finite,
        "restart_client_error_rate": restart_err_rate,
        "restart_bit_identical": restart_bit_identical,
        "restart_recovery_ms": restart_recovery_ms,
        "coldstart_restore_ms": coldstart_restore_ms,
        "coldstart_speedup": coldstart_speedup,
        "coldstart_recompiles": coldstart_recompiles,
        "fleet_hit_rate": fleet_hit_rate,
        "fleet_vs_single_hit_ratio": fleet_hit_ratio,
        "fleet_kill_bit_identical": fleet_bit_identical,
        "fleet_client_error_frames": fleet_errors,
        "fleet_failovers_replayed": fleet_replayed,
        "gateway_restart_bit_identical": gwr_bit_identical,
        "gateway_restart_client_error_frames": gwr_errors,
        "gateway_restart_restored_streams": gwr_restored,
        "gateway_restart_restore_ms": gwr_restore_ms,
        "gateway_restart_resume_ms": gwr_resume_ms,
        "disagg_itl_p99_ratio": disagg_itl_ratio,
        "disagg_handoff_bit_identical": disagg_bit_identical,
        "disagg_handoffs_transferred": disagg_handoffs,
        "disagg_kv_transfer_pages": disagg_pages,
        "disagg_client_error_frames": disagg_errors,
        "paged_bw_ratio": paged_bw_ratio,
        "paged_fused_recompiles": paged_fused_recompiles,
        "kv_int4_tok_s_ratio": kv_int4_tok_s_ratio,
        "kv_int4_bytes_ratio": kv_int4_bytes_ratio,
        "async_fallbacks": async_fallbacks,
        "slots": head["slots"],
        "platform": platform,
        "dtype": head["dtype"],
        "paged": head.get("paged"),
        "n_devices": n_devices,
        "captures": captures,
    })


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        main()
    else:
        sys.exit(run_supervised())
