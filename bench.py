"""Headline benchmark: aggregate decode throughput through the real Engine.

Measures the serving path of BASELINE.md's ladder (config 1 model: phi 2.7B,
the reference's sample CR `config/samples/ollama_v1_model.yaml` image) —
continuous-batching decode tok/s plus p50 TTFT — on whatever accelerator is
attached (one real TPU chip under the driver; CPU elsewhere). Prints ONE
JSON line:

  {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N, ...}

vs_baseline is the ratio against the earliest recorded BENCH_r*.json in the
repo root (the reference publishes no numbers — BASELINE.md — so round 1
self-baselines at 1.0 and later rounds are measured against it).

Env knobs: BENCH_MODEL (preset name), BENCH_SLOTS, BENCH_STEPS, BENCH_SEQ,
BENCH_PROMPT (prompt token count).
"""

from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Supervisor: the TPU tunnel (axon backend) is flaky — jax.devices() can hang
# indefinitely or raise UNAVAILABLE. Running the measurement in a child
# process lets us bound backend init (kill + retry with backoff) and, as a
# last resort, capture on CPU so a parseable JSON line always lands.
# ---------------------------------------------------------------------------

INIT_MARKER = "bench: model="   # child logs this right after jax.devices()


def _run_attempt(env: dict, init_timeout: float, total_timeout: float):
    """One child run. Returns (rc, stdout) — rc None on timeout-kill."""
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    init_seen = threading.Event()
    err_tail: list[str] = []

    def pump_stderr():
        for line in p.stderr:
            if INIT_MARKER in line:
                init_seen.set()
            err_tail.append(line)
            del err_tail[:-50]
            sys.stderr.write(line)
            sys.stderr.flush()

    t = threading.Thread(target=pump_stderr, daemon=True)
    t.start()
    start = time.monotonic()
    # wait for the init marker OR child exit — an instant crash (import
    # error, bad model name) must not burn the whole init window
    while not init_seen.is_set():
        if p.poll() is not None:
            out = p.stdout.read()
            t.join(timeout=5)
            return p.returncode, out
        if time.monotonic() - start > init_timeout:
            log(f"bench: backend init exceeded {init_timeout:.0f}s, "
                f"killing child")
            p.kill()
            p.wait()
            return None, ""
        time.sleep(1.0)
    remaining = total_timeout - (time.monotonic() - start)
    try:
        p.wait(timeout=max(remaining, 1.0))
    except subprocess.TimeoutExpired:
        log(f"bench: run exceeded {total_timeout:.0f}s total, killing child")
        p.kill()
        p.wait()
        return None, ""
    out = p.stdout.read()
    t.join(timeout=5)
    return p.returncode, out


def run_supervised() -> int:
    # generous init windows: this box has been observed at >85% iowait,
    # where a cold `import jax` alone can take minutes — a tight timeout
    # would kill children that are merely slow-importing, not hung
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "2"))
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "300"))
    total_timeout = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    backoff = 10.0
    for attempt in range(retries + 1):
        env = dict(os.environ, BENCH_CHILD="1")
        fallback = attempt == retries
        # NB: this image's profile exports JAX_PLATFORMS=axon (preventing
        # silent CPU fallback in normal runs), so the fallback must
        # OVERRIDE it — only an explicit cpu pin skips the accelerator
        # attempts entirely
        if fallback and os.environ.get("JAX_PLATFORMS", "") != "cpu":
            # Last attempt: the accelerator never came up. Capture on CPU —
            # a real (if slow) number beats a hang for the record. The CPU
            # box may be a single core, so the fallback also drops to the
            # tiny model unless the caller pinned one: phi-2.7B f32 decode
            # on one core would blow the child budget.
            log("bench: TPU backend unavailable after retries; CPU fallback")
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("BENCH_STEPS", "32")
            env.setdefault("BENCH_SEQ", "512")
            env.setdefault("BENCH_MODEL", "tiny")
        # CPU fallback has no hang risk but single-core init is slow;
        # give it extra headroom.
        rc, out = _run_attempt(env, init_timeout * (2 if fallback else 1),
                               total_timeout)
        if rc == 0 and out.strip():
            sys.stdout.write(out)
            sys.stdout.flush()
            return 0
        log(f"bench: attempt {attempt + 1}/{retries + 1} failed "
            f"(rc={rc}); retrying in {backoff:.0f}s" if not fallback else
            f"bench: fallback attempt failed (rc={rc})")
        if not fallback:
            time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)
    return 1


def load_baseline(metric: str) -> float | None:
    runs = []
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                       "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if rec.get("metric") == metric and isinstance(
                rec.get("value"), (int, float)):
            runs.append((int(m.group(1)), float(rec["value"])))
    if not runs:
        return None
    return min(runs)[1]


def main() -> None:
    import jax

    # sitecustomize force-sets jax_platforms="axon,cpu"; honor an explicit
    # JAX_PLATFORMS env override (CPU smoke runs) the same way conftest does.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    from ollama_operator_tpu.runtime.engine import Engine, EngineConfig

    model = os.environ.get("BENCH_MODEL", "phi")
    dtype = os.environ.get("BENCH_DTYPE", "int8")
    slots = int(os.environ.get("BENCH_SLOTS", "8"))
    steps = int(os.environ.get("BENCH_STEPS", "64"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))

    devs = jax.devices()
    log(f"bench: model={model} slots={slots} steps={steps} seq={seq} "
        f"devices={[d.platform for d in devs]}")

    on_cpu = devs[0].platform == "cpu"
    if on_cpu:
        # XLA's CPU thunk runtime lacks bf16 dots; fallback captures in f32.
        dtype = "float32"
        os.environ.setdefault("BENCH_KV_DTYPE", "float32")

    import jax.numpy as jnp
    cfg = get_config(model)
    t0 = time.perf_counter()
    params = decoder.init_params(
        cfg, jax.random.key(0),
        dtype=jnp.float32 if on_cpu else jnp.bfloat16)
    jax.block_until_ready(params)
    if dtype == "int8":
        if cfg.n_experts:
            dtype = "bfloat16"   # MoE expert stacks serve dense this round
        else:
            # weight-only int8 serving (ops/quant.py): the production
            # default — decode is HBM-bound, so halving weight bytes
            # cuts the weight-streaming share of the step
            from ollama_operator_tpu.ops.quant import quantize_params
            params = quantize_params(params)   # on-device, jitted
            jax.block_until_ready(params)
    log(f"params init ({cfg.n_params/1e9:.2f}B, serve dtype={dtype}) in "
        f"{time.perf_counter()-t0:.1f}s")

    mesh = None
    if len(devs) > 1:
        tp = 1
        while (tp * 2 <= len(devs) and cfg.n_heads % (tp * 2) == 0
               and len(devs) % (tp * 2) == 0):
            tp *= 2
        mesh = make_mesh(MeshPlan.for_devices(len(devs), tp=tp))
        log(f"mesh: {dict(mesh.shape)}")

    chunk = int(os.environ.get("BENCH_DECODE_CHUNK", "32"))
    from ollama_operator_tpu.runtime.engine import resolve_cache_dtype
    kv_dtype = resolve_cache_dtype(os.environ.get("BENCH_KV_DTYPE", "int8"))
    paged = os.environ.get("BENCH_PAGED", "") == "1"
    eng = Engine(cfg, params, mesh=mesh,
                 ecfg=EngineConfig(
                     max_slots=slots, max_seq_len=seq, decode_chunk=chunk,
                     cache_dtype=kv_dtype, paged=paged,
                     page_size=int(os.environ.get("BENCH_PAGE_SIZE", "64")),
                     n_pages=int(os.environ.get("BENCH_N_PAGES", "0"))
                     or None))

    # the whole run must fit the context whatever BENCH_* says (the
    # engine clamps max_seq to cfg.max_seq_len): prompt + warmup chunk +
    # measured steps, else cache writes would clamp into the tail and
    # corrupt the measurement
    prompt_len = min(prompt_len, eng.max_seq // 2)
    calls_budget = max(1, steps // chunk)
    need = prompt_len + chunk + calls_budget * chunk + 2
    if need > eng.max_seq:
        steps = max(chunk, (eng.max_seq - prompt_len - chunk - 2)
                    // chunk * chunk)
        log(f"bench: clamping steps to {steps} to fit context "
            f"{eng.max_seq}")
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, cfg.vocab_size, size=(slots, prompt_len),
                           endpoint=False).astype(np.int32)

    # TTFT: prompt admission → first sampled token back on host, per slot.
    # First admit pays compile; measure it separately, then re-admit.
    t0 = time.perf_counter()
    eng.admit(0, prompts[0])
    compile_s = time.perf_counter() - t0
    log(f"prefill compile+run: {compile_s:.1f}s")
    eng.release(0)

    ttfts = []
    for s in range(slots):
        t0 = time.perf_counter()
        eng.admit(s, prompts[s])
        ttfts.append(time.perf_counter() - t0)
    ttft_p50_ms = float(np.median(ttfts) * 1e3)

    t0 = time.perf_counter()
    eng.warm_buckets()   # AOT-compile every attention bucket up front
    decode_compile_s = time.perf_counter() - t0
    log(f"decode warm (all buckets): {decode_compile_s:.1f}s (chunk={chunk})")
    eng.decode_n()

    calls = max(1, steps // chunk)
    t0 = time.perf_counter()
    for _ in range(calls):
        toks = eng.decode_n()   # [chunk, B], one dispatch+sync per call
    dt = time.perf_counter() - t0
    n_steps = calls * chunk
    tok_s = n_steps * slots / dt
    per_step_ms = dt / n_steps * 1e3

    metric = f"{model}_decode_tok_s_b{slots}"
    baseline = load_baseline(metric)
    vs = tok_s / baseline if baseline else 1.0
    print(json.dumps({
        "metric": metric,
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(vs, 3),
        "ttft_p50_ms": round(ttft_p50_ms, 1),
        "decode_step_ms": round(per_step_ms, 2),
        "slots": slots,
        "platform": devs[0].platform,
        "dtype": dtype,
        "paged": paged,
        "n_devices": len(devs),
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        main()
    else:
        sys.exit(run_supervised())
