"""Repo-root conftest: force tests onto a virtual 8-device CPU mesh.

Two subtleties:
- XLA_FLAGS must be set before any backend initialises.
- This image's sitecustomize registers an `axon` TPU-tunnel platform and
  force-sets jax_platforms="axon,cpu" programmatically, so the JAX_PLATFORMS
  env var alone is NOT enough — initialising the axon client from tests
  blocks on the (single-session) TPU tunnel. Override via jax.config so tests
  never touch the tunnel (SURVEY.md §4: the CPU-mesh simulation stands in for
  the reference's envtest "real API, fake kubelet" trick — real XLA SPMD
  partitioning, no TPU hardware).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
