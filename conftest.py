"""Repo-root conftest: force tests onto a virtual 8-device CPU mesh.

Two subtleties:
- XLA_FLAGS must be set before any backend initialises.
- This image's sitecustomize registers an `axon` TPU-tunnel platform and
  force-sets jax_platforms="axon,cpu" programmatically, so the JAX_PLATFORMS
  env var alone is NOT enough — initialising the axon client from tests
  blocks on the (single-session) TPU tunnel. Override via jax.config so tests
  never touch the tunnel (SURVEY.md §4: the CPU-mesh simulation stands in for
  the reference's envtest "real API, fake kubelet" trick — real XLA SPMD
  partitioning, no TPU hardware).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Single-process suite robustness (round 5, VERDICT r4 #4): a full
# `pytest tests/` run compiles many hundreds of XLA programs in ONE
# process and segfaulted inside XLA's native compile (~85% in, during a
# model reload's warm_buckets) on this host in rounds 4 and 5 — per-file
# runs are all green, so the trigger is accumulated in-process compiler
# state, not any one test. Bound it:
# - persistent on-disk compilation cache, so the per-module cache clear
#   below costs disk reads, not recompiles (same mechanism the server
#   and bench use);
# - drop live executables between modules (jax.clear_caches) so the
#   in-process accumulation resets ~45 times instead of growing
#   monotonically.
# The persistent cache is OPT-IN (TPU_TEST_XLA_CACHE=1): on this host the
# CPU-backend executable deserialization path is itself unstable — with the
# cache enabled, a fresh cache dir reproducibly yields wrong decode tokens
# and then a native segfault within a couple of engine runs, while the
# identical workload with the cache disabled is deterministic across
# dozens of runs. Recompiling after each per-module clear costs seconds
# for test-sized CPU programs; silently-corrupt cached executables cost
# correctness.
if os.environ.get("TPU_TEST_XLA_CACHE", "") == "1":
    _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              ".xla_test_cache")
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    # 0.0, NOT the 1.0 the server/bench use: test-sized CPU programs
    # compile in well under a second and would otherwise never be
    # persisted — the per-module clear would then force full recompiles
    # instead of disk reads
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import gc  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 CI")
    config.addinivalue_line(
        "markers", "chaos: fault-injection recovery tests (runtime/faults"
        ".py); the CI chaos-smoke job runs exactly this set")


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_state():
    yield
    jax.clear_caches()
    gc.collect()


@pytest.fixture(autouse=True)
def _page_accounting():
    """Refcount leaks fail loudly: after EVERY test, each PageTable still
    alive must satisfy its accounting invariant — every non-trash page
    free exactly once XOR quarantined exactly once XOR refcounted as
    mapped+pinned (ISSUE 4). With the scheduler idle (every test ends
    that way) the epoch-fence quarantine must also be EMPTY: a page
    parked there forever is a pool leak the refcount check alone cannot
    see (ISSUE 5) — the idle scheduler loop and shutdown() both drain it,
    so residue here means a fence ack went missing."""
    yield
    from ollama_operator_tpu.runtime.paged import live_tables
    for pt in live_tables():
        pt.check()
        assert pt.quarantined == 0, (
            f"{pt.quarantined} page(s) leaked in epoch quarantine "
            f"after test teardown")


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No injected fault may leak across tests: the registry is process-
    global by design (the code under test reaches it via one module
    attribute), so every test starts and ends clean."""
    from ollama_operator_tpu.runtime.faults import FAULTS
    FAULTS.reset()
    yield
    FAULTS.reset()
