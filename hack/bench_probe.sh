#!/bin/sh
# Periodic TPU bench probe: the axon tunnel is intermittently unavailable
# (VERDICT rounds 1-2), so keep attempting a real-chip capture in the
# background until one lands in BENCH_LOCAL.json. Safe to re-run.
#
# Each attempt runs the SUPERVISED bench (init bounding, partial-capture
# recovery) with BENCH_NO_FALLBACK=1 — "TPU or nothing": a CPU fallback
# here would end the loop with a number we don't want recorded.
cd "$(dirname "$0")/.." || exit 1
LOG=.bench_probe.log
N=0
while [ "$N" -lt "${PROBE_MAX:-40}" ]; do
  N=$((N + 1))
  echo "--- probe attempt $N $(date -u +%FT%TZ)" >> "$LOG"
  if BENCH_NO_FALLBACK=1 BENCH_INIT_RETRIES=0 timeout "${PROBE_TIMEOUT:-1800}" \
      python bench.py > BENCH_LOCAL.json.tmp 2>> "$LOG" \
      && grep -q '"platform"' BENCH_LOCAL.json.tmp \
      && ! grep -q '"platform": "cpu"' BENCH_LOCAL.json.tmp; then
    mv BENCH_LOCAL.json.tmp BENCH_LOCAL.json
    echo "probe SUCCESS $(date -u +%FT%TZ)" >> "$LOG"
    cat BENCH_LOCAL.json >> "$LOG"
    exit 0
  fi
  rm -f BENCH_LOCAL.json.tmp
  sleep "${PROBE_SLEEP:-420}"
done
echo "probe gave up after $N attempts" >> "$LOG"
exit 1
