#!/usr/bin/env python3
"""Assemble dist/install.yaml from config/ (the reference builds its 588-line
installer with `kustomize build config/default`, Makefile:117-121; this is
the same single-file-apply UX without the kustomize dependency).

Applies the reference's kustomize-equivalent transforms: `ollama-operator-`
name prefix on operator-owned objects, namespace rewrite system →
ollama-operator-system, RBAC subject/roleRef re-pointing, and optional image
pin via --image.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PREFIX = "ollama-operator-"
NAMESPACE = "ollama-operator-system"

SOURCES = [
    "config/crd/ollama.ayaka.io_models.yaml",
    "config/rbac/role.yaml",
    "config/rbac/leader_election_role.yaml",
    "config/rbac/model_editor_role.yaml",
    "config/rbac/model_viewer_role.yaml",
    "config/manager/manager.yaml",
]

# objects whose metadata.name gets the prefix (CRD name must stay the
# group-qualified plural; sample CRs are not part of the installer)
PREFIXED_KINDS = {"ClusterRole", "Role", "ServiceAccount", "Deployment",
                  "Namespace"}


def split_docs(text: str):
    for doc in re.split(r"^---\s*$", text, flags=re.M):
        if doc.strip():
            yield doc


def get_field(doc: str, path: str):
    """Tiny YAML field reader for the few top-level fields we transform."""
    m = re.search(rf"^{path}:\s*(\S+)\s*$", doc, flags=re.M)
    return m.group(1) if m else None


def transform(doc: str, image: str | None) -> str:
    kind = get_field(doc, "kind")
    # namespace rewrite first (applies to metadata + rolebinding subjects)
    doc = doc.replace("namespace: system", f"namespace: {NAMESPACE}")
    if kind in PREFIXED_KINDS:
        m = re.search(r"^metadata:\n((?:  .*\n)*)", doc, flags=re.M)
        if m:
            block = m.group(0)
            new_block = re.sub(r"^(  name: )(?!ollama-operator-)(\S+)",
                               rf"\g<1>{PREFIX}\g<2>", block, count=1,
                               flags=re.M)
            doc = doc.replace(block, new_block, 1)
    if kind == "Namespace":
        doc = re.sub(r"^(  name: )\S+$", rf"\g<1>{NAMESPACE}", doc,
                     count=1, flags=re.M)
    if image and kind == "Deployment":
        doc = re.sub(r"image: \S+", f"image: {image}", doc, count=1)
    return doc


def build(image: str | None = None) -> str:
    docs = []
    for src in SOURCES:
        with open(os.path.join(ROOT, src)) as f:
            for doc in split_docs(f.read()):
                docs.append(transform(doc.strip("\n"), image))
    # bindings are generated, not stored: they must reference the prefixed
    # names and final namespace
    docs.append(f"""apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRoleBinding
metadata:
  name: {PREFIX}manager-rolebinding
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: ClusterRole
  name: {PREFIX}manager-role
subjects:
  - kind: ServiceAccount
    name: {PREFIX}controller-manager
    namespace: {NAMESPACE}""")
    docs.append(f"""apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: {PREFIX}leader-election-rolebinding
  namespace: {NAMESPACE}
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: {PREFIX}leader-election-role
subjects:
  - kind: ServiceAccount
    name: {PREFIX}controller-manager
    namespace: {NAMESPACE}""")
    return "---\n".join(d.rstrip() + "\n" for d in docs)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image", default=None, help="pin the manager image")
    p.add_argument("-o", "--output",
                   default=os.path.join(ROOT, "dist", "install.yaml"))
    args = p.parse_args()
    out = build(args.image)
    os.makedirs(os.path.dirname(args.output), exist_ok=True)
    with open(args.output, "w") as f:
        f.write(out)
    print(f"wrote {args.output} ({len(out.splitlines())} lines)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
