#!/usr/bin/env python3
"""Assemble dist/install.yaml from config/ (the reference builds its 588-line
installer with `kustomize build config/default`, Makefile:117-121; this is
the same single-file-apply UX without the kustomize dependency).

Applies the reference's kustomize-equivalent transforms: `ollama-operator-`
name prefix on operator-owned objects, namespace rewrite system →
ollama-operator-system, RBAC subject/roleRef re-pointing, and optional image
pin via --image.
"""

from __future__ import annotations

import argparse
import os
import re
import sys

import yaml

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OVERLAY = os.path.join(ROOT, "config", "default")


def load_overlay():
    """namePrefix / namespace / resources / patches from
    config/default/kustomization.yaml — the single source of deploy
    config (same file `kustomize build` consumes), so installs are
    patched there, never in this script."""
    with open(os.path.join(OVERLAY, "kustomization.yaml")) as f:
        k = yaml.safe_load(f)
    resources = [os.path.normpath(os.path.join(OVERLAY, r))
                 for r in k.get("resources", [])]
    patches = [os.path.normpath(os.path.join(OVERLAY, p["path"]))
               for p in k.get("patches", []) if isinstance(p, dict)]
    return (k.get("namePrefix", ""), k.get("namespace", "default"),
            resources, patches)


PREFIX, NAMESPACE, SOURCES, PATCHES = load_overlay()

# kustomize's prefix transformer applies namePrefix to EVERY kind except
# CRDs (their name must stay the group-qualified plural) — mirror that
# exactly so `kustomize build config/default` and this script emit
# identically-named objects for any resource added to the overlay. The
# Namespace object is additionally pinned to `namespace:` below.
UNPREFIXED_KINDS = {"CustomResourceDefinition"}


def _merge_named_lists(base: list, patch: list) -> list:
    """Strategic-merge-lite for k8s object lists keyed by `name`."""
    out = {e.get("name"): e for e in base}
    for e in patch:
        name = e.get("name")
        if name in out:
            out[name] = _merge(out[name], e)
        else:
            out[name] = e
    return list(out.values())


def _merge(base, patch):
    """Strategic merge: dicts merge by key, lists of named objects merge
    by name (containers/env/volumes/volumeMounts), other lists replace."""
    if isinstance(base, dict) and isinstance(patch, dict):
        out = dict(base)
        for k, v in patch.items():
            out[k] = _merge(base[k], v) if k in base else v
        return out
    if (isinstance(base, list) and isinstance(patch, list)
            and all(isinstance(e, dict) and "name" in e
                    for e in base + patch)):
        return _merge_named_lists(base, patch)
    return patch


def apply_patches(doc: str) -> str:
    """Apply the overlay's strategic-merge patch files to matching
    (kind, name) documents BEFORE the prefix/namespace transforms (patch
    metadata uses base names, exactly as kustomize expects)."""
    obj = yaml.safe_load(doc)
    if not isinstance(obj, dict):
        return doc
    for path in PATCHES:
        with open(path) as f:
            patch = yaml.safe_load(f)
        if (patch.get("kind") == obj.get("kind")
                and patch.get("metadata", {}).get("name")
                == obj.get("metadata", {}).get("name")):
            obj = _merge(obj, patch)
            doc = yaml.safe_dump(obj, sort_keys=False)
    return doc


def split_docs(text: str):
    for doc in re.split(r"^---\s*$", text, flags=re.M):
        if doc.strip():
            yield doc


def get_field(doc: str, path: str):
    """Tiny YAML field reader for the few top-level fields we transform."""
    m = re.search(rf"^{path}:\s*(\S+)\s*$", doc, flags=re.M)
    return m.group(1) if m else None


def transform(doc: str, image: str | None) -> str:
    kind = get_field(doc, "kind")
    # namespace rewrite first (applies to metadata + rolebinding subjects)
    doc = doc.replace("namespace: system", f"namespace: {NAMESPACE}")
    if kind not in UNPREFIXED_KINDS:
        m = re.search(r"^metadata:\n((?:  .*\n)*)", doc, flags=re.M)
        if m:
            block = m.group(0)
            new_block = re.sub(
                rf"^(  name: )(?!{re.escape(PREFIX)})(\S+)",
                               rf"\g<1>{PREFIX}\g<2>", block, count=1,
                               flags=re.M)
            doc = doc.replace(block, new_block, 1)
    if kind == "Namespace":
        doc = re.sub(r"^(  name: )\S+$", rf"\g<1>{NAMESPACE}", doc,
                     count=1, flags=re.M)
    if image and kind == "Deployment":
        doc = re.sub(r"image: \S+", f"image: {image}", doc, count=1)
    return doc


def build(image: str | None = None) -> str:
    docs = []
    for src in SOURCES:
        with open(os.path.join(ROOT, src)) as f:
            for doc in split_docs(f.read()):
                docs.append(transform(apply_patches(doc.strip("\n")),
                                      image))
    # bindings are generated, not stored: they must reference the prefixed
    # names and final namespace
    docs.append(f"""apiVersion: rbac.authorization.k8s.io/v1
kind: ClusterRoleBinding
metadata:
  name: {PREFIX}manager-rolebinding
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: ClusterRole
  name: {PREFIX}manager-role
subjects:
  - kind: ServiceAccount
    name: {PREFIX}controller-manager
    namespace: {NAMESPACE}""")
    docs.append(f"""apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: {PREFIX}leader-election-rolebinding
  namespace: {NAMESPACE}
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: {PREFIX}leader-election-role
subjects:
  - kind: ServiceAccount
    name: {PREFIX}controller-manager
    namespace: {NAMESPACE}""")
    return "---\n".join(d.rstrip() + "\n" for d in docs)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image", default=None, help="pin the manager image")
    p.add_argument("-o", "--output",
                   default=os.path.join(ROOT, "dist", "install.yaml"))
    args = p.parse_args()
    out = build(args.image)
    os.makedirs(os.path.dirname(args.output), exist_ok=True)
    with open(args.output, "w") as f:
        f.write(out)
    print(f"wrote {args.output} ({len(out.splitlines())} lines)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
