"""One-shot 7B-class TPU capture (VERDICT r3 item #2: the metric model).

Runs the shipped bench.measure() against the real chip for the 7B-class
configs the round-3 window could not fit at int8: int4 weights (~3.9 GB)
plus the int8 paged pool fit where int8's 6.9 GB did not. Writes one JSON
record per completed capture to .bench_7b.jsonl so a mid-run tunnel drop
still keeps the finished ones.

Usage: python hack/capture_7b.py [out_path]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else ".bench_7b.jsonl"
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    platform = devs[0].platform
    bench.log(f"capture_7b: devices={[str(d) for d in devs]}")
    if platform == "cpu":
        bench.log("capture_7b: no TPU — refusing (this capture is the "
                  "hardware evidence, a CPU number is useless)")
        return 1

    plan = [
        # the 7B-class GQA flagship: dense per-chip number first
        dict(model="mistral", dtype="int4", slots=8, steps=64, seq=1024,
             prompt_len=128, paged=False, mixed=False),
        # the paged pool at serving concurrency (GQA → pages by default)
        dict(model="mistral", dtype="int4", slots=32, steps=64, seq=1024,
             prompt_len=128, paged=True, mixed=True),
        # the metric model by name (BASELINE.json: llama2-7b). MHA → dense.
        dict(model="llama2", dtype="int4", slots=8, steps=64, seq=1024,
             prompt_len=128, paged=False, mixed=False),
    ]
    cache: dict = {}
    common = dict(chunk=32, page_size=64, n_pages=None, platform=platform,
                  params_cache=cache)
    f = open(out_path, "a")
    ok = 0
    for cap in plan:
        t0 = time.monotonic()
        try:
            rec = bench.measure(jax, **cap, **common)
        except Exception as e:  # keep going: each capture stands alone
            bench.log(f"capture_7b: {cap['model']} paged={cap['paged']} "
                      f"FAILED after {time.monotonic()-t0:.0f}s: "
                      f"{type(e).__name__}: {e}")
            continue
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(rec), file=f, flush=True)
        ok += 1
    f.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
