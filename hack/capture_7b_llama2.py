"""llama2-7b int4 dense capture (the BASELINE.json metric model), plus a
mistral paged retry at a smaller bucket count. Appends to .bench_7b.jsonl.
Split from capture_7b.py: the first run's mistral paged warm hung the
tunnel compile; the metric model must not queue behind a hang."""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else ".bench_7b.jsonl"
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    platform = devs[0].platform
    bench.log(f"capture_7b_llama2: devices={[str(d) for d in devs]}")
    if platform == "cpu":
        return 1
    plan = [
        dict(model="llama2", dtype="int4", slots=8, steps=64, seq=1024,
             prompt_len=128, paged=False, mixed=False),
    ]
    if os.environ.get("CAP_MISTRAL_PAGED", "") == "1":
        plan.append(
            dict(model="mistral", dtype="int4", slots=32, steps=64,
                 seq=512, prompt_len=128, paged=True, mixed=True))
    cache: dict = {}
    common = dict(chunk=32, page_size=64, n_pages=None, platform=platform,
                  params_cache=cache)
    f = open(out_path, "a")
    ok = 0
    for cap in plan:
        t0 = time.monotonic()
        try:
            rec = bench.measure(jax, **cap, **common)
        except Exception as e:
            bench.log(f"capture_7b_llama2: {cap['model']} "
                      f"paged={cap['paged']} FAILED after "
                      f"{time.monotonic()-t0:.0f}s: {type(e).__name__}: {e}")
            continue
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(rec), file=f, flush=True)
        ok += 1
    f.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
