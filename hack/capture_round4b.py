"""Round-4 third capture window: the remaining VERDICT items on-chip.

1. phi int8 speculative-decode envelope (accept-all / reject-all vs
   decode_n) — the r3 #7 "give it a number".
2. phi int8 through /api/generate (HTTP surface, r3 weak #7) next to the
   known engine-level headline band.
3. phi int8 dense decode_chunk=64 — the dispatch-floor insight says the
   headline is program-dispatch-bound; a bigger chunk amortises further.
4. mistral int4 paged-32 retry at seq 512 (the seq-1024 warm hung the
   tunnel in window 1) — the 7B paged number.

Appends one JSON per capture to .bench_r4b.jsonl.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else ".bench_r4b.jsonl"
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    if platform == "cpu":
        return 1
    cache: dict = {}
    common = dict(page_size=64, n_pages=None, platform=platform,
                  params_cache=cache)
    plan = [
        ("spec", dict(model="phi", dtype="int8", slots=8, steps=64,
                      seq=1024, prompt_len=128, paged=False, mixed=False,
                      chunk=32)),
        ("http", dict(model="phi", dtype="int8", slots=8, steps=64,
                      seq=1024, prompt_len=128, paged=False, mixed=False,
                      chunk=32)),
        ("engine", dict(model="phi", dtype="int8", slots=8, steps=128,
                        seq=1024, prompt_len=128, paged=False, mixed=False,
                        chunk=64)),
        ("engine", dict(model="mistral", dtype="int4", slots=32, steps=64,
                        seq=512, prompt_len=128, paged=True, mixed=True,
                        chunk=32)),
    ]
    f = open(out_path, "a")
    ok = 0
    for kind, cap in plan:
        fn = {"spec": bench.measure_spec, "http": bench.measure_http,
              "engine": bench.measure}[kind]
        t0 = time.monotonic()
        try:
            rec = fn(jax, **cap, **common)
        except Exception as e:
            bench.log(f"r4b: {kind} {cap['model']} FAILED after "
                      f"{time.monotonic()-t0:.0f}s: {type(e).__name__}: {e}")
            continue
        rec["kind"] = kind
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(rec), file=f, flush=True)
        ok += 1
    f.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
