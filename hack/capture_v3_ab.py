"""v2-vs-v3 paged-kernel A/B on the real chip (VERDICT r3 #1).

Captures, in one process (params cached per model):
  1. tinyllama int8 paged B=32 mixed — v2 (round-comparable flagship)
  2. same — v3 (TPU_PAGED_V3=1)
  3. phi int8 paged B=32 mixed — v2 (MHA diagnostic, known ~190 ms/step)
  4. same — v3

Appends one JSON per capture to .bench_v3ab.jsonl.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else ".bench_v3ab.jsonl"
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    if platform == "cpu":
        bench.log("needs TPU")
        return 1
    plan = [
        dict(model="tinyllama", dtype="int8", slots=32, steps=64, seq=1024,
             prompt_len=128, paged=True, mixed=True),
        dict(model="tinyllama", dtype="int8", slots=32, steps=64, seq=1024,
             prompt_len=128, paged=True, mixed=True,
             env={"TPU_PAGED_V3": "1"}),
        dict(model="phi", dtype="int8", slots=32, steps=64, seq=1024,
             prompt_len=128, paged=True, mixed=True),
        dict(model="phi", dtype="int8", slots=32, steps=64, seq=1024,
             prompt_len=128, paged=True, mixed=True,
             env={"TPU_PAGED_V3": "1"}),
    ]
    cache: dict = {}
    common = dict(chunk=32, page_size=64, n_pages=None, platform=platform,
                  params_cache=cache)
    f = open(out_path, "a")
    ok = 0
    for cap in plan:
        cap_env = cap.pop("env", {}) or {}
        saved = {k: os.environ.get(k) for k in cap_env}
        os.environ.update(cap_env)
        t0 = time.monotonic()
        try:
            rec = bench.measure(jax, **cap, **common)
        except Exception as e:
            bench.log(f"v3ab: {cap['model']} {cap_env} FAILED after "
                      f"{time.monotonic()-t0:.0f}s: {type(e).__name__}: {e}")
            continue
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        rec["env"] = cap_env
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(rec), file=f, flush=True)
        ok += 1
    f.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
