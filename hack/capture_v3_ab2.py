"""Clean v2-vs-v3 paged A/B (second window): fixed v2 (padded-scale
BlockSpec), idle host, plus a LONG-context pair — the v3 kernel's dead-step
elimination only matters when the attention bucket is much larger than the
average live prefix, which the 128-token-prompt pair cannot show.

Appends to .bench_v3ab.jsonl (env field tells the kernels apart).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else ".bench_v3ab.jsonl"
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    platform = jax.devices()[0].platform
    if platform == "cpu":
        return 1
    base = dict(dtype="int8", slots=32, steps=64, seq=1024, paged=True,
                mixed=True)
    plan = [
        # short-context pair (v2 now runs the fixed padded-scale path)
        dict(model="tinyllama", prompt_len=128, **base),
        dict(model="tinyllama", prompt_len=128, env={"TPU_PAGED_V3": "1"},
             **base),
        # long-context pair: avg live ~600 tokens, bucket 1024
        dict(model="tinyllama", prompt_len=768, **base),
        dict(model="tinyllama", prompt_len=768, env={"TPU_PAGED_V3": "1"},
             **base),
        # MHA diagnostic pair
        dict(model="phi", prompt_len=128, **base),
        dict(model="phi", prompt_len=128, env={"TPU_PAGED_V3": "1"},
             **base),
    ]
    cache: dict = {}
    common = dict(chunk=32, page_size=64, n_pages=None, platform=platform,
                  params_cache=cache)
    f = open(out_path, "a")
    ok = 0
    for cap in plan:
        cap_env = cap.pop("env", {}) or {}
        saved = {k: os.environ.get(k) for k in cap_env}
        os.environ.update(cap_env)
        t0 = time.monotonic()
        try:
            rec = bench.measure(jax, **cap, **common)
        except Exception as e:
            bench.log(f"v3ab2: {cap['model']} {cap_env} FAILED after "
                      f"{time.monotonic()-t0:.0f}s: {type(e).__name__}: {e}")
            continue
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        rec["env"] = cap_env
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        print(json.dumps(rec), file=f, flush=True)
        ok += 1
    f.close()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
