#!/bin/sh
# Runtime-image entrypoint: dispatch the container arg vocabulary the
# operator's pod factories emit (serve / pull <image> / operator …) onto
# the Python modules — the same role the `ollama` binary's subcommands play
# in the reference's containers (/root/reference/pkg/model/pod.go:18,71).
set -e
cmd="$1"
[ $# -gt 0 ] && shift
case "$cmd" in
  serve|"")
    exec python -m ollama_operator_tpu.server "$@"
    ;;
  pull)
    exec python -m ollama_operator_tpu.server.pull "$@"
    ;;
  operator)
    exec python -m ollama_operator_tpu.operator "$@"
    ;;
  *)
    exec "$cmd" "$@"
    ;;
esac
