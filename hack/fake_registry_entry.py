#!/usr/bin/env python
"""In-cluster model-registry fixture for the kind e2e.

Runs inside the CPU runtime image (tests/ are shipped in the image for
exactly this): synthesises the deterministic tiny llama GGUF and serves it
over the docker-v2-ish registry protocol the puller speaks — the e2e's
stand-in for registry.ollama.ai, so the cluster needs no egress
(ref test/e2e pulls nothing either; it only asserts the manager runs —
our e2e goes further and serves a model through the full path).
"""
import os
import sys
import time

sys.path.insert(0, "/app")
sys.path.insert(0, "/app/tests")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from fake_registry import FakeRegistry, add_tiny_model  # noqa: E402


def main():
    port = int(os.environ.get("PORT", "5000"))
    reg = FakeRegistry()
    add_tiny_model(reg)
    reg.start(host="0.0.0.0", port=port)
    print(f"fake registry serving library/tiny:latest on :{port}",
          flush=True)
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
