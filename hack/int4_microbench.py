"""int4 matmul formulation shoot-out at decode shapes (VERDICT r3 #3).

The r3 capture showed fused-pallas int4 at 537 tok/s vs int8-XLA 569 —
0.94x while touching 0.62x the bytes; bandwidth-proportional would be
~1.6x. Candidate formulations, timed per matmul at decode shapes on the
real chip:

  int8-einsum   ops/quant.qmm decode form (the int8 winner: grouped
                partial, scales applied on the 64x smaller partial)
  int4-xla      ops/quant.qmm4 decode form (two half-group dots over the
                same packed bytes - int8-equivalent traffic)
  int4-pallas   ops/pallas/quant.qmm4_pallas (fused unpack+scale+dot;
                reads each byte once but pays per-tile VPU unpack)
  int4-native   XLA s4 dtype: codes stored as jnp.int4, grouped partial
                identical to int8-einsum - the convert rides the dot's
                operand stream, each byte read once, no manual unpack.

Also verifies whether the TPU backend actually PACKS s4 in HBM (two codes
per byte) via device memory_stats - if it doesn't, int4-native is
capacity-equivalent to int8 and loses its point.

Usage: python hack/int4_microbench.py   (needs the TPU chip)
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, *args, iters=50):
    import jax
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else \
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)
    if dev.platform == "cpu":
        print("needs TPU", file=sys.stderr)
        return 1

    from ollama_operator_tpu.ops.quant import GROUP, qmm, qmm4
    from ollama_operator_tpu.ops.pallas.quant import qmm4_pallas, qmm_pallas

    # --- is s4 packed in HBM? -------------------------------------------
    # memory_stats() is unavailable through this backend (returns None);
    # fall back to the array's own device-buffer accounting
    def dev_bytes(arr):
        try:
            stats = dev.memory_stats()
            return stats["bytes_in_use"] if stats else None
        except Exception:
            return None

    s4_ok = True
    try:
        probe = jax.device_put(np.zeros((256, 256), np.int8))
        probe4 = jax.jit(lambda c: c.astype(jnp.int4))(probe)
        probe4.block_until_ready()
        print(f"s4 arrays: ok (logical nbytes {probe4.nbytes}; the "
              f"timing below is the bandwidth evidence)", file=sys.stderr)
        del probe, probe4
    except Exception as e:
        s4_ok = False
        print(f"s4 arrays unavailable on this backend: "
              f"{type(e).__name__}: {str(e)[:120]}", file=sys.stderr)

    B = 8
    results = {"s4_ok": bool(s4_ok), "shapes": []}
    rng = np.random.default_rng(0)
    for K, O in ((4096, 4096), (4096, 14336), (14336, 4096)):
        g = GROUP
        G = K // g
        codes = rng.integers(-7, 8, size=(K, O)).astype(np.int8)
        scales = (np.abs(rng.normal(size=(G, O))) * 0.01 + 1e-3) \
            .astype(np.float32)
        x = jnp.asarray(rng.normal(size=(B, K)), jnp.bfloat16)

        q8 = jnp.asarray(codes)
        s = jnp.asarray(scales)
        from ollama_operator_tpu.ops.quant import pack_int4
        q4p = jnp.asarray(pack_int4(codes))
        q4n = (jax.jit(lambda c: c.astype(jnp.int4))(jnp.asarray(codes))
               if s4_ok else None)

        row = {"K": K, "O": O}
        bytes_int8 = K * O + G * O * 4
        bytes_int4 = K * O // 2 + G * O * 4

        f_int8 = jax.jit(lambda x, q, s: qmm(x, {"q": q, "s": s}))
        t = timeit(f_int8, x, q8, s)
        row["int8_einsum_us"] = round(t * 1e6, 1)
        row["int8_einsum_gbs"] = round(bytes_int8 / t / 1e9, 1)

        f_x4 = jax.jit(lambda x, q, s: qmm4(x, {"q4": q, "s": s}))
        t = timeit(f_x4, x, q4p, s)
        row["int4_xla_us"] = round(t * 1e6, 1)
        row["int4_xla_gbs"] = round(bytes_int4 / t / 1e9, 1)

        f_p4 = jax.jit(functools.partial(qmm4_pallas, interpret=False))
        t = timeit(f_p4, x, q4p, s)
        row["int4_pallas_us"] = round(t * 1e6, 1)
        row["int4_pallas_gbs"] = round(bytes_int4 / t / 1e9, 1)

        def qmm_native(x, q, s):
            # identical structure to qmm's decode form; the s4->bf16
            # convert fuses into the dot operand stream
            xr = x.reshape(*x.shape[:-1], G, g)
            qr = q.reshape(G, g, O)
            partial = jnp.einsum("...Gg,Ggo->...Go", xr,
                                 qr.astype(x.dtype),
                                 preferred_element_type=jnp.float32)
            return jnp.einsum("...Go,Go->...o", partial, s).astype(x.dtype)

        if q4n is not None:
            try:
                f_n4 = jax.jit(qmm_native)
                t = timeit(f_n4, x, q4n, s)
                row["int4_native_us"] = round(t * 1e6, 1)
                row["int4_native_gbs"] = round(bytes_int4 / t / 1e9, 1)
            except Exception as e:
                row["int4_native_error"] = f"{type(e).__name__}: {e}"[:200]

        # int8-pallas for reference
        f_p8 = jax.jit(functools.partial(qmm_pallas, interpret=False))
        t = timeit(f_p8, x, q8, s)
        row["int8_pallas_us"] = round(t * 1e6, 1)

        print(json.dumps(row), file=sys.stderr)
        results["shapes"].append(row)

    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    main()
