"""70B north-star proof: compile + HBM-fit at REAL dimensions, no weights.

BASELINE.md config 4 (the reference's biggest listed model, llama2:70b —
ref model table `/root/reference/README.md`, served there by delegating to
llama.cpp on GPU nodes) targets a llama2:70b tensor-sharded across a
v5e-16 slice. Multi-chip hardware isn't attachable in this environment, so
this worker proves the two things that ARE checkable without it:

1. **The program compiles**: the exact serving decode step the engine jits
   (dense int8 KV, GQA 8:1, 80 layers, dim 8192) AOT-lowers and XLA-compiles
   over a 16-device tp8×sp2 mesh AND a tp8×dp2 mesh with ABSTRACT weights —
   `jax.eval_shape` builds the int8-quantized param avals so no 70B of host
   RAM is touched, and `.lower(...).compile()` runs the full GSPMD
   partitioner + XLA pipeline.
2. **It fits**: per-device bytes (int8 params + scales + KV cache pool,
   computed exactly from each leaf's NamedSharding.shard_shape) stay under
   a v5e chip's 16 GB HBM with headroom for activations, for BOTH the dense
   16-slot cache and a 32-slot paged pool layout.

Run by tests/test_70b_program.py in a subprocess with
XLA_FLAGS=--xla_force_host_platform_device_count=16. Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=16")

import jax                                                     # noqa: E402
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp                                        # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P     # noqa: E402

V5E_HBM = 16.0e9          # bytes per chip
ACT_HEADROOM = 1.5e9      # activations/temp budget we insist stays free

N_SLOTS_DENSE = 16
N_SLOTS_PAGED = 32
PAGE = 64


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def collective_stats(hlo: str, layer_trips: int) -> dict:
    """Per-decode-step collective traffic, read from the PARTITIONED HLO.

    Collects every all-reduce / all-gather / reduce-scatter /
    collective-permute result shape in the compiled module. Instructions
    inside a while body (the lax.scan over layers) execute ``layer_trips``
    times per step; everything else once. Returns logical tensor bytes —
    the roofline applies the ring factor (2·(n−1)/n for all-reduce over n
    ways) when converting to per-chip link traffic (VERDICT r4 #6: the
    1000-tok/s projection previously priced no collectives at all)."""
    import re

    # computations are blocks "name (...) -> ... {"; while bodies are
    # referenced as body=<name>. Params may contain NESTED parens (wide
    # tuple params), so the header match keys on "-> ... {" at line end
    # rather than balancing the param list.
    comp_of_line = {}
    current = None
    lines = hlo.splitlines()
    hdr_re = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*->.*\{\s*$")
    for i, ln in enumerate(lines):
        m = hdr_re.match(ln)
        if m:
            current = m.group(1)
        comp_of_line[i] = current
    body_names = set(re.findall(r"body=%?([\w.\-]+)", hlo))
    # sync forms and async -start forms (the -done half aliases the same
    # bytes, so only -start is counted). Known limitation: collectives in
    # computations CALLED from the loop body (not textually inside it)
    # are priced once — test_collectives_priced's analytic floor catches
    # that regression loudly.
    coll_re = re.compile(
        r"=\s*(?:\()?([a-z0-9]+)\[([\d,]*)\][^=]*?"
        r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
        r"(?:-start)?\(")
    itemsize = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "s8": 1, "u8": 1, "pred": 1, "f64": 8, "s64": 8}
    ops = []
    total = 0
    for i, ln in enumerate(lines):
        m = coll_re.search(ln)
        if not m:
            continue
        dt, dims, kind = m.groups()
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        nbytes = n * itemsize.get(dt, 4)
        trips = layer_trips if comp_of_line[i] in body_names else 1
        ops.append({"kind": kind, "dtype": dt, "bytes": nbytes,
                    "in_layer_loop": trips > 1})
        total += nbytes * trips
    return {"ops": ops,
            "n_in_layer_loop": sum(1 for o in ops if o["in_layer_loop"]),
            "logical_bytes_per_step": int(total)}


def leaf_device_bytes(aval_tree, sharding_tree) -> int:
    """Exact per-device bytes: every leaf's shard_shape times itemsize."""
    total = 0
    for aval, sh in zip(jax.tree.leaves(aval_tree),
                        jax.tree.leaves(sharding_tree,
                                        is_leaf=lambda x: isinstance(
                                            x, NamedSharding))):
        shard = sh.shard_shape(aval.shape)
        n = 1
        for d in shard:
            n *= d
        total += n * jnp.dtype(aval.dtype).itemsize
    return total


def main() -> None:
    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.ops.quant import quantize_params
    from ollama_operator_tpu.parallel import long_context
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    from ollama_operator_tpu.parallel.sharding import (kv_cache_pspec,
                                                       params_sharding_tree)

    cfg = get_config("llama2:70b")
    assert (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads) == \
        (80, 8192, 64, 8), "must run at REAL 70B dimensions"
    devs = jax.devices()
    assert len(devs) >= 16, f"need 16 virtual devices, have {len(devs)}"

    # abstract int8 params: avals only — nothing materializes
    p_bf16 = jax.eval_shape(
        lambda k: decoder.init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.key(0))
    p_int8 = jax.eval_shape(quantize_params, p_bf16)
    global_param_gb = sum(
        int(a.size) * jnp.dtype(a.dtype).itemsize
        for a in jax.tree.leaves(p_int8)) / 1e9
    log(f"abstract int8 params: {global_param_gb:.1f} GB global")

    results = {"model": "llama2:70b", "n_devices": 16,
               "global_param_gb": round(global_param_gb, 2), "programs": []}

    for plan_name, plan in [("tp8xsp2", MeshPlan(tp=8, sp=2)),
                            ("tp8xdp2", MeshPlan(tp=8, dp=2))]:
        mesh = make_mesh(plan, devs[:16])
        sp = mesh.shape.get("sp", 1)
        dp = mesh.shape.get("dp", 1)
        p_sh = params_sharding_tree(p_int8, mesh, cfg)
        per_dev_params = leaf_device_bytes(p_int8, p_sh)

        # dense int8 KV cache at full context, engine layout
        B, S = N_SLOTS_DENSE, cfg.max_seq_len
        L, KvH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        cache_spec = kv_cache_pspec(cfg, mesh)
        cache_sh = NamedSharding(mesh, cache_spec)
        scale_sh = NamedSharding(mesh, P(*cache_spec[:-1]))
        cache_aval = {
            "q": jax.ShapeDtypeStruct((L, B, KvH, S, hd), jnp.int8,
                                      sharding=cache_sh),
            "s": jax.ShapeDtypeStruct((L, B, KvH, S), jnp.float32,
                                      sharding=scale_sh)}
        per_dev_kv = 2 * leaf_device_bytes(
            cache_aval, {"q": cache_sh, "s": scale_sh})

        slot_sh = NamedSharding(mesh, P("dp" if dp > 1 else None))
        tokens = jax.ShapeDtypeStruct((B, 1), jnp.int32, sharding=slot_sh)
        lengths = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=slot_sh)
        p_aval = jax.tree.map(
            lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=sh),
            p_int8, p_sh,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

        if sp > 1:
            def step(params, k_cache, v_cache, tokens, lengths):
                return long_context.forward_with_cache_sp(
                    params, cfg, tokens, k_cache, v_cache, lengths,
                    mesh=mesh)
        else:
            def step(params, k_cache, v_cache, tokens, lengths):
                return decoder.forward_with_cache(
                    params, cfg, tokens, k_cache, v_cache, lengths,
                    mesh=mesh)

        t0 = time.monotonic()
        exe = jax.jit(step, donate_argnums=(1, 2)).lower(
            p_aval, cache_aval, cache_aval, tokens, lengths).compile()
        compile_s = time.monotonic() - t0
        # the partitioned program must communicate over tp (Megatron
        # row-parallel wo/w_down end in a psum) — a collective-free HLO
        # would mean GSPMD silently replicated and the fit numbers lie
        hlo = exe.as_text()
        has_coll = ("all-reduce" in hlo or "collective-permute" in hlo
                    or "all-gather" in hlo or "reduce-scatter" in hlo)
        assert has_coll, f"{plan_name}: no collectives in partitioned HLO"
        log(f"{plan_name}: decode step compiled in {compile_s:.0f}s, "
            f"collectives present")
        try:
            ma = exe.memory_analysis()
            temp_gb = round(getattr(ma, "temp_size_in_bytes", 0) / 1e9, 3)
        except Exception:
            temp_gb = None

        total = per_dev_params + per_dev_kv
        fits = total <= V5E_HBM - ACT_HEADROOM
        coll = collective_stats(hlo, cfg.n_layers)
        log(f"{plan_name}: {len(coll['ops'])} collective sites, "
            f"{coll['n_in_layer_loop']} in the layer loop, "
            f"{coll['logical_bytes_per_step']/1e6:.1f} MB logical/step")
        results["programs"].append({
            "plan": plan_name, "compiled": True,
            "compile_s": round(compile_s, 1),
            "per_device_param_gb": round(per_dev_params / 1e9, 2),
            "per_device_kv_gb": round(per_dev_kv / 1e9, 2),
            "per_device_total_gb": round(total / 1e9, 2),
            "slots": B, "seq": S, "temp_gb": temp_gb,
            "collectives": coll,
            "fits_v5e": bool(fits)})
        assert fits, (f"{plan_name}: {total/1e9:.1f} GB/device exceeds "
                      f"v5e budget")

    # paged pool on tp8: 32 mixed-length slots sharing a page pool — the
    # high-concurrency serving layout. Compile the REAL-dimension paged
    # decode program (block tables + scatter + attend per layer) AND
    # assert the exact per-shard byte budget.
    mesh = make_mesh(MeshPlan(tp=8), devs[:8])
    p_sh = params_sharding_tree(p_int8, mesh, cfg)
    per_dev_params = leaf_device_bytes(p_int8, p_sh)
    L, KvH, hd, S = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, \
        cfg.max_seq_len
    B = N_SLOTS_PAGED
    n_pages = B * S // PAGE
    nblk = S // PAGE
    pool_spec = P(None, None, "tp", None, None)
    pool_sh = NamedSharding(mesh, pool_spec)
    ps_sh = NamedSharding(mesh, P(None, None, "tp", None))
    sp_pool = -(-PAGE // 128) * 128   # engine pads scale lanes to the tile
    pool_aval = {
        "q": jax.ShapeDtypeStruct((L, n_pages + 1, KvH, PAGE, hd),
                                  jnp.int8, sharding=pool_sh),
        "s": jax.ShapeDtypeStruct((L, n_pages + 1, KvH, sp_pool),
                                  jnp.float32, sharding=ps_sh)}
    pool = leaf_device_bytes(pool_aval, {"q": pool_sh, "s": ps_sh}) * 2
    repl = NamedSharding(mesh, P())
    tables = jax.ShapeDtypeStruct((B, nblk), jnp.int32, sharding=repl)
    lengths = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=repl)
    tokens = jax.ShapeDtypeStruct((B, 1), jnp.int32, sharding=repl)
    p_aval = jax.tree.map(
        lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
        p_int8, p_sh,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

    def paged_step(params, kp, vp, tokens, lengths, tables):
        return decoder.forward_with_cache_paged(
            params, cfg, tokens, kp, vp, tables, lengths, nblk, mesh=mesh)

    t0 = time.monotonic()
    exe = jax.jit(paged_step, donate_argnums=(1, 2)).lower(
        p_aval, pool_aval, pool_aval, tokens, lengths, tables).compile()
    compile_s = time.monotonic() - t0
    hlo = exe.as_text()
    assert ("all-reduce" in hlo or "all-gather" in hlo
            or "reduce-scatter" in hlo), "paged program: no tp collectives"
    log(f"tp8 paged decode step compiled in {compile_s:.0f}s")
    total = per_dev_params + pool
    fits = total <= V5E_HBM - ACT_HEADROOM
    results["paged_pool"] = {
        "plan": "tp8", "slots": B, "n_pages": n_pages, "compiled": True,
        "compile_s": round(compile_s, 1),
        "per_device_param_gb": round(per_dev_params / 1e9, 2),
        "per_device_pool_gb": round(pool / 1e9, 2),
        "per_device_total_gb": round(total / 1e9, 2),
        "fits_v5e": bool(fits)}
    assert fits, "paged pool layout exceeds v5e budget"

    # int4: the capacity story — 70B on a QUARTER of the north-star slice
    # (v5e-4). Packed nibbles + f32 group scales ≈ 0.63 B/weight, so tp4
    # leaves ~10.8 GB/device of weights; a dense int8-KV cache at reduced
    # slots still fits under the activation headroom.
    p_int4 = jax.eval_shape(lambda p: quantize_params(p, bits=4), p_bf16)
    int4_gb = sum(int(a.size) * jnp.dtype(a.dtype).itemsize
                  for a in jax.tree.leaves(p_int4)) / 1e9
    log(f"abstract int4 params: {int4_gb:.1f} GB global")
    mesh4 = make_mesh(MeshPlan(tp=4), devs[:4])
    p_sh4 = params_sharding_tree(p_int4, mesh4, cfg)
    per_dev_params = leaf_device_bytes(p_int4, p_sh4)
    B4, S4 = 8, 2048
    cache_spec = kv_cache_pspec(cfg, mesh4)
    cache_sh = NamedSharding(mesh4, cache_spec)
    scale_sh = NamedSharding(mesh4, P(*cache_spec[:-1]))
    cache_aval = {
        "q": jax.ShapeDtypeStruct((L, B4, KvH, S4, hd), jnp.int8,
                                  sharding=cache_sh),
        "s": jax.ShapeDtypeStruct((L, B4, KvH, S4), jnp.float32,
                                  sharding=scale_sh)}
    per_dev_kv = 2 * leaf_device_bytes(
        cache_aval, {"q": cache_sh, "s": scale_sh})
    repl4 = NamedSharding(mesh4, P())
    tokens = jax.ShapeDtypeStruct((B4, 1), jnp.int32, sharding=repl4)
    lengths = jax.ShapeDtypeStruct((B4,), jnp.int32, sharding=repl4)
    p_aval = jax.tree.map(
        lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
        p_int4, p_sh4,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

    def step4(params, k_cache, v_cache, tokens, lengths):
        return decoder.forward_with_cache(
            params, cfg, tokens, k_cache, v_cache, lengths, mesh=mesh4)

    t0 = time.monotonic()
    exe = jax.jit(step4, donate_argnums=(1, 2)).lower(
        p_aval, cache_aval, cache_aval, tokens, lengths).compile()
    compile_s = time.monotonic() - t0
    hlo = exe.as_text()
    assert ("all-reduce" in hlo or "all-gather" in hlo
            or "reduce-scatter" in hlo), "int4 tp4: no collectives"
    log(f"int4 tp4 decode step compiled in {compile_s:.0f}s")
    total = per_dev_params + per_dev_kv
    fits = total <= V5E_HBM - ACT_HEADROOM
    results["int4_quarter_slice"] = {
        "plan": "tp4", "compiled": True,
        "compile_s": round(compile_s, 1),
        "global_param_gb": round(int4_gb, 2),
        "per_device_param_gb": round(per_dev_params / 1e9, 2),
        "per_device_kv_gb": round(per_dev_kv / 1e9, 2),
        "per_device_total_gb": round(total / 1e9, 2),
        "slots": B4, "seq": S4, "fits_v5e": bool(fits)}
    assert fits, "int4 tp4 layout exceeds v5e budget"

    print(json.dumps(results))


if __name__ == "__main__":
    main()
