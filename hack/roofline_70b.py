"""70B roofline projection: bridge the measured per-chip HBM utilization to
the north-star config (llama2:70b on v5e-16, >1000 tok/s aggregate —
BASELINE.json).

Decode is HBM-bandwidth-bound: per decode step every resident weight byte
streams once per chip, plus the slots' live KV windows. Given the EXACT
per-device bytes of the sharded 70B program (eval_shape + NamedSharding —
same accounting as hack/prog_70b.py, no arrays materialise) and a
bandwidth-utilization fraction, the projected aggregate throughput is

    tok/s = n_slots / (per_device_bytes / (819 GB/s x util))

This makes the north star falsifiable: the table prints the utilization
each config needs to cross 1000 tok/s, next to the utilizations actually
measured on the v5e-1 (BENCH_r*.json: 26-30% dense, 14% paged v2). Run on
a virtual 16-device CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=16 JAX_PLATFORMS=cpu \
        python hack/roofline_70b.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=16")

import jax                                    # noqa: E402

# sitecustomize force-sets jax_platforms programmatically; the env var
# alone is not enough (same guard as conftest.py / __graft_entry__.py)
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp                       # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

V5E_BW = 819e9      # bytes/s per chip (public spec)


def leaf_device_bytes(aval_tree, sharding_tree) -> int:
    total = 0
    for a, sh in zip(jax.tree.leaves(aval_tree),
                     jax.tree.leaves(sharding_tree,
                                     is_leaf=lambda x: isinstance(
                                         x, NamedSharding))):
        shard = sh.shard_shape(a.shape)
        n = 1
        for d in shard:
            n *= d
        total += n * jnp.dtype(a.dtype).itemsize
    return total


def main() -> None:
    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.ops.quant import (quantize_params)
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    from ollama_operator_tpu.parallel.sharding import params_sharding_tree

    cfg = get_config("llama2:70b")
    devs = jax.devices()
    assert len(devs) >= 16, f"need 16 virtual devices, have {len(devs)}"
    mesh = make_mesh(MeshPlan(tp=8, dp=2), devs[:16])

    p_bf16 = jax.eval_shape(
        lambda k: decoder.init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.key(0))

    def quant_avals(bits):
        from ollama_operator_tpu.ops import quant as Q
        return jax.eval_shape(lambda p: Q.quantize_params(p, bits=bits),
                              p_bf16)

    rows = []
    for dtype, bits in (("int8", 8), ("int4", 4)):
        p_q = quant_avals(bits)
        p_sh = params_sharding_tree(p_q, mesh, cfg)
        per_dev_w = leaf_device_bytes(p_q, p_sh)
        # live KV read per step per chip: each slot's window, int8 codes,
        # KvH sharded over tp8 (8 kv heads / 8 ways -> 1 head per chip),
        # batch over dp2
        L, KvH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        for slots, ctx in ((8, 1024), (32, 1024), (32, 4096)):
            kv_per_dev = (slots // 2) * ctx * L * (KvH // 8) * hd * 2  # int8
            per_dev = per_dev_w + kv_per_dev
            row = {"dtype": dtype, "slots": slots, "ctx": ctx,
                   "per_device_gb": round(per_dev / 1e9, 2)}
            for util in (0.14, 0.30, 0.45, 0.60):
                step_s = per_dev / (V5E_BW * util)
                row[f"tok_s@{int(util*100)}%"] = round(slots / step_s, 1)
            # util needed for 1000 tok/s aggregate
            need = (per_dev / V5E_BW) / (slots / 1000.0)
            row["util_for_1000"] = round(need * 100, 1)
            rows.append(row)

    print(json.dumps({"mesh": "tp8xdp2 (v5e-16)", "rows": rows}, indent=1))

    # markdown table for BASELINE.md
    print("\n| dtype | slots | ctx | GB/chip/step | tok/s @14% | @30% | "
          "@45% | @60% | util for 1000 tok/s |", file=sys.stderr)
    print("|---|---|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(f"| {r['dtype']} | {r['slots']} | {r['ctx']} | "
              f"{r['per_device_gb']} | {r['tok_s@14%']} | {r['tok_s@30%']} "
              f"| {r['tok_s@45%']} | {r['tok_s@60%']} | "
              f"{r['util_for_1000']}% |", file=sys.stderr)


if __name__ == "__main__":
    main()
