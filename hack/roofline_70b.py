"""70B roofline projection: bridge the measured per-chip HBM utilization to
the north-star config (llama2:70b on v5e-16, >1000 tok/s aggregate —
BASELINE.json).

Decode is HBM-bandwidth-bound: per decode step every resident weight byte
streams once per chip, plus the slots' live KV windows. Given the EXACT
per-device bytes of the sharded 70B program (eval_shape + NamedSharding —
same accounting as hack/prog_70b.py, no arrays materialise) and a
bandwidth-utilization fraction, the projected aggregate throughput is

    tok/s = n_slots / (per_device_bytes / (819 GB/s x util))

This makes the north star falsifiable: the table prints the utilization
each config needs to cross 1000 tok/s, next to the utilizations actually
measured on the v5e-1 (BENCH_r*.json: 26-30% dense, 14% paged v2). Run on
a virtual 16-device CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=16 JAX_PLATFORMS=cpu \
        python hack/roofline_70b.py
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=16")

import jax                                    # noqa: E402

# sitecustomize force-sets jax_platforms programmatically; the env var
# alone is not enough (same guard as conftest.py / __graft_entry__.py)
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp                       # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

V5E_BW = 819e9      # bytes/s per chip (public spec)

# ICI: v5e lists 1600 Gbps (~200 GB/s) of interchip bandwidth per chip
# across 4 links of a 2D torus. A tp8 ring all-reduce rides ONE torus
# axis — both directions of 2 links — so the effective per-chip rate for
# the tp collective is ~half the aggregate. 90 GB/s is the center
# estimate; the table prints a 45/90/180 sensitivity span because the
# real number depends on link mapping the partitioner picks.
ICI_EFF_BW = (45e9, 90e9, 180e9)


def collective_bytes_per_chip(cfg, tp: int, dp: int, slots: int) -> int:
    """Per-chip ring traffic per decode step, analytic (VERDICT r4 #6).

    Megatron row-parallel layers end in a psum: 2 all-reduces per layer
    (attention o-proj, MLP down-proj) of the [B_local, 1, dim]
    activations, B_local = slots/dp — in **f32** (the compiled HLO
    reduces pre-residual activations at f32, not bf16: 2 ×
    ``all-reduce(f32[8192,B_local,1])`` per layer trip). Ring all-reduce
    over tp ways moves 2·(tp−1)/tp × logical bytes through each chip.
    The vocab-sharded lm_head needs NO logits gather (sampling runs on
    the sharded logits; the HLO shows only one final f32[B,1,dim] AR,
    <1% of the per-layer term). Cross-checked against the partitioned
    HLO of the compiled 70B program (hack/prog_70b.py collective_stats →
    tests/test_70b_program.py::test_collectives_priced: HLO logical
    bytes 47.2 MB/step vs this model's 42 MB + index gathers)."""
    b_local = max(1, slots // max(dp, 1))
    act = b_local * cfg.dim * 4                     # f32 activations
    per_layer = 2 * act * 2 * (tp - 1) / tp         # 2 ARs, ring factor
    final = b_local * cfg.dim * 4 * 2 * (tp - 1) / tp
    return int(cfg.n_layers * per_layer + final)


def leaf_device_bytes(aval_tree, sharding_tree) -> int:
    total = 0
    for a, sh in zip(jax.tree.leaves(aval_tree),
                     jax.tree.leaves(sharding_tree,
                                     is_leaf=lambda x: isinstance(
                                         x, NamedSharding))):
        shard = sh.shard_shape(a.shape)
        n = 1
        for d in shard:
            n *= d
        total += n * jnp.dtype(a.dtype).itemsize
    return total


def main() -> None:
    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.models.config import get_config
    from ollama_operator_tpu.ops.quant import (quantize_params)
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    from ollama_operator_tpu.parallel.sharding import params_sharding_tree

    cfg = get_config("llama2:70b")
    devs = jax.devices()
    assert len(devs) >= 16, f"need 16 virtual devices, have {len(devs)}"
    mesh = make_mesh(MeshPlan(tp=8, dp=2), devs[:16])

    p_bf16 = jax.eval_shape(
        lambda k: decoder.init_params(cfg, k, dtype=jnp.bfloat16),
        jax.random.key(0))

    def quant_avals(bits):
        from ollama_operator_tpu.ops import quant as Q
        return jax.eval_shape(lambda p: Q.quantize_params(p, bits=bits),
                              p_bf16)

    rows = []
    for dtype, bits in (("int8", 8), ("int4", 4)):
        p_q = quant_avals(bits)
        p_sh = params_sharding_tree(p_q, mesh, cfg)
        per_dev_w = leaf_device_bytes(p_q, p_sh)
        # live KV read per step per chip: each slot's window, int8 codes,
        # KvH sharded over tp8 (8 kv heads / 8 ways -> 1 head per chip),
        # batch over dp2
        L, KvH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        for slots, ctx in ((8, 1024), (32, 1024), (32, 4096)):
            kv_per_dev = (slots // 2) * ctx * L * (KvH // 8) * hd * 2  # int8
            per_dev = per_dev_w + kv_per_dev
            coll = collective_bytes_per_chip(cfg, tp=8, dp=2, slots=slots)
            coll_s_mid = coll / ICI_EFF_BW[1]
            row = {"dtype": dtype, "slots": slots, "ctx": ctx,
                   "per_device_gb": round(per_dev / 1e9, 2),
                   "coll_mb_per_chip_step": round(coll / 1e6, 2),
                   "coll_ms@90GBs": round(coll_s_mid * 1e3, 3)}
            for util in (0.14, 0.30, 0.45, 0.60):
                step_s = per_dev / (V5E_BW * util)
                row[f"tok_s@{int(util*100)}%"] = round(slots / step_s, 1)
                # additive collective term (psum sits on the critical
                # path each layer; no overlap assumed — conservative)
                row[f"tok_s@{int(util*100)}%+coll"] = round(
                    slots / (step_s + coll_s_mid), 1)
            # util needed for 1000 tok/s aggregate, WITH the collective
            # term priced at the 45/90/180 GB/s ICI sensitivity span
            need = (per_dev / V5E_BW) / (slots / 1000.0)
            row["util_for_1000"] = round(need * 100, 1)
            for bw in ICI_EFF_BW:
                budget = slots / 1000.0 - coll / bw
                row[f"util_for_1000+coll@{int(bw/1e9)}GBs"] = (
                    round((per_dev / V5E_BW) / budget * 100, 1)
                    if budget > 0 else None)   # ICI alone blows the budget
            rows.append(row)

    print(json.dumps({"mesh": "tp8xdp2 (v5e-16)", "rows": rows}, indent=1))

    # markdown table for BASELINE.md
    print("\n| dtype | slots | ctx | GB/chip/step | coll MB/chip | "
          "tok/s @30% | @30%+coll | @45% | @45%+coll | util for 1000 | "
          "+coll@45/90/180 GB/s |", file=sys.stderr)
    print("|---|---|---|---|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        sens = "/".join(
            str(r[f"util_for_1000+coll@{int(bw/1e9)}GBs"])
            for bw in ICI_EFF_BW)
        print(f"| {r['dtype']} | {r['slots']} | {r['ctx']} | "
              f"{r['per_device_gb']} | {r['coll_mb_per_chip_step']} | "
              f"{r['tok_s@30%']} | {r['tok_s@30%+coll']} | "
              f"{r['tok_s@45%']} | {r['tok_s@45%+coll']} | "
              f"{r['util_for_1000']}% | {sens}% |", file=sys.stderr)


if __name__ == "__main__":
    main()
