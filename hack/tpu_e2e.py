#!/usr/bin/env python
"""End-to-end serving verification on the real TPU behind the axon tunnel.

Run as:  env -u PALLAS_AXON_POOL_IPS python hack/tpu_e2e.py

(The launcher must NOT hold the single tunnel session — every python
interpreter start under PYTHONPATH=/root/.axon_site consumes it — so the
orchestrator strips the axon env and hands it back to the server child.)

Drives: fake registry -> /api/pull -> GGUF transcode -> int8 weights +
int8 KV cache engine on the TPU -> /api/generate (greedy tokens must match
the CPU run) -> /api/show capabilities -> /v1/embeddings.
"""
import os, sys, json, time, urllib.request, subprocess, signal, socket
sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/root/repo/tests")
# the parent must NOT hold the single-session TPU tunnel: pin it to CPU
# BEFORE any repo import can transitively pull in jax
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
from fake_registry import FakeRegistry

# build the tiny gguf in a CPU subprocess so the parent never opens the tunnel
tmp = "/tmp/verify_tpu_e2e"; os.makedirs(tmp, exist_ok=True)
subprocess.run([sys.executable, "-c", f"""
import os, sys
os.environ['JAX_PLATFORMS'] = 'cpu'
sys.path.insert(0, '/root/repo'); sys.path.insert(0, '/root/repo/tests')
import jax; jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
from ollama_operator_tpu.models import config as cfglib, decoder
from test_transcode import write_tiny_llama_gguf
cfg = cfglib.PRESETS['tiny']
params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
write_tiny_llama_gguf('{tmp}/tiny.gguf', cfg, params)
"""], check=True)

reg = FakeRegistry(); url = reg.start()
reg.add_model("library", "tiny", "latest", open(tmp + "/tiny.gguf", "rb").read(),
              template="{{ .Prompt }}", params={"temperature": 0.0})
_s = socket.socket(); _s.bind(("127.0.0.1", 0)); PORT = _s.getsockname()[1]; _s.close()
srv = subprocess.Popen(
    [sys.executable, "-m", "ollama_operator_tpu.server", "--host", "127.0.0.1",
     "--port", str(PORT), "--store", tmp + "/store",
     "--dtype", "int8", "--kv-dtype", "int8", "--max-slots", "4",
     "--max-seq-len", "256"],
    env=dict(os.environ, PYTHONPATH="/root/repo:/root/.axon_site",
             PALLAS_AXON_POOL_IPS="127.0.0.1",
             PALLAS_AXON_REMOTE_COMPILE="1",
             JAX_PLATFORMS="axon"), cwd="/root/repo",
    stdout=open(tmp + "/srv.out", "w"), stderr=open(tmp + "/srv.log", "w"))
base = f"http://127.0.0.1:{PORT}"
for _ in range(120):
    try:
        urllib.request.urlopen(base + "/api/version", timeout=2); break
    except Exception: time.sleep(1)
else: srv.kill(); sys.exit("server never came up")

def post(path, payload, timeout=560):
    req = urllib.request.Request(base + path, data=json.dumps(payload).encode(),
                                 headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)

ref = url + "/library/tiny:latest"
print("pull:", post("/api/pull", {"model": ref, "stream": False}).read())
out = json.loads(post("/api/generate", {
    "model": ref, "prompt": "x", "stream": False,
    "options": {"temperature": 0, "num_predict": 8}}).read())
print("generate:", {k: out.get(k) for k in ("response", "done", "eval_count")})
show = json.loads(post("/api/show", {"model": ref}).read())
print("capabilities:", show.get("capabilities"))
emb = json.loads(post("/v1/embeddings", {"model": ref, "input": "t1"}).read())
print("v1/embeddings dims:", len(emb["data"][0]["embedding"]))
srv.send_signal(signal.SIGTERM); srv.wait(20); reg.stop()
print("TPU-E2E-OK")
