#!/usr/bin/env python
"""One-shot real-TPU lowering check for the pallas kernels.

Interpret mode skips Mosaic's TPU lowering entirely — round 3 learned
that the hard way twice (the paged kernel's 4D scale BlockSpec and the
hd=80 pool-copy OOM both only surfaced on the real chip). This script
AOT-compiles the serving kernels on the axon chip at shape-representative
(but small) configs in ~2 minutes, WITHOUT running a full bench capture:

    python hack/tpu_kernel_check.py

Run it between probe attempts (the axon tunnel is single-client: never
run it while bench.py holds the chip).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def check(name, fn, *args):
    try:
        jax.jit(fn).lower(*args).compile()
        print(f"OK   {name}")
        return True
    except Exception as e:
        print(f"FAIL {name}: {type(e).__name__}: {str(e)[:400]}")
        return False


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev})")
    if dev.platform != "tpu":
        print("not a TPU — nothing to validate", file=sys.stderr)
        return 1
    ok = True
    rng = np.random.default_rng(0)

    # fused dequant matmuls, phi-shaped (K=2560) and llama-shaped (K=4096)
    from ollama_operator_tpu.ops.pallas.quant import qmm4_pallas, qmm_pallas
    for K, O in ((2560, 2560), (4096, 4096)):
        x = jnp.asarray(rng.standard_normal((8, K)), jnp.bfloat16)
        q8 = jnp.asarray(rng.integers(-127, 128, (K, O)), jnp.int8)
        q4 = jnp.asarray(rng.integers(0, 256, (K // 2, O)), jnp.uint8)
        s = jnp.asarray(rng.random((K // 32, O)), jnp.float32)
        ok &= check(f"qmm_pallas K={K}", qmm_pallas, x, q8, s)
        ok &= check(f"qmm4_pallas K={K}", qmm4_pallas, x, q4, s)

    # paged decode kernel: quantized pool, phi-like MHA (KvH=32, hd 80→128
    # padded) and tinyllama-like GQA (KvH=4, hd=64→128); L small — compile
    # time scales with the program, not the pool
    from ollama_operator_tpu.ops.pallas.paged import paged_decode_attention
    for KvH, H in ((32, 32), (4, 32)):
        L, P, ps, hd, B, NBLK = 2, 33, 64, 128, 8, 16
        kq = jnp.zeros((L, P, KvH, ps, hd), jnp.int8)
        ksc = jnp.zeros((L, P, KvH, ps), jnp.float32)
        q = jnp.zeros((B, 1, H, hd), jnp.bfloat16)
        tables = jnp.zeros((B, NBLK), jnp.int32)
        lengths = jnp.zeros((B,), jnp.int32)

        def paged(q, kq, ksc, tables, lengths, KvH=KvH):
            kp = {"q": kq, "s": ksc}
            return paged_decode_attention(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125, nblk=8)

        ok &= check(f"paged_decode KvH={KvH}", paged, q, kq, ksc,
                    tables, lengths)

        from ollama_operator_tpu.ops.pallas.paged import \
            paged_decode_attention_v3

        # v3 requires the 128-lane-padded scale pools the engine allocates
        ksc128 = jnp.zeros((L, P, KvH, 128), jnp.float32)

        def paged_v3(q, kq, ksc, tables, lengths, KvH=KvH):
            kp = {"q": kq, "s": ksc}
            out = paged_decode_attention_v3(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125, nblk=8)
            assert out is not None, "v3 unexpectedly bailed"
            return out

        ok &= check(f"paged_decode_v3 KvH={KvH}", paged_v3, q, kq, ksc128,
                    tables, lengths)

        def paged_v3_win(q, kq, ksc, tables, lengths, KvH=KvH):
            kp = {"q": kq, "s": ksc}
            out = paged_decode_attention_v3(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125,
                sliding_window=4096, nblk=8)
            assert out is not None, "v3 unexpectedly bailed"
            return out

        ok &= check(f"paged_decode_v3 win KvH={KvH}", paged_v3_win, q, kq,
                    ksc128, tables, lengths)

        def paged_v3_bf16(q, kp, tables, lengths):
            out = paged_decode_attention_v3(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125, nblk=8)
            assert out is not None, "v3 unexpectedly bailed"
            return out

        kbf = jnp.zeros((L, P, KvH, ps, hd), jnp.bfloat16)
        ok &= check(f"paged_decode_v3 bf16 KvH={KvH}", paged_v3_bf16, q,
                    kbf, tables, lengths)

        # v4 compacted flat-grid (round 5): int8 pool + sliding window
        from ollama_operator_tpu.ops.pallas.paged import \
            paged_decode_attention_v4

        def paged_v4(q, kq, ksc, tables, lengths, KvH=KvH):
            kp = {"q": kq, "s": ksc}
            out = paged_decode_attention_v4(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125, nblk=8)
            assert out is not None, "v4 unexpectedly bailed"
            return out

        ok &= check(f"paged_decode_v4 KvH={KvH}", paged_v4, q, kq, ksc128,
                    tables, lengths)

        def paged_v4_win(q, kq, ksc, tables, lengths, KvH=KvH):
            kp = {"q": kq, "s": ksc}
            out = paged_decode_attention_v4(
                q, kp, kp, jnp.int32(0), tables, lengths, 0.125,
                sliding_window=4096, nblk=8)
            assert out is not None, "v4 unexpectedly bailed"
            return out

        ok &= check(f"paged_decode_v4 win KvH={KvH}", paged_v4_win, q, kq,
                    ksc128, tables, lengths)

    # dense decode + MHA head-tiled grids (bf16 cache)
    from ollama_operator_tpu.ops.pallas.flash import (decode_attention,
                                                      mha_decode_attention)
    kc = jnp.zeros((8, 4, 1024, 128), jnp.bfloat16)
    q = jnp.zeros((8, 1, 32, 128), jnp.bfloat16)
    qpos = jnp.zeros((8,), jnp.int32)
    # scale must stay a static python float (as production partials it
    # into the kernel) — passing it through jit would trace it and the
    # kernel closure would capture a tracer
    ok &= check("decode_attention GQA",
                lambda q, k, v, p: decode_attention(q, k, v, p, 0.125),
                q, kc, kc, qpos)
    kcm = jnp.zeros((8, 32, 1024, 80), jnp.bfloat16)
    qm = jnp.zeros((8, 1, 32, 80), jnp.bfloat16)
    ok &= check("mha_decode hd=80",
                lambda q, k, v, p: mha_decode_attention(q, k, v, p, 0.125),
                qm, kcm, kcm, qpos)
    print("ALL OK" if ok else "FAILURES", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
