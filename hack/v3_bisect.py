"""Bisect which construct in the v3 paged kernel crashes the real-TPU
Mosaic lowering (hack/tpu_kernel_check.py: INTERNAL compile-helper crash;
interpret mode passes). Each probe isolates one suspect:

  p1  batched dot_general (batch dim = KvH) on VMEM values
  p2  dynamic leading-index read of a VMEM scratch buffer (buf[slot])
  p3  make_async_copy HBM.at[lay, pg] -> VMEM scratch, traced indices
  p4  fori_loop with traced (SMEM-scalar) bounds containing pl.when+DMA
  p5  3-D broadcasted_iota + 3-D flash-style elementwise chain
"""
from __future__ import annotations

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def check(name, fn, *args):
    try:
        jax.jit(fn).lower(*args).compile()
        print(f"OK   {name}", flush=True)
        return True
    except Exception as e:
        msg = str(e).split("\n")[0][:160]
        print(f"FAIL {name}: {type(e).__name__}: {msg}", flush=True)
        return False


def main():
    KvH, Gp, ps, hd = 4, 8, 64, 128

    # p1: batched dot_general
    def k1(q_ref, k_ref, o_ref):
        s = jax.lax.dot_general(
            q_ref[...], k_ref[...], (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        o_ref[...] = s

    def p1(q, k):
        return pl.pallas_call(
            k1,
            out_shape=jax.ShapeDtypeStruct((KvH, Gp, ps), jnp.float32),
        )(q, k)

    q = jnp.zeros((KvH, Gp, hd), jnp.bfloat16)
    kk = jnp.zeros((KvH, ps, hd), jnp.bfloat16)
    check("p1 batched dot_general", p1, q, kk)

    # p2: dynamic leading-index scratch read
    def k2(i_ref, x_ref, o_ref, buf):
        buf[...] = jnp.stack([x_ref[...], x_ref[...] * 2])
        o_ref[...] = buf[i_ref[0] % 2]

    def p2(i, x):
        return pl.pallas_call(
            k2,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=(1,),
                in_specs=[pl.BlockSpec((ps, hd), lambda g, i: (0, 0))],
                out_specs=pl.BlockSpec((ps, hd), lambda g, i: (0, 0)),
                scratch_shapes=[pltpu.VMEM((2, ps, hd), jnp.float32)]),
            out_shape=jax.ShapeDtypeStruct((ps, hd), jnp.float32),
        )(i, x)

    check("p2 dynamic scratch read", p2, jnp.zeros((1,), jnp.int32),
          jnp.zeros((ps, hd), jnp.float32))

    # p3: manual DMA from HBM with traced indices
    def k3(lay_ref, tbl_ref, hbm_ref, o_ref, buf, sem):
        pg = tbl_ref[0]
        cp = pltpu.make_async_copy(hbm_ref.at[lay_ref[0], pg],
                                   buf.at[0], sem.at[0])
        cp.start()
        cp.wait()
        o_ref[...] = buf[0].astype(jnp.float32)

    def p3(lay, tbl, pool):
        return pl.pallas_call(
            k3,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2, grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM)],
                out_specs=pl.BlockSpec((KvH, ps, hd),
                                       lambda g, *p: (0, 0, 0)),
                scratch_shapes=[pltpu.VMEM((2, KvH, ps, hd), jnp.int8),
                                pltpu.SemaphoreType.DMA((2,))]),
            out_shape=jax.ShapeDtypeStruct((KvH, ps, hd), jnp.float32),
        )(lay, tbl, pool)

    pool = jnp.zeros((2, 5, KvH, ps, hd), jnp.int8)
    check("p3 manual HBM DMA", p3, jnp.zeros((1,), jnp.int32),
          jnp.zeros((4,), jnp.int32), pool)

    # p4: dynamic fori_loop with pl.when + DMA inside
    def k4(len_ref, tbl_ref, hbm_ref, o_ref, buf, sem):
        n = len_ref[0] // ps + 1

        def dma(i, slot):
            return pltpu.make_async_copy(hbm_ref.at[0, tbl_ref[i]],
                                         buf.at[slot], sem.at[slot])
        dma(0, 0).start()
        acc0 = jnp.zeros((ps, hd), jnp.float32)

        def body(i, acc):
            slot = i % 2

            @pl.when(i + 1 < n)
            def _():
                dma(i + 1, (i + 1) % 2).start()
            dma(i, slot).wait()
            return acc + buf[slot][0].astype(jnp.float32)

        acc = jax.lax.fori_loop(0, n, body, acc0)
        o_ref[...] = acc

    def p4(ln, tbl, pool):
        return pl.pallas_call(
            k4,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2, grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM)],
                out_specs=pl.BlockSpec((ps, hd), lambda g, *p: (0, 0)),
                scratch_shapes=[pltpu.VMEM((2, KvH, ps, hd), jnp.int8),
                                pltpu.SemaphoreType.DMA((2,))]),
            out_shape=jax.ShapeDtypeStruct((ps, hd), jnp.float32),
        )(ln, tbl, pool)

    check("p4 dynamic loop + DMA", p4, jnp.asarray([130], jnp.int32),
          jnp.zeros((4,), jnp.int32), pool)

    # p5: 3-D iota + flash chain
    def k5(s_ref, o_ref, m_ref, l_ref):
        s = s_ref[...]
        pos = jax.lax.broadcasted_iota(jnp.int32, (KvH, Gp, ps), 2)
        s = jnp.where(pos <= 40, s, -1e30)
        m_prev = m_ref[...]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        l_ref[...] = l_ref[...] * jnp.exp(m_prev - m_cur) + jnp.sum(
            p, axis=-1, keepdims=True)
        m_ref[...] = m_cur
        o_ref[...] = p

    def p5(s):
        return pl.pallas_call(
            k5,
            out_shape=jax.ShapeDtypeStruct((KvH, Gp, ps), jnp.float32),
            scratch_shapes=[pltpu.VMEM((KvH, Gp, 1), jnp.float32),
                            pltpu.VMEM((KvH, Gp, 1), jnp.float32)],
        )(s)

    check("p5 3-D iota+flash", p5, jnp.zeros((KvH, Gp, ps), jnp.float32))


if __name__ == "__main__":
    main()
