// Native dequantisation kernels for GGUF block formats.
//
// The TPU-native analog of the C++ weight-loading path the reference
// delegates to (llama.cpp inside the ollama image — SURVEY.md §2.2): the
// transcode step (GGUF → bf16) is host-side and bandwidth-bound, so the hot
// formats get vectorisable C++ loops here. Exposed with a plain C ABI and
// loaded from Python via ctypes (gguf/native.py); gguf/dequant.py holds the
// semantic reference implementations these must match bit-for-bit (checked
// in tests/test_native.py).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libtpuop_dequant.so dequant.cpp

#include <cstdint>
#include <cstring>

namespace {

// f16 -> f32 without F16C dependence: table-free bit manipulation
inline float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000) << 16;
    uint32_t exp = (h >> 10) & 0x1F;
    uint32_t mant = h & 0x3FF;
    uint32_t bits;
    if (exp == 0) {
        if (mant == 0) {
            bits = sign;
        } else {  // subnormal: normalise
            int e = -1;
            uint32_t m = mant;
            do { m <<= 1; e++; } while (!(m & 0x400));
            bits = sign | ((uint32_t)(127 - 15 - e) << 23) | ((m & 0x3FF) << 13);
        }
    } else if (exp == 31) {
        bits = sign | 0x7F800000u | (mant << 13);
    } else {
        bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
}

}  // namespace

extern "C" {

void dq_f16(const uint8_t* raw, float* out, int64_t n) {
    const uint16_t* h = reinterpret_cast<const uint16_t*>(raw);
    for (int64_t i = 0; i < n; i++) out[i] = f16_to_f32(h[i]);
}

void dq_bf16(const uint8_t* raw, float* out, int64_t n) {
    const uint16_t* h = reinterpret_cast<const uint16_t*>(raw);
    for (int64_t i = 0; i < n; i++) {
        uint32_t bits = (uint32_t)h[i] << 16;
        std::memcpy(&out[i], &bits, 4);
    }
}

// Q4_0: 18-byte blocks of 32: f16 d | 16 nibble bytes. x = (q - 8) d
void dq_q4_0(const uint8_t* raw, float* out, int64_t n_blocks) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* p = raw + b * 18;
        float d = f16_to_f32(*(const uint16_t*)p);
        const uint8_t* qs = p + 2;
        float* y = out + b * 32;
        for (int i = 0; i < 16; i++) {
            y[i] = ((int)(qs[i] & 0xF) - 8) * d;
            y[i + 16] = ((int)(qs[i] >> 4) - 8) * d;
        }
    }
}

// Q8_0: 34-byte blocks of 32: f16 d | 32 int8. x = q d
void dq_q8_0(const uint8_t* raw, float* out, int64_t n_blocks) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* p = raw + b * 34;
        float d = f16_to_f32(*(const uint16_t*)p);
        const int8_t* qs = reinterpret_cast<const int8_t*>(p + 2);
        float* y = out + b * 32;
        for (int i = 0; i < 32; i++) y[i] = qs[i] * d;
    }
}

static inline void get_scale_min_k4(int j, const uint8_t* s, uint8_t* sc,
                                    uint8_t* mn) {
    if (j < 4) {
        *sc = s[j] & 63;
        *mn = s[j + 4] & 63;
    } else {
        *sc = (s[j + 4] & 0xF) | ((s[j - 4] >> 6) << 4);
        *mn = (s[j + 4] >> 4) | ((s[j] >> 6) << 4);
    }
}

// Q4_K: 144-byte super-blocks of 256
void dq_q4_k(const uint8_t* raw, float* out, int64_t n_blocks) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* p = raw + b * 144;
        float d = f16_to_f32(*(const uint16_t*)p);
        float dmin = f16_to_f32(*(const uint16_t*)(p + 2));
        const uint8_t* scales = p + 4;
        const uint8_t* q = p + 16;
        float* y = out + b * 256;
        int is = 0;
        for (int j = 0; j < 256; j += 64) {
            uint8_t sc, mn;
            get_scale_min_k4(is, scales, &sc, &mn);
            float d1 = d * sc, m1 = dmin * mn;
            get_scale_min_k4(is + 1, scales, &sc, &mn);
            float d2 = d * sc, m2 = dmin * mn;
            for (int l = 0; l < 32; l++) *y++ = d1 * (q[l] & 0xF) - m1;
            for (int l = 0; l < 32; l++) *y++ = d2 * (q[l] >> 4) - m2;
            q += 32;
            is += 2;
        }
    }
}

// Q5_K: 176-byte super-blocks of 256
void dq_q5_k(const uint8_t* raw, float* out, int64_t n_blocks) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* p = raw + b * 176;
        float d = f16_to_f32(*(const uint16_t*)p);
        float dmin = f16_to_f32(*(const uint16_t*)(p + 2));
        const uint8_t* scales = p + 4;
        const uint8_t* qh = p + 16;
        const uint8_t* ql = p + 48;
        float* y = out + b * 256;
        int is = 0;
        uint8_t u1 = 1, u2 = 2;
        for (int j = 0; j < 256; j += 64) {
            uint8_t sc, mn;
            get_scale_min_k4(is, scales, &sc, &mn);
            float d1 = d * sc, m1 = dmin * mn;
            get_scale_min_k4(is + 1, scales, &sc, &mn);
            float d2 = d * sc, m2 = dmin * mn;
            for (int l = 0; l < 32; l++)
                *y++ = d1 * ((ql[l] & 0xF) + ((qh[l] & u1) ? 16 : 0)) - m1;
            for (int l = 0; l < 32; l++)
                *y++ = d2 * ((ql[l] >> 4) + ((qh[l] & u2) ? 16 : 0)) - m2;
            ql += 32;
            is += 2;
            u1 <<= 2;
            u2 <<= 2;
        }
    }
}

// Q6_K: 210-byte super-blocks of 256
void dq_q6_k(const uint8_t* raw, float* out, int64_t n_blocks) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* p = raw + b * 210;
        const uint8_t* ql = p;
        const uint8_t* qh = p + 128;
        const int8_t* sc = reinterpret_cast<const int8_t*>(p + 192);
        float d = f16_to_f32(*(const uint16_t*)(p + 208));
        float* y = out + b * 256;
        for (int n = 0; n < 2; n++) {
            for (int l = 0; l < 32; l++) {
                int is = l / 16;
                int q1 = (int)((ql[l] & 0xF) | (((qh[l] >> 0) & 3) << 4)) - 32;
                int q2 = (int)((ql[l + 32] & 0xF) | (((qh[l] >> 2) & 3) << 4)) - 32;
                int q3 = (int)((ql[l] >> 4) | (((qh[l] >> 4) & 3) << 4)) - 32;
                int q4 = (int)((ql[l + 32] >> 4) | (((qh[l] >> 6) & 3) << 4)) - 32;
                y[l] = d * sc[is] * q1;
                y[l + 32] = d * sc[is + 2] * q2;
                y[l + 64] = d * sc[is + 4] * q3;
                y[l + 96] = d * sc[is + 6] * q4;
            }
            y += 128;
            ql += 64;
            qh += 32;
            sc += 8;
        }
    }
}

// f32 -> bf16 (round-to-nearest-even), for the transcode output path.
// NaNs are passed through truncated (quiet bit forced) instead of rounded —
// adding the RNE bias to a NaN payload could carry into the exponent and
// produce Inf.
void f32_to_bf16(const float* in, uint16_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        uint32_t bits;
        std::memcpy(&bits, &in[i], 4);
        if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
            out[i] = (uint16_t)((bits >> 16) | 0x0040);  // quiet NaN
            continue;
        }
        uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
        out[i] = (uint16_t)((bits + rounding) >> 16);
    }
}

}  // extern "C"
