// JSON grammar token-mask kernel for constrained decoding.
//
// Mirrors the byte-level pushdown automaton in
// ollama_operator_tpu/ops/constrain.py over the SAME packed state contract:
//   state = [mode, aux1, aux2, key_flag] ++ stack (1 byte per open container,
//           CTX_OBJ/CTX_ARR, top = last byte)
// The hot entry json_fill_mask simulates every vocab token's bytes from the
// given state and sets one bit per grammar-legal token — vocab × avg-token-
// bytes PDA steps, microseconds in C++ vs seconds in Python for 100k vocabs.
// Python owns the per-token advance (one token per decode step) and the
// per-abstract-state mask cache; equivalence with the Python reference is
// asserted by tests/test_constrain.py.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

enum Mode : uint8_t {
  M_VALUE = 0,
  M_ARR_FIRST = 1,
  M_KEY_FIRST = 2,
  M_KEY = 3,
  M_COLON = 4,
  M_STR = 5,
  M_ESC = 6,
  M_HEX = 7,
  M_NUM = 8,
  M_LIT = 9,
  M_AFTER = 10,
};

enum Ctx : uint8_t { CTX_OBJ = 1, CTX_ARR = 2 };

enum NumState : uint8_t {
  NS_MINUS = 0, NS_ZERO, NS_INT, NS_DOT, NS_FRAC, NS_E, NS_ESIGN, NS_EXP
};

struct State {
  uint8_t mode, aux1, aux2, key;
  // stack: caller-provided prefix + pushes during one token. Capacity is
  // bounded by the caller: suffix bytes + token bytes.
  uint8_t* stack;
  int32_t depth;
};

inline bool is_ws(uint8_t b) {
  return b == ' ' || b == '\t' || b == '\n' || b == '\r';
}

inline bool is_hex(uint8_t b) {
  return (b >= '0' && b <= '9') || (b >= 'a' && b <= 'f') ||
         (b >= 'A' && b <= 'F');
}

inline bool ns_terminal(uint8_t ns) {
  return ns == NS_ZERO || ns == NS_INT || ns == NS_FRAC || ns == NS_EXP;
}

const char* kLiterals[3] = {"true", "false", "null"};
const int kLitLen[3] = {4, 5, 4};

inline bool start_value(State& s, uint8_t b) {
  switch (b) {
    case '{':
      s.stack[s.depth++] = CTX_OBJ;
      s.mode = M_KEY_FIRST; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '[':
      s.stack[s.depth++] = CTX_ARR;
      s.mode = M_ARR_FIRST; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '"':
      s.mode = M_STR; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '-':
      s.mode = M_NUM; s.aux1 = NS_MINUS; s.aux2 = s.key = 0;
      return true;
    case 't':
      s.mode = M_LIT; s.aux1 = 0; s.aux2 = 1; s.key = 0;
      return true;
    case 'f':
      s.mode = M_LIT; s.aux1 = 1; s.aux2 = 1; s.key = 0;
      return true;
    case 'n':
      s.mode = M_LIT; s.aux1 = 2; s.aux2 = 1; s.key = 0;
      return true;
    default:
      if (b == '0') {
        s.mode = M_NUM; s.aux1 = NS_ZERO; s.aux2 = s.key = 0;
        return true;
      }
      if (b >= '1' && b <= '9') {
        s.mode = M_NUM; s.aux1 = NS_INT; s.aux2 = s.key = 0;
        return true;
      }
      return false;
  }
}

inline bool after_value(State& s, uint8_t b) {
  if (is_ws(b)) { s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0; return true; }
  if (s.depth == 0) return false;
  uint8_t top = s.stack[s.depth - 1];
  if (top == CTX_OBJ) {
    if (b == ',') { s.mode = M_KEY; s.aux1 = s.aux2 = s.key = 0; return true; }
    if (b == '}') {
      s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
      return true;
    }
  } else {
    if (b == ',') { s.mode = M_VALUE; s.aux1 = s.aux2 = s.key = 0; return true; }
    if (b == ']') {
      s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
      return true;
    }
  }
  return false;
}

bool advance(State& s, uint8_t b) {
  switch (s.mode) {
    case M_VALUE:
      if (is_ws(b)) return true;
      return start_value(s, b);
    case M_ARR_FIRST:
      if (is_ws(b)) return true;
      if (b == ']') {
        s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      return start_value(s, b);
    case M_KEY_FIRST:
      if (is_ws(b)) return true;
      if (b == '"') { s.mode = M_STR; s.key = 1; return true; }
      if (b == '}') {
        s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      return false;
    case M_KEY:
      if (is_ws(b)) return true;
      if (b == '"') { s.mode = M_STR; s.key = 1; return true; }
      return false;
    case M_COLON:
      if (is_ws(b)) return true;
      if (b == ':') { s.mode = M_VALUE; s.aux1 = s.aux2 = s.key = 0; return true; }
      return false;
    case M_STR:
      if (b == '"') {
        s.mode = s.key ? M_COLON : M_AFTER;
        s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      if (b == '\\') { s.mode = M_ESC; return true; }
      return b >= 0x20;
    case M_ESC:
      switch (b) {
        case '"': case '\\': case '/': case 'b': case 'f':
        case 'n': case 'r': case 't':
          s.mode = M_STR;
          return true;
        case 'u':
          s.mode = M_HEX; s.aux1 = 4;
          return true;
        default:
          return false;
      }
    case M_HEX:
      if (!is_hex(b)) return false;
      if (--s.aux1 == 0) s.mode = M_STR;
      return true;
    case M_NUM: {
      uint8_t ns = s.aux1;
      if (b >= '0' && b <= '9') {
        switch (ns) {
          case NS_MINUS: s.aux1 = (b == '0') ? NS_ZERO : NS_INT; return true;
          case NS_INT:   return true;
          case NS_DOT:   s.aux1 = NS_FRAC; return true;
          case NS_FRAC:  return true;
          case NS_E: case NS_ESIGN: s.aux1 = NS_EXP; return true;
          case NS_EXP:   return true;
          default:       return false;  // NS_ZERO: no leading-zero digits
        }
      }
      if (b == '.' && (ns == NS_ZERO || ns == NS_INT)) {
        s.aux1 = NS_DOT;
        return true;
      }
      if ((b == 'e' || b == 'E') &&
          (ns == NS_ZERO || ns == NS_INT || ns == NS_FRAC)) {
        s.aux1 = NS_E;
        return true;
      }
      if ((b == '+' || b == '-') && ns == NS_E) {
        s.aux1 = NS_ESIGN;
        return true;
      }
      if (ns_terminal(ns)) return after_value(s, b);
      return false;
    }
    case M_LIT: {
      const char* lit = kLiterals[s.aux1];
      int len = kLitLen[s.aux1];
      if (s.aux2 < len && b == (uint8_t)lit[s.aux2]) {
        if (++s.aux2 == len) {
          s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        }
        return true;
      }
      return false;
    }
    case M_AFTER:
      return after_value(s, b);
    default:
      return false;
  }
}

}  // namespace

extern "C" {

// Sets bit `t` of mask_out (packed little-endian uint32 words, caller-zeroed)
// for every token whose bytes the PDA accepts from `state`. Tokens with no
// bytes (tok_off[t+1] == tok_off[t]) never match.
void json_fill_mask(const uint8_t* state, int32_t state_len,
                    const uint8_t* tok_bytes, const int64_t* tok_off,
                    int32_t n_tokens, uint32_t* mask_out) {
  if (state_len < 4) return;
  int32_t base_depth = state_len - 4;
  int64_t max_tok = 0;
  for (int32_t t = 0; t < n_tokens; t++) {
    int64_t l = tok_off[t + 1] - tok_off[t];
    if (l > max_tok) max_tok = l;
  }
  std::vector<uint8_t> stack(base_depth + max_tok + 1);
  for (int32_t t = 0; t < n_tokens; t++) {
    int64_t lo = tok_off[t], hi = tok_off[t + 1];
    if (hi <= lo) continue;
    State s;
    s.mode = state[0]; s.aux1 = state[1]; s.aux2 = state[2]; s.key = state[3];
    std::memcpy(stack.data(), state + 4, base_depth);
    s.stack = stack.data();
    s.depth = base_depth;
    bool ok = true;
    for (int64_t i = lo; i < hi; i++) {
      if (!advance(s, tok_bytes[i])) { ok = false; break; }
    }
    if (ok) mask_out[t >> 5] |= (uint32_t)1 << (t & 31);
  }
}

}  // extern "C"
