// JSON grammar token-mask kernel for constrained decoding.
//
// Mirrors the byte-level pushdown automaton in
// ollama_operator_tpu/ops/constrain.py over the SAME packed state contract:
//   state = [mode, aux1, aux2, key_flag] ++ stack (1 byte per open container,
//           CTX_OBJ/CTX_ARR, top = last byte)
// The hot entry json_fill_mask simulates every vocab token's bytes from the
// given state and sets one bit per grammar-legal token — vocab × avg-token-
// bytes PDA steps, microseconds in C++ vs seconds in Python for 100k vocabs.
// Python owns the per-token advance (one token per decode step) and the
// per-abstract-state mask cache; equivalence with the Python reference is
// asserted by tests/test_constrain.py.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

enum Mode : uint8_t {
  M_VALUE = 0,
  M_ARR_FIRST = 1,
  M_KEY_FIRST = 2,
  M_KEY = 3,
  M_COLON = 4,
  M_STR = 5,
  M_ESC = 6,
  M_HEX = 7,
  M_NUM = 8,
  M_LIT = 9,
  M_AFTER = 10,
};

enum Ctx : uint8_t { CTX_OBJ = 1, CTX_ARR = 2 };

enum NumState : uint8_t {
  NS_MINUS = 0, NS_ZERO, NS_INT, NS_DOT, NS_FRAC, NS_E, NS_ESIGN, NS_EXP
};

struct State {
  uint8_t mode, aux1, aux2, key;
  // stack: caller-provided prefix + pushes during one token. Capacity is
  // bounded by the caller: suffix bytes + token bytes.
  uint8_t* stack;
  int32_t depth;
};

inline bool is_ws(uint8_t b) {
  return b == ' ' || b == '\t' || b == '\n' || b == '\r';
}

inline bool is_hex(uint8_t b) {
  return (b >= '0' && b <= '9') || (b >= 'a' && b <= 'f') ||
         (b >= 'A' && b <= 'F');
}

inline bool ns_terminal(uint8_t ns) {
  return ns == NS_ZERO || ns == NS_INT || ns == NS_FRAC || ns == NS_EXP;
}

const char* kLiterals[3] = {"true", "false", "null"};
const int kLitLen[3] = {4, 5, 4};

inline bool start_value(State& s, uint8_t b) {
  switch (b) {
    case '{':
      s.stack[s.depth++] = CTX_OBJ;
      s.mode = M_KEY_FIRST; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '[':
      s.stack[s.depth++] = CTX_ARR;
      s.mode = M_ARR_FIRST; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '"':
      s.mode = M_STR; s.aux1 = s.aux2 = s.key = 0;
      return true;
    case '-':
      s.mode = M_NUM; s.aux1 = NS_MINUS; s.aux2 = s.key = 0;
      return true;
    case 't':
      s.mode = M_LIT; s.aux1 = 0; s.aux2 = 1; s.key = 0;
      return true;
    case 'f':
      s.mode = M_LIT; s.aux1 = 1; s.aux2 = 1; s.key = 0;
      return true;
    case 'n':
      s.mode = M_LIT; s.aux1 = 2; s.aux2 = 1; s.key = 0;
      return true;
    default:
      if (b == '0') {
        s.mode = M_NUM; s.aux1 = NS_ZERO; s.aux2 = s.key = 0;
        return true;
      }
      if (b >= '1' && b <= '9') {
        s.mode = M_NUM; s.aux1 = NS_INT; s.aux2 = s.key = 0;
        return true;
      }
      return false;
  }
}

inline bool after_value(State& s, uint8_t b) {
  if (is_ws(b)) { s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0; return true; }
  if (s.depth == 0) return false;
  uint8_t top = s.stack[s.depth - 1];
  if (top == CTX_OBJ) {
    if (b == ',') { s.mode = M_KEY; s.aux1 = s.aux2 = s.key = 0; return true; }
    if (b == '}') {
      s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
      return true;
    }
  } else {
    if (b == ',') { s.mode = M_VALUE; s.aux1 = s.aux2 = s.key = 0; return true; }
    if (b == ']') {
      s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
      return true;
    }
  }
  return false;
}

bool advance(State& s, uint8_t b) {
  switch (s.mode) {
    case M_VALUE:
      if (is_ws(b)) return true;
      return start_value(s, b);
    case M_ARR_FIRST:
      if (is_ws(b)) return true;
      if (b == ']') {
        s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      return start_value(s, b);
    case M_KEY_FIRST:
      if (is_ws(b)) return true;
      if (b == '"') { s.mode = M_STR; s.key = 1; return true; }
      if (b == '}') {
        s.depth--; s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      return false;
    case M_KEY:
      if (is_ws(b)) return true;
      if (b == '"') { s.mode = M_STR; s.key = 1; return true; }
      return false;
    case M_COLON:
      if (is_ws(b)) return true;
      if (b == ':') { s.mode = M_VALUE; s.aux1 = s.aux2 = s.key = 0; return true; }
      return false;
    case M_STR:
      if (b == '"') {
        s.mode = s.key ? M_COLON : M_AFTER;
        s.aux1 = s.aux2 = s.key = 0;
        return true;
      }
      if (b == '\\') { s.mode = M_ESC; return true; }
      return b >= 0x20;
    case M_ESC:
      switch (b) {
        case '"': case '\\': case '/': case 'b': case 'f':
        case 'n': case 'r': case 't':
          s.mode = M_STR;
          return true;
        case 'u':
          s.mode = M_HEX; s.aux1 = 4;
          return true;
        default:
          return false;
      }
    case M_HEX:
      if (!is_hex(b)) return false;
      if (--s.aux1 == 0) s.mode = M_STR;
      return true;
    case M_NUM: {
      uint8_t ns = s.aux1;
      if (b >= '0' && b <= '9') {
        switch (ns) {
          case NS_MINUS: s.aux1 = (b == '0') ? NS_ZERO : NS_INT; return true;
          case NS_INT:   return true;
          case NS_DOT:   s.aux1 = NS_FRAC; return true;
          case NS_FRAC:  return true;
          case NS_E: case NS_ESIGN: s.aux1 = NS_EXP; return true;
          case NS_EXP:   return true;
          default:       return false;  // NS_ZERO: no leading-zero digits
        }
      }
      if (b == '.' && (ns == NS_ZERO || ns == NS_INT)) {
        s.aux1 = NS_DOT;
        return true;
      }
      if ((b == 'e' || b == 'E') &&
          (ns == NS_ZERO || ns == NS_INT || ns == NS_FRAC)) {
        s.aux1 = NS_E;
        return true;
      }
      if ((b == '+' || b == '-') && ns == NS_E) {
        s.aux1 = NS_ESIGN;
        return true;
      }
      if (ns_terminal(ns)) return after_value(s, b);
      return false;
    }
    case M_LIT: {
      const char* lit = kLiterals[s.aux1];
      int len = kLitLen[s.aux1];
      if (s.aux2 < len && b == (uint8_t)lit[s.aux2]) {
        if (++s.aux2 == len) {
          s.mode = M_AFTER; s.aux1 = s.aux2 = s.key = 0;
        }
        return true;
      }
      return false;
    }
    case M_AFTER:
      return after_value(s, b);
    default:
      return false;
  }
}

}  // namespace

extern "C" {

// Sets bit `t` of mask_out (packed little-endian uint32 words, caller-zeroed)
// for every token whose bytes the PDA accepts from `state`. Tokens with no
// bytes (tok_off[t+1] == tok_off[t]) never match.
void json_fill_mask(const uint8_t* state, int32_t state_len,
                    const uint8_t* tok_bytes, const int64_t* tok_off,
                    int32_t n_tokens, uint32_t* mask_out) {
  if (state_len < 4) return;
  int32_t base_depth = state_len - 4;
  int64_t max_tok = 0;
  for (int32_t t = 0; t < n_tokens; t++) {
    int64_t l = tok_off[t + 1] - tok_off[t];
    if (l > max_tok) max_tok = l;
  }
  std::vector<uint8_t> stack(base_depth + max_tok + 1);
  for (int32_t t = 0; t < n_tokens; t++) {
    int64_t lo = tok_off[t], hi = tok_off[t + 1];
    if (hi <= lo) continue;
    State s;
    s.mode = state[0]; s.aux1 = state[1]; s.aux2 = state[2]; s.key = state[3];
    std::memcpy(stack.data(), state + 4, base_depth);
    s.stack = stack.data();
    s.depth = base_depth;
    bool ok = true;
    for (int64_t i = lo; i < hi; i++) {
      if (!advance(s, tok_bytes[i])) { ok = false; break; }
    }
    if (ok) mask_out[t >> 5] |= (uint32_t)1 << (t & 31);
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Schema skeleton machine (ops/schema.py) — native NFA mask fill.
//
// The compiled schema node tree is serialized by Python into flat arrays
// (one int64[6] record per node + an extra int64 pool + a byte blob); the
// current NFA state (a set of frame stacks) rides in a packed byte buffer.
// schema_fill_mask simulates every vocab token's bytes through a faithful
// port of ops/schema._advance_stack — including seq descent, alt expansion,
// enum splits, array redispatch, leaf lazy closes, and the bounded-integer
// digit DFA — and sets one bit per schema-legal token. Returns 0 on
// success, -1 when a structural cap is hit (Python then falls back to its
// reference implementation; parity is asserted by tests/test_schema.py).
// ---------------------------------------------------------------------------

namespace schema {

enum NodeType : int64_t {
  N_LIT = 0, N_LEAF = 1, N_SEQ = 2, N_ENUM = 3, N_ARR = 4, N_ALT = 5,
  N_IRANGE = 6,
};

enum LeafKind : int64_t {
  K_STRING = 0, K_NUMBER = 1, K_INTEGER = 2, K_BOOLEAN = 3, K_NULL = 4,
  K_ANY = 5,
};

constexpr int kMaxFrames = 96;
constexpr int kMaxStacks = 64;
constexpr int kMaxPda = 160;

struct Program {
  const int64_t* nodes;  // [n, 6]: type, a, b, c, d, e
  int32_t n_nodes;
  const int64_t* extra;
  const uint8_t* blob;
  int64_t type(int32_t i) const { return nodes[i * 6]; }
  int64_t a(int32_t i) const { return nodes[i * 6 + 1]; }
  int64_t b(int32_t i) const { return nodes[i * 6 + 2]; }
  int64_t c(int32_t i) const { return nodes[i * 6 + 3]; }
  int64_t d(int32_t i) const { return nodes[i * 6 + 4]; }
};

struct Frame {
  int32_t node;
  uint8_t tag;  // 0 pos, 1 leaf, 2 enum, 3 irange
  int32_t pos;
  // leaf (PDA) — only ever on the TOP frame
  uint8_t mode, aux1, aux2, key;
  int32_t depth;
  // enum
  uint64_t viable;
  // irange
  int8_t sign;
  int64_t v;
  int32_t k;
};

struct Stack {
  Frame frames[kMaxFrames];
  int32_t n;
  uint8_t pda[kMaxPda];  // leaf container stack (top frame only)

  Frame& top() { return frames[n - 1]; }
};

inline bool frame_pos(Stack& st, int32_t node, int32_t pos) {
  if (st.n >= kMaxFrames) return false;
  Frame f{}; f.node = node; f.tag = 0; f.pos = pos;
  st.frames[st.n++] = f;
  return true;
}

// init_sub for a consumer node pushed on top; false = overflow
bool push_consumer(const Program& p, Stack& st, int32_t node) {
  if (st.n >= kMaxFrames) return false;
  Frame f{};
  f.node = node;
  switch (p.type(node)) {
    case N_LIT: f.tag = 0; f.pos = 0; break;
    case N_LEAF:
      f.tag = 1; f.mode = M_VALUE; f.aux1 = f.aux2 = f.key = 0;
      f.depth = 0;
      break;
    case N_ENUM: {
      f.tag = 2; f.pos = 0;
      int64_t nalts = p.b(node);
      f.viable = (nalts >= 64) ? ~0ull : ((1ull << nalts) - 1);
      break;
    }
    case N_ARR: f.tag = 0; f.pos = 0; break;
    case N_IRANGE: f.tag = 3; f.sign = 0; f.v = 0; f.k = 0; break;
    default: return false;  // SEQ/ALT never sit on a stack top directly
  }
  st.frames[st.n++] = f;
  return true;
}

// _push_multi: push `node` onto a copy of st for each alternative path.
// Appends results to out; false on overflow (caller bails).
bool push_multi(const Program& p, const Stack& st, int32_t node,
                std::vector<Stack>& out) {
  struct Item { Stack st; int32_t node; };
  std::vector<Item> work;
  work.push_back({st, node});
  while (!work.empty()) {
    Item it = work.back();
    work.pop_back();
    int64_t t = p.type(it.node);
    if (t == N_SEQ) {
      if (!frame_pos(it.st, it.node, 0)) return false;
      int32_t child = (int32_t)p.extra[p.a(it.node)];
      work.push_back({it.st, child});
    } else if (t == N_ALT) {
      int64_t off = p.a(it.node), n = p.b(it.node);
      for (int64_t i = 0; i < n; i++)
        work.push_back({it.st, (int32_t)p.extra[off + i]});
    } else {
      if (!push_consumer(p, it.st, it.node)) return false;
      if (out.size() >= kMaxStacks) return false;
      out.push_back(it.st);
    }
  }
  return true;
}

// _completed_child: top frame popped; advance ancestors, push next
// consumer(s). Appends all results to out; false on overflow.
bool completed_child(const Program& p, Stack st, std::vector<Stack>& out) {
  while (st.n > 0) {
    Frame& f = st.top();
    int64_t t = p.type(f.node);
    if (t == N_SEQ) {
      int32_t nxt = f.pos + 1;
      if (nxt == (int32_t)p.b(f.node)) { st.n--; continue; }
      f.pos = nxt;
      int32_t child = (int32_t)p.extra[p.a(f.node) + nxt];
      return push_multi(p, st, child, out);
    }
    if (t == N_ARR) {
      f.pos = 3;
      if (out.size() >= kMaxStacks) return false;
      out.push_back(st);
      return true;
    }
    return false;  // malformed
  }
  if (out.size() >= kMaxStacks) return false;
  out.push_back(st);  // empty stack = schema complete (EOS only)
  return true;
}

inline bool irange_fits(bool has_lo, int64_t lo, bool has_hi, int64_t hi,
                        int8_t sign, __int128 a, __int128 b2) {
  __int128 vlo = sign >= 0 ? a : -b2;
  __int128 vhi = sign >= 0 ? b2 : -a;
  return (!has_hi || vlo <= (__int128)hi) && (!has_lo || vhi >= (__int128)lo);
}

bool irange_viable(bool has_lo, int64_t lo, bool has_hi, int64_t hi,
                   int8_t sign, int64_t v, int32_t k) {
  if (irange_fits(has_lo, lo, has_hi, hi, sign, v, v)) return true;
  if (v == 0) return false;  // leading zero: no extensions
  int32_t limit;
  if (sign >= 0) {
    if (!has_hi) return true;
    if (hi <= 0) return false;
    limit = 0; for (int64_t x = hi; x > 0; x /= 10) limit++;
  } else {
    if (!has_lo) return true;
    if (lo >= 0) return false;
    limit = 0; for (int64_t x = -lo; x > 0; x /= 10) limit++;
  }
  __int128 scale = 1;
  for (int32_t m = k + 1; m <= limit; m++) {
    scale *= 10;
    if (irange_fits(has_lo, lo, has_hi, hi, sign, (__int128)v * scale,
                    (__int128)v * scale + scale - 1))
      return true;
  }
  return false;
}

inline bool irange_done(const Program& p, const Frame& f) {
  if (f.k == 0) return false;
  bool has_lo = p.a(f.node) != 0, has_hi = p.c(f.node) != 0;
  int64_t lo = p.b(f.node), hi = p.d(f.node);
  int64_t val = f.sign >= 0 ? f.v : -f.v;
  return (!has_lo || val >= lo) && (!has_hi || val <= hi);
}

inline bool leaf_start_ok(int64_t kind, uint8_t b) {
  switch (kind) {
    case K_STRING:  return b == '"';
    case K_NUMBER: case K_INTEGER:
      return b == '-' || (b >= '0' && b <= '9');
    case K_BOOLEAN: return b == 't' || b == 'f';
    case K_NULL:    return b == 'n';
    default:        return true;  // any
  }
}

// one byte through one stack; appends successors to out. false = bail.
bool advance_stack(const Program& p, const Stack& st0, uint8_t b,
                   std::vector<Stack>& out, int rec = 0) {
  if (rec > 8) return false;
  if (st0.n == 0) return true;  // complete: EOS only — rejects b
  Stack st = st0;
  Frame& f = st.top();
  switch (p.type(f.node)) {
    case N_LIT: {
      int64_t off = p.a(f.node), len = p.b(f.node);
      if (p.blob[off + f.pos] != b) return true;
      if (++f.pos == (int32_t)len) {
        st.n--;
        return completed_child(p, st, out);
      }
      if (out.size() >= kMaxStacks) return false;
      out.push_back(st);
      return true;
    }
    case N_LEAF: {
      int64_t kind = p.a(f.node);
      bool fresh = f.mode == M_VALUE && f.depth == 0;
      bool allowed = !fresh || leaf_start_ok(kind, b);
      if (allowed && kind == K_INTEGER &&
          (b == '.' || b == 'e' || b == 'E'))
        allowed = false;
      State s;
      s.mode = f.mode; s.aux1 = f.aux1; s.aux2 = f.aux2; s.key = f.key;
      s.stack = st.pda; s.depth = f.depth;
      bool adv = allowed && f.depth < kMaxPda - 2 && advance(s, b);
      if (adv) {
        if (s.mode == M_AFTER && s.depth == 0) {
          st.n--;
          return completed_child(p, st, out);
        }
        f.mode = s.mode; f.aux1 = s.aux1; f.aux2 = s.aux2; f.key = s.key;
        f.depth = s.depth;
        if (out.size() >= kMaxStacks) return false;
        out.push_back(st);
        return true;
      }
      if (allowed && f.depth >= kMaxPda - 2) return false;  // cap: bail
      // lazy close (numbers complete at depth 0)
      if (f.depth == 0 &&
          (f.mode == M_AFTER || (f.mode == M_NUM && ns_terminal(f.aux1)))) {
        Stack popped = st0;
        popped.n--;
        std::vector<Stack> closed;
        if (!completed_child(p, popped, closed)) return false;
        for (auto& cs : closed)
          if (!advance_stack(p, cs, b, out, rec + 1)) return false;
        return true;
      }
      return true;
    }
    case N_ENUM: {
      int64_t off = p.a(f.node), nalts = p.b(f.node);
      uint64_t nv = 0;
      bool any_fin = false;
      for (int64_t i = 0; i < nalts; i++) {
        if (!(f.viable >> i & 1)) continue;
        int64_t aoff = p.extra[off + 2 * i], alen = p.extra[off + 2 * i + 1];
        if (f.pos < alen && p.blob[aoff + f.pos] == b) {
          if (f.pos + 1 == alen) any_fin = true;
          else nv |= 1ull << i;
        }
      }
      if (!nv && !any_fin) return true;
      if (nv) {
        Stack cont = st;
        cont.top().pos = f.pos + 1;
        cont.top().viable = nv;
        if (out.size() >= kMaxStacks) return false;
        out.push_back(cont);
      }
      if (any_fin) {
        Stack done = st;
        done.n--;
        if (!completed_child(p, done, out)) return false;
      }
      return true;
    }
    case N_ARR: {
      if (f.pos == 0) {
        if (b != '[') return true;
        f.pos = 1;
        if (out.size() >= kMaxStacks) return false;
        out.push_back(st);
        return true;
      }
      if (f.pos == 1) {  // first item or ']'
        if (b == ']' && p.b(f.node) == 0) {
          st.n--;
          return completed_child(p, st, out);
        }
        f.pos = 2;
        std::vector<Stack> pushed;
        if (!push_multi(p, st, (int32_t)p.a(f.node), pushed)) return false;
        for (auto& ps : pushed)
          if (!advance_stack(p, ps, b, out, rec + 1)) return false;
        return true;
      }
      if (f.pos == 3) {  // after an item
        if (b == ']') {
          st.n--;
          return completed_child(p, st, out);
        }
        if (b == ',') {
          f.pos = 2;
          return push_multi(p, st, (int32_t)p.a(f.node), out);
        }
        return true;
      }
      return true;
    }
    case N_IRANGE: {
      bool has_lo = p.a(f.node) != 0, has_hi = p.c(f.node) != 0;
      int64_t lo = p.b(f.node), hi = p.d(f.node);
      if (b >= '0' && b <= '9') {
        int64_t d = b - '0';
        int64_t nv; int32_t nk;
        if (f.k == 0) { nv = d; nk = 1; }
        else if (f.v == 0) return true;  // leading zero can't extend
        else if (f.v > (int64_t)1e17) {
          // unbounded-side growth: saturate (Python serialization refuses
          // finite bounds beyond 1e15, so the saturated magnitude is
          // already past every bound and comparisons stay exact)
          nv = (int64_t)1e17 + 9; nk = f.k + 1;
        } else {
          nv = f.v * 10 + d; nk = f.k + 1;
        }
        int8_t s_eff = f.sign != 0 ? f.sign : 1;
        if (!irange_viable(has_lo, lo, has_hi, hi, s_eff, nv, nk))
          return true;
        f.sign = s_eff; f.v = nv; f.k = nk;
        if (out.size() >= kMaxStacks) return false;
        out.push_back(st);
        return true;
      }
      if (b == '-' && f.sign == 0 && f.k == 0) {
        for (int64_t d = 0; d <= 9; d++) {
          if (irange_viable(has_lo, lo, has_hi, hi, -1, d, 1)) {
            f.sign = -1; f.v = 0; f.k = 0;
            if (out.size() >= kMaxStacks) return false;
            out.push_back(st);
            return true;
          }
        }
        return true;
      }
      if (irange_done(p, f)) {  // delimiter closes the integer
        Stack popped = st0;
        popped.n--;
        std::vector<Stack> closed;
        if (!completed_child(p, popped, closed)) return false;
        for (auto& cs : closed)
          if (!advance_stack(p, cs, b, out, rec + 1)) return false;
        return true;
      }
      return true;
    }
    default:
      return false;
  }
}

}  // namespace schema

extern "C" {

// Returns 0 on success (mask_out filled), -1 on a structural cap (caller
// falls back to the Python reference fill).
int32_t schema_fill_mask(const int64_t* nodes, int32_t n_nodes,
                         const int64_t* extra, const uint8_t* blob,
                         const uint8_t* state_buf, int64_t state_len,
                         const uint8_t* tok_bytes, const int64_t* tok_off,
                         int32_t n_tokens, uint32_t* mask_out) {
  using schema::Stack;
  using schema::Frame;
  schema::Program p{nodes, n_nodes, extra, blob};

  // ---- decode the packed NFA state --------------------------------------
  const uint8_t* q = state_buf;
  const uint8_t* end = state_buf + state_len;
  auto rd_u32 = [&](uint32_t& v) {
    if (q + 4 > end) return false;
    std::memcpy(&v, q, 4); q += 4; return true;
  };
  auto rd_i64 = [&](int64_t& v) {
    if (q + 8 > end) return false;
    std::memcpy(&v, q, 8); q += 8; return true;
  };
  uint32_t n_stacks;
  if (!rd_u32(n_stacks) || n_stacks == 0 || n_stacks > schema::kMaxStacks)
    return -1;
  std::vector<Stack> init(n_stacks);
  for (uint32_t si = 0; si < n_stacks; si++) {
    Stack& st = init[si];
    st.n = 0;
    uint32_t n_frames;
    if (!rd_u32(n_frames) || n_frames > schema::kMaxFrames) return -1;
    for (uint32_t fi = 0; fi < n_frames; fi++) {
      if (q + 5 > end) return -1;
      Frame f{};
      uint32_t node;
      std::memcpy(&node, q, 4); q += 4;
      f.node = (int32_t)node;
      f.tag = *q++;
      if (f.tag == 0) {
        uint32_t pos; if (!rd_u32(pos)) return -1;
        f.pos = (int32_t)pos;
      } else if (f.tag == 1) {
        uint32_t plen; if (!rd_u32(plen)) return -1;
        if (plen < 4 || q + plen > end) return -1;
        f.mode = q[0]; f.aux1 = q[1]; f.aux2 = q[2]; f.key = q[3];
        f.depth = (int32_t)plen - 4;
        if (f.depth > schema::kMaxPda - 64) return -1;  // headroom for token
        std::memcpy(st.pda, q + 4, f.depth);
        q += plen;
      } else if (f.tag == 2) {
        uint32_t pos; if (!rd_u32(pos)) return -1;
        f.pos = (int32_t)pos;
        if (q + 8 > end) return -1;
        std::memcpy(&f.viable, q, 8); q += 8;
      } else if (f.tag == 3) {
        if (q + 1 > end) return -1;
        f.sign = (int8_t)*q++;
        int64_t v; if (!rd_i64(v)) return -1;
        f.v = v;
        uint32_t k; if (!rd_u32(k)) return -1;
        f.k = (int32_t)k;
      } else {
        return -1;
      }
      if (st.n >= schema::kMaxFrames) return -1;
      st.frames[st.n++] = f;
    }
  }

  // ---- simulate every token ---------------------------------------------
  std::vector<Stack> cur, nxt;
  for (int32_t t = 0; t < n_tokens; t++) {
    int64_t lo = tok_off[t], hi = tok_off[t + 1];
    if (hi <= lo) continue;
    cur = init;
    bool alive = true;
    for (int64_t i = lo; i < hi && alive; i++) {
      nxt.clear();
      for (auto& st : cur) {
        if (!schema::advance_stack(p, st, tok_bytes[i], nxt)) return -1;
      }
      if (nxt.empty()) alive = false;
      cur.swap(nxt);
    }
    if (alive && !cur.empty())
      mask_out[t >> 5] |= (uint32_t)1 << (t & 31);
  }
  return 0;
}

}  // extern "C"
