"""tpu-model-operator: a TPU-native model-serving framework.

Re-provides the full capability surface of the `ollama-operator` reference
(a K8s operator delegating inference to the ollama/llama.cpp container — see
/root/reference, SURVEY.md) as a from-scratch JAX/XLA/Pallas stack:

- ``models/``    decoder-only transformer family (llama2/3, mistral, qwen2,
                 gemma, phi-2, tinyllama) as pure functional JAX.
- ``ops/``       numerics: RoPE, norms, attention, sampling; Pallas TPU
                 kernels with pure-JAX fallbacks.
- ``parallel/``  device mesh, sharding specs, ring attention (sequence
                 parallelism), multi-host distributed init.
- ``gguf/``      GGUF parse + dequantization + transcode cache (the
                 TPU-native replacement for the ollama blob store contents).
- ``tokenizer/`` SPM-BPE and GPT2-BPE built from GGUF metadata (no
                 sentencepiece dependency).
- ``runtime/``   serving engine: jitted prefill/decode, slot KV cache,
                 continuous batching scheduler.
- ``server/``    Ollama-compatible HTTP API + OpenAI compat + metrics +
                 registry.ollama.ai pull client.
- ``operator/``  the Kubernetes control plane: Model CRD + reconciler
                 (pure-function workload assembly mirroring the reference's
                 pkg/model, reconcile ladder mirroring
                 internal/controller/model_controller.go).
- ``training/``  LoRA/full fine-tune step used to validate dp/tp/sp sharding.
"""

__version__ = "0.1.0"
