from .reader import GGUFFile, GGUFTensor  # noqa: F401
from . import dequant  # noqa: F401
