"""Dequantisation of ggml block formats → float32 (vectorised numpy).

Semantics mirror ggml's dequantize_row_* functions (the math llama.cpp runs
inside the container the reference delegates to — SURVEY.md §2.2), expressed
as whole-tensor numpy array ops instead of per-block scalar loops. A C++
fast path (native/dequant.cpp, loaded via ctypes in native.py) accelerates
the hot formats during transcode; this module is the semantic reference and
the always-available fallback.

Layouts (per block; QK = 32 for legacy formats, 256 for k-quants):
  Q4_0: f16 d | 16B nibbles                    x = (q - 8) d
  Q4_1: f16 d, m | 16B nibbles                 x = q d + m
  Q5_0: f16 d | 4B high-bits | 16B nibbles     x = (q - 16) d
  Q5_1: f16 d, m | 4B | 16B                    x = q d + m
  Q8_0: f16 d | 32×i8                          x = q d
  Q2_K: 16B scales | 64B 2-bit | f16 d, dmin   x = d sc q - dmin m
  Q3_K: 32B hmask | 64B 2-bit | 12B scales | f16 d
  Q4_K: f16 d, dmin | 12B scales | 128B nibbles
  Q5_K: f16 d, dmin | 12B scales | 32B qh | 128B nibbles
  Q6_K: 128B ql | 64B qh | 16×i8 scales | f16 d
Nibble order (legacy): low nibbles of the 16 bytes are elements 0..15, high
nibbles are elements 16..31.
"""

from __future__ import annotations

import numpy as np

from . import reader as R


def _f16(b: np.ndarray) -> np.ndarray:
    """bytes [..., 2] → float32"""
    return b.view(np.float16).astype(np.float32)


# ---------------------------------------------------------------------------
# legacy 32-element blocks
# ---------------------------------------------------------------------------

def dq_q4_0(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 18)
    d = _f16(b[:, :2])                       # [N,1]
    qs = b[:, 2:]
    lo = (qs & 0x0F).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (q * d).reshape(-1)


def dq_q4_1(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 20)
    d = _f16(b[:, 0:2])
    m = _f16(b[:, 2:4])
    qs = b[:, 4:]
    q = np.concatenate([qs & 0x0F, qs >> 4], axis=1).astype(np.float32)
    return (q * d + m).reshape(-1)


def _q5_bits(qh_bytes: np.ndarray) -> np.ndarray:
    """4 bytes per block → [N, 32] high bits."""
    qh = qh_bytes.view(np.uint32).reshape(-1, 1)
    return ((qh >> np.arange(32, dtype=np.uint32)) & 1).astype(np.uint8)


def dq_q5_0(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 22)
    d = _f16(b[:, 0:2])
    hb = _q5_bits(np.ascontiguousarray(b[:, 2:6]))
    qs = b[:, 6:]
    lo = (qs & 0x0F) | (hb[:, :16] << 4)
    hi = (qs >> 4) | (hb[:, 16:] << 4)
    q = np.concatenate([lo, hi], axis=1).astype(np.int16) - 16
    return (q.astype(np.float32) * d).reshape(-1)


def dq_q5_1(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 24)
    d = _f16(b[:, 0:2])
    m = _f16(b[:, 2:4])
    hb = _q5_bits(np.ascontiguousarray(b[:, 4:8]))
    qs = b[:, 8:]
    lo = (qs & 0x0F) | (hb[:, :16] << 4)
    hi = (qs >> 4) | (hb[:, 16:] << 4)
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (q * d + m).reshape(-1)


def dq_q8_0(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 34)
    d = _f16(b[:, 0:2])
    q = b[:, 2:].view(np.int8).astype(np.float32)
    return (q * d).reshape(-1)


# ---------------------------------------------------------------------------
# k-quants (256-element super-blocks)
# ---------------------------------------------------------------------------

def _expand_2bit(qs: np.ndarray) -> np.ndarray:
    """[N, 64] bytes → [N, 2, 4, 32] values: halves × shifts × lanes, which
    flattens to the ggml element order (half, shift, lane)."""
    N = qs.shape[0]
    q = qs.reshape(N, 2, 32)                      # two 32-byte halves
    shifts = np.array([0, 2, 4, 6], np.uint8).reshape(1, 1, 4, 1)
    return (q[:, :, None, :] >> shifts) & 3       # [N, 2, 4, 32]


def dq_q2_k(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 84)
    N = b.shape[0]
    scales = b[:, :16]                            # 16 sub-block scale bytes
    qs = b[:, 16:80]
    d = _f16(b[:, 80:82])                         # [N,1]
    dmin = _f16(b[:, 82:84])
    q = _expand_2bit(qs).astype(np.float32)       # [N,2,4,32]
    sc = (scales & 0xF).astype(np.float32).reshape(N, 2, 4, 2, 1)
    mn = (scales >> 4).astype(np.float32).reshape(N, 2, 4, 2, 1)
    qv = q.reshape(N, 2, 4, 2, 16)
    y = d.reshape(N, 1, 1, 1, 1) * sc * qv - dmin.reshape(N, 1, 1, 1, 1) * mn
    return y.reshape(-1)


def _q3k_scales(sb: np.ndarray) -> np.ndarray:
    """12 scale bytes → 16 signed 6-bit scales (ggml aux/kmask unpack)."""
    N = sb.shape[0]
    a = sb[:, :4]
    bb = sb[:, 4:8]
    c = sb[:, 8:12]
    lo = np.concatenate([a & 0xF, bb & 0xF, a >> 4, bb >> 4], axis=1)
    hi_shift = np.repeat(np.arange(4, dtype=np.uint8) * 2, 4).reshape(1, 16)
    hi = (c[:, [0, 1, 2, 3] * 4] >> hi_shift) & 3
    return (lo | (hi << 4)).astype(np.int16) - 32  # [N,16]


def dq_q3_k(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 110)
    N = b.shape[0]
    hmask = b[:, :32]
    qs = b[:, 32:96]
    scales = _q3k_scales(b[:, 96:108]).astype(np.float32)  # [N,16]
    d = _f16(b[:, 108:110])
    q = _expand_2bit(qs).astype(np.int16)         # [N,2,4,32]
    bit = np.arange(8, dtype=np.uint8).reshape(1, 2, 4, 1)
    h = (hmask[:, None, None, :] >> bit) & 1      # [N,2,4,32]
    q = q - (1 - h.astype(np.int16)) * 4
    sc = scales.reshape(N, 2, 4, 2, 1)
    y = d.reshape(N, 1, 1, 1, 1) * sc * q.reshape(N, 2, 4, 2, 16)
    return y.reshape(-1)


def _k4_scale_min(sb: np.ndarray):
    """12 bytes → (scales[N,8], mins[N,8]) 6-bit (get_scale_min_k4)."""
    s = sb.astype(np.uint8)
    sc = np.empty(s.shape[:1] + (8,), np.uint8)
    mn = np.empty_like(sc)
    sc[:, :4] = s[:, 0:4] & 63
    mn[:, :4] = s[:, 4:8] & 63
    sc[:, 4:] = (s[:, 8:12] & 0xF) | ((s[:, 0:4] >> 6) << 4)
    mn[:, 4:] = (s[:, 8:12] >> 4) | ((s[:, 4:8] >> 6) << 4)
    return sc.astype(np.float32), mn.astype(np.float32)


def dq_q4_k(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 144)
    N = b.shape[0]
    d = _f16(b[:, 0:2])
    dmin = _f16(b[:, 2:4])
    sc, mn = _k4_scale_min(b[:, 4:16])            # [N,8]
    qs = b[:, 16:].reshape(N, 4, 32)              # 4 chunks of 64 elems
    lo = (qs & 0xF).astype(np.float32)            # [N,4,32] → sub-blocks 0,2,4,6
    hi = (qs >> 4).astype(np.float32)             # sub-blocks 1,3,5,7
    q = np.stack([lo, hi], axis=2)                # [N,4,2,32]
    dd = d.reshape(N, 1, 1, 1) * sc.reshape(N, 4, 2, 1)
    mm = dmin.reshape(N, 1, 1, 1) * mn.reshape(N, 4, 2, 1)
    return (dd * q - mm).reshape(-1)


def dq_q5_k(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 176)
    N = b.shape[0]
    d = _f16(b[:, 0:2])
    dmin = _f16(b[:, 2:4])
    sc, mn = _k4_scale_min(b[:, 4:16])
    qh = b[:, 16:48]                              # [N,32]
    qs = b[:, 48:].reshape(N, 4, 32)
    lo = (qs & 0xF).astype(np.uint8)
    hi = (qs >> 4).astype(np.uint8)
    # chunk j: low-nibble bit = 2j, high-nibble bit = 2j+1 (u1/u2 <<= 2)
    jbits = np.arange(4, dtype=np.uint8).reshape(1, 4, 1)
    hlo = (qh[:, None, :] >> (2 * jbits)) & 1
    hhi = (qh[:, None, :] >> (2 * jbits + 1)) & 1
    q = np.stack([lo + 16 * hlo, hi + 16 * hhi], axis=2).astype(np.float32)
    dd = d.reshape(N, 1, 1, 1) * sc.reshape(N, 4, 2, 1)
    mm = dmin.reshape(N, 1, 1, 1) * mn.reshape(N, 4, 2, 1)
    return (dd * q - mm).reshape(-1)


def dq_q6_k(raw: np.ndarray) -> np.ndarray:
    b = raw.reshape(-1, 210)
    N = b.shape[0]
    ql = b[:, :128].reshape(N, 2, 64)             # two halves of 128 elems
    qh = b[:, 128:192].reshape(N, 2, 32)
    scales = b[:, 192:208].view(np.int8).astype(np.float32).reshape(N, 2, 8)
    d = _f16(b[:, 208:210])
    l_lo, l_hi = ql[:, :, :32], ql[:, :, 32:]
    h = qh                                         # [N,2,32]
    q1 = (l_lo & 0xF) | (((h >> 0) & 3) << 4)
    q2 = (l_hi & 0xF) | (((h >> 2) & 3) << 4)
    q3 = (l_lo >> 4) | (((h >> 4) & 3) << 4)
    q4 = (l_hi >> 4) | (((h >> 6) & 3) << 4)
    q = np.stack([q1, q2, q3, q4], axis=2).astype(np.int16) - 32  # [N,2,4,32]
    # scale idx within a half: row k (of 4) × lane l: is = k*2 + l//16
    sc = scales.reshape(N, 2, 4, 2, 1)
    y = d.reshape(N, 1, 1, 1, 1) * sc * q.reshape(N, 2, 4, 2, 16).astype(
        np.float32)
    return y.reshape(-1)


# ---------------------------------------------------------------------------
# i-quants (non-linear 4-bit: shared LUT; ggml dequantize_row_iq4_nl/_xs)
# ---------------------------------------------------------------------------

# kvalues_iq4nl: the non-linear code→value map both iq4 formats share
_IQ4NL_LUT = np.array([-127, -104, -83, -65, -49, -35, -22, -10,
                       1, 13, 25, 38, 53, 69, 89, 113], np.float32)


def dq_iq4_nl(raw: np.ndarray) -> np.ndarray:
    """32-elem blocks, q4_0 layout (f16 d | 16B nibbles); codes map
    through the non-linear LUT instead of (q - 8)."""
    b = raw.reshape(-1, 18)
    d = _f16(b[:, :2])                       # [N,1]
    qs = b[:, 2:]
    lo = _IQ4NL_LUT[qs & 0x0F]
    hi = _IQ4NL_LUT[qs >> 4]
    q = np.concatenate([lo, hi], axis=1)
    return (q * d).reshape(-1)


def dq_iq4_xs(raw: np.ndarray) -> np.ndarray:
    """256-elem super-blocks: f16 d | u16 scales_h | 4B scales_l |
    128B nibbles. Sub-block ib (of 8×32): 6-bit scale
    ls = scales_l nibble | scales_h 2-bit pair << 4, value
    d·(ls-32)·LUT[q]; within a sub-block low nibbles are elements
    0..15, high 16..31."""
    b = raw.reshape(-1, 136)
    N = b.shape[0]
    d = _f16(b[:, 0:2])                              # [N,1]
    scales_h = np.ascontiguousarray(b[:, 2:4]).view(np.uint16)  # [N,1]
    scales_l = b[:, 4:8]                             # [N,4]
    qs = b[:, 8:].reshape(N, 8, 16)                  # [N, ib, 16]
    ib = np.arange(8)
    ls_l = (scales_l[:, ib // 2] >> (4 * (ib % 2))) & 0xF       # [N,8]
    ls_h = (scales_h >> (2 * ib).astype(np.uint16)) & 3         # [N,8]
    ls = (ls_l | (ls_h << 4)).astype(np.float32) - 32
    dl = (d * ls).reshape(N, 8, 1)                   # [N,8,1]
    lo = _IQ4NL_LUT[qs & 0x0F]                       # [N,8,16]
    hi = _IQ4NL_LUT[qs >> 4]
    y = dl * np.concatenate([lo, hi], axis=2)        # [N,8,32]
    return y.reshape(-1)


# ---------------------------------------------------------------------------
# plain types + dispatch
# ---------------------------------------------------------------------------

def dq_f32(raw: np.ndarray) -> np.ndarray:
    return raw.view(np.float32).copy()


def dq_f16(raw: np.ndarray) -> np.ndarray:
    return raw.view(np.float16).astype(np.float32)


def dq_bf16(raw: np.ndarray) -> np.ndarray:
    u = raw.view(np.uint16).astype(np.uint32) << 16
    return u.view(np.float32)


_DISPATCH = {
    R.GGML_F32: dq_f32, R.GGML_F16: dq_f16, R.GGML_BF16: dq_bf16,
    R.GGML_Q4_0: dq_q4_0, R.GGML_Q4_1: dq_q4_1,
    R.GGML_Q5_0: dq_q5_0, R.GGML_Q5_1: dq_q5_1, R.GGML_Q8_0: dq_q8_0,
    R.GGML_Q2_K: dq_q2_k, R.GGML_Q3_K: dq_q3_k, R.GGML_Q4_K: dq_q4_k,
    R.GGML_Q5_K: dq_q5_k, R.GGML_Q6_K: dq_q6_k,
    R.GGML_IQ4_NL: dq_iq4_nl, R.GGML_IQ4_XS: dq_iq4_xs,
    R.GGML_I8: lambda raw: raw.view(np.int8).astype(np.float32),
    R.GGML_I32: lambda raw: raw.view(np.int32).astype(np.float32),
}


def supported_types():
    return set(_DISPATCH)


# IQ1/IQ2/IQ3 (iq2_xxs/iq2_xs/iq2_s/iq3_xxs/iq3_s/iq1_s/iq1_m) decode
# through large SEARCHED codebooks (256–2048-entry sign/magnitude grids
# found by offline optimization in upstream llama.cpp, not derivable from
# a closed-form spec the way the q*_0/K-quant grids and the 16-entry
# iq4 LUT are). This build environment has no llama.cpp source, no gguf
# python package, and no network egress to fetch the tables, and shipping
# approximated codebooks would silently dequantize real registry images
# to WRONG weights — so these types fail loudly instead. Blocker recorded
# round 5; resolution = vendor the codebook tables when the build
# environment can obtain them.
_IQ_CODEBOOK_TYPES = {R.GGML_IQ2_XXS, R.GGML_IQ2_XS, R.GGML_IQ3_XXS,
                      R.GGML_IQ1_S, R.GGML_IQ3_S, R.GGML_IQ2_S,
                      R.GGML_IQ1_M}


def dequantize(raw: np.ndarray, ggml_type: int, shape: tuple) -> np.ndarray:
    """raw uint8 buffer → float32 array of ``shape`` (numpy row-major)."""
    if ggml_type not in _DISPATCH:
        name = R.GGML_TYPE_NAMES.get(ggml_type, ggml_type)
        if ggml_type in _IQ_CODEBOOK_TYPES:
            raise NotImplementedError(
                f"ggml type {name}: codebook i-quants need llama.cpp's "
                f"searched grid tables, which are unavailable in this "
                f"build (no vendored llama.cpp, no egress); re-pull the "
                f"model at q4_0/q8_0/K-quant/iq4 precision")
        raise NotImplementedError(f"ggml type {name} not supported")
    return _DISPATCH[ggml_type](raw).reshape(shape)


def dequantize_tensor(f: "R.GGUFFile", t: "R.GGUFTensor") -> np.ndarray:
    return dequantize(f.raw(t), t.ggml_type, t.shape)
