"""LoRA adapter merging: the Modelfile ``ADAPTER`` directive.

The reference delegates adapters to llama.cpp inside the ollama image
(/root/reference/pkg/model/pod.go:11; ADAPTER is part of the Modelfile
surface the registry serves). llama.cpp applies LoRA at runtime per matmul;
here the TPU-native choice is to **merge at load time** — W' = W + s·(B@A)
with s = alpha/rank — so the serving engine runs the exact same fused
bf16/int8 matmuls with zero per-token overhead, and the transcoded layout
(transposes + rope unpermute, gguf/transcode.py) is applied once to the
delta on the host.

Adapter format: a GGUF file (llama.cpp convert_lora_to_gguf convention) with
``adapter.lora.alpha`` metadata and tensor pairs ``<base>.lora_a`` [r, in] /
``<base>.lora_b`` [out, r] named after the base-model tensors
(blk.N.attn_q.weight, …).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from . import dequant as DQ
from .reader import GGUFFile
from .transcode import _INTERLEAVED_ROPE_ARCHES, _unpermute_rope


def _dq32(f: GGUFFile, name: str) -> np.ndarray:
    return np.asarray(DQ.dequantize_tensor(f, f.tensors[name]), np.float32)


def _targets(cfg):
    """base GGUF tensor suffix → (param key, delta post-transform).

    post maps the GGUF-layout delta [out, in] into our transposed/unpermuted
    parameter layout (mirrors load_params, gguf/transcode.py)."""
    H, KvH = cfg.n_heads, cfg.n_kv_heads
    T_ = lambda a: a.T
    return {
        "attn_q.weight": ("wq", lambda a: _unpermute_rope(a, H).T),
        "attn_k.weight": ("wk", lambda a: _unpermute_rope(a, KvH).T),
        "attn_v.weight": ("wv", T_),
        "attn_output.weight": ("wo", T_),
        "ffn_up.weight": ("w_up", T_),
        "ffn_down.weight": ("w_down", T_),
        "ffn_gate.weight": ("w_gate", T_),
    }


def apply_lora(params: Dict[str, Any], cfg, adapter_path: str
               ) -> Dict[str, Any]:
    """Merge a GGUF LoRA adapter into the (numpy, host-side) param tree.

    Returns the same tree with touched tensors replaced (copy-on-write —
    transcode-cache memmaps are never written through). Raises ValueError
    for adapters targeting tensors this model doesn't have or that merging
    doesn't support (MoE expert weights).
    """
    with GGUFFile(adapter_path) as f:
        if f.metadata.get("adapter.type", "lora") != "lora":
            raise ValueError(f"{adapter_path}: adapter.type "
                             f"{f.metadata.get('adapter.type')!r} is not "
                             f"a LoRA adapter")
        alpha = float(f.metadata.get("adapter.lora.alpha", 16.0))
        names = [n for n in f.tensors if n.endswith(".lora_a")]
        if not names:
            raise ValueError(f"{adapter_path}: no .lora_a tensors — not a "
                             f"LoRA adapter GGUF")
        targets = _targets(cfg)
        # the converter emits q/k in the base arch's layout — llama-family
        # interleaved rope needs the same unpermute as the base weights.
        # Adapters often omit general.architecture; fall back to the base
        # model's RAW GGUF arch (cfg.gguf_arch — qwen2/gemma normalize to
        # arch="llama" but their weights are NOT interleaved, so cfg.arch
        # would wrongly unpermute their q/k deltas).
        arch = (f.metadata.get("general.architecture")
                or cfg.gguf_arch or cfg.arch)
        if arch not in _INTERLEAVED_ROPE_ARCHES:
            T_ = lambda a: a.T
            targets["attn_q.weight"] = ("wq", T_)
            targets["attn_k.weight"] = ("wk", T_)
        layers = dict(params["layers"])
        copied = set()
        top_copied = set()
        out = dict(params)
        for name in sorted(names):
            base = name[: -len(".lora_a")]
            b_name = base + ".lora_b"
            if b_name not in f.tensors:
                raise ValueError(f"{adapter_path}: {name} has no matching "
                                 f".lora_b")
            A = _dq32(f, name)       # [r, in]
            B = _dq32(f, b_name)     # [out, r]
            if A.shape[0] != B.shape[1]:
                # tolerate transposed dumps
                if A.shape[1] == B.shape[1]:
                    A = A.T
                elif A.shape[0] == B.shape[0]:
                    B = B.T
                else:
                    raise ValueError(
                        f"{adapter_path}: rank mismatch {name} {A.shape} "
                        f"vs {b_name} {B.shape}")
            rank = A.shape[0]
            delta = (alpha / rank) * (B @ A)          # [out, in]

            if base == "token_embd.weight":
                if delta.shape != params["tok_emb"].shape:
                    raise ValueError(f"{adapter_path}: token_embd delta "
                                     f"{delta.shape} vs "
                                     f"{params['tok_emb'].shape}")
                if "tok_emb" not in top_copied:
                    out["tok_emb"] = np.array(out["tok_emb"])
                    top_copied.add("tok_emb")
                out["tok_emb"] += delta.astype(out["tok_emb"].dtype)
                continue
            if base == "output.weight":
                if "lm_head" not in params:
                    raise ValueError(f"{adapter_path}: adapter targets "
                                     f"output.weight but the model ties "
                                     f"embeddings")
                if "lm_head" not in top_copied:
                    out["lm_head"] = np.array(out["lm_head"])
                    top_copied.add("lm_head")
                out["lm_head"] += delta.T.astype(out["lm_head"].dtype)
                continue

            if not base.startswith("blk."):
                raise ValueError(f"{adapter_path}: unsupported LoRA target "
                                 f"{base!r}")
            _, idx, suffix = base.split(".", 2)
            i = int(idx)
            tgt = targets.get(suffix)
            if tgt is None:
                raise ValueError(f"{adapter_path}: unsupported LoRA target "
                                 f"{base!r} (MoE expert and bias adapters "
                                 f"are not mergeable here)")
            key, post = tgt
            if key not in layers or layers[key] is None:
                raise ValueError(f"{adapter_path}: adapter targets {base!r} "
                                 f"but the model has no {key!r}")
            if key not in copied:
                layers[key] = np.array(layers[key])  # [L, in, out] copy
                copied.add(key)
            d = post(delta)                           # [in, out]
            if d.shape != layers[key][i].shape:
                raise ValueError(f"{adapter_path}: delta for {base} is "
                                 f"{d.shape}, model expects "
                                 f"{layers[key][i].shape}")
            layers[key][i] += d.astype(layers[key].dtype)
        out["layers"] = layers
        return out
