"""ctypes loader for the native dequant kernels (native/dequant.cpp).

Builds the shared library on first use (g++ -O3) into native/build/ and
patches the hot entries of gguf.dequant's dispatch table. Everything degrades
gracefully to the numpy reference path if no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from . import dequant as DQ
from . import reader as R

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "dequant.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libtpuop_dequant.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
        for name in ("dq_f16", "dq_bf16", "dq_q4_0", "dq_q8_0", "dq_q4_k",
                     "dq_q5_k", "dq_q6_k"):
            fn = getattr(lib, name)
            fn.argtypes = [u8p, f32p, ctypes.c_int64]
            fn.restype = None
        lib.f32_to_bf16.argtypes = [f32p, u16p, ctypes.c_int64]
        lib.f32_to_bf16.restype = None
        _lib = lib
        return _lib


_NATIVE_MAP = {
    R.GGML_F16: "dq_f16",
    R.GGML_BF16: "dq_bf16",
    R.GGML_Q4_0: "dq_q4_0",
    R.GGML_Q8_0: "dq_q8_0",
    R.GGML_Q4_K: "dq_q4_k",
    R.GGML_Q5_K: "dq_q5_k",
    R.GGML_Q6_K: "dq_q6_k",
}


def native_dequantize(raw: np.ndarray, ggml_type: int) -> Optional[np.ndarray]:
    """Flat float32 output, or None if this type has no native kernel."""
    lib = load()
    if lib is None or ggml_type not in _NATIVE_MAP:
        return None
    fname = _NATIVE_MAP[ggml_type]
    be, bb = R.BLOCK_LAYOUT[ggml_type]
    raw = np.ascontiguousarray(raw)
    n_blocks = raw.nbytes // bb
    out = np.empty(n_blocks * be, np.float32)
    n_arg = raw.nbytes // 2 if be == 1 else n_blocks
    getattr(lib, fname)(raw, out, n_arg)
    return out


_installed = False


def install():
    """Patch gguf.dequant.dequantize to prefer the native path."""
    global _installed
    if _installed:
        return True
    if load() is None:
        return False
    _installed = True
    orig = DQ.dequantize

    def fast_dequantize(raw, ggml_type, shape):
        out = native_dequantize(raw, ggml_type)
        if out is not None:
            return out.reshape(shape)
        return orig(raw, ggml_type, shape)

    DQ.dequantize = fast_dequantize
    # dequantize_tensor resolves DQ.dequantize dynamically? It calls the
    # module-level name; rebinding the module attribute is enough only if it
    # looks it up at call time — patch it too for safety.
    def fast_tensor(f, t):
        return fast_dequantize(f.raw(t), t.ggml_type, t.shape)
    DQ.dequantize_tensor = fast_tensor
    return True
