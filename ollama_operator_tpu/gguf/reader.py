"""GGUF container parser (v2/v3, little-endian), zero-copy via mmap.

The reference never parses model bytes — GGUF loading happens inside the
delegated ollama image (SURVEY.md §2.2). Here it's first-class: this reader
feeds the dequantizer (gguf/dequant.py) and the transcoder
(gguf/transcode.py) that produce TPU-ready bf16/int8 arrays.

Format (little-endian):
  magic "GGUF" | version u32 | n_tensors u64 | n_kv u64
  n_kv × (key: string, value_type: u32, value)
  n_tensors × (name: string, n_dims: u32, dims u64×n (ne order: dims[0] is
               the contiguous/innermost axis), ggml_type u32, offset u64)
  padding to `general.alignment` (default 32)
  tensor data (each tensor at its offset from the start of the data section)

string = u64 length + utf-8 bytes. Array values = elem_type u32 + count u64 +
elements.
"""

from __future__ import annotations

import dataclasses
import mmap
import struct
from typing import Any, BinaryIO, Dict, List, Optional

import numpy as np

GGUF_MAGIC = b"GGUF"

# metadata value types
T_U8, T_I8, T_U16, T_I16, T_U32, T_I32, T_F32, T_BOOL, T_STR, T_ARR, \
    T_U64, T_I64, T_F64 = range(13)

_SCALAR_FMT = {T_U8: "<B", T_I8: "<b", T_U16: "<H", T_I16: "<h",
               T_U32: "<I", T_I32: "<i", T_F32: "<f", T_U64: "<Q",
               T_I64: "<q", T_F64: "<d"}

# ggml tensor dtypes (subset we support; ids from the ggml type enum)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0, GGML_Q8_1 = 8, 9
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K, GGML_Q8_K = \
    10, 11, 12, 13, 14, 15
GGML_I8, GGML_I16, GGML_I32 = 24, 25, 26
GGML_BF16 = 30
# importance-matrix ("i-quant") family; the 4-bit non-linear pair is the
# common one in modern registry tags (iq4_nl blocks like q4_0, iq4_xs
# k-quant-style super-blocks, both through the same non-linear LUT)
GGML_IQ4_NL, GGML_IQ4_XS = 20, 23
# codebook i-quants: named so unsupported-type errors are readable
# (decode needs llama.cpp's searched grid tables — see gguf/dequant.py)
GGML_IQ2_XXS, GGML_IQ2_XS, GGML_IQ3_XXS, GGML_IQ1_S = 16, 17, 18, 19
GGML_IQ3_S, GGML_IQ2_S, GGML_IQ1_M = 21, 22, 29

GGML_TYPE_NAMES = {
    GGML_F32: "F32", GGML_F16: "F16", GGML_BF16: "BF16",
    GGML_Q4_0: "Q4_0", GGML_Q4_1: "Q4_1", GGML_Q5_0: "Q5_0",
    GGML_Q5_1: "Q5_1", GGML_Q8_0: "Q8_0",
    GGML_Q2_K: "Q2_K", GGML_Q3_K: "Q3_K", GGML_Q4_K: "Q4_K",
    GGML_Q5_K: "Q5_K", GGML_Q6_K: "Q6_K",
    GGML_I8: "I8", GGML_I16: "I16", GGML_I32: "I32",
    GGML_IQ4_NL: "IQ4_NL", GGML_IQ4_XS: "IQ4_XS",
    GGML_IQ2_XXS: "IQ2_XXS", GGML_IQ2_XS: "IQ2_XS",
    GGML_IQ3_XXS: "IQ3_XXS", GGML_IQ1_S: "IQ1_S",
    GGML_IQ3_S: "IQ3_S", GGML_IQ2_S: "IQ2_S", GGML_IQ1_M: "IQ1_M",
}

# (block_elems, block_bytes) per quantised type
BLOCK_LAYOUT = {
    GGML_F32: (1, 4), GGML_F16: (1, 2), GGML_BF16: (1, 2),
    GGML_I8: (1, 1), GGML_I16: (1, 2), GGML_I32: (1, 4),
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24), GGML_Q8_0: (32, 34),
    GGML_Q2_K: (256, 84), GGML_Q3_K: (256, 110), GGML_Q4_K: (256, 144),
    GGML_Q5_K: (256, 176), GGML_Q6_K: (256, 210),
    GGML_IQ4_NL: (32, 18), GGML_IQ4_XS: (256, 136),
}


def tensor_byte_size(ggml_type: int, n_elems: int) -> int:
    be, bb = BLOCK_LAYOUT[ggml_type]
    assert n_elems % be == 0, (ggml_type, n_elems)
    return n_elems // be * bb


@dataclasses.dataclass
class GGUFTensor:
    name: str
    ggml_type: int
    ne: List[int]            # ggml order: ne[0] innermost/contiguous
    offset: int              # relative to data section start

    @property
    def n_elems(self) -> int:
        n = 1
        for d in self.ne:
            n *= d
        return n

    @property
    def shape(self) -> tuple:
        """Row-major numpy shape: reversed ne — e.g. a linear weight is
        (out_features, in_features)."""
        return tuple(reversed(self.ne))

    @property
    def nbytes(self) -> int:
        return tensor_byte_size(self.ggml_type, self.n_elems)

    @property
    def type_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"?{self.ggml_type}")


class _Cursor:
    def __init__(self, buf, pos=0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated GGUF file")
        self.pos += n
        return b

    def scalar(self, t: int):
        fmt = _SCALAR_FMT[t]
        v = struct.unpack(fmt, self.read(struct.calcsize(fmt)))[0]
        return v

    def string(self) -> str:
        n = self.scalar(T_U64)
        return self.read(n).decode("utf-8", errors="replace")

    def value(self, t: int):
        if t == T_BOOL:
            return bool(self.read(1)[0])
        if t == T_STR:
            return self.string()
        if t == T_ARR:
            et = self.scalar(T_U32)
            n = self.scalar(T_U64)
            return [self.value(et) for _ in range(n)]
        return self.scalar(t)


class GGUFFile:
    """Parsed GGUF: metadata dict + tensor directory + mmap'd data."""

    def __init__(self, path: str):
        self.path = path
        self._f: BinaryIO = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        cur = _Cursor(self._mm)
        if cur.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        self.version = cur.scalar(T_U32)
        if self.version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {self.version}")
        n_tensors = cur.scalar(T_U64)
        n_kv = cur.scalar(T_U64)
        self.metadata: Dict[str, Any] = {}
        for _ in range(n_kv):
            key = cur.string()
            t = cur.scalar(T_U32)
            self.metadata[key] = cur.value(t)
        self.tensors: Dict[str, GGUFTensor] = {}
        for _ in range(n_tensors):
            name = cur.string()
            n_dims = cur.scalar(T_U32)
            ne = [cur.scalar(T_U64) for _ in range(n_dims)]
            ggml_type = cur.scalar(T_U32)
            offset = cur.scalar(T_U64)
            self.tensors[name] = GGUFTensor(name, ggml_type, ne, offset)
        align = int(self.metadata.get("general.alignment", 32))
        self.data_start = (cur.pos + align - 1) // align * align

    # -- access -----------------------------------------------------------
    def raw(self, t: GGUFTensor) -> np.ndarray:
        """Raw quantised bytes of a tensor (zero-copy view into the mmap)."""
        start = self.data_start + t.offset
        return np.frombuffer(self._mm, np.uint8, t.nbytes, start)

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # -- conveniences -----------------------------------------------------
    @property
    def arch(self) -> str:
        return self.metadata.get("general.architecture", "unknown")

    def field(self, suffix: str, default=None):
        """Look up '<arch>.<suffix>' (the usual key shape)."""
        return self.metadata.get(f"{self.arch}.{suffix}", default)
