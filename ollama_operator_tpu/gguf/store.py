"""Transcoded-weights cache: the TPU-native analog of the reference's shared
model-blob store *contents*.

The reference's 100Gi PVC caches ollama blobs so pods skip re-downloading
(/root/reference/pkg/model/image_store.go:67-83, SURVEY.md §5
checkpoint/resume). On TPU the expensive step after download is
GGUF→bf16 dequantisation, so what we cache is the *transcoded* tensors:
one `weights.bin` (64-byte-aligned concatenated tensors, mmap-able) plus an
`index.json` {name → dtype, shape, offset}. Re-serving a model is then a
memmap + device_put, not a re-download + re-dequant.

dtypes: "f32", "f16", "bf16" (stored as raw u16), "i8", "i32".
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np
import ml_dtypes

ALIGN = 64

_DTYPES = {
    "f32": np.float32,
    "f16": np.float16,
    "bf16": ml_dtypes.bfloat16,
    "i8": np.int8,
    "i32": np.int32,
}
_NAMES = {np.dtype(v): k for k, v in _DTYPES.items()}


def dtype_name(dt) -> str:
    return _NAMES[np.dtype(dt)]


class TensorStoreWriter:
    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.path = path
        # unique tmp names: concurrent transcodes into a shared store (two
        # replicas racing, like the reference's shared PVC) each write their
        # own file; os.replace makes the last finisher win atomically
        self._tmp_suffix = f".tmp.{os.getpid()}.{os.urandom(4).hex()}"
        self._bin = open(os.path.join(path, "weights.bin" + self._tmp_suffix),
                         "wb")
        self._index: Dict[str, dict] = {}
        self._meta: Dict[str, object] = {}

    def add_meta(self, key: str, value):
        self._meta[key] = value

    def add(self, name: str, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        pos = self._bin.tell()
        pad = -pos % ALIGN
        self._bin.write(b"\x00" * pad)
        off = pos + pad
        self._bin.write(arr.tobytes())
        self._index[name] = {"dtype": dtype_name(arr.dtype),
                             "shape": list(arr.shape), "offset": off}

    def finish(self):
        self._bin.close()
        os.replace(os.path.join(self.path, "weights.bin" + self._tmp_suffix),
                   os.path.join(self.path, "weights.bin"))
        tmp = os.path.join(self.path, "index.json" + self._tmp_suffix)
        with open(tmp, "w") as f:
            json.dump({"meta": self._meta, "tensors": self._index}, f)
        os.replace(tmp, os.path.join(self.path, "index.json"))


class TensorStore:
    """Read side; zero-copy views into one mmap'd file."""

    def __init__(self, path: str):
        self.path = path
        with open(os.path.join(path, "index.json")) as f:
            idx = json.load(f)
        self.meta: Dict[str, object] = idx["meta"]
        self._index = idx["tensors"]
        self._mm = np.memmap(os.path.join(path, "weights.bin"),
                             np.uint8, mode="r")

    @staticmethod
    def exists(path: str) -> bool:
        return (os.path.exists(os.path.join(path, "index.json"))
                and os.path.exists(os.path.join(path, "weights.bin")))

    def names(self):
        return list(self._index)

    def get(self, name: str) -> np.ndarray:
        e = self._index[name]
        dt = np.dtype(_DTYPES[e["dtype"]])
        n = int(np.prod(e["shape"])) if e["shape"] else 1
        raw = self._mm[e["offset"]: e["offset"] + n * dt.itemsize]
        return raw.view(dt).reshape(e["shape"])

    def items(self) -> Iterator[Tuple[str, np.ndarray]]:
        for name in self._index:
            yield name, self.get(name)


# --- warm-snapshot persistence (scale-to-zero fast cold-start) -------------
# The AOT warm-bucket executable cache (engine.warm_snapshot()) lands next
# to the weight cache on the image-store PVC, keyed by the serving identity
# (digest + engine config + jax version/backend). Same atomic-write
# discipline as TensorStoreWriter: unique tmp name per writer, os.replace
# so concurrent drains of identical replicas race harmlessly — the last
# finisher wins a complete file, readers never see a torn one.

def warm_snapshot_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, "warm", f"{key}.warmsnap")


def save_warm_snapshot(cache_dir: str, key: str, blob: bytes) -> str:
    path = warm_snapshot_path(cache_dir, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}.{os.urandom(4).hex()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_warm_snapshot(cache_dir: str, key: str) -> Optional[bytes]:
    path = warm_snapshot_path(cache_dir, key)
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


# --- tier-2 prefix snapshots (fleet-shared hot KV prefixes) ----------------
# Engine.export_prefixes() blobs land beside the warm snapshots on the
# shared cache volume, keyed by digest + engine geometry + kv dtype
# (service.prefix_snapshot_key): a just-woken or freshly scaled replica
# imports the fleet's common system prompts into its host arena and
# answers its first shared-prefix request as a warm tier-2 hit instead of
# a cold-prefill storm. Same atomic-write discipline as the warm
# snapshots — concurrent drains race harmlessly, readers never see a
# torn file.

def prefix_snapshot_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, "prefix", f"{key}.kvsnap")


def save_prefix_snapshot(cache_dir: str, key: str, blob: bytes) -> str:
    path = prefix_snapshot_path(cache_dir, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}.{os.urandom(4).hex()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def load_prefix_snapshot(cache_dir: str, key: str) -> Optional[bytes]:
    path = prefix_snapshot_path(cache_dir, key)
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None
