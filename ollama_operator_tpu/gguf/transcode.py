"""GGUF checkpoint → ModelConfig + decoder params (TPU layout).

Replaces the in-container llama.cpp model loader the reference delegates to
(SURVEY.md §2.2 — "GGUF model loading + dequantization"). Three jobs:

1. **Config mapping**: '<arch>.*' metadata keys → models.config.ModelConfig.
2. **Tensor mapping**: llama.cpp tensor names (token_embd, blk.N.attn_q, …)
   → the decoder's param tree, layer tensors stacked on a leading axis,
   weights transposed to [in, out] so forward matmuls are plain ``x @ w``.
3. **RoPE convention fix**: arches that llama.cpp runs with *interleaved*
   rope (llama/mistral family) have their q/k projection rows un-permuted to
   the half-split layout used by ops/rope.py. The permutation commutes with
   attention (it maps rotation pairs (2i,2i+1)→(i, i+half) per head), so
   logits are unchanged — verified in tests/test_transcode.py.

Transcoded output is cached through gguf/store.py keyed by
(file digest, dtype) so restarts are mmap-loads.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np
import ml_dtypes

from ..models.config import ModelConfig
from . import dequant as DQ
from .reader import GGUFFile, GGUFTensor
from .store import TensorStore, TensorStoreWriter

# arches whose GGUF q/k weights are stored in the interleaved-rope (Meta)
# layout and need un-permuting for half-split rope (mistral/mixtral GGUFs
# carry arch "llama")
_INTERLEAVED_ROPE_ARCHES = {"llama", "granite", "command-r"}


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------

def _rope_scaling_from_gguf(f: GGUFFile) -> Dict[str, Any]:
    """rope.scaling.* metadata + the rope_freqs factor tensor → ModelConfig
    rope fields (ops/rope.scaled_inv_freq semantics, = llama.cpp's).

    llama3.1-family conversions pre-bake their low/high-freq scheme into a
    ``rope_freqs.weight`` tensor of per-frequency divisors; when present it
    takes precedence (scaled_inv_freq applies it INSTEAD of the metadata
    scheme, matching llama.cpp). Legacy keys ``rope.scale_linear`` /
    ``rope.scale`` (old GGUF exports) map onto the linear scheme.
    """
    out: Dict[str, Any] = {}
    stype = f.field("rope.scaling.type")
    factor = f.field("rope.scaling.factor")
    if factor is None:
        factor = f.field("rope.scale_linear", f.field("rope.scale"))
        if factor is not None and stype is None:
            stype = "linear"
    if stype is not None and str(stype) not in ("none", "linear", "yarn",
                                                "longrope"):
        raise NotImplementedError(
            f"unsupported GGUF rope.scaling.type {stype!r}")
    if stype is not None and str(stype) not in ("none", "longrope"):
        # longrope is carried entirely by the rope_factors_* tensors
        # (handled below) — the metadata type itself maps to no scheme
        out["rope_scaling_type"] = str(stype)
    if factor is not None and float(factor) > 0:
        out["rope_scaling"] = float(factor)
    octx = f.field("rope.scaling.original_context_length")
    if octx:
        out["rope_orig_ctx"] = int(octx)
    attn_f = f.field("rope.scaling.attn_factor")
    if attn_f:
        out["rope_attn_factor"] = float(attn_f)
    bf = f.field("rope.scaling.yarn_beta_fast")
    if bf:
        out["rope_yarn_beta_fast"] = float(bf)
    bs = f.field("rope.scaling.yarn_beta_slow")
    if bs:
        out["rope_yarn_beta_slow"] = float(bs)
    if "rope_freqs.weight" in f.tensors:
        ff = DQ.dequantize_tensor(f, f.tensors["rope_freqs.weight"])
        out["rope_freq_factors"] = tuple(
            float(x) for x in np.asarray(ff, np.float64).reshape(-1))
    elif "rope_factors_long.weight" in f.tensors:
        # phi3-family longrope: the conversion stores TWO per-frequency
        # divisor tensors; the serving context selects which applies
        # (long when the model's extended window exceeds the original
        # training window — llama.cpp picks per-graph by n_ctx, we serve
        # the GGUF's full declared window so the choice is static), and
        # cos/sin scale by the longrope magnitude factor
        # sqrt(1 + ln(ctx/orig)/ln(orig)) unless the conversion recorded
        # an explicit attn_factor (transformers Phi3 semantics)
        ctx = int(f.field("context_length", 4096))
        octx2 = int(octx or ctx)
        name = ("rope_factors_long.weight" if ctx > octx2
                else "rope_factors_short.weight")
        ff = DQ.dequantize_tensor(f, f.tensors[name])
        out["rope_freq_factors"] = tuple(
            float(x) for x in np.asarray(ff, np.float64).reshape(-1))
        if not out.get("rope_attn_factor") and ctx > octx2:
            out["rope_attn_factor"] = float(
                np.sqrt(1.0 + np.log(ctx / octx2) / np.log(octx2)))
    elif str(stype or "") == "longrope":
        raise ValueError(
            "rope.scaling.type is longrope but the GGUF carries no "
            "rope_factors_long/short tensors — refusing to serve with "
            "unscaled rope (outputs past the original window would be "
            "garbage)")
    # yarn needs the original window; older exports omit it — fall back to
    # context_length / factor (the convention llama.cpp applies)
    if (out.get("rope_scaling_type") == "yarn"
            and not out.get("rope_orig_ctx")):
        ctx = int(f.field("context_length", 4096))
        out["rope_orig_ctx"] = max(1, int(ctx / out.get("rope_scaling",
                                                        1.0)))
    return out


def config_from_gguf(f: GGUFFile) -> ModelConfig:
    arch = f.arch
    n_heads = int(f.field("attention.head_count"))
    dim = int(f.field("embedding_length"))
    head_dim = int(f.field("attention.key_length", dim // n_heads))
    kv = f.field("attention.head_count_kv", n_heads)
    if isinstance(kv, list):
        kv = kv[0]
    base = dict(
        gguf_arch=arch,   # raw source arch, kept for rope-layout decisions
        vocab_size=len(f.metadata["tokenizer.ggml.tokens"]),
        dim=dim,
        n_layers=int(f.field("block_count")),
        n_heads=n_heads,
        n_kv_heads=int(kv),
        head_dim=head_dim,
        ffn_dim=int(f.field("feed_forward_length")),
        max_seq_len=int(f.field("context_length", 4096)),
        rope_theta=float(f.field("rope.freq_base", 10000.0)),
        sliding_window=int(f.field("attention.sliding_window", 0) or 0),
    )
    base.update(_rope_scaling_from_gguf(f))
    eps = f.field("attention.layer_norm_rms_epsilon")
    if eps is not None:
        base["norm_eps"] = float(eps)
    n_exp = int(f.field("expert_count", 0) or 0)
    if n_exp:  # mixtral family (GGUF arch is still "llama")
        base["n_experts"] = n_exp
        base["n_experts_used"] = int(f.field("expert_used_count", 2))

    if arch in ("llama", "mistral"):
        cfg = ModelConfig(arch="llama", **base)
    elif arch == "qwen2":
        cfg = ModelConfig(arch="llama", attn_bias=True, **base)
    elif arch == "qwen3":
        # qwen2 minus the qkv bias, plus per-head RMS on q/k
        cfg = ModelConfig(arch="llama", qk_norm=True, **base)
    elif arch == "qwen2moe":
        # qwen2-style attention (qkv bias) + sparse MoE with a SHARED
        # gated expert (sigmoid-gated, runs for every token) and
        # UN-renormalised top-k router gates (norm_topk_prob=false —
        # unlike mixtral/qwen3moe)
        if not base.get("n_experts"):
            raise ValueError("qwen2moe GGUF without expert_count metadata")
        if f.field("expert_used_count") is None:
            raise ValueError(
                "qwen2moe GGUF without expert_used_count metadata")
        shared = int(f.field("expert_shared_feed_forward_length", 0) or 0)
        cfg = ModelConfig(arch="llama", attn_bias=True, moe_renorm=False,
                          n_shared_ffn=shared, **base)
    elif arch == "qwen3moe":
        # qwen3 attention (qk norms, no bias) + sparse MoE MLPs
        # (qwen3:30b-a3b etc.). Router convention: softmax renormalised
        # over the selected top-k (norm_topk_prob) — the same math the
        # mixtral path runs (_moe_gates). Expert FFN dims come from the
        # tensors themselves (expert_feed_forward_length metadata is
        # informational here).
        if not base.get("n_experts"):
            raise ValueError("qwen3moe GGUF without expert_count metadata")
        if f.field("expert_used_count") is None:
            # the generic default (2, mixtral's top-k) would silently
            # route an 8-experts-per-token model at top-2 — degraded
            # outputs with no error; require the real value
            raise ValueError(
                "qwen3moe GGUF without expert_used_count metadata")
        cfg = ModelConfig(arch="llama", qk_norm=True, **base)
    elif arch == "gemma":
        cfg = ModelConfig(arch="llama", act="gelu_tanh", emb_scale=True,
                          tie_embeddings=True, norm_weight_offset=1.0, **base)
    elif arch == "gemma2":
        if not base.get("sliding_window"):
            # alternation is part of the arch; a gguf without the window
            # metadata must fail loudly, not silently serve full attention
            raise ValueError(
                "gemma2 GGUF lacks attention.sliding_window metadata")
        # llama.cpp writes no query_pre_attn_scalar key; its graph builder
        # switches on model type — 27B (the only 46-layer gemma2) scales
        # by 1/sqrt(n_embd/n_head), 2B/9B by 1/sqrt(head_dim)
        qpas = float(f.field("attention.query_pre_attn_scalar", 0) or 0)
        if not qpas and base["n_layers"] == 46:
            qpas = base["dim"] / base["n_heads"]
        cfg = ModelConfig(
            arch="llama", act="gelu_tanh", emb_scale=True,
            tie_embeddings=True, norm_weight_offset=1.0, post_norms=True,
            altern_sliding=True,
            attn_softcap=float(f.field("attn_logit_softcapping", 50.0)),
            logit_softcap=float(f.field("final_logit_softcapping", 30.0)),
            attn_scale=qpas,
            **base)
    elif arch == "command-r":
        # cohere command-r: parallel attn+mlp block sharing one BIAS-FREE
        # LayerNorm, gated-silu MLP, tied embeddings, logits MULTIPLIED
        # by logit_scale (our field divides — store the reciprocal), and
        # interleaved-rope weight storage (the same row layout llama
        # conversions use, so the shared _unpermute_rope applies). The
        # qk-norm 08-2024 refresh stores per-head norms in the
        # interleaved layout — unsupported until mapped.
        base["norm_eps"] = float(f.field("attention.layer_norm_epsilon",
                                         1e-5))
        v = f.field("logit_scale")
        if not v:
            # the model TRAINS with logits scaled (~0.0625); serving
            # unscaled logits is near-argmax garbage with no diagnostic
            raise ValueError("command-r GGUF without logit_scale metadata")
        if "blk.0.attn_q_norm.weight" in f.tensors:
            raise NotImplementedError(
                "command-r variants with q/k norms are not supported yet")
        cfg = ModelConfig(arch="llama", norm_type="layernorm",
                          norm_bias=False, parallel_block=True,
                          tie_embeddings=True,
                          logit_scale=1.0 / float(v), **base)
    elif arch == "granite":
        # granite3 dense (2b/8b): llama block + four scalar multipliers
        # (embedding/attention/residual/logits) the conversion records
        # as granite.*.scale keys; q/k stored llama-permuted
        extra = {}
        for key, fld in (("attention.scale", "attn_scale_mult"),
                         ("embedding.scale", "emb_multiplier"),
                         ("residual.scale", "residual_multiplier"),
                         ("logit_scale", "logit_scale")):
            v = f.field(key)
            if v:
                extra[fld] = float(v)
        cfg = ModelConfig(arch="llama", **extra, **base)
    elif arch == "gemma3":
        if not base.get("sliding_window"):
            raise ValueError(
                "gemma3 GGUF lacks attention.sliding_window metadata")
        # pattern-6 alternation (every 6th layer full attention), qk RMS
        # norms (gemma (w−1) storage), DUAL rope: sliding layers at the
        # local 10k theta, full layers at the global theta (metadata
        # freq_base, 1e6) with any linear context scaling. The local
        # theta and the 6-pattern are architecture constants (llama.cpp
        # hardcodes both); query_pre_attn_scalar defaults to gemma3's 256.
        # query_pre_attn_scalar: llama.cpp writes no key (same situation
        # as gemma2); 1B/4B/12B use 256, but 27B — the only 62-layer
        # gemma3 — uses dim/n_heads (5376/32 = 168). A silent 256
        # fallback there would mis-scale every attention layer.
        qpas = float(f.field("attention.query_pre_attn_scalar", 0) or 0)
        if not qpas:
            qpas = (base["dim"] / base["n_heads"]
                    if base["n_layers"] == 62 else 256.0)
        cfg = ModelConfig(
            arch="llama", act="gelu_tanh", emb_scale=True,
            tie_embeddings=True, norm_weight_offset=1.0, post_norms=True,
            altern_sliding=True, sliding_pattern=6, qk_norm=True,
            rope_local_theta=10000.0, attn_scale=qpas,
            **base)
    elif arch == "phi3":
        # phi3/phi3.5 (mini 3.8B MHA, medium GQA): llama-family block —
        # RMSNorm, gated-silu MLP, full rotary — converted with FUSED
        # attn_qkv and gate+up ffn_up tensors (split in load_params) and
        # longrope context extension (rope_factors_long/short tensors →
        # rope_freq_factors + the magnitude factor,
        # _rope_scaling_from_gguf)
        if not base.get("sliding_window") and base["max_seq_len"] <= 4096:
            # older conversions of the 4k tags omit the window key
            # (llama.cpp hardcodes phi3's n_swa for the same reason);
            # serving full attention past the trained 2047 window would
            # silently diverge. The 128k tags set sliding_window >= ctx
            # in HF config (i.e. effectively none) — only short-context
            # models get the default.
            base["sliding_window"] = 2047
        cfg = ModelConfig(arch="llama", **base)
    elif arch == "phi2":
        base["norm_eps"] = float(f.field("attention.layer_norm_epsilon",
                                         1e-5))
        rot = int(f.field("rope.dimension_count", head_dim))
        cfg = ModelConfig(arch="phi2", norm_type="layernorm",
                          mlp_type="plain", act="gelu_tanh",
                          parallel_block=True, attn_bias=True, out_bias=True,
                          rotary_pct=rot / head_dim, **base)
    elif arch == "starcoder2":
        # starcoder2 3/7/15B: sequential pre-LN block (NOT phi2's
        # parallel one), LayerNorm + biases everywhere, plain
        # gelu-tanh MLP, full NEOX rotary, sliding-window attention
        base["norm_eps"] = float(f.field("attention.layer_norm_epsilon",
                                         1e-5))
        cfg = ModelConfig(arch="llama", norm_type="layernorm",
                          mlp_type="plain", act="gelu_tanh",
                          attn_bias=True, out_bias=True, **base)
    else:
        raise NotImplementedError(f"unsupported GGUF architecture {arch!r}")
    if not cfg.tie_embeddings and "output.weight" not in f.tensors:
        # any arch may tie the head to the embedding (llama3.2, qwen2
        # small variants): llama.cpp falls back to token_embd when the
        # output tensor is absent — arch-generic, not a qwen special case
        import dataclasses
        cfg = dataclasses.replace(cfg, tie_embeddings=True)
    return cfg.validate()


# ---------------------------------------------------------------------------
# tensors
# ---------------------------------------------------------------------------

def _unpermute_rope(w: np.ndarray, n_heads: int) -> np.ndarray:
    """[out, in] q/k weight: interleaved-pair rows → half-split rows."""
    out, inn = w.shape
    hd = out // n_heads
    return (w.reshape(n_heads, hd // 2, 2, inn)
             .transpose(0, 2, 1, 3)
             .reshape(out, inn))


def _unpermute_rope_vec(b: np.ndarray, n_heads: int) -> np.ndarray:
    out = b.shape[0]
    hd = out // n_heads
    return (b.reshape(n_heads, hd // 2, 2)
             .transpose(0, 2, 1)
             .reshape(out))


def _dq(f: GGUFFile, name: str) -> np.ndarray:
    return DQ.dequantize_tensor(f, f.tensors[name])


def load_params(f: GGUFFile, cfg: Optional[ModelConfig] = None,
                dtype=ml_dtypes.bfloat16) -> Dict[str, Any]:
    """Dequantise + remap every tensor into the decoder param tree (numpy,
    host memory)."""
    from . import native
    native.install()  # no-op when unavailable; numpy path is the fallback
    cfg = cfg or config_from_gguf(f)
    unpermute = f.arch in _INTERLEAVED_ROPE_ARCHES
    L = cfg.n_layers

    def cast(a):
        return np.ascontiguousarray(a, dtype=dtype)

    params: Dict[str, Any] = {
        "tok_emb": cast(_dq(f, "token_embd.weight")),
        "out_norm_w": cast(_dq(f, "output_norm.weight")),
    }
    if cfg.norm_type == "layernorm" and cfg.norm_bias:
        params["out_norm_b"] = cast(_dq(f, "output_norm.bias"))
    if not cfg.tie_embeddings:
        params["lm_head"] = cast(_dq(f, "output.weight").T)
    if cfg.out_bias and "output.bias" in f.tensors:
        params["lm_head_b"] = cast(_dq(f, "output.bias"))

    def stack(fmt: str, post=None, required=True):
        name0 = fmt.format(0)
        if name0 not in f.tensors:
            if required:
                raise KeyError(f"missing tensor {name0}")
            return None
        arrs = []
        for i in range(L):
            a = _dq(f, fmt.format(i))
            if post is not None:
                a = post(a)
            arrs.append(cast(a))
        return np.stack(arrs)

    H, KvH = cfg.n_heads, cfg.n_kv_heads
    unp_q = (lambda a: _unpermute_rope(a, H).T) if unpermute else (lambda a: a.T)
    unp_k = (lambda a: _unpermute_rope(a, KvH).T) if unpermute else (lambda a: a.T)
    T_ = lambda a: a.T

    layers: Dict[str, Any] = {
        "attn_norm_w": stack("blk.{}.attn_norm.weight"),
        "wo": stack("blk.{}.attn_output.weight", T_),
    }
    fused_gate_up = (cfg.mlp_type == "gated" and not cfg.n_experts
                     and "blk.0.ffn_gate.weight" not in f.tensors)
    if not cfg.n_experts:
        if fused_gate_up:
            # phi3-family: ffn_up holds [gate; up] fused ([2F, D] —
            # HF gate_up_proj order, kept by the conversion); split so
            # the decoder's separate-projection path serves unchanged
            F = cfg.ffn_dim
            gs, us = [], []
            for i in range(L):
                w = _dq(f, f"blk.{i}.ffn_up.weight")
                assert w.shape[0] == 2 * F, (
                    f"fused ffn_up rows {w.shape[0]} != 2*ffn_dim {2 * F}")
                gs.append(cast(w[:F].T))
                us.append(cast(w[F:].T))
            layers["w_gate"] = np.stack(gs)
            layers["w_up"] = np.stack(us)
        else:
            layers["w_up"] = stack("blk.{}.ffn_up.weight", T_)
        layers["w_down"] = stack("blk.{}.ffn_down.weight", T_)
    if "blk.0.attn_qkv.weight" in f.tensors:  # fused qkv (phi2)
        q_dim, kv_dim = cfg.q_dim, cfg.kv_dim
        wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
        for i in range(L):
            w = _dq(f, f"blk.{i}.attn_qkv.weight")  # [q+2kv, D]
            wq.append(cast(w[:q_dim].T))
            wk.append(cast(w[q_dim:q_dim + kv_dim].T))
            wv.append(cast(w[q_dim + kv_dim:].T))
            if f"blk.{i}.attn_qkv.bias" in f.tensors:
                b = _dq(f, f"blk.{i}.attn_qkv.bias")
                bq.append(cast(b[:q_dim]))
                bk.append(cast(b[q_dim:q_dim + kv_dim]))
                bv.append(cast(b[q_dim + kv_dim:]))
        layers["wq"], layers["wk"], layers["wv"] = map(np.stack, (wq, wk, wv))
        if bq:
            layers["bq"], layers["bk"], layers["bv"] = map(
                np.stack, (bq, bk, bv))
    else:
        layers["wq"] = stack("blk.{}.attn_q.weight", unp_q)
        layers["wk"] = stack("blk.{}.attn_k.weight", unp_k)
        layers["wv"] = stack("blk.{}.attn_v.weight", T_)
        if cfg.attn_bias:
            unp_bq = ((lambda a: _unpermute_rope_vec(a, H))
                      if unpermute else None)
            unp_bk = ((lambda a: _unpermute_rope_vec(a, KvH))
                      if unpermute else None)
            layers["bq"] = stack("blk.{}.attn_q.bias", unp_bq)
            layers["bk"] = stack("blk.{}.attn_k.bias", unp_bk)
            layers["bv"] = stack("blk.{}.attn_v.bias")

    if cfg.norm_type == "layernorm" and cfg.norm_bias:
        layers["attn_norm_b"] = stack("blk.{}.attn_norm.bias")
    if not cfg.parallel_block:
        layers["mlp_norm_w"] = stack("blk.{}.ffn_norm.weight")
        if cfg.norm_type == "layernorm" and cfg.norm_bias:
            layers["mlp_norm_b"] = stack("blk.{}.ffn_norm.bias")
    if cfg.n_experts:
        # mixtral: router ffn_gate_inp [E, D] → [D, E]; merged expert
        # tensors ffn_{gate,up}_exps [E, F, D] → [E, D, F] and
        # ffn_down_exps [E, D, F] → [E, F, D] (per-expert transpose to
        # [in, out], matching the dense path's x @ w convention)
        eT = lambda a: a.transpose(0, 2, 1)
        layers["router"] = stack("blk.{}.ffn_gate_inp.weight", T_)
        if "blk.0.ffn_gate_exps.weight" in f.tensors:
            layers["we_gate"] = stack("blk.{}.ffn_gate_exps.weight", eT)
            layers["we_up"] = stack("blk.{}.ffn_up_exps.weight", eT)
            layers["we_down"] = stack("blk.{}.ffn_down_exps.weight", eT)
        else:  # legacy per-expert split tensors (pre-merge GGUFs)
            def stack_experts(fmt: str):
                out = []
                for i in range(L):
                    es = [cast(_dq(f, fmt.format(i, e)).T)
                          for e in range(cfg.n_experts)]
                    out.append(np.stack(es))
                return np.stack(out)
            layers["we_gate"] = stack_experts("blk.{}.ffn_gate.{}.weight")
            layers["we_up"] = stack_experts("blk.{}.ffn_up.{}.weight")
            layers["we_down"] = stack_experts("blk.{}.ffn_down.{}.weight")
        if "blk.0.ffn_gate_shexp.weight" in f.tensors:
            # qwen2moe shared expert + its sigmoid gate projection
            layers["we_sh_gate"] = stack("blk.{}.ffn_gate_shexp.weight", T_)
            layers["we_sh_up"] = stack("blk.{}.ffn_up_shexp.weight", T_)
            layers["we_sh_down"] = stack("blk.{}.ffn_down_shexp.weight", T_)
            layers["sh_gate"] = stack("blk.{}.ffn_gate_inp_shexp.weight", T_)
    elif cfg.mlp_type == "gated" and not fused_gate_up:
        layers["w_gate"] = stack("blk.{}.ffn_gate.weight", T_)
    if cfg.out_bias:
        layers["bo"] = stack("blk.{}.attn_output.bias")
        layers["b_up"] = stack("blk.{}.ffn_up.bias")
        layers["b_down"] = stack("blk.{}.ffn_down.bias")
    if cfg.post_norms:
        # llama.cpp gguf-py names: ATTN_POST_NORM = post_attention_norm,
        # FFN_POST_NORM = post_ffw_norm
        layers["post_attn_norm_w"] = (
            stack("blk.{}.post_attention_norm.weight")
            if "blk.0.post_attention_norm.weight" in f.tensors
            else stack("blk.{}.attn_post_norm.weight"))
        layers["post_ffw_norm_w"] = (
            stack("blk.{}.post_ffw_norm.weight")
            if "blk.0.post_ffw_norm.weight" in f.tensors
            else stack("blk.{}.ffn_post_norm.weight"))
    if cfg.qk_norm:
        layers["q_norm_w"] = stack("blk.{}.attn_q_norm.weight")
        layers["k_norm_w"] = stack("blk.{}.attn_k_norm.weight")

    params["layers"] = {k: v for k, v in layers.items() if v is not None}
    return params


# ---------------------------------------------------------------------------
# cached transcode
# ---------------------------------------------------------------------------

def _flatten(params: Dict[str, Any]):
    for k, v in params.items():
        if k == "layers":
            for lk, lv in v.items():
                yield f"layers/{lk}", lv
        else:
            yield k, v


def _unflatten(items) -> Dict[str, Any]:
    out: Dict[str, Any] = {"layers": {}}
    for k, v in items:
        if k.startswith("layers/"):
            out["layers"][k.split("/", 1)[1]] = v
        else:
            out[k] = v
    return out


def transcode_to_store(gguf_path: str, store_path: str,
                       dtype=ml_dtypes.bfloat16) -> Tuple[ModelConfig, dict]:
    """GGUF → TensorStore on disk. Returns (cfg, tokenizer metadata)."""
    with GGUFFile(gguf_path) as f:
        cfg = config_from_gguf(f)
        params = load_params(f, cfg, dtype)
        tok_md = {k: v for k, v in f.metadata.items()
                  if k.startswith("tokenizer.")}
        w = TensorStoreWriter(store_path)
        w.add_meta("config", cfg.__dict__)
        w.add_meta("tokenizer", tok_md)
        w.add_meta("source", os.path.basename(gguf_path))
        for name, arr in _flatten(params):
            w.add(name, arr)
        w.finish()
    return cfg, tok_md


def load_from_store(store_path: str) -> Tuple[ModelConfig, Dict[str, Any], dict]:
    """mmap-load a cached transcode. Returns (cfg, params, tokenizer md)."""
    ts = TensorStore(store_path)
    cfg = ModelConfig(**ts.meta["config"]).validate()
    params = _unflatten(ts.items())
    return cfg, params, ts.meta["tokenizer"]


def content_fingerprint(path: str) -> str:
    """Cheap content digest for cache keying: sha256 over (size, head 1MiB,
    tail 1MiB). Full-file hashing of a 40GB GGUF would dominate transcode
    time; registry-pulled blobs are already content-addressed by their layer
    digest, which callers should prefer via the ``digest=`` argument."""
    import hashlib
    h = hashlib.sha256()
    size = os.path.getsize(path)
    h.update(str(size).encode())
    with open(path, "rb") as f:
        h.update(f.read(1 << 20))
        if size > (1 << 20):
            f.seek(max(size - (1 << 20), 0))
            h.update(f.read(1 << 20))
    return h.hexdigest()[:24]


def load_model(gguf_path: str, cache_dir: Optional[str] = None,
               dtype=ml_dtypes.bfloat16, digest: Optional[str] = None):
    """The serving entry point: transcode once, mmap afterwards.

    ``digest``: content digest of the GGUF (e.g. the registry layer sha256);
    computed from the file when omitted. Keys the cache so a replaced model
    file at the same path never serves stale weights.
    """
    if cache_dir is None:
        with GGUFFile(gguf_path) as f:
            cfg = config_from_gguf(f)
            params = load_params(f, cfg, dtype)
            tok_md = {k: v for k, v in f.metadata.items()
                      if k.startswith("tokenizer.")}
        return cfg, params, tok_md
    from .store import TensorStore as TS
    if digest is None:
        digest = content_fingerprint(gguf_path)
    key = f"{digest}.{np.dtype(dtype).name}"
    store_path = os.path.join(cache_dir, key)
    if not TS.exists(store_path):
        transcode_to_store(gguf_path, store_path, dtype)
    return load_from_store(store_path)


# ---------------------------------------------------------------------------
# vision tower (llava mmproj GGUF, arch "clip")
# ---------------------------------------------------------------------------

def vision_config_from_gguf(f: GGUFFile):
    """clip-arch mmproj metadata → models.vision.VisionConfig. The projector
    output width comes from the mm.2 tensor (the LLM's embedding dim)."""
    from ..models.vision import VisionConfig
    g = lambda k, d=None: f.metadata.get("clip.vision." + k, d)
    mm2 = f.tensors.get("mm.2.weight")
    proj_dim = int(mm2.shape[0]) if mm2 is not None else int(
        f.metadata.get("clip.vision.projection_dim", 4096))
    return VisionConfig(
        image_size=int(g("image_size", 336)),
        patch_size=int(g("patch_size", 14)),
        width=int(g("embedding_length", 1024)),
        n_layers=int(g("block_count", 24)),
        n_heads=int(g("attention.head_count", 16)),
        ffn_dim=int(g("feed_forward_length", 4096)),
        norm_eps=float(g("attention.layer_norm_epsilon", 1e-5)),
        proj_dim=proj_dim,
        # llama.cpp's llava converter trims the skipped final CLIP layer
        # before export (block_count already reflects the penultimate
        # selection), so a GGUF-loaded tower runs ALL file layers
        select_layer=-1,
    ).validate()


def load_vision_params(f: GGUFFile, vcfg=None,
                       dtype=np.float32) -> Dict[str, Any]:
    """mmproj tensors → models.vision param tree.

    llama.cpp's clip naming (v.patch_embd, v.blk.N.*, mm.0/mm.2); the two
    ffn tensors are mapped by SHAPE, not name, because historical mmproj
    exports disagree on which of ffn_up/ffn_down is the W→F projection.
    """
    vcfg = vcfg or vision_config_from_gguf(f)
    L, W, F = vcfg.n_layers, vcfg.width, vcfg.ffn_dim
    cast = lambda a: np.ascontiguousarray(a, dtype=dtype)

    pe = _dq(f, "v.patch_embd.weight")          # [W, 3, P, P]
    params: Dict[str, Any] = {
        "patch_emb": cast(pe.reshape(W, -1).T),  # → [3*P*P, W], (c,i,j)
        "class_emb": cast(_dq(f, "v.class_embd")),
        "pos_emb": cast(_dq(f, "v.position_embd.weight")),
        "pre_ln_w": cast(_dq(f, "v.pre_ln.weight")),
        "pre_ln_b": cast(_dq(f, "v.pre_ln.bias")),
        "mm_0": cast(_dq(f, "mm.0.weight").T),
        "mm_0_b": cast(_dq(f, "mm.0.bias")),
        "mm_2": cast(_dq(f, "mm.2.weight").T),
        "mm_2_b": cast(_dq(f, "mm.2.bias")),
    }

    def stackv(fmt, post=None):
        arrs = []
        for i in range(L):
            a = _dq(f, fmt.format(i))
            arrs.append(cast(post(a) if post else a))
        return np.stack(arrs)

    T_ = lambda a: a.T
    layers = {
        "ln1_w": stackv("v.blk.{}.ln1.weight"),
        "ln1_b": stackv("v.blk.{}.ln1.bias"),
        "ln2_w": stackv("v.blk.{}.ln2.weight"),
        "ln2_b": stackv("v.blk.{}.ln2.bias"),
        "wq": stackv("v.blk.{}.attn_q.weight", T_),
        "bq": stackv("v.blk.{}.attn_q.bias"),
        "wk": stackv("v.blk.{}.attn_k.weight", T_),
        "bk": stackv("v.blk.{}.attn_k.bias"),
        "wv": stackv("v.blk.{}.attn_v.weight", T_),
        "bv": stackv("v.blk.{}.attn_v.bias"),
        "wo": stackv("v.blk.{}.attn_out.weight", T_),
        "bo": stackv("v.blk.{}.attn_out.bias"),
    }
    # ffn tensors by shape: the W→F one is fc1 (our w_up)
    up0 = _dq(f, "v.blk.0.ffn_up.weight")
    if up0.shape == (F, W):        # stored [out, in] = [F, W] → fc1
        layers["w_up"] = stackv("v.blk.{}.ffn_up.weight", T_)
        layers["b_up"] = stackv("v.blk.{}.ffn_up.bias")
        layers["w_down"] = stackv("v.blk.{}.ffn_down.weight", T_)
        layers["b_down"] = stackv("v.blk.{}.ffn_down.bias")
    else:                           # swapped convention
        layers["w_up"] = stackv("v.blk.{}.ffn_down.weight", T_)
        layers["b_up"] = stackv("v.blk.{}.ffn_down.bias")
        layers["w_down"] = stackv("v.blk.{}.ffn_up.weight", T_)
        layers["b_down"] = stackv("v.blk.{}.ffn_up.bias")
    params["layers"] = layers
    return params


# ---------------------------------------------------------------------------
# encoder (embedding) models — BERT family
# ---------------------------------------------------------------------------

ENCODER_ARCHES = ("bert",)


def is_encoder_arch(arch: str) -> bool:
    """True for embedding-only architectures (served without an Engine —
    runtime/service.EmbeddingModel; the reference serves these images
    through llama.cpp's BERT path in the delegated container)."""
    return arch in ENCODER_ARCHES


def encoder_config_from_gguf(f: GGUFFile):
    """'<arch>.*' metadata → models.encoder.EncoderConfig (bert family)."""
    from ..models.encoder import EncoderConfig
    pooling = int(f.field("pooling_type", 1) or 1)
    if pooling not in (1, 2):
        # 1 = mean, 2 = CLS (bge-*); anything else (none/last/rank) has
        # no honest fallback — wrong pooling is silently wrong embeddings
        raise NotImplementedError(
            f"unsupported bert pooling_type {pooling} (mean=1 and cls=2 "
            f"are implemented)")
    return EncoderConfig(
        vocab_size=len(f.metadata["tokenizer.ggml.tokens"]),
        dim=int(f.field("embedding_length")),
        n_layers=int(f.field("block_count")),
        n_heads=int(f.field("attention.head_count")),
        ffn_dim=int(f.field("feed_forward_length")),
        max_seq_len=int(f.field("context_length", 512)),
        norm_eps=float(f.field("attention.layer_norm_epsilon", 1e-12)),
        pooling={1: "mean", 2: "cls"}[pooling],
        arch=f.arch)


def load_encoder_params(f: GGUFFile, cfg=None,
                        dtype=np.float32) -> Dict[str, Any]:
    """BERT tensor names (llama.cpp layout: attn_output_norm = post-attn
    LN, layer_output_norm = post-FFN LN) → models.encoder param tree."""
    cfg = cfg or encoder_config_from_gguf(f)
    L = cfg.n_layers

    def cast(a):
        return np.ascontiguousarray(a, dtype=dtype)

    def stack(fmt: str, post=None):
        arrs = []
        for i in range(L):
            a = _dq(f, fmt.format(i))
            arrs.append(cast(post(a) if post else a))
        return np.stack(arrs)

    T_ = lambda a: a.T  # noqa: E731
    params: Dict[str, Any] = {
        "tok_emb": cast(_dq(f, "token_embd.weight")),
        "pos_emb": cast(_dq(f, "position_embd.weight")),
        "type_emb": cast(_dq(f, "token_types.weight")),
        "emb_norm_w": cast(_dq(f, "token_embd_norm.weight")),
        "emb_norm_b": cast(_dq(f, "token_embd_norm.bias")),
        "layers": {
            "wq": stack("blk.{}.attn_q.weight", T_),
            "bq": stack("blk.{}.attn_q.bias"),
            "wk": stack("blk.{}.attn_k.weight", T_),
            "bk": stack("blk.{}.attn_k.bias"),
            "wv": stack("blk.{}.attn_v.weight", T_),
            "bv": stack("blk.{}.attn_v.bias"),
            "wo": stack("blk.{}.attn_output.weight", T_),
            "bo": stack("blk.{}.attn_output.bias"),
            "attn_norm_w": stack("blk.{}.attn_output_norm.weight"),
            "attn_norm_b": stack("blk.{}.attn_output_norm.bias"),
            "w_up": stack("blk.{}.ffn_up.weight", T_),
            "b_up": stack("blk.{}.ffn_up.bias"),
            "w_down": stack("blk.{}.ffn_down.weight", T_),
            "b_down": stack("blk.{}.ffn_down.bias"),
            "ffn_norm_w": stack("blk.{}.layer_output_norm.weight"),
            "ffn_norm_b": stack("blk.{}.layer_output_norm.bias"),
        },
    }
    return params
