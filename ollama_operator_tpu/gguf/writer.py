"""Minimal GGUF v3 writer.

Used by tests (synthetic checkpoints for round-trip/dequant validation and
the fake registry) and by tools that re-export models. Layout matches
reader.py's documentation of the format.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

from . import reader as R


def _pack_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<Q", len(b)) + b


def _pack_value(v: Any) -> bytes:
    """Infer the GGUF type tag from the python value."""
    if isinstance(v, bool):
        return struct.pack("<I", R.T_BOOL) + struct.pack("<B", int(v))
    if isinstance(v, int):
        if v < 0:
            return struct.pack("<I", R.T_I64) + struct.pack("<q", v)
        return struct.pack("<I", R.T_U32 if v < 2**32 else R.T_U64) + (
            struct.pack("<I", v) if v < 2**32 else struct.pack("<Q", v))
    if isinstance(v, float):
        return struct.pack("<I", R.T_F32) + struct.pack("<f", v)
    if isinstance(v, str):
        return struct.pack("<I", R.T_STR) + _pack_string(v)
    if isinstance(v, (list, tuple, np.ndarray)):
        v = list(v)
        if not v:
            return (struct.pack("<I", R.T_ARR) + struct.pack("<I", R.T_U32) +
                    struct.pack("<Q", 0))
        body = b""
        if isinstance(v[0], str):
            et = R.T_STR
            for e in v:
                body += _pack_string(e)
        elif isinstance(v[0], (float, np.floating)):
            et = R.T_F32
            body = np.asarray(v, np.float32).tobytes()
        else:
            et = R.T_I32
            body = np.asarray(v, np.int32).tobytes()
        return (struct.pack("<I", R.T_ARR) + struct.pack("<I", et) +
                struct.pack("<Q", len(v)) + body)
    raise TypeError(f"cannot encode metadata value {v!r}")


class GGUFWriter:
    def __init__(self, path: str, alignment: int = 32):
        self.path = path
        self.alignment = alignment
        self.metadata: Dict[str, Any] = {"general.alignment": alignment}
        # (name, ne, ggml_type, raw_bytes)
        self._tensors: List[Tuple[str, List[int], int, bytes]] = []

    def add_meta(self, key: str, value: Any):
        self.metadata[key] = value

    def add_tensor_f32(self, name: str, arr: np.ndarray):
        arr = np.ascontiguousarray(arr, np.float32)
        ne = list(reversed(arr.shape))
        self._tensors.append((name, ne, R.GGML_F32, arr.tobytes()))

    def add_tensor_f16(self, name: str, arr: np.ndarray):
        arr = np.ascontiguousarray(arr, np.float16)
        ne = list(reversed(arr.shape))
        self._tensors.append((name, ne, R.GGML_F16, arr.tobytes()))

    def add_tensor_raw(self, name: str, shape: tuple, ggml_type: int,
                       raw: bytes):
        """shape is the numpy row-major shape (reversed into ne)."""
        ne = list(reversed(shape))
        n = int(np.prod(shape))
        assert len(raw) == R.tensor_byte_size(ggml_type, n)
        self._tensors.append((name, ne, ggml_type, raw))

    def write(self):
        out = bytearray()
        out += R.GGUF_MAGIC
        out += struct.pack("<I", 3)
        out += struct.pack("<Q", len(self._tensors))
        out += struct.pack("<Q", len(self.metadata))
        for k, v in self.metadata.items():
            out += _pack_string(k)
            out += _pack_value(v)
        # tensor directory with aligned offsets
        offset = 0
        offsets = []
        for name, ne, t, raw in self._tensors:
            offset = -(-offset // self.alignment) * self.alignment
            offsets.append(offset)
            offset += len(raw)
        for (name, ne, t, raw), off in zip(self._tensors, offsets):
            out += _pack_string(name)
            out += struct.pack("<I", len(ne))
            for d in ne:
                out += struct.pack("<Q", d)
            out += struct.pack("<I", t)
            out += struct.pack("<Q", off)
        pad = -len(out) % self.alignment
        out += b"\x00" * pad
        data_start = len(out)
        for (name, ne, t, raw), off in zip(self._tensors, offsets):
            cur = len(out) - data_start
            out += b"\x00" * (off - cur)
            out += raw
        with open(self.path, "wb") as f:
            f.write(bytes(out))


# ---------------------------------------------------------------------------
# reference quantisers (legacy formats) — used in tests and for int8 export
# ---------------------------------------------------------------------------

def quantize_q8_0(x: np.ndarray) -> bytes:
    x = np.ascontiguousarray(x, np.float32).reshape(-1, 32)
    amax = np.abs(x).max(axis=1, keepdims=True)
    d = (amax / 127.0).astype(np.float32)
    inv = np.where(d > 0, 1.0 / np.maximum(d, 1e-30), 0.0)
    q = np.round(x * inv).clip(-127, 127).astype(np.int8)
    blocks = np.concatenate(
        [d.astype(np.float16).view(np.uint8), q.view(np.uint8)], axis=1)
    return blocks.tobytes()


def quantize_q4_0(x: np.ndarray) -> bytes:
    x = np.ascontiguousarray(x, np.float32).reshape(-1, 32)
    # ggml picks the signed max-magnitude value, maps it to -8
    idx = np.abs(x).argmax(axis=1)
    amax = x[np.arange(x.shape[0]), idx]
    d = (amax / -8.0).astype(np.float32)
    inv = np.where(d != 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    q = (x * inv[:, None] + 8.5).clip(0, 15).astype(np.uint8)
    lo, hi = q[:, :16], q[:, 16:]
    qs = lo | (hi << 4)
    blocks = np.concatenate(
        [d.astype(np.float16).view(np.uint8).reshape(-1, 2), qs], axis=1)
    return blocks.tobytes()
