from .config import ModelConfig, PRESETS, get_config  # noqa: F401
