"""Model architecture configs for the decoder family.

The reference operator never describes architectures — it delegates them to
the GGUF metadata consumed by llama.cpp inside the ollama image
(/root/reference/pkg/model/pod.go:11). Here the architecture is a first-class
config object so the engine can be jit-specialised per model, and so GGUF
metadata (gguf/reader.py) can be mapped onto it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Static architecture description. Frozen + hashable → usable as a jit
    static argument."""

    arch: str = "llama"
    gguf_arch: str = ""                # raw GGUF source arch ("" = native);
                                       # rope-layout decisions key on this,
                                       # NOT on the normalized arch (qwen2/
                                       # gemma map to arch="llama" but are
                                       # not interleaved-rope)
    vocab_size: int = 32000
    dim: int = 4096                    # model/residual width
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32               # < n_heads → GQA
    head_dim: int = 128
    ffn_dim: int = 11008               # hidden width of the MLP
    norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # context-extension rope scaling (ops/rope.scaled_inv_freq): the scheme
    # llama.cpp reads from GGUF rope.scaling.* metadata / the rope_freqs
    # tensor inside the image the reference delegates to
    # (/root/reference/pkg/model/pod.go:11)
    rope_scaling_type: str = "none"    # none | linear | yarn | llama3
    rope_scaling: float = 1.0          # the scaling factor (1.0 = off);
                                       # with type "none" a non-1 factor is
                                       # honored as linear (legacy field)
    rope_orig_ctx: int = 0             # original (pre-extension) context
    rope_attn_factor: float = 0.0      # yarn cos/sin magnitude; 0 = auto
    rope_low_freq_factor: float = 1.0  # llama3 interpolation band
    rope_high_freq_factor: float = 4.0
    rope_yarn_beta_fast: float = 32.0  # yarn correction-dim betas
    rope_yarn_beta_slow: float = 1.0
    # per-frequency factors from a GGUF rope_freqs.weight tensor
    # (llama3.1-family conversions bake their scheme into this); tuple so
    # the config stays hashable for jit static args
    rope_freq_factors: Optional[Tuple[float, ...]] = None
    rotary_pct: float = 1.0            # phi-2 rotates only part of head_dim
    max_seq_len: int = 4096
    sliding_window: int = 0            # 0 = full attention (mistral: 4096)
    # block structure
    norm_type: str = "rmsnorm"         # "rmsnorm" | "layernorm"
    norm_bias: bool = True             # layernorm only; command-r stores
                                       # NO norm biases
    norm_weight_offset: float = 0.0    # gemma: weight stored as (w - 1)
    mlp_type: str = "gated"            # "gated" (silu/gelu gate*up) | "plain"
    act: str = "silu"                  # "silu" | "gelu" | "gelu_tanh"
    parallel_block: bool = False       # phi-2: attn and mlp share the input LN
    attn_bias: bool = False            # qwen2/phi-2: bias on q/k/v
    out_bias: bool = False             # phi-2: bias on o/mlp projections
    tie_embeddings: bool = False       # share tok_emb and lm_head
    emb_scale: bool = False            # gemma: scale embeddings by sqrt(dim)
    logit_softcap: float = 0.0         # gemma2: tanh soft-capping of logits
    attn_softcap: float = 0.0          # gemma2: tanh soft-capping of scores
    post_norms: bool = False           # gemma2: sandwich norms — extra RMS
                                       # on attn/mlp OUTPUTS before the
                                       # residual adds
    altern_sliding: bool = False       # gemma2/gemma3: layers alternate
                                       # sliding-window and full attention
                                       # (einsum path only)
    sliding_pattern: int = 2           # alternation period: layer i runs
                                       # FULL attention iff
                                       # i % pattern == pattern - 1
                                       # (gemma2: 2 — odd layers full;
                                       # gemma3: 6 — every 6th layer full)
    rope_local_theta: float = 0.0      # gemma3: SLIDING layers rope at
                                       # this theta with no scaling; full
                                       # layers use rope_theta + scaling.
                                       # 0 = one rope for all layers
    attn_scale: float = 0.0            # gemma2 query_pre_attn_scalar:
                                       # scores scale 1/sqrt(this);
                                       # 0 = 1/sqrt(head_dim)
    qk_norm: bool = False              # qwen3/llama4-style per-head RMS on q,k
    # granite-family scalar multipliers (0 = off)
    emb_multiplier: float = 0.0        # embeddings scaled by this
    residual_multiplier: float = 0.0   # block outputs scaled before the
                                       # residual adds
    logit_scale: float = 0.0           # final logits DIVIDED by this
    attn_scale_mult: float = 0.0       # exact score multiplier (granite
                                       # attention_multiplier); overrides
                                       # the 1/sqrt(attn_scale|head_dim)
                                       # convention when set
    # mixture-of-experts (mixtral family); 0 experts = dense MLP
    n_experts: int = 0                 # total routed experts per layer
    n_experts_used: int = 2            # top-k experts per token
    moe_renorm: bool = True            # softmax over the SELECTED top-k
                                       # (mixtral/qwen3moe); False = full
                                       # softmax, top-k gates kept as-is
                                       # (qwen2moe norm_topk_prob=false)
    n_shared_ffn: int = 0              # qwen2moe: a SHARED gated expert
                                       # of this ffn width runs for every
                                       # token, scaled by a sigmoid gate
    moe_impl: str = "auto"             # auto|einsum|scan (models/decoder.py)
    kernels: str = "auto"              # attention impl: auto|pallas|xla|interpret
    mm_kernels: str = "auto"           # quantized-matmul impl. "auto" = XLA
                                       # (the grouped einsum measured faster
                                       # than the fused kernel for int8 on
                                       # v5e); the int4 loader sets "pallas"
                                       # on single-device TPU — only the
                                       # kernel reads packed bytes once, the
                                       # XLA int4 path reads them twice

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def rotary_dim(self) -> int:
        rd = int(self.head_dim * self.rotary_pct)
        return rd - rd % 2

    @property
    def n_params(self) -> int:
        """Approximate parameter count (for sizing / logs)."""
        d, f, l, v = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
        mlp = 3 * d * f if self.mlp_type == "gated" else 2 * d * f
        if self.n_experts:
            mlp = self.n_experts * mlp + d * self.n_experts
        emb = v * d * (1 if self.tie_embeddings else 2)
        return l * (attn + mlp) + emb

    def validate(self) -> "ModelConfig":
        assert self.n_heads % self.n_kv_heads == 0, "GQA requires n_heads % n_kv_heads == 0"
        assert self.rope_scaling_type in ("none", "linear", "yarn", "llama3")
        if self.rope_freq_factors is not None:
            # JSON round-trips (gguf/store.py meta) hand back a list; the
            # config must stay hashable for jit static args
            object.__setattr__(self, "rope_freq_factors",
                               tuple(float(x)
                                     for x in self.rope_freq_factors))
            assert len(self.rope_freq_factors) == self.rotary_dim // 2, (
                f"rope_freq_factors: {len(self.rope_freq_factors)} entries "
                f"for rotary_dim {self.rotary_dim}")
        if self.rope_scaling_type in ("yarn", "llama3"):
            assert self.rope_orig_ctx > 0, (
                f"{self.rope_scaling_type} rope scaling requires "
                "rope_orig_ctx")
        assert self.norm_type in ("rmsnorm", "layernorm")
        assert self.mlp_type in ("gated", "plain")
        assert self.act in ("silu", "gelu", "gelu_tanh")
        assert self.kernels in ("auto", "pallas", "xla", "interpret")
        assert self.mm_kernels in ("auto", "pallas", "xla", "interpret")
        assert self.moe_impl in ("auto", "einsum", "scan")
        if self.n_experts:
            assert self.mlp_type == "gated", "MoE is gated-MLP only"
            assert 0 < self.n_experts_used <= self.n_experts
        if self.rope_local_theta:
            assert self.altern_sliding, (
                "rope_local_theta pairs with per-layer (altern_sliding) "
                "attention — the dual rope selects by the same pattern")
        assert self.sliding_pattern >= 2
        return self


def _mk(**kw) -> ModelConfig:
    return ModelConfig(**kw).validate()


# --- presets -----------------------------------------------------------------
# Dims cross-checked against the public GGUF metadata of the ollama library
# images listed in the reference README model table (/root/reference/README.md).

PRESETS = {
    # tiny config for unit tests / CI (CPU mesh)
    "tiny": _mk(arch="llama", vocab_size=256, dim=64, n_layers=2, n_heads=4,
                n_kv_heads=2, head_dim=16, ffn_dim=128, max_seq_len=128),
    "tinyllama": _mk(arch="llama", vocab_size=32000, dim=2048, n_layers=22,
                     n_heads=32, n_kv_heads=4, head_dim=64, ffn_dim=5632,
                     max_seq_len=2048),
    "phi": _mk(arch="phi2", vocab_size=51200, dim=2560, n_layers=32,
               n_heads=32, n_kv_heads=32, head_dim=80, ffn_dim=10240,
               norm_type="layernorm", mlp_type="plain", act="gelu_tanh",
               parallel_block=True, attn_bias=True, out_bias=True,
               rotary_pct=0.4, max_seq_len=2048),
    # phi3-mini 3.8B (the ollama `phi3` default tag): llama-family block,
    # MHA (32/32), full rotary; the 4k-instruct variant serves without
    # longrope (the 128k tags carry rope_factors tensors the transcoder
    # maps to rope_freq_factors)
    "phi3": _mk(arch="llama", vocab_size=32064, dim=3072, n_layers=32,
                n_heads=32, n_kv_heads=32, head_dim=96, ffn_dim=8192,
                max_seq_len=4096, sliding_window=2047),
    # gemma3-4b (the ollama `gemma3` default tag): pattern-6 alternating
    # attention with DUAL rope (local 10k on sliding layers, global 1e6
    # linear-scaled ×8 on full layers), gemma-offset qk norms, sandwich
    # norms, no softcapping
    "gemma3": _mk(arch="llama", vocab_size=262208, dim=2560, n_layers=34,
                  n_heads=8, n_kv_heads=4, head_dim=256, ffn_dim=10240,
                  act="gelu_tanh", emb_scale=True, tie_embeddings=True,
                  norm_weight_offset=1.0, post_norms=True,
                  altern_sliding=True, sliding_pattern=6, qk_norm=True,
                  sliding_window=1024, rope_local_theta=10000.0,
                  rope_theta=1000000.0, rope_scaling_type="linear",
                  rope_scaling=8.0, attn_scale=256.0,
                  max_seq_len=131072),
    # starcoder2-3b (the ollama `starcoder2` default tag): LayerNorm +
    # biases, plain gelu MLP, GQA 12:1, sliding window
    "starcoder2": _mk(arch="llama", vocab_size=49152, dim=3072,
                      n_layers=30, n_heads=24, n_kv_heads=2, head_dim=128,
                      ffn_dim=12288, norm_type="layernorm",
                      mlp_type="plain", act="gelu_tanh", attn_bias=True,
                      out_bias=True, tie_embeddings=True,
                      max_seq_len=16384, sliding_window=4096,
                      rope_theta=999999.0),
    "llama2": _mk(arch="llama", vocab_size=32000, dim=4096, n_layers=32,
                  n_heads=32, n_kv_heads=32, head_dim=128, ffn_dim=11008,
                  max_seq_len=4096),
    "llama2:13b": _mk(arch="llama", vocab_size=32000, dim=5120, n_layers=40,
                      n_heads=40, n_kv_heads=40, head_dim=128, ffn_dim=13824,
                      max_seq_len=4096),
    "llama2:70b": _mk(arch="llama", vocab_size=32000, dim=8192, n_layers=80,
                      n_heads=64, n_kv_heads=8, head_dim=128, ffn_dim=28672,
                      max_seq_len=4096),
    "llama3": _mk(arch="llama", vocab_size=128256, dim=4096, n_layers=32,
                  n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
                  rope_theta=500000.0, max_seq_len=8192),
    "llama3:70b": _mk(arch="llama", vocab_size=128256, dim=8192, n_layers=80,
                      n_heads=64, n_kv_heads=8, head_dim=128, ffn_dim=28672,
                      rope_theta=500000.0, max_seq_len=8192),
    # llama3.1 shares llama3-8B dims; the 131072 context comes from
    # llama3-type rope scaling (ops/rope.scaled_inv_freq) — factor 8 over
    # the 8192 native window, low/high-freq interpolation band 1..4 (real
    # GGUF pulls carry the equivalent pre-baked rope_freqs tensor, which
    # the transcoder reads into rope_freq_factors). 3.2 are the small GQA
    # variants — factor 32, tied embeddings.
    "llama3.1": _mk(arch="llama", vocab_size=128256, dim=4096, n_layers=32,
                    n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
                    rope_theta=500000.0, rope_scaling_type="llama3",
                    rope_scaling=8.0, rope_orig_ctx=8192,
                    rope_low_freq_factor=1.0, rope_high_freq_factor=4.0,
                    max_seq_len=131072),
    "llama3.2:1b": _mk(arch="llama", vocab_size=128256, dim=2048,
                       n_layers=16, n_heads=32, n_kv_heads=8, head_dim=64,
                       ffn_dim=8192, rope_theta=500000.0,
                       rope_scaling_type="llama3", rope_scaling=32.0,
                       rope_orig_ctx=8192, rope_low_freq_factor=1.0,
                       rope_high_freq_factor=4.0,
                       tie_embeddings=True, max_seq_len=131072),
    "llama3.2:3b": _mk(arch="llama", vocab_size=128256, dim=3072,
                       n_layers=28, n_heads=24, n_kv_heads=8, head_dim=128,
                       ffn_dim=8192, rope_theta=500000.0,
                       rope_scaling_type="llama3", rope_scaling=32.0,
                       rope_orig_ctx=8192, rope_low_freq_factor=1.0,
                       rope_high_freq_factor=4.0,
                       tie_embeddings=True, max_seq_len=131072),
    "mistral": _mk(arch="llama", vocab_size=32000, dim=4096, n_layers=32,
                   n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
                   sliding_window=4096, max_seq_len=32768),
    "gemma2": _mk(arch="llama", vocab_size=256000, dim=3584, n_layers=42,
                  n_heads=16, n_kv_heads=8, head_dim=256, ffn_dim=14336,
                  act="gelu_tanh", emb_scale=True, tie_embeddings=True,
                  norm_weight_offset=1.0, post_norms=True,
                  altern_sliding=True, sliding_window=4096,
                  attn_softcap=50.0, logit_softcap=30.0,
                  max_seq_len=8192),
    "gemma2:27b": _mk(arch="llama", vocab_size=256000, dim=4608,
                      n_layers=46, n_heads=32, n_kv_heads=16, head_dim=128,
                      ffn_dim=36864, act="gelu_tanh", emb_scale=True,
                      tie_embeddings=True, norm_weight_offset=1.0,
                      post_norms=True, altern_sliding=True,
                      sliding_window=4096, attn_softcap=50.0,
                      logit_softcap=30.0, attn_scale=144.0,
                      max_seq_len=8192),
    "qwen3": _mk(arch="llama", vocab_size=151936, dim=4096, n_layers=36,
                 n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=12288,
                 qk_norm=True, rope_theta=1000000.0, max_seq_len=32768),
    "qwen2": _mk(arch="llama", vocab_size=152064, dim=3584, n_layers=28,
                 n_heads=28, n_kv_heads=4, head_dim=128, ffn_dim=18944,
                 attn_bias=True, rope_theta=1000000.0, max_seq_len=32768),
    # qwen2.5-7B keeps qwen2-7B's architecture/dims
    "qwen2.5": _mk(arch="llama", vocab_size=152064, dim=3584, n_layers=28,
                   n_heads=28, n_kv_heads=4, head_dim=128, ffn_dim=18944,
                   attn_bias=True, rope_theta=1000000.0,
                   max_seq_len=32768),
    "qwen2:0.5b": _mk(arch="llama", vocab_size=151936, dim=896, n_layers=24,
                      n_heads=14, n_kv_heads=2, head_dim=64, ffn_dim=4864,
                      attn_bias=True, tie_embeddings=True,
                      rope_theta=1000000.0, max_seq_len=32768),
    "gemma": _mk(arch="llama", vocab_size=256000, dim=3072, n_layers=28,
                 n_heads=16, n_kv_heads=16, head_dim=256, ffn_dim=24576,
                 act="gelu_tanh", emb_scale=True, tie_embeddings=True,
                 norm_weight_offset=1.0, max_seq_len=8192),
    # multimodal (vicuna-7b LLM half of llava-1.5; vision tower in
    # models/vision.py via the mmproj layer)
    "llava": _mk(arch="llama", vocab_size=32000, dim=4096, n_layers=32,
                 n_heads=32, n_kv_heads=32, head_dim=128, ffn_dim=11008,
                 max_seq_len=4096),
    # mixture-of-experts family (sparse MoE; expert-parallel over "ep")
    "tiny-moe": _mk(arch="llama", vocab_size=256, dim=64, n_layers=2,
                    n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
                    n_experts=4, n_experts_used=2, max_seq_len=128),
    "mixtral": _mk(arch="llama", vocab_size=32000, dim=4096, n_layers=32,
                   n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
                   n_experts=8, n_experts_used=2, rope_theta=1000000.0,
                   max_seq_len=32768),
    "mixtral:8x22b": _mk(arch="llama", vocab_size=32768, dim=6144,
                         n_layers=56, n_heads=48, n_kv_heads=8, head_dim=128,
                         ffn_dim=16384, n_experts=8, n_experts_used=2,
                         rope_theta=1000000.0, max_seq_len=65536),
    "dolphin-mixtral": _mk(arch="llama", vocab_size=32002, dim=4096,
                           n_layers=32, n_heads=32, n_kv_heads=8,
                           head_dim=128, ffn_dim=14336, n_experts=8,
                           n_experts_used=2, rope_theta=1000000.0,
                           max_seq_len=32768),
}


def get_config(name: str) -> ModelConfig:
    base = name.split(":")[0]
    if name in PRESETS:
        return PRESETS[name]
    if base in PRESETS:
        return PRESETS[base]
    raise KeyError(f"unknown model preset: {name!r}; known: {sorted(PRESETS)}")
