"""Decoder-only transformer family, pure functional JAX.

This is the TPU-native replacement for the inference engine the reference
operator delegates to the `ollama/ollama` image (llama.cpp/GGML, see
/root/reference/pkg/model/pod.go:11 and SURVEY.md §2.2). Design choices are
XLA-first, not a translation:

- Layer params are **stacked** along a leading ``n_layers`` axis and the
  forward pass runs ``lax.scan`` over layers → the block is traced/compiled
  once regardless of depth (fast compiles for 80-layer 70B models).
- Static shapes everywhere; prefill lengths are bucketed by the engine.
- GQA is a grouped einsum (ops/attention.py) — K/V are never repeated in HBM.
- fp32 for softmax/norm accumulation, bf16 (or int8-dequant) for matmuls so
  the MXU stays fed.
- KV cache updates are functional; the engine donates cache buffers so XLA
  aliases them in-place.

Params pytree layout (all leaves jnp arrays; layer leaves stacked on axis 0):

  tok_emb   [V, D]
  out_norm_w [D] (+ out_norm_b for layernorm archs)
  lm_head   [D, V]       (absent when cfg.tie_embeddings)
  lm_head_b [V]          (phi-2 only)
  layers/
    attn_norm_w [L, D] (+ attn_norm_b)
    wq [L, D, H*hd]  wk [L, D, KvH*hd]  wv [L, D, KvH*hd]  wo [L, H*hd, D]
    (bq, bk, bv, bo optional)
    mlp_norm_w [L, D] (+ mlp_norm_b; absent when cfg.parallel_block)
    w_gate [L, D, F] (gated only)  w_up [L, D, F]  w_down [L, F, D]
    (b_up [L, F], b_down [L, D] optional)
    q_norm_w / k_norm_w [L, hd] (qk_norm archs)
    MoE archs (cfg.n_experts > 0, mixtral family) replace w_gate/w_up/w_down:
    router [L, D, E]
    we_gate [L, E, D, F]  we_up [L, E, D, F]  we_down [L, E, F, D]
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import quant as Q
from ..ops.attention import (attend_hf, cached_attention, causal_mask,
                             chunk_attention, shard_map_compat)
from ..ops.norms import layer_norm, rms_norm
from ..ops.rope import apply_rope, rope_angles_cfg
from .config import ModelConfig

Params = Dict[str, Any]


def _mm(cfg: ModelConfig, x, w, out_dtype=None):
    """Linear against a dense array or a quantized dict leaf
    (ops/quant.py). The XLA grouped path wins on v5e for int8 full-model
    decode (the fused pallas kernel measured slower: 137 vs 147 tok/s on
    phi), so "auto" resolves to XLA here; cfg.mm_kernels overrides just
    the matmul choice (the int4 loader sets it to "pallas" on
    single-device TPU, where the kernel's read-each-byte-once is the
    whole bandwidth win), and an explicit kernels="pallas"/"interpret"
    config still routes everything through kernels."""
    if cfg.kernels in ("pallas", "interpret"):
        mode = cfg.kernels
    elif cfg.mm_kernels in ("pallas", "interpret"):
        mode = cfg.mm_kernels
    else:
        mode = "xla"
    return Q.matmul(x, w, out_dtype, kernels=mode)


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random init (for tests/benchmarks; real weights come from gguf/)."""
    L, D, F, V = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.vocab_size
    keys = iter(jax.random.split(key, 32))

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers: Dict[str, jax.Array] = {
        "attn_norm_w": jnp.ones((L, D), dtype),
        "wq": w(next(keys), (L, D, cfg.q_dim)),
        "wk": w(next(keys), (L, D, cfg.kv_dim)),
        "wv": w(next(keys), (L, D, cfg.kv_dim)),
        "wo": w(next(keys), (L, cfg.q_dim, D)),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        layers["router"] = w(next(keys), (L, D, E))
        layers["we_gate"] = w(next(keys), (L, E, D, F))
        layers["we_up"] = w(next(keys), (L, E, D, F))
        layers["we_down"] = w(next(keys), (L, E, F, D))
        if cfg.n_shared_ffn:
            Fs = cfg.n_shared_ffn
            layers["we_sh_gate"] = w(next(keys), (L, D, Fs))
            layers["we_sh_up"] = w(next(keys), (L, D, Fs))
            layers["we_sh_down"] = w(next(keys), (L, Fs, D))
            layers["sh_gate"] = w(next(keys), (L, D, 1))
    else:
        layers["w_up"] = w(next(keys), (L, D, F))
        layers["w_down"] = w(next(keys), (L, F, D))
    if cfg.norm_type == "layernorm" and cfg.norm_bias:
        layers["attn_norm_b"] = jnp.zeros((L, D), dtype)
    if not cfg.parallel_block:
        layers["mlp_norm_w"] = jnp.ones((L, D), dtype)
        if cfg.norm_type == "layernorm" and cfg.norm_bias:
            layers["mlp_norm_b"] = jnp.zeros((L, D), dtype)
    if cfg.mlp_type == "gated" and not cfg.n_experts:
        layers["w_gate"] = w(next(keys), (L, D, F))
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_dim), dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_dim), dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_dim), dtype)
    if cfg.out_bias:
        layers["bo"] = jnp.zeros((L, D), dtype)
        layers["b_up"] = jnp.zeros((L, F), dtype)
        layers["b_down"] = jnp.zeros((L, D), dtype)
    if cfg.qk_norm:
        layers["q_norm_w"] = jnp.ones((L, cfg.head_dim), dtype)
        layers["k_norm_w"] = jnp.ones((L, cfg.head_dim), dtype)
    if cfg.post_norms:
        layers["post_attn_norm_w"] = jnp.ones((L, D), dtype)
        layers["post_ffw_norm_w"] = jnp.ones((L, D), dtype)

    params: Params = {
        "tok_emb": w(next(keys), (V, D)),
        "out_norm_w": jnp.ones((D,), dtype),
        "layers": layers,
    }
    if cfg.norm_type == "layernorm" and cfg.norm_bias:
        params["out_norm_b"] = jnp.zeros((D,), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = w(next(keys), (D, V))
    if cfg.out_bias:
        params["lm_head_b"] = jnp.zeros((V,), dtype)
    return params


# --------------------------------------------------------------------------
# building blocks
# --------------------------------------------------------------------------

def _causal_window_mask(k_pos, q_pos, window: int):
    """Additive [B,1,T,A] mask for cache attention: keys at absolute slot
    k_pos visible to queries at q_pos iff k <= q (within ``window`` when
    set). Shared by the dense and paged cached forwards."""
    ok = k_pos <= q_pos
    if window:
        ok = ok & (k_pos > q_pos - window)
    return jnp.where(ok, 0.0, -1e30).astype(jnp.float32)[:, None, :, :]


def _is_full_layer(cfg: ModelConfig, i):
    """Alternating-attention pattern: layer i runs FULL attention iff
    i % sliding_pattern == sliding_pattern - 1 (gemma2: odd layers;
    gemma3: every 6th layer). ``i`` may be traced (scan index)."""
    return (i % cfg.sliding_pattern) == (cfg.sliding_pattern - 1)


def _layer_mask(cfg: ModelConfig, i, mask, m_full):
    """Per-layer attention mask: alternating archs (gemma2/gemma3) pick
    sliding vs full per layer; everything else uses ``mask`` as-is."""
    if not cfg.altern_sliding:
        return mask
    return jnp.where(_is_full_layer(cfg, i), m_full, mask)


def _layer_rope(cfg: ModelConfig, i, cos, sin, cos_l, sin_l):
    """Per-layer rope (gemma3): SLIDING layers rotate at the local theta
    (cos_l/sin_l, unscaled), FULL layers at the global theta incl. any
    context-extension scaling. Single-rope archs pass cos_l=None."""
    if cos_l is None:
        return cos, sin
    full = _is_full_layer(cfg, i)
    return jnp.where(full, cos, cos_l), jnp.where(full, sin, sin_l)


def _rope_pair(positions, cfg: ModelConfig):
    """(cos, sin, cos_l, sin_l): the global rope table plus, for dual-rope
    archs (cfg.rope_local_theta — gemma3), the local-theta table."""
    from ..ops.rope import rope_angles
    cos, sin = rope_angles_cfg(positions, cfg)
    if not cfg.rope_local_theta:
        return cos, sin, None, None
    cos_l, sin_l = rope_angles(positions, cfg.rotary_dim,
                               cfg.rope_local_theta)
    return cos, sin, cos_l, sin_l


def _attn_scale(cfg: ModelConfig) -> float:
    """Score scale: 1/sqrt(head_dim), gemma2/gemma3's
    1/sqrt(query_pre_attn_scalar), or granite's exact attention
    multiplier when the config sets one."""
    if cfg.attn_scale_mult:
        return cfg.attn_scale_mult
    return 1.0 / math.sqrt(cfg.attn_scale or cfg.head_dim)


def _norm(cfg: ModelConfig, x, w, b=None):
    if cfg.norm_type == "layernorm":
        return layer_norm(x, w, b, cfg.norm_eps)
    return rms_norm(x, w, cfg.norm_eps, cfg.norm_weight_offset)


def _act(cfg: ModelConfig, x):
    if cfg.act == "silu":
        return jax.nn.silu(x)
    if cfg.act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.gelu(x, approximate=True)


def _moe_gates(cfg: ModelConfig, lp, xf):
    """Router: top-k softmax gates scattered to a dense [N, E] fp32 matrix
    (zeros for unselected experts). With ``moe_renorm`` (mixtral,
    qwen3moe) the softmax runs over the SELECTED logits — equal to the
    full softmax renormalised over the top-k; without it (qwen2moe,
    norm_topk_prob=false) the full-softmax probabilities are kept
    un-renormalised."""
    logits = jnp.einsum("nd,de->ne", xf, lp["router"],
                        preferred_element_type=jnp.float32)  # [N, E] fp32
    if cfg.moe_renorm:
        topw, topi = lax.top_k(logits, cfg.n_experts_used)  # [N, k]
        topw = jax.nn.softmax(topw, axis=-1)
    else:
        probs = jax.nn.softmax(logits, axis=-1)
        topw, topi = lax.top_k(probs, cfg.n_experts_used)
    N = xf.shape[0]
    gates = jnp.zeros((N, cfg.n_experts), jnp.float32)
    return gates.at[jnp.arange(N)[:, None], topi].set(topw)


def _moe_mlp(cfg: ModelConfig, lp, x):
    """Sparse-MoE gated MLP (mixtral family), exact (no token dropping).

    Every expert computes over all tokens and the combine applies the gate
    (zero for unselected) — on TPU decode this costs nothing extra where it
    matters: the step is weights-bandwidth-bound and all E experts' weights
    stream from HBM regardless once the batch covers them. Two layouts:

    - "einsum": experts batched on a leading E axis. Under GSPMD with
      we_* sharded on the "ep" mesh axis (parallel/sharding.py) each device
      computes only its resident experts and XLA reduces the combine over
      ep — expert parallelism with no hand-written collective.
    - "scan": lax.scan over experts, [N, F] working set — memory-light for
      long single-device prefill where the einsum's [E, N, F] intermediate
      would spike HBM.

    "auto" picks einsum for small token counts (decode / short chunks) and
    scan beyond that.
    """
    B, T, D = x.shape
    xf = x.reshape(B * T, D)
    gates = _moe_gates(cfg, lp, xf)                          # [N, E] fp32
    impl = cfg.moe_impl
    if impl == "auto":
        impl = "einsum" if B * T <= 256 else "scan"
    if impl == "einsum":
        h = jnp.einsum("nd,edf->enf", xf, lp["we_gate"])
        u = jnp.einsum("nd,edf->enf", xf, lp["we_up"])
        o = jnp.einsum("enf,efd->end", _act(cfg, h) * u, lp["we_down"])
        y = jnp.einsum("ne,end->nd", gates, o.astype(jnp.float32))
    else:
        def body(acc, ew):
            wg, wu, wd, g = ew                   # [D,F] [D,F] [F,D] [N]
            he = _act(cfg, xf @ wg) * (xf @ wu)
            return acc + g[:, None] * (he @ wd).astype(jnp.float32), None
        acc0 = jnp.zeros((B * T, D), jnp.float32)
        y, _ = lax.scan(body, acc0, (lp["we_gate"], lp["we_up"],
                                     lp["we_down"], gates.T))
    if "we_sh_gate" in lp:
        # qwen2moe shared expert: a gated MLP every token runs, its
        # output scaled by a per-token sigmoid gate (shared_expert_gate)
        hs = _act(cfg, xf @ lp["we_sh_gate"]) * (xf @ lp["we_sh_up"])
        sh = (hs @ lp["we_sh_down"]).astype(jnp.float32)
        sg = jax.nn.sigmoid(
            (xf @ lp["sh_gate"]).astype(jnp.float32))      # [N, 1]
        y = y + sg * sh
    return y.astype(x.dtype).reshape(B, T, D)


def _mlp(cfg: ModelConfig, lp, x):
    if cfg.n_experts:
        return _moe_mlp(cfg, lp, x)
    if cfg.mlp_type == "gated":
        g = _act(cfg, _mm(cfg, x, lp["w_gate"]))
        u = _mm(cfg, x, lp["w_up"])
        return _mm(cfg, g * u, lp["w_down"])
    u = _mm(cfg, x, lp["w_up"])
    if "b_up" in lp:
        u = u + lp["b_up"]
    d = _mm(cfg, _act(cfg, u), lp["w_down"])
    if "b_down" in lp:
        d = d + lp["b_down"]
    return d


def fuse_qkv_params(params: Params, cfg: ModelConfig) -> Params:
    """Concatenate wq|wk|wv (and their biases) along the output axis into
    one ``wqkv`` leaf, so the attention input projection is ONE matmul
    instead of three. At decode batch sizes each dispatched matmul pays a
    fixed latency floor regardless of its byte count (r4 microbench,
    v5e-1: mistral-shaped GQA qkv 70.6 µs separate vs 20.2 µs fused —
    3.49×; the GQA k/v projections are tiny and each eat a full floor).
    Valid for dense and quantized (int8/int4) leaves — every output
    column of the grouped qmm is independent, so the fused result is
    bitwise identical to the separate matmuls. The engine applies this
    only on meshes without a sharded tp/sp axis (a fused column split
    would straddle the q/kv shard boundaries)."""
    layers = dict(params["layers"])
    if "wq" not in layers:
        return params

    def cat(leaves):
        if isinstance(leaves[0], dict):
            return {k: jnp.concatenate([l[k] for l in leaves], axis=-1)
                    for k in leaves[0]}
        return jnp.concatenate(leaves, axis=-1)

    layers["wqkv"] = cat([layers.pop("wq"), layers.pop("wk"),
                          layers.pop("wv")])
    if "bq" in layers:
        layers["bqkv"] = cat([layers.pop("bq"), layers.pop("bk"),
                              layers.pop("bv")])
    return {**params, "layers": layers}


def _qkv(cfg: ModelConfig, lp, h, cos, sin):
    B, T, _ = h.shape
    if "wqkv" in lp:
        y = _mm(cfg, h, lp["wqkv"])
        if "bqkv" in lp:
            y = y + lp["bqkv"]
        qd, kvd = cfg.q_dim, cfg.kv_dim
        q = y[..., :qd]
        k = y[..., qd:qd + kvd]
        v = y[..., qd + kvd:]
    else:
        q = _mm(cfg, h, lp["wq"])
        k = _mm(cfg, h, lp["wk"])
        v = _mm(cfg, h, lp["wv"])
        if "bq" in lp:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        # gemma3 stores the norm weight gemma-style as (w − 1); qwen3's
        # offset is 0, so the shared call is exact for both
        q = rms_norm(q, lp["q_norm_w"], cfg.norm_eps,
                     cfg.norm_weight_offset)
        k = rms_norm(k, lp["k_norm_w"], cfg.norm_eps,
                     cfg.norm_weight_offset)
    q = apply_rope(q, cos, sin, cfg.rotary_dim)
    k = apply_rope(k, cos, sin, cfg.rotary_dim)
    return q, k, v


def _proj_out(cfg, lp, attn_out, B, T):
    o = _mm(cfg, attn_out.reshape(B, T, -1), lp["wo"])
    if "bo" in lp:
        o = o + lp["bo"]
    return o


def _residual(cfg: ModelConfig, lp, x, h, attn):
    rm = cfg.residual_multiplier or 1.0   # granite: scaled residual adds
    if cfg.post_norms:
        # gemma2 sandwich norms: attn/mlp OUTPUTS normed before the adds
        attn = _norm(cfg, attn, lp["post_attn_norm_w"])
    if cfg.parallel_block:
        return x + attn + _mlp(cfg, lp, h)
    x = x + rm * attn
    h2 = _norm(cfg, x, lp["mlp_norm_w"], lp.get("mlp_norm_b"))
    m = _mlp(cfg, lp, h2)
    if cfg.post_norms:
        m = _norm(cfg, m, lp["post_ffw_norm_w"])
    return x + rm * m


def _block_chunk(cfg: ModelConfig, lp, x, cos, sin, mask, scale,
                 attn_fn=None, mesh=None):
    """One layer over a fresh chunk (no prior cache). Returns
    (x, (k, v)) with K/V head-first [B, KvH, T, hd] — the cache layout.
    ``attn_fn(q, k, v)`` overrides the attention core (the sequence-parallel
    path injects ring attention here; mask is unused then)."""
    B, T, _ = x.shape
    h = _norm(cfg, x, lp["attn_norm_w"], lp.get("attn_norm_b"))
    q, k, v = _qkv(cfg, lp, h, cos, sin)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    if attn_fn is not None:
        attn = attn_fn(q, k, v)
    elif cfg.altern_sliding:
        # per-layer window rides the mask (traced); kernel dispatch needs
        # a static window, so alternating archs stay on the einsum path
        attn = attend_hf(q, k, v, mask, scale, cfg.attn_softcap)
    else:
        attn = chunk_attention(cfg, q, k, v, mask, scale, mesh=mesh)
    attn = _proj_out(cfg, lp, attn, B, T)
    return _residual(cfg, lp, x, h, attn), (k, v)


def _block_cached(cfg: ModelConfig, lp, x, cos, sin, k_cache, v_cache,
                  write_pos, mask, scale, attn_fn=None, write_fn=None,
                  attn_len: Optional[int] = None, mesh=None):
    """One layer with a head-first KV cache [B, KvH, S, hd]. ``write_pos``
    [B, T] are absolute slots for the new tokens' K/V. Returns
    (x, k_cache, v_cache) updated. ``write_fn(kc, vc, k, v, pos)`` /
    ``attn_fn(q, kc, vc, pos)`` override the cache write and attention core
    (the sequence-parallel path injects shard-local variants). ``attn_len``
    statically truncates the attended cache prefix (see forward_with_cache)
    — the slice fuses into the attention reads, so slots beyond it cost no
    HBM traffic."""
    B, T, _ = x.shape
    h = _norm(cfg, x, lp["attn_norm_w"], lp.get("attn_norm_b"))
    q, k, v = _qkv(cfg, lp, h, cos, sin)
    k = k.transpose(0, 2, 1, 3)                       # [B, KvH, T, hd]
    v = v.transpose(0, 2, 1, 3)
    if write_fn is None:
        KvH = k.shape[1]
        bidx = jnp.arange(B)[:, None, None]
        hidx = jnp.arange(KvH)[None, :, None]
        pidx = write_pos[:, None, :]
        k_cache = k_cache.at[bidx, hidx, pidx].set(k.astype(k_cache.dtype))
        v_cache = v_cache.at[bidx, hidx, pidx].set(v.astype(v_cache.dtype))
    else:
        k_cache, v_cache = write_fn(k_cache, v_cache, k, v, write_pos)
    if attn_fn is None:
        attn = cached_attention(cfg, q, k_cache, v_cache, mask, write_pos,
                                scale, attn_len=attn_len, mesh=mesh)
    else:
        attn = attn_fn(q, k_cache, v_cache, write_pos)
    attn = _proj_out(cfg, lp, attn, B, T)
    return _residual(cfg, lp, x, h, attn), k_cache, v_cache


def _embed(cfg: ModelConfig, params: Params, tokens):
    x = params["tok_emb"][tokens]
    if cfg.emb_scale:
        x = (x.astype(jnp.float32) * math.sqrt(cfg.dim)).astype(x.dtype)
    if cfg.emb_multiplier:
        x = (x.astype(jnp.float32) * cfg.emb_multiplier).astype(x.dtype)
    return x


def _unembed(cfg: ModelConfig, params: Params, x):
    x = _norm(cfg, x, params["out_norm_w"], params.get("out_norm_b"))
    if not cfg.tie_embeddings and Q.is_quantized(params["lm_head"]):
        logits = _mm(cfg, x, params["lm_head"], out_dtype=jnp.float32)
    else:
        head = params["tok_emb"].T if cfg.tie_embeddings else params["lm_head"]
        logits = jnp.einsum("btd,dv->btv", x, head,
                            preferred_element_type=jnp.float32)
    if "lm_head_b" in params:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    if cfg.logit_scale:
        logits = logits / cfg.logit_scale   # granite logits_scaling
    if cfg.logit_softcap:
        logits = cfg.logit_softcap * jnp.tanh(logits / cfg.logit_softcap)
    return logits


# --------------------------------------------------------------------------
# forward passes
# --------------------------------------------------------------------------

def prefill_chunk(params: Params, cfg: ModelConfig, tokens: jax.Array,
                  n_valid: Optional[jax.Array] = None,
                  inputs_embeds: Optional[jax.Array] = None,
                  mesh=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Process a fresh chunk at positions [0, T) with no prior cache.

    tokens  [B, T] int32 (right-padded; padding is masked out of attention by
            the causal structure for queries < n_valid — callers only read
            logits at n_valid-1).
    inputs_embeds — optional [B, T, D] pre-computed embedding sequence
            (multimodal prompts: image tokens from models/vision.py spliced
            between text embeddings); replaces the tok_emb lookup.
    Returns (logits [B, T, V] fp32, k [L, B, KvH, T, hd], v [...]) — K/V
    head-first, matching the cache layout.
    """
    B, T = tokens.shape
    scale = _attn_scale(cfg)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    cos, sin, cos_l, sin_l = _rope_pair(positions, cfg)
    mask = causal_mask(T, T, 0, sliding_window=cfg.sliding_window)
    mask = jnp.broadcast_to(mask, (B, 1, T, T))

    if inputs_embeds is not None:
        x = inputs_embeds.astype(params["tok_emb"].dtype)
    else:
        x = _embed(cfg, params, tokens)

    if cfg.altern_sliding:
        # gemma2/gemma3: per-layer sliding vs full attention (and, for
        # gemma3, per-layer local vs global rope)
        m_full = jnp.broadcast_to(causal_mask(T, T, 0), (B, 1, T, T))

        def body_a(x, layer_in):
            lp, i = layer_in
            mask_l = _layer_mask(cfg, i, mask, m_full)
            cos_i, sin_i = _layer_rope(cfg, i, cos, sin, cos_l, sin_l)
            x, (k, v) = _block_chunk(cfg, lp, x, cos_i, sin_i, mask_l,
                                     scale, mesh=mesh)
            return x, (k, v)

        x, (ks, vs) = lax.scan(
            body_a, x, (params["layers"], jnp.arange(cfg.n_layers)))
    else:
        def body(x, lp):
            x, (k, v) = _block_chunk(cfg, lp, x, cos, sin, mask, scale,
                                     mesh=mesh)
            return x, (k, v)

        x, (ks, vs) = lax.scan(body, x, params["layers"])
    logits = _unembed(cfg, params, x)
    return logits, ks, vs


def forward_with_cache(params: Params, cfg: ModelConfig, tokens: jax.Array,
                       k_cache: jax.Array, v_cache: jax.Array,
                       lengths: jax.Array,
                       attn_len: Optional[int] = None,
                       mesh=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Extend sequences that already have ``lengths`` cached tokens.

    tokens   [B, T] — T=1 is the decode step; T>1 is chunked prefill
             continuation.
    k_cache  [L, B, KvH, S, hd] head-first (donate for in-place update)
    lengths  [B] int32 — number of valid cached tokens per slot.
    attn_len — static attention window: keys are read only from cache
             slots [0, attn_len). Decode is cache-bandwidth-bound, so the
             engine buckets this to the live prefix instead of streaming
             all S slots every step. Requires max(lengths) + T <= attn_len
             (new K/V land below it); None = S.
    Returns (logits [B, T, V], k_cache, v_cache).
    """
    from ..ops.quant_cache import is_quantized_cache
    B, T = tokens.shape
    kc_arr = k_cache["q"] if is_quantized_cache(k_cache) else k_cache
    L, _, _, S, _ = kc_arr.shape
    A = S if attn_len is None else min(attn_len, S)
    scale = _attn_scale(cfg)
    positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    cos, sin, cos_l, sin_l = _rope_pair(positions, cfg)
    # key j (absolute slot) is visible to query at absolute pos p iff j <= p,
    # within the sliding window; slots beyond the written region are garbage
    # but satisfy j > p so they are masked.
    k_pos = jnp.arange(A, dtype=jnp.int32)[None, None, :]
    q_pos = positions[:, :, None]

    mask = _causal_window_mask(k_pos, q_pos, cfg.sliding_window)
    m_full = (_causal_window_mask(k_pos, q_pos, 0)
              if cfg.altern_sliding else None)

    x = _embed(cfg, params, tokens)

    # The caches ride in the scan CARRY (not xs/ys): scanning over stacked
    # caches makes XLA re-stack the whole [L, B, KvH, S, hd] buffers into
    # fresh ys every step (a multi-GB copy per decode step, measured ~25%
    # of the step on v5e) — the carry aliases in place, and each layer
    # touches only its own scatter-write plus an A-sized window read.
    quant = is_quantized_cache(k_cache)
    KvH, hd = cfg.n_kv_heads, cfg.head_dim
    bidx = jnp.arange(B)[:, None, None]
    hidx = jnp.arange(KvH)[None, :, None]
    pidx = positions[:, None, :]

    def window(c, i, sizes):
        return lax.dynamic_slice(c, (i,) + (0,) * (len(sizes) - 1),
                                 (1,) + sizes[1:])[0]

    def body(carry, layer_in):
        x, kc, vc = carry
        lp, i = layer_in
        mask_l = _layer_mask(cfg, i, mask, m_full)
        cos_i, sin_i = _layer_rope(cfg, i, cos, sin, cos_l, sin_l)
        h = _norm(cfg, x, lp["attn_norm_w"], lp.get("attn_norm_b"))
        q, k, v = _qkv(cfg, lp, h, cos_i, sin_i)
        k = k.transpose(0, 2, 1, 3)                   # [B, KvH, T, hd]
        v = v.transpose(0, 2, 1, 3)
        if quant:
            from ..ops import quant_cache as QC
            kq, ks = QC.quantize_kv(k)
            vq, vs = QC.quantize_kv(v)
            kc = {"q": kc["q"].at[i, bidx, hidx, pidx].set(kq),
                  "s": kc["s"].at[i, bidx, hidx, pidx].set(ks)}
            vc = {"q": vc["q"].at[i, bidx, hidx, pidx].set(vq),
                  "s": vc["s"].at[i, bidx, hidx, pidx].set(vs)}
            kwin = {"q": window(kc["q"], i, (1, B, KvH, A, hd)),
                    "s": window(kc["s"], i, (1, B, KvH, A))}
            vwin = {"q": window(vc["q"], i, (1, B, KvH, A, hd)),
                    "s": window(vc["s"], i, (1, B, KvH, A))}
            attn = QC.attend_hf_q(q, kwin, vwin, mask_l, scale,
                                  cfg.attn_softcap, attn_len=A)
        else:
            kc = kc.at[i, bidx, hidx, pidx].set(k.astype(kc.dtype))
            vc = vc.at[i, bidx, hidx, pidx].set(v.astype(vc.dtype))
            kwin = window(kc, i, (1, B, KvH, A, hd))
            vwin = window(vc, i, (1, B, KvH, A, hd))
            if cfg.altern_sliding:
                attn = attend_hf(q, kwin, vwin, mask_l, scale,
                                 cfg.attn_softcap)
            else:
                attn = cached_attention(cfg, q, kwin, vwin, mask_l,
                                        positions, scale, attn_len=A,
                                        mesh=mesh)
        attn = _proj_out(cfg, lp, attn, B, T)
        x = _residual(cfg, lp, x, h, attn)
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = lax.scan(
        body, (x, k_cache, v_cache),
        (params["layers"], jnp.arange(cfg.n_layers)))
    logits = _unembed(cfg, params, x)
    return logits, k_cache, v_cache


# --------------------------------------------------------------------------
# paged KV cache (block-table page pool) — SURVEY.md §7 hard-part 2
# --------------------------------------------------------------------------
#
# Pool layout [L, P, KvH, ps, hd] (int8: {"q": int8 pool, "s": [L, P,
# KvH, ps] f32 scales}; int4: {"q4": [L, P, KvH, ps//2, hd] nibble-packed
# pool — two positions per byte, ops/quant_cache.pack_kv4 — same "s"
# scales}); a slot's logical block j lives in physical page
# table[slot, j] (runtime/paged.py owns allocation; page 0 is the trash
# page for bucket-padding writes — mirrored constant below to avoid a
# models → runtime import cycle).

TRASH_PAGE = 0


def _pad_hd(x, hd_pool: int):
    """Zero-pad the trailing head dim to the pool's 128-lane-padded width
    (engine.py pads the POOL so XLA never materialises padded temp copies
    of it; zeros are inert in both the score and output dots)."""
    d = hd_pool - x.shape[-1]
    if d == 0:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, d)])


def _paged_scatter(pool, i, vals, pg, off):
    """Write ``vals`` [B, KvH, T(, hd)] into layer ``i`` of a page pool at
    (page ``pg``, offset ``off``) per (row, position); pg/off [B, T]."""
    KvH = vals.shape[1]
    pgx = pg[:, None, :]                      # [B, 1, T]
    hx = jnp.arange(KvH)[None, :, None]       # [1, KvH, 1]
    offx = off[:, None, :]
    return pool.at[i, pgx, hx, offx].set(vals)


def _gather_pages(pool, i, tbl, ps: Optional[int] = None):
    """Layer ``i`` pages ``tbl`` [B, NA] → contiguous logical view
    [B, KvH, NA*ps(, hd)] (one XLA gather; only attended pages copied).
    ``ps`` slices a lane-padded last dim back to the true page size
    (scale pools pad to the 128 tile — engine.py)."""
    pages = pool[i, tbl]                      # [B, NA, KvH, ps(, hd)]
    if pages.ndim == 5:
        B, NA, KvH, psp, hd = pages.shape
        return pages.transpose(0, 2, 1, 3, 4).reshape(B, KvH, NA * psp, hd)
    if ps is not None and ps < pages.shape[-1]:
        pages = pages[..., :ps]
    B, NA, KvH, psp = pages.shape
    return pages.transpose(0, 2, 1, 3).reshape(B, KvH, NA * psp)


def paged_insert(cfg: ModelConfig, k_pool, v_pool, ks, vs, table_row,
                 n_valid):
    """Insert a fresh B=1 prefill chunk (ks/vs [L, 1, KvH, Tb, hd] from
    ``prefill_chunk``) into pool pages listed by ``table_row`` [NBLK].
    Positions >= n_valid scatter their garbage to the trash page, so
    admissions allocate pages only for real tokens."""
    quant = isinstance(k_pool, dict)
    quant4 = quant and "q4" in k_pool
    arr = (k_pool["q4"] if quant4 else k_pool["q"]) if quant else k_pool
    L, P, KvH, ps, hd = arr.shape
    if quant4:
        ps *= 2                               # packed pool: 2 positions/byte
    Tb = ks.shape[3]
    t = jnp.arange(Tb, dtype=jnp.int32)
    pg_row = jnp.where(t < n_valid, table_row[t // ps],
                       jnp.int32(TRASH_PAGE))
    off = t % ps
    lx = jnp.arange(L)[:, None, None]
    hx = jnp.arange(KvH)[None, :, None]
    pgx = pg_row[None, None, :]
    offx = off[None, None, :]

    def put(pool, vals):                      # vals [L, KvH, Tb(, hd)]
        return pool.at[lx, pgx, hx, offx].set(vals)

    if quant4:
        from ..ops import quant_cache as QC
        kq, ksc = QC.quantize_kv4(ks)     # codes [-7,7] over the TRUE hd
        vq, vsc = QC.quantize_kv4(vs)
        # admissions always start at offset 0, so the nibble pairs
        # (2j, 2j+1) are byte-aligned: pack directly, no read-modify-write.
        # A pair straddling n_valid writes its garbage high nibble one
        # position past the slot's length — beyond-length entries are
        # never attended and the next decode write overwrites the nibble.
        pg4 = pg_row[0::2]                    # pair page = even member's
        off4 = (off[0::2]) // 2               # packed byte row in the page
        pgx4 = pg4[None, None, :]
        offx4 = off4[None, None, :]

        def put4(pool, vals):                 # vals [L, KvH, Tb//2, hd]
            return pool.at[lx, pgx4, hx, offx4].set(vals)

        k_pool = {"q4": put4(k_pool["q4"],
                             QC.pack_kv4(_pad_hd(kq[:, 0], hd))),
                  "s": put(k_pool["s"], ksc[:, 0])}
        v_pool = {"q4": put4(v_pool["q4"],
                             QC.pack_kv4(_pad_hd(vq[:, 0], hd))),
                  "s": put(v_pool["s"], vsc[:, 0])}
    elif quant:
        from ..ops import quant_cache as QC
        kq, ksc = QC.quantize_kv(ks)      # quantize over the TRUE hd,
        vq, vsc = QC.quantize_kv(vs)      # then pad codes with zeros
        k_pool = {"q": put(k_pool["q"], _pad_hd(kq[:, 0], hd)),
                  "s": put(k_pool["s"], ksc[:, 0])}
        v_pool = {"q": put(v_pool["q"], _pad_hd(vq[:, 0], hd)),
                  "s": put(v_pool["s"], vsc[:, 0])}
    else:
        k_pool = put(k_pool, _pad_hd(ks[:, 0].astype(arr.dtype), hd))
        v_pool = put(v_pool, _pad_hd(vs[:, 0].astype(arr.dtype), hd))
    return k_pool, v_pool


def _paged_kernel_usable(cfg: ModelConfig, mesh, T: int, KvH: int, ps: int,
                         hd: int) -> bool:
    """Route T=1 paged decode through the pallas kernel? Unlike the dense
    path there is no MHA bail-out: the gather fallback copies every
    attended page per step, so the kernel's direct-DMA path wins for MHA
    too (the dense einsum the old measurement favoured is not available
    on a paged pool). TPU_PAGED_FUSED=0 forces the gather+einsum
    reference path — the A/B control for the fused kernel's bandwidth
    win (bench paged_bw_ratio) and the parity suite's oracle."""
    import os
    if os.environ.get("TPU_PAGED_FUSED", "1").lower() in ("0", "false"):
        return False
    from ..ops.attention import resolve_kernels
    from ..ops.pallas.flash import _lane_ok
    mode = resolve_kernels(cfg.kernels)
    if mode not in ("pallas", "interpret") or T != 1:
        return False
    if cfg.n_heads % KvH or ps % 8 or not _lane_ok(hd, mode == "interpret"):
        return False
    if cfg.altern_sliding:
        return False   # per-layer window rides the (traced) mask
    if mesh is not None and mesh.size > 1:
        tp = mesh.shape.get("tp", 1)
        if _paged_dp_axes(cfg, mesh, KvH) is None and tp != mesh.size:
            return False                   # engine enforces dp/tp meshes
        if cfg.n_heads % tp or KvH % tp:
            return False
    return True


def _paged_dp_axes(cfg: ModelConfig, mesh, KvH: int):
    """("dp", h_ax) when this mesh runs the paged forward as a dp-manual
    region (pool PAGE axis sharded over dp, per-shard LOCAL tables —
    runtime/paged.ShardedPageTable + engine.py build that layout), else
    None. Strict divisibility: inside a manual region there is no einsum
    fallback, so the engine refuses dp meshes that fail this check."""
    if mesh is None or mesh.size == 1:
        return None
    shape = dict(mesh.shape)
    dp, tp = shape.get("dp", 1), shape.get("tp", 1)
    if dp <= 1 or dp * tp != mesh.size:
        return None
    if tp > 1 and (cfg.n_heads % tp or KvH % tp):
        return None
    return "dp", ("tp" if tp > 1 else None)


def _paged_attend(cfg: ModelConfig, q, kp, vp, i, tables, lengths, mask,
                  scale, attn_blocks: int, mesh, use_kernel: bool):
    """Attention for one layer of the paged forward: pallas kernel with
    block-table scalar prefetch (T=1), else gather + einsum."""
    quant = isinstance(kp, dict)
    if use_kernel:
        from ..ops.attention import resolve_kernels
        from ..ops.pallas.paged import paged_decode_attention
        interp = resolve_kernels(cfg.kernels) == "interpret"
        if mesh is not None and mesh.size > 1:
            from jax.sharding import PartitionSpec as P
            qkey = "q4" if (quant and "q4" in kp) else "q"
            pool_spec = P(None, None, "tp", None, None)
            pool_specs = ({qkey: pool_spec, "s": P(None, None, "tp", None)}
                          if quant else pool_spec)
            qspec = P(None, None, "tp", None)

            def inner(q, kp, vp, i, tables, lengths):
                return paged_decode_attention(
                    q, kp, vp, i, tables, lengths, scale, cfg.attn_softcap,
                    cfg.sliding_window, nblk=attn_blocks, interpret=interp)

            out = shard_map_compat(
                inner, mesh=mesh,
                in_specs=(qspec, pool_specs, pool_specs, P(), P(None, None),
                          P(None)),
                out_specs=qspec,
                axis_names={"tp"})(q, kp, vp, i, tables, lengths)
        else:
            out = paged_decode_attention(
                q, kp, vp, i, tables, lengths, scale, cfg.attn_softcap,
                cfg.sliding_window, nblk=attn_blocks, interpret=interp)
        if out is not None:
            return out
    tbl = tables[:, :attn_blocks]
    # gather fallback: the pool hd is 128-lane padded; pad q to match
    # (zeros are inert in the score dot) and slice the pad lanes back off
    # the output
    quant4 = quant and "q4" in kp
    hd_q = q.shape[-1]
    qp = _pad_hd(q, ((kp["q4"] if quant4 else kp["q"]) if quant
                     else kp).shape[-1])
    if quant4:
        from ..ops.quant_cache import attend_hf_q4
        ps = kp["q4"].shape[3] * 2
        kw = {"q4": _gather_pages(kp["q4"], i, tbl),
              "s": _gather_pages(kp["s"], i, tbl, ps=ps)}
        vw = {"q4": _gather_pages(vp["q4"], i, tbl),
              "s": _gather_pages(vp["s"], i, tbl, ps=ps)}
        return attend_hf_q4(qp, kw, vw, mask, scale,
                            cfg.attn_softcap)[..., :hd_q]
    if quant:
        from ..ops.quant_cache import attend_hf_q
        ps = kp["q"].shape[3]
        kw = {"q": _gather_pages(kp["q"], i, tbl),
              "s": _gather_pages(kp["s"], i, tbl, ps=ps)}
        vw = {"q": _gather_pages(vp["q"], i, tbl),
              "s": _gather_pages(vp["s"], i, tbl, ps=ps)}
        return attend_hf_q(qp, kw, vw, mask, scale,
                           cfg.attn_softcap)[..., :hd_q]
    kw = _gather_pages(kp, i, tbl)
    vw = _gather_pages(vp, i, tbl)
    return attend_hf(qp, kw, vw, mask, scale, cfg.attn_softcap)[..., :hd_q]


def _paged_scatter4(pool, i, codes, pg, off):
    """int4 twin of ``_paged_scatter``: merge per-position codes [-7, 7]
    ([B, KvH, T, hd]) into the nibble-packed pool at byte row off//2 —
    read-modify-write, one parity class at a time (even offsets share no
    byte with other even offsets, so each pass is conflict-free, and the
    odd pass reads the even pass's merged bytes through the dataflow)."""
    KvH = codes.shape[1]
    hx = jnp.arange(KvH)[None, :, None]
    nib = (codes + 8).astype(jnp.uint8) & 0xF          # code + INT4_BIAS
    n_rows = pool.shape[3]
    for parity in (0, 1):
        sel = (off % 2) == parity                      # [B, T]
        row = off // 2
        # unselected positions write out-of-bounds and drop — writing a
        # stale readback at their (page, row) would race the selected
        # write that shares the byte
        rowx = jnp.where(sel, row, n_rows)[:, None, :]
        pgx = pg[:, None, :]
        cur = pool[i, pgx, hx, jnp.minimum(rowx, n_rows - 1)
                   ].astype(jnp.uint8)                 # [B, KvH, T, hd]
        keep, put = (0xF0, nib) if parity == 0 else (0x0F, nib << 4)
        new = ((cur & keep) | put).astype(jnp.int8)
        pool = pool.at[i, pgx, hx, rowx].set(new, mode="drop")
    return pool


def _scatter_kv_pools(kp, vp, i, k, v, pg_w, off_w):
    """Quantize (int8/int4 pools) and scatter one layer's fresh K/V into
    the pools at (page, offset) per (row, position) — shared by the
    dp-manual region and the single-shard paged forward so the write
    layout can never drift between them."""
    quant = isinstance(kp, dict)
    quant4 = quant and "q4" in kp
    arr = (kp["q4"] if quant4 else kp["q"]) if quant else kp
    hd_pool = arr.shape[-1]
    if quant4:
        from ..ops import quant_cache as QC
        kq, ksc = QC.quantize_kv4(k)
        vq, vsc = QC.quantize_kv4(v)
        kp = {"q4": _paged_scatter4(kp["q4"], i, _pad_hd(kq, hd_pool),
                                    pg_w, off_w),
              "s": _paged_scatter(kp["s"], i, ksc, pg_w, off_w)}
        vp = {"q4": _paged_scatter4(vp["q4"], i, _pad_hd(vq, hd_pool),
                                    pg_w, off_w),
              "s": _paged_scatter(vp["s"], i, vsc, pg_w, off_w)}
        return kp, vp
    if quant:
        from ..ops import quant_cache as QC
        kq, ksc = QC.quantize_kv(k)       # quantize over the TRUE hd,
        vq, vsc = QC.quantize_kv(v)       # then pad codes with zeros
        kp = {"q": _paged_scatter(kp["q"], i, _pad_hd(kq, hd_pool),
                                  pg_w, off_w),
              "s": _paged_scatter(kp["s"], i, ksc, pg_w, off_w)}
        vp = {"q": _paged_scatter(vp["q"], i, _pad_hd(vq, hd_pool),
                                  pg_w, off_w),
              "s": _paged_scatter(vp["s"], i, vsc, pg_w, off_w)}
    else:
        kp = _paged_scatter(kp, i, _pad_hd(k.astype(arr.dtype), hd_pool),
                            pg_w, off_w)
        vp = _paged_scatter(vp, i, _pad_hd(v.astype(arr.dtype), hd_pool),
                            pg_w, off_w)
    return kp, vp


def _paged_write_attend_local(cfg: ModelConfig, q, k, v, kp, vp, i, tables,
                              lengths, positions, mask, scale,
                              attn_blocks: int, use_kernel: bool,
                              interp: bool):
    """Scatter one layer's fresh K/V into the (device-local) page pool and
    attend — the body of the dp-manual region. ``tables`` carry LOCAL page
    indices; on a single device local == global and this is just the
    fused write+attend."""
    quant = isinstance(kp, dict)
    quant4 = quant and "q4" in kp
    arr = (kp["q4"] if quant4 else kp["q"]) if quant else kp
    ps = arr.shape[3] * (2 if quant4 else 1)
    NBLK = tables.shape[1]
    bi = jnp.arange(tables.shape[0])[:, None]
    blk_w = positions // ps
    pg_w = jnp.where(blk_w < NBLK, tables[bi, jnp.minimum(blk_w, NBLK - 1)],
                     jnp.int32(TRASH_PAGE))
    off_w = positions % ps
    kp, vp = _scatter_kv_pools(kp, vp, i, k, v, pg_w, off_w)
    if use_kernel:
        from ..ops.pallas.paged import paged_decode_attention
        out = paged_decode_attention(
            q, kp, vp, i, tables, lengths, scale, cfg.attn_softcap,
            cfg.sliding_window, nblk=attn_blocks, interpret=interp)
        if out is not None:
            return kp, vp, out
    out = _paged_attend(cfg, q, kp, vp, i, tables, lengths, mask, scale,
                        attn_blocks, None, False)
    return kp, vp, out


def _paged_write_attend_dp(cfg: ModelConfig, q, k, v, kp, vp, i, tables,
                           lengths, positions, mask, scale,
                           attn_blocks: int, use_kernel: bool, interp: bool,
                           mesh, h_ax):
    """dp/tp-manual wrapper around ``_paged_write_attend_local``: the pool
    PAGE axis is sharded over dp (each shard's local page 0 is its trash
    page) and tables/lengths/batch rows ride dp — so scatter AND attend
    stay device-local with no collectives, the same property the dense
    kernels get from ``ops/attention._sharded_kernel_call``."""
    from jax.sharding import PartitionSpec as P
    quant = isinstance(kp, dict)
    qkey = "q4" if (quant and "q4" in kp) else "q"
    pool_spec = P(None, "dp", h_ax, None, None)
    pool_specs = ({qkey: pool_spec, "s": P(None, "dp", h_ax, None)}
                  if quant else pool_spec)
    qspec = P("dp", None, h_ax, None)
    kvspec = P("dp", h_ax, None, None)

    def inner(q, k, v, kp, vp, i, tables, lengths, positions, mask):
        return _paged_write_attend_local(
            cfg, q, k, v, kp, vp, i, tables, lengths, positions, mask,
            scale, attn_blocks, use_kernel, interp)

    return shard_map_compat(
        inner, mesh=mesh,
        in_specs=(qspec, kvspec, kvspec, pool_specs, pool_specs, P(),
                  P("dp", None), P("dp"), P("dp", None),
                  P("dp", None, None, None)),
        out_specs=(pool_specs, pool_specs, qspec),
        axis_names={"dp", "tp"})(
        q, k, v, kp, vp, i, tables, lengths, positions, mask)


def paged_insert_dp(cfg: ModelConfig, k_pool, v_pool, ks, vs, table_rows,
                    n_valid, mesh):
    """dp twin of ``paged_insert``: ``table_rows`` [dp, NBLK] carries each
    shard's LOCAL table row — the slot's owning shard gets the real pages,
    every other shard an all-trash row, so the replicated B=1 prefill
    writes land in non-owners' own trash pages and the real insert happens
    only where the slot lives. No collectives, no cross-shard indexing."""
    from jax.sharding import PartitionSpec as P
    quant = isinstance(k_pool, dict)
    qkey = "q4" if (quant and "q4" in k_pool) else "q"
    KvH = (k_pool[qkey] if quant else k_pool).shape[2]
    tp = dict(mesh.shape).get("tp", 1)
    h_ax = "tp" if (tp > 1 and KvH % tp == 0) else None
    pool_spec = P(None, "dp", h_ax, None, None)
    pool_specs = ({qkey: pool_spec, "s": P(None, "dp", h_ax, None)}
                  if quant else pool_spec)
    kvs = P(None, None, h_ax, None, None)

    def inner(kp, vp, ks, vs, trow, n_valid):
        return paged_insert(cfg, kp, vp, ks, vs, trow[0], n_valid)

    return shard_map_compat(
        inner, mesh=mesh,
        in_specs=(pool_specs, pool_specs, kvs, kvs, P("dp", None), P()),
        out_specs=(pool_specs, pool_specs),
        axis_names={"dp", "tp"})(
        k_pool, v_pool, ks, vs, table_rows, n_valid)


def paged_extend_dp(params: Params, cfg: ModelConfig, tokens: jax.Array,
                    k_pool, v_pool, table_rows: jax.Array,
                    lengths: jax.Array, attn_blocks: int,
                    owner: jax.Array, mesh):
    """dp twin of the paged prefix-cache extend (B=1 tail prefill).

    The pool PAGE axis is dp-sharded and the reused prefix lives on ONE
    shard, so the tail replicates its compute across dp the same way
    ``paged_insert_dp`` replicates admissions: ``table_rows`` [dp, NBLK]
    carries the owner's real LOCAL row and all-trash rows elsewhere —
    non-owners scatter into their own trash page and attend garbage,
    and an owner-select psum drops their logits (jnp.where picks 0 for
    the unselected branch, so even a non-owner NaN cannot propagate).
    Manual over dp ONLY: params/pool tp shardings stay GSPMD-auto inside
    the region (the same trick parallel/long_context.py uses for sp),
    and the inner forward is the plain single-shard paged path
    (``mesh=None`` — T>1 rides the gather fallback).
    """
    from jax.sharding import PartitionSpec as P
    quant = isinstance(k_pool, dict)
    qkey = "q4" if (quant and "q4" in k_pool) else "q"
    pool_spec = P(None, "dp", None, None, None)
    pool_specs = ({qkey: pool_spec, "s": P(None, "dp", None, None)}
                  if quant else pool_spec)

    def inner(tokens, kp, vp, trow, lengths, owner):
        logits, kp, vp = forward_with_cache_paged(
            params, cfg, tokens, kp, vp, trow, lengths, attn_blocks,
            mesh=None)
        my = lax.axis_index("dp")
        logits = lax.psum(jnp.where(my == owner, logits, 0.0), "dp")
        return logits, kp, vp

    return shard_map_compat(
        inner, mesh=mesh,
        in_specs=(P(None, None), pool_specs, pool_specs, P("dp", None),
                  P(None), P()),
        out_specs=(P(None, None, None), pool_specs, pool_specs),
        axis_names={"dp"})(
        tokens, k_pool, v_pool, table_rows, lengths, owner)


def forward_with_cache_paged(params: Params, cfg: ModelConfig,
                             tokens: jax.Array, k_pool, v_pool,
                             tables: jax.Array, lengths: jax.Array,
                             attn_blocks: int, mesh=None):
    """Paged twin of ``forward_with_cache``.

    tokens   [B, T] — T=1 decode (pallas kernel path), T>1 extend tails
             (gathered einsum path; B=1 there).
    tables   [B, NBLK] int32 physical page per logical block.
    lengths  [B] int32 cached tokens per row; new token t of row b is
             written to page tables[b, (lengths[b]+t)//ps].
    attn_blocks — static width: blocks attended/gathered (bucket // ps).
    Returns (logits [B, T, V], k_pool, v_pool).
    """
    quant = isinstance(k_pool, dict)
    quant4 = quant and "q4" in k_pool
    k_arr = (k_pool["q4"] if quant4 else k_pool["q"]) if quant else k_pool
    L, P, KvH, ps, hd = k_arr.shape
    if quant4:
        ps *= 2                               # packed pool: 2 positions/byte
    B, T = tokens.shape
    scale = _attn_scale(cfg)
    positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    cos, sin, cos_l, sin_l = _rope_pair(positions, cfg)
    S_attn = attn_blocks * ps
    k_pos = jnp.arange(S_attn, dtype=jnp.int32)[None, None, :]
    q_pos = positions[:, :, None]

    mask = _causal_window_mask(k_pos, q_pos, cfg.sliding_window)
    m_full = (_causal_window_mask(k_pos, q_pos, 0)
              if cfg.altern_sliding else None)

    x = _embed(cfg, params, tokens)
    bi = jnp.arange(B)[:, None]
    # out-of-table blocks (a slot over-running max_seq) redirect to the
    # trash page — never clamp into the slot's LAST live page, which
    # would corrupt resident prefix K/V
    use_kernel = _paged_kernel_usable(cfg, mesh, T, KvH, ps, hd)
    dp_axes = _paged_dp_axes(cfg, mesh, KvH)
    if dp_axes is None:
        # single-shard write indices, computed once outside the scan (the
        # dp-manual region derives its LOCAL indices per shard instead)
        blk_w = positions // ps
        NBLK = tables.shape[1]
        pg_w = jnp.where(blk_w < NBLK,
                         tables[bi, jnp.minimum(blk_w, NBLK - 1)],
                         jnp.int32(TRASH_PAGE))
        off_w = positions % ps
    if dp_axes is not None:
        assert T == 1, ("the dp-manual region decodes only (T=1); T>1 "
                        "extends ride paged_extend_dp, whose inner "
                        "forward is the single-shard path")
        from ..ops.attention import resolve_kernels
        interp = resolve_kernels(cfg.kernels) == "interpret"

    def body(carry, layer_in):
        x, kp, vp = carry
        lp, i = layer_in
        h = _norm(cfg, x, lp["attn_norm_w"], lp.get("attn_norm_b"))
        cos_i, sin_i = _layer_rope(cfg, i, cos, sin, cos_l, sin_l)
        q, k, v = _qkv(cfg, lp, h, cos_i, sin_i)
        k = k.transpose(0, 2, 1, 3)           # [B, KvH, T, hd]
        v = v.transpose(0, 2, 1, 3)
        mask_l = _layer_mask(cfg, i, mask, m_full)
        if dp_axes is not None:
            # dp mesh: pool page axis is dp-sharded with per-shard local
            # tables — scatter AND attend run in one dp/tp-manual region
            kp, vp, attn = _paged_write_attend_dp(
                cfg, q, k, v, kp, vp, i, tables, lengths, positions,
                mask_l, scale, attn_blocks, use_kernel, interp, mesh,
                dp_axes[1])
        else:
            kp, vp = _scatter_kv_pools(kp, vp, i, k, v, pg_w, off_w)
            attn = _paged_attend(cfg, q, kp, vp, i, tables, lengths,
                                 mask_l, scale, attn_blocks, mesh,
                                 use_kernel)
        attn = _proj_out(cfg, lp, attn, B, T)
        x = _residual(cfg, lp, x, h, attn)
        return (x, kp, vp), None

    (x, k_pool, v_pool), _ = lax.scan(
        body, (x, k_pool, v_pool),
        (params["layers"], jnp.arange(cfg.n_layers)))
    logits = _unembed(cfg, params, x)
    return logits, k_pool, v_pool
