"""BERT-family text encoder (embedding models: all-minilm, bge-*, …).

The reference serves embedding images (ollama's `all-minilm`,
`mxbai-embed-large`, …) through llama.cpp's BERT implementation inside the
delegated container (/root/reference/pkg/model/pod.go:11); this is the
TPU-native equivalent. Architecture (classic BERT, post-LayerNorm):

    x = LN(tok_emb[ids] + pos_emb[0..T) + type_emb[0])
    L x [ x = LN(x + MHA_bidir(x));  x = LN(x + gelu-MLP(x)) ]
    embed = mean-pool over valid tokens  (bert.pooling_type = 1)

Everything is one jitted forward over a padded [B, T] batch with a
[B, T] validity mask — bidirectional attention (no causal mask), so
there is no KV cache, no scheduler, no decode loop: an embedding model
loads as runtime/service.EmbeddingModel, not as an Engine.

Weight layout follows the llama.cpp conversion (token_embd /
position_embd / token_types / token_embd_norm; per block attn_{q,k,v},
attn_output, attn_output_norm, ffn_up, ffn_down, layer_output_norm —
all with biases).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    ffn_dim: int
    max_seq_len: int = 512          # learned position table size
    n_token_types: int = 2
    norm_eps: float = 1e-12
    pooling: str = "mean"           # bert.pooling_type 1 = mean
    arch: str = "bert"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def n_params(self) -> int:
        D, F, L, V = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        per_layer = 4 * D * D + 2 * D * F
        return V * D + self.max_seq_len * D + L * per_layer


def init_params(cfg: EncoderConfig, key, dtype=jnp.float32) -> Dict[str, Any]:
    """Random params in the transcoded layout (tests / benches)."""
    D, F, L = cfg.dim, cfg.ffn_dim, cfg.n_layers
    ks = jax.random.split(key, 8)
    g = lambda k, sh: (jax.random.normal(k, sh, jnp.float32) * 0.02  # noqa
                       ).astype(dtype)
    layers = {
        "wq": g(ks[0], (L, D, D)), "wk": g(ks[1], (L, D, D)),
        "wv": g(ks[2], (L, D, D)), "wo": g(ks[3], (L, D, D)),
        "bq": jnp.zeros((L, D), dtype), "bk": jnp.zeros((L, D), dtype),
        "bv": jnp.zeros((L, D), dtype), "bo": jnp.zeros((L, D), dtype),
        "attn_norm_w": jnp.ones((L, D), dtype),
        "attn_norm_b": jnp.zeros((L, D), dtype),
        "w_up": g(ks[4], (L, D, F)), "b_up": jnp.zeros((L, F), dtype),
        "w_down": g(ks[5], (L, F, D)), "b_down": jnp.zeros((L, D), dtype),
        "ffn_norm_w": jnp.ones((L, D), dtype),
        "ffn_norm_b": jnp.zeros((L, D), dtype),
    }
    return {
        "tok_emb": g(ks[6], (cfg.vocab_size, D)),
        "pos_emb": g(ks[7], (cfg.max_seq_len, D)),
        "type_emb": jnp.zeros((cfg.n_token_types, D), dtype),
        "emb_norm_w": jnp.ones((D,), dtype),
        "emb_norm_b": jnp.zeros((D,), dtype),
        "layers": layers,
    }


def _ln(x, w, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def forward(params: Dict[str, Any], cfg: EncoderConfig, tokens: jnp.ndarray,
            n_valid: jnp.ndarray) -> jnp.ndarray:
    """tokens [B, T] int32 (zero-padded), n_valid [B] int32 →
    pooled embeddings [B, D] float32 (mean over valid positions)."""
    B, T = tokens.shape
    D, H, hd = cfg.dim, cfg.n_heads, cfg.head_dim
    eps = cfg.norm_eps
    valid = (jnp.arange(T, dtype=jnp.int32)[None, :]
             < n_valid[:, None])                       # [B, T]

    x = (params["tok_emb"][tokens]
         + params["pos_emb"][None, :T, :]
         + params["type_emb"][0][None, None, :])
    x = _ln(x.astype(jnp.float32), params["emb_norm_w"],
            params["emb_norm_b"], eps)

    # padding mask: every query may attend every VALID key (bidirectional)
    bias = jnp.where(valid[:, None, None, :], 0.0, -1e30)  # [B,1,1,T]
    scale = 1.0 / math.sqrt(hd)

    def body(x, lp):
        q = (x @ lp["wq"] + lp["bq"]).reshape(B, T, H, hd)
        k = (x @ lp["wk"] + lp["bk"]).reshape(B, T, H, hd)
        v = (x @ lp["wv"] + lp["bv"]).reshape(B, T, H, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale + bias
        p = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, D)
        a = a @ lp["wo"] + lp["bo"]
        x = _ln(x + a, lp["attn_norm_w"], lp["attn_norm_b"], eps)
        f = jax.nn.gelu(x @ lp["w_up"] + lp["b_up"], approximate=False)
        f = f @ lp["w_down"] + lp["b_down"]
        x = _ln(x + f, lp["ffn_norm_w"], lp["ffn_norm_b"], eps)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    if cfg.pooling == "cls":
        # bge-family: the [CLS] position's final hidden state
        return x[:, 0, :]
    # mean pooling over valid tokens (pad positions contribute zero)
    m = valid.astype(jnp.float32)[:, :, None]
    pooled = jnp.sum(x * m, axis=1) / jnp.maximum(
        jnp.sum(m, axis=1), 1.0)
    return pooled


_forward_jit = jax.jit(forward, static_argnames=("cfg",))


def _bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power-of-two >= n in [lo, hi] (clamped)."""
    b = lo
    while b < n and b < hi:
        b *= 2
    return min(b, hi)


def embed_batch(params, cfg: EncoderConfig, token_lists) -> np.ndarray:
    """Pad a list of token-id lists and run ONE jitted forward. Batch and
    length pad to power-of-two buckets so the compiled-program count
    stays O(log B x log T) under mixed traffic (same policy as the
    decoder embed path), not one program per exact shape. Returns [N, D]
    float32 (unnormalized — callers normalize per API contract)."""
    n = len(token_lists)
    t_max = max((len(t) for t in token_lists), default=1)
    T = _bucket(max(1, t_max), 16, cfg.max_seq_len)
    B = _bucket(max(1, n), 1, 1 << 20)
    toks = np.zeros((B, T), np.int32)
    lens = np.zeros((B,), np.int32)
    for i, ids in enumerate(token_lists):
        ids = list(ids)[:T]
        toks[i, :len(ids)] = ids
        lens[i] = len(ids)
    out = _forward_jit(params, cfg=cfg, tokens=jnp.asarray(toks),
                       n_valid=jnp.asarray(lens))
    return np.asarray(out[:n], np.float32)
