"""CLIP-style vision tower + multimodal projector (llava family).

The reference serves llava through the delegated ollama image, whose
llama.cpp clip encoder (C++) embeds images into the LLM's token space
(/root/reference/README.md model table lists LLaVA; SURVEY.md §2.2). This
is the TPU-native equivalent: a pure-JAX pre-LN ViT encoder whose patch
"convolution" is expressed as a reshape + one matmul (MXU-shaped — a
P×P/stride-P conv IS a per-patch linear), followed by the llava MLP
projector into the decoder's embedding width.

llava semantics mirrored from the public llava/clip conventions:
- 3×336×336 input, CLIP normalization, 14-px patches → 24×24 = 576 tokens
- features taken from the PENULTIMATE transformer layer (vision_layer -2),
  class token dropped ("patch" feature select)
- projector: Linear(vis_width → dim) · GELU · Linear(dim → dim)

Params tree (layer leaves stacked on a leading axis, like the decoder):

  patch_emb [P*P*3, W]  (pixel order (c, i, j) flattened)
  class_emb [W]
  pos_emb   [n_pos, W]          (n_pos = 1 + n_patches)
  pre_ln_w/b [W]
  layers/
    ln1_w/b [L, W]   wq/wk/wv/wo [L, W, W]   bq/bk/bv/bo [L, W]
    ln2_w/b [L, W]   w_up [L, W, F]  b_up [L, F]
                     w_down [L, F, W]  b_down [L, W]
  mm_0 [W, D]  mm_0_b [D]  mm_2 [D, D]  mm_2_b [D]   (projector)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# CLIP preprocessing constants (openai/clip-vit-large-patch14-336)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    """Static ViT architecture description (CLIP ViT-L/14-336 defaults)."""

    image_size: int = 336
    patch_size: int = 14
    width: int = 1024          # vision hidden size
    n_layers: int = 24         # clip reports 23 used + 1 skipped (select -2)
    n_heads: int = 16
    ffn_dim: int = 4096
    norm_eps: float = 1e-5
    proj_dim: int = 4096       # LLM embedding width (llava-7b: 4096)
    select_layer: int = -2     # penultimate-layer features (llava default)

    @property
    def n_patches_side(self) -> int:
        return self.image_size // self.patch_size

    @property
    def n_patches(self) -> int:
        return self.n_patches_side ** 2

    @property
    def head_dim(self) -> int:
        return self.width // self.n_heads

    def validate(self) -> "VisionConfig":
        assert self.image_size % self.patch_size == 0
        assert self.width % self.n_heads == 0
        return self


TINY_VISION = VisionConfig(image_size=16, patch_size=8, width=32, n_layers=3,
                           n_heads=4, ffn_dim=64, proj_dim=64)


def init_params(cfg: VisionConfig, key: jax.Array,
                dtype=jnp.float32) -> Dict[str, Any]:
    L, W, F, D = cfg.n_layers, cfg.width, cfg.ffn_dim, cfg.proj_dim
    P = cfg.patch_size
    keys = iter(jax.random.split(key, 16))

    def w(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    layers = {
        "ln1_w": jnp.ones((L, W), dtype), "ln1_b": jnp.zeros((L, W), dtype),
        "ln2_w": jnp.ones((L, W), dtype), "ln2_b": jnp.zeros((L, W), dtype),
        "wq": w(next(keys), (L, W, W)), "bq": jnp.zeros((L, W), dtype),
        "wk": w(next(keys), (L, W, W)), "bk": jnp.zeros((L, W), dtype),
        "wv": w(next(keys), (L, W, W)), "bv": jnp.zeros((L, W), dtype),
        "wo": w(next(keys), (L, W, W)), "bo": jnp.zeros((L, W), dtype),
        "w_up": w(next(keys), (L, W, F)), "b_up": jnp.zeros((L, F), dtype),
        "w_down": w(next(keys), (L, F, W)), "b_down": jnp.zeros((L, W), dtype),
    }
    return {
        "patch_emb": w(next(keys), (P * P * 3, W)),
        "class_emb": w(next(keys), (W,)),
        "pos_emb": w(next(keys), (1 + cfg.n_patches, W)),
        "pre_ln_w": jnp.ones((W,), dtype), "pre_ln_b": jnp.zeros((W,), dtype),
        "layers": layers,
        "mm_0": w(next(keys), (W, D)), "mm_0_b": jnp.zeros((D,), dtype),
        "mm_2": w(next(keys), (D, D)), "mm_2_b": jnp.zeros((D,), dtype),
    }


def _ln(x, w, b, eps):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.var(x, axis=-1, keepdims=True)
    return ((x - m) / jnp.sqrt(v + eps)) * w + b


def patchify(cfg: VisionConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] float → patch pixels [B, N, P*P*3].

    The P×P stride-P conv is exactly a per-patch linear over pixels in
    (c, i, j) order — one reshape feeds the MXU a single big matmul.
    """
    B, H, Wd, C = images.shape
    P = cfg.patch_size
    n = cfg.n_patches_side
    x = images.reshape(B, n, P, n, P, C)
    x = x.transpose(0, 1, 3, 5, 2, 4)          # [B, n, n, C, P, P]
    return x.reshape(B, n * n, C * P * P)


def encode(cfg: VisionConfig, params: Dict[str, Any], images: jax.Array
           ) -> jax.Array:
    """images [B, H, W, 3] (CLIP-normalised floats) → [B, n_patches, D]
    projected image tokens in the decoder's embedding space."""
    B = images.shape[0]
    scale = 1.0 / math.sqrt(cfg.head_dim)
    x = patchify(cfg, images) @ params["patch_emb"]      # [B, N, W]
    cls = jnp.broadcast_to(params["class_emb"], (B, 1, cfg.width))
    x = jnp.concatenate([cls, x], axis=1)                # [B, 1+N, W]
    x = x + params["pos_emb"][None, : x.shape[1]]
    x = _ln(x, params["pre_ln_w"], params["pre_ln_b"], cfg.norm_eps)

    n_run = cfg.n_layers + cfg.select_layer + 1 if cfg.select_layer < 0 \
        else cfg.select_layer
    lp_all = params["layers"]
    lp_run = jax.tree_util.tree_map(lambda a: a[:n_run], lp_all)

    def block(x, lp):
        B_, T, W = x.shape
        h = _ln(x, lp["ln1_w"], lp["ln1_b"], cfg.norm_eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(B_, T, cfg.n_heads, -1)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B_, T, cfg.n_heads, -1)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B_, T, cfg.n_heads, -1)
        s = jnp.einsum("bthd,bshd->bhts", q, k,
                       preferred_element_type=jnp.float32) * scale
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        a = jnp.einsum("bhts,bshd->bthd", p, v).reshape(B_, T, W)
        x = x + (a @ lp["wo"] + lp["bo"])
        h2 = _ln(x, lp["ln2_w"], lp["ln2_b"], cfg.norm_eps)
        m = jax.nn.gelu(h2 @ lp["w_up"] + lp["b_up"], approximate=False)
        x = x + (m @ lp["w_down"] + lp["b_down"])
        return x, None

    x, _ = lax.scan(block, x, lp_run)
    feats = x[:, 1:]                                     # drop class token
    h = jax.nn.gelu(feats @ params["mm_0"] + params["mm_0_b"],
                    approximate=False)
    return h @ params["mm_2"] + params["mm_2_b"]         # [B, N, D]


def preprocess(img_hwc_u8: np.ndarray, cfg: VisionConfig) -> np.ndarray:
    """uint8 [H, W, 3] → CLIP-normalised float32 [size, size, 3].

    llava-1.5 convention: pad to square with the CLIP mean color (no
    aspect-ratio distortion), then bicubic-resize to the model's input
    size — matching llama.cpp's clip preprocessing so identical requests
    see the same pixels as the reference stack."""
    from PIL import Image
    h, w = img_hwc_u8.shape[:2]
    if h != w:
        side = max(h, w)
        mean_rgb = tuple(int(round(c * 255)) for c in CLIP_MEAN)
        canvas = Image.new("RGB", (side, side), mean_rgb)
        canvas.paste(Image.fromarray(img_hwc_u8, "RGB"),
                     ((side - w) // 2, (side - h) // 2))
        im = canvas
    else:
        im = Image.fromarray(img_hwc_u8, "RGB")
    im = im.resize((cfg.image_size, cfg.image_size), Image.BICUBIC)
    x = np.asarray(im, np.float32) / 255.0
    return (x - CLIP_MEAN) / CLIP_STD
