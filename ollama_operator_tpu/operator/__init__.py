"""TPU-native control plane: the operator half of the framework.

Mirrors the reference's Go kubebuilder operator (SURVEY.md §1 L1–L4:
/root/reference/internal/controller/model_controller.go,
/root/reference/pkg/model/*, /root/reference/cmd/main.go) as a Python
manager process speaking to the apiserver through a minimal stdlib REST
client — same CRD group (`ollama.ayaka.io/v1`, kind `Model`) so existing
Model CRs apply unchanged, plus TPU extension fields (runtime/topology/
contextLength/sharding) the delegated-to-llama.cpp reference never needed.
"""

from .types import (  # noqa: F401
    GROUP, VERSION, API_VERSION, KIND, PLURAL,
    CONDITION_AVAILABLE, CONDITION_PROGRESSING, CONDITION_REPLICA_FAILURE,
    CONDITION_UNKNOWN, ModelSpecView,
)
