"""Operator entrypoint: `python -m ollama_operator_tpu.operator`.

Flag surface mirrors the reference manager (/root/reference/cmd/main.go:
61-74): health/metrics bind addresses and --leader-elect, plus
--server-image (the TPU runtime image the workloads run, analogous to the
reference's hardcoded OllamaBaseImage pin at pkg/model/pod.go:11 but
overridable like its kustomize image pin).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal


def main(argv=None) -> None:
    p = argparse.ArgumentParser("tpu-ollama-operator")
    p.add_argument("--health-probe-bind-address", default=":8081")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--namespace", default=os.environ.get("WATCH_NAMESPACE"),
                   help="restrict to one namespace (default: all)")
    p.add_argument("--server-image", default=None)
    p.add_argument("--kube-url", default=None,
                   help="apiserver URL (default: in-cluster config)")
    p.add_argument("--workers", type=int, default=2)
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s")

    from .client import KubeClient
    from .manager import Manager

    client = (KubeClient(args.kube_url) if args.kube_url
              else KubeClient.in_cluster())
    host, _, port = args.health_probe_bind_address.rpartition(":")
    mgr = Manager(client, namespace=args.namespace,
                  server_image=args.server_image,
                  leader_elect=args.leader_elect,
                  health_addr=(host or "0.0.0.0", int(port)))
    mgr.start(workers=args.workers)
    signal.pthread_sigmask(signal.SIG_BLOCK, [signal.SIGINT, signal.SIGTERM])
    signal.sigwait([signal.SIGINT, signal.SIGTERM])
    mgr.stop()


if __name__ == "__main__":
    main()
