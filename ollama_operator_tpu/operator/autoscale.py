"""Damped, fail-safe horizontal autoscaler for Model fleets.

Closes the control loop left open by PRs 8-10: `status.replicaStats`
(PR 10) carries per-replica occupancy/goodput/backlog, the admission
layer (PR 8) exposes the TTFT-SLO queue model, and graceful drain
(PR 9) makes replica removal stream-preserving. This module turns those
observations into a desired replica count; the reconciler owns the
actuation (drain-first shrink, Deployment sync, pod remediation).

Design rules, in order of precedence:

1. **Fail static, not closed.** A stale scrape, a missing scrape, or a
   scrape where every replica is unreachable is *no evidence* — the
   scrape path itself is the most likely fault. The loop holds its last
   decision and counts a hold; it never scales on partial data.
2. **Damped.** Hysteresis (sustained-streak thresholds per direction),
   per-direction cooldowns, single-step moves, and a flap detector that
   freezes the loop when direction flips too often inside a window.
3. **Zero-error scale-down.** The autoscaler only *proposes* a lower
   count; the reconciler drains the victim (readyz flips, streams
   finish) before the Deployment shrinks.
4. **Floors.** Desired never drops below ``minReplicas`` except via the
   explicit idle-TTL scale-to-zero path, and remediation replaces pods
   one at a time under exponential backoff — it never shrinks the fleet.

All knobs resolve spec-over-env: `spec.autoscale` fields win, then
`TPU_AUTOSCALE_*` environment defaults, then the constants below.
Counters (`tpu_model_autoscale_*`, `tpu_model_remediation_*`) are
pre-seeded in server/metrics.py and asserted by the metrics-lint job.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..server.metrics import GLOBAL as METRICS

# Action vocabulary for tpu_model_autoscale_decisions_total{action=...}.
ACTIONS = ("up", "down", "to_zero", "wake")
# Hold-cause vocabulary for tpu_model_autoscale_holds_total{cause=...}.
HOLD_CAUSES = ("no_data", "stale", "flap", "cooldown")
# Remediation causes for tpu_model_remediation_replacements_total{cause=...}.
REMEDIATION_CAUSES = ("unreachable", "crash_loop")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass(frozen=True)
class Policy:
    """Resolved autoscale knobs for one Model (spec over env)."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    target_occupancy: float = 0.75   # sustained >= this -> scale up
    low_occupancy: float = 0.30      # sustained <= this (idle queue) -> down
    up_cooldown_s: float = 30.0
    down_cooldown_s: float = 120.0
    up_streak: int = 2               # consecutive hot observations
    down_streak: int = 3             # consecutive cold observations
    idle_ttl_s: float = 0.0          # 0 disables scale-to-zero
    backlog_tokens_per_replica: int = 4096
    stale_s: float = 30.0            # scrape freshness bound (fail static)
    flap_window_s: float = 300.0
    flap_max_flips: int = 4          # direction changes in window -> freeze
    flap_hold_s: float = 180.0
    remediation_backoff_s: float = 10.0
    remediation_backoff_cap_s: float = 300.0
    # which demand signal drives hot/cold (disaggregated pools scale on
    # different physics): "both" (unified fleets — occupancy OR backlog),
    # "backlog" (prefill pool: queued prompt tokens / TTFT risk), or
    # "occupancy" (decode pool: slot occupancy)
    signal: str = "both"


def resolve_policy(spec_block: Dict[str, Any],
                   signal: str = "both") -> Policy:
    """Merge `spec.autoscale` over `TPU_AUTOSCALE_*` env defaults.
    ``signal`` is the default demand signal (a ``signal`` field in the
    spec block still wins)."""
    b = spec_block or {}

    def pick_f(key: str, env: str, default: float) -> float:
        v = b.get(key)
        if v is not None:
            try:
                return float(v)
            except (TypeError, ValueError):
                pass
        return _env_f(env, default)

    def pick_i(key: str, env: str, default: int) -> int:
        v = b.get(key)
        if v is not None:
            try:
                return int(v)
            except (TypeError, ValueError):
                pass
        return _env_i(env, default)

    enabled = b.get("enabled")
    if enabled is None:
        enabled = os.environ.get("TPU_AUTOSCALE", "0") == "1"
    pol = Policy(
        enabled=bool(enabled),
        min_replicas=max(0, pick_i("minReplicas", "TPU_AUTOSCALE_MIN", 1)),
        max_replicas=max(1, pick_i("maxReplicas", "TPU_AUTOSCALE_MAX", 8)),
        target_occupancy=pick_f("targetOccupancy",
                                "TPU_AUTOSCALE_TARGET_OCCUPANCY", 0.75),
        low_occupancy=pick_f("lowOccupancy",
                             "TPU_AUTOSCALE_LOW_OCCUPANCY", 0.30),
        up_cooldown_s=pick_f("upCooldownSeconds",
                             "TPU_AUTOSCALE_UP_COOLDOWN_S", 30.0),
        down_cooldown_s=pick_f("downCooldownSeconds",
                               "TPU_AUTOSCALE_DOWN_COOLDOWN_S", 120.0),
        up_streak=max(1, pick_i("upStreak", "TPU_AUTOSCALE_UP_STREAK", 2)),
        down_streak=max(1, pick_i("downStreak",
                                  "TPU_AUTOSCALE_DOWN_STREAK", 3)),
        idle_ttl_s=pick_f("idleTTLSeconds", "TPU_AUTOSCALE_IDLE_TTL_S", 0.0),
        backlog_tokens_per_replica=pick_i(
            "backlogTokensPerReplica", "TPU_AUTOSCALE_BACKLOG_TOKENS", 4096),
        stale_s=pick_f("staleSeconds", "TPU_AUTOSCALE_STALE_S", 30.0),
        flap_window_s=pick_f("flapWindowSeconds",
                             "TPU_AUTOSCALE_FLAP_WINDOW_S", 300.0),
        flap_max_flips=max(2, pick_i("flapMaxFlips",
                                     "TPU_AUTOSCALE_FLAP_MAX_FLIPS", 4)),
        flap_hold_s=pick_f("flapHoldSeconds",
                           "TPU_AUTOSCALE_FLAP_HOLD_S", 180.0),
        remediation_backoff_s=pick_f("remediationBackoffSeconds",
                                     "TPU_REMEDIATION_BACKOFF_S", 10.0),
        remediation_backoff_cap_s=pick_f("remediationBackoffCapSeconds",
                                         "TPU_REMEDIATION_BACKOFF_CAP_S",
                                         300.0),
        signal=str(b.get("signal") or signal),
    )
    return pol


def pool_policy(autoscale_block: Dict[str, Any],
                pool_block: Dict[str, Any], pool: str) -> Policy:
    """Resolved policy for one disaggregated pool: the pool's block in
    ``spec.disaggregate`` wins over the Model's ``spec.autoscale``, with
    per-pool env floors (``TPU_DISAGG_PREFILL_MIN`` /
    ``TPU_DISAGG_PREFILL_MAX`` / ``TPU_DISAGG_DECODE_MIN`` /
    ``TPU_DISAGG_DECODE_MAX``) and the pool's native demand signal:
    the prefill pool scales on queued backlog tokens, the decode pool
    on slot occupancy."""
    merged = dict(autoscale_block or {})
    merged.update({k: v for k, v in (pool_block or {}).items()
                   if v is not None})
    if pool == "prefill":
        sig = "backlog"
        lo = _env_i("TPU_DISAGG_PREFILL_MIN", 1)
        hi = _env_i("TPU_DISAGG_PREFILL_MAX", 4)
    else:
        sig = "occupancy"
        lo = _env_i("TPU_DISAGG_DECODE_MIN", 1)
        hi = _env_i("TPU_DISAGG_DECODE_MAX", 8)
    if merged.get("minReplicas") is None:
        merged["minReplicas"] = lo
    if merged.get("maxReplicas") is None:
        merged["maxReplicas"] = hi
    return resolve_policy(merged, signal=sig)


@dataclasses.dataclass
class Observation:
    """One scrape pass distilled for the control law.

    ``fresh`` is the fail-static gate: False when the scrape is missing,
    stale, or carries zero reachable replicas while pods exist.
    """

    current: int                 # Deployment's current intent (spec.replicas)
    fresh: bool
    reachable: int = 0
    draining: int = 0
    occupancy: float = 0.0       # mean over reachable non-draining replicas
    queue_depth: int = 0         # queued requests, summed
    backlog_tokens: int = 0      # queued prompt tokens, summed
    goodput_tok_s: float = 0.0   # aggregate useful tokens/s
    ttft_slo_ms: float = 0.0     # 0 = no SLO configured
    busy: bool = False           # any active stream / queue / occupancy
    stale_cause: str = "no_data"  # which hold cause when not fresh


def observe_stats(current: int, stats: Optional[List[Dict[str, Any]]],
                  scraped_age_s: Optional[float], policy: Policy
                  ) -> Observation:
    """Distil a replicaStats list (reconciler mirror schema) into an
    Observation. ``scraped_age_s`` is seconds since the scrape; None
    means the scrape never happened."""
    if stats is None or scraped_age_s is None:
        return Observation(current=current, fresh=False, stale_cause="no_data")
    if scraped_age_s > policy.stale_s:
        return Observation(current=current, fresh=False, stale_cause="stale")
    reachable = [e for e in stats if e.get("state") not in ("unreachable",)]
    draining = [e for e in reachable if e.get("state") == "draining"]
    serving = [e for e in reachable if e.get("state") != "draining"]
    if current > 0 and not reachable:
        # Pods exist but nothing answered: the scrape path (or the whole
        # fleet) is down. No evidence either way -> fail static.
        return Observation(current=current, fresh=False, stale_cause="no_data")
    occ = [float(e.get("occupancy") or 0.0) for e in serving]
    q = sum(int(e.get("queueDepth") or 0) for e in serving)
    bt = sum(int(e.get("backlogTokens") or 0) for e in serving)
    gp = sum(float(e.get("goodputTokS") or 0.0) for e in serving)
    slo = max((float(e.get("ttftSloMs") or 0.0) for e in serving),
              default=0.0)
    active = sum(int(e.get("activeStreams") or 0) for e in reachable)
    busy = bool(active or q or bt or any(o > 0.0 for o in occ))
    return Observation(
        current=current, fresh=True, reachable=len(reachable),
        draining=len(draining),
        occupancy=(sum(occ) / len(occ)) if occ else 0.0,
        queue_depth=q, backlog_tokens=bt, goodput_tok_s=gp,
        ttft_slo_ms=slo, busy=busy)


@dataclasses.dataclass(frozen=True)
class Decision:
    desired: int
    action: str      # "up" | "down" | "to_zero" | "wake" | "hold" | "steady"
    reason: str


class _ModelState:
    __slots__ = ("desired", "hot_streak", "cold_streak", "idle_since",
                 "last_up_at", "last_down_at", "moves", "frozen_until",
                 "remed_backoff_s", "remed_next_ok_at")

    def __init__(self) -> None:
        self.desired: Optional[int] = None
        self.hot_streak = 0
        self.cold_streak = 0
        self.idle_since: Optional[float] = None
        self.last_up_at = float("-inf")
        self.last_down_at = float("-inf")
        self.moves: Deque[Tuple[float, int]] = deque()  # (t, +1|-1)
        self.frozen_until = float("-inf")
        self.remed_backoff_s = 0.0
        self.remed_next_ok_at = float("-inf")


class Autoscaler:
    """Per-Model damped control law. Stateful across reconcile passes;
    the authoritative desired count is also persisted in
    ``status.autoscale.desiredReplicas`` so an operator restart fails
    static (fleet keeps its size) rather than snapping to spec."""

    def __init__(self, now: Callable[[], float] = time.monotonic):
        self._now = now
        self._state: Dict[Tuple[str, str], _ModelState] = {}

    # -- helpers ---------------------------------------------------------
    def _st(self, key: Tuple[str, str]) -> _ModelState:
        st = self._state.get(key)
        if st is None:
            st = self._state[key] = _ModelState()
        return st

    def forget(self, key: Tuple[str, str]) -> None:
        self._state.pop(key, None)

    @staticmethod
    def _hold(cause: str, desired: int, reason: str) -> Decision:
        METRICS.inc("tpu_model_autoscale_holds_total", 1.0,
                    f'{{cause="{cause}"}}')
        return Decision(desired=desired, action="hold", reason=reason)

    def _record_move(self, st: _ModelState, now: float, direction: int,
                     policy: Policy) -> None:
        st.moves.append((now, direction))
        horizon = now - policy.flap_window_s
        while st.moves and st.moves[0][0] < horizon:
            st.moves.popleft()

    def _flapping(self, st: _ModelState, now: float, policy: Policy) -> bool:
        horizon = now - policy.flap_window_s
        flips = 0
        prev = 0
        for t, d in st.moves:
            if t < horizon:
                continue
            if prev and d != prev:
                flips += 1
            prev = d
        return flips >= policy.flap_max_flips

    # -- control law -----------------------------------------------------
    def observe(self, key: Tuple[str, str], policy: Policy,
                obs: Observation, wake: bool = False) -> Decision:
        """One control-law step. Returns the Decision; desired is always
        clamped to [min, max] except the explicit to_zero path."""
        now = self._now()
        st = self._st(key)
        if st.desired is None:
            st.desired = obs.current
        desired = st.desired

        # Wake beats everything: a sleeping model with demand must come
        # back even through cooldowns, freezes, or a stale scrape.
        if wake and desired <= 0:
            st.desired = max(1, policy.min_replicas)
            st.idle_since = None
            st.hot_streak = st.cold_streak = 0
            st.last_up_at = now
            self._record_move(st, now, +1, policy)
            METRICS.inc("tpu_model_autoscale_decisions_total", 1.0,
                        '{action="wake"}')
            return Decision(st.desired, "wake", "wake annotation")

        # Fail static: no usable evidence -> hold the last decision.
        if not obs.fresh:
            if desired <= 0 and obs.current <= 0:
                # Sleeping model with no pods: nothing to scrape, not a
                # fault. Steady state until a wake signal arrives.
                return Decision(desired, "steady", "sleeping")
            return self._hold(obs.stale_cause, desired,
                              f"scrape {obs.stale_cause}; holding {desired}")

        # Flap freeze: too many direction changes inside the window.
        if now < st.frozen_until:
            return self._hold("flap", desired,
                              f"flap freeze until +{st.frozen_until - now:.0f}s")
        if self._flapping(st, now, policy):
            st.frozen_until = now + policy.flap_hold_s
            return self._hold("flap", desired, "flap detected; freezing")

        # Signal extraction. "hot" mirrors the PR 8 queue model: either
        # sustained occupancy at target, raw backlog beyond what the
        # fleet can absorb, or predicted TTFT (backlog / goodput) past
        # the SLO.
        per_rep = policy.backlog_tokens_per_replica * max(1, obs.current)
        slo_risk = False
        if obs.ttft_slo_ms > 0 and obs.backlog_tokens > 0:
            gp = max(obs.goodput_tok_s, 1e-6)
            slo_risk = (obs.backlog_tokens / gp) * 1000.0 > obs.ttft_slo_ms
        occ_hot = obs.occupancy >= policy.target_occupancy
        backlog_hot = obs.backlog_tokens > per_rep or slo_risk
        if policy.signal == "backlog":
            # prefill pool: demand is the queued prompt-token backlog;
            # occupancy of 1-token decode slots says nothing here
            hot = backlog_hot
            cold = obs.queue_depth == 0 and obs.backlog_tokens == 0
        elif policy.signal == "occupancy":
            # decode pool: demand is slot occupancy; backlog queues on
            # the prefill pool, not here
            hot = occ_hot
            cold = (obs.occupancy <= policy.low_occupancy
                    and obs.queue_depth == 0)
        else:
            hot = occ_hot or backlog_hot
            cold = (obs.occupancy <= policy.low_occupancy
                    and obs.queue_depth == 0 and obs.backlog_tokens == 0)
        st.hot_streak = st.hot_streak + 1 if hot else 0
        st.cold_streak = st.cold_streak + 1 if cold else 0
        if obs.busy:
            st.idle_since = None
        elif st.idle_since is None:
            st.idle_since = now

        # Scale up: sustained hot, cooldown passed, headroom left.
        if st.hot_streak >= policy.up_streak and desired < policy.max_replicas:
            if now - st.last_up_at < policy.up_cooldown_s:
                return self._hold("cooldown", desired,
                                  "hot but inside up-cooldown")
            st.desired = min(policy.max_replicas, max(desired, obs.current) + 1)
            st.last_up_at = now
            st.hot_streak = 0
            st.idle_since = None
            self._record_move(st, now, +1, policy)
            METRICS.inc("tpu_model_autoscale_decisions_total", 1.0,
                        '{action="up"}')
            return Decision(st.desired, "up",
                            f"occ={obs.occupancy:.2f} backlog="
                            f"{obs.backlog_tokens} slo_risk={slo_risk}")

        # Scale to zero: fully idle past the TTL (and the TTL is set).
        if (policy.idle_ttl_s > 0 and desired > 0 and st.idle_since is not None
                and now - st.idle_since >= policy.idle_ttl_s):
            if now - st.last_down_at < policy.down_cooldown_s:
                return self._hold("cooldown", desired,
                                  "idle but inside down-cooldown")
            st.desired = 0
            st.last_down_at = now
            st.cold_streak = 0
            self._record_move(st, now, -1, policy)
            METRICS.inc("tpu_model_autoscale_decisions_total", 1.0,
                        '{action="to_zero"}')
            return Decision(0, "to_zero",
                            f"idle {now - st.idle_since:.0f}s >= ttl")

        # Scale down: sustained cold, cooldown passed, above the floor.
        # Going below 1 is only ever the idle-TTL path above — a cold
        # but non-idle fleet keeps at least max(minReplicas, 1).
        floor = max(policy.min_replicas, 1)
        if st.cold_streak >= policy.down_streak and desired > floor:
            if now - st.last_down_at < policy.down_cooldown_s:
                return self._hold("cooldown", desired,
                                  "cold but inside down-cooldown")
            st.desired = desired - 1
            st.last_down_at = now
            st.cold_streak = 0
            self._record_move(st, now, -1, policy)
            METRICS.inc("tpu_model_autoscale_decisions_total", 1.0,
                        '{action="down"}')
            return Decision(st.desired, "down",
                            f"occ={obs.occupancy:.2f} idle queue")

        return Decision(desired, "steady", "within band")

    # -- remediation backoff --------------------------------------------
    def remediation_due(self, key: Tuple[str, str], policy: Policy) -> bool:
        """Gate a replacement behind the exponential backoff. Counts a
        backoff hold when the gate is closed."""
        st = self._st(key)
        if self._now() >= st.remed_next_ok_at:
            return True
        METRICS.inc("tpu_model_remediation_backoff_holds_total", 1.0)
        return False

    def note_remediation(self, key: Tuple[str, str], policy: Policy,
                         cause: str) -> None:
        """Record one replacement: count it and double the backoff."""
        st = self._st(key)
        base = max(policy.remediation_backoff_s, 0.1)
        st.remed_backoff_s = (base if st.remed_backoff_s <= 0
                              else min(st.remed_backoff_s * 2.0,
                                       policy.remediation_backoff_cap_s))
        st.remed_next_ok_at = self._now() + st.remed_backoff_s
        METRICS.inc("tpu_model_remediation_replacements_total", 1.0,
                    f'{{cause="{cause}"}}')

    def note_clean_pass(self, key: Tuple[str, str]) -> None:
        """A fresh scrape with every replica healthy resets the backoff."""
        st = self._st(key)
        st.remed_backoff_s = 0.0
        st.remed_next_ok_at = float("-inf")

    def remediation_backoff_s(self, key: Tuple[str, str]) -> float:
        return self._st(key).remed_backoff_s

    def desired(self, key: Tuple[str, str]) -> Optional[int]:
        st = self._state.get(key)
        return None if st is None else st.desired

    def seed_desired(self, key: Tuple[str, str], desired: int) -> None:
        """Adopt a persisted desired count (status.autoscale) after an
        operator restart so the loop fails static across restarts."""
        st = self._st(key)
        if st.desired is None:
            st.desired = desired
