"""Minimal Kubernetes REST client over the stdlib (no kubernetes pip dep).

The reference gets this layer for free from controller-runtime's
`client.Client` (typed CRUD + caching informers). Here it is explicit: a
thin JSON-over-HTTPS client speaking the apiserver's REST conventions —
enough for the reconciler's ensure/poll ladder (get/create/update/patch/
status/list/watch/events). In-cluster config comes from the serviceaccount
token exactly like client-go's rest.InClusterConfig.

Objects are plain dicts with apiVersion/kind; group→path mapping is
computed (`/api/v1` for core, `/apis/<group>/<version>` otherwise) and
kind→plural comes from a small table covering every kind the operator
touches plus a `<lower>s` fallback.
"""

from __future__ import annotations

import json
import os
import random
import ssl
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..runtime.faults import FAULTS, InjectedFault
from ..runtime.trace import FLIGHT
from ..server.metrics import GLOBAL as METRICS

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

PLURALS = {
    "Model": "models",
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
    "Service": "services",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "PersistentVolume": "persistentvolumes",
    "Pod": "pods",
    "Event": "events",
    "Lease": "leases",
    "Namespace": "namespaces",
    "StorageClass": "storageclasses",
    "Endpoints": "endpoints",
}

CLUSTER_SCOPED = {"PersistentVolume", "Namespace", "StorageClass"}


class ApiError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status
        self.message = message


class NotFound(ApiError):
    pass


class Conflict(ApiError):
    """409 — resourceVersion conflict or AlreadyExists on create."""


def _raise_for(status: int, body: str) -> None:
    msg = body
    try:
        msg = json.loads(body).get("message", body)
    except (json.JSONDecodeError, AttributeError):
        pass
    if status == 404:
        raise NotFound(status, msg)
    if status == 409:
        raise Conflict(status, msg)
    raise ApiError(status, msg)


def resource_path(api_version: str, kind: str, namespace: Optional[str],
                  name: Optional[str] = None,
                  subresource: Optional[str] = None) -> str:
    if "/" in api_version:
        group, version = api_version.split("/", 1)
        base = f"/apis/{group}/{version}"
    else:
        base = f"/api/{api_version}"
    plural = PLURALS.get(kind, kind.lower() + "s")
    parts = [base]
    if namespace and kind not in CLUSTER_SCOPED:
        parts += ["namespaces", namespace]
    parts.append(plural)
    if name:
        parts.append(name)
    if subresource:
        parts.append(subresource)
    return "/".join(parts)


class KubeClient:
    """Direct apiserver client. Thread-safe (no shared mutable state beyond
    the opener)."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None, verify: bool = True,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        if base_url.startswith("https"):
            if ca_file and verify:
                self._ctx: Optional[ssl.SSLContext] = \
                    ssl.create_default_context(cafile=ca_file)
            elif not verify:
                self._ctx = ssl._create_unverified_context()  # tests only
            else:
                self._ctx = ssl.create_default_context()
        else:
            self._ctx = None

    @classmethod
    def in_cluster(cls) -> "KubeClient":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return cls(f"https://{host}:{port}", token=token,
                   ca_file=f"{SA_DIR}/ca.crt")

    # --- raw ------------------------------------------------------------
    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 query: Optional[Dict[str, str]] = None,
                 timeout: Optional[float] = None) -> Tuple[int, str]:
        url = self.base_url + path
        if query:
            from urllib.parse import urlencode
            url += "?" + urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        FAULTS.check("kube.request")
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ctx) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def _json(self, method: str, path: str,
              body: Optional[Dict[str, Any]] = None,
              query: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        status, text = self._request(method, path, body, query)
        if status >= 400:
            _raise_for(status, text)
        return json.loads(text) if text else {}

    # --- typed CRUD -----------------------------------------------------
    def get(self, api_version: str, kind: str, namespace: Optional[str],
            name: str) -> Optional[Dict[str, Any]]:
        try:
            return retry_transient(lambda: self._json(
                "GET", resource_path(api_version, kind, namespace, name)))
        except NotFound:
            return None

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        ns = (obj.get("metadata") or {}).get("namespace")
        return self._json(
            "POST", resource_path(obj["apiVersion"], obj["kind"], ns), obj)

    def update(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        meta = obj.get("metadata") or {}
        return self._json(
            "PUT", resource_path(obj["apiVersion"], obj["kind"],
                                 meta.get("namespace"), meta["name"]), obj)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        meta = obj.get("metadata") or {}
        return self._json(
            "PUT", resource_path(obj["apiVersion"], obj["kind"],
                                 meta.get("namespace"), meta["name"],
                                 "status"), obj)

    def delete(self, api_version: str, kind: str, namespace: Optional[str],
               name: str) -> None:
        try:
            self._json("DELETE",
                       resource_path(api_version, kind, namespace, name))
        except NotFound:
            pass

    def list(self, api_version: str, kind: str,
             namespace: Optional[str] = None,
             label_selector: Optional[str] = None) -> List[Dict[str, Any]]:
        query = {}
        if label_selector:
            query["labelSelector"] = label_selector
        out = retry_transient(lambda: self._json(
            "GET", resource_path(api_version, kind, namespace), query=query))
        return out.get("items", [])

    # --- watch ----------------------------------------------------------
    def watch(self, api_version: str, kind: str,
              namespace: Optional[str] = None,
              resource_version: Optional[str] = None,
              timeout_seconds: int = 300,
              stop: Optional[threading.Event] = None,
              ) -> Iterator[Dict[str, Any]]:
        """Yield watch events ({type, object}) until the server closes the
        stream or `stop` is set. Caller re-invokes with the last seen
        resourceVersion (manager.py handles 410 Gone by relisting)."""
        from urllib.parse import urlencode
        query = {"watch": "true", "timeoutSeconds": str(timeout_seconds)}
        if resource_version:
            query["resourceVersion"] = resource_version
        url = (self.base_url + resource_path(api_version, kind, namespace)
               + "?" + urlencode(query))
        req = urllib.request.Request(url)
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        def _open():
            # reconnect-with-backoff on transient open failures: a watch
            # that dies on an apiserver blip otherwise drops events until
            # the manager's next full relist
            return urllib.request.urlopen(req, timeout=timeout_seconds + 15,
                                          context=self._ctx)

        try:
            with retry_transient(_open) as resp:
                for line in resp:
                    if stop is not None and stop.is_set():
                        return
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        evt = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if evt.get("type") == "ERROR":
                        code = (evt.get("object") or {}).get("code", 500)
                        _raise_for(code, json.dumps(evt.get("object", {})))
                    yield evt
        except urllib.error.HTTPError as e:
            _raise_for(e.code, e.read().decode())
        except (TimeoutError, ConnectionError, urllib.error.URLError):
            return  # caller restarts the watch


def retry_on_conflict(fn: Callable[[], Any], attempts: int = 5,
                      backoff: float = 0.05) -> Any:
    """controller-runtime refetches on 409 inside client.Update retries;
    same idea for our read-modify-write status updates."""
    for i in range(attempts):
        try:
            return fn()
        except Conflict:
            if i == attempts - 1:
                raise
            time.sleep(backoff * (2 ** i))


def _is_transient(e: Exception) -> bool:
    """Failures worth retrying on READ-ONLY verbs: apiserver 5xx, raw
    connection errors, and injected kube.request faults. 4xx (incl.
    NotFound/Conflict, both status < 500) are real answers — never
    retried. Writes are not retried at all: a timed-out create may have
    landed, and blind replays would duplicate side effects."""
    from ..runtime.faults import InjectedFault
    if isinstance(e, ApiError):
        return e.status >= 500
    # HTTPError subclasses URLError — classify by code first
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    return isinstance(e, (urllib.error.URLError, TimeoutError,
                          ConnectionError, InjectedFault))


def retry_transient(fn: Callable[[], Any], attempts: int = 4,
                    backoff: float = 0.05, cap: float = 2.0) -> Any:
    """Capped exponential backoff + full jitter around a read-only call,
    mirroring retry_on_conflict's shape (client-go's default GET backoff
    does the same against apiserver blips)."""
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — filtered by _is_transient
            if not _is_transient(e) or i == attempts - 1:
                raise
            time.sleep(min(cap, backoff * (2 ** i))
                       * (0.5 + random.random() / 2))


def update_status_with_retry(c: "KubeClient", obj: Dict[str, Any],
                             attempts: int = 4, backoff: float = 0.05,
                             cap: float = 2.0) -> Dict[str, Any]:
    """Conflict-aware, transient-tolerant status-subresource write.

    retry_transient deliberately refuses writes (a replayed create can
    duplicate side effects), which left status PUTs during scale churn
    dying on the first apiserver blip. A status PUT is the one write
    where replay is safe: it is a full-replace of a subresource only
    this controller owns, so sending the same payload twice converges
    to the same state. On 409 the live resourceVersion is re-read and
    the same status reapplied — during scale churn the spec and
    workload mirror race us constantly, but the status content itself
    is never contended.

    Returns the written object, or ``obj`` unchanged if the resource
    vanished (deletion races a status write; not an error).
    """
    meta = obj.get("metadata") or {}
    for i in range(attempts):
        try:
            return retry_transient(lambda: c.update_status(obj),
                                   attempts=attempts, backoff=backoff,
                                   cap=cap)
        except Conflict:
            if i == attempts - 1:
                raise
            fresh = c.get(obj.get("apiVersion"), obj.get("kind"),
                          meta.get("namespace"), meta.get("name"))
            if fresh is None:
                return obj
            obj["metadata"]["resourceVersion"] = \
                (fresh.get("metadata") or {}).get("resourceVersion")
        except NotFound:
            return obj
    return obj


def fetch_replica_ps(url: str, timeout: float = 2.0) -> Optional[Dict]:
    """GET a model server's /api/ps and return the parsed body, or None
    on any failure. This is the reconciler's replica-stats scrape (plain
    pod-network HTTP, not an apiserver call): utilization mirroring is an
    optimisation, so it must never be able to wedge the control loop —
    short timeout, no retries, every error collapses to None. The
    autoscaler treats a None (unreachable replica) as missing evidence
    and fails static — but the failure itself must not be silent: each
    one increments tpu_model_scrape_failures_total{cause} and drops a
    flight-recorder `scrape_failed` breadcrumb, so a run of
    autoscale_holds_total{cause="no_data"} is attributable to the
    network / pod / payload fault that caused it. `operator.scrape` is
    the chaos hook: fail modes collapse to None like a real network
    fault, delay modes stall like a slow pod."""
    body = b""
    cause = "network"
    try:
        FAULTS.check("operator.scrape")
        req = urllib.request.Request(url, headers={"Accept":
                                                   "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
        cause = "parse"
        return json.loads(body.decode())
    except InjectedFault as e:
        _scrape_failed(url, "fault", repr(e))
        return None
    except urllib.error.HTTPError as e:
        _scrape_failed(url, "http", f"HTTP {e.code}")
        return None
    except Exception as e:  # noqa: BLE001 — best-effort scrape by design
        _scrape_failed(url, cause, repr(e))
        return None


def _scrape_failed(url: str, cause: str, detail: str) -> None:
    """Account one lost replica scrape (counter + flight breadcrumb)."""
    METRICS.inc("tpu_model_scrape_failures_total", 1.0,
                f'{{cause="{cause}"}}')
    FLIGHT.record("scrape_failed", url=url, cause=cause, detail=detail)


def post_replica_drain(url: str, timeout: float = 2.0) -> bool:
    """POST a model server's /api/drain (idempotent: begins graceful
    drain, readyz flips, streams finish). Returns True when the pod
    acknowledged. Same best-effort contract as the scrape: an
    unreachable pod reads as False and the reconciler retries on the
    next poll."""
    try:
        req = urllib.request.Request(url, data=b"{}", method="POST",
                                     headers={"Accept": "application/json",
                                              "Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except Exception:  # noqa: BLE001 — retried on next reconcile poll
        return False
