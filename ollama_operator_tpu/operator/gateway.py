"""Fleet gateway: cache-aware routing, circuit breaking, stream failover.

ROADMAP item 1 step 1. `replicas: K` used to be a plain round-robin
Service: shared prefixes missed ~(K-1)/K of the time and a replica death
mid-stream was a client-visible error — the one failure PR 9's restart
replay cannot absorb, because replay is replica-local. This stdlib-only
HTTP gateway (one per Model, the Service backend built by workload.py,
drivable in-process against tests/fake_kube.py) closes both gaps:

**Routing law** (cache-aware, deterministic). The routing key is the
request's prompt text (system + prompt for /api/generate, concatenated
message contents for /api/chat), hashed in page-aligned chunks of
``TPU_GATEWAY_HASH_CHUNK`` characters: ``h_i = sha256(h_{i-1} || chunk_i)``
— a chain, so ``h_i`` names the *entire* prefix through chunk i exactly
like the radix tree's page-chain identity (PR 4). Resolution order:

1. **affinity** — longest chain hash present in the gateway's affinity
   table whose replica is routable wins: requests sharing a prefix land
   where that prefix's KV pages already live;
2. **probe**  — on a table miss (gateway restart, evicted entry) the
   request is scattered as a non-mutating ``POST /api/prefix_probe`` to
   routable replicas and the longest ``matched_tokens`` wins;
3. **least_loaded** — no cache evidence anywhere: the replica with the
   fewest active+queued streams from the last ``/api/ps`` scrape (the
   same admission/utilization blocks PR 10 mirrors into
   ``status.replicaStats``).

**Health state machine** (per replica): probe → healthy → ejected
(circuit open) → half_open, fed by the background scrape loop (latency
vs ``TPU_GATEWAY_SLOW_SCRAPE_MS``, ``/readyz``, ``/api/ps``) and by
per-request outcomes (connect errors, 5xx). ``TPU_GATEWAY_EJECT_FAILURES``
consecutive failures open the circuit for ``TPU_GATEWAY_EJECT_S``;
half-open admits EXACTLY ONE live request — success closes the circuit,
failure re-opens it. A replica whose /readyz says "draining" (PR 9/11)
stops receiving work without an ejection: drain is intent, not illness.

**Failover contract** (the journal). Every proxied generation keeps a
journal entry: prompt, resolved options/seed, class/tenant, emitted
frame count and a rolling sha256 of the emitted text. When a replica
dies mid-stream:

- *replayable* streams (PR 9 eligibility: greedy ``temperature==0`` or
  seeded ``seed>=0``, within ``TPU_RESTART_REPLAY_TOKENS``) are
  re-dispatched to a healthy replica; the gateway consumes the new
  stream silently up to the already-emitted offset, verifies the prefix
  against the rolling hash (bit-identity or bust), and continues on the
  SAME client response stream — zero client-visible error frames;
- *queued-but-unstarted* requests (zero frames emitted) fail over
  unconditionally, eligibility irrelevant;
- *non-replayable* streams (unseeded sampling) get the classic
  exactly-once error frame with a computed finite ``retry_after_s``.

**Disaggregated serving** (ISSUE 20, ``TPU_DISAGG``). When the fleet is
split into a prefill pool and a decode pool (pod label
``ollama.ayaka.io/pool``), a replayable generation runs as a PLANNED
failover built from the exact machinery above: the request is first
dispatched to a prefill replica with ``options.disagg_prefill=true``
(the replica prefill + emits ONE token, parks the prompt's KV in its
radix tree, and finishes with ``done_reason:"handoff"``); the gateway
holds that final frame, asks a decode replica to pull the KV pages
straight from the prefill replica (``/api/kv_import`` with
``source=<prefill url>``; the replica-to-replica pull is paced by
``TPU_DISAGG_TRANSFER_MB_S``), then re-dispatches the FULL request to
the decode pool — `_pump`'s skip-and-verify splice consumes the decode
replica's regenerated prefix silently (bit-identity or bust) and
continues on the same client connection. Every degraded rung is a rung
of the existing ladder: transfer failed → the decode replica simply
re-prefills (journal replay); prefill replica died mid-handoff →
replay/requeue on the decode pool; decode pool empty → unified serving
on any routable replica. ``tpu_model_disagg_handoffs_total{result}``
counts the rung taken; non-replayable streams skip the handoff and are
served directly by the decode pool. The ``gateway.handoff`` fault point
fires between the held handoff frame and the KV transfer — the chaos
drills kill the orchestration there and assert zero client-visible
error frames.

Chaos hooks: ``gateway.route`` fires after a replica is picked but
before dispatch (a fail counts as that replica failing); ``gateway.stream``
fires per upstream frame (a fail severs the upstream exactly like a
replica death — the drill the failover machinery is tested by);
``gateway.handoff`` fires mid-handoff (see above).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request
import weakref
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..runtime.faults import FAULTS, InjectedFault
from ..runtime.trace import FLIGHT
from ..server.metrics import GLOBAL as METRICS
from .client import fetch_replica_ps

STATES = ("probe", "healthy", "ejected", "half_open", "draining")
ROUTABLE = ("healthy", "half_open", "probe")
# pod label carrying a replica's pool in a disaggregated fleet
# ("prefill" / "decode"; absent = unified)
POOL_LABEL = "ollama.ayaka.io/pool"
POOLS = ("unified", "prefill", "decode")

# Live gateways for the circuit-state gauges: registered once at module
# import (described + asserted by metrics-lint), summed over instances so
# tests creating several gateways in one process stay coherent.
_LIVE: "weakref.WeakSet[Gateway]" = weakref.WeakSet()


def _state_total(state: str) -> float:
    n = 0
    for gw in list(_LIVE):
        n += gw.state_counts().get(state, 0)
    return float(n)


for _s in STATES:
    METRICS.gauge_fn("tpu_model_gateway_replicas",
                     (lambda s=_s: _state_total(s)),
                     labels=f'{{state="{_s}"}}')


def _pool_total(pool: str) -> float:
    n = 0
    for gw in list(_LIVE):
        n += gw.pool_counts().get(pool, 0)
    return float(n)


for _p in POOLS:
    METRICS.gauge_fn("tpu_model_disagg_pool_replicas",
                     (lambda p=_p: _pool_total(p)),
                     labels=f'{{pool="{_p}"}}')


class NoReplicas(Exception):
    """No routable replica for a request; carries a finite retry hint."""

    def __init__(self, retry_after_s: int):
        super().__init__("no routable replica")
        self.retry_after_s = retry_after_s


class _ClientGone(Exception):
    """The CLIENT connection died — abort, nothing left to fail over for."""


class _UpstreamDead(Exception):
    """The upstream replica connection died mid-request."""


class _ReplayMismatch(Exception):
    """A failover continuation diverged from the already-emitted prefix —
    the bit-identity guarantee cannot be kept, fail the stream instead of
    silently splicing different text."""


class Replica:
    """One backend server and its health/circuit bookkeeping. All fields
    are guarded by the owning Gateway's lock."""

    def __init__(self, name: str, url: str, pool: str = ""):
        self.name = name
        self.url = url.rstrip("/")
        self.pool = pool            # "" unified, else "prefill"/"decode"
        self.state = "probe"
        self.fails = 0              # consecutive failures
        self.ejected_until = 0.0
        self.half_open_busy = False  # the single admitted trial request
        self.load = 0.0             # active + queued streams (last scrape)
        self.scrape_ms = 0.0
        self.last_error = ""
        self.served = 0             # requests dispatched here
        self.failed = 0             # dispatches that counted as failures

    def view(self) -> Dict[str, Any]:
        return {"name": self.name, "url": self.url, "state": self.state,
                "pool": self.pool or "unified",
                "load": self.load, "scrape_ms": round(self.scrape_ms, 1),
                "served": self.served, "failed": self.failed,
                "last_error": self.last_error}


def kube_discovery(kube, namespace: str, app: str,
                   port: int = 11434) -> Callable[[], List[Tuple[str, str]]]:
    """Replica discovery over a KubeClient-shaped object (the real client
    or tests/fake_kube.FakeKube): ready pods of the model workload, named
    by pod name, addressed by podIP. Drain victims are surfaced too — the
    scrape sees their /readyz say draining and parks them. A pod labeled
    ``ollama.ayaka.io/pool`` joins that pool (disaggregated fleets);
    unlabeled pods are the unified fleet."""
    def discover() -> List[Tuple[str, str, str]]:
        try:
            pods = kube.list("v1", "Pod", namespace,
                             label_selector=f"app={app}")
        except Exception as e:  # noqa: BLE001 — discovery is best-effort
            FLIGHT.record("gateway_discovery_failed", error=repr(e))
            return []
        out = []
        for pod in sorted(pods, key=lambda p: (p.get("metadata") or {})
                          .get("name", "")):
            ip = (pod.get("status") or {}).get("podIP")
            name = (pod.get("metadata") or {}).get("name", "")
            pool = ((pod.get("metadata") or {}).get("labels")
                    or {}).get(POOL_LABEL, "")
            if ip and name:
                out.append((name, f"http://{ip}:{port}", pool))
        return out
    return discover


def static_replicas(urls: List[str]) -> List[Tuple[str, str]]:
    return [(f"replica-{i}", u) for i, u in enumerate(urls)]


class _FrozenHash:
    """Stand-in for a journal entry's rolling sha256 restored from the
    persist log: the live hash object died with the previous gateway
    process, but _pump only needs ``hexdigest()`` at the skip boundary —
    where it swaps in the freshly verified hash and the entry is live
    again. ``update`` before that swap would silently corrupt the
    bit-identity check, so it is a hard error."""

    def __init__(self, hexdigest: str):
        self._hex = hexdigest

    def hexdigest(self) -> str:
        return self._hex

    def update(self, _data) -> None:
        raise RuntimeError("restored journal hash is frozen until the "
                           "replayed prefix has been verified")


class _PersistLog:
    """Bounded append-log for the gateway's crash-recovery snapshot
    (request journal + affinity table), living on the weight-cache
    volume so it survives gateway pod churn (``TPU_GATEWAY_PERSIST``).

    Records are NDJSON, buffered and fsynced at most once per flush
    window (``TPU_GATEWAY_PERSIST_FLUSH_MS``) — the journal is advisory
    recovery state, not a database: losing the final window in a crash
    only downgrades a resume to the classic exactly-once error frame.
    The log is bounded by compaction: once enough appends accumulate it
    is atomically rewritten as a snapshot of the current state."""

    def __init__(self, path: str, flush_window_s: float):
        self.path = path
        self.flush_window_s = flush_window_s
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._last_sync = 0.0
        self._since_compact = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def read_all(self) -> List[Dict[str, Any]]:
        """Replay the log left by the previous process (called once,
        before any append). A torn tail line — the write the crash
        interrupted — ends the replay; everything before it parsed."""
        out: List[Dict[str, Any]] = []
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        break
        except OSError:
            return []
        return out

    def append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(json.dumps(rec, separators=(",", ":")))
            self._since_compact += 1
            now = time.monotonic()
            if now - self._last_sync >= self.flush_window_s:
                self._flush_locked(now)
        METRICS.inc("tpu_model_gateway_persist_writes_total")

    def flush(self) -> None:
        with self._lock:
            self._flush_locked(time.monotonic())

    def _flush_locked(self, now: float) -> None:
        if self._buf:
            self._f.write("\n".join(self._buf) + "\n")
            self._buf.clear()
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError) as e:
            FLIGHT.record("gateway_persist_error", error=repr(e))
        self._last_sync = now

    def maybe_compact(self, snapshot: Callable[[], List[Dict[str, Any]]],
                      threshold: int = 16384) -> None:
        """Atomically rewrite the log as the current state snapshot once
        ``threshold`` appends have accumulated — this is what keeps the
        append-log bounded. ``snapshot`` may take the gateway lock;
        appenders never hold it while appending, so the persist→gateway
        lock order here is acyclic."""
        with self._lock:
            if self._since_compact < threshold:
                return
            records = snapshot()
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in records:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "a", encoding="utf-8")
            # buffered records describe state the snapshot already holds
            self._buf.clear()
            self._since_compact = 0

    def close(self) -> None:
        with self._lock:
            self._flush_locked(time.monotonic())
            self._f.close()


class Gateway:
    """One Model's fleet front: routing, circuits, journal, failover."""

    def __init__(self, replicas: Optional[List] = None,
                 discover: Optional[Callable[[], List[Tuple[str, str]]]]
                 = None,
                 host: str = "127.0.0.1", port: Optional[int] = None,
                 scrape_period_s: Optional[float] = None):
        e = os.environ
        self.hash_chunk = max(1, int(e.get("TPU_GATEWAY_HASH_CHUNK", "256")))
        self.probe_enabled = e.get("TPU_GATEWAY_PROBE", "1") != "0"
        self.eject_failures = max(1, int(e.get("TPU_GATEWAY_EJECT_FAILURES",
                                               "3")))
        self.eject_s = float(e.get("TPU_GATEWAY_EJECT_S", "10"))
        self.slow_scrape_ms = float(e.get("TPU_GATEWAY_SLOW_SCRAPE_MS",
                                          "1000"))
        self.scrape_s = (float(e.get("TPU_GATEWAY_SCRAPE_S", "2"))
                         if scrape_period_s is None else scrape_period_s)
        self.hedge_ms = float(e.get("TPU_GATEWAY_HEDGE_MS", "0"))
        self.journal_keep = max(1, int(e.get("TPU_GATEWAY_JOURNAL", "512")))
        self.replay_tokens = int(e.get("TPU_RESTART_REPLAY_TOKENS", "65536"))
        # crash-recovery persistence: "" disables, "1" puts the log on
        # the weight-cache volume, anything else is an explicit path
        raw_persist = e.get("TPU_GATEWAY_PERSIST", "")
        if raw_persist in ("", "0"):
            self.persist_path = ""
        elif raw_persist == "1":
            self.persist_path = os.path.join(
                e.get("TPU_WEIGHT_CACHE") or ".", "gateway-journal.ndjson")
        else:
            self.persist_path = raw_persist
        self.persist_flush_s = max(
            0.0, float(e.get("TPU_GATEWAY_PERSIST_FLUSH_MS", "50")) / 1000.0)
        self.host = host
        self.port = (int(e.get("TPU_GATEWAY_PORT", "11434"))
                     if port is None else port)

        self._discover = discover
        self._lock = threading.Lock()
        self._replicas: "OrderedDict[str, Replica]" = OrderedDict()
        for item in replicas or []:
            pool = ""
            if isinstance(item, tuple):
                if len(item) == 3:
                    name, url, pool = item
                else:
                    name, url = item
            else:
                name, url = f"replica-{len(self._replicas)}", item
            self._replicas[name] = Replica(name, url, pool)
        # chain hash -> replica name, LRU-bounded; the gateway-side mirror
        # of "whose radix tree holds this prefix"
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._affinity_keep = 65536
        # request journal: live entries (streams in flight) + a bounded
        # ring of finished ones (TPU_GATEWAY_JOURNAL) for post-mortems
        self._live: Dict[int, Dict[str, Any]] = {}
        self._done: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._rid = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._scrape_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # streams journaled by the PREVIOUS gateway process, keyed by the
        # client-supplied request_id, waiting for their client to
        # reconnect (resume-or-error per the replay eligibility rules)
        self._restored: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.draining = False
        self._drain_deadline = 0.0
        self._persist: Optional[_PersistLog] = None
        if self.persist_path:
            self._persist = _PersistLog(self.persist_path,
                                        self.persist_flush_s)
            self._restore_from_log()
        _LIVE.add(self)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Gateway":
        """Bind + serve on a background thread; scrape loop too unless
        scrape_period_s was 0 (tests drive scrape_once() by hand)."""
        self.refresh_replicas()
        self.scrape_once()
        gw = self
        handler = type("GatewayHandler", (_Handler,), {"gateway": gw})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        if self.scrape_s > 0:
            self._scrape_thread = threading.Thread(target=self._scrape_loop,
                                                   daemon=True)
            self._scrape_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._persist is not None:
            self._persist.flush()

    def begin_drain(self, timeout_s: Optional[float] = None) -> None:
        """The gateway's SIGTERM contract, mirroring the PR 9 server
        drain: stop accepting new generation work (503 + Retry-After;
        /readyz says draining so the Service parks us), let in-flight
        proxied streams finish within the drain window
        (``TPU_DRAIN_TIMEOUT_S``), flush the persist log, return.
        Streams still live at the deadline stay journaled in the persist
        log — the next gateway process offers them resume-or-error."""
        with self._lock:
            if self.draining:
                return
            self.draining = True
            live = len(self._live)
        timeout = (float(os.environ.get("TPU_DRAIN_TIMEOUT_S", "30"))
                   if timeout_s is None else timeout_s)
        self._drain_deadline = time.monotonic() + timeout
        METRICS.inc("tpu_model_gateway_drain_total")
        FLIGHT.record("gateway_drain", live=live, timeout_s=timeout)
        while time.monotonic() < self._drain_deadline:
            with self._lock:
                if not self._live:
                    break
            time.sleep(0.05)
        if self._persist is not None:
            self._persist.flush()

    def _drain_retry_s(self) -> int:
        """Retry-After for work shed during drain: past the drain window
        a replacement gateway should be answering."""
        remain = self._drain_deadline - time.monotonic()
        return int(max(1, min(30, remain + 1))) if remain > 0 else 1

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _scrape_loop(self) -> None:
        while not self._stop.wait(self.scrape_s):
            try:
                self.refresh_replicas()
                self.scrape_once()
                if self._persist is not None:
                    self._persist.maybe_compact(self._snapshot_records)
            except Exception as e:  # noqa: BLE001 — loop must survive
                FLIGHT.record("gateway_scrape_error", error=repr(e))

    # -- replica set & health -------------------------------------------

    def refresh_replicas(self) -> None:
        if self._discover is None:
            return
        found = [(item if len(item) == 3 else (item[0], item[1], ""))
                 for item in self._discover()]
        with self._lock:
            names = {n for n, _, _ in found}
            for name, url, pool in found:
                if name not in self._replicas:
                    self._replicas[name] = Replica(name, url, pool)
                else:
                    self._replicas[name].url = url.rstrip("/")
                    self._replicas[name].pool = pool
            for name in [n for n in self._replicas if n not in names]:
                del self._replicas[name]

    def state_counts(self) -> Dict[str, int]:
        with self._lock:
            out = {s: 0 for s in STATES}
            for r in self._replicas.values():
                out[r.state] = out.get(r.state, 0) + 1
            return out

    def pool_counts(self) -> Dict[str, int]:
        """Replicas per pool (the per-pool fleet gauges; ejected
        replicas still count — pool membership is topology, not health)."""
        with self._lock:
            out = {p: 0 for p in POOLS}
            for r in self._replicas.values():
                p = r.pool or "unified"
                out[p] = out.get(p, 0) + 1
            return out

    def _disagg_active(self) -> bool:
        """Disaggregated routing is live when TPU_DISAGG allows it
        ("auto"/"1"; "0" kills it) AND both pools currently have a
        routable replica — a half-provisioned split serves unified, so
        rollout/rollback of the pool topology is never an outage."""
        if os.environ.get("TPU_DISAGG", "auto") == "0":
            return False
        with self._lock:
            pools = {r.pool for r in self._replicas.values()
                     if r.state in ROUTABLE}
        return "prefill" in pools and "decode" in pools

    def status(self) -> Dict[str, Any]:
        with self._lock:
            reps = [r.view() for r in self._replicas.values()]
        return {"replicas": reps, "journal": self.journal_stats(),
                "affinity_entries": len(self._affinity)}

    def journal_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"live": len(self._live), "kept": len(self._done)}

    def scrape_once(self) -> None:
        """One health/load pass over every replica: /readyz (latency,
        drain detection) then /api/ps (load). Feeds the state machine."""
        with self._lock:
            targets = list(self._replicas.values())
            self._tick_circuits_locked()
        for r in targets:
            t0 = time.monotonic()
            ready, draining, err = self._get_readyz(r.url)
            ms = (time.monotonic() - t0) * 1000.0
            load = None
            if ready or draining:
                load = self._get_load(r.url)
            with self._lock:
                if r.name not in self._replicas:
                    continue
                r.scrape_ms = ms
                if load is not None:
                    r.load = load
                if draining:
                    if r.state in ("probe", "healthy"):
                        r.state = "draining"
                    continue
                if not ready:
                    self._fail_locked(r, "not_ready", err or "readyz failed")
                elif ms > self.slow_scrape_ms:
                    self._fail_locked(r, "slow", f"scrape {ms:.0f}ms")
                else:
                    # scrape success heals probe/draining; a half-open
                    # circuit is only closed by its single trial REQUEST
                    r.fails = 0
                    r.last_error = ""
                    if r.state in ("probe", "draining"):
                        r.state = "healthy"

    def _get_readyz(self, url: str) -> Tuple[bool, bool, str]:
        try:
            req = urllib.request.Request(f"{url}/readyz")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                return resp.status == 200, False, ""
        except urllib.error.HTTPError as e:
            body = b""
            try:
                body = e.read()
            except Exception:  # noqa: BLE001
                body = b""
            if e.code == 503 and b"drain" in body:
                return False, True, ""
            return False, False, f"readyz HTTP {e.code}"
        except Exception as e:  # noqa: BLE001 — network fault = not ready
            return False, False, repr(e)

    def _get_load(self, url: str) -> Optional[float]:
        try:
            req = urllib.request.Request(f"{url}/api/ps")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                body = json.loads(resp.read().decode())
        except Exception:  # noqa: BLE001 — load is advisory
            return None
        load = 0.0
        for m in (body or {}).get("models") or []:
            life = m.get("lifecycle") or {}
            adm = m.get("admission") or {}
            q = adm.get("queued_by_class") or {}
            load += float(life.get("active_streams") or 0)
            load += float(sum(q.values()) if q else 0)
        return load

    # health feeds (call with lock held) ---------------------------------

    def _tick_circuits_locked(self) -> None:
        now = time.monotonic()
        for r in self._replicas.values():
            if r.state == "ejected" and now >= r.ejected_until:
                r.state = "half_open"
                r.half_open_busy = False

    def _fail_locked(self, r: Replica, cause: str, detail: str) -> None:
        r.fails += 1
        r.failed += 1
        r.last_error = detail
        if r.state == "half_open":
            METRICS.inc("tpu_model_gateway_half_open_probes_total", 1.0,
                        '{result="fail"}')
            self._eject_locked(r, cause)
        elif r.state in ("probe", "healthy", "draining") \
                and r.fails >= self.eject_failures:
            self._eject_locked(r, cause)

    def _eject_locked(self, r: Replica, cause: str) -> None:
        r.state = "ejected"
        r.ejected_until = time.monotonic() + self.eject_s
        r.half_open_busy = False
        METRICS.inc("tpu_model_gateway_ejections_total", 1.0,
                    f'{{cause="{cause}"}}')
        FLIGHT.record("gateway_eject", replica=r.name, cause=cause,
                      detail=r.last_error, eject_s=self.eject_s)

    def _request_ok(self, name: str) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return
            was_half_open = r.state == "half_open"
            r.fails = 0
            r.last_error = ""
            r.half_open_busy = False
            if r.state in ("probe", "half_open"):
                r.state = "healthy"
            if was_half_open:
                METRICS.inc("tpu_model_gateway_half_open_probes_total", 1.0,
                            '{result="ok"}')

    def _request_failed(self, name: str, detail: str) -> None:
        with self._lock:
            r = self._replicas.get(name)
            if r is not None:
                self._fail_locked(r, "failures", detail)

    # -- routing ---------------------------------------------------------

    def chunk_hashes(self, text: str) -> List[str]:
        """Chained page-aligned prefix hashes: only FULL chunks hash (the
        partial tail can't be page-shared by the radix cache either), and
        hash i commits to every chunk before it, so equal h_i ⇔ equal
        prefix through chunk i."""
        h = hashlib.sha256()
        out = []
        for i in range(len(text) // self.hash_chunk):
            chunk = text[i * self.hash_chunk:(i + 1) * self.hash_chunk]
            h.update(chunk.encode("utf-8", "surrogatepass"))
            out.append(h.hexdigest())
        return out

    def _routable_locked(self, exclude: frozenset,
                         pool: Optional[str] = None) -> List[Replica]:
        self._tick_circuits_locked()
        cands = [r for r in self._replicas.values()
                 if r.name not in exclude
                 and (pool is None or r.pool == pool)
                 and (r.state in ("healthy", "probe")
                      or (r.state == "half_open" and not r.half_open_busy))]
        # prefer proven-healthy over unproven; never route to ejected or
        # draining replicas at all
        healthy = [r for r in cands if r.state != "probe"]
        return healthy or cands

    def _retry_after_s(self) -> int:
        with self._lock:
            qtotal = sum(r.load for r in self._replicas.values())
        return int(max(1, min(30, 1 + qtotal)))

    def _remediation_retry_s_locked(self) -> int:
        """Retry-After while the whole candidate set is mid-remediation
        (ejected/draining): the shortest remaining ejection timer is the
        soonest capacity can reappear, so that is the computed hint (the
        PR 8 shed contract — finite and honest, never a flat guess).
        Falls back to the full eject window when nothing is on an
        ejection clock (e.g. every replica is draining)."""
        now = time.monotonic()
        remaining = [r.ejected_until - now for r in self._replicas.values()
                     if r.state == "ejected"]
        soonest = min(remaining) if remaining else self.eject_s
        return int(max(1, min(30, soonest + 1)))

    def pick(self, route_key: str, probe_body: Optional[Dict] = None,
             exclude: frozenset = frozenset(),
             pool: Optional[str] = None) -> Tuple[str, str]:
        """The routing law. Returns (replica name, path) and records the
        request's chain hashes in the affinity table. ``probe_body`` is
        the upstream /api/prefix_probe payload (None disables step 2 —
        bench drives the law without HTTP). ``pool`` restricts the
        candidate set to one disagg pool (affinity entries pointing at
        out-of-pool replicas are simply not routable candidates, so the
        law degrades to probe/least-loaded within the pool)."""
        hashes = self.chunk_hashes(route_key)
        with self._lock:
            cands = self._routable_locked(exclude, pool)
            if not cands:
                raise NoReplicas(self._remediation_retry_s_locked())
            names = {r.name for r in cands}
            chosen, path = None, ""
            for hx in reversed(hashes):
                name = self._affinity.get(hx)
                if name in names:
                    chosen, path = name, "affinity"
                    self._affinity.move_to_end(hx)
                    break
            probe_targets = ([(r.name, r.url) for r in cands]
                             if chosen is None and self.probe_enabled
                             and probe_body is not None and len(cands) > 1
                             else [])
        if chosen is None and probe_targets:
            # longest match wins; on a matched-length tie prefer the
            # lowest tier (0 = all-HBM, restitch-free; 1 = host restitch;
            # 2 = imported fleet-snapshot pages). Tier-aware routing is
            # what makes affinity valid across a replica wake: the woken
            # replica imports the fleet prefix snapshot, answers the
            # probe with matched > 0 at tier 2, and wins shared-prefix
            # traffic away from a cold cohort instead of starting at 0.
            best, best_tier = -1, 3
            payload = json.dumps(probe_body).encode()
            for name, url in probe_targets:
                matched, tier = self._probe_one(url, payload)
                if matched > best or (matched == best and tier < best_tier):
                    best, best_tier, chosen = matched, tier, name
            if best > 0:
                path = "probe"
            else:
                chosen = None  # nobody has the prefix: fall through
        with self._lock:
            cands = self._routable_locked(exclude, pool)
            if not cands:
                raise NoReplicas(self._remediation_retry_s_locked())
            live = {r.name: r for r in cands}
            if chosen is None or chosen not in live:
                chosen = min(live.values(),
                             key=lambda r: (r.load, r.name)).name
                path = "least_loaded"
            r = live[chosen]
            if r.state == "half_open":
                r.half_open_busy = True  # the ONE admitted trial
            r.served += 1
            for hx in hashes:
                self._affinity[hx] = chosen
                self._affinity.move_to_end(hx)
            while len(self._affinity) > self._affinity_keep:
                self._affinity.popitem(last=False)
        if self._persist is not None and hashes:
            self._persist.append({"t": "aff", "r": chosen, "h": hashes})
        METRICS.inc("tpu_model_gateway_routes_total", 1.0,
                    f'{{path="{path}"}}')
        return chosen, path

    def _probe_one(self, url: str, payload: bytes) -> Tuple[int, int]:
        """(matched_tokens, matched_tier) from one replica's probe.
        Errors return (-1, 3): no match, worse than any real tier.
        Pre-tiering replicas omit matched_tier and default to 0."""
        try:
            req = urllib.request.Request(
                f"{url}/api/prefix_probe", data=payload, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                body = json.loads(resp.read().decode())
            return (int(body.get("matched_tokens") or 0),
                    int(body.get("matched_tier") or 0))
        except Exception:  # noqa: BLE001 — a probe miss is just no info
            return -1, 3

    # -- journal ---------------------------------------------------------

    @staticmethod
    def replayable(options: Optional[Dict]) -> bool:
        """PR 9 eligibility, resolved from the request options the
        gateway can see: greedy (temperature == 0) or seeded (seed >= 0).
        Anything else is sampled from an unseeded RNG on the replica —
        a re-run cannot reproduce the emitted prefix."""
        o = options or {}
        t = o.get("temperature")
        if t is not None and float(t) == 0.0:
            return True
        seed = o.get("seed")
        return seed is not None and int(seed) >= 0

    def journal_open(self, body: Dict, route_key: str) -> Dict[str, Any]:
        o = body.get("options") or {}
        with self._lock:
            self._rid += 1
            entry = {
                "id": self._rid,
                "request_id": (str(body["request_id"])
                               if body.get("request_id") else None),
                "model": body.get("model"),
                "prompt_sha": hashlib.sha256(
                    route_key.encode("utf-8", "surrogatepass")).hexdigest(),
                "class": o.get("priority") or o.get("class"),
                "tenant": o.get("tenant"),
                "seed": o.get("seed"),
                "temperature": o.get("temperature"),
                "replayable": self.replayable(o),
                "frames": 0,
                "chars": 0,
                "hash": hashlib.sha256(),
                "replica": None,
                "failovers": 0,
                "outcome": None,
                "handoff_result": None,   # disagg: rung taken, if any
            }
            self._live[entry["id"]] = entry
        if self._persist is not None:
            self._persist.append(self._entry_rec(entry))
        return entry

    def journal_close(self, entry: Dict[str, Any], outcome: str) -> None:
        entry["outcome"] = outcome
        with self._lock:
            self._live.pop(entry["id"], None)
            kept = dict(entry, hash=entry["hash"].hexdigest())
            self._done[entry["id"]] = kept
            while len(self._done) > self.journal_keep:
                self._done.popitem(last=False)
        if self._persist is not None:
            self._persist.append({"t": "close", "id": entry["id"],
                                  "outcome": outcome})

    # -- crash-recovery persistence (TPU_GATEWAY_PERSIST) ----------------

    @staticmethod
    def _entry_rec(entry: Dict[str, Any]) -> Dict[str, Any]:
        """The journal snapshot the next process needs to resume-or-error
        this stream: identity + the resolved eligibility inputs. The raw
        prompt is deliberately NOT persisted — the reconnecting client
        re-sends it, and prompt_sha proves it is the same one."""
        return {"t": "open", "id": entry["id"],
                "request_id": entry.get("request_id"),
                "model": entry.get("model"),
                "prompt_sha": entry["prompt_sha"],
                "class": entry.get("class"), "tenant": entry.get("tenant"),
                "seed": entry.get("seed"),
                "temperature": entry.get("temperature"),
                "replayable": entry["replayable"]}

    def _persist_progress(self, entry: Dict[str, Any]) -> None:
        if self._persist is None:
            return
        self._persist.append({"t": "prog", "id": entry["id"],
                              "frames": entry["frames"],
                              "chars": entry["chars"],
                              "hash": entry["hash"].hexdigest()})

    def _snapshot_records(self) -> List[Dict[str, Any]]:
        """Current affinity + live journal + unclaimed restores as
        persist records: the compaction image — everything a restart
        needs, nothing more."""
        with self._lock:
            by_rep: Dict[str, List[str]] = {}
            for hx, name in self._affinity.items():
                by_rep.setdefault(name, []).append(hx)
            recs: List[Dict[str, Any]] = [
                {"t": "aff", "r": n, "h": hs}
                for n, hs in sorted(by_rep.items())]
            for entry in self._live.values():
                recs.append(self._entry_rec(entry))
                if entry["chars"]:
                    recs.append({"t": "prog", "id": entry["id"],
                                 "frames": entry["frames"],
                                 "chars": entry["chars"],
                                 "hash": entry["hash"].hexdigest()})
            for rec in self._restored.values():
                recs.append(dict(rec, t="open"))
                if rec.get("chars"):
                    recs.append({"t": "prog", "id": rec["id"],
                                 "frames": rec["frames"],
                                 "chars": rec["chars"],
                                 "hash": rec["hash"]})
            return recs

    def _restore_from_log(self) -> None:
        """Replay the append-log left by the previous gateway process:
        affinity records feed the routing table directly; journal entries
        that never closed become resume candidates keyed by the client's
        request_id. Replica HEALTH is deliberately not persisted —
        start() rebuilds it from scratch by scraping the live fleet."""
        open_recs: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        max_id = 0
        for rec in self._persist.read_all():
            t = rec.get("t")
            if t == "aff":
                for hx in rec.get("h") or []:
                    self._affinity[hx] = rec.get("r")
                    self._affinity.move_to_end(hx)
            elif t == "open" and "id" in rec:
                open_recs[rec["id"]] = dict(rec, frames=0, chars=0, hash="")
                max_id = max(max_id, int(rec["id"]))
            elif t == "prog":
                e = open_recs.get(rec.get("id"))
                if e is not None:
                    e.update(frames=rec.get("frames", 0),
                             chars=rec.get("chars", 0),
                             hash=rec.get("hash", ""))
            elif t == "close":
                open_recs.pop(rec.get("id"), None)
        while len(self._affinity) > self._affinity_keep:
            self._affinity.popitem(last=False)
        self._rid = max_id
        for rec in open_recs.values():
            rid = rec.get("request_id")
            if not rid:
                continue  # anonymous stream: no way to reconnect to it
            self._restored[str(rid)] = rec
            METRICS.inc("tpu_model_gateway_persist_restores_total")
        while len(self._restored) > self.journal_keep:
            self._restored.popitem(last=False)
        if open_recs or max_id:
            FLIGHT.record("gateway_persist_restore",
                          streams=len(self._restored), last_id=max_id)

    def _maybe_adopt_restored(self, entry: Dict[str, Any]) -> str:
        """If the request_id names a stream the previous gateway process
        journaled mid-flight, adopt its offsets so _pump splices the
        remainder byte-identically onto this (re)connection. Returns
        "resume", "error" (restored but not replay-eligible: the
        exactly-once error frame is owed), or "" (no match)."""
        rid = entry.get("request_id")
        if not rid:
            return ""
        with self._lock:
            rec = self._restored.pop(rid, None)
        if rec is None:
            return ""
        if rec.get("prompt_sha") != entry["prompt_sha"]:
            FLIGHT.record("gateway_resume_mismatch", request_id=rid)
            return ""  # same id, different prompt: treat as new work
        entry["frames"] = int(rec.get("frames") or 0)
        entry["chars"] = int(rec.get("chars") or 0)
        if entry["chars"] == 0:
            # journaled but nothing emitted yet: a plain re-dispatch,
            # eligibility irrelevant (the queued-but-unstarted rule)
            FLIGHT.record("gateway_resume", request_id=rid, chars=0)
            return "resume"
        entry["hash"] = _FrozenHash(rec.get("hash") or "")
        if not self._failover_eligible(entry):
            return "error"
        METRICS.inc("tpu_model_gateway_failovers_total", 1.0,
                    '{result="replayed"}')
        entry["failovers"] += 1
        FLIGHT.record("gateway_resume", request_id=rid,
                      chars=entry["chars"], frames=entry["frames"])
        return "resume"

    # -- the proxied generation (failover core) --------------------------

    def _dispatch(self, url: str, path: str, payload: bytes):
        """Open the upstream stream. Raises _UpstreamDead on connection
        errors and retryable statuses; urllib.error.HTTPError with a
        client-error status propagates (forwarded, never failed over)."""
        FAULTS.check("gateway.route")
        timeout = (self.hedge_ms / 1000.0) if self.hedge_ms > 0 else 300.0
        req = urllib.request.Request(
            f"{url}{path}", data=payload, method="POST",
            headers={"Content-Type": "application/json",
                     "Accept": "application/x-ndjson"})
        try:
            return urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            if e.code >= 500 or e.code == 429:
                raise _UpstreamDead(f"HTTP {e.code}") from e
            raise
        except InjectedFault as e:
            raise  # pragma: no cover — check() fires before urlopen
        except Exception as e:  # noqa: BLE001 — connect/timeout/refused
            raise _UpstreamDead(repr(e)) from e

    @staticmethod
    def _iter_ndjson(resp):
        buf = b""
        while True:
            chunk = resp.read1(65536) if hasattr(resp, "read1") \
                else resp.read(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if line.strip():
                    yield line
        if buf.strip():
            yield buf

    def stream_request(self, body: Dict, route_key: str, api_path: str,
                       extract: Callable[[Dict], Optional[str]],
                       reframe: Callable[[Dict, str], Dict],
                       emit: Callable[[bytes], None],
                       on_commit: Callable[[], None]) -> Dict[str, Any]:
        """Proxy one generation with journal + cross-replica failover.

        ``extract`` returns a data frame's text piece (None for the final
        frame), ``reframe`` rewrites a frame's piece (the failover
        boundary may split inside an upstream frame), ``emit`` writes one
        NDJSON line to the client (raising _ClientGone when the client is
        gone), ``on_commit`` sends the 200 + chunked headers exactly once
        before the first emitted byte. Returns the journal entry.

        Raises NoReplicas / HTTPError only BEFORE anything was emitted
        (the handler maps them to real HTTP statuses). After commit,
        failures either fail over invisibly or end with the exactly-once
        error frame — never an exception to the handler."""
        entry = self.journal_open(body, route_key)
        if self._maybe_adopt_restored(entry) == "error":
            # interrupted by the previous gateway's death and not
            # replay-eligible: the contract owes exactly one error frame
            on_commit()
            self._stream_error(entry, emit,
                               "stream interrupted by gateway restart "
                               "and is not replayable")
            return entry
        upstream_body = dict(body)
        upstream_body["stream"] = True
        upstream_body.pop("request_id", None)  # gateway-level key only
        payload = json.dumps(upstream_body).encode()
        probe_body = {k: body[k] for k in
                      ("model", "prompt", "system", "template", "raw",
                       "suffix") if k in body} if "prompt" in body else None
        # disaggregated serving (see module docstring): the prefill leg
        # runs first and decides which pool the main loop serves from
        serve_pool: Optional[str] = None
        if self._disagg_active():
            if entry["chars"] == 0 and entry["replayable"]:
                try:
                    outcome = self._disagg_prefill(
                        body, route_key, api_path, entry, extract,
                        reframe, emit, on_commit, probe_body)
                except _ClientGone:
                    self.journal_close(entry, "client_gone")
                    raise
                if outcome == "done":
                    # the stream genuinely finished during prefill (EOG
                    # or stop sequence on the first token): no handoff
                    self.journal_close(entry, "ok")
                    return entry
                entry["handoff_result"] = outcome
                METRICS.inc("tpu_model_disagg_handoffs_total", 1.0,
                            f'{{result="{outcome}"}}')
                serve_pool = (None if outcome == "unified_fallback"
                              else "decode")
            else:
                # non-replayable (or resumed) streams skip the handoff
                # and live on the decode pool: prefill replicas are
                # reserved for prefill work
                serve_pool = "decode"
        tried: set = set()
        budget = max(2 * len(self._replicas) + 2, 4)
        while True:
            budget -= 1
            try:
                name, _path = self.pick(route_key, probe_body=probe_body,
                                        exclude=frozenset(tried),
                                        pool=serve_pool)
            except NoReplicas:
                if serve_pool is not None:
                    # the decode pool lost its last routable replica:
                    # downgrade THIS stream to unified serving rather
                    # than erroring it — pool topology is never worth a
                    # client-visible failure
                    serve_pool = None
                    if entry.get("handoff_result") is None:
                        entry["handoff_result"] = "unified_fallback"
                        METRICS.inc("tpu_model_disagg_handoffs_total", 1.0,
                                    '{result="unified_fallback"}')
                    if budget > 0:
                        continue
                if entry["frames"] == 0:
                    if tried:  # everyone tried and failed: widen once
                        tried = set()
                        try:
                            name, _path = self.pick(route_key,
                                                    probe_body=None)
                        except NoReplicas:
                            self.journal_close(entry, "no_replicas")
                            raise
                    else:
                        self.journal_close(entry, "no_replicas")
                        raise
                else:
                    self._stream_error(entry, emit,
                                       "fleet has no routable replica")
                    return entry
            entry["replica"] = name
            tried.add(name)
            with self._lock:
                r = self._replicas.get(name)
                url = r.url if r is not None else None
            if url is None:
                continue
            try:
                resp = self._dispatch(url, api_path, payload)
            except _UpstreamDead as e:
                self._request_failed(name, str(e))
                if entry["frames"] == 0:
                    METRICS.inc("tpu_model_gateway_failovers_total", 1.0,
                                '{result="requeued"}')
                    entry["failovers"] += 1
                    FLIGHT.record("gateway_failover", request=entry["id"],
                                  replica=name, result="requeued",
                                  detail=str(e))
                    if budget > 0:
                        continue
                    self.journal_close(entry, "exhausted")
                    raise NoReplicas(self._retry_after_s()) from e
                if not self._failover_eligible(entry):
                    self._stream_error(entry, emit, str(e))
                    return entry
                if budget > 0:
                    continue
                self._stream_error(entry, emit, "failover budget exhausted")
                return entry
            except InjectedFault as e:
                self._request_failed(name, repr(e))
                if budget > 0:
                    continue
                self.journal_close(entry, "exhausted")
                raise NoReplicas(self._retry_after_s()) from e
            except urllib.error.HTTPError:
                self.journal_close(entry, "rejected")
                raise
            try:
                self._pump(resp, entry, extract, reframe, emit, on_commit)
            except _ClientGone:
                self.journal_close(entry, "client_gone")
                raise
            except _ReplayMismatch:
                self._request_failed(name, "replay mismatch")
                self._stream_error(entry, emit,
                                   "failover continuation diverged from "
                                   "the emitted prefix")
                return entry
            except Exception as e:  # noqa: BLE001 — upstream died mid-pump
                self._request_failed(name, repr(e))
                was_started = entry["frames"] > 0
                if was_started and not self._failover_eligible(entry):
                    self._stream_error(entry, emit, repr(e))
                    return entry
                result = "replayed" if was_started else "requeued"
                METRICS.inc("tpu_model_gateway_failovers_total", 1.0,
                            f'{{result="{result}"}}')
                entry["failovers"] += 1
                FLIGHT.record("gateway_failover", request=entry["id"],
                              replica=name, result=result, detail=repr(e))
                if budget > 0:
                    continue
                self._stream_error(entry, emit, "failover budget exhausted")
                return entry
            else:
                self._request_ok(name)
                self.journal_close(entry, "ok")
                return entry

    def _disagg_prefill(self, body: Dict, route_key: str, api_path: str,
                        entry: Dict[str, Any],
                        extract: Callable[[Dict], Optional[str]],
                        reframe: Callable[[Dict, str], Dict],
                        emit: Callable[[bytes], None],
                        on_commit: Callable[[], None],
                        probe_body: Optional[Dict]) -> str:
        """The prefill leg of a disaggregated handoff. Dispatches the
        request to a prefill replica with ``options.disagg_prefill``
        injected, streams its frames (prefill + first token) to the
        client, holds the ``done_reason:"handoff"`` final frame, then
        asks a decode replica to pull the KV pages straight from the
        prefill replica. Returns the rung taken:

        - ``"done"``: the stream finished for real during prefill —
          the final frame was emitted, nothing left to serve;
        - ``"transferred"``: KV pages landed on the decode replica; the
          caller serves the full request from the decode pool and the
          splice skips the already-emitted chars;
        - ``"replayed"``: no KV moved (export/import/transfer failed,
          prefill replica died mid-handoff, injected gateway.handoff
          fault) — the decode pool re-prefills; same splice;
        - ``"unified_fallback"``: no routable prefill replica — the
          caller serves unified.

        Every rung keeps the client stream intact; only _ClientGone
        propagates."""
        try:
            name, _ = self.pick(route_key, probe_body=probe_body,
                                pool="prefill")
        except NoReplicas:
            return "unified_fallback"
        with self._lock:
            r = self._replicas.get(name)
            prefill_url = r.url if r is not None else None
        if prefill_url is None:
            return "unified_fallback"
        entry["replica"] = name
        upstream = dict(body)
        upstream["stream"] = True
        upstream.pop("request_id", None)
        upstream["options"] = dict(upstream.get("options") or {},
                                   disagg_prefill=True)
        payload = json.dumps(upstream).encode()
        try:
            resp = self._dispatch(prefill_url, api_path, payload)
            held = self._pump(
                resp, entry, extract, reframe, emit, on_commit,
                intercept_final=lambda f:
                    f.get("done_reason") == "handoff")
        except _ClientGone:
            raise
        except Exception as e:  # noqa: BLE001 — prefill replica failed
            # mid-handoff (or a legacy replica 400ed the option): the
            # decode pool replays/requeues whatever was emitted — the
            # client never sees this
            self._request_failed(name, repr(e))
            FLIGHT.record("gateway_handoff_failed", request=entry["id"],
                          replica=name, detail=repr(e))
            return "replayed"
        self._request_ok(name)
        if held is None:
            return "done"
        try:
            # the drill point: between the held handoff frame and the
            # KV transfer dispatch
            FAULTS.check("gateway.handoff")
            dec_name, _p = self.pick(route_key, probe_body=None,
                                     pool="decode")
            with self._lock:
                r = self._replicas.get(dec_name)
                dec_url = r.url if r is not None else None
            if dec_url is None:
                return "replayed"
            fwd = {k: body[k] for k in
                   ("model", "prompt", "system", "template", "suffix",
                    "raw", "context", "messages", "tools", "keep_alive")
                   if body.get(k) is not None}
            fwd["source"] = prefill_url
            timeout = float(os.environ.get("TPU_DISAGG_HANDOFF_TIMEOUT_S",
                                           "30") or 30)
            req = urllib.request.Request(
                f"{dec_url}/api/kv_import", data=json.dumps(fwd).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                res = json.loads(resp.read().decode())
            pages = int(res.get("imported_pages") or 0)
        except Exception as e:  # noqa: BLE001 — incl. NoReplicas and
            # injected gateway.handoff faults
            # any transfer trouble is a soft downgrade: the decode pool
            # re-prefills from the prompt (journal replay), losing only
            # the transfer win, never the stream
            FLIGHT.record("gateway_kv_transfer_failed",
                          request=entry["id"], prefill=name,
                          detail=repr(e))
            return "replayed"
        FLIGHT.record("gateway_handoff", request=entry["id"], prefill=name,
                      decode=dec_name, pages=pages, chars=entry["chars"])
        return "transferred" if pages > 0 else "replayed"

    def _failover_eligible(self, entry: Dict[str, Any]) -> bool:
        """Mid-stream failover needs PR 9 replay eligibility AND the
        emitted prefix to fit the replay budget (frames ≈ detokenizer
        pieces ≥ tokens, so the frame count is a conservative proxy)."""
        return bool(entry["replayable"]
                    and entry["frames"] <= self.replay_tokens)

    def _stream_error(self, entry: Dict[str, Any],
                      emit: Callable[[bytes], None], detail: str) -> None:
        """The classic exactly-once terminal error frame (PR 9 contract)
        with a computed finite Retry-After."""
        retry = self._retry_after_s()
        METRICS.inc("tpu_model_gateway_failovers_total", 1.0,
                    '{result="errored"}')
        FLIGHT.record("gateway_stream_error", request=entry["id"],
                      replica=entry["replica"], detail=detail,
                      retry_after_s=retry)
        self.journal_close(entry, "errored")
        frame = {"error": f"replica failed mid-stream and the request is "
                          f"not replayable ({detail})",
                 "retry_after_s": retry}
        try:
            emit(json.dumps(frame).encode() + b"\n")
        except _ClientGone:
            pass  # lint: allow(exception-hygiene): client left before the
            # terminal error frame — nothing further to deliver it to

    def _pump(self, resp, entry: Dict[str, Any],
              extract: Callable[[Dict], Optional[str]],
              reframe: Callable[[Dict, str], Dict],
              emit: Callable[[bytes], None],
              on_commit: Callable[[], None],
              intercept_final: Optional[Callable[[Dict], bool]] = None
              ) -> Optional[Dict[str, Any]]:
        """Forward one upstream stream to the client. After a failover,
        ``entry['chars']`` > 0: the fresh upstream regenerates from token
        zero, so consume silently up to that offset, verify the replayed
        prefix is BIT-IDENTICAL to what the client already saw (rolling
        sha256), then splice the remainder onto the same client stream.

        ``intercept_final`` (the disagg handoff hook): a predicate over
        the upstream's final frame — when it answers True the frame is
        HELD (returned, not emitted) so the caller can continue the same
        client stream on another replica. Returns the held frame, or
        None when the stream completed normally."""
        skip = entry["chars"]
        prefix_hex = entry["hash"].hexdigest()
        verify = hashlib.sha256()
        acc = 0
        saw_final = False
        held: Optional[Dict[str, Any]] = None
        for line in self._iter_ndjson(resp):
            FAULTS.check("gateway.stream")
            frame = json.loads(line)
            if "error" in frame and "done" not in frame:
                raise _UpstreamDead(f"upstream error frame: "
                                    f"{frame['error']!r}")
            piece = extract(frame)
            if piece is None:
                if acc < skip:
                    raise _ReplayMismatch(
                        f"replay finished at {acc} < {skip} chars")
                saw_final = True
                if intercept_final is not None and intercept_final(frame):
                    held = frame
                    continue
                on_commit()
                try:
                    emit(line + b"\n")
                except (BrokenPipeError, ConnectionResetError) as e:
                    raise _ClientGone() from e
                continue
            if acc + len(piece) <= skip:
                verify.update(piece.encode("utf-8", "surrogatepass"))
                acc += len(piece)
                if acc == skip:
                    if verify.hexdigest() != prefix_hex:
                        raise _ReplayMismatch("replayed prefix hash "
                                              "mismatch")
                    # verify holds the identical byte stream — swapping
                    # it in re-arms an entry whose hash was a frozen
                    # hexdigest restored from the persist log
                    entry["hash"] = verify
                continue
            if acc < skip:
                head, piece = piece[:skip - acc], piece[skip - acc:]
                verify.update(head.encode("utf-8", "surrogatepass"))
                acc = skip
                if verify.hexdigest() != prefix_hex:
                    raise _ReplayMismatch("replayed prefix hash mismatch")
                entry["hash"] = verify
                frame = reframe(frame, piece)
                line = json.dumps(frame).encode()
            acc += len(piece)
            on_commit()
            try:
                emit(line + b"\n")
            except (BrokenPipeError, ConnectionResetError) as e:
                raise _ClientGone() from e
            entry["frames"] += 1
            entry["chars"] += len(piece)
            entry["hash"].update(piece.encode("utf-8", "surrogatepass"))
            self._persist_progress(entry)
        if not saw_final:
            raise _UpstreamDead("upstream closed before the final frame")
        return held

    # -- raw proxy (non-journaled endpoints) -----------------------------

    def proxy(self, method: str, path: str, payload: Optional[bytes],
              exclude: frozenset = frozenset()):
        """Least-loaded pass-through for endpoints outside the failover
        contract (pull/show/tags/...). Unstarted requests retry once per
        replica; the raw response object is handed back to the handler."""
        tried = set(exclude)
        last: Optional[Exception] = None
        for _ in range(max(len(self._replicas), 1)):
            with self._lock:
                cands = self._routable_locked(frozenset(tried))
                if not cands:
                    break
                r = min(cands, key=lambda x: (x.load, x.name))
                name, url = r.name, r.url
            tried.add(name)
            req = urllib.request.Request(
                f"{url}{path}", data=payload, method=method,
                headers=({"Content-Type": "application/json"}
                         if payload is not None else {}))
            try:
                return urllib.request.urlopen(req, timeout=300.0)
            except urllib.error.HTTPError as e:
                if e.code >= 500 or e.code == 429:
                    self._request_failed(name, f"HTTP {e.code}")
                    last = e
                    continue
                return e  # client error: forward verbatim
            except Exception as e:  # noqa: BLE001 — connect/timeout
                self._request_failed(name, repr(e))
                last = e
        raise NoReplicas(self._retry_after_s()) from last

    def aggregate_ps(self) -> Dict[str, Any]:
        """Fleet /api/ps: every replica's models list annotated with the
        replica name, plus the gateway's own health table."""
        with self._lock:
            targets = [(r.name, r.url) for r in self._replicas.values()
                       if r.state not in ("ejected",)]
        models = []
        for name, url in targets:
            # shares the reconciler's scrape contract: an unreachable
            # replica is skipped but accounted (scrape_failures{cause})
            body = fetch_replica_ps(f"{url}/api/ps")
            if body is None:
                continue
            for m in (body or {}).get("models") or []:
                m = dict(m)
                m["replica"] = name
                models.append(m)
        return {"models": models, "gateway": self.status()}


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    gateway: Gateway  # injected by Gateway.start()

    def log_message(self, *_a):  # quiet; the journal is the record
        pass

    # -- plumbing -------------------------------------------------------

    def _json_body(self) -> Dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        if not raw:
            return {}
        return json.loads(raw.decode())

    def _send_json(self, obj, status=200,
                   headers: Optional[Dict[str, str]] = None):
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _start_stream(self):
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def _chunk(self, data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _end_stream(self):
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    # -- GET ------------------------------------------------------------

    def do_GET(self):
        path = self.path.split("?")[0]
        gw = self.gateway
        if path == "/metrics":
            data = METRICS.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return
        if path in ("/healthz", "/livez"):
            self._send_json({"status": "ok"})
            return
        if path == "/readyz":
            if gw.draining:
                # same drain signature the gateway itself looks for in
                # replica readyz bodies: intent, not illness
                self._send_json({"status": "draining"}, 503)
                return
            counts = gw.state_counts()
            routable = sum(counts.get(s, 0) for s in ROUTABLE)
            if routable > 0:
                self._send_json({"status": "ok", "replicas": counts})
            else:
                self._send_json({"status": "no routable replica",
                                 "replicas": counts}, 503)
            return
        if path == "/gateway/status":
            self._send_json(gw.status())
            return
        if path == "/api/ps":
            self._send_json(gw.aggregate_ps())
            return
        # everything else: pass through to a routable replica
        try:
            resp = gw.proxy("GET", self.path, None)
        except NoReplicas as e:
            self._send_json({"error": "no routable replica"}, 503,
                            headers={"Retry-After": str(e.retry_after_s)})
            return
        self._forward_response(resp)

    def _forward_response(self, resp):
        body = resp.read()
        status = getattr(resp, "status", None) or resp.getcode()
        self.send_response(status)
        ctype = resp.headers.get("Content-Type") or "application/json"
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- POST -----------------------------------------------------------

    def do_POST(self):
        path = self.path.split("?")[0]
        if self.gateway.draining and path in ("/api/generate", "/api/chat"):
            # begin_drain: finish in-flight streams, shed new work with
            # a finite hint pointing past the drain window
            self._send_json(
                {"error": "gateway draining"}, 503,
                headers={"Retry-After": str(self.gateway._drain_retry_s())})
            return
        try:
            if path == "/api/generate":
                self._proxy_generation(
                    path,
                    extract=lambda f: (None if f.get("done")
                                       else f.get("response", "")),
                    reframe=lambda f, t: dict(f, response=t),
                    final_text_key="response")
            elif path == "/api/chat":
                self._proxy_generation(
                    path,
                    extract=lambda f: (None if f.get("done")
                                       else (f.get("message") or {})
                                       .get("content", "")),
                    reframe=lambda f, t: dict(
                        f, message=dict(f.get("message") or {}, content=t)),
                    final_text_key="message")
            else:
                body = self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                resp = self.gateway.proxy("POST", self.path, body or b"{}")
                self._stream_through(resp)
        except NoReplicas as e:
            self._send_json({"error": "no routable replica"}, 503,
                            headers={"Retry-After": str(e.retry_after_s)})
        except urllib.error.HTTPError as e:
            self._forward_response(e)
        except _ClientGone:
            pass  # lint: allow(exception-hygiene): client hung up; there
            # is no one left to report the abort to
        except (BrokenPipeError, ConnectionResetError):
            pass  # lint: allow(exception-hygiene): same — client is gone
        except Exception as e:  # noqa: BLE001
            try:
                self._send_json({"error": f"gateway internal: {e}"}, 500)
            except (BrokenPipeError, ConnectionResetError):
                pass  # lint: allow(exception-hygiene): client gone mid-500

    def _stream_through(self, resp):
        """Chunked pass-through for non-journaled streaming endpoints
        (/api/pull progress frames etc.)."""
        status = getattr(resp, "status", None) or resp.getcode()
        if status != 200:
            self._forward_response(resp)
            return
        self._start_stream()
        while True:
            chunk = resp.read1(65536) if hasattr(resp, "read1") \
                else resp.read(65536)
            if not chunk:
                break
            self._chunk(chunk)
        self._end_stream()

    def _proxy_generation(self, api_path, extract, reframe, final_text_key):
        gw = self.gateway
        body = self._json_body()
        if api_path == "/api/chat":
            route_key = "".join((m.get("content") or "")
                                for m in body.get("messages") or [])
        else:
            route_key = ((body.get("system") or "")
                         + (body.get("prompt") or ""))
        client_stream = body.get("stream", True)
        state = {"started": False}
        if client_stream:
            def on_commit():
                if not state["started"]:
                    state["started"] = True
                    self._start_stream()

            def emit(line: bytes):
                try:
                    self._chunk(line)
                except (BrokenPipeError, ConnectionResetError) as e:
                    raise _ClientGone() from e

            try:
                gw.stream_request(body, route_key, api_path, extract,
                                  reframe, emit, on_commit)
            except NoReplicas as e:
                if state["started"]:
                    raise  # handler swallows; stream already errored
                self._send_json(
                    {"error": "no routable replica"}, 503,
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            if not state["started"]:
                # upstream produced only a final frame path that never
                # committed (shouldn't happen) — degrade gracefully
                self._send_json({"error": "empty upstream stream"}, 502)
                return
            self._end_stream()
        else:
            # non-streaming client: the gateway still streams upstream
            # (failover needs frames), aggregates, and answers once
            pieces: List[str] = []
            final: Dict[str, Any] = {}

            def on_commit():
                state["started"] = True

            def emit(line: bytes):
                frame = json.loads(line)
                if frame.get("done"):
                    final.update(frame)
                elif "error" in frame:
                    final.update(frame)
                else:
                    piece = extract(frame)
                    if piece:
                        pieces.append(piece)

            gw.stream_request(body, route_key, api_path, extract, reframe,
                              emit, on_commit)
            if "error" in final:
                retry = final.get("retry_after_s")
                self._send_json(
                    {"error": final["error"]}, 502,
                    headers=({"Retry-After": str(int(retry))}
                             if retry else None))
                return
            text = "".join(pieces)
            if final_text_key == "message":
                final["message"] = dict(final.get("message")
                                        or {"role": "assistant"},
                                        content=text)
            else:
                final[final_text_key] = text
            self._send_json(final)


# ---------------------------------------------------------------------------
# entrypoint (the gateway Deployment's container runs this module)
# ---------------------------------------------------------------------------

def _discovery_from_env():
    e = os.environ
    urls = e.get("TPU_GATEWAY_REPLICAS")
    if urls:
        fixed = static_replicas([u.strip() for u in urls.split(",")
                                 if u.strip()])
        return fixed, None
    selector = e.get("TPU_GATEWAY_SELECTOR")
    if selector and "/" in selector:
        namespace, app = selector.split("/", 1)
        from .client import KubeClient
        return None, kube_discovery(KubeClient(), namespace, app)
    raise SystemExit("gateway needs TPU_GATEWAY_REPLICAS (static URLs) or "
                     "TPU_GATEWAY_SELECTOR (namespace/app)")


def main() -> None:
    replicas, discover = _discovery_from_env()
    gw = Gateway(replicas=replicas, discover=discover, host="0.0.0.0")
    gw.start()
    FLIGHT.record("gateway_started", port=gw.port,
                  replicas=len(gw._replicas))
    stop = threading.Event()

    def _on_term(signum, _frame):
        FLIGHT.record("gateway_sigterm", signal=int(signum))
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    try:
        while not stop.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass
    # SIGTERM / Ctrl-C: stop accepting, finish proxied streams within
    # the drain window, persist the journal, exit (the PR 9 contract,
    # gateway edition — preStop in pod.py covers the Service lag)
    gw.begin_drain()
    gw.stop()


if __name__ == "__main__":
    main()
