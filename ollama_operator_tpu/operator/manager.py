"""Manager process: watch → workqueue → reconcile, plus the runtime-host
concerns the reference gets from controller-runtime (/root/reference/cmd/
main.go:54-150): leader election, healthz/readyz, metrics.

Differences from the reference worth knowing:
- The reference watches ONLY Model CRs (model_controller.go:172-176), so
  drift in owned Deployments is corrected only on Model events/requeues
  (SURVEY.md §3.1 note). We additionally watch owned workloads by label
  and map them back to their Model — drift heals promptly.
- Leader election uses a coordination.k8s.io/v1 Lease directly (client-go's
  leaselock under resourcelock, same semantics, id default
  `300b498d.ayaka.io` kept for drop-in parity with cmd/main.go:108).
- The workqueue enforces single-reconcile-per-key with dedupe and
  rate-limited requeue — the controller-runtime concurrency model the
  whole reconciler assumes.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from .client import ApiError, Conflict, KubeClient, NotFound
from .reconciler import ModelReconciler, Result
from .recorder import Recorder
from .types import API_VERSION, KIND

log = logging.getLogger("manager")

LEASE_NAME = "300b498d.ayaka.io"  # cmd/main.go:108's election id


class WorkQueue:
    """Deduping delay queue of (namespace, name) keys with
    single-processor-per-key semantics (controller-runtime's workqueue
    processing/dirty sets): a key handed to a worker is *processing*; an
    add() arriving meanwhile marks it *dirty* instead of re-queueing, and
    done() re-queues dirty keys — so two workers can never reconcile one
    Model concurrently."""

    def __init__(self):
        self._cond = threading.Condition()
        self._heap: list = []          # (ready_at, seq, key)
        self._pending: Dict[Tuple[str, str], float] = {}
        self._processing: set = set()
        self._dirty: set = set()
        self._seq = itertools.count()
        self._shutdown = False

    def add(self, key: Tuple[str, str], delay: float = 0.0) -> None:
        ready = time.monotonic() + delay
        with self._cond:
            if key in self._processing:
                self._dirty.add(key)
                return
            cur = self._pending.get(key)
            if cur is not None and cur <= ready:
                return  # already queued sooner
            self._pending[key] = ready
            heapq.heappush(self._heap, (ready, next(self._seq), key))
            self._cond.notify()

    def get(self, timeout: Optional[float] = None
            ) -> Optional[Tuple[str, str]]:
        """Pop a ready key and mark it processing; callers MUST call
        done(key) when finished with it."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._shutdown:
                    return None
                now = time.monotonic()
                while self._heap:
                    ready, _, key = self._heap[0]
                    if self._pending.get(key) != ready:
                        heapq.heappop(self._heap)  # superseded entry
                        continue
                    break
                if self._heap:
                    ready, _, key = self._heap[0]
                    if ready <= now:
                        heapq.heappop(self._heap)
                        del self._pending[key]
                        self._processing.add(key)
                        return key
                    wait = ready - now
                else:
                    wait = None
                if deadline is not None:
                    remain = deadline - now
                    if remain <= 0:
                        return None
                    wait = remain if wait is None else min(wait, remain)
                self._cond.wait(wait)

    def done(self, key: Tuple[str, str], requeue_after: float = -1.0) -> None:
        """Finish processing. requeue_after >= 0 schedules the next run;
        a dirty mark (event during processing) requeues immediately."""
        with self._cond:
            self._processing.discard(key)
            dirty = key in self._dirty
            self._dirty.discard(key)
        if dirty:
            self.add(key)
        elif requeue_after >= 0:
            self.add(key, delay=requeue_after)

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


class LeaderElector:
    """Lease-based leader election (coordination.k8s.io/v1)."""

    def __init__(self, client: KubeClient, namespace: str,
                 identity: Optional[str] = None,
                 lease_name: str = LEASE_NAME,
                 lease_seconds: int = 15, retry_period: float = 2.0):
        self.c = client
        self.ns = namespace
        self.id = identity or f"{socket.gethostname()}_{os.getpid()}"
        self.name = lease_name
        self.lease_seconds = lease_seconds
        self.retry = retry_period
        self.is_leader = threading.Event()
        self._stop = threading.Event()

    def _try_acquire(self) -> bool:
        import datetime
        now = datetime.datetime.now(datetime.timezone.utc)
        now_s = now.strftime("%Y-%m-%dT%H:%M:%S.%f0Z")
        lease = self.c.get("coordination.k8s.io/v1", "Lease", self.ns,
                           self.name)
        if lease is None:
            lease = {
                "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.ns},
                "spec": {"holderIdentity": self.id,
                         "leaseDurationSeconds": self.lease_seconds,
                         "acquireTime": now_s, "renewTime": now_s,
                         "leaseTransitions": 0},
            }
            try:
                self.c.create(lease)
                return True
            except (Conflict, ApiError):
                return False
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        renew = spec.get("renewTime")
        expired = True
        if renew:
            try:
                t = datetime.datetime.strptime(
                    renew[:26].rstrip("Z"), "%Y-%m-%dT%H:%M:%S.%f"
                ).replace(tzinfo=datetime.timezone.utc)
                expired = (now - t).total_seconds() > \
                    spec.get("leaseDurationSeconds", self.lease_seconds)
            except ValueError:
                pass
        if holder == self.id or not holder or expired:
            if holder != self.id:
                spec["leaseTransitions"] = \
                    int(spec.get("leaseTransitions") or 0) + 1
                spec["acquireTime"] = now_s
            spec["holderIdentity"] = self.id
            spec["renewTime"] = now_s
            spec["leaseDurationSeconds"] = self.lease_seconds
            lease["spec"] = spec
            try:
                self.c.update(lease)
                return True
            except (Conflict, ApiError):
                return False
        return False

    def run(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire():
                self.is_leader.set()
                self._stop.wait(self.lease_seconds / 3)
            else:
                if self.is_leader.is_set():
                    log.warning("lost leadership")
                self.is_leader.clear()
                self._stop.wait(self.retry)

    def stop(self) -> None:
        self._stop.set()


class Manager:
    def __init__(self, client: KubeClient, namespace: Optional[str] = None,
                 server_image: Optional[str] = None,
                 leader_elect: bool = False,
                 health_addr: Tuple[str, int] = ("0.0.0.0", 8081),
                 resync_seconds: float = 300.0):
        from .pod import SERVER_BASE_IMAGE
        self.c = client
        self.ns = namespace  # None = all namespaces
        self.queue = WorkQueue()
        self.recorder = Recorder(client)
        self.reconciler = ModelReconciler(
            client, self.recorder,
            server_image=server_image or os.environ.get(
                "TPU_SERVER_IMAGE", SERVER_BASE_IMAGE))
        self.leader_elect = leader_elect
        self.health_addr = health_addr
        self.resync = resync_seconds
        # metrics protection (the reference fronts manager metrics with a
        # kube-rbac-proxy sidecar, config/default/manager_auth_proxy_patch
        # .yaml; the native equivalent here is a bearer token mounted from
        # a Secret — config/default wires METRICS_TOKEN_FILE and the
        # ServiceMonitor reads the same Secret). Unset = open (dev).
        self._metrics_token: Optional[str] = None
        tok_file = os.environ.get("METRICS_TOKEN_FILE")
        if tok_file:
            try:
                with open(tok_file) as f:
                    self._metrics_token = f.read().strip()
            except OSError as e:
                log.warning("METRICS_TOKEN_FILE %r unreadable (%s); "
                            "/metrics FAILS CLOSED until the Secret "
                            "exists and the pod restarts", tok_file, e)
                self._metrics_token = None
            if not self._metrics_token:
                # unreadable OR empty: deny-all (an empty token must not
                # grant access to a bare "Bearer " header)
                import secrets as _secrets
                self._metrics_token = _secrets.token_hex(32)
        elif os.environ.get("METRICS_TOKEN"):
            self._metrics_token = os.environ["METRICS_TOKEN"].strip()
            if not self._metrics_token:
                # whitespace-only token: deny-all, never match "Bearer "
                import secrets as _secrets
                self._metrics_token = _secrets.token_hex(32)
        self._stop = threading.Event()
        self._threads: list = []
        self._elector: Optional[LeaderElector] = None
        self.reconcile_total = 0
        self.reconcile_errors = 0

    # --- watch loops ----------------------------------------------------
    def _watch_models(self) -> None:
        rv: Optional[str] = None
        while not self._stop.is_set():
            try:
                if rv is None:
                    items = self.c.list(API_VERSION, KIND, self.ns)
                    for m in items:
                        meta = m.get("metadata") or {}
                        self.queue.add((meta.get("namespace", "default"),
                                        meta.get("name", "")))
                    rv = ""  # watch from now
                for evt in self.c.watch(API_VERSION, KIND, self.ns,
                                        resource_version=rv or None,
                                        stop=self._stop):
                    obj = evt.get("object") or {}
                    meta = obj.get("metadata") or {}
                    rv = meta.get("resourceVersion") or rv
                    if meta.get("name"):
                        self.queue.add((meta.get("namespace", "default"),
                                        meta["name"]))
            except ApiError as e:
                if e.status == 410:  # Gone: relist
                    rv = None
                else:
                    log.warning("model watch error: %s", e)
                    self._stop.wait(2)
            except Exception as e:  # noqa: BLE001 — watch must survive
                log.warning("model watch error: %s", e)
                self._stop.wait(2)

    def _watch_workloads(self, kind: str) -> None:
        """Map owned workload events back to their Model so drift heals
        without waiting for resync (closes the reference's watch gap,
        SURVEY.md §3.1). One loop per kind: Deployments (single-host) and
        StatefulSets (multi-host slices + the image store)."""
        while not self._stop.is_set():
            try:
                for evt in self.c.watch("apps/v1", kind, self.ns,
                                        stop=self._stop):
                    self._enqueue_owner(evt.get("object") or {})
            except Exception as e:  # noqa: BLE001
                log.debug("%s watch error: %s", kind, e)
                self._stop.wait(5)

    def _enqueue_owner(self, obj: Dict[str, Any]) -> None:
        meta = obj.get("metadata") or {}
        for ref in meta.get("ownerReferences") or []:
            if ref.get("apiVersion") == API_VERSION and \
                    ref.get("kind") == KIND:
                self.queue.add((meta.get("namespace", "default"),
                                ref.get("name", "")))

    def _resync_loop(self) -> None:
        while not self._stop.wait(self.resync):
            try:
                for m in self.c.list(API_VERSION, KIND, self.ns):
                    meta = m.get("metadata") or {}
                    self.queue.add((meta.get("namespace", "default"),
                                    meta.get("name", "")))
            except Exception as e:  # noqa: BLE001
                log.warning("resync list failed: %s", e)

    # --- reconcile workers ----------------------------------------------
    # a requeue at/above this is a steady-state POLL (reconciler.POLL is
    # 5s; KICKOFF and rollout-progress requeues are shorter) and is
    # eligible for per-model backoff
    POLL_BACKOFF_FLOOR = 2.0
    POLL_BACKOFF_CAP = 60.0

    def _worker(self) -> None:
        backoff: Dict[Tuple[str, str], float] = {}
        # consecutive steady-state POLL results per model: a Model stuck
        # waiting (image pull, scheduling, quota) polls at 5s, then 7.5s,
        # … capped at 60s instead of hammering the apiserver at a fixed
        # interval forever; any non-POLL result (progress!) resets it
        poll_streak: Dict[Tuple[str, str], int] = {}
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            if self._elector and not self._elector.is_leader.is_set():
                self.queue.done(key, requeue_after=2.0)
                continue
            self.reconcile_total += 1
            try:
                res: Result = self.reconciler.reconcile(*key)
                backoff.pop(key, None)
                requeue = (res.requeue_after
                           if res.requeue_after is not None else -1.0)
                if requeue >= self.POLL_BACKOFF_FLOOR:
                    streak = poll_streak.get(key, 0)
                    requeue = min(requeue * (1.5 ** streak),
                                  self.POLL_BACKOFF_CAP)
                    poll_streak[key] = streak + 1
                else:
                    poll_streak.pop(key, None)
                self.queue.done(key, requeue_after=requeue)
            except NotFound:
                backoff.pop(key, None)
                poll_streak.pop(key, None)
                self.queue.done(key)
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                self.reconcile_errors += 1
                delay = min(backoff.get(key, 0.5) * 2, 60.0)
                backoff[key] = delay
                log.exception("reconcile %s failed (requeue %.1fs): %s",
                              key, delay, e)
                self.queue.done(key, requeue_after=delay)

    # --- health/metrics endpoint ----------------------------------------
    def _health_server(self) -> ThreadingHTTPServer:
        mgr = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path in ("/healthz", "/readyz"):
                    body, code = b"ok", 200
                elif self.path == "/metrics":
                    tok = mgr._metrics_token
                    if tok is not None:
                        import hmac
                        auth = self.headers.get("Authorization", "")
                        if not (auth.startswith("Bearer ") and
                                hmac.compare_digest(
                                    auth[7:].encode("utf-8", "replace"),
                                    tok.encode("utf-8"))):
                            body, code = b"unauthorized", 401
                            self.send_response(code)
                            self.send_header("WWW-Authenticate", "Bearer")
                            self.send_header("Content-Length",
                                             str(len(body)))
                            self.end_headers()
                            self.wfile.write(body)
                            return
                    lines = [
                        "# TYPE controller_reconcile_total counter",
                        f"controller_reconcile_total {mgr.reconcile_total}",
                        "# TYPE controller_reconcile_errors_total counter",
                        "controller_reconcile_errors_total "
                        f"{mgr.reconcile_errors}",
                        "# TYPE leader_election_master_status gauge",
                        "leader_election_master_status "
                        f"{int(not mgr._elector or mgr._elector.is_leader.is_set())}",
                    ]
                    # the shared registry carries the autoscale/remediation
                    # counters the reconciler increments — without this the
                    # closed-loop decisions would be invisible from the
                    # operator's own scrape endpoint
                    try:
                        from ..server.metrics import GLOBAL as _G
                        shared = _G.render()
                    except Exception:  # noqa: BLE001 — scrape must not 500
                        shared = ""
                    body = ("\n".join(lines) + "\n" + shared).encode()
                    code = 200
                else:
                    body, code = b"not found", 404
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(self.health_addr, Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        return httpd

    # --- lifecycle ------------------------------------------------------
    def start(self, workers: int = 2, serve_health: bool = True) -> None:
        if self.leader_elect:
            self._elector = LeaderElector(
                self.c, self.ns or os.environ.get("POD_NAMESPACE", "default"))
            self._spawn(self._elector.run)
        self._httpd = self._health_server() if serve_health else None
        self._spawn(self._watch_models)
        self._spawn(lambda: self._watch_workloads("Deployment"))
        self._spawn(lambda: self._watch_workloads("StatefulSet"))
        self._spawn(self._resync_loop)
        for _ in range(workers):
            self._spawn(self._worker)

    def _spawn(self, fn: Callable[[], None]) -> None:
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        if self._elector:
            self._elector.stop()
        if getattr(self, "_httpd", None):
            self._httpd.shutdown()

    def wait(self) -> None:
        try:
            while not self._stop.is_set():
                time.sleep(1)
        except KeyboardInterrupt:
            self.stop()
