"""Container factories for model-serving pods.

The reference builds two containers (/root/reference/pkg/model/pod.go):
`NewOllamaServerContainer` — the `ollama/ollama` image running `serve` with
the blob PVC mounted, /api/tags probes with FailureThreshold 2500 — and
`NewOllamaPullerContainer` — `ollama pull <image>` pointed at the store
Service. Same roles here, but the server image is the TPU runtime
(JAX/XLA engine + Ollama-compatible HTTP front) and the server container
additionally carries TPU resources/topology selectors and the
jax.distributed env for multi-host slices (no reference analog —
SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .types import TpuPlacement

# Default runtime image; pinned per-release by kustomize exactly like the
# reference pins ghcr.io/nekomeowww/ollama-operator
# (/root/reference/config/manager/kustomization.yaml:5-8).
SERVER_BASE_IMAGE = "ghcr.io/ollama-operator-tpu/tpu-runtime"

STORE_MOUNT = "/root/.ollama"
CACHE_SUBPATH = "tpu-cache"  # transcoded-weights cache inside the same PVC
VOLUME_NAME = "image-storage"
PORT = 11434

# The reference tolerates hours of model loading before probes fail
# (pod.go:50,62: FailureThreshold 2500 × 10s). Transcode+shard of a 70B is
# minutes, not hours, but a cold pull still dominates — keep the window.
PROBE_FAILURE_THRESHOLD = 2500

# Graceful-termination geometry.  On SIGTERM the server drains: /readyz
# flips to 503, new submits shed with Retry-After, running streams finish
# within TPU_DRAIN_TIMEOUT_S (runtime/scheduler.py drain()).  The preStop
# sleep holds the container alive while the endpoints controller
# deprograms the pod from the Service, so no connection is routed to a
# server that is already draining; the grace period must cover
# preStop + drain + engine teardown or the kubelet SIGKILLs mid-drain.
PRESTOP_SLEEP_S = 5
DRAIN_TIMEOUT_S = 30
TERMINATION_GRACE_S = PRESTOP_SLEEP_S + DRAIN_TIMEOUT_S + 25


def _probe(path: str, initial_delay: int = 5,
           failure_threshold: int = PROBE_FAILURE_THRESHOLD
           ) -> Dict[str, Any]:
    return {
        "httpGet": {"path": path, "port": PORT},
        "initialDelaySeconds": initial_delay,
        "periodSeconds": 10,
        "failureThreshold": failure_threshold,
    }


def new_server_container(
    *,
    read_only: bool,
    image: str = SERVER_BASE_IMAGE,
    model: Optional[str] = None,
    store_only: bool = False,
    placement: Optional[TpuPlacement] = None,
    context_length: Optional[int] = None,
    quantization: Optional[str] = None,
    tp: int = 0,
    extra_env: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The serving container (pod.go:14-66 equivalent).

    read_only mirrors the reference's store-vs-model mount split: the store
    StatefulSet mounts the PVC RW (image_store.go:169), model pods RO
    (model.go:97). The transcoded-weights cache needs RW, so model pods get
    a separate subPath mount for it (cache writes are content-addressed and
    concurrent-safe, gguf/store.py).
    """
    env = [
        {"name": "OLLAMA_HOST_BIND", "value": "0.0.0.0"},
        {"name": "OLLAMA_MODELS", "value": f"{STORE_MOUNT}/models"},
        {"name": "TPU_WEIGHT_CACHE", "value": f"{STORE_MOUNT}/{CACHE_SUBPATH}"},
    ]
    if store_only:
        env.append({"name": "TPU_STORE_ONLY", "value": "1"})
    if model:
        env.append({"name": "TPU_PRELOAD_MODEL", "value": model})
    if context_length:
        env.append({"name": "TPU_MAX_SEQ_LEN", "value": str(context_length)})
    if quantization:
        # CRD quantization -> the server's weight-dtype knob (CRD spells
        # bf16, the server bfloat16); int8/int4 also turn on the quantized
        # KV cache (the pairing every quantized config wants: half/quarter
        # the weight AND half the cache traffic)
        dtype = {"bf16": "bfloat16"}.get(quantization, quantization)
        env.append({"name": "TPU_ENGINE_DTYPE", "value": dtype})
        if quantization in ("int8", "int4"):
            env.append({"name": "TPU_KV_DTYPE", "value": "int8"})
    if placement is not None:
        # a TPU pod that silently fell back to CPU must crash, not serve
        # at 1/100th speed (server __main__ enforces this)
        env.append({"name": "TPU_EXPECT_PLATFORM", "value": "tpu"})
    if tp:
        env.append({"name": "TPU_TENSOR_PARALLEL", "value": str(tp)})
    # keep the server's drain window in lockstep with the pod's
    # terminationGracePeriodSeconds (workload._pod_template)
    env.append({"name": "TPU_DRAIN_TIMEOUT_S", "value": str(DRAIN_TIMEOUT_S)})
    if not store_only:
        # scale-to-zero fast cold-start: the AOT warm-bucket executable
        # cache is snapshotted into the shared cache volume at drain time
        # and restored on wake (runtime/service.py warm snapshot; the
        # cache subpath is the same PVC the transcoded weights live on)
        env.append({"name": "TPU_WARM_SNAPSHOT", "value": "1"})
    env.extend(extra_env or [])

    mounts = [{
        "name": VOLUME_NAME,
        "mountPath": STORE_MOUNT,
        "readOnly": not store_only and read_only,
    }]
    if read_only and not store_only:
        # RW cache mount layered over the RO blob mount (same PVC).
        mounts.append({
            "name": VOLUME_NAME,
            "mountPath": f"{STORE_MOUNT}/{CACHE_SUBPATH}",
            "subPath": CACHE_SUBPATH,
            "readOnly": False,
        })

    container: Dict[str, Any] = {
        "name": "server",
        "image": image,
        "args": ["serve"],
        "env": env,
        "ports": [{"name": "http", "containerPort": PORT, "protocol": "TCP"}],
        "volumeMounts": mounts,
        # startup gates liveness through the hours-long pull/transcode
        # window (the reference piles its 2500-failure tolerance onto both
        # probes, pod.go:50,62); once serving, a wedged engine should be
        # restarted in ~30s, not 7h, so liveness itself fails fast.
        "startupProbe": _probe("/healthz"),
        "readinessProbe": _probe("/api/tags"),
        "livenessProbe": _probe("/livez", failure_threshold=3),
        # preStop runs before SIGTERM: the sleep keeps the pod serving
        # while kube-proxy/endpoints converge on its removal, then the
        # server's own SIGTERM handler drains (readyz 503 + shed +
        # stream-preserving finish).  /livez stays ok while draining so
        # the kubelet never restarts a pod mid-drain.
        "lifecycle": {
            "preStop": {
                "exec": {"command": ["sh", "-c",
                                     f"sleep {PRESTOP_SLEEP_S}"]},
            },
        },
    }
    if placement is not None:
        container["resources"] = {
            "requests": {"google.com/tpu": str(placement.chips_per_host)},
            "limits": {"google.com/tpu": str(placement.chips_per_host)},
        }
    return container


def new_gateway_container(
    *,
    namespace: str,
    app: str,
    image: str = SERVER_BASE_IMAGE,
) -> Dict[str, Any]:
    """The fleet-gateway container (operator/gateway.py): cache-aware
    router + circuit breaker + stream-failover front for a replicated
    Model. Runs the same runtime image (the gateway is stdlib-only, the
    image has it), discovers replicas via the pod label selector, and
    needs no TPU — it schedules anywhere.

    Crash recovery: the request journal + affinity table persist to an
    append-log on the shared weight-cache volume (TPU_GATEWAY_PERSIST),
    so a replacement gateway pod restores in-flight replayable streams
    for reconnecting clients. SIGTERM triggers the gateway's own
    begin_drain (mirroring the server drain contract); the preStop sleep
    covers Service endpoint deprogramming exactly as for server pods."""
    return {
        "name": "gateway",
        "image": image,
        "command": ["python", "-m", "ollama_operator_tpu.operator.gateway"],
        "env": [
            {"name": "TPU_GATEWAY_SELECTOR", "value": f"{namespace}/{app}"},
            {"name": "TPU_GATEWAY_PORT", "value": str(PORT)},
            {"name": "TPU_WEIGHT_CACHE",
             "value": f"{STORE_MOUNT}/{CACHE_SUBPATH}"},
            # "1" = journal to <TPU_WEIGHT_CACHE>/gateway-journal.ndjson
            {"name": "TPU_GATEWAY_PERSIST", "value": "1"},
            {"name": "TPU_DRAIN_TIMEOUT_S", "value": str(DRAIN_TIMEOUT_S)},
        ],
        "ports": [{"name": "http", "containerPort": PORT,
                   "protocol": "TCP"}],
        "volumeMounts": [{
            # only the RW cache subpath: the gateway needs a durable home
            # for its journal, not the model blobs
            "name": VOLUME_NAME,
            "mountPath": f"{STORE_MOUNT}/{CACHE_SUBPATH}",
            "subPath": CACHE_SUBPATH,
            "readOnly": False,
        }],
        "startupProbe": _probe("/healthz", failure_threshold=30),
        # ready iff >=1 replica is routable: an all-ejected fleet drops
        # out of the Service instead of 503ing every request
        "readinessProbe": _probe("/readyz", failure_threshold=3),
        "livenessProbe": _probe("/healthz", failure_threshold=3),
        "lifecycle": {
            "preStop": {
                "exec": {"command": ["sh", "-c",
                                     f"sleep {PRESTOP_SLEEP_S}"]},
            },
        },
    }


def new_puller_container(
    *,
    image: str,
    namespace: str,
    server_image: str = SERVER_BASE_IMAGE,
) -> Dict[str, Any]:
    """Init container pulling through the store (pod.go:68-83 equivalent):
    OLLAMA_HOST points at the store Service, so the *store* downloads into
    the shared PVC and every model pod on the cluster reuses the blobs."""
    from .workload import IMAGE_STORE_SERVICE
    return {
        "name": "ollama-image-pull",
        "image": server_image,
        "args": ["pull", image],
        "env": [{
            "name": "OLLAMA_HOST",
            "value": f"{IMAGE_STORE_SERVICE}.{namespace}",
        }],
    }


def multihost_env(headless_service: str, namespace: str, hosts: int,
                  chips_per_host: int) -> List[Dict[str, Any]]:
    """jax.distributed env for a multi-host slice StatefulSet.

    Pod ordinal = process index (parsed from the pod hostname by
    parallel/distributed.py), pod-0's stable DNS name = coordinator.
    The reference has no analog — its replicas are independent servers
    (SURVEY.md §2.3); this is what makes one *sharded model* span hosts.
    """
    return [
        {"name": "TPU_DIST_HOSTS", "value": str(hosts)},
        {"name": "TPU_DIST_CHIPS_PER_HOST", "value": str(chips_per_host)},
        {"name": "TPU_DIST_COORDINATOR",
         "value": f"$(TPU_DIST_STS_NAME)-0.{headless_service}"
                  f".{namespace}.svc:8476"},
        # leader→follower serving control stream (runtime/follower.py):
        # process 0 broadcasts load/engine calls here so the whole slice
        # dispatches identical SPMD programs
        {"name": "TPU_DIST_CONTROL",
         "value": f"$(TPU_DIST_STS_NAME)-0.{headless_service}"
                  f".{namespace}.svc:8477"},
        {"name": "TPU_DIST_POD_NAME",
         "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}}},
    ]
