"""The Model control loop.

Reproduces the reference reconciler's ensure/poll ladder
(/root/reference/internal/controller/model_controller.go:61-169, traced in
SURVEY.md §3.2): condition gating → image-store ensure/poll → workload
ensure/update/poll → service ensure/poll → status replica mirror →
Available. Requeue cadence matches: 1s after first Progressing, 5s for
every not-ready poll.

Deliberate behavior fixes over the reference (SURVEY.md §2.1 gaps):
- conditions are ADDITIVE (the reference replaces the whole array so only
  one condition ever exists, model_controller.go:192-199); the current
  condition is kept at index 0 so the reference's printcolumn
  `.status.conditions[0].type` still shows the live state;
- ReplicaFailure is actually set (declared-but-never-produced there);
- Available is cleared back to Progressing if replicas later fail;
- spec.image changes are reconciled (workload.update_model_workload).

TPU addition: multi-host placements (tpu.topology with >1 host) get a
StatefulSet + headless rendezvous Service instead of a Deployment — one
replica group is ONE jax.distributed world serving a sharded model.
"""

from __future__ import annotations

import dataclasses
import datetime
import logging
from typing import Any, Dict, Optional

from . import autoscale, workload
from .client import (KubeClient, NotFound, fetch_replica_ps,
                     post_replica_drain, update_status_with_retry)
from .pod import PORT, SERVER_BASE_IMAGE
from .recorder import Recorder
from .types import (API_VERSION, CONDITION_AVAILABLE, CONDITION_PROGRESSING,
                    CONDITION_REPLICA_FAILURE, KIND, ModelSpecView)

log = logging.getLogger("reconciler")


@dataclasses.dataclass(frozen=True)
class Result:
    requeue_after: Optional[float] = None  # seconds; None = done

    @property
    def done(self) -> bool:
        return self.requeue_after is None


DONE = Result()
POLL = Result(requeue_after=5.0)     # model_controller.go:101 et al.
KICKOFF = Result(requeue_after=1.0)  # model_controller.go:78


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _age_s(stamp: Optional[str]) -> Optional[float]:
    """Seconds since an RFC3339 stamp written by _now(); None if unparseable."""
    if not stamp:
        return None
    try:
        t = datetime.datetime.strptime(
            stamp, "%Y-%m-%dT%H:%M:%SZ").replace(
                tzinfo=datetime.timezone.utc)
    except ValueError:
        return None
    return max(0.0, (datetime.datetime.now(datetime.timezone.utc)
                     - t).total_seconds())


# --- condition helpers ------------------------------------------------------

def get_condition(model: Dict[str, Any], type_: str) -> Optional[Dict]:
    for c in (model.get("status") or {}).get("conditions") or []:
        if c.get("type") == type_:
            return c
    return None


def is_condition_true(model: Dict[str, Any], type_: str) -> bool:
    c = get_condition(model, type_)
    return bool(c and c.get("status") == "True")


def set_condition(model: Dict[str, Any], type_: str, status: str,
                  reason: str, message: str = "") -> bool:
    """Upsert; move the asserted condition to index 0 (printcolumn compat).
    Returns True if anything changed."""
    status_obj = model.setdefault("status", {})
    conds = status_obj.setdefault("conditions", [])
    cur = get_condition(model, type_)
    now = _now()
    if cur is None:
        cur = {"type": type_, "status": status, "reason": reason,
               "message": message, "lastUpdateTime": now,
               "lastTransitionTime": now}
        # index 0 is reserved for the live (True) condition so the
        # reference's printcolumn `.status.conditions[0].type` stays honest
        if status == "True":
            conds.insert(0, cur)
        else:
            conds.append(cur)
        return True
    changed = False
    if cur.get("status") != status:
        cur["status"] = status
        cur["lastTransitionTime"] = now
        changed = True
    if cur.get("reason") != reason or cur.get("message") != message:
        cur["reason"], cur["message"] = reason, message
        changed = True
    if changed:
        cur["lastUpdateTime"] = now
    if status == "True" and conds and conds[0] is not cur:
        conds.remove(cur)
        conds.insert(0, cur)
        changed = True
    return changed


class ModelReconciler:
    """One reconciler instance serves all Models (controller-runtime's
    single-reconcile-per-key concurrency model is enforced by the manager's
    workqueue, manager.py)."""

    def __init__(self, client: KubeClient, recorder: Recorder,
                 server_image: str = SERVER_BASE_IMAGE,
                 ps_fetch=None, drain_post=None, autoscaler=None):
        self.c = client
        self.rec = recorder
        self.server_image = server_image
        # replica-stats scrape (GET <pod>/api/ps): injectable so the
        # fake-kube e2e can hand back canned bodies without a server
        self.ps_fetch = ps_fetch or fetch_replica_ps
        # drain trigger (POST <pod>/api/drain): injectable for the same
        # reason — the fake-kube e2e drains simulated replicas
        self.drain_post = drain_post or post_replica_drain
        # per-Model control-law state; injectable so tests drive the
        # cooldown/idle clocks deterministically
        self.scaler = autoscaler or autoscale.Autoscaler()

    # --- status writers -------------------------------------------------
    def _write_status(self, model: Dict[str, Any]) -> Dict[str, Any]:
        """Status update: conflict-aware refetch AND transient-5xx retry
        (client.update_status_with_retry — during scale churn the spec
        and workload mirror race us constantly, and a status write that
        dies on an apiserver blip would drop a scale decision)."""
        return update_status_with_retry(self.c, model)

    def set_progressing(self, model: Dict[str, Any], reason: str,
                        message: str = "") -> None:
        c1 = set_condition(model, CONDITION_PROGRESSING, "True", reason,
                           message)
        c2 = set_condition(model, CONDITION_AVAILABLE, "False", reason, "")
        if c1 or c2:
            self._write_status(model)

    def set_available(self, model: Dict[str, Any]) -> None:
        c1 = set_condition(model, CONDITION_AVAILABLE, "True",
                           "ModelAvailable", "model is ready to serve")
        c2 = set_condition(model, CONDITION_PROGRESSING, "False",
                           "ModelAvailable", "")
        c3 = set_condition(model, CONDITION_REPLICA_FAILURE, "False",
                           "ModelAvailable", "")
        if c1 or c2 or c3:
            self._write_status(model)
            self.rec.event(model, "Normal", "ModelAvailable",
                           "model is available")

    def set_replica_failure(self, model: Dict[str, Any], message: str) -> None:
        c1 = set_condition(model, CONDITION_REPLICA_FAILURE, "True",
                           "WorkloadReplicaFailure", message)
        c2 = set_condition(model, CONDITION_AVAILABLE, "False",
                           "WorkloadReplicaFailure", message)
        if c1 or c2:
            self._write_status(model)
            self.rec.event(model, "Warning", "ReplicaFailure", message)

    # --- replica utilization mirror -------------------------------------
    def _replica_utilization(self, namespace: str,
                             app: str) -> list:
        """Scrape every pod of the model workload for its /api/ps and
        condense the utilization/health block into one compact entry per
        replica — the data ROADMAP item 4's utilization-aware router
        routes on. Best-effort by design: unreachable pods are marked,
        a failed pod list yields [] and the mirror is simply skipped."""
        try:
            pods = self.c.list("v1", "Pod", namespace,
                               label_selector=f"app={app}")
        except Exception:  # noqa: BLE001 — mirror must never wedge
            return []
        out = []
        for pod in sorted(pods, key=lambda p: (p.get("metadata") or {})
                          .get("name", "")):
            st = pod.get("status") or {}
            ip = st.get("podIP")
            if not ip:
                continue
            entry = {"pod": (pod.get("metadata") or {}).get("name", ""),
                     "ip": ip}
            pool = ((pod.get("metadata") or {}).get("labels")
                    or {}).get(workload.POOL_LABEL)
            if pool:  # disaggregated fleets scale per pool (ISSUE 20)
                entry["pool"] = pool
            if workload.pod_is_drain_victim(pod):
                entry["drainRequested"] = True
            body = self.ps_fetch(f"http://{ip}:{PORT}/api/ps")
            served = None
            for m in (body or {}).get("models") or []:
                if m.get("utilization"):
                    served = m
                    break
            if body is None:
                entry["state"] = "unreachable"
            elif served is None:
                entry["state"] = "no_model"
            else:
                util = served.get("utilization") or {}
                life = served.get("lifecycle") or {}
                adm = served.get("admission") or {}
                rec = util.get("recompiles") or {}
                q = adm.get("queued_by_class") or {}
                bt = adm.get("backlog_tokens_by_class") or {}
                entry.update({
                    "state": life.get("state") or "serving",
                    "model": served.get("name", ""),
                    "mfu": util.get("mfu"),
                    "goodputTokS": util.get("goodput_tok_s"),
                    "occupancy": util.get("occupancy"),
                    "wastePct": util.get("waste_pct"),
                    "recompiles": int(sum(rec.values())) if rec else 0,
                    # control-law inputs (PR 8 queue model + PR 9 drain):
                    # queued work, backlog tokens, live streams, SLO bound
                    "activeStreams": int(life.get("active_streams") or 0),
                    "queueDepth": int(sum(q.values())) if q else 0,
                    "backlogTokens": int(sum(bt.values())) if bt else 0,
                    "ttftSloMs": float(adm.get("ttft_slo_ms") or 0.0),
                })
            out.append(entry)
        return out

    # --- the ladder -----------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Result:
        model = self.c.get(API_VERSION, KIND, namespace, name)
        if model is None:
            return DONE  # deleted; GC cascades via ownerReferences
        spec = ModelSpecView(model)
        if not spec.image:
            self.set_progressing(model, "InvalidSpec", "spec.image is empty")
            return DONE

        if not is_condition_true(model, CONDITION_AVAILABLE) and \
                not is_condition_true(model, CONDITION_PROGRESSING):
            self.set_progressing(model, "ModelCreating",
                                 f"provisioning {spec.image}")
            self.rec.event(model, "Normal", "ModelCreating",
                           f"provisioning {spec.image}")
            return KICKOFF

        # 1) shared image store (PVC + store server + Service)
        workload.ensure_image_store(self.c, self.rec, model, spec,
                                    self.server_image)
        if not workload.is_statefulset_ready(self.c, namespace,
                                             workload.IMAGE_STORE_NAME):
            self.set_progressing(model, "ImageStoreNotReady",
                                 "waiting for image store")
            return POLL
        if not workload.is_service_ready(self.c, namespace,
                                         workload.IMAGE_STORE_SERVICE):
            return POLL

        # 2) model workload (Deployment, or StatefulSet for multi-host;
        #    a disaggregated Model gets TWO pool Deployments — ISSUE 20)
        placement = spec.tpu_placement()
        multi_host = placement is not None and placement.multi_host
        disagg = workload.disagg_enabled(spec)
        app = workload.model_app_name(name)
        image = spec.server_image or self.server_image  # per-CR pin wins
        # autoscaling (single-host Deployments only: a multi-host replica
        # group is ONE jax.distributed world; its size is the topology).
        # Disaggregated pools scale per-pool inside _sync_pools instead.
        policy = autoscale.resolve_policy(spec.autoscale)
        scaling = policy.enabled and not multi_host and not disagg
        asc_status = (model.get("status") or {}).get("autoscale") or {}
        if disagg:
            r = self._sync_pools(model, spec, namespace, app, image)
            if r is not None:
                return r
        else:
            if not multi_host:
                # disable transition: tear the pool Deployments back down
                # BEFORE the unified fleet resyncs (their pods share the
                # app label; two owners must never coexist)
                for pool in workload.DISAGG_POOLS:
                    pname = workload.pool_app_name(name, pool)
                    if self.c.get("apps/v1", "Deployment", namespace,
                                  pname) is not None:
                        self.c.delete("apps/v1", "Deployment", namespace,
                                      pname)
                        self.rec.event(model, "Normal", "WorkloadUnified",
                                       f"removed pool deployment {pname}")
                        return POLL
            if scaling and asc_status.get("desiredReplicas") is not None:
                # adopt the persisted count so an operator restart fails
                # static (keeps the fleet size) instead of snapping to spec
                self.scaler.seed_desired((namespace, name),
                                         int(asc_status["desiredReplicas"]))
            if multi_host:
                want = workload.build_model_statefulset(model, image)
                workload._ensure(self.c,
                                 workload.build_headless_service(model))
            else:
                want = workload.build_model_deployment(model, image)
            workload.stamp_spec_hash(want)
            cur = self.c.get("apps/v1", want["kind"], namespace, app)
            if scaling:
                desired0 = self.scaler.desired((namespace, name))
                if desired0 is None:
                    desired0 = spec.replicas
                cur_replicas = (int((cur.get("spec") or {}).get("replicas")
                                    or 0) if cur is not None else None)
                # Growth syncs through the normal ladder; shrink ONLY via
                # the drain protocol (_scale_down_step decrements after the
                # victim's streams finish — never let the plain replica
                # sync kill a serving pod).
                if cur_replicas is None or desired0 >= cur_replicas:
                    want["spec"]["replicas"] = max(0, int(desired0))
                else:
                    want["spec"]["replicas"] = cur_replicas
            if cur is None:
                self.c.create(want)
                self.rec.event(model, "Normal", "WorkloadCreated",
                               f"created {want['kind']} {app}")
                self.set_progressing(model, "WorkloadCreated",
                                     f"waiting for {app}")
                return POLL
            if workload.update_model_workload(self.c, self.rec, model, cur,
                                              want):
                return POLL

            # replica failure surfacing (the reference never does this) +
            # crash-loop remediation when the control loop owns the fleet
            failure = workload.deployment_replica_failure(cur)
            if failure:
                if scaling:
                    self._remediate_crash_loop(model, policy, namespace, app)
                self.set_replica_failure(model, failure)
                return POLL

            want_ready = placement.hosts if multi_host else spec.replicas
            if scaling:
                # readiness tracks the autoscaler's intent, not
                # spec.replicas; drain victims are intentionally not-ready
                # and must not read as "workload not ready" (that would
                # wedge the shrink)
                want_ready = max(0, int(want["spec"].get("replicas") or 0)
                                 - len(asc_status.get("draining") or []))
            if multi_host:
                ready = workload.is_statefulset_ready(self.c, namespace,
                                                      app, want=want_ready)
            else:
                ready = workload.is_deployment_ready(self.c, namespace,
                                                     app, want=want_ready)
            if not ready:
                self.set_progressing(model, "WorkloadNotReady",
                                     f"waiting for {app} readiness")
                return POLL

        # 2b) fleet gateway (replicated single-host Models only): ensured
        # and spec-synced, but NEVER gating — Available tracks the model
        # replicas; a slow gateway rollout must not mask a serving fleet,
        # and the gateway itself goes unready when no replica is routable.
        self._ensure_gateway(model, spec, namespace, image)

        # 3) serving Service (selector SYNCED, not just created: enabling
        # or disabling the gateway repoints the existing Service)
        svc = workload.build_model_service(model)
        cur_svc = self.c.get("v1", "Service", namespace, app)
        if cur_svc is None:
            self.c.create(svc)
            self.rec.event(model, "Normal", "ServiceCreated",
                           f"created service {app}")
            return POLL
        want_sel = (svc.get("spec") or {}).get("selector") or {}
        if ((cur_svc.get("spec") or {}).get("selector") or {}) != want_sel:
            cur_svc.setdefault("spec", {})["selector"] = want_sel
            self.c.update(cur_svc)
            self.rec.event(model, "Normal", "ServiceSelectorSynced",
                           f"service {app} now selects "
                           f"{want_sel.get('app', app)}")
            return POLL
        if not workload.is_service_ready(self.c, namespace, app):
            return POLL

        # 4) status replica mirror (model_controller.go:240-273); a
        # disaggregated Model mirrors the SUM over both pool Deployments
        mirrored = {"replicas": 0, "readyReplicas": 0,
                    "availableReplicas": 0, "unavailableReplicas": 0}
        if disagg:
            for pool in workload.DISAGG_POOLS:
                d = self.c.get("apps/v1", "Deployment", namespace,
                               workload.pool_app_name(name, pool))
                st = (d or {}).get("status") or {}
                for k in mirrored:
                    mirrored[k] += int(st.get(k) or 0)
        else:
            cur = self.c.get("apps/v1", want["kind"], namespace, app) or cur
            st = cur.get("status") or {}
            for k in mirrored:
                mirrored[k] = int(st.get(k) or 0)
        status_obj = model.setdefault("status", {})
        if any(status_obj.get(k) != v for k, v in mirrored.items()):
            status_obj.update(mirrored)
            self._write_status(model)
            return POLL

        # 5) per-replica utilization mirror + available. The scrape rides
        # the converged pass only (pods are ready here); without
        # autoscaling it stays DONE — the mirror refreshes on the next
        # watch-driven reconcile, it must not turn a settled Model into
        # a perpetual requeue. With autoscaling the loop IS the point:
        # the pass ends in POLL so the fleet keeps breathing.
        stats = self._replica_utilization(namespace, app)
        if stats:
            status_obj = model.setdefault("status", {})
            prev = (status_obj.get("replicaStats") or {}).get("replicas")
            if prev != stats:
                status_obj["replicaStats"] = {"scrapedAt": _now(),
                                              "replicas": stats}
                self._write_status(model)
        if disagg:
            # per-pool control loops: prefill scales on backlog tokens,
            # decode on slot occupancy (autoscale.pool_policy)
            dis = spec.disaggregate
            any_scaling = False
            for pool in workload.DISAGG_POOLS:
                ppolicy = autoscale.pool_policy(spec.autoscale,
                                                dis.get(pool) or {}, pool)
                if not ppolicy.enabled:
                    continue
                any_scaling = True
                dep = self.c.get("apps/v1", "Deployment", namespace,
                                 workload.pool_app_name(name, pool))
                if dep is None:
                    return POLL
                pstats = [e for e in stats if e.get("pool") == pool]
                self._autoscale_pass(model, spec, ppolicy, namespace, app,
                                     dep, pstats, pool=pool)
            if any_scaling:
                return POLL
            self.set_available(model)
            return DONE
        if scaling:
            return self._autoscale_pass(model, spec, policy, namespace,
                                        app, cur, stats)
        self.set_available(model)
        return DONE

    def _sync_pools(self, model: Dict[str, Any], spec: ModelSpecView,
                    namespace: str, app: str,
                    image: str) -> Optional[Result]:
        """Ladder step 2 for a disaggregated Model (ISSUE 20): two pool
        Deployments (prefill/decode) instead of the unified one, each
        sized by its own control loop. Returns a Result to short-circuit
        the ladder, or None when both pools are synced and ready."""
        # enable transition: tear the unified Deployment down FIRST — its
        # pods share the app label with the pool pods, and two owners for
        # one fleet selector must never coexist
        if self.c.get("apps/v1", "Deployment", namespace, app) is not None:
            self.c.delete("apps/v1", "Deployment", namespace, app)
            self.rec.event(model, "Normal", "WorkloadSplit",
                           f"splitting {app} into prefill/decode pools")
            self.set_progressing(model, "WorkloadSplit",
                                 "splitting fleet into pools")
            return POLL
        dis = spec.disaggregate
        asc_all = (model.get("status") or {}).get("autoscale") or {}
        for pool in workload.DISAGG_POOLS:
            pname = workload.pool_app_name(spec.name, pool)
            ppolicy = autoscale.pool_policy(spec.autoscale,
                                            dis.get(pool) or {}, pool)
            key = (namespace, f"{spec.name}/{pool}")
            asc = asc_all.get(pool) or {}
            if ppolicy.enabled and asc.get("desiredReplicas") is not None:
                self.scaler.seed_desired(key, int(asc["desiredReplicas"]))
            want = workload.build_pool_deployment(model, pool, image)
            workload.stamp_spec_hash(want)
            cur = self.c.get("apps/v1", "Deployment", namespace, pname)
            if ppolicy.enabled:
                desired0 = self.scaler.desired(key)
                if desired0 is None:
                    desired0 = workload.pool_replicas(spec, pool)
                cur_replicas = (int((cur.get("spec") or {}).get("replicas")
                                    or 0) if cur is not None else None)
                # same split as the unified ladder: grow via the normal
                # replica sync, shrink ONLY via the drain protocol
                if cur_replicas is None or desired0 >= cur_replicas:
                    want["spec"]["replicas"] = max(0, int(desired0))
                else:
                    want["spec"]["replicas"] = cur_replicas
            if cur is None:
                self.c.create(want)
                self.rec.event(model, "Normal", "WorkloadCreated",
                               f"created Deployment {pname}")
                self.set_progressing(model, "WorkloadCreated",
                                     f"waiting for {pname}")
                return POLL
            if workload.update_model_workload(self.c, self.rec, model,
                                              cur, want):
                return POLL
            failure = workload.deployment_replica_failure(cur)
            if failure:
                if ppolicy.enabled:
                    self._remediate_crash_loop(model, ppolicy, namespace,
                                               app, pool=pool)
                self.set_replica_failure(model, f"{pool}: {failure}")
                return POLL
            want_ready = int((cur.get("spec") or {}).get("replicas") or 0)
            if ppolicy.enabled:
                want_ready = max(0, want_ready
                                 - len(asc.get("draining") or []))
            if not workload.is_deployment_ready(self.c, namespace, pname,
                                                want=want_ready):
                self.set_progressing(model, "WorkloadNotReady",
                                     f"waiting for {pname} readiness")
                return POLL
        return None

    def _ensure_gateway(self, model: Dict[str, Any], spec: ModelSpecView,
                        namespace: str, image: str) -> None:
        """Ensure (or tear down) the per-Model fleet gateway Deployment.
        Non-gating by contract: callers never block Available on it."""
        gw_app = workload.gateway_app_name(spec.name)
        if not workload.gateway_enabled(spec):
            if self.c.get("apps/v1", "Deployment", namespace,
                          gw_app) is not None:
                self.c.delete("apps/v1", "Deployment", namespace, gw_app)
                self.rec.event(model, "Normal", "GatewayRemoved",
                               f"removed fleet gateway {gw_app}")
            return
        want = workload.build_gateway_deployment(model, image)
        workload.stamp_spec_hash(want)
        cur = self.c.get("apps/v1", "Deployment", namespace, gw_app)
        if cur is None:
            self.c.create(want)
            self.rec.event(model, "Normal", "GatewayCreated",
                           f"created fleet gateway {gw_app}")
            return
        workload.update_model_workload(self.c, self.rec, model, cur, want)

    # --- closed-loop fleet control --------------------------------------
    def _autoscale_pass(self, model: Dict[str, Any], spec: ModelSpecView,
                        policy: "autoscale.Policy", namespace: str, app: str,
                        dep: Dict[str, Any], stats: list,
                        pool: str = "") -> Result:
        """One control-loop step on the converged ladder: remediate broken
        replicas, run the damped control law, actuate (grow via the
        normal replica sync; shrink strictly drain-first). Always POLLs —
        the autoscaled Model is a live loop, not a settled object.
        With ``pool`` set this is one disagg pool's loop: its own state
        key, pool-filtered stats from the caller, and status nested under
        status.autoscale.<pool>."""
        key = (namespace, f"{spec.name}/{pool}" if pool else spec.name)
        status_obj = model.setdefault("status", {})
        cur_replicas = int((dep.get("spec") or {}).get("replicas") or 0)

        # Remediation first: a fleet with a dead member gets repaired
        # before any sizing decision (and sizing on a degraded fleet is
        # suppressed — the scrape hole already fails the freshness gate).
        if self._remediate_unreachable(model, policy, key, namespace, stats):
            return POLL

        obs = autoscale.observe_stats(cur_replicas, stats, 0.0, policy)
        if not obs.fresh:
            # distinguish a persistent outage (stale) from a fresh hole
            age = _age_s((status_obj.get("replicaStats") or {})
                         .get("scrapedAt"))
            if age is not None and age > policy.stale_s:
                obs = dataclasses.replace(obs, stale_cause="stale")

        anns = (model.get("metadata") or {}).get("annotations") or {}
        # scale-from-zero wake stays a whole-Model affair; pool loops
        # never sleep the fleet (pool min floors are >= 1 by default)
        wake = not pool and workload.WAKE_ANNOTATION in anns
        decision = self.scaler.observe(key, policy, obs, wake=wake)
        if wake and decision.action == "wake":
            self._clear_wake(model)
            self.rec.event(model, "Normal", "AutoscaleWake",
                           f"waking to {decision.desired} replicas")
        elif wake and decision.desired > 0:
            # stale wake: the gateway re-annotated while pods were still
            # starting. Consume it now or it would fire a spurious wake
            # the instant the model next scales to zero.
            self._clear_wake(model)
        desired = decision.desired

        asc = status_obj.get("autoscale") or {}
        if pool:
            asc = asc.get(pool) or {}
        pending_drains = list(asc.get("draining") or [])
        if desired < cur_replicas or pending_drains:
            # a marked victim is doomed (PR 9 drain is one-way): finish
            # its removal even if the law flipped back up meanwhile —
            # the next pass re-grows with a fresh pod
            return self._scale_down_step(model, policy, namespace, app,
                                         dep, stats, desired, decision,
                                         pool=pool)
        if desired > cur_replicas:
            dep.setdefault("spec", {})["replicas"] = desired
            self.c.update(dep)
            self.rec.event(model, "Normal", "AutoscaleUp",
                           f"{pool + ': ' if pool else ''}{cur_replicas}"
                           f" -> {desired} replicas ({decision.reason})")
            self._update_autoscale_status(model, desired, decision, [],
                                          pool=pool)
            return POLL

        self._update_autoscale_status(model, desired, decision, [],
                                      pool=pool)
        self.set_available(model)
        return POLL

    def _scale_down_step(self, model: Dict[str, Any],
                         policy: "autoscale.Policy", namespace: str,
                         app: str, dep: Dict[str, Any], stats: list,
                         desired: int, decision: "autoscale.Decision",
                         pool: str = "") -> Result:
        """Drain-first shrink, re-entrant across polls: mark one victim,
        tell its server to drain (readyz flips, streams finish), and only
        shrink the Deployment once the victim reports zero active work.
        Zero client-visible error frames by construction. With ``pool``
        set, only that pool's pods are candidates (the app label is
        fleet-wide; the pool label narrows it)."""
        try:
            pods = self.c.list("v1", "Pod", namespace,
                               label_selector=f"app={app}")
        except Exception:  # noqa: BLE001 — retry next poll
            return POLL
        if pool:
            pods = [p for p in pods
                    if ((p.get("metadata") or {}).get("labels") or {})
                    .get(workload.POOL_LABEL) == pool]
        pods = sorted(pods, key=lambda p: (p.get("metadata") or {})
                      .get("name", ""))
        by_name = {e.get("pod"): e for e in stats or []}
        victims = [p for p in pods if workload.pod_is_drain_victim(p)]
        cur_replicas = int((dep.get("spec") or {}).get("replicas") or 0)
        excess = cur_replicas - desired
        if len(victims) < excess:
            # one new victim per pass (damped): the least-loaded
            # non-victim pod finishes its streams fastest
            candidates = [p for p in pods
                          if not workload.pod_is_drain_victim(p)]

            def _load(p):
                name = (p.get("metadata") or {}).get("name", "")
                e = by_name.get(name) or {}
                return (int(e.get("activeStreams") or 0),
                        float(e.get("occupancy") or 0.0), name)

            candidates.sort(key=_load)
            if candidates:
                victim = candidates[0]
                workload.mark_drain_victim(self.c, victim)
                victims.append(victim)
                vname = (victim.get("metadata") or {}).get("name", "")
                self.rec.event(model, "Normal", "AutoscaleDrainStarted",
                               f"draining {vname} ({decision.reason})")

        # fail-static guard for the shortcut below: "unreachable victim"
        # only means "dead pod" when at least one replica DID answer this
        # pass — in a total scrape outage everything reads unreachable
        # and a still-streaming victim must not be killed on no evidence
        scrape_ok = any(e.get("state") != "unreachable"
                        for e in (stats or []))
        completed, pending = [], []
        for v in victims:
            vname = (v.get("metadata") or {}).get("name", "")
            ip = (v.get("status") or {}).get("podIP")
            e = by_name.get(vname) or {}
            drained = (e.get("state") == "draining"
                       and not int(e.get("activeStreams") or 0)
                       and not int(e.get("queueDepth") or 0))
            # an unreachable victim can't be serving anyone; holding the
            # shrink for a dead pod helps nobody
            if scrape_ok and (e.get("state") == "unreachable" or not e):
                drained = True
            if drained:
                completed.append(v)
                continue
            pending.append(vname)
            if ip:
                # idempotent: /api/drain re-POSTs are no-ops server-side
                self.drain_post(f"http://{ip}:{PORT}/api/drain")

        if completed:
            dep.setdefault("spec", {})["replicas"] = \
                max(0, cur_replicas - len(completed))
            self.c.update(dep)
            for v in completed:
                vname = (v.get("metadata") or {}).get("name", "")
                self.c.delete("v1", "Pod", namespace, vname)
                self.rec.event(model, "Normal", "AutoscaleDown",
                               f"removed drained replica {vname}")
        self._update_autoscale_status(model, desired, decision, pending,
                                      pool=pool)
        return POLL

    def _remediate_unreachable(self, model: Dict[str, Any],
                               policy: "autoscale.Policy", key,
                               namespace: str, stats: list) -> bool:
        """Replace ONE unreachable replica (delete; the ReplicaSet
        recreates — the Deployment size never shrinks, so the
        minReplicas floor holds structurally). Quorum-gated: when NO
        replica answers, the scrape path itself is suspect and the loop
        fails static instead. Exponential backoff between replacements."""
        entries = stats or []
        reachable = [e for e in entries if e.get("state") != "unreachable"]
        unreachable = [e for e in entries
                       if e.get("state") == "unreachable"
                       and not e.get("drainRequested")]
        if not unreachable:
            if entries and reachable:
                self.scaler.note_clean_pass(key)
            return False
        if not reachable:
            return False  # zero evidence -> fail static, no action
        if not self.scaler.remediation_due(key, policy):
            return False
        victim = unreachable[0]
        self.c.delete("v1", "Pod", namespace, victim.get("pod", ""))
        self.scaler.note_remediation(key, policy, "unreachable")
        self.rec.event(model, "Warning", "ReplicaRemediated",
                       f"replaced unreachable replica {victim.get('pod')}"
                       f" (backoff "
                       f"{self.scaler.remediation_backoff_s(key):.0f}s)")
        return True

    def _remediate_crash_loop(self, model: Dict[str, Any],
                              policy: "autoscale.Policy", namespace: str,
                              app: str, pool: str = "") -> bool:
        """Replace ONE crash-looping pod under the same backoff gate.
        Detected from pod containerStatuses (not scrapes — a crash-looping
        pod has no server to scrape), triggered by the Deployment's
        ReplicaFailure condition in the ladder."""
        mname = ModelSpecView(model).name
        key = (namespace, f"{mname}/{pool}" if pool else mname)
        try:
            pods = self.c.list("v1", "Pod", namespace,
                               label_selector=f"app={app}")
        except Exception:  # noqa: BLE001 — retry next poll
            return False
        if pool:
            pods = [p for p in pods
                    if ((p.get("metadata") or {}).get("labels") or {})
                    .get(workload.POOL_LABEL) == pool]
        looping = []
        for p in sorted(pods, key=lambda p: (p.get("metadata") or {})
                        .get("name", "")):
            for cs in (p.get("status") or {}).get("containerStatuses") or []:
                waiting = (cs.get("state") or {}).get("waiting") or {}
                if (waiting.get("reason") == "CrashLoopBackOff"
                        or int(cs.get("restartCount") or 0) >= 3):
                    looping.append(p)
                    break
        if not looping:
            return False
        if not self.scaler.remediation_due(key, policy):
            return False
        victim = looping[0]
        vname = (victim.get("metadata") or {}).get("name", "")
        self.c.delete("v1", "Pod", namespace, vname)
        self.scaler.note_remediation(key, policy, "crash_loop")
        self.rec.event(model, "Warning", "ReplicaRemediated",
                       f"replaced crash-looping replica {vname} (backoff "
                       f"{self.scaler.remediation_backoff_s(key):.0f}s)")
        return True

    def _update_autoscale_status(self, model: Dict[str, Any], desired: int,
                                 decision: "autoscale.Decision",
                                 draining: list, pool: str = "") -> None:
        """Persist the control loop's intent in status.autoscale (the
        fail-static anchor across operator restarts) — written only on
        change so steady passes don't churn resourceVersions. Pool loops
        nest under status.autoscale.<pool> so each survives restarts
        independently."""
        status_obj = model.setdefault("status", {})
        top = status_obj.get("autoscale") or {}
        prev = (top.get(pool) or {}) if pool else top
        new = {"desiredReplicas": desired,
               "lastAction": decision.action,
               "lastReason": decision.reason,
               "lastActionAt": prev.get("lastActionAt"),
               "draining": sorted(draining),
               "sleeping": desired == 0}
        if decision.action in autoscale.ACTIONS and (
                prev.get("lastAction") != decision.action
                or prev.get("desiredReplicas") != desired):
            new["lastActionAt"] = _now()
        if new != prev:
            if pool:
                status_obj["autoscale"] = dict(top, **{pool: new})
            else:
                status_obj["autoscale"] = new
            self._write_status(model)

    def _clear_wake(self, model: Dict[str, Any]) -> None:
        """Best-effort removal of the wake annotation (a Conflict just
        means someone else wrote the CR; the annotation survives and the
        next pass clears it — wake is idempotent)."""
        from .client import Conflict
        spec = ModelSpecView(model)
        fresh = self.c.get(API_VERSION, KIND, spec.namespace, spec.name)
        if fresh is None:
            return
        anns = (fresh.get("metadata") or {}).get("annotations") or {}
        if workload.WAKE_ANNOTATION not in anns:
            return
        anns.pop(workload.WAKE_ANNOTATION, None)
        fresh["metadata"]["annotations"] = anns
        try:
            self.c.update(fresh)
        except (Conflict, NotFound):
            pass
