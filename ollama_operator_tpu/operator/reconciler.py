"""The Model control loop.

Reproduces the reference reconciler's ensure/poll ladder
(/root/reference/internal/controller/model_controller.go:61-169, traced in
SURVEY.md §3.2): condition gating → image-store ensure/poll → workload
ensure/update/poll → service ensure/poll → status replica mirror →
Available. Requeue cadence matches: 1s after first Progressing, 5s for
every not-ready poll.

Deliberate behavior fixes over the reference (SURVEY.md §2.1 gaps):
- conditions are ADDITIVE (the reference replaces the whole array so only
  one condition ever exists, model_controller.go:192-199); the current
  condition is kept at index 0 so the reference's printcolumn
  `.status.conditions[0].type` still shows the live state;
- ReplicaFailure is actually set (declared-but-never-produced there);
- Available is cleared back to Progressing if replicas later fail;
- spec.image changes are reconciled (workload.update_model_workload).

TPU addition: multi-host placements (tpu.topology with >1 host) get a
StatefulSet + headless rendezvous Service instead of a Deployment — one
replica group is ONE jax.distributed world serving a sharded model.
"""

from __future__ import annotations

import dataclasses
import datetime
import logging
from typing import Any, Dict, Optional

from . import workload
from .client import KubeClient, NotFound, fetch_replica_ps
from .pod import PORT, SERVER_BASE_IMAGE
from .recorder import Recorder
from .types import (API_VERSION, CONDITION_AVAILABLE, CONDITION_PROGRESSING,
                    CONDITION_REPLICA_FAILURE, KIND, ModelSpecView)

log = logging.getLogger("reconciler")


@dataclasses.dataclass(frozen=True)
class Result:
    requeue_after: Optional[float] = None  # seconds; None = done

    @property
    def done(self) -> bool:
        return self.requeue_after is None


DONE = Result()
POLL = Result(requeue_after=5.0)     # model_controller.go:101 et al.
KICKOFF = Result(requeue_after=1.0)  # model_controller.go:78


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


# --- condition helpers ------------------------------------------------------

def get_condition(model: Dict[str, Any], type_: str) -> Optional[Dict]:
    for c in (model.get("status") or {}).get("conditions") or []:
        if c.get("type") == type_:
            return c
    return None


def is_condition_true(model: Dict[str, Any], type_: str) -> bool:
    c = get_condition(model, type_)
    return bool(c and c.get("status") == "True")


def set_condition(model: Dict[str, Any], type_: str, status: str,
                  reason: str, message: str = "") -> bool:
    """Upsert; move the asserted condition to index 0 (printcolumn compat).
    Returns True if anything changed."""
    status_obj = model.setdefault("status", {})
    conds = status_obj.setdefault("conditions", [])
    cur = get_condition(model, type_)
    now = _now()
    if cur is None:
        cur = {"type": type_, "status": status, "reason": reason,
               "message": message, "lastUpdateTime": now,
               "lastTransitionTime": now}
        # index 0 is reserved for the live (True) condition so the
        # reference's printcolumn `.status.conditions[0].type` stays honest
        if status == "True":
            conds.insert(0, cur)
        else:
            conds.append(cur)
        return True
    changed = False
    if cur.get("status") != status:
        cur["status"] = status
        cur["lastTransitionTime"] = now
        changed = True
    if cur.get("reason") != reason or cur.get("message") != message:
        cur["reason"], cur["message"] = reason, message
        changed = True
    if changed:
        cur["lastUpdateTime"] = now
    if status == "True" and conds and conds[0] is not cur:
        conds.remove(cur)
        conds.insert(0, cur)
        changed = True
    return changed


class ModelReconciler:
    """One reconciler instance serves all Models (controller-runtime's
    single-reconcile-per-key concurrency model is enforced by the manager's
    workqueue, manager.py)."""

    def __init__(self, client: KubeClient, recorder: Recorder,
                 server_image: str = SERVER_BASE_IMAGE,
                 ps_fetch=None):
        self.c = client
        self.rec = recorder
        self.server_image = server_image
        # replica-stats scrape (GET <pod>/api/ps): injectable so the
        # fake-kube e2e can hand back canned bodies without a server
        self.ps_fetch = ps_fetch or fetch_replica_ps

    # --- status writers -------------------------------------------------
    def _write_status(self, model: Dict[str, Any]) -> Dict[str, Any]:
        """Status update with refetch-on-conflict (controller-runtime's
        client.Status().Update + RetryOnConflict idiom)."""
        from .client import Conflict
        for _ in range(4):
            try:
                return self.c.update_status(model)
            except Conflict:
                spec = ModelSpecView(model)
                fresh = self.c.get(API_VERSION, KIND, spec.namespace,
                                   spec.name)
                if fresh is None:
                    return model
                model["metadata"]["resourceVersion"] = \
                    (fresh["metadata"] or {}).get("resourceVersion")
            except NotFound:
                return model
        return model

    def set_progressing(self, model: Dict[str, Any], reason: str,
                        message: str = "") -> None:
        c1 = set_condition(model, CONDITION_PROGRESSING, "True", reason,
                           message)
        c2 = set_condition(model, CONDITION_AVAILABLE, "False", reason, "")
        if c1 or c2:
            self._write_status(model)

    def set_available(self, model: Dict[str, Any]) -> None:
        c1 = set_condition(model, CONDITION_AVAILABLE, "True",
                           "ModelAvailable", "model is ready to serve")
        c2 = set_condition(model, CONDITION_PROGRESSING, "False",
                           "ModelAvailable", "")
        c3 = set_condition(model, CONDITION_REPLICA_FAILURE, "False",
                           "ModelAvailable", "")
        if c1 or c2 or c3:
            self._write_status(model)
            self.rec.event(model, "Normal", "ModelAvailable",
                           "model is available")

    def set_replica_failure(self, model: Dict[str, Any], message: str) -> None:
        c1 = set_condition(model, CONDITION_REPLICA_FAILURE, "True",
                           "WorkloadReplicaFailure", message)
        c2 = set_condition(model, CONDITION_AVAILABLE, "False",
                           "WorkloadReplicaFailure", message)
        if c1 or c2:
            self._write_status(model)
            self.rec.event(model, "Warning", "ReplicaFailure", message)

    # --- replica utilization mirror -------------------------------------
    def _replica_utilization(self, namespace: str,
                             app: str) -> list:
        """Scrape every pod of the model workload for its /api/ps and
        condense the utilization/health block into one compact entry per
        replica — the data ROADMAP item 4's utilization-aware router
        routes on. Best-effort by design: unreachable pods are marked,
        a failed pod list yields [] and the mirror is simply skipped."""
        try:
            pods = self.c.list("v1", "Pod", namespace,
                               label_selector=f"app={app}")
        except Exception:  # noqa: BLE001 — mirror must never wedge
            return []
        out = []
        for pod in sorted(pods, key=lambda p: (p.get("metadata") or {})
                          .get("name", "")):
            st = pod.get("status") or {}
            ip = st.get("podIP")
            if not ip:
                continue
            entry = {"pod": (pod.get("metadata") or {}).get("name", ""),
                     "ip": ip}
            body = self.ps_fetch(f"http://{ip}:{PORT}/api/ps")
            served = None
            for m in (body or {}).get("models") or []:
                if m.get("utilization"):
                    served = m
                    break
            if body is None:
                entry["state"] = "unreachable"
            elif served is None:
                entry["state"] = "no_model"
            else:
                util = served.get("utilization") or {}
                life = served.get("lifecycle") or {}
                rec = util.get("recompiles") or {}
                entry.update({
                    "state": life.get("state") or "serving",
                    "model": served.get("name", ""),
                    "mfu": util.get("mfu"),
                    "goodputTokS": util.get("goodput_tok_s"),
                    "occupancy": util.get("occupancy"),
                    "wastePct": util.get("waste_pct"),
                    "recompiles": int(sum(rec.values())) if rec else 0,
                })
            out.append(entry)
        return out

    # --- the ladder -----------------------------------------------------
    def reconcile(self, namespace: str, name: str) -> Result:
        model = self.c.get(API_VERSION, KIND, namespace, name)
        if model is None:
            return DONE  # deleted; GC cascades via ownerReferences
        spec = ModelSpecView(model)
        if not spec.image:
            self.set_progressing(model, "InvalidSpec", "spec.image is empty")
            return DONE

        if not is_condition_true(model, CONDITION_AVAILABLE) and \
                not is_condition_true(model, CONDITION_PROGRESSING):
            self.set_progressing(model, "ModelCreating",
                                 f"provisioning {spec.image}")
            self.rec.event(model, "Normal", "ModelCreating",
                           f"provisioning {spec.image}")
            return KICKOFF

        # 1) shared image store (PVC + store server + Service)
        workload.ensure_image_store(self.c, self.rec, model, spec,
                                    self.server_image)
        if not workload.is_statefulset_ready(self.c, namespace,
                                             workload.IMAGE_STORE_NAME):
            self.set_progressing(model, "ImageStoreNotReady",
                                 "waiting for image store")
            return POLL
        if not workload.is_service_ready(self.c, namespace,
                                         workload.IMAGE_STORE_SERVICE):
            return POLL

        # 2) model workload (Deployment, or StatefulSet for multi-host)
        placement = spec.tpu_placement()
        multi_host = placement is not None and placement.multi_host
        app = workload.model_app_name(name)
        image = spec.server_image or self.server_image  # per-CR pin wins
        if multi_host:
            want = workload.build_model_statefulset(model, image)
            workload._ensure(self.c, workload.build_headless_service(model))
        else:
            want = workload.build_model_deployment(model, image)
        workload.stamp_spec_hash(want)
        cur = self.c.get("apps/v1", want["kind"], namespace, app)
        if cur is None:
            self.c.create(want)
            self.rec.event(model, "Normal", "WorkloadCreated",
                           f"created {want['kind']} {app}")
            self.set_progressing(model, "WorkloadCreated",
                                 f"waiting for {app}")
            return POLL
        if workload.update_model_workload(self.c, self.rec, model, cur, want):
            return POLL

        # replica failure surfacing (the reference never does this)
        failure = workload.deployment_replica_failure(cur)
        if failure:
            self.set_replica_failure(model, failure)
            return POLL

        want_ready = placement.hosts if multi_host else spec.replicas
        if multi_host:
            ready = workload.is_statefulset_ready(self.c, namespace, app,
                                                  want=want_ready)
        else:
            ready = workload.is_deployment_ready(self.c, namespace, app,
                                                 want=want_ready)
        if not ready:
            self.set_progressing(model, "WorkloadNotReady",
                                 f"waiting for {app} readiness")
            return POLL

        # 3) serving Service
        svc = workload.build_model_service(model)
        if self.c.get("v1", "Service", namespace, app) is None:
            self.c.create(svc)
            self.rec.event(model, "Normal", "ServiceCreated",
                           f"created service {app}")
            return POLL
        if not workload.is_service_ready(self.c, namespace, app):
            return POLL

        # 4) status replica mirror (model_controller.go:240-273)
        cur = self.c.get("apps/v1", want["kind"], namespace, app) or cur
        st = cur.get("status") or {}
        mirrored = {
            "replicas": int(st.get("replicas") or 0),
            "readyReplicas": int(st.get("readyReplicas") or 0),
            "availableReplicas": int(st.get("availableReplicas") or 0),
            "unavailableReplicas": int(st.get("unavailableReplicas") or 0),
        }
        status_obj = model.setdefault("status", {})
        if any(status_obj.get(k) != v for k, v in mirrored.items()):
            status_obj.update(mirrored)
            self._write_status(model)
            return POLL

        # 5) per-replica utilization mirror + available. The scrape rides
        # the converged pass only (pods are ready here); it stays DONE —
        # the mirror refreshes on the next watch-driven reconcile, it
        # must not turn a settled Model into a perpetual requeue
        stats = self._replica_utilization(namespace, app)
        if stats:
            status_obj = model.setdefault("status", {})
            prev = (status_obj.get("replicaStats") or {}).get("replicas")
            if prev != stats:
                status_obj["replicaStats"] = {"scrapedAt": _now(),
                                              "replicas": stats}
                self._write_status(model)
        self.set_available(model)
        return DONE
