"""Kubernetes Event recorder bound to one object.

Equivalent of the reference's `WrappedRecorder[T]`
(/root/reference/pkg/model/recorder.go:8-32) minus client-go's event
aggregation: we dedupe by (reason, message) within a short window and bump
`count` instead, which is what the aggregator does for the single-object
case. Events are the reference's primary user-facing progress channel
(SURVEY.md §5) — kept that way here.
"""

from __future__ import annotations

import datetime
import hashlib
import threading
import time
from typing import Any, Dict

from .client import ApiError, KubeClient


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


class Recorder:
    def __init__(self, client: KubeClient, component: str = "model-controller"):
        self._c = client
        self._component = component
        self._lock = threading.Lock()
        self._recent: Dict[str, float] = {}  # event name -> last emit time

    def event(self, obj: Dict[str, Any], type_: str, reason: str,
              message: str) -> None:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace", "default")
        key = hashlib.sha1(
            f"{ns}/{meta.get('name')}/{reason}/{message}".encode()
        ).hexdigest()[:16]
        name = f"{meta.get('name')}.{key}"
        now = time.time()
        with self._lock:
            recent = self._recent.get(name, 0)
            self._recent[name] = now
            if len(self._recent) > 1024:  # bound the dedupe table
                cutoff = now - 600
                self._recent = {k: v for k, v in self._recent.items()
                                if v > cutoff}
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": ns},
            "involvedObject": {
                "apiVersion": obj.get("apiVersion"),
                "kind": obj.get("kind"),
                "name": meta.get("name"),
                "namespace": ns,
                "uid": meta.get("uid"),
            },
            "type": type_,
            "reason": reason,
            "message": message,
            "source": {"component": self._component},
            "firstTimestamp": _now(),
            "lastTimestamp": _now(),
            "count": 1,
        }
        try:
            if now - recent < 600:
                cur = self._c.get("v1", "Event", ns, name)
                if cur is not None:
                    cur["count"] = int(cur.get("count", 1)) + 1
                    cur["lastTimestamp"] = _now()
                    self._c.update(cur)
                    return
            self._c.create(ev)
        except ApiError:
            pass  # events are best-effort, like client-go's recorder

    def eventf(self, obj: Dict[str, Any], type_: str, reason: str,
               fmt: str, *args: Any) -> None:
        self.event(obj, type_, reason, fmt % args if args else fmt)


class NullRecorder(Recorder):
    """For unit tests of pure builders."""

    def __init__(self):  # noqa: D107 — no client
        self._events = []

    def event(self, obj, type_, reason, message):  # noqa: D102
        self._events.append((type_, reason, message))
