"""Model CRD schema: group/kind constants, spec accessors, condition types.

The reference declares these as Go structs (`ModelSpec`/`ModelStatus`,
/root/reference/api/v1/model_types.go:35-139) compiled into a CRD by
controller-gen. Here the schema lives in `config/crd/` (hand-maintained
OpenAPI, built into dist/install.yaml by hack/build_installer.py) and this
module gives typed *views* over the plain-dict objects the stdlib client
returns — no codegen, no deepcopy layer (dicts are copied by the client
boundary instead of zz_generated.deepcopy.go).

Reference-compatible fields: replicas, image, imagePullPolicy,
imagePullSecrets, storageClassName, persistentVolumeClaim,
persistentVolume.accessMode (model_types.go:41-76). TPU extensions (all
optional, absent = reference behavior on CPU): runtime, tpu.topology,
tpu.accelerator, contextLength, sharding.{tp,sp,dp}, quantization.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

GROUP = "ollama.ayaka.io"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "Model"
PLURAL = "models"

# Condition types — the same vocabulary as model_types.go:84-97, but unlike
# the reference (which replaces the whole array, model_controller.go:192-199)
# our conditions are additive and ReplicaFailure is actually produced
# (SURVEY.md §2.1 "spec-surface vs. behavior gaps").
CONDITION_UNKNOWN = "Unknown"
CONDITION_AVAILABLE = "Available"
CONDITION_PROGRESSING = "Progressing"
CONDITION_REPLICA_FAILURE = "ReplicaFailure"

# TPU topology catalog: name -> (hosts, chips_per_host, gke topology label).
# v5e host = 4 chips (v5litepod); one entry per ladder config in BASELINE.md.
TPU_TOPOLOGIES: Dict[str, tuple] = {
    "v5e-1": (1, 1, "1x1"),
    "v5e-4": (1, 4, "2x2"),
    "v5e-8": (2, 4, "2x4"),
    "v5e-16": (4, 4, "4x4"),
    "v5e-32": (8, 4, "4x8"),
    "v5e-64": (16, 4, "8x8"),
    "v5e-128": (32, 4, "8x16"),
    "v5e-256": (64, 4, "16x16"),
}

# GKE nodeSelector values per topology family (cloud.google.com/gke-tpu-*).
GKE_ACCELERATOR = {"v5e": "tpu-v5-lite-podslice"}


@dataclasses.dataclass(frozen=True)
class TpuPlacement:
    """Resolved hardware placement for one Model."""

    topology: str
    hosts: int
    chips_per_host: int
    accelerator: str
    gke_topology: str = "1x1"

    @property
    def chips(self) -> int:
        return self.hosts * self.chips_per_host

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1


class ModelSpecView:
    """Read-only accessor over a Model object dict with defaulting."""

    def __init__(self, model: Dict[str, Any]):
        self._m = model or {}
        self._spec = self._m.get("spec") or {}

    # --- metadata -------------------------------------------------------
    @property
    def name(self) -> str:
        return (self._m.get("metadata") or {}).get("name", "")

    @property
    def namespace(self) -> str:
        return (self._m.get("metadata") or {}).get("namespace", "default")

    @property
    def uid(self) -> Optional[str]:
        return (self._m.get("metadata") or {}).get("uid")

    # --- reference-compatible spec fields -------------------------------
    @property
    def image(self) -> str:
        return self._spec.get("image", "")

    @property
    def replicas(self) -> int:
        r = self._spec.get("replicas")
        return 1 if r is None else int(r)

    @property
    def image_pull_policy(self) -> Optional[str]:
        return self._spec.get("imagePullPolicy")

    @property
    def image_pull_secrets(self) -> List[Dict[str, Any]]:
        return self._spec.get("imagePullSecrets") or []

    @property
    def storage_class_name(self) -> Optional[str]:
        return self._spec.get("storageClassName")

    @property
    def persistent_volume_claim(self) -> Optional[Dict[str, Any]]:
        return self._spec.get("persistentVolumeClaim")

    @property
    def pv_access_mode(self) -> Optional[str]:
        pv = self._spec.get("persistentVolume") or {}
        return pv.get("accessMode")

    # --- TPU extensions -------------------------------------------------
    @property
    def runtime(self) -> str:
        """`tpu` (default) or `cpu` (kind e2e / dev clusters)."""
        return self._spec.get("runtime") or "tpu"

    @property
    def context_length(self) -> Optional[int]:
        v = self._spec.get("contextLength")
        return None if v is None else int(v)

    @property
    def quantization(self) -> Optional[str]:
        return self._spec.get("quantization")

    @property
    def sharding(self) -> Dict[str, int]:
        """Explicit mesh override {tp,sp,dp}; empty = auto from topology."""
        return {k: int(v) for k, v in (self._spec.get("sharding") or {}).items()}

    @property
    def server_image(self) -> Optional[str]:
        """Override for the runtime container image (spec.serverImage)."""
        return self._spec.get("serverImage")

    @property
    def gateway(self) -> Optional[bool]:
        """`spec.gateway` tri-state: True forces the fleet gateway on,
        False forces it off, None (absent) = auto — enabled whenever the
        Model is a fleet (replicas > 1 or autoscaling), where round-robin
        Service routing would shred prefix-cache locality."""
        v = self._spec.get("gateway")
        return None if v is None else bool(v)

    @property
    def disaggregate(self) -> Dict[str, Any]:
        """`spec.disaggregate`: split the fleet into a prefill pool and
        a decode pool with direct KV page transfer at first token
        (ISSUE 20). Absent/false = today's unified fleet, untouched.
        ``true`` enables with defaults; a dict form carries per-pool
        blocks::

            disaggregate:
              enabled: true
              prefill: {minReplicas: 1, maxReplicas: 4}
              decode:  {minReplicas: 2, maxReplicas: 8}

        Returns {} when off, else a dict with at least
        ``{"enabled": True}`` (the bool form normalizes to that)."""
        v = self._spec.get("disaggregate")
        if not v:
            return {}
        if v is True:
            return {"enabled": True}
        if isinstance(v, dict):
            return {} if v.get("enabled") is False else dict(v, enabled=True)
        return {"enabled": True}

    @property
    def autoscale(self) -> Dict[str, Any]:
        """`spec.autoscale` block (absent = autoscaling off).

        Fields (all optional, env `TPU_AUTOSCALE_*` supplies defaults —
        see operator/autoscale.py): enabled, minReplicas, maxReplicas,
        targetOccupancy, lowOccupancy, upCooldownSeconds,
        downCooldownSeconds, upStreak, downStreak, idleTTLSeconds,
        backlogTokensPerReplica, staleSeconds, flapWindowSeconds,
        flapMaxFlips, flapHoldSeconds.
        """
        return self._spec.get("autoscale") or {}

    def tpu_placement(self) -> Optional[TpuPlacement]:
        if self.runtime != "tpu":
            return None
        tpu = self._spec.get("tpu") or {}
        topology = tpu.get("topology") or "v5e-1"
        if topology not in TPU_TOPOLOGIES:
            raise ValueError(
                f"unknown tpu.topology {topology!r}; "
                f"known: {sorted(TPU_TOPOLOGIES)}")
        hosts, cph, gke = TPU_TOPOLOGIES[topology]
        family = topology.split("-")[0]
        accelerator = tpu.get("accelerator") or GKE_ACCELERATOR.get(
            family, GKE_ACCELERATOR["v5e"])
        return TpuPlacement(topology=topology, hosts=hosts,
                            chips_per_host=cph, accelerator=accelerator,
                            gke_topology=gke)


def owner_reference(model: Dict[str, Any], controller: bool = True
                    ) -> Dict[str, Any]:
    """OwnerReference back to the Model CR (model.go:63-69 equivalent)."""
    meta = model.get("metadata") or {}
    return {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "name": meta.get("name"),
        "uid": meta.get("uid"),
        "controller": controller,
        "blockOwnerDeletion": True,
    }
