"""Workload assembly: pure functions that build & poll K8s objects.

The reference's L1 (/root/reference/pkg/model/image_store.go, model.go):
a namespace-singleton image-store trio (PVC + StatefulSet running the
store server + ClusterIP Service) shared by all models, and a per-model
Deployment (puller init container + server container, PVC mounted RO) +
Service. Same shape here, with TPU additions:

- single-host placements stay a Deployment (replica fan-out = dp, exactly
  the reference's only parallelism, SURVEY.md §2.3);
- multi-host slices become a StatefulSet + headless Service per replica
  group, because jax.distributed needs stable per-process identities and a
  coordinator address — pods of one group form ONE sharded model server.

Deliberate fixes over the reference (SURVEY.md §2.1 gap list): spec.image
changes ARE reconciled (update_deployment syncs the puller arg + preload
env, not just replicas); imagePullPolicy/imagePullSecrets are honored;
per-model storage knobs apply to the store PVC as before.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from . import pod as podf
from .client import Conflict, KubeClient
from .recorder import Recorder
from .types import ModelSpecView, TpuPlacement, owner_reference

IMAGE_STORE_NAME = "ollama-models-store"
IMAGE_STORE_PVC = "ollama-models-store-pvc"
IMAGE_STORE_SERVICE = IMAGE_STORE_NAME
DEFAULT_STORE_SIZE = "100Gi"  # image_store.go:77 hardcodes the same


def model_app_name(name: str) -> str:
    """model.go:20-22 — the `ollama-model-<name>` convention."""
    return f"ollama-model-{name}"


def headless_service_name(name: str) -> str:
    return f"{model_app_name(name)}-hosts"


def gateway_app_name(name: str) -> str:
    """The per-Model fleet-gateway Deployment/pod app label."""
    return f"{model_app_name(name)}-gateway"


# Pod label carrying a replica's disagg pool; must match what the
# gateway's kube discovery reads (operator/gateway.py POOL_LABEL).
POOL_LABEL = "ollama.ayaka.io/pool"
DISAGG_POOLS = ("prefill", "decode")


def pool_app_name(name: str, pool: str) -> str:
    """Deployment name for one disagg pool (`ollama-model-<name>-prefill`
    / `-decode`). Pods keep the shared ``app`` label — discovery, the
    Service, and the utilization scrape see one fleet — and add
    POOL_LABEL for pool-aware routing/scaling."""
    return f"{model_app_name(name)}-{pool}"


def disagg_enabled(spec: ModelSpecView) -> bool:
    """Disaggregated prefill/decode pools (`spec.disaggregate`,
    ISSUE 20). Single-host fleets only: a multi-host slice is one
    sharded server — there is no fleet to split."""
    placement = spec.tpu_placement()
    if placement is not None and placement.multi_host:
        return False
    return bool(spec.disaggregate)


def pool_replicas(spec: ModelSpecView, pool: str) -> int:
    """Seed replica count for one pool: explicit
    ``disaggregate.<pool>.replicas`` wins; defaults keep the total near
    ``spec.replicas`` (prefill 1, decode the rest) because decode slots
    dominate steady-state demand."""
    block = (spec.disaggregate.get(pool) or {})
    r = block.get("replicas")
    if r is not None:
        return max(0, int(r))
    if pool == "prefill":
        return 1
    return max(1, spec.replicas - 1)


def gateway_enabled(spec: ModelSpecView) -> bool:
    """The gateway fronts single-host FLEETS: spec.gateway forces it
    on/off; absent means auto — on when replicas > 1 or autoscaling is
    enabled (the cases where the plain Service's random routing shreds
    prefix-cache locality and a replica death is client-visible).
    Multi-host slices are one sharded server behind host-0; nothing to
    route across. A disaggregated fleet ALWAYS has the gateway: it is
    the handoff orchestrator."""
    placement = spec.tpu_placement()
    if placement is not None and placement.multi_host:
        return False
    if disagg_enabled(spec):
        return True
    if spec.gateway is not None:
        return spec.gateway
    autoscaling = bool((spec.autoscale or {}).get("enabled"))
    return spec.replicas > 1 or autoscaling


# ---------------------------------------------------------------------------
# image store (namespace singleton): PVC + StatefulSet + Service
# ---------------------------------------------------------------------------

def build_store_pvc(namespace: str, spec: ModelSpecView) -> Dict[str, Any]:
    pvc_spec: Dict[str, Any] = {
        # RWX so every model pod on every node mounts the same blobs;
        # overridable via spec.persistentVolume.accessMode
        # (image_store.go:62-65).
        "accessModes": [spec.pv_access_mode or "ReadWriteMany"],
        "resources": {"requests": {"storage": DEFAULT_STORE_SIZE}},
    }
    if spec.storage_class_name:
        pvc_spec["storageClassName"] = spec.storage_class_name
    if spec.persistent_volume_claim:
        # spec.persistentVolumeClaim points at a pre-provisioned claim; the
        # reference forwards its claimName via the volume instead of
        # creating — handled in volumes() below.
        pass
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": IMAGE_STORE_PVC, "namespace": namespace},
        "spec": pvc_spec,
    }


def _store_volume(spec: ModelSpecView) -> Dict[str, Any]:
    claim = IMAGE_STORE_PVC
    if spec.persistent_volume_claim:
        claim = spec.persistent_volume_claim.get("claimName", claim)
    return {
        "name": podf.VOLUME_NAME,
        "persistentVolumeClaim": {"claimName": claim},
    }


def build_store_statefulset(namespace: str, spec: ModelSpecView,
                            server_image: str) -> Dict[str, Any]:
    labels = {"app": IMAGE_STORE_NAME}
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": IMAGE_STORE_NAME, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "serviceName": IMAGE_STORE_SERVICE,
            "replicas": 1,
            "selector": {"matchLabels": dict(labels)},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "restartPolicy": "Always",
                    "containers": [podf.new_server_container(
                        read_only=False, image=server_image,
                        store_only=True)],
                    "volumes": [_store_volume(spec)],
                },
            },
        },
    }


def build_store_service(namespace: str) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": IMAGE_STORE_SERVICE, "namespace": namespace},
        "spec": {
            "type": "ClusterIP",
            "selector": {"app": IMAGE_STORE_NAME},
            "ports": [{"name": "http", "port": podf.PORT,
                       "targetPort": podf.PORT, "protocol": "TCP"}],
        },
    }


# ---------------------------------------------------------------------------
# per-model workload
# ---------------------------------------------------------------------------

def _pod_template(model: Dict[str, Any], spec: ModelSpecView,
                  server_image: str,
                  placement: Optional[TpuPlacement],
                  multihost_sts: Optional[str] = None,
                  pool: Optional[str] = None) -> Dict[str, Any]:
    name = spec.name
    labels = {"app": model_app_name(name)}
    extra_env: Optional[list] = None
    if multihost_sts and placement:
        extra_env = (
            [{"name": "TPU_DIST_STS_NAME", "value": multihost_sts}]
            + podf.multihost_env(headless_service_name(name),
                                 spec.namespace, placement.hosts,
                                 placement.chips_per_host))
    if pool:
        # the shared app label keeps discovery/Service/scrape fleet-wide;
        # the pool label is what the gateway routes on
        labels[POOL_LABEL] = pool
        extra_env = (extra_env or []) + [
            {"name": "TPU_DISAGG_ROLE", "value": pool}]
    server = podf.new_server_container(
        read_only=True, image=server_image, model=spec.image,
        placement=placement, context_length=spec.context_length,
        quantization=spec.quantization,
        tp=spec.sharding.get("tp", 0),
        extra_env=extra_env,
    )
    if spec.image_pull_policy:  # honored, unlike the reference (§2.1 gaps)
        server["imagePullPolicy"] = spec.image_pull_policy
    puller = podf.new_puller_container(
        image=spec.image, namespace=spec.namespace, server_image=server_image)
    if spec.image_pull_policy:
        puller["imagePullPolicy"] = spec.image_pull_policy

    pod_spec: Dict[str, Any] = {
        "initContainers": [puller],
        "containers": [server],
        "volumes": [_store_volume(spec)],
        # must cover preStop sleep + the server's SIGTERM drain window +
        # engine teardown, or rollouts SIGKILL pods with streams still
        # finishing (pod.TERMINATION_GRACE_S keeps the three in lockstep)
        "terminationGracePeriodSeconds": podf.TERMINATION_GRACE_S,
    }
    if spec.image_pull_secrets:
        pod_spec["imagePullSecrets"] = copy.deepcopy(spec.image_pull_secrets)
    if placement is not None:
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": placement.accelerator,
            "cloud.google.com/gke-tpu-topology": placement.gke_topology,
        }
        pod_spec["tolerations"] = [{
            "key": "google.com/tpu", "operator": "Exists",
            "effect": "NoSchedule"}]
    return {"metadata": {"labels": labels}, "spec": pod_spec}


def build_model_deployment(model: Dict[str, Any],
                           server_image: str = podf.SERVER_BASE_IMAGE
                           ) -> Dict[str, Any]:
    """Single-host serving: Deployment with spec.replicas fan-out
    (model.go:39-115 equivalent — each replica an independent server, the
    Service load-balances)."""
    spec = ModelSpecView(model)
    placement = spec.tpu_placement()
    app = model_app_name(spec.name)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": app, "namespace": spec.namespace,
            "labels": {"app": app},
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "replicas": spec.replicas,
            "selector": {"matchLabels": {"app": app}},
            "template": _pod_template(model, spec, server_image, placement),
        },
    }


def build_pool_deployment(model: Dict[str, Any], pool: str,
                          server_image: str = podf.SERVER_BASE_IMAGE
                          ) -> Dict[str, Any]:
    """One disagg pool's Deployment (ISSUE 20): named
    ``ollama-model-<name>-<pool>``, selector narrowed by POOL_LABEL so
    the prefill and decode Deployments coexist under the shared ``app``
    label without fighting over pods. The server container gets
    ``TPU_DISAGG_ROLE=<pool>`` so replicas can report their role."""
    spec = ModelSpecView(model)
    placement = spec.tpu_placement()
    app = model_app_name(spec.name)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": pool_app_name(spec.name, pool),
            "namespace": spec.namespace,
            "labels": {"app": app, POOL_LABEL: pool},
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "replicas": pool_replicas(spec, pool),
            "selector": {"matchLabels": {"app": app, POOL_LABEL: pool}},
            "template": _pod_template(model, spec, server_image, placement,
                                      pool=pool),
        },
    }


def build_model_statefulset(model: Dict[str, Any],
                            server_image: str = podf.SERVER_BASE_IMAGE
                            ) -> Dict[str, Any]:
    """Multi-host slice: ONE replica group = `hosts` pods with stable ids;
    `spec.replicas` scales whole groups via `hosts × replicas` pods where
    each group of `hosts` ordinals is one jax.distributed world. Round 1
    supports replicas=1 (one sharded server); the scheduler-level fan-out
    of groups is a documented TODO in the reconciler."""
    spec = ModelSpecView(model)
    placement = spec.tpu_placement()
    assert placement is not None and placement.multi_host
    app = model_app_name(spec.name)
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": app, "namespace": spec.namespace,
            "labels": {"app": app},
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "serviceName": headless_service_name(spec.name),
            "replicas": placement.hosts,
            "podManagementPolicy": "Parallel",  # all hosts must start to
            # rendezvous — ordered startup would deadlock jax.distributed
            "selector": {"matchLabels": {"app": app}},
            "template": _pod_template(model, spec, server_image, placement,
                                      multihost_sts=app),
        },
    }


def build_headless_service(model: Dict[str, Any]) -> Dict[str, Any]:
    """Stable DNS for multi-host rendezvous (`<sts>-0.<svc>.<ns>.svc`)."""
    spec = ModelSpecView(model)
    app = model_app_name(spec.name)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": headless_service_name(spec.name),
            "namespace": spec.namespace,
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "clusterIP": "None",
            "publishNotReadyAddresses": True,  # coordinator DNS must
            # resolve before readiness (rendezvous happens pre-Ready)
            "selector": {"app": app},
            "ports": [{"name": "dist", "port": 8476, "protocol": "TCP"}],
        },
    }


def build_gateway_deployment(model: Dict[str, Any],
                             server_image: str = podf.SERVER_BASE_IMAGE
                             ) -> Dict[str, Any]:
    """One fleet-gateway Deployment per gatewayed Model (operator/
    gateway.py): cache-aware routing by prefix hash, per-replica circuit
    breaking, and zero-error cross-replica stream failover. The model
    Service's selector is pointed at THIS deployment when the gateway is
    enabled (build_model_service), so clients keep the same DNS name."""
    spec = ModelSpecView(model)
    app = model_app_name(spec.name)
    gw_app = gateway_app_name(spec.name)
    gw = podf.new_gateway_container(namespace=spec.namespace, app=app,
                                    image=server_image)
    if spec.image_pull_policy:
        gw["imagePullPolicy"] = spec.image_pull_policy
    pod_spec: Dict[str, Any] = {
        "containers": [gw],
        # the persist log lives on the same PVC the weight cache uses
        "volumes": [_store_volume(spec)],
        # preStop sleep + begin_drain window + persist flush must all fit
        # before the kubelet SIGKILLs (same geometry as server pods)
        "terminationGracePeriodSeconds": podf.TERMINATION_GRACE_S,
    }
    if spec.image_pull_secrets:
        pod_spec["imagePullSecrets"] = copy.deepcopy(spec.image_pull_secrets)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": gw_app, "namespace": spec.namespace,
            "labels": {"app": gw_app},
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": gw_app}},
            "template": {"metadata": {"labels": {"app": gw_app}},
                         "spec": pod_spec},
        },
    }


def build_model_service(model: Dict[str, Any]) -> Dict[str, Any]:
    """ClusterIP LB over serving pods (model.go:203-256 equivalent). For
    multi-host, only host-0 carries the `serving` role label so requests
    land on the process that owns the HTTP front. When the fleet gateway
    is enabled the Service selects the gateway pod instead — same DNS
    name, routing-law-aware backend."""
    spec = ModelSpecView(model)
    app = model_app_name(spec.name)
    placement = spec.tpu_placement()
    selector = {"app": app}
    if placement is not None and placement.multi_host:
        selector["apps.kubernetes.io/pod-index"] = "0"
    elif gateway_enabled(spec):
        selector = {"app": gateway_app_name(spec.name)}
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": app, "namespace": spec.namespace,
            # the managed label is the ServiceMonitor's scrape selector
            # (config/prometheus/monitor.yaml)
            "labels": {"app": app, "ollama.ayaka.io/managed": "true"},
            # the reference owner-refs the Service to the Deployment
            # (model.go:225-231); we owner-ref the Model so a CR delete
            # cascades everything in one sweep — same end state.
            "ownerReferences": [owner_reference(model)],
        },
        "spec": {
            "type": "ClusterIP",
            "selector": selector,
            "ports": [{"name": "http", "port": podf.PORT,
                       "targetPort": podf.PORT, "protocol": "TCP"}],
        },
    }


# ---------------------------------------------------------------------------
# ensure / poll — the reconciler's verbs (create-if-absent + readiness)
# ---------------------------------------------------------------------------

def _ensure(c: KubeClient, obj: Dict[str, Any]) -> Dict[str, Any]:
    meta = obj["metadata"]
    cur = c.get(obj["apiVersion"], obj["kind"], meta.get("namespace"),
                meta["name"])
    if cur is not None:
        # create-if-absent, except labels: sync missing desired labels so
        # upgrades can retrofit selectors (e.g. the ServiceMonitor scrape
        # label) onto objects created by older operator versions
        want = meta.get("labels") or {}
        have = (cur.get("metadata") or {}).get("labels") or {}
        missing = {k: v for k, v in want.items() if have.get(k) != v}
        if missing:
            cur.setdefault("metadata", {}).setdefault(
                "labels", {}).update(missing)
            try:
                return c.update(cur)
            except Conflict:
                return cur
        return cur
    try:
        return c.create(obj)
    except Conflict:
        return c.get(obj["apiVersion"], obj["kind"], meta.get("namespace"),
                     meta["name"]) or obj


def ensure_image_store(c: KubeClient, rec: Recorder, model: Dict[str, Any],
                       spec: ModelSpecView, server_image: str) -> None:
    """PVC → StatefulSet → Service (image_store.go:41,126,239 ladder)."""
    ns = spec.namespace
    if c.get("v1", "PersistentVolumeClaim", ns, IMAGE_STORE_PVC) is None \
            and not spec.persistent_volume_claim:
        c.create(build_store_pvc(ns, spec))
        rec.event(model, "Normal", "ImageStorePVCCreated",
                  f"created {IMAGE_STORE_PVC} in {ns}")
    if c.get("apps/v1", "StatefulSet", ns, IMAGE_STORE_NAME) is None:
        _ensure(c, build_store_statefulset(ns, spec, server_image))
        rec.event(model, "Normal", "ImageStoreStatefulSetCreated",
                  f"created {IMAGE_STORE_NAME} in {ns}")
    if c.get("v1", "Service", ns, IMAGE_STORE_SERVICE) is None:
        _ensure(c, build_store_service(ns))
        rec.event(model, "Normal", "ImageStoreServiceCreated",
                  f"created {IMAGE_STORE_SERVICE} in {ns}")


def is_statefulset_ready(c: KubeClient, namespace: str, name: str,
                         want: int = 1) -> bool:
    sts = c.get("apps/v1", "StatefulSet", namespace, name)
    if sts is None:
        return False
    return int((sts.get("status") or {}).get("readyReplicas") or 0) >= want


def is_service_ready(c: KubeClient, namespace: str, name: str) -> bool:
    svc = c.get("v1", "Service", namespace, name)
    if svc is None:
        return False
    s = svc.get("spec") or {}
    return bool(s.get("clusterIP"))  # "None" (headless) is also ready


def is_deployment_ready(c: KubeClient, namespace: str, name: str,
                        want: int) -> bool:
    dep = c.get("apps/v1", "Deployment", namespace, name)
    if dep is None:
        return False
    return int((dep.get("status") or {}).get("readyReplicas") or 0) >= want


def deployment_replica_failure(dep: Dict[str, Any]) -> Optional[str]:
    """Surface apps/v1 ReplicaFailure (the reference declares the condition
    type but never sets it — model_types.go:96, SURVEY.md §2.1)."""
    for cond in (dep.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "ReplicaFailure" and \
                cond.get("status") == "True":
            return cond.get("message") or cond.get("reason") or "ReplicaFailure"
    return None


SPEC_HASH_ANNOTATION = "ollama.ayaka.io/spec-hash"

# Drain-first scale-down protocol (PR 9 drain made stream-preserving
# removal possible; the autoscaler uses it for every shrink). The victim
# pod is annotated, its server is told to drain (readyz flips, streams
# finish), and only then does the Deployment shrink — the deletion-cost
# annotation steers the ReplicaSet controller to remove OUR victim, not
# a random healthy pod.
DRAIN_ANNOTATION = "ollama.ayaka.io/draining"
POD_DELETION_COST = "controller.kubernetes.io/pod-deletion-cost"
# Wake signal for scale-to-zero: the gateway/router (or an admin) sets
# this annotation on the Model CR; the reconciler scales to
# max(1, minReplicas) and clears it.
WAKE_ANNOTATION = "ollama.ayaka.io/wake"


def pod_is_drain_victim(pod: Dict[str, Any]) -> bool:
    anns = (pod.get("metadata") or {}).get("annotations") or {}
    return anns.get(DRAIN_ANNOTATION) == "true"


def mark_drain_victim(c: KubeClient, pod: Dict[str, Any]) -> None:
    """Annotate the victim (idempotent) so the choice survives operator
    restarts and the ReplicaSet controller deletes it first."""
    anns = pod.setdefault("metadata", {}).setdefault("annotations", {})
    if anns.get(DRAIN_ANNOTATION) == "true":
        return
    anns[DRAIN_ANNOTATION] = "true"
    anns[POD_DELETION_COST] = "-999"
    c.update(pod)


def spec_hash(want: Dict[str, Any]) -> str:
    """Stable digest of the pod template we intend. Drift detection
    compares this recorded intent against the new intent — never the live
    object's template, because the apiserver enriches live templates with
    defaulted fields (imagePullPolicy, probe timeouts, …) that would read
    as spurious drift on every reconcile."""
    import hashlib
    import json as _json
    payload = _json.dumps(want["spec"]["template"], sort_keys=True,
                          separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def stamp_spec_hash(want: Dict[str, Any]) -> Dict[str, Any]:
    want["metadata"].setdefault("annotations", {})[SPEC_HASH_ANNOTATION] = \
        spec_hash(want)
    return want


def update_model_workload(c: KubeClient, rec: Recorder, model: Dict[str, Any],
                          cur: Dict[str, Any], want: Dict[str, Any]) -> bool:
    """Sync mutable fields of the existing workload: replicas AND the pod
    template (the reference only syncs replicas, model.go:149-186 — image
    drift is a known gap we close). Template changes are detected via the
    recorded spec-hash annotation (see spec_hash). Returns True if an
    update was written (caller requeues)."""
    changed = False
    cs, ws = cur.get("spec") or {}, want["spec"]
    if cs.get("replicas") != ws.get("replicas"):
        cs["replicas"] = ws["replicas"]
        changed = True
    want_hash = spec_hash(want)
    cur_hash = ((cur.get("metadata") or {}).get("annotations") or {}
                ).get(SPEC_HASH_ANNOTATION)
    if cur_hash != want_hash:
        cs["template"] = want["spec"]["template"]
        cur.setdefault("metadata", {}).setdefault(
            "annotations", {})[SPEC_HASH_ANNOTATION] = want_hash
        changed = True
    if changed:
        cur["spec"] = cs
        c.update(cur)
        rec.event(model, "Normal", "WorkloadUpdated",
                  f"synced {cur['kind']} {cur['metadata']['name']}")
    return changed
