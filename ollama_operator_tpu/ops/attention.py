"""Attention cores (pure-JAX reference paths).

These are the semantics-defining implementations; ``ops/pallas`` provides
TPU-tuned kernels that must match them bit-approximately. GQA is expressed as
a grouped einsum (no materialised head repeat) so XLA keeps the MXU matmuls
large and avoids an HBM-resident K/V copy.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def softcap_scores(scores, cap: float):
    """Gemma2-style tanh soft-capping (no-op when cap <= 0)."""
    if cap and cap > 0.0:
        return cap * jnp.tanh(scores / cap)
    return scores


_softcap = softcap_scores


def attend(q, k, v, mask, scale: float, softcap: float = 0.0):
    """Grouped-query attention.

    q    [B, T, H, hd]
    k, v [B, S, KvH, hd]
    mask [B, 1, T, S] additive (0 or NEG_INF), broadcastable
    →    [B, T, H, hd]
    """
    B, T, H, hd = q.shape
    KvH = k.shape[2]
    G = H // KvH
    qg = q.reshape(B, T, KvH, G, hd)
    # scores [B, KvH, G, T, S]
    scores = jnp.einsum("btkgh,bskh->bkgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = _softcap(scores, softcap)
    scores = scores + mask[:, :, None, :, :]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, hd)


def attend_hf(q, k, v, mask, scale: float, softcap: float = 0.0):
    """Grouped-query attention with **head-first** K/V — the serving
    layout: the KV cache keeps (seq, head_dim) as its trailing dims so the
    pallas kernels tile it directly and XLA reads it without relayout.

    q    [B, T, H, hd]
    k, v [B, KvH, S, hd]
    mask [B, 1, T, S] additive, broadcastable
    →    [B, T, H, hd]
    """
    B, T, H, hd = q.shape
    KvH = k.shape[1]
    G = H // KvH
    qg = q.reshape(B, T, KvH, G, hd)
    scores = jnp.einsum("btkgh,bksh->bkgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = _softcap(scores * scale, softcap)
    scores = scores + mask[:, :, None, :, :]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bksh->btkgh", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, hd)


def causal_mask(T: int, S: int, offset, dtype=jnp.float32,
                sliding_window: int = 0):
    """Additive [1, 1, T, S] mask. Query i sits at absolute position
    offset + i; key j at absolute position j. Supports a sliding window
    (mistral) when ``sliding_window > 0``."""
    q_pos = offset + jnp.arange(T)[:, None]
    k_pos = jnp.arange(S)[None, :]
    ok = k_pos <= q_pos
    if sliding_window:
        ok = ok & (k_pos > q_pos - sliding_window)
    return jnp.where(ok, 0.0, NEG_INF).astype(dtype)[None, None]


# ---------------------------------------------------------------------------
# kernel dispatch (ModelConfig.kernels: auto | pallas | xla | interpret)
# ---------------------------------------------------------------------------

KERNEL_MODES = ("auto", "pallas", "xla", "interpret")


def _mesh_attn_axes(mesh, B: int, H: int, KvH: int):
    """(batch_axis, head_axis) for a dp/tp-manual ``shard_map`` around the
    attention kernels, or None when this mesh can't shard them evenly.

    pallas_call is opaque to GSPMD — on a real mesh the kernels must run
    inside a manual region where each device sees only its local heads /
    batch rows (attention needs no cross-device traffic along dp or tp:
    heads and batch entries are independent). sp/pp paths wrap attention
    themselves (parallel/long_context.py, parallel/pipeline.py) and ep
    meshes stay on the einsum path (MoE attention operands would be
    GSPMD-auto along ep inside the manual region — untested; einsum is
    correct there)."""
    if mesh is None or mesh.size == 1:
        return None
    shape = dict(mesh.shape)
    if (shape.get("sp", 1) > 1 or shape.get("pp", 1) > 1
            or shape.get("ep", 1) > 1):
        return None
    dp, tp = shape.get("dp", 1), shape.get("tp", 1)
    if dp * tp != mesh.size:
        return None
    if B % dp or H % tp or KvH % tp:
        return None
    return ("dp" if dp > 1 else None), ("tp" if tp > 1 else None)


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names):
    """jax.shard_map across jax versions: the top-level API (with
    axis_names/check_vma) landed after 0.4. The old experimental shard_map
    cannot express partial-manual regions that use ``lax.axis_index`` (its
    ``auto=`` lowering emits a PartitionId op GSPMD rejects), so the
    fallback goes fully manual instead: axes outside ``axis_names`` are
    unmentioned in the specs, so their values — including closed-over
    params — replicate into the region. Same results, more per-device
    memory; only the newer-jax path runs partial-manual."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names,
                             check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def axis_size_compat(axis_name):
    """Static mesh-axis size inside a shard_map region across jax versions:
    ``lax.axis_size`` is newer; older jax exposes the same static int via
    ``core.axis_frame``."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.core.axis_frame(axis_name)


def pcast_varying_compat(x, axis_name):
    """``lax.pcast(..., to="varying")`` where available. Older jax's
    shard_map has no varying-type system (we run it with check_rep=False),
    so the cast is a no-op there."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    return x


def _sharded_kernel_call(mesh, q, KvH: int, tileable, inner, args,
                         with_pos: bool):
    """Run a pallas attention kernel inside a dp/tp-manual shard_map.

    ``tileable(H_local, KvH_local)`` re-checks the kernel's bail conditions
    at per-device shapes BEFORE entering the manual region (a mid-trace
    None-fallback is impossible inside shard_map). Returns the sharded
    result, or None when the mesh can't shard or the kernel wouldn't tile —
    callers then fall back to the einsum path (GSPMD-auto). ``args`` are
    (q, k, v[, pos]) with k/v head-first; ``with_pos`` appends the [B]
    q_pos spec."""
    from jax.sharding import PartitionSpec as P
    B, _, H, _ = q.shape
    axes = _mesh_attn_axes(mesh, B, H, KvH)
    if axes is None:
        return None
    tp = mesh.shape.get("tp", 1)
    if not tileable(H // tp, KvH // tp):
        return None
    b_ax, h_ax = axes
    qspec = P(b_ax, None, h_ax, None)
    kvspec = P(b_ax, h_ax, None, None)
    in_specs = (qspec, kvspec, kvspec) + ((P(b_ax),) if with_pos else ())
    return shard_map_compat(inner, mesh=mesh, in_specs=in_specs,
                            out_specs=qspec, axis_names={"dp", "tp"})(*args)


def resolve_kernels(kernels: str) -> str:
    """Trace-time kernel choice. ``auto`` → pallas on TPU backends, XLA
    elsewhere. The OLLAMA_TPU_KERNELS env var overrides only the ``auto``
    choice — an explicit config always wins. (On >1-device meshes the
    dispatchers below run the kernels inside a dp/tp-manual shard_map;
    there is no multi-device XLA fallback anymore.)"""
    env = os.environ.get("OLLAMA_TPU_KERNELS", "")
    if env:
        if env not in KERNEL_MODES:
            raise ValueError(
                f"OLLAMA_TPU_KERNELS={env!r}; expected one of {KERNEL_MODES}")
        if kernels == "auto":
            kernels = env
    if kernels == "auto":
        kernels = "pallas" if jax.default_backend() == "tpu" else "xla"
    return kernels


def chunk_attention(cfg, q, k, v, mask, scale: float, mesh=None):
    """Prefill attention over a fresh chunk (chunk-local causal semantics,
    the mask callers build via ``causal_mask(T, T, 0)``). K/V are
    head-first [B, KvH, T, hd]. Routes to the pallas flash kernel when
    enabled and tileable, else the einsum path. On a >1-device ``mesh``
    the kernel runs inside a dp/tp-manual shard_map (each device computes
    its local heads/batch rows; no collectives — attention is independent
    along both axes), so GSPMD never sees the opaque pallas_call."""
    mode = resolve_kernels(cfg.kernels)
    if mode in ("pallas", "interpret"):
        from .pallas import flash_prefill, prefill_tileable
        interp = mode == "interpret"
        T, hd = q.shape[1], q.shape[3]

        def inner(q, k, v):
            return flash_prefill(q, k, v, scale, cfg.attn_softcap,
                                 cfg.sliding_window, interpret=interp)

        if mesh is not None and mesh.size > 1:
            out = _sharded_kernel_call(
                mesh, q, k.shape[1],
                lambda h, kvh: prefill_tileable(T, h, kvh, hd, interp),
                inner, (q, k, v), with_pos=False)
            # None → mesh not shardable/tileable → einsum (GSPMD-auto)
        else:
            out = inner(q, k, v)
        if out is not None:
            return out
    return attend_hf(q, k, v, mask, scale, cfg.attn_softcap)


def cached_attention(cfg, q, k_cache, v_cache, mask, q_pos, scale: float,
                     attn_len=None, mesh=None):
    """Attention against the head-first slot KV cache [B, KvH, S, hd].
    ``q_pos`` [B, T] are the new tokens' absolute positions (the T=1 decode
    step routes to the pallas kernel, which skips unread cache blocks; T>1
    continuations use the masked einsum path). ``attn_len`` statically
    bounds the attended prefix: the einsum path slices the cache view (the
    lazy slice fuses into its reads). The decode path (forward_with_cache)
    hands this an A-sized window sliced from the full cache carry, so the
    pallas kernel's operand is that window — materialized once per layer
    either way; the kernel's q_pos block clamp still elides unread blocks'
    DMAs within it. On a >1-device ``mesh`` the kernel runs inside a
    dp/tp-manual shard_map (see chunk_attention)."""
    mode = resolve_kernels(cfg.kernels)
    # MHA (G == 1) maps badly onto the GQA decode kernel's (B, KvH, nk)
    # grid — B×KvH tiny 8-row programs lose to one big XLA einsum
    # (measured on v5e: phi 128 vs 147 tok/s) — so "auto"-resolved pallas
    # skips it; an explicit pallas choice (config or OLLAMA_TPU_KERNELS)
    # still forces it. TPU_MHA_KERNEL=1 instead routes MHA through the
    # head-tiled mha_decode kernel (grid (B, H/8, nk) — pallas/flash.py);
    # it stays opt-in until a chip capture shows it beating the einsum
    # (bench.py measures both).
    explicit_pallas = (cfg.kernels == "pallas"
                       or os.environ.get("OLLAMA_TPU_KERNELS") == "pallas")
    is_mha = q.shape[2] == k_cache.shape[1]
    mha_kernel = is_mha and os.environ.get("TPU_MHA_KERNEL", "") == "1"
    gqa_ok = (not is_mha) or explicit_pallas or mha_kernel
    if (mode in ("pallas", "interpret") and q.shape[1] == 1
            and (gqa_ok or mode == "interpret")):
        from .pallas import (decode_attention, decode_tileable,
                             mha_decode_attention, mha_decode_tileable)
        interp = mode == "interpret"
        hd, S = q.shape[3], k_cache.shape[2]

        if mha_kernel:
            def inner(q, k_cache, v_cache, pos):
                return mha_decode_attention(
                    q, k_cache, v_cache, pos, scale, cfg.attn_softcap,
                    cfg.sliding_window, interpret=interp)

            def tileable(h, kvh):
                return mha_decode_tileable(S, h, kvh, hd, interp)
        else:
            def inner(q, k_cache, v_cache, pos):
                return decode_attention(
                    q, k_cache, v_cache, pos, scale, cfg.attn_softcap,
                    cfg.sliding_window, interpret=interp)

            def tileable(h, kvh):
                return decode_tileable(S, h, kvh, hd, interp)

        if mesh is not None and mesh.size > 1:
            out = _sharded_kernel_call(
                mesh, q, k_cache.shape[1], tileable,
                inner, (q, k_cache, v_cache, q_pos[:, 0]), with_pos=True)
            # None → mesh not shardable/tileable → einsum (GSPMD-auto)
        else:
            out = inner(q, k_cache, v_cache, q_pos[:, 0])
        if out is not None:
            return out
    if attn_len is not None and attn_len < k_cache.shape[2]:
        k_cache = k_cache[:, :, :attn_len, :]
        v_cache = v_cache[:, :, :attn_len, :]
    return attend_hf(q, k_cache, v_cache, mask, scale, cfg.attn_softcap)
