"""Attention cores (pure-JAX reference paths).

These are the semantics-defining implementations; ``ops/pallas`` provides
TPU-tuned kernels that must match them bit-approximately. GQA is expressed as
a grouped einsum (no materialised head repeat) so XLA keeps the MXU matmuls
large and avoids an HBM-resident K/V copy.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def _softcap(scores, cap: float):
    if cap and cap > 0.0:
        return cap * jnp.tanh(scores / cap)
    return scores


def attend(q, k, v, mask, scale: float, softcap: float = 0.0):
    """Grouped-query attention.

    q    [B, T, H, hd]
    k, v [B, S, KvH, hd]
    mask [B, 1, T, S] additive (0 or NEG_INF), broadcastable
    →    [B, T, H, hd]
    """
    B, T, H, hd = q.shape
    KvH = k.shape[2]
    G = H // KvH
    qg = q.reshape(B, T, KvH, G, hd)
    # scores [B, KvH, G, T, S]
    scores = jnp.einsum("btkgh,bskh->bkgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = _softcap(scores, softcap)
    scores = scores + mask[:, :, None, :, :]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgts,bskh->btkgh", probs.astype(v.dtype), v)
    return out.reshape(B, T, H, hd)


def causal_mask(T: int, S: int, offset, dtype=jnp.float32,
                sliding_window: int = 0):
    """Additive [1, 1, T, S] mask. Query i sits at absolute position
    offset + i; key j at absolute position j. Supports a sliding window
    (mistral) when ``sliding_window > 0``."""
    q_pos = offset + jnp.arange(T)[:, None]
    k_pos = jnp.arange(S)[None, :]
    ok = k_pos <= q_pos
    if sliding_window:
        ok = ok & (k_pos > q_pos - sliding_window)
    return jnp.where(ok, 0.0, NEG_INF).astype(dtype)[None, None]


def length_mask(lengths, S: int, dtype=jnp.float32, q_pos: Optional[jax.Array] = None,
                sliding_window: int = 0):
    """Additive [B, 1, 1, S] mask for decode: key j valid iff j < lengths[b].
    ``q_pos`` (defaults to lengths-1) enables the sliding window check."""
    k_pos = jnp.arange(S)[None, :]
    ok = k_pos < lengths[:, None]
    if sliding_window:
        qp = (lengths - 1) if q_pos is None else q_pos
        ok = ok & (k_pos > qp[:, None] - sliding_window)
    return jnp.where(ok, 0.0, NEG_INF).astype(dtype)[:, None, None, :]
