"""Grammar-constrained decoding: the Ollama ``format: "json"`` option.

The reference delegates structured output to llama.cpp's GBNF sampler inside
the ollama image (/root/reference/pkg/model/pod.go:11; the API field is part
of the /api/generate surface the reference's probes assume). Here the design
is TPU-native: sampling stays **on device**, and the grammar contributes one
packed ``uint32`` bitmask per slot that the jitted decode step unpacks and
applies to the logits (engine.py). The host advances a byte-level JSON
pushdown automaton with each sampled token and uploads the next mask — a
[B, ceil(V/32)] transfer, not a logits download.

Pieces:
- a byte-level PDA over a *packed state* (``bytes``): mode/aux/key flag +
  one byte per open container. Pure-Python reference implementation here;
  ``native/grammar.cpp`` implements the identical contract for the hot
  mask-fill (vocab × token-bytes simulations per novel state).
- ``TokenTable``: per-tokenizer concatenated token bytes + offsets, shared
  mask cache keyed by an *abstract* state (the stack suffix a token of
  ``max_len`` bytes could possibly touch — exact, see ``_cache_key``).
- ``JsonConstraint``: per-request PDA state; ``mask_row()`` → packed mask,
  ``advance(tid)`` → feed the sampled token.

EOS is allowed exactly when the JSON value is complete at depth 0; once
complete, *only* EOS is allowed, which forces generation to stop instead of
trailing whitespace forever.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections import OrderedDict
from typing import Iterable, List, Optional, Sequence

import numpy as np

# --- packed PDA state --------------------------------------------------------
# state = bytes([mode, aux1, aux2, key_flag]) + stack (one byte per open
# container, CTX_OBJ/CTX_ARR, top of stack = last byte)

M_VALUE = 0       # expecting a value
M_ARR_FIRST = 1   # expecting a value or ']' (right after '[')
M_KEY_FIRST = 2   # expecting '"' (object key) or '}' (right after '{')
M_KEY = 3         # expecting '"' (object key, after ',')
M_COLON = 4       # expecting ':'
M_STR = 5         # inside a string (key_flag: 1 = object key)
M_ESC = 6         # after '\'
M_HEX = 7         # inside \uXXXX (aux1 = hex digits remaining)
M_NUM = 8         # inside a number (aux1 = numstate)
M_LIT = 9         # inside true/false/null (aux1 = literal id, aux2 = pos)
M_AFTER = 10      # after a complete value

CTX_OBJ, CTX_ARR = 1, 2

NS_MINUS, NS_ZERO, NS_INT, NS_DOT, NS_FRAC, NS_E, NS_ESIGN, NS_EXP = range(8)
_NS_TERMINAL = frozenset((NS_ZERO, NS_INT, NS_FRAC, NS_EXP))

_LITERALS = (b"true", b"false", b"null")
_WS = frozenset(b" \t\n\r")
_HEXD = frozenset(b"0123456789abcdefABCDEF")
_ESCAPES = frozenset(b'"\\/bfnrt')

INITIAL_STATE = bytes((M_VALUE, 0, 0, 0))


def _start_value(b: int, stack: bytes) -> Optional[bytes]:
    """Value-start byte → new packed state, or None if not a value start.

    Depth is deliberately unbounded (1 byte per open container, and a
    request can open at most num_predict containers) — a depth cap would
    make token acceptance depend on the depth itself and break the
    stack-suffix mask cache (TokenTable._cache_key)."""
    if b == 0x7B:  # {
        return bytes((M_KEY_FIRST, 0, 0, 0)) + stack + bytes((CTX_OBJ,))
    if b == 0x5B:  # [
        return bytes((M_ARR_FIRST, 0, 0, 0)) + stack + bytes((CTX_ARR,))
    if b == 0x22:  # "
        return bytes((M_STR, 0, 0, 0)) + stack
    if b == 0x2D:  # -
        return bytes((M_NUM, NS_MINUS, 0, 0)) + stack
    if b == 0x30:  # 0
        return bytes((M_NUM, NS_ZERO, 0, 0)) + stack
    if 0x31 <= b <= 0x39:
        return bytes((M_NUM, NS_INT, 0, 0)) + stack
    if b == 0x74:  # t
        return bytes((M_LIT, 0, 1, 0)) + stack
    if b == 0x66:  # f
        return bytes((M_LIT, 1, 1, 0)) + stack
    if b == 0x6E:  # n
        return bytes((M_LIT, 2, 1, 0)) + stack
    return None


def _after_value(b: int, stack: bytes) -> Optional[bytes]:
    """One byte in M_AFTER → new packed state, or None."""
    if b in _WS:
        return bytes((M_AFTER, 0, 0, 0)) + stack
    if not stack:
        return None
    top = stack[-1]
    if top == CTX_OBJ:
        if b == 0x2C:  # ,
            return bytes((M_KEY, 0, 0, 0)) + stack
        if b == 0x7D:  # }
            return bytes((M_AFTER, 0, 0, 0)) + stack[:-1]
    else:  # CTX_ARR
        if b == 0x2C:
            return bytes((M_VALUE, 0, 0, 0)) + stack
        if b == 0x5D:  # ]
            return bytes((M_AFTER, 0, 0, 0)) + stack[:-1]
    return None


def advance_byte(state: bytes, b: int) -> Optional[bytes]:
    """Feed one byte to the PDA; returns the new packed state or None."""
    mode, aux1, aux2, key = state[0], state[1], state[2], state[3]
    stack = state[4:]
    if mode == M_VALUE:
        if b in _WS:
            return state
        return _start_value(b, stack)
    if mode == M_ARR_FIRST:
        if b in _WS:
            return state
        if b == 0x5D:  # ]
            return bytes((M_AFTER, 0, 0, 0)) + stack[:-1]
        return _start_value(b, stack)
    if mode == M_KEY_FIRST:
        if b in _WS:
            return state
        if b == 0x22:
            return bytes((M_STR, 0, 0, 1)) + stack
        if b == 0x7D:  # }
            return bytes((M_AFTER, 0, 0, 0)) + stack[:-1]
        return None
    if mode == M_KEY:
        if b in _WS:
            return state
        if b == 0x22:
            return bytes((M_STR, 0, 0, 1)) + stack
        return None
    if mode == M_COLON:
        if b in _WS:
            return state
        if b == 0x3A:  # :
            return bytes((M_VALUE, 0, 0, 0)) + stack
        return None
    if mode == M_STR:
        if b == 0x22:  # closing quote
            if key:
                return bytes((M_COLON, 0, 0, 0)) + stack
            return bytes((M_AFTER, 0, 0, 0)) + stack
        if b == 0x5C:  # backslash
            return bytes((M_ESC, 0, 0, key)) + stack
        if b < 0x20:   # raw control bytes are invalid in JSON strings
            return None
        return state
    if mode == M_ESC:
        if b in _ESCAPES:
            return bytes((M_STR, 0, 0, key)) + stack
        if b == 0x75:  # u
            return bytes((M_HEX, 4, 0, key)) + stack
        return None
    if mode == M_HEX:
        if b in _HEXD:
            if aux1 == 1:
                return bytes((M_STR, 0, 0, key)) + stack
            return bytes((M_HEX, aux1 - 1, 0, key)) + stack
        return None
    if mode == M_NUM:
        ns = aux1
        if 0x30 <= b <= 0x39:  # digit
            nxt = {NS_MINUS: NS_ZERO if b == 0x30 else NS_INT,
                   NS_INT: NS_INT, NS_DOT: NS_FRAC, NS_FRAC: NS_FRAC,
                   NS_E: NS_EXP, NS_ESIGN: NS_EXP, NS_EXP: NS_EXP}.get(ns)
            if ns == NS_ZERO:  # leading zero: no more int digits
                nxt = None
            if nxt is None:
                return None
            return bytes((M_NUM, nxt, 0, 0)) + stack
        if b == 0x2E and ns in (NS_ZERO, NS_INT):  # .
            return bytes((M_NUM, NS_DOT, 0, 0)) + stack
        if b in (0x65, 0x45) and ns in (NS_ZERO, NS_INT, NS_FRAC):  # e E
            return bytes((M_NUM, NS_E, 0, 0)) + stack
        if b in (0x2B, 0x2D) and ns == NS_E:  # + -
            return bytes((M_NUM, NS_ESIGN, 0, 0)) + stack
        if ns in _NS_TERMINAL:  # delimiter terminates the number
            return _after_value(b, stack)
        return None
    if mode == M_LIT:
        lit = _LITERALS[aux1]
        if aux2 < len(lit) and b == lit[aux2]:
            if aux2 + 1 == len(lit):
                return bytes((M_AFTER, 0, 0, 0)) + stack
            return bytes((M_LIT, aux1, aux2 + 1, 0)) + stack
        return None
    if mode == M_AFTER:
        return _after_value(b, stack)
    return None


def advance_bytes(state: bytes, data: bytes) -> Optional[bytes]:
    for b in data:
        state = advance_byte(state, b)
        if state is None:
            return None
    return state


def eos_ok(state: bytes) -> bool:
    """EOS is legal iff a complete JSON value sits at depth 0."""
    if len(state) > 4:  # open containers
        return False
    mode, aux1 = state[0], state[1]
    return mode == M_AFTER or (mode == M_NUM and aux1 in _NS_TERMINAL)


# --- native kernel -----------------------------------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "grammar.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libtpuop_grammar.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load_native():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                   "-o", _LIB, _SRC]
            try:
                # read-only filesystems (hardened pods) must fall back to
                # the pure-Python mask path, not 500
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
            except (subprocess.SubprocessError, FileNotFoundError, OSError):
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        try:
            lib.json_fill_mask.argtypes = [
                u8p, ctypes.c_int32, u8p, i64p, ctypes.c_int32, u32p]
            lib.json_fill_mask.restype = None
        except AttributeError:
            # a stale prebuilt .so (restored build cache) may predate a
            # symbol; the contract is fall-back-to-Python, never raise
            return None
        try:
            # schema skeleton-machine fill (ops/schema.py) lives in the
            # same library; rc 0 = filled, -1 = cap → python fallback
            lib.schema_fill_mask.argtypes = [
                i64p, ctypes.c_int32, i64p, u8p,
                u8p, ctypes.c_int64, u8p, i64p, ctypes.c_int32, u32p]
            lib.schema_fill_mask.restype = ctypes.c_int32
        except AttributeError:
            # .so predates the schema machine: keep the (working) generic
            # json path native, schema fills fall back to Python
            lib.schema_fill_mask = None
        _lib = lib
        return _lib


# --- token table + constraint ------------------------------------------------

class TokenTable:
    """Per-tokenizer token byte table + shared mask cache.

    Tokens with empty byte content (control/unknown pieces) are never
    grammar-legal; EOG ids are OR-ed in by ``mask_for`` when the state
    accepts end-of-output.
    """

    def __init__(self, pieces: Sequence[bytes], eog_ids: Iterable[int]):
        self.pieces: List[bytes] = [bytes(p) for p in pieces]
        self.n_vocab = len(self.pieces)
        self.n_words = (self.n_vocab + 31) // 32
        self.eog_ids = [i for i in eog_ids if 0 <= i < self.n_vocab]
        self.max_len = max((len(p) for p in self.pieces), default=1)
        # concatenated layout for the native kernel
        self._flat = np.frombuffer(
            b"".join(self.pieces) or b"\0", np.uint8).copy()
        off = np.zeros(self.n_vocab + 1, np.int64)
        np.cumsum([len(p) for p in self.pieces], out=off[1:])
        self._off = off
        self._eog_packed = np.zeros(self.n_words, np.uint32)
        for i in self.eog_ids:
            self._eog_packed[i >> 5] |= np.uint32(1 << (i & 31))
        # LRU-bounded: abstract states are minted per nesting pattern, so
        # an adversarial '[{[{[…' stream would otherwise grow this (and
        # pay a fresh vocab-wide fill) without limit
        self._cache: OrderedDict = OrderedDict()
        self._cache_cap = 4096
        self._cache_lock = threading.Lock()
        # prime on the constructing (HTTP) thread: builds the native
        # kernel (a g++ shell-out on first use) and the initial-state
        # mask so the scheduler loop never stalls on either
        _load_native()
        self.mask_for(INITIAL_STATE)

    _build_lock = threading.Lock()

    @classmethod
    def for_tokenizer(cls, tok) -> "TokenTable":
        """Build (and cache on the tokenizer) the table for a Tokenizer.
        Locked: concurrent cold format:"json" requests must not each pay
        the table build + native-kernel compile + initial mask fill."""
        with cls._build_lock:
            tbl = getattr(tok, "_constrain_table", None)
            if tbl is None:
                tbl = cls([tok.piece_bytes(i) for i in range(tok.n_vocab)],
                          tok.eog_ids)
                tok._constrain_table = tbl
        return tbl

    def _cache_key(self, state: bytes) -> bytes:
        """Abstract state: header + the stack suffix a single token could
        touch. A token of L bytes pops at most L containers, so a suffix of
        ``max_len`` container bytes (plus emptiness, which the suffix
        preserves) fully determines every token's acceptance."""
        return state[:4] + state[4:][-self.max_len:]

    def mask_for(self, state: bytes) -> np.ndarray:
        """Packed allowed-token mask [n_words] uint32 for ``state``."""
        key = self._cache_key(state)
        with self._cache_lock:
            m = self._cache.get(key)
            if m is not None:
                self._cache.move_to_end(key)
                return m
        mask = np.zeros(self.n_words, np.uint32)
        lib = _load_native()
        if lib is not None:
            st = np.frombuffer(key, np.uint8).copy()
            lib.json_fill_mask(st, np.int32(len(key)), self._flat,
                               self._off, np.int32(self.n_vocab), mask)
        else:
            for tid, piece in enumerate(self.pieces):
                if piece and advance_bytes(state, piece) is not None:
                    mask[tid >> 5] |= np.uint32(1 << (tid & 31))
        if eos_ok(state):
            if state[0] == M_AFTER:
                # value definitely closed: only whitespace could follow —
                # force EOS so the model stops instead of trailing forever
                mask = self._eog_packed.copy()
            else:
                # e.g. a top-level number: legal to extend OR to stop
                mask = mask | self._eog_packed
        with self._cache_lock:
            self._cache[key] = mask
            self._cache.move_to_end(key)
            while len(self._cache) > self._cache_cap:
                self._cache.popitem(last=False)
        return mask


class GrammarTable:
    """Device-side grammar tables: BFS over the PDA's reachable packed
    states (up to ``cap``) → a dense mask table + token transition table
    the decode program can index with a per-slot int32 state.

    ``mask [G, n_words] uint32`` — row g is ``mask_for`` of state g;
    ``trans [G, V] int32`` — next state id, or -1 when sampling that
    token leaves the table (state beyond ``cap``, an EOG token, or a
    grammar-illegal token the mask already excludes). The engine treats
    -1 as an ESCAPE: the slot freezes for the rest of the dispatch and
    the scheduler falls back to host-uploaded masks for it
    (runtime/scheduler.py ``grammar_ack``). EOG escapes are harmless —
    the request finishes on that token anyway.

    State 0 is the BFS root (``start``). The tables are built once per
    (TokenTable, start, cap) and cached on the TokenTable; the build
    simulates only mask-allowed tokens, so it costs G native mask fills
    plus the allowed-token byte walks. JSON decode typically closes over
    a handful of abstract states, so a small ``cap`` (default 64 via
    TPU_GRAMMAR_STATES) covers common nesting depths and everything
    deeper degrades to the host path, never to wrong output."""

    def __init__(self, table: TokenTable, start: bytes = INITIAL_STATE,
                 cap: int = 64):
        self.table = table
        self.cap = cap
        V, n_words = table.n_vocab, table.n_words
        states: List[bytes] = [start]
        ids = {start: 0}
        mask_rows: List[np.ndarray] = []
        trans_rows: List[np.ndarray] = []
        eog = set(table.eog_ids)
        i = 0
        while i < len(states):
            st = states[i]
            i += 1
            mrow = table.mask_for(st)
            mask_rows.append(mrow)
            trow = np.full(V, -1, np.int32)
            allowed = np.nonzero(
                (mrow[:, None] >> np.arange(32, dtype=np.uint32)) & 1)
            for tid in (allowed[0] * 32 + allowed[1]):
                tid = int(tid)
                if tid >= V or tid in eog:
                    continue
                piece = table.pieces[tid]
                ns = advance_bytes(st, piece) if piece else None
                if ns is None:
                    continue
                nid = ids.get(ns)
                if nid is None:
                    if len(states) >= cap:
                        continue           # beyond cap → escape (-1)
                    nid = len(states)
                    ids[ns] = nid
                    states.append(ns)
                trow[tid] = nid
            trans_rows.append(trow)
        self.states = states
        self._ids = ids
        self.n_states = len(states)
        self.mask = np.stack(mask_rows)                    # [G, n_words]
        self.trans = np.stack(trans_rows)                  # [G, V]

    @classmethod
    def for_table(cls, table: TokenTable, start: bytes = INITIAL_STATE,
                  cap: int = 64) -> "GrammarTable":
        key = (bytes(start), int(cap))
        cache = getattr(table, "_grammar_tables", None)
        if cache is None:
            cache = table._grammar_tables = {}
        gt = cache.get(key)
        if gt is None:
            gt = cache[key] = cls(table, start, cap)
        return gt

    def state_id(self, state: Optional[bytes]) -> int:
        """Table id for an exact packed state, or -1 if it escaped.
        States from a different machine (e.g. a schema NFA tuple) never
        match — they stay on host masks."""
        if state is None:
            return -1
        try:
            return self._ids.get(bytes(state), -1)
        except (TypeError, ValueError):
            return -1


class JsonConstraint:
    """Per-request JSON grammar state for the engine/scheduler."""

    # packed-bytes PDA state: GrammarTable rows ARE this state space, so
    # the scheduler may run the constraint from device tables. Schema
    # constraints (NFA tuple states, per-schema masks) must stay on host
    # masks — their masks are strictly tighter than the JSON grammar's.
    grammar_table_ok = True

    def __init__(self, table: TokenTable):
        self.table = table
        self.state: Optional[bytes] = INITIAL_STATE

    @classmethod
    def for_tokenizer(cls, tok) -> "JsonConstraint":
        return cls(TokenTable.for_tokenizer(tok))

    def mask_row(self) -> np.ndarray:
        assert self.state is not None, "constraint already dead"
        return self.table.mask_for(self.state)

    def advance(self, tid: int) -> bool:
        """Feed one sampled token; False if it was grammar-illegal (which
        a masked sampler should never produce)."""
        if self.state is None:
            return False
        piece = (self.table.pieces[tid]
                 if 0 <= tid < self.table.n_vocab else b"")
        if not piece:
            return False
        nxt = advance_bytes(self.state, piece)
        self.state = nxt
        return nxt is not None

    @property
    def done(self) -> bool:
        return self.state is not None and eos_ok(self.state)
