"""Normalisation ops (fp32 accumulation, bf16 in/out — XLA fuses these into
the surrounding matmuls, so no Pallas kernel is needed here)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x, weight, eps: float, weight_offset: float = 0.0):
    """RMSNorm with fp32 accumulation.

    ``weight_offset=1.0`` implements gemma's convention of storing the scale
    as (w - 1).
    """
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps)
    y = y * (weight_offset + weight.astype(jnp.float32))
    return y.astype(x.dtype)


def layer_norm(x, weight, bias, eps: float):
    """LayerNorm; ``bias=None`` = bias-free variant (command-r stores no
    LN biases)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)
