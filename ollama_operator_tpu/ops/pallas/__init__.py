"""TPU Pallas kernels for the hot attention ops.

The semantics-defining implementations live in ``ops/attention.py`` (pure
JAX); these kernels must match them bit-approximately and are selected by
``ops.attention.chunk_attention`` / ``cached_attention`` based on
``ModelConfig.kernels`` ("auto" → pallas on TPU backends, XLA elsewhere;
"interpret" runs the same kernels through the pallas interpreter so CPU
tests exercise the kernel code paths).

The reference delegates these ops to llama.cpp's C++/CUDA kernels inside
the `ollama/ollama` image (/root/reference/pkg/model/pod.go:11); here they
are Mosaic/Pallas programs tiled for the MXU with fp32 online-softmax
accumulation.
"""

from .flash import (decode_attention, decode_tileable,  # noqa: F401
                    flash_prefill, mha_decode_attention,
                    mha_decode_tileable, prefill_tileable)
