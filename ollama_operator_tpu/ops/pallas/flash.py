"""Flash-attention Pallas kernels: causal GQA prefill + cached decode.

Both kernels keep the classic flash structure — stream K/V blocks through
VMEM, fp32 online softmax (running max ``m``, normaliser ``l``, accumulator
``acc`` in VMEM scratch that persists across the innermost grid dimension) —
with two TPU-specific tricks:

- **Causal / length DMA elision.** The K/V block index map clamps the block
  index to the last block the current query can see; Pallas elides the DMA
  when consecutive grid steps map to the same block, so fully-masked tail
  blocks cost neither bandwidth nor compute (the ``@pl.when`` guard skips
  the math).
- **Scalar-prefetched lengths (decode).** Slot lengths ride in SMEM via
  ``PrefetchScalarGridSpec`` so the clamp above can depend on the per-slot
  length — a slot at position 100 in a 4096-slot cache reads 1 block, not 16.

Layout: K/V are **head-first** ([B, KvH, S, hd] — the KV-cache layout the
whole serving stack uses) so every block is a (seq, head_dim) tile, the
natural (sublane, lane) orientation for the MXU. GQA never repeats K/V:
prefill points each query head's K/V spec at ``head // group``; decode lays
q out as [B, KvH, G, hd].

The reference delegates these ops to llama.cpp's C++/CUDA kernels inside
the `ollama/ollama` image (/root/reference/pkg/model/pod.go:11).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the newer pallas API renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from ..attention import NEG_INF, softcap_scores

_BLOCKS = (512, 256, 128, 64, 32, 16, 8)


def _pick_block(n: int, cap: int) -> Optional[int]:
    for b in _BLOCKS:
        if b <= cap and n % b == 0:
            return b
    return None


def _lane_ok(hd: int, interpret: bool) -> bool:
    # Mosaic pads the trailing (lane) dim to 128 internally, so any
    # 16-multiple head dim compiles and runs correctly on TPU (verified on
    # v5e for 64/80/96 — phi's hd=80 included); the padding costs some
    # VMEM but the length-clamped DMA elision is a far bigger win than the
    # XLA path's full-cache reads. Truly odd dims still fall back.
    return interpret or hd % 16 == 0


def prefill_tileable(T: int, H: int, KvH: int, hd: int, interpret: bool,
                     block_q: int = 256, block_k: int = 512) -> bool:
    """True iff flash_prefill will NOT bail for these (possibly
    device-local) shapes — checked BEFORE entering a shard_map region,
    where a mid-trace None-fallback is no longer possible."""
    return (KvH > 0 and H % KvH == 0 and _lane_ok(hd, interpret)
            and _pick_block(T, block_q) is not None
            and _pick_block(T, block_k) is not None)


def decode_tileable(S: int, H: int, KvH: int, hd: int, interpret: bool,
                    block_k: int = 512) -> bool:
    """True iff decode_attention will NOT bail (see prefill_tileable)."""
    return (KvH > 0 and H % KvH == 0 and _lane_ok(hd, interpret)
            and _pick_block(S, block_k) is not None)


# ---------------------------------------------------------------------------
# prefill: causal self-attention over a fresh chunk (positions [0, T))
# ---------------------------------------------------------------------------

def _prefill_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                    scale: float, softcap: float, window: int,
                    bq: int, bk: int, nk: int):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    k_start = ki * bk
    needed = k_start <= (qi + 1) * bq - 1  # block overlaps the causal tri
    if window:
        # any (q, k) pair in range: k_end > min_q_pos - window
        needed = jnp.logical_and(needed, k_start + bk - 1 > qi * bq - window)

    @pl.when(needed)
    def _step():
        q = q_ref[0, 0, :, :]                                # [bq, hd]
        k = k_ref[0, 0, :, :]                                # [bk, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bk]
        s = softcap_scores(s, softcap)
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        ok = k_pos <= q_pos
        if window:
            ok = jnp.logical_and(ok, k_pos > q_pos - window)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[:]                                     # [bq, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # rows with no valid key yet keep m == NEG_INF; exp would turn the
        # masked NEG_INF scores into 1s, so gate p on a live running max.
        p = jnp.where(m_cur > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0, :, :]                                 # [bk, hd]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_cur

    @pl.when(ki == nk - 1)
    def _done():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


def flash_prefill(q, k, v, scale: float, softcap: float = 0.0,
                  sliding_window: int = 0, *, block_q: int = 256,
                  block_k: int = 512, interpret: bool = False):
    """Causal GQA self-attention for a fresh chunk.

    q [B, T, H, hd], k/v head-first [B, KvH, T, hd] → [B, T, H, hd]
    (q.dtype). Query i attends keys j <= i (positions are chunk-local,
    offset 0), optionally within ``sliding_window``. Returns None when the
    shapes don't tile (caller falls back to the XLA path).
    """
    B, T, H, hd = q.shape
    KvH = k.shape[1]
    if not prefill_tileable(T, H, KvH, hd, interpret, block_q, block_k):
        return None
    bq = _pick_block(T, block_q)
    bk = _pick_block(T, block_k)
    G = H // KvH
    nq, nk = T // bq, T // bk
    q_hf = q.transpose(0, 2, 1, 3)                            # [B, H, T, hd]

    def kv_index(b, h, qi, ki):
        # clamp to the last causally-needed block → tail DMAs are elided
        last = ((qi + 1) * bq - 1) // bk
        return (b, h // G, jnp.minimum(ki, last), 0)

    kernel = functools.partial(
        _prefill_kernel, scale=scale, softcap=softcap,
        window=sliding_window, bq=bq, bk=bk, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, bk, hd), kv_index),
            pl.BlockSpec((1, 1, bk, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd),
                               lambda b, h, qi, ki: (b, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, hd), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, hd), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q_hf, k, v)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# decode: one new query per slot against the slot's KV cache rows
# ---------------------------------------------------------------------------

def _decode_kernel(qpos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *,
                   scale: float, softcap: float, window: int,
                   bk: int, nk: int):
    b, ki = pl.program_id(0), pl.program_id(2)
    qp = qpos_ref[b]                       # query's absolute position

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    k_start = ki * bk
    needed = k_start <= qp                 # keys j <= qp are visible
    if window:
        needed = jnp.logical_and(needed, k_start + bk - 1 > qp - window)

    @pl.when(needed)
    def _step():
        q = q_ref[0, 0, :, :]                                # [Gp, hd]
        kb = k_ref[0, 0, :, :]                                # [bk, hd]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [Gp, bk]
        s = softcap_scores(s, softcap)
        Gp = s.shape[0]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (Gp, bk), 1)
        ok = k_pos <= qp
        if window:
            ok = jnp.logical_and(ok, k_pos > qp - window)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(m_cur > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        vb = v_ref[0, 0, :, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_cur

    @pl.when(ki == nk - 1)
    def _done():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, q_pos, scale: float,
                     softcap: float = 0.0, sliding_window: int = 0, *,
                     block_k: int = 512, interpret: bool = False):
    """Single-token GQA attention against the head-first slot KV cache.

    q [B, 1, H, hd]; k_cache/v_cache [B, KvH, S, hd]; q_pos [B] int32 —
    the query's absolute position (keys at j <= q_pos are attended; blocks
    beyond are neither read nor computed). → [B, 1, H, hd] (q.dtype).
    Returns None when the shapes don't tile.
    """
    B, T, H, hd = q.shape
    KvH, S = k_cache.shape[1], k_cache.shape[2]
    if T != 1 or not decode_tileable(S, H, KvH, hd, interpret, block_k):
        return None
    bk = _pick_block(S, block_k)
    G = H // KvH
    Gp = max(8, -(-G // 8) * 8)            # pad group to a sublane multiple
    nk = S // bk

    qg = q.reshape(B, KvH, G, hd)
    if Gp != G:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Gp - G), (0, 0)))

    def kv_index(b, h, ki, qpos_ref):
        last = qpos_ref[b] // bk           # last visible block for this slot
        return (b, h, jnp.minimum(ki, last), 0)

    kernel = functools.partial(
        _decode_kernel, scale=scale, softcap=softcap,
        window=sliding_window, bk=bk, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, KvH, nk),
            in_specs=[
                pl.BlockSpec((1, 1, Gp, hd),
                             lambda b, h, ki, qpos_ref: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, bk, hd), kv_index),
                pl.BlockSpec((1, 1, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, 1, Gp, hd),
                                   lambda b, h, ki, qpos_ref: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((Gp, hd), jnp.float32),
                pltpu.VMEM((Gp, 1), jnp.float32),
                pltpu.VMEM((Gp, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KvH, Gp, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_pos.astype(jnp.int32), qg, k_cache, v_cache)
    return out[:, :, :G, :].reshape(B, 1, H, hd)


# ---------------------------------------------------------------------------
# MHA decode: head-tiled grid (no GQA grouping axis to tile on)
# ---------------------------------------------------------------------------

def _mha_decode_kernel(qpos_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, *,
                       scale: float, softcap: float, window: int,
                       bk: int, nk: int):
    """Grid (B, H//Ht, nk): each program advances Ht whole heads one key
    block. MHA has G == 1, so the GQA kernel's (B, KvH, nk) grid degrades
    to B×H tiny programs whose matmul rows are 7/8 padding; tiling HEADS
    instead makes each DMA Ht pages wide and the per-head dot an
    elementwise-mul + lane reduction (VPU) — decode is bandwidth-bound,
    the MXU was idle either way (round-2 VERDICT weak #3)."""
    b, ki = pl.program_id(0), pl.program_id(2)
    qp = qpos_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    k_start = ki * bk
    needed = k_start <= qp
    if window:
        needed = jnp.logical_and(needed, k_start + bk - 1 > qp - window)

    @pl.when(needed)
    def _step():
        q = q_ref[0].astype(jnp.float32)               # [Ht, hd]
        kb = k_ref[0].astype(jnp.float32)              # [Ht, bk, hd]
        s = jnp.sum(q[:, None, :] * kb, axis=-1) * scale   # [Ht, bk]
        s = softcap_scores(s, softcap)
        Ht = s.shape[0]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (Ht, bk), 1)
        ok = k_pos <= qp
        if window:
            ok = jnp.logical_and(ok, k_pos > qp - window)
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(m_cur > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        vb = v_ref[0].astype(jnp.float32)              # [Ht, bk, hd]
        acc_ref[:] = acc_ref[:] * alpha + jnp.sum(
            p[:, :, None] * vb, axis=1)
        m_ref[:] = m_cur

    @pl.when(ki == nk - 1)
    def _done():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


def mha_decode_tileable(S: int, H: int, KvH: int, hd: int, interpret: bool,
                        block_k: int = 512, head_tile: int = 8) -> bool:
    """True iff mha_decode_attention will NOT bail for these shapes."""
    return (KvH == H and H % head_tile == 0 and _lane_ok(hd, interpret)
            and _pick_block(S, block_k) is not None)


def mha_decode_attention(q, k_cache, v_cache, q_pos, scale: float,
                         softcap: float = 0.0, sliding_window: int = 0, *,
                         block_k: int = 512, head_tile: int = 8,
                         interpret: bool = False):
    """Single-token MHA attention against the head-first slot KV cache.

    q [B, 1, H, hd]; k_cache/v_cache [B, H, S, hd] (KvH == H); q_pos [B].
    Grid (B, H//head_tile, nk) — see _mha_decode_kernel. Returns
    [B, 1, H, hd] (q.dtype) or None when the shapes don't tile.
    """
    B, T, H, hd = q.shape
    KvH, S = k_cache.shape[1], k_cache.shape[2]
    if T != 1 or not mha_decode_tileable(S, H, KvH, hd, interpret,
                                         block_k, head_tile):
        return None
    bk = _pick_block(S, block_k)
    Ht = head_tile
    nk = S // bk
    q2 = q.reshape(B, H, hd)

    def kv_index(b, hi, ki, qpos_ref):
        last = qpos_ref[b] // bk
        return (b, hi, jnp.minimum(ki, last), 0)

    kernel = functools.partial(
        _mha_decode_kernel, scale=scale, softcap=softcap,
        window=sliding_window, bk=bk, nk=nk)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H // Ht, nk),
            in_specs=[
                pl.BlockSpec((1, Ht, hd),
                             lambda b, hi, ki, qpos_ref: (b, hi, 0)),
                pl.BlockSpec((1, Ht, bk, hd), kv_index),
                pl.BlockSpec((1, Ht, bk, hd), kv_index),
            ],
            out_specs=pl.BlockSpec((1, Ht, hd),
                                   lambda b, hi, ki, qpos_ref: (b, hi, 0)),
            scratch_shapes=[
                pltpu.VMEM((Ht, hd), jnp.float32),
                pltpu.VMEM((Ht, 1), jnp.float32),
                pltpu.VMEM((Ht, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q_pos.astype(jnp.int32), q2, k_cache, v_cache)
    return out.reshape(B, 1, H, hd)
