"""Paged-attention decode kernel (block-table KV cache).

The serving cache is a physical page pool ``[L, P, KvH, ps, hd]`` shared by
all slots; a slot's logical positions ``[0, len)`` live in the pages listed
by its block-table row (``runtime/paged.py`` owns allocation). This kernel
is the decode step against that pool:

- **Block-table indirection via scalar prefetch.** Tables and per-slot
  lengths ride in SMEM (``PrefetchScalarGridSpec``), so the K/V index map
  dereferences ``table[b, block]`` at grid time — pages are DMA'd straight
  out of the pool with no gather copy.
- **Head-blocked grid (B, nblk).** Each step reads a page ACROSS all its
  KV heads (one [KvH, ps, hd] DMA) and runs the per-head flash updates
  unrolled inside the kernel. The first on-chip capture ran the old
  (B, KvH, nblk) grid and measured phi (MHA, KvH=32) at 233 ms/step —
  16384 tiny 8 KB steps/layer, 2.1% of HBM bandwidth; folding heads into
  the block cuts the grid by KvH and makes every DMA page-contiguous.
- **Per-slot DMA elision.** The block index is clamped to the slot's last
  live block; Pallas elides the repeated DMA and ``@pl.when`` skips the
  math — a 100-token slot in a 4096-token-bucket batch reads 1-2 pages,
  not the bucket.
- **Lane-wise int8 dequant.** For the quantized pool the per-position
  scales multiply the score matrix (``s * k_scale[None, :]``) and the
  probability matrix (``p * v_scale[None, :]``) — both lane-aligned
  broadcasts, so dequant adds no relayout and page DMAs stay int8. int4
  pools (``{"q4": ..}``, two positions per byte along the page axis —
  ops/quant_cache.py) DMA at half that width again and unpack in-register
  (``_unpack4``) before the dots, same scale algebra. Scales
  ride as [L, P, KvH, 1, ps]: the unit axis keeps the block's trailing
  dims equal to their array dims (Mosaic's (8,128) rule — the 4D spec
  lowers in interpret mode but is rejected by the real TPU lowering).
- **bf16 score/probability dots.** int8 codes are exact in bf16's 8-bit
  mantissa and the MXU is bf16-native; dotting f32 (the first kernel
  generation) runs at a fraction of MXU rate. f32 activations (CPU
  tests) keep f32 dots for bit-stable parity.

The layer index is a prefetched scalar too: the kernel reads the full
``[L, ...]`` pool and the grid never materialises a per-layer slice.

The reference delegates paged/continuous batching to llama.cpp inside the
`ollama/ollama` image (/root/reference/pkg/model/pod.go:11); this is its
TPU-native equivalent (SURVEY.md §7 hard-part 2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the newer pallas API renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
# older pallas has TPUMemorySpace with no HBM member; ANY is its
# "stays in device memory, kernel DMAs slices itself" space
_MemorySpace = getattr(pltpu, "MemorySpace",
                       getattr(pltpu, "TPUMemorySpace", None))
_HBM = getattr(_MemorySpace, "HBM", _MemorySpace.ANY)

from ..attention import NEG_INF, softcap_scores
from .flash import _lane_ok


def _unpack4(kb):
    """Nibble-packed page rows [..., ps//2, hd] int8 → int4 codes [-7, 7]
    as int8 [..., ps, hd] (position 2j rides the low nibble —
    ops/quant_cache.pack_kv4). A register-level shift/mask + sublane
    interleave; the page DMA itself stays at int4 width, which is the
    whole bandwidth win."""
    b = kb.astype(jnp.uint8)
    lo = (b & 0xF).astype(jnp.int8) - 8
    hi = ((b >> 4) & 0xF).astype(jnp.int8) - 8
    st = jnp.stack([lo, hi], axis=-2)          # [..., ps//2, 2, hd]
    return st.reshape(*kb.shape[:-2], kb.shape[-2] * 2, kb.shape[-1])


def _pool_arrs(k_pool, v_pool):
    """(quant, quant4, k_arr, v_arr) for a plain / {"q","s"} / {"q4","s"}
    pool pair."""
    quant = isinstance(k_pool, dict)
    quant4 = quant and "q4" in k_pool
    k_arr = (k_pool["q4"] if quant4 else k_pool["q"]) if quant else k_pool
    v_arr = (v_pool["q4"] if quant4 else v_pool["q"]) if quant else v_pool
    return quant, quant4, k_arr, v_arr


def _paged_kernel(lay_ref, len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *,
                  scale: float, softcap: float, window: int,
                  ps: int, nblk: int, kvh: int, gp: int, cdt,
                  quant: bool, quant4: bool = False,
                  ks_ref=None, vs_ref=None):
    # NB: scale blocks span the full (possibly 128-lane-padded) scale
    # array dim; reads below slice the live [: ps] lanes
    """Grid (B, nblk). Block ki covers the slot's logical positions
    [ki*ps, (ki+1)*ps) across ALL KvH heads; the per-head flash updates
    are unrolled below (static python loop — KvH is a trace-time
    constant). With ``quant`` the k/v refs are int8 pages and ks/vs carry
    the per-position f32 scales; with ``quant4`` the pages are
    nibble-packed ([ps//2, hd] stored rows) and unpack in-register before
    the dots — ``ps`` is always the LOGICAL page size."""
    b, ki = pl.program_id(0), pl.program_id(1)
    qp = len_ref[b]                        # query's absolute position

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    k_start = ki * ps
    needed = k_start <= qp
    if window:
        needed = jnp.logical_and(needed, k_start + ps - 1 > qp - window)

    @pl.when(needed)
    def _step():
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (gp, ps), 1)
        ok = k_pos <= qp
        if window:
            ok = jnp.logical_and(ok, k_pos > qp - window)
        for h in range(kvh):               # unrolled per kv head
            r0 = h * gp
            q = q_ref[0, h, :, :].astype(cdt)                 # [Gp, hd]
            kb = k_ref[0, 0, h, :, :]                         # [ps, hd]
            if quant4:
                kb = _unpack4(kb)          # [ps//2, hd] packed → [ps, hd]
            s = jax.lax.dot_general(
                q, kb.astype(cdt), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # [Gp, ps]
            if quant:
                # per-position k scale: lane-aligned broadcast
                s = s * ks_ref[0, 0, h, 0, :ps][None, :]
            s = softcap_scores(s, softcap)
            s = jnp.where(ok, s, NEG_INF)

            m_prev = m_ref[r0:r0 + gp, :]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.where(m_cur > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
            alpha = jnp.exp(m_prev - m_cur)
            l_ref[r0:r0 + gp, :] = (l_ref[r0:r0 + gp, :] * alpha
                                    + jnp.sum(p, axis=-1, keepdims=True))
            vb = v_ref[0, 0, h, :, :]                         # [ps, hd]
            if quant4:
                vb = _unpack4(vb)
            if quant:
                # fold the per-position v scale into p (lane-aligned)
                p = p * vs_ref[0, 0, h, 0, :ps][None, :]
            acc_ref[r0:r0 + gp, :] = (
                acc_ref[r0:r0 + gp, :] * alpha + jax.lax.dot_general(
                    p.astype(cdt), vb.astype(cdt),
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32))
            m_ref[r0:r0 + gp, :] = m_cur

    @pl.when(ki == nblk - 1)
    def _done():
        out = acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, :, :] = out.astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, layer, tables, lengths,
                           scale: float, softcap: float = 0.0,
                           sliding_window: int = 0, *, nblk: int,
                           interpret: bool = False):
    """Single-token attention against the paged pool.

    q        [B, 1, H, hd]
    k_pool   [L, P, KvH, ps, hd] (bf16/f32), {"q": int8 pool,
             "s": [L, P, KvH, ps] f32 scales}, or {"q4": nibble-packed
             [L, P, KvH, ps//2, hd] int8, "s": same scale layout}
    layer    [] / [1] int32 — which L slice to attend
    tables   [B, NBLK] int32 physical page per logical block
    lengths  [B] int32 — query's absolute position per slot
    nblk     static number of grid blocks (attention bucket // ps;
             must be <= NBLK)
    → [B, 1, H, hd] (q.dtype), or None when the shapes don't tile.

    The live-page async-DMA pipeline (:func:`paged_decode_attention_v3`)
    is the DEFAULT — the round-4 same-window A/B measured it ahead of
    this grid kernel everywhere (GQA short +2%, GQA long-context +17%,
    MHA +30%; BASELINE.md round-4). ``TPU_PAGED_V3=0`` opts back into
    the v2 grid kernel below.
    """
    import os
    if os.environ.get("TPU_PAGED_V4", "0") == "1":
        # experimental compacted flat-grid formulation (A/B against v3
        # before any default change)
        out = paged_decode_attention_v4(
            q, k_pool, v_pool, layer, tables, lengths, scale, softcap,
            sliding_window, nblk=nblk, interpret=interpret)
        if out is not None:
            return out
    if os.environ.get("TPU_PAGED_V3", "1") == "1":
        out = paged_decode_attention_v3(
            q, k_pool, v_pool, layer, tables, lengths, scale, softcap,
            sliding_window, nblk=nblk, interpret=interpret)
        if out is not None:
            return out
    quant, quant4, k_arr, v_arr = _pool_arrs(k_pool, v_pool)
    B, T, H, hd_q = q.shape
    L, P, KvH, psq, hd = k_arr.shape
    ps = psq * 2 if quant4 else psq            # logical vs stored rows
    NBLK = tables.shape[1]
    if T != 1 or H % KvH or not _lane_ok(hd, interpret) or nblk > NBLK:
        return None
    if ps % 8:
        return None
    G = H // KvH
    Gp = max(8, -(-G // 8) * 8)
    cdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32

    qg = q.reshape(B, KvH, G, hd_q)
    if Gp != G or hd != hd_q:
        # group rows pad to a sublane multiple; the head dim pads to the
        # pool's 128-lane width (engine pads the POOL; zero q lanes are
        # inert in the score dot and the pad outputs are sliced off below)
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Gp - G), (0, hd - hd_q)))

    def kv_index(b, ki, lay_ref, len_ref, tbl_ref):
        last = len_ref[b] // ps
        pg = tbl_ref[b, jnp.minimum(ki, last)]
        return (lay_ref[0], pg, 0, 0, 0)

    kernel = functools.partial(
        _paged_kernel, scale=scale, softcap=softcap, window=sliding_window,
        ps=ps, nblk=nblk, kvh=KvH, gp=Gp, cdt=cdt, quant=quant,
        quant4=quant4)
    in_specs = [
        pl.BlockSpec((1, KvH, Gp, hd), lambda b, ki, *pref: (b, 0, 0, 0)),
        pl.BlockSpec((1, 1, KvH, psq, hd), kv_index),
        pl.BlockSpec((1, 1, KvH, psq, hd), kv_index),
    ]
    args = [qg, k_arr, v_arr]
    if quant:
        def kernel(*refs):  # noqa: F811 — rebind scale refs by position
            (lay_ref, len_ref, tbl_ref, q_ref, k_ref, v_ref,
             ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref) = refs
            return _paged_kernel(
                lay_ref, len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                acc_ref, m_ref, l_ref, scale=scale, softcap=softcap,
                window=sliding_window, ps=ps, nblk=nblk, kvh=KvH, gp=Gp,
                cdt=cdt, quant=True, quant4=quant4,
                ks_ref=ks_ref, vs_ref=vs_ref)
        # scale arrays may be lane-padded past ps (engine pads to the 128
        # tile for the v3 DMA path); the block stays ps wide at block
        # index 0, so only the live lanes are read
        sp = k_pool["s"].shape[-1]
        in_specs += [pl.BlockSpec((1, 1, KvH, 1, sp), kv_index),
                     pl.BlockSpec((1, 1, KvH, 1, sp), kv_index)]
        args += [k_pool["s"].reshape(L, P, KvH, 1, -1),
                 v_pool["s"].reshape(L, P, KvH, 1, -1)]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, nblk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KvH * Gp, hd),
                                   lambda b, ki, *pref: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KvH * Gp, hd), jnp.float32),
                pltpu.VMEM((KvH * Gp, 1), jnp.float32),
                pltpu.VMEM((KvH * Gp, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KvH * Gp, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.reshape(layer, (1,)).astype(jnp.int32),
      lengths.astype(jnp.int32), tables.astype(jnp.int32),
      qg, *args[1:])
    out = out.reshape(B, KvH, Gp, hd)
    return out[:, :, :G, :hd_q].reshape(B, 1, H, hd_q)


# ---------------------------------------------------------------------------
# shared pieces of the v3/v4 formulations
# ---------------------------------------------------------------------------

def _flash_page_update(qv, kb, vb, ksc, vsc, m_ref, l_ref, acc_ref, *,
                       k_start, qp, scale: float, softcap: float,
                       window: int, ps: int, kvh: int, gp: int, cdt):
    """KvH-batched online-softmax update for ONE [KvH, ps, hd] page —
    the body both the v3 per-slot walk and the v4 flat grid run per live
    page (one score dot + one p·v dot, batch dim = kv head). ``ksc``/
    ``vsc`` are the per-position dequant scale rows ([KvH, ·, ps]) or
    None for bf16/f32 pools. Mutates m/l/acc scratch in place."""
    s = jax.lax.dot_general(
        qv.astype(cdt), kb.astype(cdt), (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale      # [KvH, Gp, ps]
    if ksc is not None:
        s = s * ksc
    s = softcap_scores(s, softcap)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (kvh, gp, ps), 2)
    ok = k_pos <= qp
    if window:
        ok = jnp.logical_and(ok, k_pos > qp - window)
    s = jnp.where(ok, s, NEG_INF)

    m_prev = m_ref[...]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(m_cur > NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
    alpha = jnp.exp(m_prev - m_cur)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    if vsc is not None:
        p = p * vsc
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(cdt), vb.astype(cdt), (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_cur


def _prep_paged(q, k_pool, v_pool, tables, nblk: int, interpret: bool):
    """Shared v3/v4 wrapper preamble: shape/tiling guards and the padded
    grouped query. Returns None when the shapes don't tile (the caller
    bails to the next formulation), else
    (quant, quant4, k_arr, v_arr, dims, sp, G, Gp, cdt, qg) with
    dims = (B, H, hd_q, L, P, KvH, ps, hd); ``ps`` is the LOGICAL page
    size (nibble-packed int4 pools store ps//2 physical rows)."""
    quant, quant4, k_arr, v_arr = _pool_arrs(k_pool, v_pool)
    B, T, H, hd_q = q.shape
    L, P, KvH, ps, hd = k_arr.shape
    if quant4:
        ps *= 2
    NBLK = tables.shape[1]
    if T != 1 or H % KvH or not _lane_ok(hd, interpret) or nblk > NBLK:
        return None
    if ps % 8:
        return None
    sp = k_pool["s"].shape[-1] if quant else ps
    G = H // KvH
    Gp = max(8, -(-G // 8) * 8)
    cdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    qg = q.reshape(B, KvH, G, hd_q)
    if Gp != G or hd != hd_q:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, Gp - G), (0, hd - hd_q)))
    return (quant, quant4, k_arr, v_arr, (B, H, hd_q, L, P, KvH, ps, hd),
            sp, G, Gp, cdt, qg)


# ---------------------------------------------------------------------------
# v4: compacted flat-grid (grid over the slot-sorted list of LIVE pages)
# ---------------------------------------------------------------------------

def _paged_kernel_v4(nb_ref, slot_ref, page_ref, blk_ref, lay_ref, len_ref,
                     q_ref, k_ref, v_ref, *rest,
                     scale: float, softcap: float, window: int,
                     ps: int, flat_n: int, kvh: int, gp: int, cdt,
                     quant: bool, quant4: bool = False):
    """Grid (flat_n,): step n processes LIVE page n of the slot-sorted
    flat list (slot_ref/page_ref/blk_ref scalars; nb_ref[0] = live total).

    The design swaps v3's per-slot fori_loop (whose per-page flash update
    serializes behind each DMA wait — the measured B=32 floor) for v2's
    implicit cross-step pipeline, but with ZERO dead interior steps: the
    flat list contains only live pages, consecutive steps of one slot
    revisit the same q/out block (no re-DMA), and dead tail steps beyond
    nb_ref[0] freeze the index maps so their DMAs elide. Dots are
    KvH-batched like v3 (one score + one pv dot_general per page, batch
    dim = kv head), not v2's per-head unrolled chain.

    Accumulators live in scratch [KvH, Gp, hd]; a slot boundary
    (slot_ref[n] != slot_ref[n-1]) resets them, and the slot's LAST live
    page (slot changes at n+1, or n is the live total − 1) normalizes
    and stores the output block."""
    if quant:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
        ks_ref = vs_ref = None
    n = pl.program_id(0)
    n_total = nb_ref[0]
    slot = slot_ref[n]
    qp = len_ref[slot]
    valid = n < n_total

    first = jnp.logical_or(n == 0, slot_ref[jnp.maximum(n - 1, 0)] != slot)

    @pl.when(jnp.logical_and(valid, first))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(valid)
    def _step():
        kb, vb = k_ref[0, 0], v_ref[0, 0]
        if quant4:
            kb, vb = _unpack4(kb), _unpack4(vb)
        _flash_page_update(
            q_ref[0], kb, vb,
            ks_ref[0, 0][:, :, :ps] if quant else None,
            vs_ref[0, 0][:, :, :ps] if quant else None,
            m_ref, l_ref, acc_ref,
            k_start=blk_ref[n] * ps, qp=qp, scale=scale, softcap=softcap,
            window=window, ps=ps, kvh=kvh, gp=gp, cdt=cdt)

        last = jnp.logical_or(
            n + 1 >= n_total,
            slot_ref[jnp.minimum(n + 1, flat_n - 1)] != slot)

        @pl.when(last)
        def _done():
            out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
            o_ref[0] = out.astype(o_ref.dtype)


def paged_decode_attention_v4(q, k_pool, v_pool, layer, tables, lengths,
                              scale: float, softcap: float = 0.0,
                              sliding_window: int = 0, *, nblk: int,
                              interpret: bool = False):
    """Same contract as :func:`paged_decode_attention`; the compacted
    flat-grid formulation. The flat (slot, page, block) list is built in
    XLA from the live lengths (cumsum + searchsorted) and handed to the
    kernel as prefetched scalars; the static grid is the worst case
    B·nblk, with every step past the live total frozen to the last live
    index so its DMAs elide at the revisit check."""
    prep = _prep_paged(q, k_pool, v_pool, tables, nblk, interpret)
    if prep is None:
        return None
    quant, quant4, k_arr, v_arr, dims, sp, G, Gp, cdt, qg = prep
    B, H, hd_q, L, P, KvH, ps, hd = dims
    psq = ps // 2 if quant4 else ps            # stored page rows
    flat_n = B * nblk

    lengths = lengths.astype(jnp.int32)
    tables = tables.astype(jnp.int32)
    nlive = jnp.minimum(lengths // ps + 1, nblk)           # [B]
    ends = jnp.cumsum(nlive)                               # [B]
    starts = ends - nlive
    n_total = ends[-1]
    idx = jnp.arange(flat_n, dtype=jnp.int32)
    slot = jnp.minimum(jnp.searchsorted(ends, idx, side="right"),
                       B - 1).astype(jnp.int32)            # [flat_n]
    blk = jnp.clip(idx - starts[slot], 0, nblk - 1)
    page = tables[slot, blk]
    # freeze dead tail steps to the LAST live index so their q/kv/out
    # block indices repeat and pallas elides the copies
    live = idx < n_total
    last_blk = jnp.clip(nlive[B - 1] - 1, 0, nblk - 1)
    page = jnp.where(live, page, tables[B - 1, last_blk])
    blk = jnp.where(live, blk, last_blk)

    def q_index(n, nb, slot_r, page_r, blk_r, lay_r, len_r):
        return (slot_r[n], 0, 0, 0)

    def kv_index(n, nb, slot_r, page_r, blk_r, lay_r, len_r):
        return (lay_r[0], page_r[n], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, KvH, Gp, hd), q_index),
        pl.BlockSpec((1, 1, KvH, psq, hd), kv_index),
        pl.BlockSpec((1, 1, KvH, psq, hd), kv_index),
    ]
    args = [qg, k_arr, v_arr]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, KvH, 1, sp), kv_index),
                     pl.BlockSpec((1, 1, KvH, 1, sp), kv_index)]
        args += [k_pool["s"].reshape(L, P, KvH, 1, -1),
                 v_pool["s"].reshape(L, P, KvH, 1, -1)]

    kernel = functools.partial(
        _paged_kernel_v4, scale=scale, softcap=softcap,
        window=sliding_window, ps=ps, flat_n=flat_n, kvh=KvH, gp=Gp,
        cdt=cdt, quant=quant, quant4=quant4)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(flat_n,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KvH, Gp, hd), q_index),
            scratch_shapes=[
                pltpu.VMEM((KvH, Gp, hd), jnp.float32),
                pltpu.VMEM((KvH, Gp, 1), jnp.float32),
                pltpu.VMEM((KvH, Gp, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KvH, Gp, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(jnp.reshape(n_total, (1,)).astype(jnp.int32), slot, page, blk,
      jnp.reshape(layer, (1,)).astype(jnp.int32), lengths,
      *args)
    return out[:, :, :G, :hd_q].reshape(B, 1, H, hd_q)


# ---------------------------------------------------------------------------
# v3: live-page async-DMA pipeline (grid (B,), dynamic block loop)
# ---------------------------------------------------------------------------

def _paged_kernel_v3(lay_ref, len_ref, tbl_ref, q_ref, k_hbm, v_hbm, *rest,
                     scale: float, softcap: float, window: int,
                     ps: int, sp: int, kvh: int, gp: int, hd: int, cdt,
                     quant: bool, quant4: bool = False, depth: int = 2):
    """One grid step per SLOT; the kernel walks only the slot's LIVE pages
    with a depth-2 manually-pipelined DMA (pltpu.make_async_copy), so

    - dead grid steps vanish: the v2 grid runs ``nblk`` (= the attention
      bucket) steps per slot and relies on clamped-DMA elision, paying a
      grid-step of overhead per dead block — a mixed-length B=32 batch at
      bucket 1024 is ~80% dead steps;
    - the per-page HBM reads overlap the flash update of the previous
      page (double buffer), instead of riding the grid's implicit
      pipeline across (mostly dead) steps;
    - the per-head python-unrolled flash updates collapse into KvH-batched
      ``dot_general``s (batch dim = kv head): one MXU dispatch per page
      for scores and one for p·v, instead of 2·KvH tiny dispatches (the
      r3 MHA diagnosis: 32 unrolled per-head dots × live blocks × layers
      dominate the step).

    Refs (in order): prefetched lay/len/tbl scalars; q [1, KvH, Gp, hd]
    VMEM block; k/v pools ([L, P, KvH, ps, hd], HBM — DMA'd manually);
    with ``quant`` the k/v scale pools ([L, P, KvH, ps] f32, HBM); the
    output block; then scratch: kbuf/vbuf [2, KvH, ps, hd], (ksbuf/vsbuf
    [2, KvH, ps],) acc [KvH, Gp, hd] f32, m/l [KvH, Gp, 1] f32, sem.
    """
    if quant:
        (ks_hbm, vs_hbm, o_ref, kbuf, vbuf, ksbuf, vsbuf,
         acc_ref, m_ref, l_ref, sem) = rest
    else:
        o_ref, kbuf, vbuf, acc_ref, m_ref, l_ref, sem = rest
        ks_hbm = vs_hbm = ksbuf = vsbuf = None
    b = pl.program_id(0)
    lay = lay_ref[0]
    qp = len_ref[b]                          # query's absolute position
    nlive = qp // ps + 1                     # pages covering [0, qp]
    start = jnp.int32(0)
    if window:
        # first block holding a key inside the window (older positions in
        # that block are masked off below)
        start = jnp.maximum(start, (qp - window + 1) // ps)

    def start_dma(i, slot):
        pg = tbl_ref[b, i]
        pltpu.make_async_copy(k_hbm.at[lay, pg], kbuf.at[slot],
                              sem.at[0, slot]).start()
        pltpu.make_async_copy(v_hbm.at[lay, pg], vbuf.at[slot],
                              sem.at[1, slot]).start()
        if quant:
            pltpu.make_async_copy(ks_hbm.at[lay, pg], ksbuf.at[slot],
                                  sem.at[2, slot]).start()
            pltpu.make_async_copy(vs_hbm.at[lay, pg], vsbuf.at[slot],
                                  sem.at[3, slot]).start()

    def wait_dma(i, slot):
        pg = tbl_ref[b, i]
        pltpu.make_async_copy(k_hbm.at[lay, pg], kbuf.at[slot],
                              sem.at[0, slot]).wait()
        pltpu.make_async_copy(v_hbm.at[lay, pg], vbuf.at[slot],
                              sem.at[1, slot]).wait()
        if quant:
            pltpu.make_async_copy(ks_hbm.at[lay, pg], ksbuf.at[slot],
                                  sem.at[2, slot]).wait()
            pltpu.make_async_copy(vs_hbm.at[lay, pg], vsbuf.at[slot],
                                  sem.at[3, slot]).wait()

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    # prologue: depth−1 pages in flight before the first wait, so per-page
    # DMA latency amortizes depth−1 deep instead of serializing (depth 2 =
    # the classic double buffer)
    for j in range(depth - 1):
        @pl.when(start + j < nlive)
        def _prime(j=j):
            start_dma(start + j, (start + j) % depth)

    qv = q_ref[0]                            # [KvH, Gp, hd]

    def body(i, _):
        slot = i % depth

        @pl.when(i + depth - 1 < nlive)
        def _prefetch():
            start_dma(i + depth - 1, (i + depth - 1) % depth)

        wait_dma(i, slot)
        # scale buffers are 4-D [depth, KvH, 1, sp] (a 3-D buffer's
        # dynamic-slot load lowers as an unsupported gather) and
        # lane-padded to sp >= ps (Mosaic DMA tile rule); the unit axis
        # is the broadcast axis and only the live ps lanes multiply
        kb, vb = kbuf[slot], vbuf[slot]
        if quant4:
            # pages land nibble-packed [KvH, ps//2, hd]; unpack after the
            # (half-width) DMA so HBM traffic stays at int4
            kb, vb = _unpack4(kb), _unpack4(vb)
        _flash_page_update(
            qv, kb, vb,
            ksbuf[slot][:, :, :ps] if quant else None,
            vsbuf[slot][:, :, :ps] if quant else None,
            m_ref, l_ref, acc_ref,
            k_start=i * ps, qp=qp, scale=scale, softcap=softcap,
            window=window, ps=ps, kvh=kvh, gp=gp, cdt=cdt)
        return 0

    jax.lax.fori_loop(start, nlive, body, 0)
    out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
    o_ref[0] = out.astype(o_ref.dtype)   # [KvH, Gp, hd] — caller reshapes


def paged_decode_attention_v3(q, k_pool, v_pool, layer, tables, lengths,
                              scale: float, softcap: float = 0.0,
                              sliding_window: int = 0, *, nblk: int,
                              interpret: bool = False):
    """Same contract as :func:`paged_decode_attention`; the live-page
    async-DMA formulation. ``nblk`` only bounds validity (tables must
    cover it) — the walked range is the slot's live count."""
    import os
    prep = _prep_paged(q, k_pool, v_pool, tables, nblk, interpret)
    if prep is None:
        return None
    quant, quant4, k_arr, v_arr, dims, sp, G, Gp, cdt, qg = prep
    B, H, hd_q, L, P, KvH, ps, hd = dims
    psq = ps // 2 if quant4 else ps            # stored page rows
    if quant and not interpret and sp % 128:
        # manual f32 DMAs need a 128-lane minor dim; unpadded scale pools
        # (hand-built tests, older stores) fall back to the v2 grid kernel
        return None
    if quant4 and not interpret and psq % 32:
        # int8 arrays tile (32, 128); half-width int4 pages below that
        # sublane multiple fall back to the v2 grid kernel
        return None
    # DMA pipeline depth: how many page fetches are in flight ahead of
    # the flash update (2 = classic double buffer). Deeper hides more
    # per-page latency at the cost of depth x page VMEM buffers.
    depth = max(2, int(os.environ.get("TPU_PAGED_DEPTH", "2") or "2"))

    hbm = pl.BlockSpec(memory_space=_HBM)
    in_specs = [
        pl.BlockSpec((1, KvH, Gp, hd), lambda b, *pref: (b, 0, 0, 0)),
        hbm, hbm,
    ]
    args = [qg, k_arr, v_arr]
    scratch = [
        pltpu.VMEM((depth, KvH, psq, hd), k_arr.dtype),
        pltpu.VMEM((depth, KvH, psq, hd), v_arr.dtype),
    ]
    if quant:
        in_specs += [hbm, hbm]
        args += [k_pool["s"].reshape(L, P, KvH, 1, -1).astype(jnp.float32),
                 v_pool["s"].reshape(L, P, KvH, 1, -1).astype(jnp.float32)]
        scratch += [pltpu.VMEM((depth, KvH, 1, sp), jnp.float32),
                    pltpu.VMEM((depth, KvH, 1, sp), jnp.float32)]
    scratch += [
        pltpu.VMEM((KvH, Gp, hd), jnp.float32),
        pltpu.VMEM((KvH, Gp, 1), jnp.float32),
        pltpu.VMEM((KvH, Gp, 1), jnp.float32),
        pltpu.SemaphoreType.DMA((4 if quant else 2, depth)),
    ]

    kernel = functools.partial(
        _paged_kernel_v3, scale=scale, softcap=softcap,
        window=sliding_window, ps=ps, sp=sp, kvh=KvH, gp=Gp, hd=hd,
        cdt=cdt, quant=quant, quant4=quant4, depth=depth)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KvH, Gp, hd),
                                   lambda b, *pref: (b, 0, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((B, KvH, Gp, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(jnp.reshape(layer, (1,)).astype(jnp.int32),
      lengths.astype(jnp.int32), tables.astype(jnp.int32),
      *args)
    return out[:, :, :G, :hd_q].reshape(B, 1, H, hd_q)
