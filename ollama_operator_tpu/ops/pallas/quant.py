"""Fused dequant-matmul Pallas kernel (weight-only int8, W8A16).

Decode matmuls are HBM-bound: the win is streaming int8 weight tiles
(half the bytes of bf16) into VMEM and dequantizing in-register right
before the MXU dot — the bf16 weight tensor never exists in HBM. The
XLA grouped-einsum path (ops/quant.qmm) is the portable fallback; this
kernel is the single-chip fast path, dispatched through the same
kernels switch as the flash-attention kernels (ops/attention.py).

Grid (oi, ki), ki innermost: each step loads an (bk, bo) int8 tile plus
its (bk/g, bo) scales, dequantizes to one bf16 tile in VMEM, and
accumulates x_tile @ w_tile into an f32 scratch that persists across ki.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quant import GROUP, qmm

_BLOCKS = (512, 256, 128, 64, 32)


def _pick(n: int, cap: int, multiple: int = 1):
    for b in _BLOCKS:
        if b <= cap and n % b == 0 and b % multiple == 0:
            return b
    return None


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int, g: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...]                                   # [B, bk] bf16
    qb = q_ref[...]                                   # [bk, bo] int8
    sb = s_ref[...]                                   # [bk/g, bo] f32
    bk, bo = qb.shape
    w = qb.astype(jnp.float32).reshape(bk // g, g, bo) * sb[:, None, :]
    w = w.reshape(bk, bo)
    acc_ref[...] += jax.lax.dot_general(
        xb.astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def flush():
        o_ref[...] = acc_ref[...]


def qmm_pallas(x: jax.Array, q: jax.Array, s: jax.Array,
               interpret: bool = False) -> jax.Array:
    """x [B, K] @ dequant(q [K, O], s [K/g, O]) → [B, O] f32.

    Falls back to the XLA grouped path when the shapes don't tile cleanly
    (odd dims, tiny K/O) — callers never need to care.
    """
    B, K = x.shape
    K2, O = q.shape
    G = s.shape[0]
    g = K // G
    bk = _pick(K, 512, multiple=g) if g in (16, 32, 64, 128) else None
    bo = _pick(O, 512)
    lanes_ok = interpret or (O % 128 == 0 and bo is not None and
                             bo % 128 == 0)
    if bk is None or bo is None or not lanes_ok:
        return qmm(x, {"q": q, "s": s}, out_dtype=jnp.float32)

    Bp = max(8, B)
    if Bp != B:
        x = jnp.pad(x, ((0, Bp - B), (0, 0)))
    nk = K // bk

    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, g=g),
        grid=(O // bo, nk),
        in_specs=[
            pl.BlockSpec((Bp, bk), lambda oi, ki: (0, ki)),
            pl.BlockSpec((bk, bo), lambda oi, ki: (ki, oi)),
            pl.BlockSpec((bk // g, bo), lambda oi, ki: (ki, oi)),
        ],
        out_specs=pl.BlockSpec((Bp, bo), lambda oi, ki: (0, oi)),
        out_shape=jax.ShapeDtypeStruct((Bp, O), jnp.float32),
        scratch_shapes=[pltpu.VMEM((Bp, bo), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, q, s.astype(jnp.float32))
    return out[:B]
