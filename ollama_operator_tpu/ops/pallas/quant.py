"""Fused dequant-matmul Pallas kernels (weight-only int8/int4).

Decode matmuls are HBM-bound: the win is streaming quantized weight
tiles (half / a quarter of bf16's bytes) into VMEM and dequantizing
in-register right before the MXU dot — the bf16 weight tensor never
exists in HBM. The XLA grouped-einsum paths (ops/quant.qmm / qmm4) are
the portable fallbacks; these kernels are the single-chip fast path,
dispatched through the same kernels switch as the flash-attention
kernels (ops/attention.py).

Grid (oi, ki), ki innermost: each step loads a (bk, bo) int8 tile (or
(bk/2, bo) packed-nibble tile) plus its (bk/g, bo) scales, dequantizes
to one tile in VMEM, and accumulates x_tile @ w_tile into an f32
scratch that persists across ki. The int4 unpack exploits the
group-local packing (ops/quant.pack_int4): low/high nibble planes are
whole half-groups, so rebuilding weight rows is one sublane-granular
concat per tile, and each packed byte is read from HBM exactly once —
the traffic halving the XLA int4 path can't get.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the newer pallas API renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from ..quant import GROUP, qmm, qmm4

_BLOCKS = (512, 256, 128, 64, 32)


def _pick(n: int, cap: int, multiple: int = 1):
    for b in _BLOCKS:
        if b <= cap and n % b == 0 and b % multiple == 0:
            return b
    return None


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int, g: int, cdt):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...]                                   # [B, bk] bf16
    qb = q_ref[...]                                   # [bk, bo] int8
    sb = s_ref[...]                                   # [bk/g, bo] f32
    bk, bo = qb.shape
    # dequant in f32 (exact: int8 code x f32 scale), then drop to the
    # compute dtype for the MXU dot — bf16 operands run at full MXU rate
    # where the first kernel generation's f32 dot measured a fraction of
    # it (on-chip: int4 527.8 tok/s vs int8-XLA 569.2 despite 38% fewer
    # bytes). f32 activations (CPU tests) keep f32 for bit-stable parity.
    w = qb.astype(jnp.float32).reshape(bk // g, g, bo) * sb[:, None, :]
    w = w.reshape(bk, bo)
    acc_ref[...] += jax.lax.dot_general(
        xb.astype(cdt), w.astype(cdt), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def flush():
        o_ref[...] = acc_ref[...]


def qmm_pallas(x: jax.Array, q: jax.Array, s: jax.Array,
               interpret: bool = False) -> jax.Array:
    """x [B, K] @ dequant(q [K, O], s [K/g, O]) → [B, O] f32.

    Falls back to the XLA grouped path when the shapes don't tile cleanly
    (odd dims, tiny K/O) — callers never need to care.
    """
    B, K = x.shape
    K2, O = q.shape
    G = s.shape[0]
    g = K // G
    bk = _pick(K, 512, multiple=g) if g in (16, 32, 64, 128) else None
    bo = _pick(O, 512)
    lanes_ok = interpret or (O % 128 == 0 and bo is not None and
                             bo % 128 == 0)
    if bk is None or bo is None or not lanes_ok:
        return qmm(x, {"q": q, "s": s}, out_dtype=jnp.float32)

    Bp = max(8, B)
    if Bp != B:
        x = jnp.pad(x, ((0, Bp - B), (0, 0)))
    nk = K // bk
    cdt = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32

    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, g=g, cdt=cdt),
        grid=(O // bo, nk),
        in_specs=[
            pl.BlockSpec((Bp, bk), lambda oi, ki: (0, ki)),
            pl.BlockSpec((bk, bo), lambda oi, ki: (ki, oi)),
            pl.BlockSpec((bk // g, bo), lambda oi, ki: (ki, oi)),
        ],
        out_specs=pl.BlockSpec((Bp, bo), lambda oi, ki: (0, oi)),
        out_shape=jax.ShapeDtypeStruct((Bp, O), jnp.float32),
        scratch_shapes=[pltpu.VMEM((Bp, bo), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, q, s.astype(jnp.float32))
    return out[:B]


def _kernel4(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int, g: int, cdt):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...]                                   # [B, bk] bf16
    qb = q_ref[...]                                   # [bk/2, bo] uint8
    sb = s_ref[...]                                   # [bk/g, bo] f32
    bkp, bo = qb.shape
    h = g // 2
    bi = qb.astype(jnp.int32).reshape(bkp // h, h, bo)
    lo = (bi & 0xF) - 8                               # rows [0, g/2) of
    hi = (bi >> 4) - 8                                # each group; [g/2, g)
    w = jnp.concatenate([lo, hi], axis=1).astype(jnp.float32)
    w = (w * sb[:, None, :]).reshape(2 * bkp, bo)
    # bf16 dot for the MXU (see _kernel); f32 x keeps f32 parity
    acc_ref[...] += jax.lax.dot_general(
        xb.astype(cdt), w.astype(cdt), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def flush():
        o_ref[...] = acc_ref[...]


def qmm4_pallas(x: jax.Array, q4: jax.Array, s: jax.Array,
                interpret: bool = False) -> jax.Array:
    """x [B, K] @ dequant(q4 [K/2, O] packed, s [K/g, O]) → [B, O] f32.

    Falls back to the XLA grouped path when the shapes don't tile cleanly
    (odd dims, tiny K/O) — callers never need to care.
    """
    B, K = x.shape
    Kp, O = q4.shape
    assert 2 * Kp == K, (Kp, K)
    G = s.shape[0]
    g = K // G
    # bk % 2g keeps the packed tile's sublane count a multiple of g —
    # no partial groups, and the uint8 tile stays (32, 128)-tileable
    bk = _pick(K, 512, multiple=2 * g) if g in (16, 32, 64, 128) else None
    bo = _pick(O, 512)
    lanes_ok = interpret or (O % 128 == 0 and bo is not None and
                             bo % 128 == 0)
    if bk is None or bo is None or not lanes_ok:
        return qmm4(x, {"q4": q4, "s": s}, out_dtype=jnp.float32)

    Bp = max(8, B)
    if Bp != B:
        x = jnp.pad(x, ((0, Bp - B), (0, 0)))
    nk = K // bk
    cdt = jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32

    out = pl.pallas_call(
        functools.partial(_kernel4, nk=nk, g=g, cdt=cdt),
        grid=(O // bo, nk),
        in_specs=[
            pl.BlockSpec((Bp, bk), lambda oi, ki: (0, ki)),
            pl.BlockSpec((bk // 2, bo), lambda oi, ki: (ki, oi)),
            pl.BlockSpec((bk // g, bo), lambda oi, ki: (ki, oi)),
        ],
        out_specs=pl.BlockSpec((Bp, bo), lambda oi, ki: (0, oi)),
        out_shape=jax.ShapeDtypeStruct((Bp, O), jnp.float32),
        scratch_shapes=[pltpu.VMEM((Bp, bo), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, q4, s.astype(jnp.float32))
    return out[:B]
