"""Weight-only int8/int4 quantization for serving (W8A16 / W4A16).

The reference serves GGUF-quantized weights through llama.cpp's CPU/GPU
dequant kernels inside the delegated ollama image (SURVEY.md §2.2). The
TPU-native equivalent keeps weights **quantized in HBM** and dequantizes on
the fly inside the matmul — decode is HBM-bandwidth-bound, so halving the
weight bytes roughly doubles decode throughput and is what lets llama2:70b
fit comfortably across a v5e-16 (BASELINE.md north star).

Representation: a quantized linear is a dict leaf in the params pytree —

    int8: {"q":  int8  [..., K,   O], "s": f32 [..., K/g, O]}
    int4: {"q4": uint8 [..., K/2, O], "s": f32 [..., K/g, O]}

symmetric, group-wise along the contracted (input) axis with group size
``g`` = 32, llama.cpp's q8_0/q4_0 block size — so transcoding q8_0 weights
onto the int8 grid adds (almost) no error beyond the original quantization,
and q4-family weights land on the int4 grid with only the clip of q4_0's
lone -8 code (we keep the symmetric [-7, 7] range).

int4 packing is **group-local**: each group of 32 rows packs into 16 bytes
where byte j holds row j in its low nibble and row j+16 in its high nibble
(both biased by +8 into [1, 15]). Group-local packing means any K-tile
that is a multiple of the group unpacks with a sublane-granular concat —
no cross-tile shuffles — which is what the pallas kernel wants.

Matmul paths:
- ``qmm`` / ``qmm4``: pure-XLA grouped partial einsums — correct on any
  backend and under GSPMD (the convert fuses into the dot's operand
  stream). The int4 decode form runs two half-group dots over the same
  packed bytes, so its HBM traffic matches int8's — the *capacity* win
  (~0.63 B/weight with the f32 group scales; 70B int4 ≈ 43 GB) is
  unconditional, the *bandwidth* win needs the kernel below.
- ``ops/pallas/quant.py``: fused dequant-matmul kernels (int8 and int4);
  the int4 kernel reads each packed byte once, i.e. half int8's weight
  traffic.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

GROUP = 32

# matmul leaves worth quantizing (the big projections). tok_emb stays dense
# (it is a gather, not a matmul); MoE expert stacks stay dense this round.
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
QUANT_TOP_KEYS = ("lm_head",)


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and ("q" in w or "q4" in w) and "s" in w


def is_int4(w: Any) -> bool:
    return isinstance(w, dict) and "q4" in w


def quantize_groupwise(w, group: int = GROUP) -> Dict[str, Any]:
    """Symmetric int8 per ``group`` along the second-to-last (input) axis.

    w [..., K, O] float → {"q" int8 [..., K, O], "s" f32 [..., K/g, O]}.
    jax arrays quantize on-device (jitted — milliseconds even for 70B
    leaves); numpy stays on host for the memory-bounded transcode path.
    """
    if isinstance(w, jax.Array):
        return _quantize_jax(w, group)
    w = np.asarray(w)
    *lead, K, O = w.shape
    assert K % group == 0, f"group {group} must divide in-dim {K}"
    if lead:
        # stacked [L, ...] leaves quantize one slice at a time — the f32
        # temporaries below are per-slice, so peak host RAM stays one
        # layer, not 3x the whole (potentially 70B-scale) leaf
        q = np.empty(w.shape, np.int8)
        s = np.empty((*lead, K // group, O), np.float32)
        flat_w = w.reshape(-1, K, O)
        flat_q = q.reshape(-1, K, O)
        flat_s = s.reshape(-1, K // group, O)
        for i in range(flat_w.shape[0]):
            sl = quantize_groupwise(flat_w[i], group)
            flat_q[i], flat_s[i] = sl["q"], sl["s"]
        return {"q": q, "s": s}
    w = np.asarray(w, np.float32)
    wr = w.reshape(K // group, group, O)
    amax = np.abs(wr).max(axis=-2, keepdims=True)          # [K/g, 1, O]
    s = (amax / 127.0).astype(np.float32)
    q = np.rint(np.where(s > 0, wr / np.maximum(s, 1e-30), 0.0))
    q = np.clip(q, -127, 127).astype(np.int8)
    return {"q": q.reshape(K, O), "s": s[:, 0, :]}


@partial(jax.jit, donate_argnums=(0,))
def _quantize_jax_impl(w, group: int = GROUP):
    *lead, K, O = w.shape
    wr = w.astype(jnp.float32).reshape(*lead, K // group, group, O)
    amax = jnp.max(jnp.abs(wr), axis=-2, keepdims=True)
    s = amax / 127.0
    q = jnp.round(jnp.where(s > 0, wr / jnp.maximum(s, 1e-30), 0.0))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return {"q": q.reshape(*lead, K, O), "s": s[..., 0, :]}


def _quantize_jax(w: jax.Array, group: int = GROUP) -> Dict[str, Any]:
    assert w.shape[-2] % group == 0
    assert group == GROUP, "jit path is specialised to the default group"
    return _quantize_jax_impl(w)


def pack_int4(q, bias: int = 8):
    """Pack int codes in [-7, 7] ([..., K, O]) into group-local nibbles
    ([..., K/2, O] uint8): within each 32-row group, byte j = row j
    (low nibble) | row j+16 (high nibble), both biased by +8."""
    xp = jnp if isinstance(q, jax.Array) else np
    *lead, K, O = q.shape
    assert K % GROUP == 0
    qr = (q.reshape(*lead, K // GROUP, GROUP, O) + bias).astype(xp.uint8)
    lo, hi = qr[..., :GROUP // 2, :], qr[..., GROUP // 2:, :]
    return (lo | (hi << 4)).reshape(*lead, K // 2, O)


def unpack_int4(q4, bias: int = 8):
    """Inverse of pack_int4: [..., K/2, O] uint8 → int8 [..., K, O]."""
    xp = jnp if isinstance(q4, jax.Array) else np
    *lead, Kp, O = q4.shape
    h = GROUP // 2
    assert Kp % h == 0
    b = q4.reshape(*lead, Kp // h, h, O)
    lo = (b & 0xF).astype(xp.int8) - bias
    hi = (b >> 4).astype(xp.int8) - bias
    return xp.concatenate([lo, hi], axis=-2).reshape(*lead, 2 * Kp, O)


def quantize_groupwise_int4(w, group: int = GROUP) -> Dict[str, Any]:
    """Symmetric int4 per ``group`` along the input axis, nibble-packed.

    w [..., K, O] float → {"q4" uint8 [..., K/2, O], "s" f32 [..., K/g, O]}.
    Codes clip to [-7, 7]: q4_0's asymmetric -8 code costs one extra
    grid point of error on transcode, and symmetry keeps dequant a pure
    multiply (no zero-point correction term in the matmuls).
    """
    assert group == GROUP, "int4 packing is specialised to the group size"
    if isinstance(w, jax.Array):
        return _quantize_jax_int4(w)
    w = np.asarray(w)
    *lead, K, O = w.shape
    assert K % group == 0, f"group {group} must divide in-dim {K}"
    if lead:
        q4 = np.empty((*lead, K // 2, O), np.uint8)
        s = np.empty((*lead, K // group, O), np.float32)
        flat_w = w.reshape(-1, K, O)
        flat_q = q4.reshape(-1, K // 2, O)
        flat_s = s.reshape(-1, K // group, O)
        for i in range(flat_w.shape[0]):
            sl = quantize_groupwise_int4(flat_w[i], group)
            flat_q[i], flat_s[i] = sl["q4"], sl["s"]
        return {"q4": q4, "s": s}
    w = np.asarray(w, np.float32)
    wr = w.reshape(K // group, group, O)
    amax = np.abs(wr).max(axis=-2, keepdims=True)
    s = (amax / 7.0).astype(np.float32)
    q = np.rint(np.where(s > 0, wr / np.maximum(s, 1e-30), 0.0))
    q = np.clip(q, -7, 7).astype(np.int8).reshape(K, O)
    return {"q4": pack_int4(q), "s": s[:, 0, :]}


@partial(jax.jit, donate_argnums=(0,))
def _quantize_jax_int4_impl(w):
    *lead, K, O = w.shape
    g = GROUP
    wr = w.astype(jnp.float32).reshape(*lead, K // g, g, O)
    amax = jnp.max(jnp.abs(wr), axis=-2, keepdims=True)
    s = amax / 7.0
    q = jnp.round(jnp.where(s > 0, wr / jnp.maximum(s, 1e-30), 0.0))
    q = jnp.clip(q, -7, 7).astype(jnp.int8).reshape(*lead, K, O)
    return {"q4": pack_int4(q), "s": s[..., 0, :]}


def _quantize_jax_int4(w: jax.Array) -> Dict[str, Any]:
    assert w.shape[-2] % GROUP == 0
    return _quantize_jax_int4_impl(w)


def dequantize_groupwise(qw: Dict[str, Any]) -> jnp.ndarray:
    """Reference inverse of quantize_groupwise[_int4] (f32)."""
    if is_int4(qw):
        q = unpack_int4(jnp.asarray(qw["q4"]))
    else:
        q = jnp.asarray(qw["q"])
    s = jnp.asarray(qw["s"])
    *lead, K, O = q.shape
    G = s.shape[-2]
    qr = q.reshape(*lead, G, K // G, O).astype(jnp.float32)
    return (qr * s[..., :, None, :]).reshape(*lead, K, O)


def qmm4(x: jax.Array, qw: Dict[str, Any],
         out_dtype: Optional[Any] = None) -> jax.Array:
    """x [..., K] @ dequant(int4 qw) — XLA formulation (portable/GSPMD).

    Same N-split as qmm. The decode form dots the two nibble planes
    separately against the matching half-group activation slices —
    group-local packing makes those static slices, no gather — so the
    packed bytes are each read twice (int8-equivalent traffic); the
    pallas kernel is the half-traffic path.
    """
    q4, s = qw["q4"], qw["s"]
    Kp, O = q4.shape
    K = 2 * Kp
    G = s.shape[0]
    g = K // G
    h = g // 2
    N = 1
    for d in x.shape[:-1]:
        N *= d
    if N > 16:
        # dequantize in f32, cast the product once — the decode form and
        # the pallas kernel apply f32 scales post-dot, so prefill must not
        # see scale values rounded through bf16's 8-bit mantissa
        w = (unpack_int4(q4).reshape(G, g, O).astype(jnp.float32)
             * s[:, None, :]).reshape(K, O).astype(x.dtype)
        y = jnp.einsum("...k,ko->...o", x, w,
                       preferred_element_type=jnp.float32)
        return y.astype(out_dtype or x.dtype)
    xr = x.reshape(*x.shape[:-1], G, g)
    b = q4.reshape(G, h, O)
    lo = ((b & 0xF).astype(jnp.int8) - 8).astype(x.dtype)
    hi = ((b >> 4).astype(jnp.int8) - 8).astype(x.dtype)
    partial = (jnp.einsum("...Gg,Ggo->...Go", xr[..., :h], lo,
                          preferred_element_type=jnp.float32)
               + jnp.einsum("...Gg,Ggo->...Go", xr[..., h:], hi,
                            preferred_element_type=jnp.float32))
    y = jnp.einsum("...Go,Go->...o", partial, s)
    return y.astype(out_dtype or x.dtype)


def qmm(x: jax.Array, qw: Dict[str, Any],
        out_dtype: Optional[Any] = None) -> jax.Array:
    """x [..., K] @ dequant(qw [K, O]) with group-wise scales.

    Two formulations, picked by the (static) token count N = prod(lead):

    - **decode** (N small): grouped partial, keeping the scale multiply
      outside the inner dot so the int8→bf16 convert fuses into the dot's
      read stream and the weight is read once at 1 byte/element:

          y[.., o] = Σ_G s[G, o] · Σ_{k∈G} x[.., k] · q[k, o]

      The [N, K/g, O] fp32 partial is tiny for decode batches.
    - **prefill** (N large): that partial scales as N × weight-bytes×4 —
      gigabytes per matmul at N=128+ — so dequantize the weight to one
      [K, O] transient instead and run a single dense dot; prefill is
      MXU-bound, the extra weight-write bandwidth is noise there.
    """
    q, s = qw["q"], qw["s"]
    K, O = q.shape
    G = s.shape[0]
    g = K // G
    N = 1
    for d in x.shape[:-1]:
        N *= d
    if N > 16:
        # f32 scales (same reasoning as qmm4's batch form)
        w = (q.reshape(G, g, O).astype(jnp.float32)
             * s[:, None, :]).reshape(K, O).astype(x.dtype)
        y = jnp.einsum("...k,ko->...o", x, w,
                       preferred_element_type=jnp.float32)
        return y.astype(out_dtype or x.dtype)
    xr = x.reshape(*x.shape[:-1], G, g)
    qr = q.reshape(G, g, O)
    partial = jnp.einsum("...Gg,Ggo->...Go", xr, qr.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    y = jnp.einsum("...Go,Go->...o", partial, s)
    return y.astype(out_dtype or x.dtype)


def matmul(x: jax.Array, w: Any, out_dtype: Optional[Any] = None,
           kernels: str = "xla") -> jax.Array:
    """Unified linear: dense jnp array or quantized dict weight.

    ``kernels`` follows ops/attention.resolve_kernels semantics — "pallas"
    routes 2D-reshapeable quantized matmuls through the fused kernel.
    """
    if not is_quantized(w):
        y = x @ w
        return y.astype(out_dtype) if out_dtype is not None else y
    if kernels in ("pallas", "interpret"):
        from .pallas.quant import qmm4_pallas, qmm_pallas
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        if is_int4(w):
            y = qmm4_pallas(x2, w["q4"], w["s"],
                            interpret=(kernels == "interpret"))
        else:
            y = qmm_pallas(x2, w["q"], w["s"],
                           interpret=(kernels == "interpret"))
        return y.reshape(*lead, -1).astype(out_dtype or x.dtype)
    return (qmm4 if is_int4(w) else qmm)(x, w, out_dtype)


def quantize_params(params: Dict[str, Any], group: int = GROUP,
                    keys_layer=QUANT_LAYER_KEYS, keys_top=QUANT_TOP_KEYS,
                    bits: int = 8) -> Dict[str, Any]:
    """Convert the big matmul leaves of a decoder param tree to int8
    (``bits=8``) or packed int4 (``bits=4``).

    Works on numpy (host) or jax (on-device) arrays; stacked [L, ...]
    layer leaves quantize along their input axis, which is second-to-last
    either way.

    On-device (jax) sources are DONATED leaf by leaf — each bf16 leaf's
    HBM is released as its quantized replacement materialises, so peak
    memory is the bf16 tree + one leaf, never bf16 + quantized trees
    together (a 7B bf16 tree alone is 13.4 GB of a v5e chip's 16).
    """
    assert bits in (8, 4), bits
    quant = quantize_groupwise if bits == 8 else quantize_groupwise_int4
    out: Dict[str, Any] = {}
    for k in list(params.keys()):
        v = params[k]
        if k == "layers":
            lo = {}
            for lk in list(v.keys()):
                if lk in keys_layer:
                    lo[lk] = quant(v.pop(lk), group)
                else:
                    lo[lk] = v[lk]
            out[k] = lo
        elif k in keys_top:
            out[k] = quant(params.pop(k), group)
        else:
            out[k] = v
    return out


def int4_mm_kernels(cfg, mesh) -> Any:
    """The ``mm_kernels`` value an int4 load should serve with: the fused
    pallas kernel on a single-device TPU (the only matmul path that reads
    each packed byte once), the portable XLA einsum under GSPMD meshes —
    and an explicitly-set ``mm_kernels`` (config) or ``kernels=xla``
    (config or OLLAMA_TPU_KERNELS) stays the escape hatch if the kernel
    miscompiles — the matmul hatch works independently of the attention
    switch. One helper so the server loader and bench.py can never drift
    onto different matmul paths (they feed the same BASELINE numbers).
    Returns the cfg, possibly replaced."""
    import dataclasses

    import jax

    from .attention import resolve_kernels
    if cfg.mm_kernels != "auto":
        return cfg
    if (jax.default_backend() == "tpu"
            and (mesh is None or mesh.size == 1)
            and resolve_kernels(cfg.kernels) != "xla"):
        return dataclasses.replace(cfg, mm_kernels="pallas")
    return cfg


def quantized_bytes(params: Dict[str, Any]) -> int:
    """HBM footprint of a (possibly partly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
