"""Weight-only int8 quantization for serving (W8A16-style).

The reference serves GGUF-quantized weights through llama.cpp's CPU/GPU
dequant kernels inside the delegated ollama image (SURVEY.md §2.2). The
TPU-native equivalent keeps weights **quantized in HBM** and dequantizes on
the fly inside the matmul — decode is HBM-bandwidth-bound, so halving the
weight bytes roughly doubles decode throughput and is what lets llama2:70b
fit comfortably across a v5e-16 (BASELINE.md north star).

Representation: a quantized linear is a dict leaf in the params pytree —

    {"q": int8 [..., K, O],  "s": f32 [..., K/g, O]}

symmetric, group-wise along the contracted (input) axis with group size
``g`` = 32, llama.cpp's q8_0 block size — so transcoding q8_0 weights onto
this grid adds (almost) no error beyond the original quantization, and
finer GGUF grids (q4_*) are strictly refined by it.

Two matmul paths:
- ``qmm``: pure-XLA grouped partial einsum — correct on any backend and
  under GSPMD (the int8→bf16 convert fuses into the dot's operand stream).
- ``ops/pallas/quant.py``: fused dequant-matmul kernel for single-chip
  decode, dispatched via the same kernels switch as attention.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

GROUP = 32

# matmul leaves worth quantizing (the big projections). tok_emb stays dense
# (it is a gather, not a matmul); MoE expert stacks stay dense this round.
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
QUANT_TOP_KEYS = ("lm_head",)


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_groupwise(w, group: int = GROUP) -> Dict[str, Any]:
    """Symmetric int8 per ``group`` along the second-to-last (input) axis.

    w [..., K, O] float → {"q" int8 [..., K, O], "s" f32 [..., K/g, O]}.
    jax arrays quantize on-device (jitted — milliseconds even for 70B
    leaves); numpy stays on host for the memory-bounded transcode path.
    """
    if isinstance(w, jax.Array):
        return _quantize_jax(w, group)
    w = np.asarray(w)
    *lead, K, O = w.shape
    assert K % group == 0, f"group {group} must divide in-dim {K}"
    if lead:
        # stacked [L, ...] leaves quantize one slice at a time — the f32
        # temporaries below are per-slice, so peak host RAM stays one
        # layer, not 3x the whole (potentially 70B-scale) leaf
        q = np.empty(w.shape, np.int8)
        s = np.empty((*lead, K // group, O), np.float32)
        flat_w = w.reshape(-1, K, O)
        flat_q = q.reshape(-1, K, O)
        flat_s = s.reshape(-1, K // group, O)
        for i in range(flat_w.shape[0]):
            sl = quantize_groupwise(flat_w[i], group)
            flat_q[i], flat_s[i] = sl["q"], sl["s"]
        return {"q": q, "s": s}
    w = np.asarray(w, np.float32)
    wr = w.reshape(K // group, group, O)
    amax = np.abs(wr).max(axis=-2, keepdims=True)          # [K/g, 1, O]
    s = (amax / 127.0).astype(np.float32)
    q = np.rint(np.where(s > 0, wr / np.maximum(s, 1e-30), 0.0))
    q = np.clip(q, -127, 127).astype(np.int8)
    return {"q": q.reshape(K, O), "s": s[:, 0, :]}


@partial(jax.jit, donate_argnums=(0,))
def _quantize_jax_impl(w, group: int = GROUP):
    *lead, K, O = w.shape
    wr = w.astype(jnp.float32).reshape(*lead, K // group, group, O)
    amax = jnp.max(jnp.abs(wr), axis=-2, keepdims=True)
    s = amax / 127.0
    q = jnp.round(jnp.where(s > 0, wr / jnp.maximum(s, 1e-30), 0.0))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return {"q": q.reshape(*lead, K, O), "s": s[..., 0, :]}


def _quantize_jax(w: jax.Array, group: int = GROUP) -> Dict[str, Any]:
    assert w.shape[-2] % group == 0
    assert group == GROUP, "jit path is specialised to the default group"
    return _quantize_jax_impl(w)


def dequantize_groupwise(qw: Dict[str, Any]) -> jnp.ndarray:
    """Reference inverse of quantize_groupwise (f32)."""
    q, s = jnp.asarray(qw["q"]), jnp.asarray(qw["s"])
    *lead, K, O = q.shape
    G = s.shape[-2]
    qr = q.reshape(*lead, G, K // G, O).astype(jnp.float32)
    return (qr * s[..., :, None, :]).reshape(*lead, K, O)


def qmm(x: jax.Array, qw: Dict[str, Any],
        out_dtype: Optional[Any] = None) -> jax.Array:
    """x [..., K] @ dequant(qw [K, O]) with group-wise scales.

    Two formulations, picked by the (static) token count N = prod(lead):

    - **decode** (N small): grouped partial, keeping the scale multiply
      outside the inner dot so the int8→bf16 convert fuses into the dot's
      read stream and the weight is read once at 1 byte/element:

          y[.., o] = Σ_G s[G, o] · Σ_{k∈G} x[.., k] · q[k, o]

      The [N, K/g, O] fp32 partial is tiny for decode batches.
    - **prefill** (N large): that partial scales as N × weight-bytes×4 —
      gigabytes per matmul at N=128+ — so dequantize the weight to one
      [K, O] transient instead and run a single dense dot; prefill is
      MXU-bound, the extra weight-write bandwidth is noise there.
    """
    q, s = qw["q"], qw["s"]
    K, O = q.shape
    G = s.shape[0]
    g = K // G
    N = 1
    for d in x.shape[:-1]:
        N *= d
    if N > 16:
        w = (q.reshape(G, g, O).astype(x.dtype)
             * s[:, None, :].astype(x.dtype)).reshape(K, O)
        y = jnp.einsum("...k,ko->...o", x, w,
                       preferred_element_type=jnp.float32)
        return y.astype(out_dtype or x.dtype)
    xr = x.reshape(*x.shape[:-1], G, g)
    qr = q.reshape(G, g, O)
    partial = jnp.einsum("...Gg,Ggo->...Go", xr, qr.astype(x.dtype),
                         preferred_element_type=jnp.float32)
    y = jnp.einsum("...Go,Go->...o", partial, s)
    return y.astype(out_dtype or x.dtype)


def matmul(x: jax.Array, w: Any, out_dtype: Optional[Any] = None,
           kernels: str = "xla") -> jax.Array:
    """Unified linear: dense jnp array or quantized dict weight.

    ``kernels`` follows ops/attention.resolve_kernels semantics — "pallas"
    routes 2D-reshapeable quantized matmuls through the fused kernel.
    """
    if not is_quantized(w):
        y = x @ w
        return y.astype(out_dtype) if out_dtype is not None else y
    if kernels in ("pallas", "interpret"):
        from .pallas.quant import qmm_pallas
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        y = qmm_pallas(x2, w["q"], w["s"], interpret=(kernels == "interpret"))
        return y.reshape(*lead, -1).astype(out_dtype or x.dtype)
    return qmm(x, w, out_dtype)


def quantize_params(params: Dict[str, Any], group: int = GROUP,
                    keys_layer=QUANT_LAYER_KEYS, keys_top=QUANT_TOP_KEYS
                    ) -> Dict[str, Any]:
    """Convert the big matmul leaves of a decoder param tree to int8.

    Works on numpy (host) or jax (on-device) arrays; stacked [L, ...]
    layer leaves quantize along their input axis, which is second-to-last
    either way.

    On-device (jax) sources are DONATED leaf by leaf — each bf16 leaf's
    HBM is released as its int8 replacement materialises, so peak memory
    is the bf16 tree + one leaf, never bf16 + int8 trees together (a 7B
    bf16 tree alone is 13.4 GB of a v5e chip's 16).
    """
    out: Dict[str, Any] = {}
    for k in list(params.keys()):
        v = params[k]
        if k == "layers":
            lo = {}
            for lk in list(v.keys()):
                if lk in keys_layer:
                    lo[lk] = quantize_groupwise(v.pop(lk), group)
                else:
                    lo[lk] = v[lk]
            out[k] = lo
        elif k in keys_top:
            out[k] = quantize_groupwise(params.pop(k), group)
        else:
            out[k] = v
    return out


def quantized_bytes(params: Dict[str, Any]) -> int:
    """HBM footprint of a (possibly partly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
