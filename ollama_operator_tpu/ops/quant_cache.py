"""int8 KV cache: quantized storage + attention over it.

Decode attention traffic is the KV cache itself; storing K/V as int8 with
one f32 scale per (position, head) halves that traffic and doubles how
much context fits in HBM — the same lever llama.cpp pulls with its
quantized KV options inside the reference's delegated container.

Layout mirrors the bf16 cache, plus a scale array one axis short:

    q [.., KvH, S, hd] int8      s [.., KvH, S] f32

The arithmetic stays exact-shaped with the dense path (ops/attention.py
``attend_hf``): scores pick up the key scale AFTER the q·k dot (the scale
is per key position, so it factors out), and the value scale folds into
the probabilities before the p·v dot — dequantized V tensors never
materialise:

    scores[.., t, j] = (q_t · kq_j) * ks_j
    out[.., t]       = Σ_j (p_tj * vs_j) · vq_j
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .attention import NEG_INF, softcap_scores


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., hd] float → (int8 [..., hd], f32 scale [...])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = amax / 127.0
    q = jnp.round(x.astype(jnp.float32) / jnp.maximum(s[..., None], 1e-30))
    return jnp.clip(q, -127, 127).astype(jnp.int8), s


def attend_hf_q(q, kc: Dict, vc: Dict, mask, scale: float,
                softcap: float = 0.0, attn_len=None, compute_dtype=None):
    """Grouped-query attention against the quantized head-first cache.

    q [B, T, H, hd]; kc/vc {"q" [B, KvH, S, hd] int8, "s" [B, KvH, S]};
    mask [B, 1, T, A] additive. → [B, T, H, hd] (q.dtype).
    """
    B, T, H, hd = q.shape
    kq, ks = kc["q"], kc["s"]
    vq, vs = vc["q"], vc["s"]
    if attn_len is not None and attn_len < kq.shape[2]:
        kq, ks = kq[:, :, :attn_len], ks[:, :, :attn_len]
        vq, vs = vq[:, :, :attn_len], vs[:, :, :attn_len]
    KvH = kq.shape[1]
    G = H // KvH
    dt = compute_dtype or q.dtype
    qg = q.reshape(B, T, KvH, G, hd)
    scores = jnp.einsum("btkgh,bksh->bkgts", qg, kq.astype(dt),
                        preferred_element_type=jnp.float32)
    scores = scores * ks[:, :, None, None, :]          # key scale, per j
    scores = softcap_scores(scores * scale, softcap)
    scores = scores + mask[:, :, None, :, :]
    probs = jax.nn.softmax(scores, axis=-1)
    pv = (probs * vs[:, :, None, None, :]).astype(dt)  # value scale into p
    out = jnp.einsum("bkgts,bksh->btkgh", pv, vq.astype(dt))
    return out.reshape(B, T, H, hd)


def is_quantized_cache(kc) -> bool:
    return isinstance(kc, dict) and "q" in kc and "s" in kc


def empty_cache(L: int, B: int, KvH: int, S: int, hd: int) -> Dict:
    return {"q": jnp.zeros((L, B, KvH, S, hd), jnp.int8),
            "s": jnp.zeros((L, B, KvH, S), jnp.float32)}
