"""int8/int4 KV cache: quantized storage + attention over it.

Decode attention traffic is the KV cache itself; storing K/V as int8 with
one f32 scale per (position, head) halves that traffic and doubles how
much context fits in HBM — the same lever llama.cpp pulls with its
quantized KV options inside the reference's delegated container.

Layout mirrors the bf16 cache, plus a scale array one axis short:

    q [.., KvH, S, hd] int8      s [.., KvH, S] f32

The arithmetic stays exact-shaped with the dense path (ops/attention.py
``attend_hf``): scores pick up the key scale AFTER the q·k dot (the scale
is per key position, so it factors out), and the value scale folds into
the probabilities before the p·v dot — dequantized V tensors never
materialise:

    scores[.., t, j] = (q_t · kq_j) * ks_j
    out[.., t]       = Σ_j (p_tj * vs_j) · vq_j

int4 (paged pools only, TPU_KV_DTYPE=int4): same per-(position, head)
scale layout, codes in [-7, 7] (scale = amax/7, ops/quant.py's symmetric
int4 range) stored two POSITIONS per byte along the page axis —

    q4 [.., KvH, ps//2, hd] uint-packed int8      s [.., KvH, ps] f32

position 2j rides the low nibble, 2j+1 the high nibble, both biased +8
(codes land in 1..15; 8 == 0.0 is the empty-pool value is wrong — zeros
decode to -8*scale, but empty pages carry scale 0 so they still read as
exact 0.0). Packing along the position (sublane) axis keeps the pool's
128-lane head dim intact, which is what lets the fused pallas kernel DMA
int4 pages with the same lane alignment as int8 ones.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .attention import NEG_INF, softcap_scores


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., hd] float → (int8 [..., hd], f32 scale [...])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = amax / 127.0
    q = jnp.round(x.astype(jnp.float32) / jnp.maximum(s[..., None], 1e-30))
    return jnp.clip(q, -127, 127).astype(jnp.int8), s


def attend_hf_q(q, kc: Dict, vc: Dict, mask, scale: float,
                softcap: float = 0.0, attn_len=None, compute_dtype=None):
    """Grouped-query attention against the quantized head-first cache.

    q [B, T, H, hd]; kc/vc {"q" [B, KvH, S, hd] int8, "s" [B, KvH, S]};
    mask [B, 1, T, A] additive. → [B, T, H, hd] (q.dtype).
    """
    B, T, H, hd = q.shape
    kq, ks = kc["q"], kc["s"]
    vq, vs = vc["q"], vc["s"]
    if attn_len is not None and attn_len < kq.shape[2]:
        kq, ks = kq[:, :, :attn_len], ks[:, :, :attn_len]
        vq, vs = vq[:, :, :attn_len], vs[:, :, :attn_len]
    KvH = kq.shape[1]
    G = H // KvH
    dt = compute_dtype or q.dtype
    qg = q.reshape(B, T, KvH, G, hd)
    scores = jnp.einsum("btkgh,bksh->bkgts", qg, kq.astype(dt),
                        preferred_element_type=jnp.float32)
    scores = scores * ks[:, :, None, None, :]          # key scale, per j
    scores = softcap_scores(scores * scale, softcap)
    scores = scores + mask[:, :, None, :, :]
    probs = jax.nn.softmax(scores, axis=-1)
    pv = (probs * vs[:, :, None, None, :]).astype(dt)  # value scale into p
    out = jnp.einsum("bkgts,bksh->btkgh", pv, vq.astype(dt))
    return out.reshape(B, T, H, hd)


def is_quantized_cache(kc) -> bool:
    return isinstance(kc, dict) and ("q" in kc or "q4" in kc) and "s" in kc


def empty_cache(L: int, B: int, KvH: int, S: int, hd: int) -> Dict:
    return {"q": jnp.zeros((L, B, KvH, S, hd), jnp.int8),
            "s": jnp.zeros((L, B, KvH, S), jnp.float32)}


# --------------------------------------------------------------------------
# int4 pool codecs (per-page KV layout)
# --------------------------------------------------------------------------

INT4_BIAS = 8   # stored nibble = code + 8, codes in [-7, 7]


def pool_codes(pool: Dict) -> jax.Array:
    """The code array of a quantized pool dict ({"q"} int8 or {"q4"}
    nibble-packed)."""
    return pool["q4"] if "q4" in pool else pool["q"]


def pool_bits(pool) -> int:
    """Code width of a pool: 4 for nibble-packed dicts, 8 for int8 dicts,
    and the storage itemsize*8 for plain (unquantized) arrays."""
    if isinstance(pool, dict):
        return 4 if "q4" in pool else 8
    return pool.dtype.itemsize * 8


def quantize_kv4(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[..., hd] float → (int4 codes [-7, 7] as int8 [..., hd], f32 scale
    [...]). Same shape contract as ``quantize_kv``; packing into nibbles
    is a separate step because the paged scatter needs per-position codes
    (``pack_kv4`` / the read-modify-write nibble scatter in
    models/decoder.py)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    s = amax / 7.0
    q = jnp.round(x.astype(jnp.float32) / jnp.maximum(s[..., None], 1e-30))
    return jnp.clip(q, -7, 7).astype(jnp.int8), s


def pack_kv4(codes: jax.Array, axis: int = -2) -> jax.Array:
    """Pack int4 codes [-7, 7] pairwise along ``axis`` (the position axis;
    must be even-sized): position 2j → low nibble, 2j+1 → high nibble,
    biased +8. Returns int8 with ``axis`` halved."""
    codes = jnp.moveaxis(codes, axis, -1)
    n = codes.shape[-1]
    assert n % 2 == 0, f"pack_kv4: axis size {n} must be even"
    b = (codes + INT4_BIAS).astype(jnp.uint8)
    lo, hi = b[..., 0::2], b[..., 1::2]
    packed = (lo | (hi << 4)).astype(jnp.int8)
    return jnp.moveaxis(packed, -1, axis)


def unpack_kv4(packed: jax.Array, axis: int = -2) -> jax.Array:
    """Inverse of ``pack_kv4``: int8 nibble pairs → int4 codes [-7, 7]
    (int8), ``axis`` doubled."""
    b = jnp.moveaxis(packed, axis, -1).astype(jnp.uint8)
    lo = (b & 0xF).astype(jnp.int8) - INT4_BIAS
    hi = ((b >> 4) & 0xF).astype(jnp.int8) - INT4_BIAS
    out = jnp.stack([lo, hi], axis=-1)            # [..., n//2, 2]
    out = out.reshape(*out.shape[:-2], -1)        # [..., n]
    return jnp.moveaxis(out, -1, axis)


def attend_hf_q4(q, kc: Dict, vc: Dict, mask, scale: float,
                 softcap: float = 0.0, attn_len=None, compute_dtype=None):
    """``attend_hf_q`` over an int4 pool view: unpack the nibble codes
    back to per-position int8 codes, then run the shared scaled-dot path
    (the unpack is a register-level shift/mask — no f32 KV materialises).
    kc/vc {"q4" [B, KvH, S//2, hd], "s" [B, KvH, S]}."""
    kc8 = {"q": unpack_kv4(kc["q4"]), "s": kc["s"]}
    vc8 = {"q": unpack_kv4(vc["q4"]), "s": vc["s"]}
    return attend_hf_q(q, kc8, vc8, mask, scale, softcap, attn_len,
                       compute_dtype)
