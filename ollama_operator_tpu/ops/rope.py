"""Rotary position embeddings, with context-extension scaling.

One convention everywhere: **half-split (NeoX/HF) layout** — the head dim is
split into two halves rotated against each other. GGUF llama-family
checkpoints store weights for the *interleaved* convention; the transcoder
(gguf/transcode.py) permutes wq/wk rows at load time so this single
implementation is correct for every arch. phi-2 style partial rotary is
supported via ``rotary_dim < head_dim``.

Scaling: the reference serves long-context models through llama.cpp inside
the delegated ``ollama/ollama`` image (/root/reference/pkg/model/pod.go:11),
which honors the GGUF ``rope.scaling.*`` metadata (linear and YaRN) and the
per-frequency ``rope_freqs.weight`` factor tensor that llama3.1-family
conversions bake in. This module is the TPU-native equivalent: every scheme
reduces to a **static per-frequency rescale of inv_freq** (plus a scalar
cos/sin magnitude for YaRN's attention factor), computed in numpy at trace
time — zero per-step cost inside jit, and exactly one rope implementation
regardless of scheme.

Parity targets: transformers' ROPE_INIT_FUNCTIONS (linear / yarn / llama3),
which match llama.cpp's runtime math — verified in tests/test_rope_scaling.py.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=64)
def scaled_inv_freq(rotary_dim: int, theta: float, *,
                    scaling_type: str = "none", factor: float = 1.0,
                    orig_ctx: int = 0, low_freq_factor: float = 1.0,
                    high_freq_factor: float = 4.0, attn_factor: float = 0.0,
                    beta_fast: float = 32.0, beta_slow: float = 1.0,
                    freq_factors: Optional[Tuple[float, ...]] = None,
                    ) -> Tuple[Tuple[float, ...], float]:
    """The per-frequency rotation rates after context-extension scaling.

    Returns ``(inv_freq, mscale)`` — ``inv_freq`` a length rotary_dim//2
    tuple of f32 rates, ``mscale`` the scalar the YaRN scheme multiplies
    cos/sin by (1.0 for everything else). All inputs are static config
    fields, so the result is a trace-time constant (lru-cached: the decode
    loop re-traces per bucket).

    Schemes (factor > 1 extends context ``factor``-fold past ``orig_ctx``):

    - ``none``  — plain RoPE. A ``factor != 1`` is honored as linear for
      back-compat with the old bare-scalar config field.
    - ``linear`` — positions divided by ``factor`` (all frequencies).
    - ``yarn``  — NTK-by-parts: frequencies whose wavelength fits the
      original window are untouched, long wavelengths interpolate by
      ``factor``, with a linear ramp between the ``beta_fast``/``beta_slow``
      correction dims; cos/sin scale by ``attn_factor`` (default
      ``0.1·ln(factor)+1``).
    - ``llama3`` — low/high-frequency interpolation: wavelengths beyond
      ``orig_ctx/low_freq_factor`` divide by ``factor``, those inside
      ``orig_ctx/high_freq_factor`` are untouched, smooth blend between.
    - ``freq_factors`` (from a GGUF ``rope_freqs.weight`` tensor) divide
      inv_freq directly — llama3.1-family conversions pre-bake their
      scheme into this tensor, so when present it *is* the scaling and the
      metadata scheme is not applied on top (llama.cpp behavior).
    """
    half = rotary_dim // 2
    inv_freq = 1.0 / (theta ** (np.arange(half, dtype=np.float64) / half))
    mscale = 1.0

    if freq_factors is not None:
        ff = np.asarray(freq_factors, dtype=np.float64)
        assert ff.shape == (half,), (
            f"rope_freq_factors has {ff.shape[0]} entries; rotary_dim "
            f"{rotary_dim} needs {half}")
        inv_freq = inv_freq / ff
        if attn_factor > 0:
            # phi3-family longrope: the factor tensor rescales frequencies
            # AND cos/sin scale by the magnitude factor (transformers
            # Phi3LongRoPE; plain llama3.1 rope_freqs carry no attn_factor
            # so their mscale stays 1)
            mscale = attn_factor
    elif scaling_type == "linear" or (scaling_type == "none"
                                      and factor != 1.0):
        inv_freq = inv_freq / factor
    elif scaling_type == "llama3":
        assert orig_ctx > 0, "llama3 rope scaling needs rope_orig_ctx"
        low_wavelen = orig_ctx / low_freq_factor
        high_wavelen = orig_ctx / high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        scaled = np.where(wavelen > low_wavelen, inv_freq / factor, inv_freq)
        smooth = ((orig_ctx / wavelen - low_freq_factor)
                  / (high_freq_factor - low_freq_factor))
        blended = (1.0 - smooth) * scaled / factor + smooth * scaled
        medium = (wavelen >= high_wavelen) & (wavelen <= low_wavelen)
        inv_freq = np.where(medium, blended, scaled)
    elif scaling_type == "yarn":
        assert orig_ctx > 0, "yarn rope scaling needs rope_orig_ctx"

        def correction_dim(n_rot: float) -> float:
            return (rotary_dim
                    * math.log(orig_ctx / (n_rot * 2.0 * math.pi))
                    / (2.0 * math.log(theta)))

        low = max(math.floor(correction_dim(beta_fast)), 0)
        high = min(math.ceil(correction_dim(beta_slow)), rotary_dim - 1)
        if low == high:
            high = low + 0.001  # avoid a 0-width ramp
        ramp = np.clip((np.arange(half, dtype=np.float64) - low)
                       / (high - low), 0.0, 1.0)
        extrap = 1.0 - ramp          # 1 at high-freq dims: keep original
        inv_freq = (inv_freq / factor) * (1.0 - extrap) + inv_freq * extrap
        mscale = attn_factor if attn_factor > 0 else (
            0.1 * math.log(factor) + 1.0 if factor > 1.0 else 1.0)
    elif scaling_type != "none":
        raise ValueError(f"unknown rope scaling type {scaling_type!r}")

    return tuple(np.asarray(inv_freq, np.float32).tolist()), float(mscale)


def rope_angles(positions, rotary_dim: int, theta: float,
                scaling: float = 1.0, *, inv_freq=None, mscale: float = 1.0):
    """positions [..] int32 → (cos, sin) [.., rotary_dim//2] float32.

    The legacy form (``scaling`` = bare linear factor) stays for callers
    without a full config; cfg-aware paths use :func:`rope_angles_cfg`.
    """
    if inv_freq is None:
        half = rotary_dim // 2
        inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32)
                                    / half))
        pos = positions.astype(jnp.float32) / scaling
    else:
        inv_freq = jnp.asarray(inv_freq, jnp.float32)
        pos = positions.astype(jnp.float32)
    angles = pos[..., None] * inv_freq  # [.., half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if mscale != 1.0:
        cos, sin = cos * mscale, sin * mscale
    return cos, sin


def rope_angles_cfg(positions, cfg):
    """cfg-driven rope_angles: applies the model's full scaling scheme
    (ModelConfig.rope_scaling_type & friends, gguf/transcode.py)."""
    inv_freq, mscale = scaled_inv_freq(
        cfg.rotary_dim, cfg.rope_theta,
        scaling_type=cfg.rope_scaling_type, factor=cfg.rope_scaling,
        orig_ctx=cfg.rope_orig_ctx,
        low_freq_factor=cfg.rope_low_freq_factor,
        high_freq_factor=cfg.rope_high_freq_factor,
        attn_factor=cfg.rope_attn_factor,
        beta_fast=cfg.rope_yarn_beta_fast,
        beta_slow=cfg.rope_yarn_beta_slow,
        freq_factors=cfg.rope_freq_factors)
    return rope_angles(positions, cfg.rotary_dim, cfg.rope_theta,
                       inv_freq=inv_freq, mscale=mscale)


def apply_rope(x, cos, sin, rotary_dim: int):
    """x [B, T, H, head_dim]; cos/sin [B, T, rotary_dim//2].

    Rotates the first ``rotary_dim`` channels (half-split), passes the rest
    through unchanged.
    """
    half = rotary_dim // 2
    x_rot = x[..., :rotary_dim].astype(jnp.float32)
    x1 = x_rot[..., :half]
    x2 = x_rot[..., half:]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    out = jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)
    if rotary_dim == x.shape[-1]:
        return out
    return jnp.concatenate([out, x[..., rotary_dim:]], axis=-1)
