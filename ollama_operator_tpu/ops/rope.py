"""Rotary position embeddings.

One convention everywhere: **half-split (NeoX/HF) layout** — the head dim is
split into two halves rotated against each other. GGUF llama-family
checkpoints store weights for the *interleaved* convention; the transcoder
(gguf/transcode.py) permutes wq/wk rows at load time so this single
implementation is correct for every arch. phi-2 style partial rotary is
supported via ``rotary_dim < head_dim``.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(positions, rotary_dim: int, theta: float, scaling: float = 1.0):
    """positions [..] int32 → (cos, sin) [.., rotary_dim//2] float32."""
    half = rotary_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = positions.astype(jnp.float32) / scaling
    angles = pos[..., None] * inv_freq  # [.., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, rotary_dim: int):
    """x [B, T, H, head_dim]; cos/sin [B, T, rotary_dim//2].

    Rotates the first ``rotary_dim`` channels (half-split), passes the rest
    through unchanged.
    """
    half = rotary_dim // 2
    x_rot = x[..., :rotary_dim].astype(jnp.float32)
    x1 = x_rot[..., :half]
    x2 = x_rot[..., half:]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    out = jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)
    if rotary_dim == x.shape[-1]:
        return out
    return jnp.concatenate([out, x[..., rotary_dim:]], axis=-1)
