"""Token sampling — fully jittable, batched over slots.

Replaces llama.cpp's sampler chain (delegated by the reference via the
ollama image, /root/reference/pkg/model/pod.go:11) with a vectorised
implementation: every slot in the decode batch samples in one fused XLA
program, with per-slot parameters carried as arrays so heterogeneous
requests share one compiled decode step.

Supported (matching the Ollama API options surface): temperature, top_k,
top_p, min_p, repeat_penalty (over a token-count buffer), presence/frequency
penalty, per-slot PRNG seed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-slot sampling parameters, all shape [B] arrays (jit-friendly)."""

    temperature: jax.Array   # [B] f32; <=0 → greedy
    top_k: jax.Array         # [B] i32; <=0 → off
    top_p: jax.Array         # [B] f32; >=1 → off
    min_p: jax.Array         # [B] f32; <=0 → off
    repeat_penalty: jax.Array    # [B] f32; 1.0 → off
    presence_penalty: jax.Array  # [B] f32
    frequency_penalty: jax.Array  # [B] f32

    @staticmethod
    def make(B: int, temperature=0.8, top_k=40, top_p=0.9, min_p=0.0,
             repeat_penalty=1.1, presence_penalty=0.0, frequency_penalty=0.0):
        f = lambda v: jnp.full((B,), v, jnp.float32)
        return SamplingParams(
            temperature=f(temperature), top_k=jnp.full((B,), top_k, jnp.int32),
            top_p=f(top_p), min_p=f(min_p), repeat_penalty=f(repeat_penalty),
            presence_penalty=f(presence_penalty),
            frequency_penalty=f(frequency_penalty))


jax.tree_util.register_dataclass(
    SamplingParams,
    data_fields=["temperature", "top_k", "top_p", "min_p", "repeat_penalty",
                 "presence_penalty", "frequency_penalty"],
    meta_fields=[])


def apply_penalties(logits, token_counts, sp: SamplingParams):
    """logits [B, V] f32; token_counts [B, V] i32 (counts in the window)."""
    seen = token_counts > 0
    rp = sp.repeat_penalty[:, None]
    penalised = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalised, logits)
    logits = logits - sp.presence_penalty[:, None] * seen.astype(jnp.float32)
    logits = logits - sp.frequency_penalty[:, None] * token_counts.astype(
        jnp.float32)
    return logits


N_CANDIDATES = 1024


def sample(logits, token_counts, sp: SamplingParams, key,
           n_candidates: int = N_CANDIDATES):
    """logits [B, V] f32 → tokens [B] i32.

    Greedy where temperature <= 0, otherwise penalised + top-k/p/min-p
    filtered categorical sampling. ``key`` is either a single PRNG key
    (shared across the batch) or a [B] array of per-slot keys (each request
    carries its own seed, per the Ollama API `seed` option).

    The filters run in a compressed top-``n_candidates`` space: ONE
    ``lax.top_k`` replaces the two full [B, V] sorts the masks would
    otherwise need (a large share of the decode step at 50k+ vocabs), and
    since candidates come out sorted the top-p cumsum needs no further
    sort. ``top_k`` is effectively capped at n_candidates, and top-p mass
    beyond the top-1024 logits is treated as zero — both far outside any
    practical sampling configuration (Ollama defaults: top_k=40).
    """
    logits = apply_penalties(logits, token_counts, sp)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    B, V = logits.shape
    C = min(V, n_candidates)
    vals, cand = jax.lax.top_k(logits, C)           # [B, C], sorted desc
    t = jnp.maximum(sp.temperature, 1e-6)[:, None]
    scaled = vals / t

    # top-k: the k-th largest is simply column k-1 of the sorted values
    k = jnp.clip(sp.top_k, 1, C)
    kth = jnp.take_along_axis(scaled, (k - 1)[:, None], axis=-1)
    keep = scaled >= kth
    keep = jnp.where((sp.top_k > 0)[:, None], keep, True)
    scaled = jnp.where(keep, scaled, NEG_INF)

    # top-p over the (sorted) candidate probabilities
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < sp.top_p[:, None]        # always keeps the first
    keep = jnp.where((sp.top_p < 1.0)[:, None], keep, True)
    scaled = jnp.where(keep, scaled, NEG_INF)

    # min-p relative to the max candidate probability
    probs = jax.nn.softmax(scaled, axis=-1)
    keep = probs >= (sp.min_p[:, None] * probs[:, :1])
    keep = jnp.where((sp.min_p > 0.0)[:, None], keep, True)
    scaled = jnp.where(keep, scaled, NEG_INF)

    if getattr(key, "ndim", 0) >= 1:  # per-slot keys
        ci = jax.vmap(jax.random.categorical)(key, scaled)
    else:
        ci = jax.random.categorical(key, scaled, axis=-1)
    sampled = jnp.take_along_axis(cand, ci[:, None], axis=-1)[:, 0]
    sampled = sampled.astype(jnp.int32)

    return jnp.where(sp.temperature <= 0.0, greedy, sampled)
