"""Token sampling — fully jittable, batched over slots.

Replaces llama.cpp's sampler chain (delegated by the reference via the
ollama image, /root/reference/pkg/model/pod.go:11) with a vectorised
implementation: every slot in the decode batch samples in one fused XLA
program, with per-slot parameters carried as arrays so heterogeneous
requests share one compiled decode step.

Supported (matching the Ollama API options surface): temperature, top_k,
top_p, min_p, repeat_penalty (over a token-count buffer), presence/frequency
penalty, per-slot PRNG seed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-slot sampling parameters, all shape [B] arrays (jit-friendly)."""

    temperature: jax.Array   # [B] f32; <=0 → greedy
    top_k: jax.Array         # [B] i32; <=0 → off
    top_p: jax.Array         # [B] f32; >=1 → off
    min_p: jax.Array         # [B] f32; <=0 → off
    repeat_penalty: jax.Array    # [B] f32; 1.0 → off
    presence_penalty: jax.Array  # [B] f32
    frequency_penalty: jax.Array  # [B] f32

    @staticmethod
    def make(B: int, temperature=0.8, top_k=40, top_p=0.9, min_p=0.0,
             repeat_penalty=1.1, presence_penalty=0.0, frequency_penalty=0.0):
        f = lambda v: jnp.full((B,), v, jnp.float32)
        return SamplingParams(
            temperature=f(temperature), top_k=jnp.full((B,), top_k, jnp.int32),
            top_p=f(top_p), min_p=f(min_p), repeat_penalty=f(repeat_penalty),
            presence_penalty=f(presence_penalty),
            frequency_penalty=f(frequency_penalty))


jax.tree_util.register_dataclass(
    SamplingParams,
    data_fields=["temperature", "top_k", "top_p", "min_p", "repeat_penalty",
                 "presence_penalty", "frequency_penalty"],
    meta_fields=[])


def apply_penalties(logits, token_counts, sp: SamplingParams):
    """logits [B, V] f32; token_counts [B, V] i32 (counts in the window)."""
    seen = token_counts > 0
    rp = sp.repeat_penalty[:, None]
    penalised = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalised, logits)
    logits = logits - sp.presence_penalty[:, None] * seen.astype(jnp.float32)
    logits = logits - sp.frequency_penalty[:, None] * token_counts.astype(
        jnp.float32)
    return logits


def _mask_top_k(logits, top_k):
    """Vectorised top-k: keep logits >= the k-th largest (per row)."""
    V = logits.shape[-1]
    sorted_desc = -jnp.sort(-logits, axis=-1)           # [B, V]
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    keep = logits >= kth
    keep = jnp.where((top_k > 0)[:, None], keep, True)
    return jnp.where(keep, logits, NEG_INF)


def _mask_top_p(logits, top_p):
    """Nucleus sampling mask over softmax probabilities."""
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds top_p (always keep the first)
    keep_sorted = (cum - probs) < top_p[:, None]
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(logits.shape[0])[:, None], sort_idx].set(keep_sorted)
    keep = jnp.where((top_p < 1.0)[:, None], keep, True)
    return jnp.where(keep, logits, NEG_INF)


def _mask_min_p(logits, min_p):
    probs = jax.nn.softmax(logits, axis=-1)
    pmax = jnp.max(probs, axis=-1, keepdims=True)
    keep = probs >= (min_p[:, None] * pmax)
    keep = jnp.where((min_p > 0.0)[:, None], keep, True)
    return jnp.where(keep, logits, NEG_INF)


def sample(logits, token_counts, sp: SamplingParams, key):
    """logits [B, V] f32 → tokens [B] i32.

    Greedy where temperature <= 0, otherwise penalised + top-k/p/min-p
    filtered categorical sampling. ``key`` is either a single PRNG key
    (shared across the batch) or a [B] array of per-slot keys (each request
    carries its own seed, per the Ollama API `seed` option).
    """
    logits = apply_penalties(logits, token_counts, sp)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    t = jnp.maximum(sp.temperature, 1e-6)[:, None]
    scaled = logits / t
    scaled = _mask_top_k(scaled, sp.top_k)
    scaled = _mask_top_p(scaled, sp.top_p)
    scaled = _mask_min_p(scaled, sp.min_p)
    if getattr(key, "ndim", 0) >= 1:  # per-slot keys
        sampled = jax.vmap(jax.random.categorical)(key, scaled)
    else:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    sampled = sampled.astype(jnp.int32)

    return jnp.where(sp.temperature <= 0.0, greedy, sampled)
