"""Token sampling — fully jittable, batched over slots.

Replaces llama.cpp's sampler chain (delegated by the reference via the
ollama image, /root/reference/pkg/model/pod.go:11) with a vectorised
implementation: every slot in the decode batch samples in one fused XLA
program, with per-slot parameters carried as arrays so heterogeneous
requests share one compiled decode step.

Supported (matching the Ollama API options surface): temperature, top_k,
top_p, min_p, typical_p, repeat_penalty (over a token-count buffer),
presence/frequency penalty, mirostat v1/v2 (per-slot ``mu`` state carried
by the engine), per-slot PRNG seed.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-slot sampling parameters, all shape [B] arrays (jit-friendly)."""

    temperature: jax.Array   # [B] f32; <=0 → greedy
    top_k: jax.Array         # [B] i32; <=0 → off
    top_p: jax.Array         # [B] f32; >=1 → off
    min_p: jax.Array         # [B] f32; <=0 → off
    typical_p: jax.Array     # [B] f32; >=1 → off; <=0 → keep
                             #   only the most-typical token
    repeat_penalty: jax.Array    # [B] f32; 1.0 → off
    presence_penalty: jax.Array  # [B] f32
    frequency_penalty: jax.Array  # [B] f32
    mirostat: jax.Array      # [B] i32; 0 off, 1/2 → replaces the filters
    mirostat_tau: jax.Array  # [B] f32 target surprise (bits/token)
    mirostat_eta: jax.Array  # [B] f32 learning rate for mu

    @staticmethod
    def make(B: int, temperature=0.8, top_k=40, top_p=0.9, min_p=0.0,
             typical_p=1.0, repeat_penalty=1.1, presence_penalty=0.0,
             frequency_penalty=0.0, mirostat=0, mirostat_tau=5.0,
             mirostat_eta=0.1):
        f = lambda v: jnp.full((B,), v, jnp.float32)
        return SamplingParams(
            temperature=f(temperature), top_k=jnp.full((B,), top_k, jnp.int32),
            top_p=f(top_p), min_p=f(min_p), typical_p=f(typical_p),
            repeat_penalty=f(repeat_penalty),
            presence_penalty=f(presence_penalty),
            frequency_penalty=f(frequency_penalty),
            mirostat=jnp.full((B,), mirostat, jnp.int32),
            mirostat_tau=f(mirostat_tau), mirostat_eta=f(mirostat_eta))


jax.tree_util.register_dataclass(
    SamplingParams,
    data_fields=["temperature", "top_k", "top_p", "min_p", "typical_p",
                 "repeat_penalty", "presence_penalty", "frequency_penalty",
                 "mirostat", "mirostat_tau", "mirostat_eta"],
    meta_fields=[])


def apply_penalties(logits, token_counts, sp: SamplingParams):
    """logits [B, V] f32; token_counts [B, V] i32 (counts in the window)."""
    seen = token_counts > 0
    rp = sp.repeat_penalty[:, None]
    penalised = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen, penalised, logits)
    logits = logits - sp.presence_penalty[:, None] * seen.astype(jnp.float32)
    logits = logits - sp.frequency_penalty[:, None] * token_counts.astype(
        jnp.float32)
    return logits


N_CANDIDATES = 1024


_LN2 = 0.6931471805599453
_MIROSTAT_M = 100   # v1's zipf-fit window (llama.cpp default)


def sample(logits, token_counts, sp: SamplingParams, key, mu=None,
           n_candidates: int = N_CANDIDATES):
    """logits [B, V] f32 → tokens [B] i32, or (tokens, mu') when ``mu``
    ([B] f32, the mirostat surprise-budget state) is given.

    Greedy where temperature <= 0, otherwise penalised + top-k/typical/
    top-p/min-p filtered categorical sampling. Slots with mirostat 1/2
    replace the static filters with the adaptive surprise truncation
    (llama.cpp's sampler chain does the same: penalties → temp →
    mirostat); their ``mu`` entries update per sampled token, everyone
    else's pass through unchanged. Callers that never serve mirostat may
    omit ``mu`` and get the plain token array. ``key`` is either a single
    PRNG key (shared across the batch) or a [B] array of per-slot keys
    (each request carries its own seed, per the Ollama API `seed` option).

    The filters run in a compressed top-``n_candidates`` space: ONE
    ``lax.top_k`` replaces the full [B, V] sorts the masks would
    otherwise need (a large share of the decode step at 50k+ vocabs), and
    since candidates come out sorted the top-p cumsum needs no further
    sort (typical_p re-orders by entropy deviation — its argsort runs
    over [B, C], not [B, V]). ``top_k`` is effectively capped at
    n_candidates, and top-p/typical mass beyond the top-1024 logits is
    treated as zero — both far outside any practical sampling
    configuration (Ollama defaults: top_k=40).
    """
    logits = apply_penalties(logits, token_counts, sp)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    B, V = logits.shape
    C = min(V, n_candidates)
    vals, cand = jax.lax.top_k(logits, C)           # [B, C], sorted desc
    t = jnp.maximum(sp.temperature, 1e-6)[:, None]
    scaled = vals / t

    # The static filters (top-k/typical/top-p/min-p) all evaluate at T=1:
    # llama.cpp's chain runs them BEFORE temperature (top_k → typ_p →
    # top_p → min_p → temp), so the kept set must not depend on the
    # temperature — only the final categorical draw does. ``filt`` is the
    # T=1 view carrying the accumulated mask; temperature applies when
    # the mask transfers onto ``scaled`` below.

    # top-k: the k-th largest is simply column k-1 of the sorted values
    k = jnp.clip(sp.top_k, 1, C)
    kth = jnp.take_along_axis(vals, (k - 1)[:, None], axis=-1)
    keep = vals >= kth
    keep = jnp.where((sp.top_k > 0)[:, None], keep, True)
    filt = jnp.where(keep, vals, NEG_INF)

    # locally-typical: keep the candidates whose surprise deviates least
    # from the distribution's entropy, up to typical_p cumulative mass
    # (Meister et al.; llama.cpp llama_sampler_typical). Deviation order
    # is not the sorted-logit order, so this is the one filter that pays
    # its own [B, C] argsort. The first deviation-ordered token is always
    # kept (min_keep=1): typical_p <= 0 degrades to "most typical token
    # only", exactly llama.cpp's limit behaviour, not a blank
    # distribution.
    probs = jax.nn.softmax(filt, axis=-1)
    nlp = -jnp.log(jnp.maximum(probs, 1e-30))       # nats
    ent = jnp.sum(jnp.where(probs > 0, probs * nlp, 0.0), axis=-1,
                  keepdims=True)
    order = jnp.argsort(jnp.abs(nlp - ent), axis=-1)
    p_ord = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(p_ord, axis=-1)
    keep_ord = (cum - p_ord) < sp.typical_p[:, None]
    keep_ord = keep_ord.at[:, 0].set(True)          # min_keep = 1
    bi = jnp.arange(B)[:, None]
    keep = jnp.zeros((B, C), bool).at[bi, order].set(keep_ord)
    keep = jnp.where((sp.typical_p < 1.0)[:, None], keep, True)
    filt = jnp.where(keep, filt, NEG_INF)

    # top-p over the (sorted) candidate probabilities
    probs = jax.nn.softmax(filt, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < sp.top_p[:, None]        # always keeps the first
    keep = jnp.where((sp.top_p < 1.0)[:, None], keep, True)
    filt = jnp.where(keep, filt, NEG_INF)

    # min-p relative to the max SURVIVING candidate probability (not
    # column 0 — typical_p may have dropped the global argmax)
    probs = jax.nn.softmax(filt, axis=-1)
    keep = probs >= (sp.min_p[:, None]
                     * jnp.max(probs, axis=-1, keepdims=True))
    keep = jnp.where((sp.min_p > 0.0)[:, None], keep, True)
    filt = jnp.where(keep, filt, NEG_INF)

    # transfer the T=1 mask onto the temperature-scaled logits for the
    # final draw
    filt = jnp.where(filt > NEG_INF / 2, scaled, NEG_INF)

    if mu is not None:
        # mirostat truncation over the UNfiltered temp-scaled candidates
        # (the adaptive cut replaces the static filters). v2 drops
        # candidates whose surprise (-log2 p) exceeds mu; v1 derives a
        # top-k cut from a zipf-exponent fit over the head of the
        # distribution (llama.cpp llama_sampler_mirostat{,_v2}).
        pm = jax.nn.softmax(scaled, axis=-1)
        surprise = -jnp.log(jnp.maximum(pm, 1e-30)) / _LN2   # bits
        m = min(_MIROSTAT_M, C)
        t_i = jnp.log(jnp.arange(2, m + 1) / jnp.arange(1, m))   # [m-1]
        b_i = jnp.log(jnp.maximum(pm[:, :m - 1], 1e-30)
                      / jnp.maximum(pm[:, 1:m], 1e-30))          # [B, m-1]
        s_hat = jnp.sum(t_i * b_i, axis=-1) / jnp.sum(t_i * t_i)  # [B]
        eps = jnp.maximum(s_hat - 1.0, 1e-5)
        k1 = ((eps * jnp.exp2(jnp.minimum(mu, 60.0)))
              / (1.0 - float(V) ** (-eps))) ** (1.0 / jnp.maximum(s_hat,
                                                                  1e-5))
        k1 = jnp.clip(jnp.nan_to_num(k1, nan=float(C)), 1.0, float(C))
        col = jnp.arange(C)[None, :]
        keep1 = col < k1[:, None]
        keep2 = surprise <= mu[:, None]
        keep_m = jnp.where((sp.mirostat == 2)[:, None], keep2, keep1)
        keep_m = keep_m.at[:, 0].set(True)          # min_keep = 1
        use_m = (sp.mirostat > 0)[:, None]
        filt = jnp.where(use_m, jnp.where(keep_m, scaled, NEG_INF), filt)

    if getattr(key, "ndim", 0) >= 1:  # per-slot keys
        ci = jax.vmap(jax.random.categorical)(key, filt)
    else:
        ci = jax.random.categorical(key, filt, axis=-1)
    sampled = jnp.take_along_axis(cand, ci[:, None], axis=-1)[:, 0]
    sampled = sampled.astype(jnp.int32)
    toks = jnp.where(sp.temperature <= 0.0, greedy, sampled)
    if mu is None:
        return toks

    # observed surprise of the sampled token in the truncated,
    # re-normalised distribution drives the mu update (llama.cpp measures
    # p from the post-truncation softmax the same way)
    pf = jax.nn.softmax(filt, axis=-1)
    p_sel = jnp.take_along_axis(pf, ci[:, None], axis=-1)[:, 0]
    e_obs = -jnp.log(jnp.maximum(p_sel, 1e-30)) / _LN2
    mu2 = mu - sp.mirostat_eta * (e_obs - sp.mirostat_tau)
    live = (sp.mirostat > 0) & (sp.temperature > 0.0)
    return toks, jnp.where(live, mu2, mu)


def spec_accept(drafts, greedy, ok, sampled, vocab_size):
    """Vectorized accept/rollback for speculative verification.

    ``drafts`` [B, k] are the proposed continuations, ``greedy`` [B, k+1]
    the verify pass's argmax at each scored position, ``ok`` [B] bool
    marks slots where raw-argmax acceptance is exact (greedy, neutral
    penalties, unconstrained, active), and ``sampled`` [B] is the
    decode-identical single token for every other slot.

    Returns ``(n_acc, out)``: per-slot accepted-draft counts [B] and the
    emission matrix [B, k+1] — row b holds its accepted draft prefix,
    then the bonus token (``greedy[b, n_acc]`` for accepting slots,
    ``sampled[b]`` otherwise), then ``vocab_size`` sentinel padding.
    Rejection is thereby only a mask: positions at or beyond the first
    draft/argmax mismatch pad to the sentinel and the caller rolls slot
    lengths forward by the accepted count alone — no second dispatch, no
    KV copy (rejected positions sit above the advanced length and are
    never attended)."""
    B, k = drafts.shape
    match = (drafts == greedy[:, :-1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    n_acc = jnp.where(ok, n_acc, 0)
    bi = jnp.arange(B)
    bonus = jnp.where(ok, greedy[bi, n_acc], sampled)
    t_idx = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    dpad = jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
    out = jnp.where(t_idx < n_acc[:, None], dpad, jnp.int32(vocab_size))
    out = out.at[bi, n_acc].set(bonus)
    return n_acc, out
