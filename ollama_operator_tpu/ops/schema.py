"""JSON-Schema-constrained decoding (Ollama ``format: {…}``).

Upstream ollama compiles a JSON schema to a GBNF grammar inside llama.cpp
(/root/reference/pkg/model/pod.go:11 delegates it). The TPU-native design
keeps sampling on device like the generic JSON mode (ops/constrain.py):
the host advances a byte automaton and uploads one packed mask per step.

The automaton is a **skeleton machine**: the schema compiles to a node
tree —

  ("lit",  bytes)            fixed structural bytes ('{"name":', ',', '}')
  ("leaf", kind)             a typed value hole, validated by the generic
                             byte PDA with kind restrictions (string /
                             number / integer / boolean / null / any)
  ("seq",  (children, ...))  object skeleton: literals + holes in the
                             schema's property order
  ("enum", (alts, ...))      one of several literal JSON values
  ("arr",  item, min1)       '[' item (',' item)* ']' (or empty)

and the machine state is a stack of (node, position) frames — a
recursive-descent acceptor driven one byte at a time, so token pieces
that cross hole/literal boundaries are handled exactly.

Unsupported schema constructs (anyOf, patternProperties, additional
properties, numeric ranges, …) make ``compile_schema`` return None and
the caller falls back to generic JSON mode with a warning — never a
silently wrong constraint.

Masks are cached per (schema, machine state) on the compiled Schema
object, which the server shares across requests with the same schema.
A 256-bucket first-byte index keeps mask fills cheap for the (many)
structural states whose next byte is nearly determined; hole-interior
states cache by the PDA's abstract stack-suffix key, so each DISTINCT
abstract state pays one pure-Python vocab sweep (amortised across the
response and across requests sharing the schema). Porting the skeleton
machine to native/grammar.cpp would remove that first-sweep cost; until
then the generic format:"json" path remains the native-accelerated one.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from threading import Lock
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .constrain import (INITIAL_STATE, M_AFTER, TokenTable,
                        advance_byte, eos_ok)

Kind = str
Node = Tuple  # see module docstring

_START_BYTES = {
    "string": b'"',
    "number": b"-0123456789",
    "integer": b"-0123456789",
    "boolean": b"tf",
    "null": b"n",
    "any": None,               # unrestricted
}
_INT_FORBIDDEN = frozenset(b".eE")


# ---------------------------------------------------------------------------
# schema → node tree
# ---------------------------------------------------------------------------

# annotation-only keywords that never change validation
_BENIGN_KEYS = {"title", "description", "default", "examples", "$schema",
                "$id", "$comment", "deprecated", "readOnly", "writeOnly"}


def _only_keys(schema: dict, allowed: frozenset) -> bool:
    """WHITELIST check: any keyword we don't implement (exclusiveMinimum,
    multipleOf, prefixItems, …) must route to the generic-JSON fallback —
    compiling past it would silently under-constrain."""
    return not (set(schema) - allowed - _BENIGN_KEYS)


def _compile_node(schema) -> Optional[Node]:
    if not isinstance(schema, dict):
        return None
    if "enum" in schema:
        if not _only_keys(schema, frozenset({"enum", "type"})):
            return None
        try:
            alts = tuple(json.dumps(v, separators=(",", ":"),
                                    ensure_ascii=False).encode()
                         for v in schema["enum"])
        except (TypeError, ValueError):
            return None
        return ("enum", alts) if alts else None
    if "const" in schema:
        if not _only_keys(schema, frozenset({"const", "type"})):
            return None
        try:
            return ("enum", (json.dumps(schema["const"],
                                        separators=(",", ":"),
                                        ensure_ascii=False).encode(),))
        except (TypeError, ValueError):
            return None
    t = schema.get("type")
    if isinstance(t, list):
        return None
    if t == "object" or (t is None and "properties" in schema):
        if not _only_keys(schema, frozenset(
                {"type", "properties", "required", "additionalProperties"})):
            return None
        props = schema.get("properties")
        if not isinstance(props, dict) or not props:
            return None
        if schema.get("additionalProperties") not in (None, False):
            return None
        req = schema.get("required")
        if req is not None and set(req) != set(props):
            # optional properties would need alternation; keep v1 exact
            return None
        parts: List[Node] = []
        for i, (key, sub) in enumerate(props.items()):
            child = _compile_node(sub)
            if child is None:
                return None
            prefix = ("{" if i == 0 else ",") + json.dumps(key) + ":"
            parts.append(("lit", prefix.encode()))
            parts.append(child)
        parts.append(("lit", b"}"))
        return ("seq", tuple(parts))
    if t == "array":
        if not _only_keys(schema, frozenset({"type", "items", "minItems"})):
            return None
        items = schema.get("items")
        child = _compile_node(items) if items is not None else ("leaf", "any")
        if child is None:
            return None
        min_items = schema.get("minItems", 0)
        # (maxItems never reaches here — it fails the _only_keys whitelist
        # above and falls back to the generic JSON PDA)
        if min_items not in (0, 1):
            return None
        return ("arr", child, int(min_items))
    if not _only_keys(schema, frozenset({"type"})):
        return None
    if t in ("string", "number", "integer", "boolean", "null"):
        return ("leaf", t)
    if t is None:
        return ("leaf", "any")
    return None


def compile_schema(schema) -> Optional["Schema"]:
    """Schema dict → Schema machine, or None when a construct is outside
    the supported subset (caller falls back to generic JSON mode)."""
    root = _compile_node(schema)
    if root is None:
        return None
    return Schema(root)


# ---------------------------------------------------------------------------
# the skeleton machine
# ---------------------------------------------------------------------------

def _init_sub(node: Node):
    tag = node[0]
    if tag == "lit":
        return 0
    if tag == "leaf":
        return INITIAL_STATE
    if tag == "enum":
        return (0, tuple(range(len(node[1]))), False)
    if tag == "arr":
        return 0
    raise AssertionError(tag)


def _push(stack: list, node: Node):
    """Push ``node``, descending into seq heads so the top frame is
    always an active byte consumer."""
    while node[0] == "seq":
        stack.append((node, 0))
        node = node[1][0]
    stack.append((node, _init_sub(node)))


def _completed_child(stack: list):
    """Top frame finished and was popped; advance ancestors (possibly
    completing them too) and push the next consumer if any."""
    while stack:
        node, sub = stack[-1]
        tag = node[0]
        if tag == "seq":
            nxt = sub + 1
            if nxt == len(node[1]):
                stack.pop()
                continue
            stack[-1] = (node, nxt)
            _push(stack, node[1][nxt])
            return
        if tag == "arr":
            stack[-1] = (node, 3)   # after an item: ',' or ']'
            return
        raise AssertionError(tag)


def machine_init(root: Node) -> tuple:
    stack: list = []
    _push(stack, root)
    return tuple(stack)


def machine_advance(root: Node, state: tuple, b: int) -> Optional[tuple]:
    """One byte through the skeleton machine; None = rejected. ``state``
    is an immutable tuple of (node, sub) frames."""
    stack = list(state)
    for _ in range(128):                    # pop-chain guard
        if not stack:
            return None                     # schema complete: EOS only
        node, sub = stack[-1]
        tag = node[0]
        if tag == "lit":
            data = node[1]
            if data[sub] != b:
                return None
            sub += 1
            if sub == len(data):
                stack.pop()
                _completed_child(stack)
            else:
                stack[-1] = (node, sub)
            return tuple(stack)
        if tag == "leaf":
            kind = node[1]
            allowed = True
            if sub == INITIAL_STATE:
                start = _START_BYTES[kind]
                allowed = start is None or b in start
            if allowed and kind == "integer" and b in _INT_FORBIDDEN:
                allowed = False
            ns = advance_byte(sub, b) if allowed else None
            if ns is not None:
                if len(ns) == 4 and ns[0] == M_AFTER:
                    stack.pop()             # value definitely closed
                    _completed_child(stack)
                else:
                    stack[-1] = (node, ns)
                return tuple(stack)
            if eos_ok(sub):                 # lazy close (numbers)
                stack.pop()
                _completed_child(stack)
                continue                    # redispatch b
            return None
        if tag == "enum":
            off, viable, done = sub
            nv = tuple(i for i in viable if off < len(node[1][i])
                       and node[1][i][off] == b)
            if nv:
                off += 1
                fin = any(len(node[1][i]) == off for i in nv)
                ext = tuple(i for i in nv if len(node[1][i]) > off)
                if fin and not ext:
                    stack.pop()
                    _completed_child(stack)
                else:
                    stack[-1] = (node, (off, ext or nv, fin))
                return tuple(stack)
            if done:                        # a full alt matched earlier
                stack.pop()
                _completed_child(stack)
                continue
            return None
        if tag == "arr":
            if sub == 0:
                if b != ord("["):
                    return None
                stack[-1] = (node, 1)
                return tuple(stack)
            if sub == 1:                    # first item or ']'
                if b == ord("]") and node[2] == 0:
                    stack.pop()
                    _completed_child(stack)
                    return tuple(stack)
                stack[-1] = (node, 2)
                _push(stack, node[1])
                continue                    # redispatch into the item
            if sub == 3:                    # after an item
                if b == ord("]"):
                    stack.pop()
                    _completed_child(stack)
                    return tuple(stack)
                if b == ord(","):
                    stack[-1] = (node, 2)
                    _push(stack, node[1])
                    return tuple(stack)
                return None
            return None                     # sub == 2 never sits on top
        raise AssertionError(tag)
    return None


def machine_eos_ok(state: tuple) -> bool:
    """EOS legal iff every open frame can close without more bytes."""
    stack = list(state)
    while stack:
        node, sub = stack[-1]
        tag = node[0]
        if tag == "leaf" and eos_ok(sub):
            stack.pop()
            # complete ancestors WITHOUT pushing new consumers
            while stack:
                pn, ps = stack[-1]
                if pn[0] == "seq" and ps + 1 == len(pn[1]):
                    stack.pop()
                    continue
                return False
            return True
        if tag == "enum" and sub[2]:
            stack.pop()
            while stack:
                pn, ps = stack[-1]
                if pn[0] == "seq" and ps + 1 == len(pn[1]):
                    stack.pop()
                    continue
                return False
            return True
        return False
    return True


# ---------------------------------------------------------------------------
# masks
# ---------------------------------------------------------------------------

class Schema:
    """Compiled schema + per-state mask cache (shared across requests)."""

    def __init__(self, root: Node):
        self.root = root
        self._masks: OrderedDict = OrderedDict()
        self._lock = Lock()
        self._cap = 8192

    def _state_key(self, table: TokenTable, state: tuple):
        # leaf PDA states use constrain.py's abstract stack-suffix key: a
        # token of max_len bytes can pop at most max_len containers, so
        # deeper "any"-hole nesting cannot change any token's acceptance
        # — without this, '[[[…' would mint (and full-vocab-sweep) a
        # fresh state per depth
        def sub_key(n, s):
            if n[0] == "leaf" and isinstance(s, bytes):
                return s[:4] + s[4:][-table.max_len:]
            return s
        return (id(table),) + tuple((id(n), sub_key(n, s))
                                    for n, s in state)

    def mask_for(self, table: TokenTable, state: tuple) -> np.ndarray:
        key = self._state_key(table, state)
        with self._lock:
            m = self._masks.get(key)
            if m is not None:
                self._masks.move_to_end(key)
                return m
        first = bytes(b for b in range(256)
                      if machine_advance(self.root, state, b) is not None)
        idx = _byte_index(table)
        if len(first) <= 32:
            cand: List[int] = []
            for b in first:
                cand.extend(idx[b])
        else:
            cand = range(table.n_vocab)
        mask = np.zeros(table.n_words, np.uint32)
        for tid in cand:
            piece = table.pieces[tid]
            if not piece:
                continue
            st = state
            for b in piece:
                st = machine_advance(self.root, st, b)
                if st is None:
                    break
            if st is not None:
                mask[tid >> 5] |= np.uint32(1 << (tid & 31))
        if machine_eos_ok(state):
            if not first:
                mask = table._eog_packed.copy()   # nothing else is legal
            else:
                mask = mask | table._eog_packed
        with self._lock:
            self._masks[key] = mask
            self._masks.move_to_end(key)
            while len(self._masks) > self._cap:
                self._masks.popitem(last=False)
        return mask


_byte_index_lock = Lock()


def _byte_index(table: TokenTable) -> List[List[int]]:
    """First-byte → token ids, built once and stored ON the table (its
    lifetime owns the index; an id()-keyed global would leak across
    model unloads and could serve a recycled address the wrong vocab)."""
    idx = getattr(table, "_schema_byte_index", None)
    if idx is None:
        with _byte_index_lock:
            idx = getattr(table, "_schema_byte_index", None)
            if idx is None:
                idx = [[] for _ in range(256)]
                for tid, piece in enumerate(table.pieces):
                    if piece:
                        idx[piece[0]].append(tid)
                table._schema_byte_index = idx
    return idx


class SchemaConstraint:
    """Per-request schema state; same interface as JsonConstraint."""

    def __init__(self, schema: Schema, table: TokenTable):
        self.schema = schema
        self.table = table
        self.state: Optional[tuple] = machine_init(schema.root)

    @classmethod
    def for_tokenizer(cls, schema: Schema, tok) -> "SchemaConstraint":
        return cls(schema, TokenTable.for_tokenizer(tok))

    def mask_row(self) -> np.ndarray:
        assert self.state is not None, "constraint already dead"
        return self.schema.mask_for(self.table, self.state)

    def advance(self, tid: int) -> bool:
        if self.state is None:
            return False
        piece = (self.table.pieces[tid]
                 if 0 <= tid < self.table.n_vocab else b"")
        if not piece:
            return False
        st = self.state
        for b in piece:
            st = machine_advance(self.schema.root, st, b)
            if st is None:
                break
        self.state = st
        return st is not None

    @property
    def done(self) -> bool:
        return self.state is not None and machine_eos_ok(self.state)
