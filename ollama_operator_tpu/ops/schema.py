"""JSON-Schema-constrained decoding (Ollama ``format: {…}``).

Upstream ollama compiles a JSON schema to a GBNF grammar inside llama.cpp
(/root/reference/pkg/model/pod.go:11 delegates it). The TPU-native design
keeps sampling on device like the generic JSON mode (ops/constrain.py):
the host advances a byte automaton and uploads one packed mask per step.

The automaton is a **skeleton machine**: the schema compiles to a node
tree —

  ("lit",  bytes)            fixed structural bytes ('{"name":', ',', '}')
  ("leaf", kind)             a typed value hole, validated by the generic
                             byte PDA with kind restrictions (string /
                             number / integer / boolean / null / any)
  ("seq",  (children, ...))  object skeleton: literals + holes in the
                             schema's property order
  ("enum", (alts, ...))      one of several literal JSON values
  ("arr",  item, min1)       '[' item (',' item)* ']' (or empty)
  ("alt",  (children, ...))  anyOf/oneOf alternation — expanded at push
                             time into one NFA branch per child
  ("irange", lo, hi)         integer hole with bounds: a digit-count DFA
                             decides which prefixes can still land in
                             [lo, hi] (None = unbounded side)

and the machine state is a SET of stacks of (node, position) frames — a
recursive-descent acceptor driven one byte at a time (alternation makes
it an NFA; branches prune as bytes disambiguate), so token pieces that
cross hole/literal boundaries are handled exactly.

Unsupported schema constructs (patternProperties, additionalProperties
schemas, string length/pattern, float ranges, multipleOf, …) make
``compile_schema`` return None and the caller falls back to generic JSON
mode with a warning — never a silently wrong constraint.

Masks are cached per (schema, machine state) on the compiled Schema
object, which the server shares across requests with the same schema.
A 256-bucket first-byte index keeps mask fills cheap for the (many)
structural states whose next byte is nearly determined; hole-interior
states cache by the PDA's abstract stack-suffix key, so each DISTINCT
abstract state pays one pure-Python vocab sweep (amortised across the
response and across requests sharing the schema). Porting the skeleton
machine to native/grammar.cpp would remove that first-sweep cost; until
then the generic format:"json" path remains the native-accelerated one.
"""

from __future__ import annotations

import json
from collections import OrderedDict
from threading import Lock
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .constrain import (INITIAL_STATE, M_AFTER, TokenTable,
                        advance_byte, eos_ok)

Kind = str
Node = Tuple  # see module docstring

_START_BYTES = {
    "string": b'"',
    "number": b"-0123456789",
    "integer": b"-0123456789",
    "boolean": b"tf",
    "null": b"n",
    "any": None,               # unrestricted
}
_INT_FORBIDDEN = frozenset(b".eE")


# ---------------------------------------------------------------------------
# schema → node tree
# ---------------------------------------------------------------------------

# annotation-only keywords that never change validation
_BENIGN_KEYS = {"title", "description", "default", "examples", "$schema",
                "$id", "$comment", "deprecated", "readOnly", "writeOnly"}


def _only_keys(schema: dict, allowed: frozenset) -> bool:
    """WHITELIST check: any keyword we don't implement (exclusiveMinimum,
    multipleOf, prefixItems, …) must route to the generic-JSON fallback —
    compiling past it would silently under-constrain."""
    return not (set(schema) - allowed - _BENIGN_KEYS)


def _compile_node(schema) -> Optional[Node]:
    if not isinstance(schema, dict):
        return None
    if "anyOf" in schema or "oneOf" in schema:
        # oneOf's exclusivity is unenforceable token-by-token (a prefix can
        # be extended into several alternatives); constraining to the anyOf
        # union is the sound over-approximation every grammar sampler makes
        key = "anyOf" if "anyOf" in schema else "oneOf"
        if not _only_keys(schema, frozenset({key})):
            return None
        alts = schema[key]
        if not isinstance(alts, list) or not alts:
            return None
        children = tuple(_compile_node(a) for a in alts)
        if any(c is None for c in children):
            return None
        return ("alt", children)
    if "enum" in schema:
        if not _only_keys(schema, frozenset({"enum", "type"})):
            return None
        try:
            alts = tuple(json.dumps(v, separators=(",", ":"),
                                    ensure_ascii=False).encode()
                         for v in schema["enum"])
        except (TypeError, ValueError):
            return None
        return ("enum", alts) if alts else None
    if "const" in schema:
        if not _only_keys(schema, frozenset({"const", "type"})):
            return None
        try:
            return ("enum", (json.dumps(schema["const"],
                                        separators=(",", ":"),
                                        ensure_ascii=False).encode(),))
        except (TypeError, ValueError):
            return None
    t = schema.get("type")
    if isinstance(t, list):
        return None
    if t == "object" or (t is None and "properties" in schema):
        if not _only_keys(schema, frozenset(
                {"type", "properties", "required", "additionalProperties"})):
            return None
        props = schema.get("properties")
        if not isinstance(props, dict) or not props:
            return None
        if schema.get("additionalProperties") not in (None, False):
            return None
        req = schema.get("required")
        if req is not None and set(req) != set(props):
            # optional properties would need alternation; keep v1 exact
            return None
        parts: List[Node] = []
        for i, (key, sub) in enumerate(props.items()):
            child = _compile_node(sub)
            if child is None:
                return None
            prefix = ("{" if i == 0 else ",") + json.dumps(key) + ":"
            parts.append(("lit", prefix.encode()))
            parts.append(child)
        parts.append(("lit", b"}"))
        return ("seq", tuple(parts))
    if t == "array":
        if not _only_keys(schema, frozenset({"type", "items", "minItems"})):
            return None
        items = schema.get("items")
        child = _compile_node(items) if items is not None else ("leaf", "any")
        if child is None:
            return None
        min_items = schema.get("minItems", 0)
        # (maxItems never reaches here — it fails the _only_keys whitelist
        # above and falls back to the generic JSON PDA)
        if min_items not in (0, 1):
            return None
        return ("arr", child, int(min_items))
    if t == "integer" and not _only_keys(schema, frozenset({"type"})):
        # bounded integers: minimum/maximum (and their exclusive forms)
        # compile to the digit-count DFA ("irange"); anything further
        # falls back
        if not _only_keys(schema, frozenset(
                {"type", "minimum", "maximum",
                 "exclusiveMinimum", "exclusiveMaximum"})):
            return None
        lo, hi = schema.get("minimum"), schema.get("maximum")
        xlo, xhi = schema.get("exclusiveMinimum"), \
            schema.get("exclusiveMaximum")
        bounds = [b for b in (lo, hi, xlo, xhi) if b is not None]
        if not all(isinstance(b, int) and not isinstance(b, bool)
                   for b in bounds):
            return None            # float bounds on integers: fall back
        if xlo is not None:
            lo = xlo + 1 if lo is None else max(lo, xlo + 1)
        if xhi is not None:
            hi = xhi - 1 if hi is None else min(hi, xhi - 1)
        if lo is not None and hi is not None and lo > hi:
            return None            # unsatisfiable: nothing could ever emit
        return ("irange", lo, hi)
    if not _only_keys(schema, frozenset({"type"})):
        return None
    if t in ("string", "number", "integer", "boolean", "null"):
        return ("leaf", t)
    if t is None:
        return ("leaf", "any")
    return None


def compile_schema(schema) -> Optional["Schema"]:
    """Schema dict → Schema machine, or None when a construct is outside
    the supported subset (caller falls back to generic JSON mode)."""
    root = _compile_node(schema)
    if root is None:
        return None
    return Schema(root)


# ---------------------------------------------------------------------------
# the skeleton machine
# ---------------------------------------------------------------------------

def _irange_viable(lo, hi, sign: int, v: int, k: int) -> bool:
    """Can the k-digit magnitude ``v`` (sign ``sign``), extended by zero or
    more digits, still land in [lo, hi] (None = unbounded side)? The
    digit-count DFA behind ("irange", lo, hi): at most ~19 interval checks
    per byte, no enumeration."""
    def fits(a, b2):
        vlo, vhi = (a, b2) if sign >= 0 else (-b2, -a)
        return (hi is None or vlo <= hi) and (lo is None or vhi >= lo)

    if fits(v, v):
        return True
    if v == 0:
        return False                    # leading zero: no extensions
    if sign >= 0:
        if hi is None:
            return True                 # magnitude grows past any lo
        limit = len(str(hi)) if hi > 0 else k
    else:
        if lo is None:
            return True
        limit = len(str(-lo)) if lo < 0 else k
    for m in range(k + 1, limit + 1):
        scale = 10 ** (m - k)
        if fits(v * scale, v * scale + scale - 1):
            return True
    return False


def _irange_done(node: Node, sub) -> bool:
    """Current irange digits form a complete in-range integer."""
    sign, v, k = sub
    if k == 0:
        return False
    val = v if sign >= 0 else -v
    return ((node[1] is None or val >= node[1])
            and (node[2] is None or val <= node[2]))


def _init_sub(node: Node):
    tag = node[0]
    if tag == "lit":
        return 0
    if tag == "leaf":
        return INITIAL_STATE
    if tag == "enum":
        return (0, tuple(range(len(node[1]))), False)
    if tag == "arr":
        return 0
    if tag == "irange":
        return (0, 0, 0)                # (sign, magnitude, n_digits)
    raise AssertionError(tag)


def _push_multi(stack: tuple, node: Node) -> List[tuple]:
    """All stacks reachable by pushing ``node``: seq heads are descended,
    alt nodes expand into one branch per alternative (the NFA split)."""
    out: List[tuple] = []
    work = [(list(stack), node)]
    while work:
        st, n = work.pop()
        tag = n[0]
        if tag == "seq":
            st.append((n, 0))
            work.append((st, n[1][0]))
        elif tag == "alt":
            for child in n[1]:
                work.append((list(st), child))
        else:
            st.append((n, _init_sub(n)))
            out.append(tuple(st))
    return out


def _completed_child(stack: tuple) -> List[tuple]:
    """Top frame finished and was popped; advance ancestors (possibly
    completing them too) and push the next consumer if any. Returns all
    resulting stacks (alternation in a following consumer can split)."""
    st = list(stack)
    while st:
        node, sub = st[-1]
        tag = node[0]
        if tag == "seq":
            nxt = sub + 1
            if nxt == len(node[1]):
                st.pop()
                continue
            st[-1] = (node, nxt)
            return _push_multi(tuple(st), node[1][nxt])
        if tag == "arr":
            st[-1] = (node, 3)          # after an item: ',' or ']'
            return [tuple(st)]
        raise AssertionError(tag)
    return [tuple(st)]


def machine_init(root: Node) -> tuple:
    """Initial NFA state: a tuple of stacks (alternation at the root
    yields several)."""
    return tuple(_push_multi((), root))


def _advance_stack(root: Node, stack: tuple, b: int) -> List[tuple]:
    """One byte through a single stack; returns every successor stack
    (alternation pushes and lazy closes can split), [] = rejected."""
    if not stack:
        return []                           # schema complete: EOS only
    st = list(stack)
    node, sub = st[-1]
    tag = node[0]
    if tag == "lit":
        data = node[1]
        if data[sub] != b:
            return []
        sub += 1
        if sub == len(data):
            st.pop()
            return _completed_child(tuple(st))
        st[-1] = (node, sub)
        return [tuple(st)]
    if tag == "leaf":
        kind = node[1]
        allowed = True
        if sub == INITIAL_STATE:
            start = _START_BYTES[kind]
            allowed = start is None or b in start
        if allowed and kind == "integer" and b in _INT_FORBIDDEN:
            allowed = False
        ns = advance_byte(sub, b) if allowed else None
        if ns is not None:
            if len(ns) == 4 and ns[0] == M_AFTER:
                st.pop()                    # value definitely closed
                return _completed_child(tuple(st))
            st[-1] = (node, ns)
            return [tuple(st)]
        if eos_ok(sub):                     # lazy close (numbers)
            st.pop()
            out: List[tuple] = []
            for cs in _completed_child(tuple(st)):
                out.extend(_advance_stack(root, cs, b))   # redispatch b
            return out
        return []
    if tag == "enum":
        off, viable, _ = sub
        nv = tuple(i for i in viable if off < len(node[1][i])
                   and node[1][i][off] == b)
        if not nv:
            return []
        off += 1
        ext = tuple(i for i in nv if len(node[1][i]) > off)
        results: List[tuple] = []
        if ext:
            st2 = list(st)
            st2[-1] = (node, (off, ext, False))
            results.append(tuple(st2))
        if any(len(node[1][i]) == off for i in nv):
            st2 = list(st)
            st2.pop()                       # an alternative fully matched
            results.extend(_completed_child(tuple(st2)))
        return results
    if tag == "arr":
        if sub == 0:
            if b != ord("["):
                return []
            st[-1] = (node, 1)
            return [tuple(st)]
        if sub == 1:                        # first item or ']'
            if b == ord("]") and node[2] == 0:
                st.pop()
                return _completed_child(tuple(st))
            st[-1] = (node, 2)
            out = []
            for ps in _push_multi(tuple(st), node[1]):
                out.extend(_advance_stack(root, ps, b))   # redispatch b
            return out
        if sub == 3:                        # after an item
            if b == ord("]"):
                st.pop()
                return _completed_child(tuple(st))
            if b == ord(","):
                st[-1] = (node, 2)
                return _push_multi(tuple(st), node[1])
            return []
        return []                           # sub == 2 never sits on top
    if tag == "irange":
        sign, v, k = sub
        lo, hi = node[1], node[2]
        if 0x30 <= b <= 0x39:
            d = b - 0x30
            if k == 0:
                nv_, nk = d, 1
            elif v == 0:
                return []                   # leading zero can't extend
            else:
                nv_, nk = v * 10 + d, k + 1
            s_eff = sign if sign != 0 else 1
            if not _irange_viable(lo, hi, s_eff, nv_, nk):
                return []
            st[-1] = (node, (s_eff, nv_, nk))
            return [tuple(st)]
        if b == 0x2D and sign == 0 and k == 0:            # '-'
            if any(_irange_viable(lo, hi, -1, d, 1) for d in range(10)):
                st[-1] = (node, (-1, 0, 0))
                return [tuple(st)]
            return []
        if _irange_done(node, sub):         # delimiter closes the integer
            st.pop()
            out = []
            for cs in _completed_child(tuple(st)):
                out.extend(_advance_stack(root, cs, b))   # redispatch b
            return out
        return []
    raise AssertionError(tag)


def machine_advance(root: Node, state: tuple, b: int) -> Optional[tuple]:
    """One byte through the NFA; None = rejected. ``state`` is a tuple of
    stacks, each an immutable tuple of (node, sub) frames."""
    out: List[tuple] = []
    seen = set()
    for stack in state:
        for ns in _advance_stack(root, stack, b):
            if ns not in seen:
                seen.add(ns)
                out.append(ns)
    return tuple(out) if out else None


def _stack_eos_ok(stack: tuple) -> bool:
    """One stack closable without more bytes? Only lazily-closing holes
    (numbers, bounded integers) can sit open at EOS — everything else
    completes eagerly on its final byte, leaving the empty stack."""
    if not stack:
        return True                         # schema complete
    st = list(stack)
    node, sub = st[-1]
    tag = node[0]
    closable = ((tag == "leaf" and eos_ok(sub))
                or (tag == "irange" and _irange_done(node, sub)))
    if not closable:
        return False
    st.pop()
    # ancestors must all be at their last position — no new consumers
    while st:
        pn, ps = st[-1]
        if pn[0] == "seq" and ps + 1 == len(pn[1]):
            st.pop()
            continue
        return False
    return True


def machine_eos_ok(state: tuple) -> bool:
    """EOS legal iff SOME branch can close without more bytes."""
    return any(_stack_eos_ok(s) for s in state)


# ---------------------------------------------------------------------------
# native program serialization (native/grammar.cpp: schema_fill_mask)
# ---------------------------------------------------------------------------

_KIND_IDS = {"string": 0, "number": 1, "integer": 2, "boolean": 3,
             "null": 4, "any": 5}
_MAX_BOUND = 10 ** 15       # |irange bound| the C++ saturation stays exact for
_MAX_ALTS = 63              # enum viable set rides a u64 bitmask


class _Refuse(Exception):
    pass


def _serialize_program(root: Node):
    """Node tree → (nodes int64 [n,6], extra int64, blob u8, id-map) for
    the C++ interpreter, or None when a structural cap applies (the pure
    Python machine then serves those schemas)."""
    nodes: List[list] = []
    extra: List[int] = []
    blob = bytearray()
    ids: Dict[int, int] = {}

    def walk(n: Node) -> int:
        if id(n) in ids:
            return ids[id(n)]
        idx = len(nodes)
        rec = [0, 0, 0, 0, 0, 0]
        nodes.append(rec)
        ids[id(n)] = idx
        tag = n[0]
        if tag == "lit":
            rec[0] = 0
            rec[1], rec[2] = len(blob), len(n[1])
            blob.extend(n[1])
        elif tag == "leaf":
            rec[0] = 1
            rec[1] = _KIND_IDS[n[1]]
        elif tag == "seq":
            kids = [walk(c) for c in n[1]]
            rec[0] = 2
            rec[1], rec[2] = len(extra), len(kids)
            extra.extend(kids)
        elif tag == "enum":
            if len(n[1]) > _MAX_ALTS:
                raise _Refuse
            rec[0] = 3
            rec[1], rec[2] = len(extra), len(n[1])
            for alt in n[1]:
                extra.extend((len(blob), len(alt)))
                blob.extend(alt)
        elif tag == "arr":
            item = walk(n[1])
            rec[0] = 4
            rec[1], rec[2] = item, int(n[2])
        elif tag == "alt":
            kids = [walk(c) for c in n[1]]
            rec[0] = 5
            rec[1], rec[2] = len(extra), len(kids)
            extra.extend(kids)
        elif tag == "irange":
            lo, hi = n[1], n[2]
            for bnd in (lo, hi):
                if bnd is not None and abs(bnd) > _MAX_BOUND:
                    raise _Refuse
            rec[0] = 6
            rec[1], rec[2] = int(lo is not None), int(lo or 0)
            rec[3], rec[4] = int(hi is not None), int(hi or 0)
        else:
            raise _Refuse
        return idx

    try:
        walk(root)
    except _Refuse:
        return None
    nodes_arr = np.asarray(nodes, np.int64).reshape(-1)
    extra_arr = (np.asarray(extra, np.int64) if extra
                 else np.zeros(1, np.int64))
    blob_arr = (np.frombuffer(bytes(blob), np.uint8).copy() if blob
                else np.zeros(1, np.uint8))
    return nodes_arr, extra_arr, blob_arr, ids


def _serialize_state(state: tuple, ids: Dict[int, int],
                     max_pda: int = 100) -> Optional[bytes]:
    """NFA state → the packed buffer schema_fill_mask decodes (format
    documented there). None when a cap applies → python fill."""
    import struct
    if not state or len(state) > 64:
        return None
    out = bytearray(struct.pack("<I", len(state)))
    for stack in state:
        if len(stack) > 96:
            return None
        out += struct.pack("<I", len(stack))
        for node, sub in stack:
            nid = ids.get(id(node))
            if nid is None:
                return None
            tag = node[0]
            if tag in ("lit", "seq", "arr"):
                out += struct.pack("<iBI", nid, 0, int(sub))
            elif tag == "leaf":
                if not isinstance(sub, bytes) or len(sub) > max_pda:
                    return None
                out += struct.pack("<iBI", nid, 1, len(sub)) + sub
            elif tag == "enum":
                off, viable, _ = sub
                mask = 0
                for i in viable:
                    mask |= 1 << i
                out += struct.pack("<iBIQ", nid, 2, int(off), mask)
            elif tag == "irange":
                sign, v, k = sub
                if abs(int(v)) > 10 ** 17 + 9:
                    return None
                out += struct.pack("<iBbqI", nid, 3, int(sign), int(v),
                                   int(k))
            else:
                return None
    return bytes(out)


# ---------------------------------------------------------------------------
# masks
# ---------------------------------------------------------------------------

class Schema:
    """Compiled schema + per-state mask cache (shared across requests)."""

    def __init__(self, root: Node):
        self.root = root
        self._masks: OrderedDict = OrderedDict()
        self._lock = Lock()
        self._cap = 8192
        # native fill program (round-2 VERDICT weak #7: cold hole-interior
        # states paid a pure-Python vocab sweep)
        self._prog = _serialize_program(root)

    def _state_key(self, table: TokenTable, state: tuple):
        # leaf PDA states use constrain.py's abstract stack-suffix key: a
        # token of max_len bytes can pop at most max_len containers, so
        # deeper "any"-hole nesting cannot change any token's acceptance
        # — without this, '[[[…' would mint (and full-vocab-sweep) a
        # fresh state per depth. The NFA state is a SET of stacks, so the
        # key is order-insensitive (frozenset).
        def sub_key(n, s):
            if n[0] == "leaf" and isinstance(s, bytes):
                return s[:4] + s[4:][-table.max_len:]
            return s
        return (id(table),
                frozenset(tuple((id(n), sub_key(n, s)) for n, s in stack)
                          for stack in state))

    def _native_fill(self, table: TokenTable, state: tuple
                     ) -> Optional[np.ndarray]:
        """Whole-vocab fill through native/grammar.cpp's NFA interpreter;
        None → caller runs the Python reference sweep."""
        from .constrain import _load_native
        lib = _load_native()
        if (lib is None or self._prog is None
                or getattr(lib, "schema_fill_mask", None) is None):
            return None
        sb = _serialize_state(state, self._prog[3])
        if sb is None:
            return None
        nodes_arr, extra_arr, blob_arr, _ = self._prog
        mask = np.zeros(table.n_words, np.uint32)
        rc = lib.schema_fill_mask(
            nodes_arr, np.int32(len(nodes_arr) // 6), extra_arr, blob_arr,
            np.frombuffer(sb, np.uint8), np.int64(len(sb)),
            table._flat, table._off, np.int32(table.n_vocab), mask)
        return mask if rc == 0 else None

    def mask_for(self, table: TokenTable, state: tuple) -> np.ndarray:
        key = self._state_key(table, state)
        with self._lock:
            m = self._masks.get(key)
            if m is not None:
                self._masks.move_to_end(key)
                return m
        mask = self._native_fill(table, state)
        if mask is None:
            # Python reference sweep with the first-byte prefilter
            first = bytes(b for b in range(256)
                          if machine_advance(self.root, state, b)
                          is not None)
            idx = _byte_index(table)
            if len(first) <= 32:
                cand: List[int] = []
                for b in first:
                    cand.extend(idx[b])
            else:
                cand = range(table.n_vocab)
            mask = np.zeros(table.n_words, np.uint32)
            for tid in cand:
                piece = table.pieces[tid]
                if not piece:
                    continue
                st = state
                for b in piece:
                    st = machine_advance(self.root, st, b)
                    if st is None:
                        break
                if st is not None:
                    mask[tid >> 5] |= np.uint32(1 << (tid & 31))
        if machine_eos_ok(state):
            if not any(machine_advance(self.root, state, b) is not None
                       for b in range(256)):
                mask = table._eog_packed.copy()   # nothing else is legal
            else:
                mask = mask | table._eog_packed
        with self._lock:
            self._masks[key] = mask
            self._masks.move_to_end(key)
            while len(self._masks) > self._cap:
                self._masks.popitem(last=False)
        return mask


_byte_index_lock = Lock()


def _byte_index(table: TokenTable) -> List[List[int]]:
    """First-byte → token ids, built once and stored ON the table (its
    lifetime owns the index; an id()-keyed global would leak across
    model unloads and could serve a recycled address the wrong vocab)."""
    idx = getattr(table, "_schema_byte_index", None)
    if idx is None:
        with _byte_index_lock:
            idx = getattr(table, "_schema_byte_index", None)
            if idx is None:
                idx = [[] for _ in range(256)]
                for tid, piece in enumerate(table.pieces):
                    if piece:
                        idx[piece[0]].append(tid)
                table._schema_byte_index = idx
    return idx


class SchemaConstraint:
    """Per-request schema state; same interface as JsonConstraint."""

    def __init__(self, schema: Schema, table: TokenTable):
        self.schema = schema
        self.table = table
        self.state: Optional[tuple] = machine_init(schema.root)

    @classmethod
    def for_tokenizer(cls, schema: Schema, tok) -> "SchemaConstraint":
        return cls(schema, TokenTable.for_tokenizer(tok))

    def mask_row(self) -> np.ndarray:
        assert self.state is not None, "constraint already dead"
        return self.schema.mask_for(self.table, self.state)

    def advance(self, tid: int) -> bool:
        if self.state is None:
            return False
        piece = (self.table.pieces[tid]
                 if 0 <= tid < self.table.n_vocab else b"")
        if not piece:
            return False
        st = self.state
        for b in piece:
            st = machine_advance(self.schema.root, st, b)
            if st is None:
                break
        self.state = st
        return st is not None

    @property
    def done(self) -> bool:
        return self.state is not None and machine_eos_ok(self.state)
