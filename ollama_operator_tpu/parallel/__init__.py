from .mesh import MeshPlan, make_mesh, set_mesh_compat  # noqa: F401
from .sharding import params_pspec_tree, shard_params  # noqa: F401
