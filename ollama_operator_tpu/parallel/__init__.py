from .mesh import MeshPlan, make_mesh  # noqa: F401
from .sharding import params_pspec_tree, shard_params  # noqa: F401
