"""Multi-host slice initialisation (`jax.distributed`).

The runtime half of the multi-host story: the operator renders per-pod
identity into env vars (operator/pod.py:multihost_env — coordinator DNS
from the StatefulSet's pod-0, process index from the pod ordinal) and this
module consumes them inside the server before any backend touch. After
``maybe_initialize()``, `jax.devices()` spans every chip in the slice and
GSPMD treats it as ONE device mesh — collectives ride ICI within a host
and the inter-host links across; no NCCL/MPI-style backend exists to
configure (SURVEY.md §2.3: the reference's only inter-pod channel is
HTTP, because its replicas never share model state).

Env contract (all set by the operator; absent = single-host no-op):

  TPU_DIST_HOSTS            number of processes (StatefulSet replicas)
  TPU_DIST_CHIPS_PER_HOST   chips each process owns (informational)
  TPU_DIST_COORDINATOR      host:port of process 0 (stable DNS)
  TPU_DIST_POD_NAME         this pod's name; trailing "-<ordinal>" is the
                            process index
"""

from __future__ import annotations

import os
import sys
from typing import Optional

_initialized = False


def process_index_from_pod_name(pod_name: str) -> int:
    """StatefulSet pods are named <sts>-<ordinal>; the ordinal IS the
    jax.distributed process id (stable across pod restarts, unlike any
    registration-order scheme)."""
    try:
        return int(pod_name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        raise ValueError(
            f"pod name {pod_name!r} has no trailing ordinal; multi-host "
            f"slices must run as a StatefulSet") from None


def maybe_initialize(env: Optional[dict] = None) -> bool:
    """Initialise jax.distributed when the operator's multi-host env is
    present. Returns True if a multi-host world was joined. Idempotent;
    single-host pods (no TPU_DIST_HOSTS or hosts == 1) are a no-op."""
    global _initialized
    e = env if env is not None else os.environ
    hosts = int(e.get("TPU_DIST_HOSTS", "1") or "1")
    if hosts <= 1:
        return False
    if _initialized:
        return True
    coordinator = e.get("TPU_DIST_COORDINATOR")
    pod_name = e.get("TPU_DIST_POD_NAME", "")
    if not coordinator:
        raise ValueError("TPU_DIST_HOSTS > 1 but TPU_DIST_COORDINATOR "
                         "is not set (operator/pod.py renders both)")
    pid = process_index_from_pod_name(pod_name)
    import jax
    print(f"jax.distributed: joining {hosts}-process world as {pid} "
          f"(coordinator {coordinator})", file=sys.stderr, flush=True)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=hosts, process_id=pid)
    _initialized = True
    return True
