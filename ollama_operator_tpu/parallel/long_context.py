"""Sequence-parallel decoder forwards (long-context serving).

`shard_map` wrappers around the decoder's building blocks that shard the
sequence axis over the mesh's ``sp`` axis: prefill runs ring attention
(K/V chunks rotating over ICI, parallel/ring_attention.py) and decode runs
against a sequence-sharded KV cache with an exact flash-partial combine.
The wrappers are manual over ``sp`` ONLY — dp/tp stay GSPMD-auto, so the
closed-over params keep their Megatron TP sharding (parallel/sharding.py)
and XLA still inserts the tp all-reduces inside the manual region.

This is a new capability over the reference, whose context length is
whatever llama.cpp defaults to inside the delegated image (SURVEY.md §5):
here a Model CR's ``contextLength`` can exceed one chip's HBM and the cache
spans the slice.

Semantics match models/decoder.py exactly (tests/test_ring_attention.py
asserts logits and caches agree with the dense single-device path).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.decoder import (Params, _attn_scale, _block_cached,
                              _block_chunk, _embed, _unembed)
from ..ops.attention import shard_map_compat
from ..ops.rope import rope_angles_cfg
from .ring_attention import (ring_attention, sp_cache_write,
                             sp_decode_attention)

SP_AXIS = "sp"


def prefill_chunk_sp(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     mesh: Mesh, inputs_embeds: jax.Array = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel twin of ``decoder.prefill_chunk``.

    tokens [B, T] with T divisible by mesh sp; returns (logits [B,T,V] fp32,
    k [L,B,KvH,T,hd], v [...]) — logits and K/V sharded over ``sp`` along
    their sequence axis. ``inputs_embeds`` [B, T, D] (multimodal prompts)
    replaces the embedding lookup; it shards over sp along T like tokens.
    """
    sp = mesh.shape[SP_AXIS]
    B, T = tokens.shape
    assert T % sp == 0, f"prefill length {T} must divide sp={sp}"
    if cfg.altern_sliding:
        raise NotImplementedError(
            "per-layer alternating windows / dual rope (gemma2, gemma3) are not implemented "
            "on the sequence-parallel path")
    scale = _attn_scale(cfg)

    def inner(tokens, inputs_embeds):
        my = lax.axis_index(SP_AXIS)
        Bc, Tc = tokens.shape
        positions = my * Tc + jnp.arange(Tc, dtype=jnp.int32)
        positions = jnp.broadcast_to(positions[None], (Bc, Tc))
        cos, sin = rope_angles_cfg(positions, cfg)
        if inputs_embeds is not None:
            x = inputs_embeds.astype(params["tok_emb"].dtype)
        else:
            x = _embed(cfg, params, tokens)

        def attn_fn(q, k, v):
            return ring_attention(q, k, v, scale, SP_AXIS, cfg.attn_softcap,
                                  cfg.sliding_window)

        def body(x, lp):
            return _block_chunk(cfg, lp, x, cos, sin, None, scale,
                                attn_fn=attn_fn)

        x, (ks, vs) = lax.scan(body, x, params["layers"])
        logits = _unembed(cfg, params, x)
        return logits, ks, vs

    seq_spec = P(None, None, None, SP_AXIS, None)   # [L,B,KvH,T@sp,hd]
    emb_spec = None if inputs_embeds is None else P(None, SP_AXIS, None)
    return shard_map_compat(
        inner, mesh=mesh,
        in_specs=(P(None, SP_AXIS), emb_spec),
        out_specs=(P(None, SP_AXIS, None), seq_spec, seq_spec),
        axis_names={SP_AXIS})(tokens, inputs_embeds)


def forward_with_cache_sp(params: Params, cfg: ModelConfig,
                          tokens: jax.Array, k_cache: jax.Array,
                          v_cache: jax.Array, lengths: jax.Array,
                          mesh: Mesh
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel twin of ``decoder.forward_with_cache``.

    k_cache/v_cache [L,B,KvH,S,hd] sharded over ``sp`` along S — dense, or
    int8 dicts {"q", "s": [L,B,KvH,S]} (the sp collectives quantize fresh
    K/V and dequantize via scales folded into scores/probs, closing
    round-1's int8×sp exclusion). The fresh tokens' compute is replicated
    across sp (decode is memory-bound; sp exists for HBM capacity) — only
    the cache reads/writes are sharded.
    Returns (logits [B,T,V] replicated, k_cache, v_cache).
    """
    if cfg.altern_sliding:
        raise NotImplementedError(
            "per-layer alternating windows / dual rope (gemma2, gemma3) are not implemented "
            "on the sequence-parallel path")
    scale = _attn_scale(cfg)
    quant = isinstance(k_cache, dict)

    def inner(tokens, k_cache, v_cache, lengths):
        B, T = tokens.shape
        positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        cos, sin = rope_angles_cfg(positions, cfg)
        x = _embed(cfg, params, tokens)

        def attn_fn(q, kc, vc, pos):
            return sp_decode_attention(q, kc, vc, pos, scale, SP_AXIS,
                                       cfg.attn_softcap, cfg.sliding_window)

        def write_fn(kc, vc, k, v, pos):
            return sp_cache_write(kc, vc, k, v, pos, SP_AXIS)

        def body(x, layer_in):
            lp, kc, vc = layer_in
            x, kc, vc = _block_cached(cfg, lp, x, cos, sin, kc, vc,
                                      positions, None, scale,
                                      attn_fn=attn_fn, write_fn=write_fn)
            return x, (kc, vc)

        x, (k_cache, v_cache) = lax.scan(
            body, x, (params["layers"], k_cache, v_cache))
        logits = _unembed(cfg, params, x)
        return logits, k_cache, v_cache

    cache_spec = P(None, None, None, SP_AXIS, None)
    if quant:
        cache_spec = {"q": cache_spec,
                      "s": P(None, None, None, SP_AXIS)}
    return shard_map_compat(
        inner, mesh=mesh,
        in_specs=(P(None, None), cache_spec, cache_spec, P(None)),
        out_specs=(P(None, None, None), cache_spec, cache_spec),
        axis_names={SP_AXIS})(tokens, k_cache, v_cache, lengths)
