"""Device mesh construction.

The reference's only parallelism is K8s replica fan-out
(/root/reference/pkg/model/model.go:72 — spec.replicas → Deployment
replicas); every other axis here is new TPU-native capability (SURVEY.md
§2.3). Axis conventions used across the framework:

  dp — data parallel (batch). Maps across slices / DCN, or within a slice.
  pp — pipeline parallel (layer stages; p2p ppermute, tolerates DCN).
  sp — sequence parallel (ring attention for long context).
  ep — expert parallel (MoE experts resident per device group).
  tp — tensor parallel (heads / ffn / vocab). Must ride ICI.

Axis order is outermost→innermost by communication cost tolerance: tp is
innermost (latency-critical all-reduce every layer → physically adjacent
ICI neighbours), ep next (per-layer combine-reduce), sp next (ring
per layer), pp (one p2p per stage boundary), dp outermost (gradient-free
serving: no traffic at all).

Single-chip and CPU-test configs are just degenerate meshes (1×…×1 or
8-device CPU meshes via --xla_force_host_platform_device_count=8).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """How to lay devices out over the 5 serving axes (any may be 1)."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.ep * self.tp

    @property
    def dims(self) -> tuple:
        """Sizes in AXES order."""
        return (self.dp, self.pp, self.sp, self.ep, self.tp)

    @staticmethod
    def for_devices(n: int, tp: Optional[int] = None, sp: int = 1,
                    pp: int = 1, ep: int = 1) -> "MeshPlan":
        """Default plan: all tensor-parallel unless told otherwise."""
        if tp is None:
            tp = n // (sp * pp * ep)
        dp = n // (tp * sp * pp * ep)
        plan = MeshPlan(dp=dp, sp=sp, tp=tp, pp=pp, ep=ep)
        assert plan.n_devices == n, f"{plan} does not cover {n} devices"
        return plan


def make_mesh(plan: MeshPlan, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if len(devices) < plan.n_devices:
        raise ValueError(f"need {plan.n_devices} devices, have {len(devices)}")
    arr = np.array(devices[: plan.n_devices]).reshape(plan.dims)
    return Mesh(arr, AXES)


def set_mesh_compat(mesh: Mesh):
    """``jax.set_mesh(mesh)`` context across jax versions. Older jax has no
    set_mesh; there the Mesh object itself is the context manager that
    installs the active mesh."""
    try:
        return jax.set_mesh(mesh)
    except AttributeError:
        return mesh
