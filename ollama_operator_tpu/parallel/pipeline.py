"""Pipeline-parallel decoder forwards (layer stages over the ``pp`` axis).

The reference has no pipeline concept at all — its only scale-out is
independent replicas behind a Service (/root/reference/pkg/model/model.go:72,
SURVEY.md §2.3). This module is new TPU-native capability: it lets a model
whose weights exceed one host's HBM span hosts along the *layer* axis, where
the only inter-stage traffic is one [b, T, D] activation ppermute per
microbatch per tick — point-to-point, tolerant of DCN between hosts (unlike
tp's per-layer all-reduces, which need ICI).

Design (GPipe-style schedule, SPMD formulation):
- Layer-stacked params [L, ...] are reshaped to [pp, L/pp, ...] and passed
  into a ``jax.shard_map`` manual over ``pp`` ONLY — each device holds its
  stage's layers. Non-layer params (embeddings, norms, lm_head) are closed
  over and keep their GSPMD sharding (Megatron tp stays live inside the
  manual region, same trick as long_context.py).
- The KV cache [L, B, KvH, S, hd] is likewise stage-sharded on L.
- The batch is cut into M microbatches of b = B/M rows. A static loop of
  M + pp - 1 ticks runs: at tick t, stage s processes microbatch m = t - s
  (a masked no-op outside [0, M)), then ppermutes its activation to stage
  s+1. Stage 0 ingests (embeds) microbatch t; the last stage accumulates
  final hidden states, psum-broadcast after the loop so the unembed runs
  replicated (or tp-sharded) outside the manual region.

All control flow is static — the schedule compiles to one XLA program with
a fori_loop, no host round-trips between ticks.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import dataclasses

from ..models.config import ModelConfig
from ..models.decoder import _attn_scale, Params, _block_cached, _embed, _unembed
from ..ops.attention import pcast_varying_compat, shard_map_compat
from ..ops.rope import rope_angles_cfg
from .sharding import resolve_moe_impl

PP_AXIS = "pp"


def split_stages(layer_params, pp: int):
    """Reshape every stacked layer leaf [L, ...] → [pp, L/pp, ...]."""
    def r(a):
        L = a.shape[0]
        assert L % pp == 0, f"pp={pp} must divide n_layers {L}"
        return a.reshape(pp, L // pp, *a.shape[1:])
    return jax.tree_util.tree_map(r, layer_params)


def merge_stages(layer_params):
    """Inverse of split_stages: [pp, L/pp, ...] → [L, ...]."""
    def r(a):
        return a.reshape(a.shape[0] * a.shape[1], *a.shape[2:])
    return jax.tree_util.tree_map(r, layer_params)


def forward_with_cache_pp(params: Params, cfg: ModelConfig,
                          tokens: jax.Array, k_cache: jax.Array,
                          v_cache: jax.Array, lengths: jax.Array,
                          mesh: Mesh,
                          n_microbatches: Optional[int] = None
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pipeline-parallel twin of ``decoder.forward_with_cache``.

    tokens [B, T]; k_cache/v_cache [L, B, KvH, S, hd] sharded over ``pp``
    along L; lengths [B]. Returns (logits [B, T, V] fp32 replicated over pp,
    k_cache, v_cache updated).
    """
    pp = mesh.shape[PP_AXIS]
    if cfg.n_experts:
        cfg = dataclasses.replace(cfg, moe_impl=resolve_moe_impl(cfg, mesh))
    B, T = tokens.shape
    L = cfg.n_layers
    M = n_microbatches or pp
    assert B % M == 0, f"microbatches {M} must divide batch {B}"
    assert M >= pp, f"need at least pp={pp} microbatches, got {M}"
    b = B // M
    Lpp = L // pp
    if cfg.altern_sliding:
        raise NotImplementedError(
            "per-layer alternating windows / dual rope (gemma2, gemma3) are not implemented "
            "on the pipeline path")
    scale = _attn_scale(cfg)
    KvH, hd = cfg.n_kv_heads, cfg.head_dim
    S = k_cache.shape[3]

    stages = split_stages(params["layers"], pp)
    kc5 = k_cache.reshape(pp, Lpp, B, KvH, S, hd)
    vc5 = v_cache.reshape(pp, Lpp, B, KvH, S, hd)

    def inner(stage_lp, kc, vc, tokens, lengths):
        # the mapped pp axis arrives as a leading size-1 dim — drop it
        stage_lp = jax.tree_util.tree_map(lambda a: a[0], stage_lp)
        kc, vc = kc[0], vc[0]
        # per-device: stage_lp [Lpp, ...], kc/vc [Lpp, B, KvH, S, hd]
        s = lax.axis_index(PP_AXIS)
        positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        k_pos = jnp.arange(S, dtype=jnp.int32)[None, None, :]

        def run_stage(x_mb, kc_mb, vc_mb, pos_mb):
            cos, sin = rope_angles_cfg(pos_mb, cfg)
            ok = k_pos <= pos_mb[:, :, None]
            if cfg.sliding_window:
                ok = ok & (k_pos > pos_mb[:, :, None] - cfg.sliding_window)
            mask = jnp.where(ok, 0.0, -1e30).astype(jnp.float32)[:, None]

            def body(x, layer_in):
                lp, kcl, vcl = layer_in
                x, kcl, vcl = _block_cached(cfg, lp, x, cos, sin, kcl, vcl,
                                            pos_mb, mask, scale)
                return x, (kcl, vcl)

            x, (kc_mb, vc_mb) = lax.scan(body, x_mb, (stage_lp, kc_mb, vc_mb))
            return x, kc_mb, vc_mb

        D = cfg.dim
        dtype = params["tok_emb"].dtype
        # embed the whole batch once, outside the tick loop — a per-tick
        # embed would re-gather the (possibly vocab-sharded) table on every
        # stage every tick only to be consumed on stage 0
        x_all = _embed(cfg, params, tokens)

        def tick(t, carry):
            act, kc, vc, out = carry
            # stage 0 ingests microbatch t (garbage once t >= M; masked off)
            in_off = jnp.clip(t, 0, M - 1) * b
            x0 = lax.dynamic_slice_in_dim(x_all, in_off, b, axis=0)
            x_in = jnp.where(s == 0, x0, act)
            # this stage works on microbatch m = t - s
            m = t - s
            valid = (m >= 0) & (m < M)
            boff = jnp.clip(m, 0, M - 1) * b
            pos_mb = lax.dynamic_slice_in_dim(positions, boff, b, axis=0)
            kc_mb = lax.dynamic_slice(kc, (0, boff, 0, 0, 0),
                                      (Lpp, b, KvH, S, hd))
            vc_mb = lax.dynamic_slice(vc, (0, boff, 0, 0, 0),
                                      (Lpp, b, KvH, S, hd))
            x_out, kc_new, vc_new = run_stage(x_in, kc_mb, vc_mb, pos_mb)
            # masked cache writeback (writes original values when invalid)
            kc_sel = jnp.where(valid, kc_new, kc_mb)
            vc_sel = jnp.where(valid, vc_new, vc_mb)
            kc = lax.dynamic_update_slice(kc, kc_sel, (0, boff, 0, 0, 0))
            vc = lax.dynamic_update_slice(vc, vc_sel, (0, boff, 0, 0, 0))
            # last stage banks the final hidden states for microbatch m
            is_out = valid & (s == pp - 1)
            mo = jnp.clip(m, 0, M - 1)
            out = out.at[mo].set(
                jnp.where(is_out, x_out.astype(out.dtype), out[mo]))
            # hand activation to the next stage (ring; stage 0's incoming
            # slot is overwritten by fresh ingest next tick)
            act = lax.ppermute(x_out, PP_AXIS,
                               [(i, (i + 1) % pp) for i in range(pp)])
            return act, kc, vc, out

        act0 = pcast_varying_compat(jnp.zeros((b, T, D), dtype), PP_AXIS)
        out0 = pcast_varying_compat(jnp.zeros((M, b, T, D), jnp.float32),
                                    PP_AXIS)
        act, kc, vc, out = lax.fori_loop(0, M + pp - 1, tick,
                                         (act0, kc, vc, out0))
        # replicate the last stage's bank to every device
        out = lax.psum(jnp.where(s == pp - 1, out, 0), PP_AXIS)
        return out, kc[None], vc[None]

    cache_spec = P(PP_AXIS, None, None, None, None, None)
    out, kc5, vc5 = shard_map_compat(
        inner, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(
            lambda _: P(PP_AXIS), stages), cache_spec, cache_spec,
            P(None, None), P(None)),
        out_specs=(P(None, None, None, None), cache_spec, cache_spec),
        axis_names={PP_AXIS})(stages, kc5, vc5, tokens, lengths)

    hidden = out.reshape(B, T, cfg.dim).astype(params["tok_emb"].dtype)
    logits = _unembed(cfg, params, hidden)
    return (logits, kc5.reshape(L, B, KvH, S, hd),
            vc5.reshape(L, B, KvH, S, hd))


def prefill_chunk_pp(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     mesh: Mesh, n_microbatches: Optional[int] = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pipeline-parallel prefill: fresh chunk at positions [0, T).

    Same contract as ``decoder.prefill_chunk`` (logits [B,T,V] fp32,
    k/v [L,B,KvH,T,hd]) — implemented as a cached forward into an empty
    T-slot cache, which is exactly equivalent.
    """
    B, T = tokens.shape
    shape = (cfg.n_layers, B, cfg.n_kv_heads, T, cfg.head_dim)
    dtype = params["tok_emb"].dtype
    zeros = jnp.zeros(shape, dtype)
    lengths = jnp.zeros((B,), jnp.int32)
    return forward_with_cache_pp(params, cfg, tokens, zeros, zeros, lengths,
                                 mesh, n_microbatches)
