"""Ring attention + sequence-parallel decode collectives.

Long-context capability the reference lacks entirely (SURVEY.md §5
"Long-context / sequence parallelism: ABSENT" — the reference inherits
whatever context llama.cpp defaults to inside the delegated `ollama/ollama`
image, /root/reference/pkg/model/pod.go:11). Here the sequence axis is
sharded over the mesh's ``sp`` axis so prompts / KV caches larger than one
chip's HBM span the slice over ICI.

Two primitives, both designed to run INSIDE a ``jax.shard_map`` region that
is manual over ``sp`` (and only ``sp`` — tp/dp stay GSPMD-auto, so the
Megatron TP sharding of the closed-over weights keeps working around these
calls; see parallel/long_context.py for the wrappers):

- ``ring_attention``: causal flash attention for sequence-sharded prefill.
  Each device holds one contiguous chunk of Q and of K/V; K/V chunks rotate
  around the ring via ``lax.ppermute`` while an fp32 online-softmax carry
  (running max ``m``, normaliser ``l``, accumulator ``acc``) stays put with
  Q. Blocks that the causal structure (or a sliding window) makes fully
  invisible are skipped with ``lax.cond`` — compute AND the softmax update
  are elided, only the ring DMA still moves.

- ``sp_decode_attention``: decode against a sequence-sharded KV cache. Each
  device computes a flash partial (m, l, acc) over its local cache chunk,
  then one ``pmax`` + two ``psum`` combine the partials exactly — the
  per-step collective traffic is O(B·H·hd), independent of context length.

Chunking convention: contiguous ("chunked") sharding — device i owns
absolute positions [i·C, (i+1)·C). The causal skip makes the compute
triangular rather than balanced; a zig-zag layout would balance it but
complicates the KV-cache write path, so round 1 keeps the simple layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (NEG_INF, axis_size_compat, pcast_varying_compat,
                             softcap_scores)

_FP32 = jnp.float32


def _accum(carry, q, k, v, mask, scale: float, softcap: float,
           k_s=None, v_s=None):
    """One online-softmax accumulation step.

    carry: (m [B,KvH,G,T], l [B,KvH,G,T], acc [B,KvH,G,T,hd]) fp32
    q [B,T,H,hd]; k/v head-first [B,KvH,S,hd]; mask [B,T,S] additive fp32.
    ``k_s``/``v_s`` [B,KvH,S] — per-position dequant scales for int8
    chunks (ops/quant_cache.py convention: the key scale factors out of
    the q·k dot onto the scores; the value scale folds into the
    probabilities — dequantized tensors never materialise).
    """
    m, l, acc = carry
    B, T, H, hd = q.shape
    KvH = k.shape[1]
    G = H // KvH
    qg = q.reshape(B, T, KvH, G, hd)
    kc = k.astype(q.dtype) if k_s is not None else k
    s = jnp.einsum("btkgh,bksh->bkgts", qg, kc, preferred_element_type=_FP32)
    if k_s is not None:
        s = s * k_s[:, :, None, None, :]
    s = softcap_scores(s * scale, softcap)
    s = s + mask[:, None, None, :, :]
    m_new = jnp.maximum(m, s.max(axis=-1))
    # m_new can still be NEG_INF when nothing is visible yet; keep exp args
    # finite so p/alpha are exactly 0/1 rather than NaN.
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l = l * alpha + p.sum(axis=-1)
    if v_s is not None:
        p = p * v_s[:, :, None, None, :]
        vc = v.astype(q.dtype)
    else:
        vc = v
    acc = acc * alpha[..., None] + jnp.einsum(
        "bkgts,bksh->bkgth", p.astype(vc.dtype), vc,
        preferred_element_type=_FP32)
    return m_new, l, acc


def _finish(carry, B, T, H, hd):
    m, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    # [B,KvH,G,T,hd] -> [B,T,H,hd]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, hd)


def ring_attention(q, k, v, scale: float, axis_name: str = "sp",
                   softcap: float = 0.0, sliding_window: int = 0):
    """Causal ring flash attention over sequence-sharded chunks.

    Per-device shapes (inside shard_map, manual over ``axis_name``):
      q      [B, Tc, H, hd]   — this device's query chunk
      k, v   [B, KvH, Tc, hd] — this device's key/value chunk (head-first)
    Device i owns absolute positions [i·Tc, (i+1)·Tc). Returns [B,Tc,H,hd]
    in q.dtype — bitwise semantics of dense causal attention over the full
    sequence.
    """
    sp = axis_size_compat(axis_name)
    my = lax.axis_index(axis_name)
    B, Tc, H, hd = q.shape
    KvH = k.shape[1]
    G = H // KvH

    q_pos = my * Tc + jnp.arange(Tc, dtype=jnp.int32)          # [Tc]
    carry = (jnp.full((B, KvH, G, Tc), NEG_INF, _FP32),
             jnp.zeros((B, KvH, G, Tc), _FP32),
             jnp.zeros((B, KvH, G, Tc, hd), _FP32))
    # the accumulated carry is device-varying (per-chunk); mark the literal
    # init as such so both lax.cond branches type-check under check_vma
    carry = jax.tree.map(
        lambda a: pcast_varying_compat(a, axis_name), carry)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    for step in range(sp):
        src = (my - step) % sp            # origin of the chunk we now hold
        k_pos = src * Tc + jnp.arange(Tc, dtype=jnp.int32)     # [Tc]
        ok = k_pos[None, :] <= q_pos[:, None]
        if sliding_window:
            ok = ok & (k_pos[None, :] > q_pos[:, None] - sliding_window)
        mask = jnp.where(ok, 0.0, NEG_INF).astype(_FP32)
        mask = jnp.broadcast_to(mask[None], (B, Tc, Tc))

        # Block-level visibility: any (q, k) pair unmasked? Causal: the
        # earliest key must not exceed the latest query; window: the latest
        # key must be inside the window of the earliest query.
        visible = (src * Tc) <= (my * Tc + Tc - 1)
        if sliding_window:
            visible = visible & ((src * Tc + Tc - 1) >
                                 (my * Tc - sliding_window))
        carry = lax.cond(
            visible,
            lambda c, kk, vv, mm: _accum(c, q, kk, vv, mm, scale, softcap),
            lambda c, kk, vv, mm: c,
            carry, k, v, mask)

        if step < sp - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)

    return _finish(carry, B, Tc, H, hd).astype(q.dtype)


def sp_decode_attention(q, k_chunk, v_chunk, q_pos, scale: float,
                        axis_name: str = "sp", softcap: float = 0.0,
                        sliding_window: int = 0):
    """Attention of fresh tokens against a sequence-sharded KV cache.

    Per-device shapes (inside shard_map, manual over ``axis_name``):
      q                [B, T, H, hd]    — replicated across sp (T=1 decode,
                                          T>1 chunked continuation)
      k_chunk, v_chunk [B, KvH, Sc, hd] — local cache chunk; device i holds
                                          absolute slots [i·Sc, (i+1)·Sc)
      q_pos            [B, T] int32     — absolute positions of the queries
    Returns [B, T, H, hd] replicated across sp (psum-combined partials).
    """
    my = lax.axis_index(axis_name)
    B, T, H, hd = q.shape
    quant = isinstance(k_chunk, dict)
    k_s = k_chunk["s"] if quant else None
    v_s = v_chunk["s"] if quant else None
    if quant:
        k_chunk, v_chunk = k_chunk["q"], v_chunk["q"]
    KvH, Sc = k_chunk.shape[1], k_chunk.shape[2]
    G = H // KvH

    k_pos = my * Sc + jnp.arange(Sc, dtype=jnp.int32)          # [Sc]
    ok = k_pos[None, None, :] <= q_pos[:, :, None]             # [B,T,Sc]
    if sliding_window:
        ok = ok & (k_pos[None, None, :] > q_pos[:, :, None] - sliding_window)
    mask = jnp.where(ok, 0.0, NEG_INF).astype(_FP32)

    # local flash partial = one _accum step from an empty carry
    zero = (jnp.full((B, KvH, G, T), NEG_INF, _FP32),
            jnp.zeros((B, KvH, G, T), _FP32),
            jnp.zeros((B, KvH, G, T, hd), _FP32))
    m_loc, l_loc, acc_loc = _accum(zero, q, k_chunk, v_chunk, mask, scale,
                                   softcap, k_s=k_s, v_s=v_s)

    m_g = lax.pmax(m_loc, axis_name)
    corr = jnp.exp(m_loc - m_g)                                # 0 when local
    l_g = lax.psum(l_loc * corr, axis_name)                    # chunk empty
    acc_g = lax.psum(acc_loc * corr[..., None], axis_name)

    out = acc_g / jnp.maximum(l_g, 1e-30)[..., None]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, H, hd).astype(q.dtype)


def sp_cache_write(k_cache, v_cache, k_new, v_new, write_pos,
                   axis_name: str = "sp"):
    """Write fresh K/V into a sequence-sharded cache chunk.

    k_cache/v_cache [B, KvH, Sc, hd] — local chunk (device i owns absolute
    slots [i·Sc, (i+1)·Sc)), or int8 dicts {"q": entries, "s": [B,KvH,Sc]
    scales} — fresh K/V is then quantized before the scatter; k_new/v_new
    [B, KvH, T, hd] replicated across sp; write_pos [B, T] absolute slots.
    Positions outside the local chunk are dropped (they land on the owning
    device instead).
    """
    my = lax.axis_index(axis_name)
    quant = isinstance(k_cache, dict)
    Sc = (k_cache["q"] if quant else k_cache).shape[2]
    B, KvH = k_new.shape[0], k_new.shape[1]
    local = write_pos - my * Sc                                # [B,T]
    # mode="drop" discards scatters whose local index is outside [0, Sc) —
    # they belong to another shard — but negative indices would wrap
    # (numpy semantics) before the bounds check, so send them out of bounds
    # explicitly. (No clip-then-select: clipping would alias a dropped write
    # onto the chunk-boundary slot, and duplicate scatter indices have
    # undefined update order.)
    local = jnp.where(local < 0, Sc, local)
    bidx = jnp.arange(B)[:, None, None]
    hidx = jnp.arange(KvH)[None, :, None]
    pidx = local[:, None, :]
    if quant:
        from ..ops.quant_cache import quantize_kv
        kq, ks = quantize_kv(k_new)
        vq, vs = quantize_kv(v_new)
        k_cache = {"q": k_cache["q"].at[bidx, hidx, pidx].set(
                       kq, mode="drop"),
                   "s": k_cache["s"].at[bidx, hidx, pidx].set(
                       ks, mode="drop")}
        v_cache = {"q": v_cache["q"].at[bidx, hidx, pidx].set(
                       vq, mode="drop"),
                   "s": v_cache["s"].at[bidx, hidx, pidx].set(
                       vs, mode="drop")}
        return k_cache, v_cache
    k_cache = k_cache.at[bidx, hidx, pidx].set(
        k_new.astype(k_cache.dtype), mode="drop")
    v_cache = v_cache.at[bidx, hidx, pidx].set(
        v_new.astype(v_cache.dtype), mode="drop")
    return k_cache, v_cache
