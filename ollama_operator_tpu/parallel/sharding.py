"""Parameter and activation sharding specs (GSPMD / NamedSharding).

Megatron-style tensor parallelism expressed declaratively: column-parallel
q/k/v/gate/up, row-parallel o/down, vocab-parallel embedding + lm_head. XLA
inserts the all-reduces (psum over "tp") at the row-parallel boundaries —
there is no hand-written collective on the dense path (the ring-attention
path in ring_attention.py is the exception, by design).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig

# Specs for stacked layer leaves: leading axis is n_layers (never sharded).
_LAYER_SPECS: Dict[str, P] = {
    "attn_norm_w": P(None, None),
    "attn_norm_b": P(None, None),
    "mlp_norm_w": P(None, None),
    "mlp_norm_b": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    # fused qkv (engine-side, only on meshes without a sharded tp axis —
    # a tp split would straddle the q/kv column boundary)
    "wqkv": P(None, None, None),
    "bqkv": P(None, None),
    "wo": P(None, "tp", None),
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
    "bo": P(None, None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
    "b_up": P(None, "tp"),
    "b_down": P(None, None),
    "q_norm_w": P(None, None),
    "k_norm_w": P(None, None),
    "post_attn_norm_w": P(None, None),
    "post_ffw_norm_w": P(None, None),
    # MoE (mixtral family): experts on "ep", per-expert Megatron TP on "tp"
    "router": P(None, None, None),
    "we_gate": P(None, "ep", None, "tp"),
    "we_up": P(None, "ep", None, "tp"),
    "we_down": P(None, "ep", "tp", None),
    # qwen2moe shared expert: dense Megatron TP like w_gate/w_up/w_down;
    # the sigmoid gate projection replicates ([L, D, 1])
    "we_sh_gate": P(None, None, "tp"),
    "we_sh_up": P(None, None, "tp"),
    "we_sh_down": P(None, "tp", None),
    "sh_gate": P(None, None, None),
}

_TOP_SPECS: Dict[str, P] = {
    "tok_emb": P("tp", None),   # vocab-parallel; XLA all-gathers the lookup
    "out_norm_w": P(None),
    "out_norm_b": P(None),
    "lm_head": P(None, "tp"),
    "lm_head_b": P("tp"),
}


def resolve_specs(cfg: Optional[ModelConfig], mesh: Optional[Mesh]
                  ) -> tuple[Dict[str, P], Dict[str, P]]:
    """(top_specs, layer_specs) adjusted for GQA divisibility.

    With few KV heads (llama2:70b has 8) and a wide tp axis, KV heads may
    not divide tp; the standard layout then replicates K/V (and their
    projections) across the extra tp ways — each replica serves its local
    group of Q heads. Vocab-parallel embedding falls back to replication if
    the vocab doesn't divide tp.
    """
    top, layer = dict(_TOP_SPECS), dict(_LAYER_SPECS)
    if cfg is None or mesh is None:
        return top, layer
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and cfg.n_kv_heads % tp != 0:
        layer.update(wk=P(None, None, None), wv=P(None, None, None),
                     bk=P(None, None), bv=P(None, None))
    if tp > 1 and cfg.vocab_size % tp != 0:
        top.update(tok_emb=P(None, None), lm_head=P(None, None),
                   lm_head_b=P(None))
    ep = mesh.shape.get("ep", 1)
    if ep > 1 and cfg.n_experts % ep != 0:
        layer.update(we_gate=P(None, None, None, "tp"),
                     we_up=P(None, None, None, "tp"),
                     we_down=P(None, None, "tp", None))
        # shared-expert leaves keep their dense-TP specs
    return top, layer


def experts_ep_sharded(cfg: Optional[ModelConfig], mesh: Optional[Mesh]
                       ) -> bool:
    """True iff resolve_specs places the expert axis on "ep" for this mesh
    (the single source of truth for the divisibility fallback above)."""
    if cfg is None or mesh is None or not cfg.n_experts:
        return False
    ep = mesh.shape.get("ep", 1)
    return ep > 1 and cfg.n_experts % ep == 0


def resolve_moe_impl(cfg: ModelConfig, mesh: Optional[Mesh]) -> str:
    """The MoE impl an "auto" config must use on this mesh: the einsum
    layout whenever the experts are actually ep-sharded — the scan layout
    slices the expert axis per step, which under GSPMD would all-gather
    every ep-sharded expert weight onto every device."""
    if cfg.moe_impl == "auto" and experts_ep_sharded(cfg, mesh):
        return "einsum"
    return cfg.moe_impl


def _leaf_spec(spec: P, v: Any, mesh: Optional[Mesh], name: str = "?"):
    """A quantized dict leaf {"q"|"q4", "s"} shares its dense spec: q has
    the dense shape (q4 the packed K/2 at the same position) and the group
    axis of s is K/g at the same position, so the same PartitionSpec
    usually partitions both. When a scale dim is too small to divide its
    mesh axis (tiny K/g), that axis replicates for s only — XLA still
    partials the dot over the sharded q rows. An int4 leaf whose shard
    boundary splits a packing group (GROUP/2 packed rows carry one
    group's nibbles) still computes correctly — GSPMD reshards around
    qmm4's (G, g/2, O) reshape (tests/test_quant.py pins it) — but the
    reshard is an all-gather-class copy on a hot decode matmul, so it is
    flagged loudly at load with the leaf and mesh axis named."""
    from ..ops.quant import GROUP, is_int4, is_quantized
    if not is_quantized(v):
        return spec
    if is_int4(v) and mesh is not None:
        kp = v["q4"].shape[-2]          # packed K/2 rows
        ax = spec[-2] if len(spec) >= 2 else None
        size = mesh.shape.get(ax, 1) if ax else 1
        if size > 1 and (kp % size or (kp // size) % (GROUP // 2)):
            import warnings
            warnings.warn(
                f"int4 leaf {name!r}: packed K axis ({kp} rows) sharded "
                f"{size}-way over mesh axis {ax!r} does not split on "
                f"whole {GROUP}-row packing groups ({GROUP // 2} packed "
                f"rows); GSPMD inserts a reshard on this matmul every "
                f"decode step — prefer a tp that divides K into "
                f"multiples of {GROUP}, or serve this model int8",
                stacklevel=2)
    s_shape = v["s"].shape
    s_spec = []
    for i, ax in enumerate(spec):
        size = mesh.shape.get(ax, 1) if (mesh is not None and ax) else 1
        s_spec.append(ax if ax and s_shape[i] % size == 0 else None)
    return {("q4" if is_int4(v) else "q"): spec, "s": P(*s_spec)}


def params_pspec_tree(params: Dict[str, Any],
                      cfg: Optional[ModelConfig] = None,
                      mesh: Optional[Mesh] = None) -> Dict[str, Any]:
    top, layer = resolve_specs(cfg, mesh)
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {lk: _leaf_spec(layer[lk], lv, mesh, name=lk)
                      for lk, lv in v.items()}
        else:
            out[k] = _leaf_spec(top[k], v, mesh, name=k)
    return out


def params_sharding_tree(params: Dict[str, Any], mesh: Mesh,
                         cfg: Optional[ModelConfig] = None) -> Dict[str, Any]:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        params_pspec_tree(params, cfg, mesh),
        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Dict[str, Any], mesh: Mesh,
                 cfg: Optional[ModelConfig] = None) -> Dict[str, Any]:
    """device_put the params pytree with TP/vocab-parallel layout."""
    shardings = params_sharding_tree(params, mesh, cfg)
    return jax.device_put(params, shardings)


def kv_cache_pspec(cfg: Optional[ModelConfig] = None,
                   mesh: Optional[Mesh] = None) -> P:
    """KV cache [L, B, KvH, S, hd] (head-first): batch on dp, heads on tp
    (replicated over tp when KV heads don't divide it — see resolve_specs),
    sequence on sp when the mesh has a sequence-parallel axis (long-context
    mode, parallel/long_context.py)."""
    if cfg is not None and mesh is not None:
        tp = mesh.shape.get("tp", 1)
        dp = mesh.shape.get("dp", 1)
        sp = mesh.shape.get("sp", 1)
        b = "dp" if dp > 1 else None
        s = "sp" if sp > 1 else None
        h = "tp" if (tp > 1 and cfg.n_kv_heads % tp == 0) else None
        return P(None, b, h, s, None)
    return P(None, "dp", "tp", "sp", None)


def act_pspec() -> P:
    """Activations [B, T, D]: batch on dp."""
    return P("dp", None, None)
