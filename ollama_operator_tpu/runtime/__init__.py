"""Serving runtime package.

Engine/EngineConfig re-export lazily (PEP 562): the operator's control
plane imports light runtime modules (faults, errors) for fault injection
and typed failures, and must not drag jax/XLA into the manager process
just by touching the package.
"""


def __getattr__(name):
    if name in ("Engine", "EngineConfig"):
        from .engine import Engine, EngineConfig
        return {"Engine": Engine, "EngineConfig": EngineConfig}[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
