from .engine import Engine, EngineConfig  # noqa: F401
