"""Utilization & goodput accounting: analytic FLOPs/MFU model, occupancy
and padding-waste tracking, and a wall-clock breakdown accumulator.

PR 7 (runtime/trace.py) answered "where did this request's *latency* go";
this module answers "what fraction of the device's peak are we extracting,
and where does the rest go".  Three instruments, all zero-dependency and
host-side only (nothing here touches the device program stream, so the
multi-host follower replay invariant is untouched):

1. **Analytic per-dispatch FLOPs model** derived from `models/config.py` in
   the MFU convention of Chowdhery et al. (PaLM): matmul FLOPs only
   (projections + attention + MLP/MoE + lm-head; norms/activations/rope are
   noise at these widths).  Closed forms — the attention term over a span of
   positions is an arithmetic series, never a per-position Python loop — so
   the accounting rides the dispatch path at well under the 2% tok/s budget
   `bench.py measure_mixed` enforces (`acct_tok_s_ratio`).

2. **Goodput split**: every dispatch's slot·step grid is divided into
   useful tokens (active slots, accepted drafts, real prompt positions) vs
   bucket-padding waste (empty batch slots, prefill positions beyond the
   prompt chunk, rejected speculative drafts).  Occupancy is the
   token-weighted useful fraction — the continuous-batching efficiency
   measure in the tradition of Yu et al. (Orca).

3. **Wall-clock breakdown**: scheduler time classified into dispatch-wait
   (blocked on the device via `DecodeHandle.t_launch/t_done`), idle (no
   work queued), and host overhead (everything else — detok, HTTP, Python).

MFU convention notes (also in docs/en/guide/tpu-serving.md):
- The numerator counts FLOPs issued for *active* slots only, including
  speculative positions that are later rejected (the device really ran
  them); padded batch slots and padded prefill positions are excluded.
  So MFU answers "useful-work FLOPs vs peak" and `waste_pct` separately
  answers "how much of the issued grid was padding".
- Peak FLOPs comes from the detected TPU generation (bf16 dense peak per
  chip), overridable via `TPU_PEAK_FLOPS`.  On CPU there is no meaningful
  peak: MFU reads null unless the override is set.

Kill switch: TPU_ACCOUNTING=0 swaps the scheduler's accounting for the
shared no-op instance (the bench A/B arm flips the module flag the same
way `trace.TRACE_ENABLED` is flipped).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..models.config import ModelConfig
from ..server.metrics import GLOBAL as METRICS

# Kill switch mirror of trace.TRACE_ENABLED: read at Scheduler construction
# (bench.py builds one scheduler per arm, flipping this between arms).
ACCOUNTING_ENABLED = os.environ.get(
    "TPU_ACCOUNTING", "1") not in ("0", "false", "")

# How many seconds of per-second aggregates /debug/utilization keeps.
RING_SECONDS = int(os.environ.get("TPU_ACCOUNTING_RING_S", "120"))

# Dense bf16 peak FLOPs/s per chip by TPU generation (public spec sheets).
# Matched as substrings of jax's device_kind, most specific first.
PEAK_FLOPS_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
)


def detect_peak_flops() -> Tuple[float, str]:
    """Return (peak_flops_per_s, device_kind).

    `TPU_PEAK_FLOPS` wins over detection (the only way to get an MFU on
    CPU smoke runs); 0.0 means "no meaningful peak" and MFU reads null.
    The jax import is lazy and guarded so this module stays importable
    in jax-free contexts (the operator process, unit tests of the math).
    """
    env = os.environ.get("TPU_PEAK_FLOPS", "")
    if env:
        try:
            return float(env), "override"
        except ValueError:
            pass
    try:
        import jax  # noqa: PLC0415 — deliberate lazy import
        dev = jax.devices()[0]
        kind = str(getattr(dev, "device_kind", "") or dev.platform)
    except Exception:
        return 0.0, "unknown"
    low = kind.lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in low:
            return peak, kind
    return 0.0, kind


# --- analytic FLOPs model ---------------------------------------------------
#
# Matmul-only per-position cost split into a context-independent base and a
# context-proportional attention term:
#
#   flops(position p) = base + 4 * q_dim * Σ_layers attended_keys(p, layer)
#
# where attended_keys is p+1 on full-attention layers and min(p+1, window)
# on sliding-window layers (gemma2/3 alternate by sliding_pattern).  Spans
# of positions sum the attention term as an arithmetic series.


def _layer_split(cfg: ModelConfig) -> Tuple[int, int]:
    """(full_attention_layers, sliding_window_layers)."""
    L = cfg.n_layers
    if cfg.sliding_window <= 0:
        return L, 0
    if cfg.altern_sliding:
        p = cfg.sliding_pattern
        full = sum(1 for i in range(L) if i % p == p - 1)
        return full, L - full
    return 0, L


def _ctx_sum(start: int, n: int, window: int = 0) -> float:
    """Σ over positions p in [start, start+n) of attended key count
    (p+1, capped at `window` when nonzero) — closed form, no loop."""
    if n <= 0:
        return 0.0
    end = start + n
    if window and start + 1 >= window:
        return float(n * window)
    if window and end > window:
        n_lin = window - start
        lin = (start + 1 + window) * n_lin / 2.0
        return lin + (end - window) * float(window)
    return (start + 1 + end) * n / 2.0


def per_token_flops(cfg: ModelConfig) -> float:
    """Context-independent matmul FLOPs for one position: per-layer
    projections + MLP (dense or MoE top-k + shared expert + router) plus
    the lm-head.  The lm-head is counted for every position — the engine
    really computes logits for the whole padded step, and on the tiny CI
    configs it dominates; the docs carry the caveat."""
    d, f, L, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    q, kv = cfg.q_dim, cfg.kv_dim
    proj = 2 * (d * q + 2 * d * kv + q * d)
    mlp_mult = 6 if cfg.mlp_type == "gated" else 4
    mlp = mlp_mult * d * f
    if cfg.n_experts:
        mlp = cfg.n_experts_used * mlp + 2 * d * cfg.n_experts
        if cfg.n_shared_ffn:
            mlp += 6 * d * cfg.n_shared_ffn
    head = 2 * d * v
    return float(L * (proj + mlp) + head)


def attn_span_flops(cfg: ModelConfig, start: int, n: int) -> float:
    """Attention score+value matmul FLOPs (4·q_dim per attended key) for
    positions [start, start+n), respecting sliding windows per layer."""
    full, sliding = _layer_split(cfg)
    tot = full * _ctx_sum(start, n)
    if sliding:
        tot += sliding * _ctx_sum(start, n, cfg.sliding_window)
    return 4.0 * cfg.q_dim * tot


def prefill_flops(cfg: ModelConfig, start: int, n: int) -> float:
    """One prefill chunk: `n` real prompt positions beginning at absolute
    position `start` (chunked prefill passes start=job.done)."""
    return n * per_token_flops(cfg) + attn_span_flops(cfg, start, n)


def decode_flops(cfg: ModelConfig, ctx: int, n_steps: int = 1) -> float:
    """`n_steps` autoregressive steps for one slot whose attended context
    is `ctx` keys at the first step (step j attends ctx+j)."""
    return (n_steps * per_token_flops(cfg)
            + attn_span_flops(cfg, ctx - 1, n_steps))


def spec_verify_flops(cfg: ModelConfig, ctx: int, k: int) -> float:
    """One speculative verify dispatch for one slot: k drafts + 1 bonus
    position, contexts ctx..ctx+k — identical math to a (k+1)-token
    prefill chunk starting at position ctx-1."""
    return prefill_flops(cfg, ctx - 1, k + 1)


# --- accumulator ------------------------------------------------------------

_KINDS = ("decode", "prefill", "spec")


class UtilizationAccounting:
    """Thread-safe accumulator fed by the scheduler's dispatch sites.

    Totals are monotone (Prometheus counters mirror them); the per-second
    ring backs `GET /debug/utilization` and the windowed rates in
    `snapshot()` (MFU, goodput tok/s, occupancy).
    """

    enabled = True

    def __init__(self, cfg: Optional[ModelConfig] = None,
                 peak_flops: Optional[float] = None,
                 device_kind: Optional[str] = None):
        self.cfg = cfg
        if peak_flops is None:
            peak_flops, detected = detect_peak_flops()
            if device_kind is None:
                device_kind = detected
        self.peak_flops = float(peak_flops or 0.0)
        self.device_kind = device_kind or "unknown"
        self._base = per_token_flops(cfg) if cfg is not None else 0.0
        self._lock = threading.Lock()
        self._t_start = time.monotonic()
        self.useful_tokens: Dict[str, float] = {k: 0.0 for k in _KINDS}
        self.padded_tokens: Dict[str, float] = {k: 0.0 for k in _KINDS}
        self.model_flops = 0.0
        self.wait_s = 0.0
        self.idle_s = 0.0
        self.dispatches: Dict[str, int] = {k: 0 for k in _KINDS}
        # per-second ring: {int(monotonic): [flops, useful, padded, busy_s]}
        self._ring: Dict[int, List[float]] = {}
        # incremental host-overhead attribution: between consecutive
        # wait/idle events every elapsed second not spent blocked is
        # host work (detok, HTTP, Python) — synced into the phase counter
        self._synced_wall = self._t_start

    # -- feed sites ----------------------------------------------------------

    def _bump(self, kind: str, flops: float, useful: float,
              padded: float, dur_s: float) -> None:
        now = int(time.monotonic())
        with self._lock:
            self.useful_tokens[kind] += useful
            self.padded_tokens[kind] += padded
            self.model_flops += flops
            self.dispatches[kind] += 1
            cell = self._ring.get(now)
            if cell is None:
                cell = self._ring[now] = [0.0, 0.0, 0.0, 0.0]
                if len(self._ring) > RING_SECONDS + 8:
                    cutoff = now - RING_SECONDS
                    for t in [t for t in self._ring if t < cutoff]:
                        del self._ring[t]
            cell[0] += flops
            cell[1] += useful
            cell[2] += padded
            cell[3] += dur_s
        METRICS.inc("tpu_model_useful_tokens_total", useful,
                    f'{{kind="{kind}"}}')
        METRICS.inc("tpu_model_padded_tokens_total", padded,
                    f'{{kind="{kind}"}}')
        METRICS.inc("tpu_model_model_flops_total", flops)

    def on_decode(self, dur_s: float, ctxs: Iterable[int], n_steps: int,
                  capacity: int) -> None:
        """One (possibly multi-step) decode dispatch: `ctxs` are the
        attended context lengths of the ACTIVE slots at the first step,
        `capacity` the padded batch bucket the device actually ran."""
        if self.cfg is None:
            return
        flops = 0.0
        n_active = 0
        for c in ctxs:
            n_active += 1
            flops += (n_steps * self._base
                      + attn_span_flops(self.cfg, c - 1, n_steps))
        useful = float(n_active * n_steps)
        padded = float(max(0, capacity - n_active) * n_steps)
        self._bump("decode", flops, useful, padded, dur_s)

    def on_spec(self, dur_s: float, ctxs: Iterable[int], k: int,
                emitted: float, capacity: int) -> None:
        """One speculative verify dispatch: every slot in the bucket runs
        k+1 positions; `emitted` is the number of tokens that actually
        advanced streams (accepted drafts + bonus).  FLOPs count the
        active slots' full verify windows (rejected drafts were really
        computed); waste = the issued grid minus emitted."""
        if self.cfg is None:
            return
        flops = 0.0
        n_active = 0
        for c in ctxs:
            n_active += 1
            flops += spec_verify_flops(self.cfg, c, k)
        issued = float(capacity * (k + 1))
        useful = float(min(emitted, issued))
        self._bump("spec", flops, useful, max(0.0, issued - useful), dur_s)

    def on_prefill(self, dur_s: float, start: int, n_new: int,
                   bucket: int) -> None:
        """One prefill chunk (admit / extend / one admit_many member):
        `n_new` real prompt positions from absolute position `start`,
        padded to `bucket` on device."""
        if self.cfg is None or n_new <= 0:
            return
        flops = prefill_flops(self.cfg, start, n_new)
        padded = float(max(0, bucket - n_new))
        self._bump("prefill", flops, float(n_new), padded, dur_s)

    def _sync_phase(self, phase: str, dur_s: float) -> None:
        """Fold a blocked interval into the phase counters; the wall time
        since the previous sync minus the blocked part is host overhead."""
        now = time.monotonic()
        with self._lock:
            host = max(0.0, (now - self._synced_wall) - dur_s)
            self._synced_wall = now
        METRICS.inc("tpu_model_breakdown_seconds_total", dur_s,
                    f'{{phase="{phase}"}}')
        if host > 0.0:
            METRICS.inc("tpu_model_breakdown_seconds_total", host,
                        '{phase="host"}')

    def on_wait(self, dur_s: float) -> None:
        with self._lock:
            self.wait_s += dur_s
        self._sync_phase("dispatch_wait", dur_s)

    def on_idle(self, dur_s: float) -> None:
        with self._lock:
            self.idle_s += dur_s
        self._sync_phase("idle", dur_s)

    # -- reads ---------------------------------------------------------------

    def breakdown(self) -> Dict[str, float]:
        with self._lock:
            wall = time.monotonic() - self._t_start
            wait, idle = self.wait_s, self.idle_s
        host = max(0.0, wall - wait - idle)
        return {"wall_s": round(wall, 3),
                "dispatch_wait_s": round(wait, 3),
                "idle_s": round(idle, 3),
                "host_s": round(host, 3)}

    def snapshot(self, window_s: float = 60.0) -> Dict[str, Any]:
        """Windowed rates + lifetime totals; the `/api/ps` utilization
        block and the operator's CR status mirror read this."""
        now = int(time.monotonic())
        window = max(1, min(int(window_s), RING_SECONDS))
        with self._lock:
            flops = useful = padded = busy = 0.0
            secs = 0
            for t, cell in self._ring.items():
                # skip the in-progress second so rates aren't biased low
                if now - window <= t < now:
                    flops += cell[0]
                    useful += cell[1]
                    padded += cell[2]
                    busy += cell[3]
                    secs += 1
            elapsed = min(window, max(1.0, time.monotonic() - self._t_start))
            totals = {
                "useful_tokens": dict(self.useful_tokens),
                "padded_tokens": dict(self.padded_tokens),
                "model_flops": self.model_flops,
                "dispatches": dict(self.dispatches),
            }
        issued = useful + padded
        mfu = (flops / elapsed / self.peak_flops) if self.peak_flops else None
        return {
            "enabled": True,
            "device_kind": self.device_kind,
            "peak_flops": self.peak_flops or None,
            "window_s": window,
            "mfu": (round(mfu, 6) if mfu is not None else None),
            "model_flops_per_s": round(flops / elapsed, 1),
            "goodput_tok_s": round(useful / elapsed, 2),
            "occupancy": round(useful / issued, 4) if issued else None,
            "waste_pct": round(100.0 * padded / issued, 2) if issued else 0.0,
            "busy_s": round(busy, 3),
            "active_seconds": secs,
            "breakdown": self.breakdown(),
            "totals": totals,
        }

    def ring(self, last: int = 60) -> List[Dict[str, Any]]:
        """Per-second aggregates, oldest first — /debug/utilization."""
        with self._lock:
            items = sorted(self._ring.items())[-max(1, last):]
            t_now = int(time.monotonic())
        return [{"t_rel_s": t - t_now, "model_flops": cell[0],
                 "useful_tokens": cell[1], "padded_tokens": cell[2],
                 "busy_ms": round(cell[3] * 1e3, 3)}
                for t, cell in items]


class _NullAccounting:
    """Shared no-op stand-in when TPU_ACCOUNTING=0: call sites never
    branch, the bench counters-off arm measures pure overhead."""

    enabled = False
    cfg = None
    peak_flops = 0.0
    device_kind = "disabled"
    model_flops = 0.0

    def on_decode(self, *a: Any, **kw: Any) -> None:
        pass

    def on_spec(self, *a: Any, **kw: Any) -> None:
        pass

    def on_prefill(self, *a: Any, **kw: Any) -> None:
        pass

    def on_wait(self, dur_s: float) -> None:
        pass

    def on_idle(self, dur_s: float) -> None:
        pass

    def breakdown(self) -> Dict[str, float]:
        return {}

    def snapshot(self, window_s: float = 60.0) -> Dict[str, Any]:
        return {"enabled": False}

    def ring(self, last: int = 60) -> List[Dict[str, Any]]:
        return []


NULL_ACCOUNTING = _NullAccounting()


def make_accounting(cfg: Optional[ModelConfig]):
    """Factory the scheduler calls at construction: honors the module
    kill switch at call time (bench flips it between arms)."""
    if not ACCOUNTING_ENABLED:
        return NULL_ACCOUNTING
    return UtilizationAccounting(cfg)
